"""Shuffle & broadcast exchanges (reference ``GpuShuffleExchangeExecBase``,
``GpuBroadcastExchangeExec``, SURVEY §2.8/§3.4).

Local-mode data plane: rows are routed by a partitioner id column and
compacted per target with static-shape gathers (the contiguousSplit analog).
Multi-chip data plane (parallel/shuffle.py) swaps this loop for an ICI
all-to-all under shard_map; the exec contract (materialize once, serve
per-partition) is identical, mirroring the reference's shuffle-manager SPI.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional

import numpy as np

from ...columnar.batch import ColumnarBatch
from ...observability import tracer as _trace
from ...parallel.partitioning import (HashPartitioning, Partitioning,
                                      RangePartitioning, RoundRobinPartitioning,
                                      SinglePartitioning)
from ..expressions.core import EvalContext
from .base import TPU, PhysicalPlan, TaskContext


def empty_batch_for(attrs) -> ColumnarBatch:
    """Zero-row batch with the schema of an exec's output attributes."""
    from ... import types as T
    return ColumnarBatch.empty(T.StructType(tuple(
        T.StructField(a.name, a.dtype, True) for a in attrs)))


#: observability for the AQE skew-split reader (tests assert on these)
STATS = {"skew_splits": 0, "skew_chunks": 0}


class ShuffleExchangeExec(PhysicalPlan):
    def __init__(self, partitioning: Partitioning, child: PhysicalPlan,
                 backend=TPU, coalescible: bool = True,
                 skew_splittable: bool = False):
        super().__init__(child)
        self.backend = backend
        self.partitioning = partitioning.bind(child.output)
        #: skew splitting only pays off for consumers that STREAM their
        #: per-partition batches (shuffled-hash-join probe sides); an
        #: aggregate/sort/window would just concat the chunks back at
        #: device-copy cost, so the join planner opts the probe exchange
        #: in explicitly (same pattern as coalescible/map_side_filter)
        self._skew_splittable = skew_splittable
        #: AQE partition coalescing is only sound when no sibling exchange
        #: must stay aligned with this one — the two exchanges feeding a
        #: co-partitioned join decide INDEPENDENTLY, so one coalescing
        #: while the other keeps hashing would silently mis-join; the join
        #: planner passes coalescible=False for both sides
        self._coalescible = coalescible
        self._materialized: Optional[List[List[ColumnarBatch]]] = None
        #: serializes one-shot materialization: under the parallel
        #: partition scheduler (and prefetch producer threads) several
        #: reduce partitions race into the first execute — a double
        #: materialize would run the whole map side twice and double-write
        #: shuffle blocks
        self._mat_lock = threading.Lock()
        self._split_fn = self._jit(self._split_one, key=("split",))
        #: map-side runtime filter (bloom-filter join pushdown): applied to
        #: each map partition's merged output BEFORE the split/write, so
        #: dropped rows never ride the shuffle.  Installed by the join
        #: after its build side materializes (ops/bloom.py; reference
        #: GpuBloomFilterMightContain pushed below the exchange).
        self.map_side_filter = None

    @property
    def output(self):
        return self.children[0].output

    def num_partitions(self):
        return self.partitioning.num_partitions

    # --- device kernels ---------------------------------------------------
    def _split_one(self, batch: ColumnarBatch, pids, target):
        from .basic import compact_batch
        keep = (pids == target) & batch.row_mask()
        return compact_batch(self.xp, batch, keep)

    # --- materialization --------------------------------------------------
    def _ensure_materialized(self, tctx: TaskContext):
        if self._materialized is not None:
            return
        with self._mat_lock:
            if self._materialized is not None:
                return
            with _trace.span("shuffle", "exchange.materialize",
                             partitions=self.num_partitions()):
                self._materialize(tctx)
            # materialized partitions are RETAINED by this exec and may be
            # re-served (shared-subtree parents, AQE readers): pin them so
            # a downstream fused stage never donates their buffers
            from ...memory import retention as _ret
            for part in self._materialized or []:
                for b in part:
                    _ret.pin_batch(b)

    def _materialize(self, tctx: TaskContext):
        """Map side: split each child batch by target and hand the pieces to
        the shuffle manager (serializer + SORT/MULTITHREADED/ICI data
        plane); reduce side then fetches + host-concats per partition
        (SURVEY §3.4 write/read paths).

        ICI mode with a live multi-device mesh routes the whole exchange
        through ONE compiled all_to_all program instead
        (parallel/mesh.py) — the planned-query analog of the reference's
        UCX device-direct path."""
        from ...shuffle import get_shuffle_manager
        child = self.children[0]
        nt = self.num_partitions()
        mgr = get_shuffle_manager(tctx.conf)
        shuffle_id = mgr.new_shuffle_id()

        # run the child plan exactly ONCE; every downstream consumer
        # (range-bounds sampling, mesh plane, local plane) shares the
        # collected map outputs
        num_maps = child.num_partitions()
        map_out: List[Optional[ColumnarBatch]] = []
        from ...serving import lifecycle as _lc
        for cpid in range(num_maps):
            # lifecycle poll site `exchange`: the map side is the one
            # place a query re-runs its whole subtree serially — a
            # cancel/deadline must drain between map tasks, not after
            # all of them
            _lc.check_cancel("exchange")
            ctctx = TaskContext(cpid, tctx.conf, parent=tctx)
            with ctctx.as_current():
                got = list(child.execute(cpid, ctctx))
            map_out.append(ColumnarBatch.concat(got) if len(got) > 1
                           else (got[0] if got else None))

        if self.map_side_filter is not None:
            map_out = [self.map_side_filter(b) if b is not None else None
                       for b in map_out]

        # AQE partition coalescing: a tiny total map output routes whole
        # to reduce partition 0 — equal keys stay co-located (trivially)
        # and a range order is trivially preserved, while the downstream
        # plan stops paying nt-1 empty split/launch/sync rounds
        # (GpuCustomShuffleReaderExec coalesced-partitions analog)
        from ...config import ADAPTIVE_COALESCE_ROWS, ADAPTIVE_ENABLED
        from ...shuffle import get_shuffle_manager as _gsm
        _topo = _gsm(tctx.conf).topology
        coalesce = (nt > 1 and self._coalescible
                    # multi-slice: the coalesce decision is DATA-dependent
                    # (local map row count), so two slices could partition
                    # the same shuffle differently and split a key across
                    # reduce partitions — same hazard as co-partitioned
                    # sibling exchanges (coalescible=False); never coalesce
                    and (_topo is None or not _topo.multi_slice)
                    and bool(tctx.conf.get(ADAPTIVE_ENABLED))
                    and sum(b.num_rows_int for b in map_out
                            if b is not None)
                    <= int(tctx.conf.get(ADAPTIVE_COALESCE_ROWS)))

        if isinstance(self.partitioning, RangePartitioning) and not coalesce:
            self._compute_range_bounds(map_out)

        topo = mgr.topology
        multi = topo is not None and topo.multi_slice

        if (mgr.mode == "ICI" and self.backend == TPU and nt > 1
                and not coalesce and not multi):
            # multi-slice MUST take the block path: the mesh plane would
            # assemble all nt partitions from this slice's maps alone and
            # publish nothing for the peer slices to pull
            if self._try_mesh_materialize(map_out, nt):
                tctx.inc_metric("meshExchanges")
                self._maybe_skew_split(tctx)
                return
            tctx.inc_metric("meshFallbacks")

        # multi-slice: namespace map ids per slice so the peer slices'
        # blocks never collide with ours (symmetric deployments: every
        # slice runs the same plan, so num_maps agrees — docs/distributed)
        map_base = topo.slice_id * num_maps if multi else 0

        def _write_map(cpid: int, merged: ColumnarBatch) -> None:
            if nt == 1 or coalesce:
                pieces: List[Optional[ColumnarBatch]] = [merged]
            else:
                ctx = EvalContext(merged, xp=self.xp)
                pids = self.partitioning.partition_ids(ctx, merged, cpid)
                pieces = [self._split_fn(merged, pids, t).shrunk()
                          for t in range(nt)]
            mgr.write_map_output(shuffle_id, map_base + cpid, pieces)

        for cpid, merged in enumerate(map_out):
            if merged is None:
                continue
            _write_map(cpid, merged)

        # lost-block recompute lineage: the collected map outputs + the
        # bound partitioner (range bounds already fixed above) make the
        # re-split deterministic, so a recomputed block is bit-identical
        # to the lost one.  Only THIS slice's maps are recomputable; a
        # peer slice's lost block keeps the FetchFailed contract.
        def _recompute_map(map_id: int) -> None:
            local = map_id - map_base
            if not (0 <= local < num_maps):
                from ...shuffle import ShuffleFetchFailed
                raise ShuffleFetchFailed(
                    f"map {map_id} belongs to a peer slice; no local "
                    f"lineage to recompute it")
            merged = map_out[local]
            if merged is not None:
                _write_map(local, merged)
        mgr.register_recompute(shuffle_id, _recompute_map)

        total_maps = num_maps * (topo.num_slices if multi else 1)
        out: List[List[ColumnarBatch]] = []
        try:
            for t in range(nt):
                if multi and not topo.is_local(t, nt):
                    # two-tier plane: this slice assembles ONLY the reduce
                    # partitions it owns; peer slices pull their own blocks
                    # (published above) over the DCN transport
                    out.append([])
                    continue
                got = mgr.read_reduce_partition(shuffle_id, total_maps, t)
                out.append([got] if got is not None else [])
        except BaseException:
            # an aborted materialization (query cancel/deadline, fetch
            # failure) must not leave the lineage closure — which pins
            # every map output batch — registered in the process-wide
            # manager forever (found by tools/leak_sentinel.py)
            mgr.unregister_recompute(shuffle_id)
            mgr.cleanup(shuffle_id)
            raise
        if not multi:
            mgr.cleanup(shuffle_id)
        else:
            # peers may still be fetching this shuffle's blocks — defer
            # reclamation to the TTL sweep instead of leaking forever.
            # The recompute lineage is only reachable from OUR read loop
            # (a peer's failed fetch fails in the peer's manager), so it
            # must not pin the map outputs across the TTL window.
            mgr.unregister_recompute(shuffle_id)
            mgr.defer_cleanup(shuffle_id)
        self._materialized = out
        self._maybe_skew_split(tctx)

    def _maybe_skew_split(self, tctx: TaskContext) -> None:
        """AQE skew handling at the reader (reference
        ``GpuCustomShuffleReaderExec.scala:87-91`` skewed-partition
        specs): a materialized reduce partition whose row count exceeds
        skewedPartitionFactor x the median non-empty partition (and the
        absolute row threshold) is re-sliced into contiguous
        median-sized chunks.  Downstream shuffled hash joins stream
        probe batches, so each chunk joins against the full build
        partition — one hot key no longer sends the join through the
        OOM-retry path.  Chunks stay inside their partition, so key
        co-location (and range order: slices are contiguous) is
        untouched, which also keeps it safe for co-partitioned sibling
        exchanges, unlike coalescing."""
        from ...config import (ADAPTIVE_ENABLED, SKEW_JOIN_ENABLED,
                               SKEW_JOIN_FACTOR, SKEW_JOIN_ROWS)
        if not (self._skew_splittable
                and bool(tctx.conf.get(ADAPTIVE_ENABLED))
                and bool(tctx.conf.get(SKEW_JOIN_ENABLED))):
            return
        sizes = [sum(b.num_rows_int for b in p)
                 for p in self._materialized]
        nonzero = sorted(s for s in sizes if s > 0)
        if len(nonzero) < 2:
            return
        median = nonzero[len(nonzero) // 2]
        factor = int(tctx.conf.get(SKEW_JOIN_FACTOR))
        thresh = int(tctx.conf.get(SKEW_JOIN_ROWS))
        target = max(median, thresh // factor, 1)
        for t, part in enumerate(self._materialized):
            if sizes[t] <= thresh or sizes[t] <= factor * median:
                continue
            chunks: List[ColumnarBatch] = []
            for b in part:
                n = b.num_rows_int
                k = -(-n // target)
                if k <= 1:
                    chunks.append(b)
                    continue
                step = -(-n // k)
                for off in range(0, n, step):
                    chunks.append(b.sliced(off, min(step, n - off)))
            if len(chunks) > len(part):
                STATS["skew_splits"] += 1
                STATS["skew_chunks"] += len(chunks) - len(part)
                tctx.inc_metric("skewSplitPartitions")
                self._materialized[t] = chunks

    def _empty_batch(self) -> ColumnarBatch:
        return empty_batch_for(self.output)

    def _try_mesh_materialize(self, map_out: List[Optional[ColumnarBatch]],
                              nt: int) -> bool:
        """Run the exchange through the compiled mesh all_to_all plane.
        Returns False (clean fallback to the local plane) when no multi-
        device mesh exists or the batch layout cannot ride it.

        ``nt`` may exceed the device count when it is a multiple of it:
        rows route over ICI to their OWNER device (target % n_dev) and
        each device's received batch splits locally into the `group`
        partitions it owns — so partition counts no longer have to match
        the mesh exactly (VERDICT r2 weak #8)."""
        from ...parallel.mesh import (MeshShuffleUnsupported, align_batches,
                                      device_mesh, mesh_shuffle_batches)
        from ...parallel.partitioning import (HashPartitioning,
                                              RangePartitioning)
        mesh = device_mesh(nt)
        group = 1
        if mesh is None:
            import jax
            nd = len(jax.devices())
            # content-determined partitionings only: the second-stage
            # split recomputes partition ids on the RECEIVED batch, which
            # round-robin (source-position-dependent) cannot survive
            if (nd >= 2 and nt % nd == 0
                    and isinstance(self.partitioning,
                                   (HashPartitioning, RangePartitioning))):
                mesh = device_mesh(nd)
                group = nt // nd
        if mesh is None:
            return False
        n_dev = nt // group

        # group map outputs onto the n_dev shards (m -> m % n_dev)
        shard_batches: List[List[ColumnarBatch]] = [[] for _ in range(n_dev)]
        for cpid, b in enumerate(map_out):
            if b is not None:
                shard_batches[cpid % n_dev].append(b)
        merged = [ColumnarBatch.concat(bs) if len(bs) > 1
                  else (bs[0] if bs else self._empty_batch())
                  for bs in shard_batches]
        try:
            aligned = align_batches(merged)
            pids = []
            for i, b in enumerate(aligned):
                ctx = EvalContext(b, xp=self.xp)
                p = self.partitioning.partition_ids(ctx, b, i)
                if group > 1:
                    p = p % n_dev  # ICI stage routes to the owner device
                pids.append(p)
            out = mesh_shuffle_batches(mesh, aligned, pids, n_dev)
        except MeshShuffleUnsupported:
            from ...parallel.mesh import STATS
            STATS["fallbacks"] += 1
            return False
        if group == 1:
            self._materialized = [[b] if b.num_rows_int > 0 else []
                                  for b in out]
            return True
        # second stage: device d owns targets {d, d+n_dev, ...} — split
        # its received batch by the full partition id, locally
        mat: List[List[ColumnarBatch]] = [[] for _ in range(nt)]
        for d, b in enumerate(out):
            if b.num_rows_int == 0:
                continue
            ctx = EvalContext(b, xp=self.xp)
            full = self.partitioning.partition_ids(ctx, b, d)
            for t in range(d, nt, n_dev):
                piece = self._split_fn(b, full, t).shrunk()
                if piece.num_rows_int > 0:
                    mat[t].append(piece)
        self._materialized = mat
        return True

    def _compute_range_bounds(self, map_out: List[Optional[ColumnarBatch]]):
        """Sample the collected map outputs, sort the sample by the orders,
        take quantile rows as bounds (reference
        GpuRangePartitioner.createRangeBounds)."""
        from .sortlimit import SortExec
        part: RangePartitioning = self.partitioning  # type: ignore
        samples = []
        for batch in map_out:
            if batch is None:
                continue
            n = batch.num_rows_int
            if n > 4096:  # cheap deterministic sample
                batch = batch.sliced(0, 4096)
            samples.append(batch)
        if not samples:
            part.set_bounds(self._empty_batch())
            return
        merged = ColumnarBatch.concat(samples) if len(samples) > 1 else samples[0]
        sorter = SortExec(part.orders, self.children[0], self.backend)
        merged = sorter._fn(merged)
        # evaluate sort keys over the sorted batch, pick boundary rows
        ctx = EvalContext(merged, xp=self.xp)
        key_cols = tuple(o.child.eval(ctx) for o in sorter._bound)
        names = tuple(f"_k{i}" for i in range(len(key_cols)))
        keys_batch = ColumnarBatch(names, key_cols, merged.num_rows)
        n = merged.num_rows_int
        nparts = part.num_partitions
        idxs = [min(n - 1, max(0, (i + 1) * n // nparts))
                for i in range(nparts - 1)] if n else []
        rows = [keys_batch.sliced(i, 1) for i in idxs]
        bounds = ColumnarBatch.concat(rows) if len(rows) > 1 else (
            rows[0] if rows else keys_batch.sliced(0, 0))
        part.set_bounds(bounds)

    def execute(self, pid, tctx):
        self._ensure_materialized(tctx)
        yield from self._materialized[pid]

    def simple_string(self):
        return f"{self.node_name()} {self.partitioning.simple_string()}"


class BroadcastExchangeExec(PhysicalPlan):
    """Materialize the (small) child once as a single concatenated batch,
    served to every consumer partition (reference serializes to host and
    re-uploads per task; locally the device batch is just shared).

    Build-cache contract: consumers attach derived build-side artifacts to
    the batch itself (``_join_build_sides`` — the hash-join fast path's
    sorted key tuples, keyed by bound build-key signature), so every probe
    partition and every probe batch of every join over this broadcast
    shares ONE build-side preparation, exactly like the reference builds
    its broadcast hash table once (``GpuHashJoin.scala:298``).  The dict
    lives on the batch, not the exec, so it dies with the batch."""

    def __init__(self, child: PhysicalPlan, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self._cached: Optional[ColumnarBatch] = None
        #: parallel consumer partitions race into the first
        #: broadcast_batch; the build must run exactly once
        self._mat_lock = threading.Lock()

    @property
    def output(self):
        return self.children[0].output

    def num_partitions(self):
        return 1

    def broadcast_batch(self, tctx: TaskContext) -> ColumnarBatch:
        if self._cached is not None:
            return self._cached
        with self._mat_lock:
            return self._broadcast_batch_locked(tctx)

    def _broadcast_batch_locked(self, tctx: TaskContext) -> ColumnarBatch:
        if self._cached is None:
            # cross-query broadcast sharing (docs/serving.md): key the
            # child subtree by content and serve a process-cached batch —
            # the same dimension table broadcast by N queries/sessions
            # uploads and build-prepares once.  The shared batch stays
            # pinned by the cache, so donation safety is unchanged.
            from ...config import SERVING_BROADCAST_SHARE
            share_key = None
            if bool(tctx.conf.get(SERVING_BROADCAST_SHARE)):
                from ...serving import broadcast_cache as _bc
                share_key = _bc.content_key(self.children[0], tctx.conf)
                if share_key is not None:
                    got = _bc.lookup(share_key)
                    if got is not None:
                        # this exec takes its OWN pin (below) so a cache
                        # eviction can never unpin a batch a live plan
                        # still serves; the artifact dict already exists
                        # from the original build
                        self._cached = got
                        from ...memory import retention as _ret
                        _ret.pin_batch(self._cached)
                        return self._cached
            batches = []
            with _trace.span("shuffle", "broadcast.materialize"):
                for cpid in range(self.children[0].num_partitions()):
                    ctctx = TaskContext(cpid, tctx.conf, parent=tctx)
                    with ctctx.as_current():
                        batches.extend(
                            self.children[0].execute(cpid, ctctx))
            if not batches:
                self._cached = empty_batch_for(self.output)
            else:
                self._cached = (ColumnarBatch.concat(batches)
                                if len(batches) > 1 else batches[0])
            if share_key is not None:
                from ...serving import broadcast_cache as _bc
                _bc.store(share_key, self._cached,
                          int(self.children[0].estimate_bytes() or 0))
            # seed the artifact cache eagerly: a concat result could be a
            # pass-through of a child batch that already carries artifacts
            # from an unrelated join over different keys — the per-key
            # signatures keep those distinct, but the dict must exist on
            # THIS object for all consumers to share one instance
            if getattr(self._cached, "_join_build_sides", None) is None:
                self._cached._join_build_sides = {}
            # the broadcast batch is shared by every probe partition for
            # the plan's lifetime: pin it against whole-stage donation
            from ...memory import retention as _ret
            _ret.pin_batch(self._cached)
        return self._cached

    def execute(self, pid, tctx):
        yield self.broadcast_batch(tctx)
