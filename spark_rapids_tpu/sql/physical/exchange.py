"""Shuffle & broadcast exchanges (reference ``GpuShuffleExchangeExecBase``,
``GpuBroadcastExchangeExec``, SURVEY §2.8/§3.4).

Local-mode data plane: rows are routed by a partitioner id column and
compacted per target with static-shape gathers (the contiguousSplit analog).
Multi-chip data plane (parallel/shuffle.py) swaps this loop for an ICI
all-to-all under shard_map; the exec contract (materialize once, serve
per-partition) is identical, mirroring the reference's shuffle-manager SPI.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from ...columnar.batch import ColumnarBatch
from ...parallel.partitioning import (HashPartitioning, Partitioning,
                                      RangePartitioning, RoundRobinPartitioning,
                                      SinglePartitioning)
from ..expressions.core import EvalContext
from .base import TPU, PhysicalPlan, TaskContext


class ShuffleExchangeExec(PhysicalPlan):
    def __init__(self, partitioning: Partitioning, child: PhysicalPlan,
                 backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.partitioning = partitioning.bind(child.output)
        self._materialized: Optional[List[List[ColumnarBatch]]] = None
        self._split_fn = self._jit(self._split_one, key=("split",))

    @property
    def output(self):
        return self.children[0].output

    def num_partitions(self):
        return self.partitioning.num_partitions

    # --- device kernels ---------------------------------------------------
    def _split_one(self, batch: ColumnarBatch, pids, target):
        from .basic import compact_batch
        keep = (pids == target) & batch.row_mask()
        return compact_batch(self.xp, batch, keep)

    # --- materialization --------------------------------------------------
    def _ensure_materialized(self, tctx: TaskContext):
        """Map side: split each child batch by target and hand the pieces to
        the shuffle manager (serializer + SORT/MULTITHREADED/ICI data
        plane); reduce side then fetches + host-concats per partition
        (SURVEY §3.4 write/read paths)."""
        if self._materialized is not None:
            return
        from ...shuffle import get_shuffle_manager
        child = self.children[0]
        nt = self.num_partitions()
        mgr = get_shuffle_manager(tctx.conf)
        shuffle_id = mgr.new_shuffle_id()

        if isinstance(self.partitioning, RangePartitioning):
            self._compute_range_bounds(tctx)

        num_maps = child.num_partitions()
        for cpid in range(num_maps):
            map_batches = list(child.execute(cpid,
                                             TaskContext(cpid, tctx.conf)))
            if not map_batches:
                continue
            merged = ColumnarBatch.concat(map_batches) \
                if len(map_batches) > 1 else map_batches[0]
            if nt == 1:
                pieces: List[Optional[ColumnarBatch]] = [merged]
            else:
                ctx = EvalContext(merged, xp=self.xp)
                pids = self.partitioning.partition_ids(ctx, merged, cpid)
                pieces = [self._split_fn(merged, pids, t).shrunk()
                          for t in range(nt)]
            mgr.write_map_output(shuffle_id, cpid, pieces)

        out: List[List[ColumnarBatch]] = []
        for t in range(nt):
            got = mgr.read_reduce_partition(shuffle_id, num_maps, t)
            out.append([got] if got is not None else [])
        mgr.cleanup(shuffle_id)
        self._materialized = out

    def _compute_range_bounds(self, tctx: TaskContext):
        """Sample child output, sort sample by the orders, take quantile rows
        as bounds (reference GpuRangePartitioner.createRangeBounds)."""
        from .sortlimit import SortExec
        child = self.children[0]
        part: RangePartitioning = self.partitioning  # type: ignore
        samples = []
        for cpid in range(child.num_partitions()):
            for batch in child.execute(cpid, TaskContext(cpid, tctx.conf)):
                n = batch.num_rows_int
                if n > 4096:  # cheap deterministic sample
                    batch = batch.sliced(0, 4096)
                samples.append(batch)
        if not samples:
            schema = self.children[0].output
            from ... import types as T
            from ...columnar.batch import ColumnarBatch as CB
            empty = CB.empty(T.StructType(tuple(
                T.StructField(a.name, a.dtype, True) for a in schema)))
            part.set_bounds(empty)
            return
        merged = ColumnarBatch.concat(samples) if len(samples) > 1 else samples[0]
        sorter = SortExec(part.orders, self.children[0], self.backend)
        merged = sorter._fn(merged)
        # evaluate sort keys over the sorted batch, pick boundary rows
        ctx = EvalContext(merged, xp=self.xp)
        key_cols = tuple(o.child.eval(ctx) for o in sorter._bound)
        names = tuple(f"_k{i}" for i in range(len(key_cols)))
        keys_batch = ColumnarBatch(names, key_cols, merged.num_rows)
        n = merged.num_rows_int
        nparts = part.num_partitions
        idxs = [min(n - 1, max(0, (i + 1) * n // nparts))
                for i in range(nparts - 1)] if n else []
        rows = [keys_batch.sliced(i, 1) for i in idxs]
        bounds = ColumnarBatch.concat(rows) if len(rows) > 1 else (
            rows[0] if rows else keys_batch.sliced(0, 0))
        part.set_bounds(bounds)

    def execute(self, pid, tctx):
        self._ensure_materialized(tctx)
        yield from self._materialized[pid]

    def simple_string(self):
        return f"{self.node_name()} {self.partitioning.simple_string()}"


class BroadcastExchangeExec(PhysicalPlan):
    """Materialize the (small) child once as a single concatenated batch,
    served to every consumer partition (reference serializes to host and
    re-uploads per task; locally the device batch is just shared)."""

    def __init__(self, child: PhysicalPlan, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self._cached: Optional[ColumnarBatch] = None

    @property
    def output(self):
        return self.children[0].output

    def num_partitions(self):
        return 1

    def broadcast_batch(self, tctx: TaskContext) -> ColumnarBatch:
        if self._cached is None:
            batches = []
            for cpid in range(self.children[0].num_partitions()):
                batches.extend(self.children[0].execute(
                    cpid, TaskContext(cpid, tctx.conf)))
            if not batches:
                from ... import types as T
                schema = T.StructType(tuple(
                    T.StructField(a.name, a.dtype, True)
                    for a in self.output))
                self._cached = ColumnarBatch.empty(schema)
            else:
                self._cached = (ColumnarBatch.concat(batches)
                                if len(batches) > 1 else batches[0])
        return self._cached

    def execute(self, pid, tctx):
        yield self.broadcast_batch(tctx)
