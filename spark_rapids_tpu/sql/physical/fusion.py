"""Whole-stage fusion — one compiled XLA program per pipeline segment.

The reference gets kernel fusion two ways: cuDF fuses within a kernel, and
tiered projection dedups subexpressions (``basicPhysicalOperators.scala:500``).
On TPU the equivalent (and bigger) lever is compiling a whole
filter→project→…[→partial-agg] chain as ONE jitted program:

* fused filters don't compact — the predicate ANDs into a live-row mask that
  threads through the stage (one compaction at the stage end, or none at all
  when the terminal is a hash aggregate, which consumes the mask directly);
* XLA fuses the elementwise project math into its consumers;
* no intermediate batch materialization between member ops.

The planner pass (``fuse_stages``) runs after transition insertion and only
touches same-backend TPU chains; the CPU fallback path keeps per-op
execution, which also keeps it a more independent oracle.
"""

from __future__ import annotations

from typing import List

from ...columnar.batch import ColumnarBatch
from .base import TPU, PhysicalPlan
from .basic import FilterExec, ProjectExec, compact_batch


class FusedStageExec(PhysicalPlan):
    """A chain of Filter/Project members compiled as one program with a
    single terminal compaction."""

    def __init__(self, members: List[PhysicalPlan], child: PhysicalPlan):
        super().__init__(child)
        self.backend = TPU
        self.members = members  # producer -> consumer order
        key = ("stage",) + tuple(m._fuse_key() for m in members)
        self._fn = self._jit(self._compute, key=key)

    @property
    def output(self):
        return self.members[-1].output

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        xp = self.xp
        mask = batch.row_mask()
        for m in self.members:
            batch, mask = m._fuse_step(batch, mask, xp)
        return compact_batch(xp, batch, mask)

    def execute(self, pid, tctx):
        for batch in self.children[0].execute(pid, tctx):
            tctx.inc_metric("fusedStageBatches")
            yield self._fn(batch)

    def simple_string(self):
        inner = " -> ".join(m.node_name() for m in self.members)
        return f"{self.node_name()} [{inner}]"


def _fusible(plan: PhysicalPlan) -> bool:
    return (isinstance(plan, (FilterExec, ProjectExec))
            and plan.backend == TPU
            and not plan._placement_reasons)


def _collect_chain(plan: PhysicalPlan):
    """Walk down through fusible ops; returns (members bottom-up, child)."""
    chain = []
    node = plan
    while _fusible(node):
        chain.append(node)
        node = node.children[0]
    chain.reverse()  # producer first
    return chain, node


def fuse_stages(plan: PhysicalPlan) -> PhysicalPlan:
    """Bottom-up rewrite: absorb Filter/Project chains into their terminal
    hash aggregate's partial kernel, and collapse remaining chains of >= 2
    map ops into a FusedStageExec."""
    from .aggregate import HashAggregateExec

    if (isinstance(plan, HashAggregateExec) and plan.backend == TPU
            and plan.mode in ("partial", "complete")):
        chain, below = _collect_chain(plan.children[0])
        if chain:
            plan.absorb_pre_steps(chain, below)

    if _fusible(plan):
        chain, below = _collect_chain(plan)
        if len(chain) >= 2:
            fused = FusedStageExec(chain, below)
            fused.children = (fuse_stages(below),)
            return fused

    plan.children = tuple(fuse_stages(c) for c in plan.children)
    return plan
