"""Whole-stage fusion — one compiled XLA program per pipeline stage.

The reference gets kernel fusion two ways: cuDF fuses within a kernel, and
tiered projection dedups subexpressions (``basicPhysicalOperators.scala:500``).
On TPU the equivalent (and bigger) lever is compiling a whole
scan→filter→project→…→terminal chain as ONE jitted program:

* fused filters don't compact — the predicate ANDs into a live-row mask that
  threads through the stage (one compaction at the stage end, or none at all
  when the terminal is a hash aggregate or a join probe, which consume the
  mask directly);
* XLA fuses the elementwise project math into its consumers;
* no intermediate batch materialization between member ops.

Stage shapes (docs/whole_stage.md):

* **map stage** — a chain of >= 2 Filter/Project ops compiled as one
  program with a single terminal compaction.  The only shape eligible for
  input-buffer DONATION (``wholeStage.donation.enabled``): when the
  retention registry (memory/retention.py) proves the input batch is
  sole-owner, the program is built with ``donate_argnums`` so the output
  reuses the input's HBM.  Terminal stages never donate — their inputs
  are registered with the spill tier for the OOM retry protocol.
* **aggregate terminal** — ``HashAggregateExec`` (partial/complete)
  absorbs the upstream chain into its own partial/group/reduce programs
  (``absorb_pre_steps``) and the whole stage appears as one
  ``FusedStageExec`` node wrapping the aggregate.
* **probe terminal** — a hash join absorbs the probe-side chain
  (``BaseJoinExec.absorb_probe_steps``); the fused filter mask feeds the
  probe search directly and the cached build-side artifact enters the
  program as a cross-call constant.  The join node itself is the stage
  node (wrapping both children would desynchronize the probe/build
  references the async planner pass relies on).
* **sort/window terminal** — ``SortExec`` absorbs the upstream chain
  into its first-touch program (``absorb_pre_steps``); ``WindowExec``
  additionally absorbs the planner's partition sort (``absorb_sort``) so
  single-chunk inputs evaluate chain + sort + window in ONE program.
  Gated by ``wholeStage.sortWindowTerminal.enabled``.

Map stages additionally run the **dispatch coalescer**
(``dispatch.coalesce.{enabled,maxBatches,maxRows}``): consecutive
same-signature small batches are stacked on a leading axis INSIDE one
jitted program and the stage computation is vmapped over them — N
batches, one real device launch (``deviceDispatches`` counts launches;
the ``stage`` trace span carries ``coalesced_n``).

Programs are built LAZILY on first execute under one stage-signature
kernel-cache key (member ``_fuse_key``s + encode params + input layout),
so AQE-replanned or CPU-fallback-discarded plans register nothing.

The planner pass (``fuse_stages``) runs after transition insertion and only
touches same-backend TPU chains; the CPU fallback path keeps per-op
execution, which also keeps it a more independent oracle.
"""

from __future__ import annotations

import time
from typing import List, Optional

from ...columnar.batch import ColumnarBatch
from ...memory import retention as _ret
from ...observability import tracer as _trace
from .base import TPU, PhysicalPlan
from .basic import FilterExec, ProjectExec, compact_batch


def _col_coalesce_sig(c):
    """Structural stack-compatibility signature for one column, or None
    when the column can't coalesce (encoded columns carry per-dictionary
    aux data — content hashes — that break the common treedef)."""
    from ...columnar.column import DeviceColumn
    if type(c) is not DeviceColumn:
        return None
    kids = tuple(_col_coalesce_sig(ch) for ch in c.children)
    if any(k is None for k in kids):
        return None
    return (str(c.dtype),
            None if c.data is None else (tuple(c.data.shape),
                                         str(c.data.dtype)),
            None if c.validity is None else tuple(c.validity.shape),
            None if c.lengths is None else str(c.lengths.dtype),
            None if c.aux is None else (tuple(c.aux.shape),
                                        str(c.aux.dtype)),
            kids)


def coalesce_signature(batch: ColumnarBatch):
    """Batches with equal signatures stack leaf-for-leaf into one
    batch-of-batches launch (same names, capacity bucket, and per-column
    array structure — string widths included).  None = not coalescible."""
    sigs = tuple(_col_coalesce_sig(c) for c in batch.columns)
    if any(s is None for s in sigs):
        return None
    return (batch.names, batch.capacity, sigs)


class FusedStageExec(PhysicalPlan):
    """A whole pipeline stage: a chain of Filter/Project members plus an
    optional terminal (hash aggregate), compiled as one program."""

    def __init__(self, members: List[PhysicalPlan], child: PhysicalPlan,
                 terminal: Optional[PhysicalPlan] = None):
        super().__init__(child)
        self.backend = TPU
        self.members = list(members)  # producer -> consumer order
        #: stage terminal (HashAggregateExec partial/complete) — owns the
        #: fused programs via its absorbed pre-steps; execution delegates
        self.terminal = terminal
        #: donate(bool) -> compiled program; built lazily on first execute
        #: (plan-construction must register nothing in the kernel cache)
        self._fns: dict = {}

    @property
    def output(self):
        if self.terminal is not None:
            return self.terminal.output
        return self.members[-1].output

    def num_partitions(self):
        return self.children[0].num_partitions()

    def _stage_key(self, conf):
        """The ONE stage-signature kernel-cache key replacing the members'
        per-op keys: member fuse keys + encode params + input layout."""
        from ...columnar.encoded import encode_params
        layout = tuple((a.name, str(a.dtype))
                       for a in self.children[0].output)
        return (("stage",) + tuple(m._fuse_key() for m in self.members)
                + (encode_params(conf), layout))

    def _get_fn(self, donate: bool, conf):
        fn = self._fns.get(donate)
        if fn is None:
            key = self._stage_key(conf) + (("donate",) if donate else ())
            fn = self._jit(self._compute, key=key,
                           donate_argnums=(0,) if donate else None)
            self._fns[donate] = fn
        return fn

    def _get_coalesced_fn(self, n: int, conf):
        """One program for N stacked same-signature batches: the stack,
        the vmapped stage computation, AND the unstack all trace into a
        single jitted program — exactly one real device launch replaces
        N (the dispatch coalescer, docs/whole_stage.md).  Coalesced
        groups never donate (N inputs share one program invocation; the
        sole-owner proof is per-batch)."""
        key = ("coalesce", n)
        fn = self._fns.get(key)
        if fn is None:
            def impl(*batches):
                import jax
                xp = self.xp
                stacked = jax.tree_util.tree_map(
                    lambda *ls: xp.stack(ls), *batches)
                outs = jax.vmap(self._compute)(stacked)
                return tuple(
                    jax.tree_util.tree_map(lambda l, i=i: l[i], outs)
                    for i in range(n))
            fn = self._jit(impl,
                           key=self._stage_key(conf) + (("coalesce", n),))
            self._fns[key] = fn
        return fn

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        xp = self.xp
        mask = batch.row_mask()
        for m in self.members:
            batch, mask = m._fuse_step(batch, mask, xp)
        return compact_batch(xp, batch, mask)

    def _donation_on(self, tctx) -> bool:
        from ...config import WHOLE_STAGE_DONATION
        return (self.terminal is None
                and bool(tctx.conf.get(WHOLE_STAGE_DONATION)))

    def _stage_label(self) -> str:
        inner = "+".join(m.node_name() for m in self.members)
        if self.terminal is not None:
            inner += "+" + self.terminal.node_name()
        return f"stage.{inner}"

    def execute(self, pid, tctx):
        if self.terminal is not None:
            yield from self._execute_terminal(pid, tctx)
            return
        donate_on = self._donation_on(tctx)
        label = self._stage_label()
        from ...config import (DISPATCH_COALESCE_ENABLED,
                               DISPATCH_COALESCE_MAX_BATCHES,
                               DISPATCH_COALESCE_MAX_ROWS)
        co_max = (int(tctx.conf.get(DISPATCH_COALESCE_MAX_BATCHES))
                  if bool(tctx.conf.get(DISPATCH_COALESCE_ENABLED)) else 1)
        co_rows = int(tctx.conf.get(DISPATCH_COALESCE_MAX_ROWS))

        def run_one(batch):
            tctx.inc_metric("fusedStageBatches")
            tctx.inc_metric("wholeStageDispatches")
            tctx.inc_metric("stageOpDispatches")
            donate = False
            if donate_on:
                donate, _why = _ret.may_donate(batch)
                if donate:
                    tctx.inc_metric("wholeStageDonatedBatches")
                    _ret.count_donated()
                else:
                    tctx.inc_metric("wholeStageDonationDeclined")
            fn = self._get_fn(donate, tctx.conf)
            with _trace.span("stage", label, partition=pid):
                out = fn(batch)
            return _ret.mark_transient(out)

        pending: list = []
        pending_sig = None

        def flush():
            nonlocal pending, pending_sig
            group, pending, pending_sig = pending, [], None
            if not group:
                return
            if len(group) == 1:
                yield run_one(group[0])
                return
            n = len(group)
            tctx.inc_metric("fusedStageBatches", n)
            tctx.inc_metric("wholeStageDispatches")
            tctx.inc_metric("stageOpDispatches")
            tctx.inc_metric("dispatchCoalescedBatches", n)
            tctx.inc_metric("dispatchCoalescedLaunches")
            fn = self._get_coalesced_fn(n, tctx.conf)
            with _trace.span("stage", label, partition=pid,
                             coalesced_n=n):
                outs = fn(*group)
            for out in outs:
                yield _ret.mark_transient(out)

        for batch in self.children[0].execute(pid, tctx):
            if co_max > 1 and batch.num_rows_bound <= co_rows:
                sig = coalesce_signature(batch)
                if sig is not None:
                    if pending and sig != pending_sig:
                        yield from flush()
                    pending.append(batch)
                    pending_sig = sig
                    if len(pending) >= co_max:
                        yield from flush()
                    continue
            yield from flush()
            yield run_one(batch)
        yield from flush()

    def _execute_terminal(self, pid, tctx):
        """Delegate to the terminal exec (its absorbed pre-steps ARE the
        fused stage program).  The terminal's child references are re-synced
        from this node's children first, so planner rewrites applied above
        this node (async prefetch wrappers, AQE substitutions) stay
        visible to the delegated execution.  Under the parallel partition
        scheduler every task writes the SAME post-planning tuple, so the
        concurrent re-sync is idempotent."""
        t = self.terminal
        t.children = self.children
        label = self._stage_label()
        tracing = _trace.TRACING["on"]
        it = t.execute(pid, tctx)
        while True:
            t0 = time.perf_counter() if tracing else 0.0
            try:
                batch = next(it)
            except StopIteration:
                return
            if tracing:
                _trace.get_tracer().complete(
                    "stage", label, t0, time.perf_counter() - t0,
                    partition=pid)
            tctx.inc_metric("fusedStageBatches")
            yield batch

    def simple_string(self):
        inner = " -> ".join(m.node_name() for m in self.members)
        if self.terminal is not None:
            inner += (" -> " if inner else "") \
                + self.terminal.simple_string()
        return f"{self.node_name()} [{inner}]"


def _fusible(plan: PhysicalPlan) -> bool:
    return (isinstance(plan, (FilterExec, ProjectExec))
            and plan.backend == TPU
            and not plan._placement_reasons)


def _collect_chain(plan: PhysicalPlan):
    """Walk down through fusible ops; returns (members bottom-up, child)."""
    chain = []
    node = plan
    while _fusible(node):
        chain.append(node)
        node = node.children[0]
    chain.reverse()  # producer first
    return chain, node


def fuse_stages(plan: PhysicalPlan, conf=None) -> PhysicalPlan:
    """Bottom-up rewrite: absorb Filter/Project chains into their terminal
    hash aggregate's partial kernel or a hash join's probe phase (stage
    terminals, gated by ``spark.rapids.tpu.sql.wholeStage.enabled``), and
    collapse remaining chains of >= 2 map ops into a FusedStageExec."""
    from ...config import (WHOLE_STAGE_ENABLED, WHOLE_STAGE_SORT_WINDOW,
                           RapidsConf)
    from .aggregate import HashAggregateExec
    from .join import BroadcastHashJoinExec, ShuffledHashJoinExec
    from .sortlimit import SortExec
    from .window import WindowExec

    conf = conf or RapidsConf.get_global()
    whole = bool(conf.get(WHOLE_STAGE_ENABLED))
    sortwin = whole and bool(conf.get(WHOLE_STAGE_SORT_WINDOW))

    if (whole and isinstance(plan, HashAggregateExec)
            and plan.backend == TPU
            and plan.mode in ("partial", "complete")):
        chain, below = _collect_chain(plan.children[0])
        if chain:
            plan.absorb_pre_steps(chain, below)
            fused = FusedStageExec(chain, below, terminal=plan)
            fused.children = (fuse_stages(below, conf),)
            return fused

    if (sortwin and isinstance(plan, WindowExec) and plan.backend == TPU
            and plan._sorter is None
            and isinstance(plan.children[0], SortExec)
            and plan.children[0].backend == TPU
            and not plan.children[0]._pre_steps
            and plan.can_absorb_sort(plan.children[0])):
        # window terminal: absorb the planner's partition sort (and any
        # chain below it) — single-chunk inputs run chain + sort +
        # window as ONE program
        sort = plan.children[0]
        chain, below = _collect_chain(sort.children[0])
        if chain:
            sort.absorb_pre_steps(chain, below)
        plan.absorb_sort(sort)
        if chain:
            fused = FusedStageExec(chain, below, terminal=plan)
            fused.children = (fuse_stages(below, conf),)
            return fused
        plan.children = tuple(fuse_stages(c, conf) for c in plan.children)
        return plan

    if (sortwin and isinstance(plan, SortExec) and plan.backend == TPU
            and not plan._pre_steps):
        chain, below = _collect_chain(plan.children[0])
        if chain:
            plan.absorb_pre_steps(chain, below)
            fused = FusedStageExec(chain, below, terminal=plan)
            fused.children = (fuse_stages(below, conf),)
            return fused

    if (whole and plan.backend == TPU
            and isinstance(plan, (ShuffledHashJoinExec,
                                  BroadcastHashJoinExec))):
        pi = 1 if plan._flipped else 0
        chain, below = _collect_chain(plan.children[pi])
        if chain:
            plan.absorb_probe_steps(chain, below)

    if _fusible(plan):
        chain, below = _collect_chain(plan)
        if len(chain) >= 2:
            fused = FusedStageExec(chain, below)
            fused.children = (fuse_stages(below, conf),)
            return fused

    plan.children = tuple(fuse_stages(c, conf) for c in plan.children)
    return plan


def annotate_stage_coverage(plan: PhysicalPlan) -> PhysicalPlan:
    """Record plan-time fusion coverage on the root's metrics:
    ``wholeStageOps`` counts ops executing inside a fused stage program
    (map members + terminals), ``unfusedOps`` counts stage-eligible ops
    (Filter/Project/partial-or-complete HashAggregate/hash-join probes)
    left on per-op dispatch.  Folded into last_query_metrics via the
    standard collect_metrics walk."""
    from .aggregate import HashAggregateExec
    from .collect_fusion import FusedCollectExec
    from .join import BaseJoinExec, NestedLoopJoinExec
    from .window import WindowExec

    fused = unfused = 0
    stack = [plan]
    while stack:
        n = stack.pop()
        if isinstance(n, FusedStageExec):
            fused += len(n.members) + (1 if n.terminal is not None else 0)
            if getattr(n.terminal, "_sorter", None) is not None:
                fused += 1  # the window terminal's absorbed partition sort
        elif isinstance(n, WindowExec) \
                and getattr(n, "_sorter", None) is not None:
            fused += 2  # sort-only absorption: window + its partition sort
        elif isinstance(n, FusedCollectExec):
            fused += 1 + len(getattr(n._agg, "_pre_steps", ()))
        elif isinstance(n, (FilterExec, ProjectExec)):
            unfused += 1
        elif isinstance(n, HashAggregateExec) \
                and n.mode in ("partial", "complete"):
            if n._pre_steps:
                fused += 1 + len(n._pre_steps)
            else:
                unfused += 1
        elif isinstance(n, BaseJoinExec) \
                and not isinstance(n, NestedLoopJoinExec):
            steps = getattr(n, "_probe_steps", ())
            if steps:
                fused += 1 + len(steps)
            else:
                unfused += 1
        stack.extend(n.children)
    plan.metrics["wholeStageOps"] = float(fused)
    plan.metrics["unfusedOps"] = float(unfused)
    return plan
