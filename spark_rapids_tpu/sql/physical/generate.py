"""Generate exec (explode/posexplode) — reference ``GpuGenerateExec.scala``
(793 LoC; SURVEY §2.3).

TPU shape strategy: an exploded batch has at most ``cap * w`` rows (every
slot of every row), a static bound.  We build the flat slot grid, compact
live slots to the front with one stable argsort (keeping row-major order =
Spark's output order), and gather both the repeated input columns and the
element column through the same permutation."""

from __future__ import annotations

from ... import types as T
from ...columnar.batch import ColumnarBatch
from ...columnar.column import DeviceColumn
from ..expressions.collections import Explode
from ..expressions.core import EvalContext, bind_references
from .base import TPU, PhysicalPlan


class GenerateExec(PhysicalPlan):
    def __init__(self, generator: Explode, outer: bool, gen_output,
                 child: PhysicalPlan, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.generator = generator
        self.outer = outer
        self.gen_output = list(gen_output)
        self._bound = bind_references(generator, child.output)
        from .kernel_cache import expr_key
        self._fn = self._jit(
            self._compute,
            key=(expr_key(self._bound), self.outer,
                 tuple(a.name for a in self.gen_output)))

    @property
    def output(self):
        return list(self.children[0].output) + self.gen_output

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        xp = self.xp
        ctx = EvalContext(batch, xp=xp)
        arr = self._bound.children[0].eval(ctx)
        cap = batch.capacity
        w = arr.array_width
        live_rows = batch.row_mask()

        j = xp.arange(w, dtype=xp.int32)[None, :]
        slot_live = (j < arr.lengths[:, None]) & arr.validity[:, None] & \
            live_rows[:, None]
        if self.outer:
            # rows with empty/null collections still emit one all-null row
            empty = live_rows & (~arr.validity | (arr.lengths == 0))
            slot_live = slot_live | (empty[:, None] & (j == 0))
        flat_keep = slot_live.reshape(-1)

        # stable compaction keeps (row, slot) order
        if xp.__name__ == "numpy":
            import numpy as np
            perm = np.argsort(~flat_keep, kind="stable")
        else:
            perm = xp.argsort(~flat_keep, stable=True)
        perm = perm.astype(xp.int32)
        n_out = xp.sum(flat_keep).astype(xp.int32)
        kept = flat_keep[perm]

        # repeated input columns: source row = perm // w
        row_idx = perm // w
        out_cols = [c.gather(row_idx, kept) for c in batch.columns]

        elem_valid_mask = kept
        if self.outer:
            # synthetic slots (empty/null collections) yield all-null
            # generator outputs, including pos (Spark emits (null, null))
            real = (arr.validity[row_idx] &
                    ((perm % w) < arr.lengths[row_idx]))
            elem_valid_mask = kept & real
        gen_cols = []
        if self.generator.with_position:
            pos = (perm % w).astype(xp.int32)
            gen_cols.append(DeviceColumn(T.INT, pos, elem_valid_mask))
        if isinstance(arr.dtype, T.MapType):
            gen_cols.append(arr.children[0].gather(perm, elem_valid_mask))
            gen_cols.append(arr.children[1].gather(perm, elem_valid_mask))
        else:
            gen_cols.append(arr.children[0].gather(perm, elem_valid_mask))

        names = tuple(a.name for a in self.output)
        return ColumnarBatch(names, tuple(out_cols) + tuple(gen_cols), n_out)

    def execute(self, pid, tctx):
        for batch in self.children[0].execute(pid, tctx):
            out = self._fn(batch)
            if out.num_rows_int:
                yield out

    def simple_string(self):
        return f"{self.node_name()} [{self.generator.sql()}]"
