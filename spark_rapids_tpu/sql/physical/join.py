"""Join execs — the analog of the reference's join family (SURVEY §2.3):
``GpuShuffledHashJoinExec`` (440 LoC), ``GpuBroadcastHashJoinExecBase``,
``GpuBroadcastNestedLoopJoinExecBase``, ``GpuCartesianProductExec``,
``ExistenceJoin``, with gather-map construction in ``GpuHashJoin.scala:298``
and chunked output via ``JoinGatherer.scala``.

TPU shape discipline: phase 1 (``ops/join.join_build``) is one compiled
program per (probe-cap, build-cap); the host reads three scalar totals to
pick an output capacity bucket; phase 2 gathers + evaluates any residual
(non-equi) condition + assembles the join-type-specific output, one compiled
program per (caps, out-cap).  Sort-merge joins are replaced by shuffled hash
joins exactly like the reference (``GpuSortMergeJoinMeta.scala``).
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Sequence, Tuple

from ... import types as T
from ...columnar.batch import ColumnarBatch
from ...observability import tracer as _tracer
from ...columnar.column import bucket_capacity
from ...ops.join import (JoinBuildSide, JoinInfo, compact_indices,
                         cross_pairs, fastpath_supported, gather_pairs,
                         join_build, matched_per_row, PairMaps,
                         prepare_build_side, probe_join_info)
from ..expressions.core import (AttributeReference, EvalContext, Expression,
                                bind_references)
from .base import PROFILING, TPU, PhysicalPlan, TaskContext
from .exchange import BroadcastExchangeExec

_PAIR_JOINS = ("inner", "left", "full", "cross")
_FILTER_JOINS = ("left_semi", "left_anti", "existence")

#: observability for tests: build_sorts counts ACTUAL build-side sort
#: program executions (a broadcast join with B probe batches must show 1,
#: not B); host_readbacks counts blocking device->host scalar fetches on
#: the sizing path; spec_hits/spec_misses track speculative output sizing
STATS = {"chunked_joins": 0, "build_sorts": 0, "fastpath_probes": 0,
         "fallback_probes": 0, "spec_hits": 0, "spec_misses": 0,
         "host_readbacks": 0, "fused_probes": 0}

#: realized join selectivity (inner pairs per probe row) per program
#: identity — the speculative output-sizing seed, learned from the first
#: batch so later batches dispatch their gather without waiting for the
#: count readback (aggregate.py _OUT_SPECULATION analog; cleared with the
#: kernel cache)
_JOIN_SELECTIVITY: Dict[tuple, float] = {}
#: guards the selectivity dict against concurrent sessions — a plain-dict
#: read-modify-write racing a clear could resurrect state for a dead
#: kernel-cache generation (docs/serving.md clearing contract)
_SEL_LOCK = threading.Lock()


def record_selectivity(spec_key, sel: float,
                       generation: Optional[int] = None) -> None:
    """Record observed selectivity, max-joined: a low-match tail batch
    must not shrink the prediction a dense batch needs (which would make
    every later dense batch mis-speculate and gather twice, forever).

    ``generation`` is the kernel-cache generation the caller captured
    when it LOOKED UP the prediction; if the cache was cleared in
    between, the write is dropped — a concurrent clearKernelCache must
    never be repopulated with learning from the dead generation."""
    from .kernel_cache import cache_generation
    with _SEL_LOCK:
        if generation is not None and generation != cache_generation():
            STATS["stale_selectivity_drops"] = \
                STATS.get("stale_selectivity_drops", 0) + 1
            return
        if len(_JOIN_SELECTIVITY) > 1024:
            # keys embed literals (kernel-cache rule)
            _JOIN_SELECTIVITY.clear()
        prev = _JOIN_SELECTIVITY.get(spec_key, 0.0)
        _JOIN_SELECTIVITY[spec_key] = max(prev, sel)


def lookup_selectivity(spec_key) -> Optional[float]:
    with _SEL_LOCK:
        return _JOIN_SELECTIVITY.get(spec_key)


def clear_selectivity() -> None:
    """Called by kernel_cache.clear_cache AFTER the generation bump —
    the bump-then-clear order is what makes racing recorders drop."""
    with _SEL_LOCK:
        _JOIN_SELECTIVITY.clear()


class BaseJoinExec(PhysicalPlan):
    """Shared machinery: side normalization (right joins flip to left),
    output schema, pair gathering, residual-condition assembly."""

    def __init__(self, how: str, left_keys: Sequence[Expression],
                 right_keys: Sequence[Expression],
                 condition: Optional[Expression],
                 left: PhysicalPlan, right: PhysicalPlan, backend=TPU):
        super().__init__(left, right)
        self.backend = backend
        self.how = how
        self.condition = condition
        #: one-shot per-join setup (bloom install, AQE choice) must run
        #: exactly once even when the parallel partition scheduler drives
        #: several probe partitions into execute concurrently
        self._setup_lock = threading.Lock()
        self._flipped = how == "right"
        if self._flipped:
            # right outer == left outer with sides swapped + column reorder
            self._probe, self._build = right, left
            self._probe_keys, self._build_keys = list(right_keys), list(left_keys)
            self._norm_how = "left"
        else:
            self._probe, self._build = left, right
            self._probe_keys, self._build_keys = list(left_keys), list(right_keys)
            self._norm_how = how

        self._out_left = list(left.output)
        self._out_right = list(right.output)
        #: pair-layout schemas, frozen at construction: absorb_probe_steps
        #: rewires self._probe BELOW the fused chain, but the pair batch is
        #: built from the POST-chain probe the join was bound against
        self._probe_attrs = list(self._probe.output)
        self._build_attrs = list(self._build.output)
        self._bound_pkeys = [bind_references(e, self._probe.output)
                             for e in self._probe_keys]
        self._bound_bkeys = [bind_references(e, self._build.output)
                             for e in self._build_keys]
        # pair-batch layout: [probe cols][build cols]
        pair_attrs = list(self._probe.output) + list(self._build.output)
        self._bound_cond = (bind_references(condition, pair_attrs)
                            if condition is not None else None)
        from .kernel_cache import expr_key, exprs_key
        self._sig = (self._norm_how, self._flipped,
                     exprs_key(self._bound_pkeys),
                     exprs_key(self._bound_bkeys),
                     expr_key(self._bound_cond)
                     if self._bound_cond is not None else None,
                     tuple(a.name for a in self.output))
        #: whole-stage probe terminal (docs/whole_stage.md): a fused
        #: upstream Filter/Project chain applied INSIDE every probe-side
        #: program — the fused filter mask feeds the probe search
        #: directly, nothing compacts or materializes between the scan
        #: and the search
        self._probe_steps: tuple = ()
        self._gather_cache: Dict[int, object] = {}
        # programs built lazily on first use (whole-stage laziness
        # contract — AQE shape-only instances register nothing)
        self._build_fn = None
        self._prep_fn = None
        self._probe_fn = None
        # join fast path: build-side sort cached per build batch + probe-only
        # tuple search; array/map keys keep the union-rank fallback
        self._fast_ok = fastpath_supported(
            [e.data_type for e in self._bound_pkeys + self._bound_bkeys])
        self._bs_key = ("bs", exprs_key(self._bound_bkeys))

    # --- whole-stage probe fusion ----------------------------------------
    def absorb_probe_steps(self, steps, new_probe: PhysicalPlan) -> None:
        """Fuse an upstream probe-side Filter/Project chain into this
        join's probe phase (fusion.py).  The chain reproduced the probe
        schema this join was bound against, so bound keys/conditions and
        the output layout stay valid; fused filters contribute a live-row
        mask consumed by the probe search instead of compacting.  The
        stage signature joins ``_sig``, so probe/gather programs never
        alias their unfused counterparts, and the compiled-fn caches are
        reset (they are lazy, so nothing was registered yet at plan
        time)."""
        self._probe_steps = tuple(steps)
        self._probe = new_probe
        kids = list(self.children)
        kids[1 if self._flipped else 0] = new_probe
        self.children = tuple(kids)
        self._sig = self._sig + (
            ("stage",) + tuple(s._fuse_key() for s in steps),)
        self._build_fn = None
        self._probe_fn = None
        self._gather_cache = {}

    def _apply_probe_steps(self, probe: ColumnarBatch, xp):
        """(post-chain batch, live mask) — runs INSIDE jitted programs;
        elementwise step math re-evaluated per program fuses into its
        consumer, costing zero extra dispatches."""
        mask = probe.row_mask()
        for s in self._probe_steps:
            probe, mask = s._fuse_step(probe, mask, xp)
        return probe, mask

    def _get_build_fn(self):
        if self._build_fn is None:
            self._build_fn = self._jit(self._build_info,
                                       key=("build", self._sig))
        return self._build_fn

    def _get_prep_fn(self):
        if self._prep_fn is None:
            self._prep_fn = self._jit(self._prepare_build,
                                      key=("prep", self._bs_key))
        return self._prep_fn

    def _get_probe_fn(self):
        if self._probe_fn is None:
            self._probe_fn = self._jit(self._probe_info,
                                       key=("probe", self._sig))
        return self._probe_fn

    # --- schema -----------------------------------------------------------
    @property
    def output(self) -> List[AttributeReference]:
        how = self.how
        lo = list(self._out_left)
        ro = list(self._out_right)
        if how in ("left_semi", "left_anti"):
            return lo
        if how == "existence":
            return lo + [AttributeReference("exists", T.BOOLEAN, False)]
        def _nullable(attrs):
            return [AttributeReference(a.name, a.dtype, True, a.expr_id)
                    for a in attrs]
        if how == "left":
            ro = _nullable(ro)
        elif how == "right":
            lo = _nullable(lo)
        elif how == "full":
            lo, ro = _nullable(lo), _nullable(ro)
        return lo + ro

    # --- phase 1 ----------------------------------------------------------
    def _build_info(self, probe: ColumnarBatch, build: ColumnarBatch
                    ) -> JoinInfo:
        xp = self.xp
        probe, lmask = self._apply_probe_steps(probe, xp)
        pctx = EvalContext(probe, xp=xp)
        bctx = EvalContext(build, xp=xp)
        pkeys = [e.eval(pctx) for e in self._bound_pkeys]
        bkeys = [e.eval(bctx) for e in self._bound_bkeys]
        return join_build(xp, pkeys, bkeys, lmask, build.row_mask())

    def _prepare_build(self, build: ColumnarBatch) -> JoinBuildSide:
        """Fast-path phase 0: sort the build side's key tuples (one jitted
        program per build capacity, result cached on the build batch)."""
        xp = self.xp
        bctx = EvalContext(build, xp=xp)
        bkeys = [e.eval(bctx) for e in self._bound_bkeys]
        return prepare_build_side(xp, bkeys, build.row_mask())

    def _probe_info(self, probe: ColumnarBatch, build: ColumnarBatch,
                    bs: JoinBuildSide) -> JoinInfo:
        """Fast-path phase 1: probe-only — fused probe steps + key
        transform + one multi-key binary search against the pre-sorted
        build side (plus run-end lookups).  With absorbed probe steps the
        fused filter mask IS the probe live mask: filtered-out rows are
        dead rows to the search, exactly like compaction padding.
        Build-unmatched flags are only materialized for full joins, the
        one type that emits them (_norm_how is in the jit sig, so the
        static flag can't alias programs)."""
        xp = self.xp
        probe, lmask = self._apply_probe_steps(probe, xp)
        pctx = EvalContext(probe, xp=xp)
        pkeys = [e.eval(pctx) for e in self._bound_pkeys]
        return probe_join_info(
            xp, pkeys, lmask, build.row_mask(), bs,
            need_b_matched=self._norm_how == "full",
            need_l_unmatched=self._norm_how in ("left", "full"))

    #: tracer category per join stage: the sizing readback is a blocking
    #: device sync; every other stage is host-side dispatch work
    _STAGE_CAT = {"readback": "sync"}

    @contextmanager
    def _stage(self, tctx: Optional[TaskContext], name: str):
        """Per-stage join profiling: a jax.profiler TraceAnnotation around
        the host-side stage (dispatch or blocking fetch) plus a wall-time
        metric in last_query_metrics (joinStage<Name>Ms) and a tracer
        span (cat ``sync`` for the sizing readback)."""
        ann = None
        if PROFILING["on"] and self.backend == TPU:
            import jax.profiler
            ann = jax.profiler.TraceAnnotation(f"join:{name}")
            ann.__enter__()
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if tctx is not None:
                tctx.inc_metric(f"joinStage{name[0].upper()}{name[1:]}Ms",
                                dt * 1e3)
            if _tracer.TRACING["on"]:
                _tracer.get_tracer().complete(
                    self._STAGE_CAT.get(name, "op"), f"join.{name}", t0, dt)
            if ann is not None:
                ann.__exit__(None, None, None)

    def _fast_path_on(self, tctx: Optional[TaskContext]) -> bool:
        if not self._fast_ok:
            return False
        from ...config import JOIN_BUILD_CACHE_ENABLED
        conf = tctx.conf if tctx is not None else None
        if conf is None:
            from ...config import RapidsConf
            conf = RapidsConf.get_global()
        return bool(conf.get(JOIN_BUILD_CACHE_ENABLED))

    def _fused_probe_on(self, tctx: Optional[TaskContext]) -> bool:
        """Single-program probe pipeline kill switch: probe search +
        run-end expansion + pair generation + the all-columns gather ride
        ONE compiled program that also returns the sizing scalars."""
        from ...config import JOIN_FUSED_PROBE
        conf = tctx.conf if tctx is not None else None
        if conf is None:
            from ...config import RapidsConf
            conf = RapidsConf.get_global()
        return bool(conf.get(JOIN_FUSED_PROBE))

    def _lower_encoded_keys(self, probe: ColumnarBatch, build: ColumnarBatch,
                            tctx: Optional[TaskContext]
                            ) -> Tuple[ColumnarBatch, ColumnarBatch]:
        """Encoded join lowering (docs/encoded_columns.md): for every key
        pair that is a bare column reference to a dict-encoded string
        column on BOTH sides, remap the probe side's codes into the build
        dictionary's (sorted) code space and mark both columns with
        ``join_codes`` — the jitted join programs then sort/search ONE
        int32 key per string key instead of width/8 byte-chunk keys.

        Invariant kept pairwise: a key position either carries join_codes
        on BOTH sides or on NEITHER (a one-sided marking would make
        ``join_search_keys`` emit mismatched key structures).  The lowered
        build batch shares the original's build-side artifact cache; its
        lowering signature joins the cache key so code-space and raw sorts
        never alias."""
        from ...columnar import encoded as E
        from ..expressions.core import BoundReference
        conf = tctx.conf if tctx is not None else None
        if not (E.op_enabled("join", conf) and self._fast_ok):
            return probe, build
        from .basic import ProjectExec
        if any(isinstance(s, ProjectExec) for s in self._probe_steps):
            # fused probe projections change the probe schema, so the
            # bound key ordinals no longer address the PRE-chain batch
            # this host-side lowering inspects; decline (bit-identical by
            # the decline-to-materialize property, docs/encoded_columns.md)
            E._bump("join_code_declines")
            return probe, build
        lowered: List[Tuple[int, int, object, object]] = []
        for pk, bk in zip(self._bound_pkeys, self._bound_bkeys):
            if not (isinstance(pk, BoundReference)
                    and isinstance(bk, BoundReference)):
                continue
            pcol = probe.columns[pk.ordinal]
            bcol = build.columns[bk.ordinal]
            if not (isinstance(pcol, E.DictEncodedColumn)
                    and isinstance(bcol, E.DictEncodedColumn)) \
                    or pcol.dtype != bcol.dtype:
                continue
            pair = E.lower_join_codes(pcol, bcol)
            if pair is None:
                E._bump("join_code_declines")
                continue
            lowered.append((pk.ordinal, bk.ordinal) + pair)
        if not lowered:
            return probe, build
        pcols = list(probe.columns)
        bcols = list(build.columns)
        for po, bo, p2, b2 in lowered:
            pcols[po] = p2
            bcols[bo] = b2
        new_probe = ColumnarBatch(probe.names, tuple(pcols), probe.num_rows)
        new_build = ColumnarBatch(build.names, tuple(bcols), build.num_rows)
        for src, dst in ((probe, new_probe), (build, new_build)):
            cached = getattr(src, "_nrows_host", None)
            if cached is not None:
                dst._nrows_host = cached
        # share the artifact cache so the build sort still happens once per
        # (build batch, lowering signature) across all probe batches
        cache = getattr(build, "_join_build_sides", None)
        if cache is None:
            cache = build._join_build_sides = {}
        new_build._join_build_sides = cache
        new_build._enc_lower_sig = tuple(
            (bo, bcols[bo].dictionary.content_hash)
            for _, bo, _, _ in lowered)
        E._bump("join_code_lowerings", len(lowered))
        if tctx is not None:
            tctx.inc_metric("joinCodeLowerings", len(lowered))
        return new_probe, new_build

    def _get_build_side(self, build: ColumnarBatch,
                        tctx: Optional[TaskContext]) -> JoinBuildSide:
        """The build batch's cached :class:`JoinBuildSide` for this join's
        build keys, computing (and caching) it on first use — a broadcast
        build side shared by B probe batches/partitions sorts exactly
        once."""
        cache = getattr(build, "_join_build_sides", None)
        if cache is None:
            cache = {}
            build._join_build_sides = cache
        key = (self.backend,) + self._bs_key \
            + (getattr(build, "_enc_lower_sig", None),)
        bs = cache.get(key)
        if bs is None:
            with self._stage(tctx, "buildSort"):
                bs = self._get_prep_fn()(build)
            STATS["build_sorts"] += 1
            if tctx is not None:
                tctx.inc_metric("joinBuildSorts")
            cache[key] = bs
        return bs

    def _join_info(self, probe: ColumnarBatch, build: ColumnarBatch,
                   tctx: Optional[TaskContext]) -> JoinInfo:
        """Phase 1 dispatch: cached-build-side probe search when the key
        shapes support it, union-rank fallback otherwise.  Both produce
        the same :class:`JoinInfo` contract (parity-tested).  One device
        dispatch either way — the stage-scope dispatch counter's probe
        terminal (fused probe steps ride the same program)."""
        from .base import count_stage_dispatch
        count_stage_dispatch()
        if self._fast_path_on(tctx):
            bs = self._get_build_side(build, tctx)
            STATS["fastpath_probes"] += 1
            if tctx is not None:
                tctx.inc_metric("joinFastpathProbes")
            with self._stage(tctx, "probeSearch"):
                return self._get_probe_fn()(probe, build, bs)
        STATS["fallback_probes"] += 1
        if tctx is not None:
            tctx.inc_metric("joinFallbackProbes")
        with self._stage(tctx, "unionRankBuild"):
            return self._get_build_fn()(probe, build)

    def _fetch_totals(self, info: JoinInfo,
                      tctx: Optional[TaskContext]) -> Tuple[int, int, int]:
        """The ONE blocking host readback per probe batch: all three sizing
        scalars ride a single batched ``jax.device_get`` instead of three
        per-scalar ``int()`` syncs (each a full tunnel round trip)."""
        STATS["host_readbacks"] += 1
        if tctx is not None:
            tctx.inc_metric("joinHostReadbacks")
        with self._stage(tctx, "readback"):
            if self.backend == TPU:
                import jax
                tot, unl, unb = jax.device_get(list(info.sizing_scalars()))
            else:
                tot, unl, unb = info.sizing_scalars()
        return int(tot), int(unl), int(unb)

    # --- phase 2 ----------------------------------------------------------
    def _gather_fn(self, out_cap: int):
        fn = self._gather_cache.get(out_cap)
        if fn is None:
            def impl(probe, build, info):
                return self._gather_impl(probe, build, info, out_cap)
            fn = self._jit(impl, key=("gather", self._sig, out_cap))
            self._gather_cache[out_cap] = fn
        return fn

    def _fused_probe_fn(self, out_cap: int):
        """The single-program probe pipeline (ISSUE 14 tentpole): fused
        probe steps + key transform + multi-key tuple search + run-end
        expansion + pair generation + the pytree-at-once gather of every
        output column on both sides, ONE compiled program per (sig,
        out_cap).  It also returns the :class:`JoinInfo` pytree so the
        sizing scalars for the one batched readback — and the overflow
        re-gather's inputs — ride the same launch instead of a separate
        probe program."""
        key = ("fusedprobe", out_cap)
        fn = self._gather_cache.get(key)
        if fn is None:
            def impl(probe, build, bs):
                info = self._probe_info(probe, build, bs)
                out = self._gather_impl(probe, build, info, out_cap)
                return out, info
            fn = self._jit(impl, key=("fusedprobe", self._sig, out_cap))
            self._gather_cache[key] = fn
        return fn

    def _pair_batch(self, probe: ColumnarBatch, build: ColumnarBatch,
                    maps: PairMaps) -> ColumnarBatch:
        lb = probe.gather(maps.l_idx, maps.l_ok, maps.num_out)
        rb = build.gather(maps.r_idx, maps.r_ok, maps.num_out)
        names = tuple(a.name for a in self._probe_attrs) + \
            tuple(a.name for a in self._build_attrs)
        return ColumnarBatch(names, lb.columns + rb.columns, maps.num_out)

    def _eval_condition(self, pair: ColumnarBatch, inner_ok):
        xp = self.xp
        ctx = EvalContext(pair, xp=xp)
        c = self._bound_cond.eval(ctx)
        return c.data & c.validity & inner_ok

    def _gather_impl(self, probe: ColumnarBatch, build: ColumnarBatch,
                     info: JoinInfo, out_cap: int) -> ColumnarBatch:
        xp = self.xp
        how = self._norm_how
        cond = self._bound_cond
        # fused probe steps re-applied inside this program: the pair
        # gather reads POST-chain columns and the live mask excludes
        # filtered-out probe rows (elementwise recompute, zero extra
        # dispatches — XLA fuses it into the gathers)
        probe, lmask = self._apply_probe_steps(probe, xp)
        lcap, rcap = probe.capacity, build.capacity

        if how in _FILTER_JOINS and cond is None:
            matched = info.counts > 0
            return self._emit_filter_join(probe, matched, lmask)

        if cond is None:
            maps = gather_pairs(xp, info, out_cap,
                                with_unmatched_left=how in ("left", "full"),
                                with_unmatched_right=how == "full")
            pair = self._pair_batch(probe, build, maps)
            return self._project_output(pair, maps)

        # residual condition: inner pairs -> pass mask -> reassemble
        maps = gather_pairs(xp, info, out_cap)
        pair = self._pair_batch(probe, build, maps)
        pass_mask = self._eval_condition(pair, maps.l_ok)

        if how in _FILTER_JOINS:
            matched = matched_per_row(xp, pass_mask, maps.l_idx, lcap) > 0
            return self._emit_filter_join(probe, matched, lmask)

        final = self._assemble_with_pass(probe, build, maps, pass_mask,
                                         out_cap, lmask)
        pair = self._pair_batch(probe, build, final)
        return self._project_output(pair, final)

    def _assemble_with_pass(self, probe: ColumnarBatch, build: ColumnarBatch,
                            maps: PairMaps, pass_mask, out_cap: int,
                            lmask=None) -> PairMaps:
        """Compact pairs surviving the residual condition to the front, then
        append unmatched-left/right rows per the (normalized) join type.
        ``lmask`` is the probe live mask (the fused-stage mask when probe
        steps are absorbed; defaults to the batch's row mask)."""
        xp = self.xp
        how = self._norm_how
        lcap, rcap = probe.capacity, build.capacity
        if lmask is None:
            lmask = probe.row_mask()
        cp = compact_indices(xp, pass_mask)
        n_pass = xp.sum(pass_mask).astype(xp.int64)
        k = xp.arange(out_cap, dtype=xp.int64)
        sel_pair = k < n_pass
        src = cp[xp.clip(k, 0, cp.shape[0] - 1).astype(xp.int32)]
        l_idx = xp.where(sel_pair, maps.l_idx[src], 0)
        r_idx = xp.where(sel_pair, maps.r_idx[src], 0)
        l_ok = sel_pair
        r_ok = sel_pair
        num_out = n_pass

        if how in ("left", "full"):
            m = matched_per_row(xp, pass_mask, maps.l_idx, lcap) > 0
            unl = lmask & ~m
            n_unl = xp.sum(unl.astype(xp.int64))
            ul = compact_indices(xp, unl)
            sel = (k >= num_out) & (k < num_out + n_unl)
            t = xp.clip(k - num_out, 0, lcap - 1).astype(xp.int32)
            l_idx = xp.where(sel, ul[t], l_idx)
            l_ok = l_ok | sel
            num_out = num_out + n_unl
        if how == "full":
            mb = matched_per_row(xp, pass_mask, maps.r_idx, rcap) > 0
            unb = build.row_mask() & ~mb
            n_unb = xp.sum(unb.astype(xp.int64))
            ub = compact_indices(xp, unb)
            sel = (k >= num_out) & (k < num_out + n_unb)
            t = xp.clip(k - num_out, 0, rcap - 1).astype(xp.int32)
            r_idx = xp.where(sel, ub[t], r_idx)
            r_ok = r_ok | sel
            num_out = num_out + n_unb

        return PairMaps(l_idx.astype(xp.int32), r_idx.astype(xp.int32),
                        l_ok, r_ok, num_out.astype(xp.int32))

    def _emit_filter_join(self, probe: ColumnarBatch, matched, lmask=None):
        """semi/anti/existence output (left rows only).  ``lmask`` is the
        probe live mask (the fused-stage mask when probe steps are
        absorbed — filtered-out rows must not resurface here)."""
        xp = self.xp
        how = self._norm_how
        if lmask is None:
            lmask = probe.row_mask()
        if how == "existence":
            from ...columnar.column import DeviceColumn
            ex = DeviceColumn(T.BOOLEAN, matched & lmask,
                              xp.ones_like(matched))
            names = tuple(a.name for a in self._out_left) + ("exists",)
            out = ColumnarBatch(names, probe.columns + (ex,),
                                probe.num_rows)
            if self._probe_steps:
                # fused filters never compacted upstream — rows they
                # dropped must not ride the existence passthrough out
                from .basic import compact_batch
                out = compact_batch(xp, out, lmask)
            return out
        keep = lmask & (matched if how == "left_semi" else ~matched)
        n = xp.sum(keep).astype(xp.int32)
        perm = compact_indices(xp, keep)
        cols = tuple(c.gather(perm, keep[perm]) for c in probe.columns)
        return ColumnarBatch(tuple(a.name for a in self._out_left), cols, n)

    def _project_output(self, pair: ColumnarBatch, maps: PairMaps
                        ) -> ColumnarBatch:
        """Reorder pair columns [probe][build] into [left][right] output."""
        np_, nb = len(self._probe_attrs), len(self._build_attrs)
        if self._flipped:
            idx = list(range(np_, np_ + nb)) + list(range(np_))
        else:
            idx = list(range(np_ + nb))
        names = tuple(a.name for a in self.output)
        cols = tuple(pair.columns[i] for i in idx)
        return ColumnarBatch(names, cols, maps.num_out)

    # --- sizing -----------------------------------------------------------
    def _out_capacity(self, info: JoinInfo, n_probe: int, n_build: int,
                      tctx: Optional[TaskContext] = None) -> int:
        how = self._norm_how
        if how in _FILTER_JOINS and self._bound_cond is None:
            return 8  # unused; filter joins reuse the probe capacity
        total, unl, unb = self._fetch_totals(info, tctx)
        if self._bound_cond is not None:
            extra = (n_probe if how in ("left", "full") else 0) + \
                (n_build if how == "full" else 0)
            return bucket_capacity(total + extra)
        extra = (unl if how in ("left", "full") else 0) + \
            (unb if how == "full" else 0)
        return bucket_capacity(total + extra)

    def _speculative_capacity(self, probe: ColumnarBatch,
                              build: ColumnarBatch,
                              tctx: TaskContext) -> Optional[int]:
        """Predicted output bucket from the learned (or configured initial)
        selectivity — host-only arithmetic on row-count BOUNDS, zero device
        syncs.  Outer-join null-extension slack is bounded exactly (≤ live
        probe/build rows), so only the inner-pair count is a guess."""
        from ...config import (JOIN_INITIAL_SELECTIVITY,
                               JOIN_SPECULATIVE_SIZING)
        if not bool(tctx.conf.get(JOIN_SPECULATIVE_SIZING)):
            return None
        how = self._norm_how
        n_probe = probe.num_rows_bound
        # capture the cache generation WITH the prediction: if a
        # concurrent clearKernelCache lands before this batch's observed
        # selectivity records, the record is dropped instead of seeding
        # the fresh generation with learning from dead programs
        from .kernel_cache import cache_generation
        self._sel_generation = cache_generation()
        sel = lookup_selectivity(self._sig)
        if sel is None:
            sel = float(tctx.conf.get(JOIN_INITIAL_SELECTIVITY))
        pred = int(sel * max(n_probe, 1)) + 1
        pred += (n_probe if how in ("left", "full") else 0)
        pred += (build.num_rows_bound if how == "full" else 0)
        return bucket_capacity(pred)

    def _record_selectivity(self, probe: ColumnarBatch, total: int) -> None:
        record_selectivity(self._sig,
                           total / max(probe.num_rows_bound, 1),
                           generation=getattr(self, "_sel_generation",
                                              None))

    def _cached_kernel(self, tag: str, chunk_cap: int, make_impl):
        """Get-or-build the jitted windowed kernel for (tag, chunk_cap) —
        shared by the hash-join and nested-loop chunked gathers."""
        key = (tag, chunk_cap)
        fn = self._gather_cache.get(key)
        if fn is None:
            fn = self._jit(make_impl(), key=(tag, self._sig, chunk_cap))
            self._gather_cache[key] = fn
        return fn

    def _chunk_fn(self, chunk_cap: int):
        """Windowed gather (JoinGatherer.scala:730 analog): one compiled
        program per chunk capacity; the window offset is a traced scalar."""
        how = self._norm_how

        def make():
            def impl(probe, build, info, offset):
                probe, _lmask = self._apply_probe_steps(probe, self.xp)
                maps = gather_pairs(
                    self.xp, info, chunk_cap,
                    with_unmatched_left=how in ("left", "full"),
                    with_unmatched_right=how == "full",
                    offset=offset)
                pair = self._pair_batch(probe, build, maps)
                return self._project_output(pair, maps)
            return impl
        return self._cached_kernel("gather_chunk", chunk_cap, make)

    def _join_one(self, probe: ColumnarBatch, build: ColumnarBatch,
                  tctx: Optional[TaskContext] = None) -> ColumnarBatch:
        info = self._join_info(probe, build, tctx)
        out_cap = self._out_capacity(info, probe.num_rows_int,
                                     build.num_rows_int, tctx)
        with self._stage(tctx, "gather"):
            return self._gather_fn(out_cap)(probe, build, info)

    def _join_batches(self, probe: ColumnarBatch, build: ColumnarBatch,
                      tctx: TaskContext):
        """Join output with donation provenance: gather-built outputs are
        freshly computed device buffers, so they are marked transient for
        downstream fused-stage donation (memory/retention.py).  Existence
        outputs may alias probe columns (passthrough) and stay unmarked."""
        from ...memory.retention import mark_transient
        passthrough = self._norm_how == "existence"
        for b in self._join_batches_impl(probe, build, tctx):
            yield b if passthrough else mark_transient(b)

    def _join_batches_impl(self, probe: ColumnarBatch,
                           build: ColumnarBatch, tctx: TaskContext):
        """Yield the join output, chunked when it exceeds the configured
        chunk rows (condition/filter joins keep the single-buffer path —
        their residual bookkeeping spans the whole pair space).

        Non-blocking output sizing: the gather for the PREDICTED output
        bucket dispatches before any host readback, so the one batched
        sizing fetch overlaps the gather's device execution instead of
        serializing build -> readback -> gather.  Only an overflow of the
        predicted bucket (realized rows > capacity) pays a re-gather."""
        probe, build = self._lower_encoded_keys(probe, build, tctx)
        how = self._norm_how
        if (self._bound_cond is not None or how in _FILTER_JOINS):
            yield self._join_one(probe, build, tctx)
            return
        from ...config import JOIN_OUTPUT_CHUNK_ROWS
        chunk = int(tctx.conf.get(JOIN_OUTPUT_CHUNK_ROWS))
        spec_cap = self._speculative_capacity(probe, build, tctx)
        speculating = spec_cap is not None \
            and spec_cap <= bucket_capacity(chunk)

        def total_out_of(tot, unl, unb):
            return tot + (unl if how in ("left", "full") else 0) + \
                (unb if how == "full" else 0)

        if speculating and self._fused_probe_on(tctx) \
                and self._fast_path_on(tctx):
            # single-program probe pipeline: search + expansion + pair
            # generation + the all-columns gather are ONE launch, with the
            # JoinInfo returned alongside for the one batched sizing
            # readback.  At most a second launch (the exact re-gather) on
            # bucket overflow — the fused-vs-two-program choice is a host
            # decision, so outputs stay bit-identical either way.
            from .base import count_stage_dispatch
            count_stage_dispatch()
            bs = self._get_build_side(build, tctx)
            STATS["fastpath_probes"] += 1
            STATS["fused_probes"] += 1
            tctx.inc_metric("joinFastpathProbes")
            tctx.inc_metric("joinFusedProbes")
            with self._stage(tctx, "fusedProbe"):
                out, info = self._fused_probe_fn(spec_cap)(probe, build, bs)
        else:
            info = self._join_info(probe, build, tctx)
            if speculating:
                with self._stage(tctx, "gather"):
                    out = self._gather_fn(spec_cap)(probe, build, info)

        tot, unl, unb = self._fetch_totals(info, tctx)
        self._record_selectivity(probe, tot)
        total_out = total_out_of(tot, unl, unb)
        if speculating:
            if total_out <= spec_cap:
                STATS["spec_hits"] += 1
                tctx.inc_metric("joinSpecHits")
                yield out.with_known_rows(total_out)
                return
            # overflow: the realized output exceeds the predicted bucket —
            # re-gather at the exact capacity (the totals are on the host
            # already, so this costs no extra readback)
            STATS["spec_misses"] += 1
            tctx.inc_metric("joinSpecMisses")
        if total_out <= chunk:
            out_cap = bucket_capacity(total_out)
            with self._stage(tctx, "gather"):
                out = self._gather_fn(out_cap)(probe, build, info)
            yield out.with_known_rows(total_out)
            return
        STATS["chunked_joins"] += 1
        chunk_cap = bucket_capacity(chunk)
        fn = self._chunk_fn(chunk_cap)
        xp = self.xp
        for off in range(0, total_out, chunk_cap):
            with self._stage(tctx, "gather"):
                got = fn(probe, build, info,
                         xp.asarray(off, dtype=xp.int64))
            # chunk row counts are host arithmetic — shrunk() must not pay
            # a per-chunk num_rows sync (a hidden second blocking readback)
            yield got.with_known_rows(
                min(chunk_cap, total_out - off)).shrunk()

    # --- helpers ----------------------------------------------------------
    def _empty_batch(self, attrs) -> ColumnarBatch:
        schema = T.StructType(tuple(
            T.StructField(a.name, a.dtype, True) for a in attrs))
        b = ColumnarBatch.empty(schema)
        if self.backend != TPU:
            import jax
            b = jax.device_get(b)
        return b

    def _concat_or_empty(self, batches, attrs) -> ColumnarBatch:
        if not batches:
            return self._empty_batch(attrs)
        return ColumnarBatch.concat(batches) if len(batches) > 1 else batches[0]

    def simple_string(self):
        keys = ", ".join(f"{l.sql()}={r.sql()}" for l, r in
                         zip(self._probe_keys, self._build_keys))
        c = f" cond={self.condition.sql()}" if self.condition is not None else ""
        if self._probe_steps:
            chain = " -> ".join(s.node_name() for s in self._probe_steps)
            c += f" [fusedProbe: {chain}]"
        return f"{self.node_name()} {self.how} [{keys}]{c}"


class ShuffledHashJoinExec(BaseJoinExec):
    """Both sides co-partitioned by key hash (planner inserts the
    exchanges); per partition the build side is concatenated and each probe
    batch is joined against it (reference ``GpuShuffledHashJoinExec``).

    Probe-filtering joins (inner/left-semi) additionally build a bloom
    filter from the materialized build exchange and install it as the
    probe exchange's map-side filter — the reference's AQE-gated
    runtime-filter pushdown (``GpuBloomFilterMightContain.scala:1``),
    re-shaped for this engine's eager exchange materialization: the build
    exchange always materializes fully before the probe's map stage runs,
    so the filter needs no separate aggregation plan."""

    _bloom_tried = False

    def num_partitions(self):
        return self._probe.num_partitions()

    def _maybe_install_bloom(self, tctx: TaskContext) -> None:
        from ...config import (BLOOM_JOIN_BITS_PER_ROW, BLOOM_JOIN_ENABLED,
                               BLOOM_JOIN_MAX_BUILD_ROWS)
        from ...ops import bloom as B
        from .basic import compact_batch
        from .exchange import ShuffleExchangeExec
        from .kernel_cache import exprs_key
        from ..expressions.hashing import XxHash64
        if self._bloom_tried:
            return
        self._bloom_tried = True
        probe, build = self._probe, self._build
        if (self._norm_how not in ("inner", "left_semi")
                or self.backend != TPU
                or not isinstance(probe, ShuffleExchangeExec)
                or not isinstance(build, ShuffleExchangeExec)
                or probe._materialized is not None
                or probe.map_side_filter is not None
                or not bool(tctx.conf.get(BLOOM_JOIN_ENABLED))):
            return
        # multi-slice shuffles materialize only the slice-LOCAL reduce
        # partitions here (peer-owned slots come back empty), so a bloom
        # built from them would cover a SUBSET of build rows and its
        # map-side filter would drop probe rows whose matches live in
        # peer-owned partitions — a false negative.  Same guard as the
        # AQE partition-coalescing one in exchange.py.
        from ...shuffle.manager import get_shuffle_manager
        topo = get_shuffle_manager(tctx.conf).topology
        if topo is not None and topo.multi_slice:
            return
        # equal join-key values must hash identically on both sides; a
        # dtype mismatch (missing analyzer cast) would make that false and
        # a bloom false NEGATIVE drops matching rows — so require it
        if any(p.data_type != b.data_type
               for p, b in zip(self._bound_pkeys, self._bound_bkeys)):
            return
        build._ensure_materialized(tctx)
        parts = [b for ps in build._materialized for b in ps
                 if b is not None]
        total = sum(b.num_rows_int for b in parts)
        if total == 0 or total > int(tctx.conf.get(BLOOM_JOIN_MAX_BUILD_ROWS)):
            return
        xp = self.xp
        bits_per_row = int(tctx.conf.get(BLOOM_JOIN_BITS_PER_ROW))
        m, k = B.bloom_params(total, bits_per_row)
        hb = XxHash64(*self._bound_bkeys)
        hp = XxHash64(*self._bound_pkeys)

        def build_step(bits, batch):
            ctx = EvalContext(batch, xp=xp)
            return B.bloom_build(xp, bits, hb.eval(ctx).data,
                                 batch.row_mask(), k)

        bkey = ("bloomb", m, k, exprs_key(self._bound_bkeys))
        step = self._jit(build_step, key=bkey)
        bits = xp.zeros(m, dtype=bool)
        for b in parts:
            bits = step(bits, b)

        # bits is an ARGUMENT, not a closure: the kernel cache shares
        # compiled programs by key across joins, so baking the bitset in
        # as a trace constant would let a second join with the same key
        # silently reuse the first join's filter
        def probe_filter(bits_, batch):
            ctx = EvalContext(batch, xp=xp)
            keep = B.bloom_might_contain(xp, bits_, hp.eval(ctx).data, k) \
                & batch.row_mask()
            return compact_batch(xp, batch, keep)

        fkey = ("bloomp", m, k, exprs_key(self._bound_pkeys))
        filt = self._jit(probe_filter, key=fkey)

        def map_filter(batch):
            out = filt(bits, batch).shrunk()
            B.STATS["probe_rows_in"] += batch.num_rows_int
            B.STATS["probe_rows_kept"] += out.num_rows_int
            tctx.inc_metric("bloomFilteredRows",
                            batch.num_rows_int - out.num_rows_int)
            return out

        probe.map_side_filter = map_filter
        B.STATS["blooms_built"] += 1
        tctx.inc_metric("bloomFiltersBuilt")

    def execute(self, pid: int, tctx: TaskContext):
        with self._setup_lock:
            self._maybe_install_bloom(tctx)
        btctx = TaskContext(pid, tctx.conf, parent=tctx)
        with btctx.as_current():
            build_batches = list(self._build.execute(pid, btctx))
        build = self._concat_or_empty(build_batches, self._build.output)
        probes = list(self._probe.execute(pid, tctx))
        how = self._norm_how
        if how == "full" and len(probes) > 1:
            # unmatched-build rows must be emitted once per partition,
            # not once per probe batch
            probes = [ColumnarBatch.concat(probes)]
        if not probes:
            probes = [self._empty_batch(self._probe.output)]
        for probe in probes:
            yield from self._join_batches(probe, build, tctx)


class BroadcastHashJoinExec(BaseJoinExec):
    """Build side is a broadcast exchange shared across all probe
    partitions (reference ``GpuBroadcastHashJoinExecBase``).  Only valid
    for join types whose build side is not preserved (inner/left/semi/
    anti/existence with build=right) — the planner enforces this."""

    def num_partitions(self):
        return self._probe.num_partitions()

    def execute(self, pid: int, tctx: TaskContext):
        assert isinstance(self._build, BroadcastExchangeExec)
        build = self._build.broadcast_batch(tctx)
        probes = list(self._probe.execute(pid, tctx))
        if not probes:
            probes = [self._empty_batch(self._probe.output)]
        for probe in probes:
            yield from self._join_batches(probe, build, tctx)


class NestedLoopJoinExec(BaseJoinExec):
    """Cartesian product + optional condition (reference
    ``GpuBroadcastNestedLoopJoinExecBase`` / ``GpuCartesianProductExec``).
    The build side is broadcast; pair space is all (i, j) combinations."""

    def num_partitions(self):
        return self._probe.num_partitions()

    def _build_info(self, probe, build):  # not used
        raise NotImplementedError

    def _join_one(self, probe: ColumnarBatch, build: ColumnarBatch,
                  tctx: Optional[TaskContext] = None) -> ColumnarBatch:
        n_probe = probe.num_rows_int
        n_build = build.num_rows_int
        how = self._norm_how
        # outer no-key joins need slack for null-extended rows even without
        # a condition (e.g. left join against an empty build side)
        extra = (n_probe if how in ("left", "full") else 0) + \
            (n_build if how == "full" else 0)
        out_cap = bucket_capacity(n_probe * n_build + extra)
        return self._nl_fn(out_cap)(probe, build)

    def _nl_fn(self, out_cap: int):
        fn = self._gather_cache.get(out_cap)
        if fn is None:
            def impl(probe, build):
                return self._nl_impl(probe, build, out_cap)
            fn = self._jit(impl, key=("nl", self._sig, out_cap))
            self._gather_cache[out_cap] = fn
        return fn

    def _join_batches_impl(self, probe: ColumnarBatch,
                           build: ColumnarBatch, tctx: TaskContext):
        """Chunk the (probe x build) pair space for condition-free
        inner/cross products; everything else keeps the one-buffer path."""
        how = self._norm_how
        if self._bound_cond is not None or how not in ("inner", "cross"):
            yield self._join_one(probe, build, tctx)
            return
        from ...config import JOIN_OUTPUT_CHUNK_ROWS
        chunk = int(tctx.conf.get(JOIN_OUTPUT_CHUNK_ROWS))
        total = probe.num_rows_int * build.num_rows_int
        if total <= chunk:
            yield self._join_one(probe, build, tctx)
            return
        STATS["chunked_joins"] += 1
        chunk_cap = bucket_capacity(chunk)

        def make():
            def impl(probe_, build_, offset):
                maps = cross_pairs(self.xp, probe_.num_rows,
                                   build_.num_rows, chunk_cap, offset=offset)
                pair = self._pair_batch(probe_, build_, maps)
                return self._project_output(pair, maps)
            return impl
        fn = self._cached_kernel("nl_chunk", chunk_cap, make)
        xp = self.xp
        for off in range(0, total, chunk_cap):
            yield fn(probe, build, xp.asarray(off, dtype=xp.int64)).shrunk()

    def _nl_impl(self, probe: ColumnarBatch, build: ColumnarBatch,
                 out_cap: int) -> ColumnarBatch:
        xp = self.xp
        how = self._norm_how
        lcap, rcap = probe.capacity, build.capacity
        maps = cross_pairs(xp, probe.num_rows, build.num_rows, out_cap)
        pair = self._pair_batch(probe, build, maps)
        if self._bound_cond is None and how in ("inner", "cross"):
            return self._project_output(pair, maps)
        pass_mask = (self._eval_condition(pair, maps.l_ok)
                     if self._bound_cond is not None else maps.l_ok)

        if how in _FILTER_JOINS:
            matched = matched_per_row(xp, pass_mask, maps.l_idx, lcap) > 0
            return self._emit_filter_join(probe, matched)

        final = self._assemble_with_pass(probe, build, maps, pass_mask,
                                         out_cap)
        pair = self._pair_batch(probe, build, final)
        return self._project_output(pair, final)

    def execute(self, pid: int, tctx: TaskContext):
        if isinstance(self._build, BroadcastExchangeExec):
            build = self._build.broadcast_batch(tctx)
        else:
            # every probe partition needs the whole build stream
            batches = []
            for bpid in range(self._build.num_partitions()):
                btctx = TaskContext(bpid, tctx.conf)
                with btctx.as_current():
                    batches.extend(self._build.execute(bpid, btctx))
            build = self._concat_or_empty(batches, self._build.output)
        probes = list(self._probe.execute(pid, tctx))
        how = self._norm_how
        if how == "full" and len(probes) > 1:
            probes = [ColumnarBatch.concat(probes)]
        if not probes:
            probes = [self._empty_batch(self._probe.output)]
        for probe in probes:
            yield from self._join_batches(probe, build, tctx)


def _release_catalog_handles(catalog, handles) -> None:
    """weakref.finalize target (must not reference the finalized object):
    drop the spill-catalog registrations a dead MaterializedExec owned.
    ``remove`` is a no-op for handles already gone (catalog reset)."""
    for h in handles:
        try:
            catalog.remove(h)
        except Exception:  # pragma: no cover - teardown must never raise
            pass


class MaterializedExec(PhysicalPlan):
    """Leaf serving pre-computed batches per partition — the runtime-stats
    carrier AQE re-plans over (GpuCustomShuffleReaderExec's shuffle-stage
    analog).  Batches are registered with the spill catalog so the stage's
    working set can be demoted off-device between the size observation and
    the chosen plan's execution (the reference keeps materialized stages
    in the spillable shuffle catalog for the same reason)."""

    def __init__(self, attrs, parts: List[List[ColumnarBatch]], backend=TPU):
        super().__init__()
        self.backend = backend
        self._attrs = list(attrs)
        self._nbytes = 0
        if backend == TPU:
            import weakref
            from ...memory.spill import (BufferCatalog,
                                         OUTPUT_FOR_SHUFFLE_PRIORITY,
                                         SpillableColumnarBatch,
                                         batch_device_bytes)
            self._nbytes = sum(batch_device_bytes(b)
                               for bs in parts for b in bs)
            self._parts = [[SpillableColumnarBatch.create(
                b, OUTPUT_FOR_SHUFFLE_PRIORITY) for b in bs]
                for bs in parts]
            # the spillables live as long as this node (AQE may re-serve
            # them to every probe partition), so their catalog handles
            # are released when the PLAN dies — without this every
            # adaptive join leaked its materialized build side until
            # process exit (found by tools/leak_sentinel.py)
            catalog = BufferCatalog.get()
            handles = [sb._handle for bs in self._parts for sb in bs]
            self._finalizer = weakref.finalize(
                self, _release_catalog_handles, catalog, handles)
        else:
            self._parts = parts

    @property
    def output(self):
        return self._attrs

    def num_partitions(self):
        return max(1, len(self._parts))

    def estimate_bytes(self):
        if self.backend != TPU:
            from ...memory.spill import batch_device_bytes
            return sum(batch_device_bytes(b)
                       for bs in self._parts for b in bs)
        return self._nbytes

    def execute(self, pid, tctx):
        if pid < len(self._parts):
            for item in self._parts[pid]:
                yield item.get() if hasattr(item, "get") else item


class AdaptiveJoinExec(PhysicalPlan):
    """AQE join: defer the broadcast-vs-shuffle decision until the build
    side's ACTUAL size is observed at execution time (the reference's AQE
    integration re-plans query stages from materialized shuffle statistics,
    ``GpuOverrides.scala:4392-4452``).  The static planner falls back to
    this when its estimates say "shuffle"; if the materialized build side
    turns out to fit the broadcast threshold, the cheaper broadcast hash
    join is picked instead — a provably different plan on mis-estimated
    inputs."""

    def __init__(self, node, left: PhysicalPlan, right: PhysicalPlan,
                 backend, conf):
        super().__init__(left, right)
        self.backend = backend
        self._node = node
        self._conf = conf
        self._chosen: Optional[PhysicalPlan] = None
        self._choose_lock = threading.Lock()
        self.chosen_strategy: Optional[str] = None
        # static shape only (output schema / explain); never executed
        self._shape = ShuffledHashJoinExec(
            node.how, node.left_keys, node.right_keys, node.condition,
            left, right, backend=backend)

    @property
    def output(self):
        return self._shape.output

    def num_partitions(self):
        return int(self._conf.shuffle_partitions)

    def _choose(self, tctx: TaskContext):
        if self._chosen is not None:
            return
        with self._choose_lock:
            if self._chosen is None:
                self._choose_locked(tctx)

    def _choose_locked(self, tctx: TaskContext):
        from ...config import AUTO_BROADCAST_THRESHOLD
        node, left, right = self._node, self.children[0], self.children[1]
        parts = []
        for p in range(right.num_partitions()):
            rtctx = TaskContext(p, tctx.conf, parent=tctx)
            with rtctx.as_current():
                parts.append(list(right.execute(p, rtctx)))
        right_m = MaterializedExec(right.output, parts, backend=self.backend)
        threshold = int(self._conf.get(AUTO_BROADCAST_THRESHOLD))
        can_broadcast = (node.how in ("inner", "left", "left_semi",
                                      "left_anti", "existence")
                         and right_m.estimate_bytes() <= threshold)
        if can_broadcast:
            build = BroadcastExchangeExec(right_m, backend=self.backend)
            self._chosen = BroadcastHashJoinExec(
                node.how, node.left_keys, node.right_keys, node.condition,
                left, build, backend=self.backend)
            self.chosen_strategy = "broadcast"
        else:
            n = self.num_partitions()
            from ...parallel.partitioning import HashPartitioning
            from .exchange import ShuffleExchangeExec
            lx = ShuffleExchangeExec(
                HashPartitioning(node.left_keys, n), left,
                backend=self.backend, coalescible=False,
                skew_splittable=node.how != "full")
            rx = ShuffleExchangeExec(
                HashPartitioning(node.right_keys, n), right_m,
                backend=self.backend, coalescible=False)
            self._chosen = ShuffledHashJoinExec(
                node.how, node.left_keys, node.right_keys, node.condition,
                lx, rx, backend=self.backend)
            self.chosen_strategy = "shuffle"

    def execute(self, pid, tctx):
        self._choose(tctx)
        n = self.num_partitions()
        m = self._chosen.num_partitions()
        # serve the chosen plan's m partitions through our fixed n pids
        for p in range(pid, m, n) if m > n else (
                [pid] if pid < m else []):
            ctctx = TaskContext(p, tctx.conf, parent=tctx)
            with ctctx.as_current():
                got = list(self._chosen.execute(p, ctctx))
            yield from got

    def simple_string(self):
        tag = self.chosen_strategy or "undecided"
        return f"{self.node_name()} {self._node.how} [aqe: {tag}]"


# --------------------------------------------------------------------------
# planning
# --------------------------------------------------------------------------

def plan_join(node, left: PhysicalPlan, right: PhysicalPlan, backend,
              conf) -> PhysicalPlan:
    """Join strategy selection (the reference's exec rules for
    BroadcastHashJoinExec / ShuffledHashJoinExec / SortMergeJoinExec /
    CartesianProductExec / BroadcastNestedLoopJoinExec)."""
    from ...parallel.partitioning import HashPartitioning, SinglePartitioning
    from .exchange import ShuffleExchangeExec

    how = node.how
    if not node.left_keys:
        # condition-only / cross join -> nested loop with broadcast build.
        # right/full preserve the build side, so the probe must see the
        # whole stream exactly once -> coalesce to a single partition.
        if how in ("right", "full") and left.num_partitions() > 1:
            left = ShuffleExchangeExec(SinglePartitioning(), left,
                                       backend=backend)
        build = BroadcastExchangeExec(right, backend=backend)
        return NestedLoopJoinExec(how, (), (), node.condition, left, build,
                                  backend=backend)

    from ...config import AUTO_BROADCAST_THRESHOLD
    threshold = int(conf.get(AUTO_BROADCAST_THRESHOLD))
    build_bytes = right.estimate_bytes()
    hinted = bool(getattr(node, "broadcast_hint", False))
    can_broadcast = (how in ("inner", "left", "left_semi", "left_anti",
                             "existence")
                     and (hinted
                          or (build_bytes is not None
                              and build_bytes <= threshold)))
    if can_broadcast and (hinted or left.num_partitions() > 1):
        build = BroadcastExchangeExec(right, backend=backend)
        # dynamic partition pruning: a hive-partitioned probe scan joined
        # on its partition column skips files the broadcast keys rule out.
        # ONLY probe-filtering joins qualify — outer/anti/existence joins
        # must emit probe rows with NO build match, which are exactly the
        # rows pruning would drop
        if how in ("inner", "left_semi"):
            from .dpp import apply_dpp
            left = apply_dpp(left, node.left_keys, node.right_keys, build)
        return BroadcastHashJoinExec(how, node.left_keys, node.right_keys,
                                     node.condition, left, build,
                                     backend=backend)

    from ...config import ADAPTIVE_ENABLED
    nparts = max(left.num_partitions(), right.num_partitions())
    if (bool(conf.get(ADAPTIVE_ENABLED)) and nparts > 1
            and how in ("inner", "left", "left_semi", "left_anti",
                        "existence")):
        # the static estimate said "shuffle" (or was unknown): let AQE
        # re-decide from the materialized build side at runtime
        return AdaptiveJoinExec(node, left, right, backend, conf)
    if nparts > 1:
        n = int(conf.shuffle_partitions)
        # the PROBE side gets skew splitting; right joins flip sides in
        # BaseJoinExec (probe=right, build=left), full joins concat
        # their probe batches back (join.py execute), so neither benefits
        left = ShuffleExchangeExec(
            HashPartitioning(node.left_keys, n), left, backend=backend,
            coalescible=False,
            skew_splittable=how not in ("full", "right"))
        right = ShuffleExchangeExec(
            HashPartitioning(node.right_keys, n), right, backend=backend,
            coalescible=False, skew_splittable=how == "right")
    return ShuffledHashJoinExec(how, node.left_keys, node.right_keys,
                                node.condition, left, right, backend=backend)
