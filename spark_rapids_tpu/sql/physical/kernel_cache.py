"""Module-level compiled-kernel cache.

The reference's whole perf model is one kernel launch per op per batch with
*reused* compiled kernels (``RapidsConf.scala:550``, SURVEY §3.3) — cuDF
kernels are compiled once per process.  Here the analog is: one ``jax.jit``
wrapper per *program identity* (exec type + bound expression tree + static
params), shared across every exec instance and every ``collect()``.  XLA's
own trace cache then keys on input avals (schema dtypes, capacity buckets,
batch names), so repeated queries hit compiled code instead of re-tracing.

Program identity keys are built from ``Expression.semantic_key()`` over
*bound* expression trees (BoundReference → ordinal), so two plans of the
same query constructed at different times share kernels.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Any, Callable, Dict, Optional, Tuple

from ...observability import metrics as _om
from ...observability import tracer as _trace
from ...robustness import faults as _faults

#: LRU bound — each entry pins its exec instance (and that exec's child
#: subtree) via the jitted closure, and keys embed literal values, so an
#: unbounded cache would grow with every distinct constant a long-running
#: session ever used.  Reference analog: cuDF kernels are per-op, not
#: per-literal; bounding the per-literal programs keeps the same spirit.
_MAX_ENTRIES = int(os.environ.get("SRT_KERNEL_CACHE_SIZE", "1024"))

_CACHE: "OrderedDict[Tuple, Callable]" = OrderedDict()
_LOCK = threading.Lock()
_STATS = {"hits": 0, "misses": 0, "evictions": 0,
          "compiles": 0, "compile_ms": 0.0, "dispatches": 0}

#: cache GENERATION, bumped under ``_LOCK`` by every :func:`clear_cache`.
#: The concurrent-sessions clearing contract (docs/serving.md): a clear
#: while another session executes never breaks in-flight work — handed-out
#: ``_TrackedKernel`` wrappers keep their jitted callables (the dict only
#: drops ITS references) — and learned state derived from a dead
#: generation's programs (join selectivities, aggregate group-size
#: speculations) is dropped instead of written back: learners capture the
#: generation when they first consult the cache and the recorders refuse
#: the write when it no longer matches.
_GENERATION = [0]

#: per-key trace+compile accounting (observability report: "compile ms
#: per key"); keyed by the human-readable kernel label
_COMPILE_BY_KEY: Dict[str, Dict[str, float]] = {}

#: per-key LAUNCH accounting (doctor's dispatch-bound evidence names the
#: top kernel keys); lock-free like _STATS["dispatches"] — a lost
#: increment under contention is metric noise, a per-launch lock is
#: hot-path cost.  Keyed by the human-readable kernel label.
_DISPATCH_BY_KEY: Dict[str, int] = {}


class _TrackedKernel:
    """Thin wrapper over a jitted callable that detects re-traces (via
    the jit wrapper's ``_cache_size``) and accounts trace+compile wall
    time per kernel key — the tracer's ``kernel_compile`` spans.

    Cost model: when tracing is OFF this is one dict lookup + one extra
    Python call per kernel launch (launches are per batch per op, never
    per row).  When ON, a ``_cache_size()`` probe brackets the call; a
    size increase means this call traced+compiled, and its wall time
    (dispatch included — XLA compiles synchronously inside the call) is
    recorded against the key.
    """

    __slots__ = ("_fn", "_label")

    def __init__(self, fn: Callable, label: str):
        self._fn = fn
        self._label = label

    def __call__(self, *args, **kwargs):
        _faults.maybe_inject("kernel.compile", exc=RuntimeError,
                             kernel=self._label)
        # device-dispatch accounting (whole-stage fusion evidence,
        # docs/whole_stage.md): one increment per compiled-program launch.
        # Deliberately lock-free — a lost increment under contention is
        # metric noise, a per-launch lock is hot-path cost.
        _STATS["dispatches"] = _STATS["dispatches"] + 1
        _DISPATCH_BY_KEY[self._label] = \
            _DISPATCH_BY_KEY.get(self._label, 0) + 1
        if _om.METRICS["on"]:
            reg = _om.get_registry()
            reg.inc("device_dispatches_total")
            # kernel-labeled series: the doctor's dispatch-bound verdict
            # names the top-K launch sources from these
            reg.inc("device_dispatches_by_kernel_total",
                    kernel=self._label)
        if not _trace.TRACING["on"]:
            return self._fn(*args, **kwargs)
        _trace.get_tracer().counter("deviceDispatches")
        cs = getattr(self._fn, "_cache_size", None)
        before = cs() if cs is not None else -1
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        dt = time.perf_counter() - t0
        if cs is not None and cs() > before:
            ms = dt * 1e3
            with _LOCK:
                _STATS["compiles"] += 1
                _STATS["compile_ms"] += ms
                e = _COMPILE_BY_KEY.setdefault(
                    self._label, {"compiles": 0, "ms": 0.0})
                e["compiles"] += 1
                e["ms"] += ms
            _trace.get_tracer().complete("kernel_compile", self._label,
                                         t0, dt)
            if _om.METRICS["on"]:
                _om.get_registry().observe("kernel_compile_ms", ms,
                                           kernel=self._label)
        return out


def _trace_salt() -> Tuple:
    """Global knobs that change TRACED PROGRAMS without appearing in any
    exec's own key (the _jit contract: the key must capture everything
    that affects the trace).  Today: the radix-sort decision — lex_sort
    branches on it inside sort kernels, so flipping the conf or a fresh
    bake-off verdict must not reuse comparator-sort programs.

    The frozen bake-off base measurement is RESOLVED HERE (bakeoff_base
    probes once per backend) so the salt is stable from the first
    cached_jit on — a measurement landing mid-session would otherwise
    flip the salt and invalidate the whole kernel cache.  All pass-count
    verdicts derive deterministically from that one base."""
    try:
        import jax.numpy as jnp

        from ...config import RapidsConf

        mode = str(RapidsConf.get_global().get(
            "spark.rapids.sql.sort.radix", "auto")).lower()
        if mode == "auto":
            from ...ops.radix_sort import bakeoff_base
            return ("radix-auto", bakeoff_base(jnp))
        return ("radix", mode)
    except ImportError:
        return ()
    except Exception as e:  # pragma: no cover - transient probe failure
        # an empty salt can reuse programs traced under a different sort
        # verdict; make the (rare) degradation visible instead of silent
        import warnings
        warnings.warn(f"radix trace-salt resolution failed ({e!r}); "
                      f"kernel cache proceeds unsalted")
        return ()


def donation_supported() -> bool:
    """XLA:CPU accepts but ignores donate_argnums (and warns per unusable
    buffer); only real device backends reclaim donated HBM.  The donation
    DECISION (memory/retention.py) runs everywhere — this gates only
    whether the marker reaches jax.jit."""
    try:
        import jax
        return jax.default_backend() not in ("cpu",)
    except Exception:  # pragma: no cover - backend probe failure
        return False


def cached_jit(key: Tuple, fn: Callable,
               donate_argnums: Optional[Tuple[int, ...]] = None) -> Callable:
    """Return the process-wide jitted callable for ``key``.

    ``fn`` is jitted and cached on first sight of ``key``; later callers get
    the cached wrapper (their own ``fn`` is dropped — the key must capture
    everything that affects the trace).  Least-recently-used entries are
    evicted past ``_MAX_ENTRIES``.

    ``donate_argnums`` requests XLA input-buffer donation for those
    argument positions (whole-stage fusion, docs/whole_stage.md).  The
    caller owns BOTH safety obligations: the key must distinguish donating
    from non-donating programs, and donated arguments must be sole-owner
    batches (retention.may_donate) that are never touched after the call.
    """
    key = key + _trace_salt()
    with _LOCK:
        cached = _CACHE.get(key)
        if cached is not None:
            _STATS["hits"] += 1
            _CACHE.move_to_end(key)
            _om.inc("kernel_cache_hits_total")
            return cached
        _STATS["misses"] += 1
        _om.inc("kernel_cache_misses_total")
        import jax
        if donate_argnums and donation_supported():
            jitted = jax.jit(fn, donate_argnums=tuple(donate_argnums))
        else:
            jitted = jax.jit(fn)
        label = f"{key[0]}#{abs(hash(key)) & 0xFFFF:04x}"
        wrapper = _TrackedKernel(jitted, label)
        _CACHE[key] = wrapper
        while len(_CACHE) > _MAX_ENTRIES:
            _CACHE.popitem(last=False)
            _STATS["evictions"] += 1
        return wrapper


def cache_stats() -> Dict[str, int]:
    with _LOCK:
        return dict(_STATS, size=len(_CACHE))


def cache_generation() -> int:
    """Current cache generation (bumped by every clear) — learners of
    cache-coupled state (join selectivities, agg size speculations)
    capture this at lookup time and pass it back at record time so a
    concurrent clear drops, rather than resurrects, their learning."""
    with _LOCK:
        return _GENERATION[0]


def compile_stats_by_key() -> Dict[str, Dict[str, float]]:
    """Per-kernel-key trace+compile accounting (label -> compiles, ms);
    only accrues while tracing is on."""
    with _LOCK:
        return {k: dict(v) for k, v in _COMPILE_BY_KEY.items()}


def dispatch_stats_by_key() -> Dict[str, int]:
    """Per-kernel-key launch counts (label -> dispatches) since the last
    cache clear — the doctor's dispatch-bound evidence source."""
    return dict(_DISPATCH_BY_KEY)


def clear_cache() -> None:
    """Drop every cached program and the learned state coupled to them.

    Safe under concurrent sessions: the generation bumps BEFORE the
    learned-state dicts clear, so a query mid-flight that learned against
    the old programs fails its generation check at record time instead of
    repopulating a dead generation's state; its already-handed-out kernel
    wrappers keep working (they own their jitted callables)."""
    with _LOCK:
        _GENERATION[0] += 1
        _CACHE.clear()
        _COMPILE_BY_KEY.clear()
        _STATS["hits"] = 0
        _STATS["misses"] = 0
        _STATS["evictions"] = 0
        _STATS["compiles"] = 0
        _STATS["compile_ms"] = 0.0
        _STATS["dispatches"] = 0
        _DISPATCH_BY_KEY.clear()
    # stale group-size speculations point at programs just dropped; a
    # speculated miss would recompile a size that may immediately
    # mis-speculate
    from .aggregate import clear_speculation
    clear_speculation()
    # same rule for learned join selectivities: a stale prediction would
    # recompile gather programs for sizes that immediately mis-speculate
    from .join import clear_selectivity
    clear_selectivity()


def release_compiled_programs() -> None:
    """Free compiled XLA executables — the ONE recipe (tests/conftest.py
    per test module, scaletest.run_suite per query): the engine kernel
    wrappers AND jax's executable caches.  Accumulated compiled-code
    state segfaults the XLA:CPU JIT inside backend_compile_and_load past
    a few hundred programs (round-4 postmortem; the round-5 60-query rig
    reproduced it as 'LLVM compilation error: Cannot allocate memory').
    Callers recompile their own plans anyway; only shared kernels pay
    again."""
    import jax
    clear_cache()
    jax.clear_caches()


def expr_key(e) -> Tuple:
    """Stable structural key for a bound expression (or SortOrder)."""
    from ..plan import SortOrder
    if isinstance(e, SortOrder):
        return ("SortOrder", expr_key(e.child), e.ascending, e.nulls_first)
    return e.semantic_key()


def exprs_key(exprs) -> Tuple:
    return tuple(expr_key(e) for e in exprs)
