"""Python-worker execs — the analog of the reference's
``org/apache/spark/sql/rapids/execution/python/`` family (SURVEY §2.9):
``GpuMapInPandasExec`` and ``GpuFlatMapGroupsInPandasExec``.  Batches move
to the Python function as pandas DataFrames through Arrow; the device
semaphore is released while user Python runs (the reference's
``GpuArrowPythonRunner`` releases it while waiting on the worker,
``GpuArrowEvalPythonExec.scala:172``) so device-bound tasks can overlap
with Python time."""

from __future__ import annotations

from contextlib import contextmanager
from typing import Iterator, List

from ... import types as T
from ...columnar.batch import ColumnarBatch
from .base import TPU, PhysicalPlan, TaskContext


@contextmanager
def _semaphore_released(backend: str, tctx: TaskContext):
    """Release the device semaphore around user Python ONLY if this task
    holds it — execs driven inside another task's materialization (e.g. a
    downstream exchange) run under the OUTER task's permit, and acquiring
    a second one here would deadlock a permits=1 chip.  While the device
    permit is out, a PYTHON-worker permit bounds how many user-Python
    sections run at once (reference PythonWorkerSemaphore)."""
    from ...memory.python_worker import PythonWorkerSemaphore
    pysem = PythonWorkerSemaphore.get(tctx.conf)
    if backend != TPU:
        with pysem.running_python():
            yield
        return
    from ...memory.semaphore import TpuSemaphore
    sem = TpuSemaphore.get()
    held = sem.holds(tctx.partition_id)
    if held:
        sem.release_if_necessary(tctx.partition_id)
    try:
        with pysem.running_python():
            yield
    finally:
        if held:
            sem.acquire_if_necessary(tctx.partition_id, tctx)


def _run_job(tctx: TaskContext, job_fn, tables, user_fn=None):
    """Route a pandas job (Arrow tables in/out) through the
    out-of-process worker pool (pyworker.py; in-process when
    worker.isolated=false).  A user fn marked __srt_force_inprocess__
    (df.foreach/foreachPartition — side effects ARE the output) always
    runs in-process."""
    from ...pyworker import run_pandas_job
    force = bool(getattr(user_fn, "__srt_force_inprocess__", False))
    return run_pandas_job(tctx.conf, job_fn, tables, force_inprocess=force)


def _to_arrow(batch: ColumnarBatch):
    from ...columnar.convert import device_to_arrow
    return device_to_arrow(batch)


def _from_arrow(table, schema: T.StructType, backend: str) -> ColumnarBatch:
    import pyarrow as pa
    from ...columnar.convert import arrow_to_device
    table = table.cast(pa.schema([
        pa.field(f.name, T.to_arrow(f.data_type))
        for f in schema.fields]))
    batch = arrow_to_device(table)
    if backend != TPU:
        import jax
        batch = jax.device_get(batch)
    return batch


class MapInPandasExec(PhysicalPlan):
    """User fn: Iterator[pd.DataFrame] -> Iterator[pd.DataFrame]."""

    def __init__(self, func, out_schema: T.StructType, child: PhysicalPlan,
                 backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.func = func
        self.out_schema = out_schema

    @property
    def output(self):
        from ..expressions.core import AttributeReference
        return [AttributeReference(f.name, f.data_type, True)
                for f in self.out_schema.fields]

    def execute(self, pid: int, tctx: TaskContext):
        # device->host transfer happens BEFORE the semaphore is released
        # (GpuArrowPythonRunner ordering); user Python then runs without
        # holding the chip
        tables = [_to_arrow(b)
                  for b in self.children[0].execute(pid, tctx)]
        if not tables:
            return
        func = self.func

        def job(frames):
            return [o for o in func(iter(frames))
                    if o is not None and len(o)]

        with _semaphore_released(self.backend, tctx):
            outs = _run_job(tctx, job, tables, user_fn=func)
        for tab in outs:
            yield _from_arrow(tab, self.out_schema, self.backend)

    def simple_string(self):
        return (f"{self.node_name()} "
                f"{getattr(self.func, '__name__', '<fn>')}")


class FlatMapGroupsInPandasExec(PhysicalPlan):
    """groupBy(keys).applyInPandas: one pandas DataFrame per key group in,
    one out; groups are formed per partition (the planner hash-partitions
    the child by the grouping keys first, so groups are complete)."""

    def __init__(self, grouping_names: List[str], func,
                 out_schema: T.StructType, child: PhysicalPlan, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.grouping_names = list(grouping_names)
        self.func = func
        self.out_schema = out_schema

    @property
    def output(self):
        from ..expressions.core import AttributeReference
        return [AttributeReference(f.name, f.data_type, True)
                for f in self.out_schema.fields]

    def execute(self, pid: int, tctx: TaskContext):
        batches = list(self.children[0].execute(pid, tctx))
        if not batches:
            return
        merged = (ColumnarBatch.concat(batches) if len(batches) > 1
                  else batches[0])
        table = _to_arrow(merged)
        if not table.num_rows:
            return
        del merged, batches  # free the device batch before user Python
        # runs with the semaphore released (another task may need HBM)
        func = self.func
        grouping_names = self.grouping_names

        def job(frames):
            # grouping runs INSIDE the job (worker-side when isolated):
            # one table crosses the pipe instead of one per group, and
            # both modes hand user code identical group frames — each
            # with the fresh RangeIndex PySpark's applyInPandas gives
            f = frames[0]
            return [o for o in (
                func(g.reset_index(drop=True))
                for _, g in f.groupby(grouping_names, sort=False,
                                      dropna=False))
                if o is not None and len(o)]

        with _semaphore_released(self.backend, tctx):
            outs = _run_job(tctx, job, [table])
        for out in outs:
            yield _from_arrow(out, self.out_schema, self.backend)

    def simple_string(self):
        keys = ", ".join(self.grouping_names)
        return (f"{self.node_name()} [{keys}] "
                f"{getattr(self.func, '__name__', '<fn>')}")


class AggregateInPandasExec(PhysicalPlan):
    """groupBy(keys).agg(grouped-agg pandas UDFs): each UDF reduces its
    argument Series to ONE scalar per key group (reference
    ``GpuAggregateInPandasExec``).  The planner hash-partitions the child
    by the keys, so each partition holds complete groups; the device
    semaphore is released while user Python runs (the reference's
    semaphore-aware Arrow exchange, ``GpuArrowEvalPythonExec:97``)."""

    def __init__(self, grouping_names: List[str], agg_udfs,
                 child: PhysicalPlan, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.grouping_names = list(grouping_names)
        self.agg_udfs = list(agg_udfs)  # (name, GroupedAggPandasUDF)

    @property
    def output(self):
        from ..expressions.core import AttributeReference
        child_out = self.children[0].output
        keys = [a for n in self.grouping_names
                for a in child_out if a.name == n]
        aggs = [AttributeReference(name, u.return_type, True)
                for name, u in self.agg_udfs]
        return keys + aggs

    def execute(self, pid: int, tctx: TaskContext):
        batches = list(self.children[0].execute(pid, tctx))
        if not batches:
            return
        merged = (ColumnarBatch.concat(batches) if len(batches) > 1
                  else batches[0])
        table = _to_arrow(merged)
        if not table.num_rows:
            return
        del merged, batches  # free the device batch before user Python
        # argument column names per udf (children are resolved attributes)
        arg_names = []
        for _name, u in self.agg_udfs:
            arg_names.append([getattr(c, "name", str(c)) for c in u.children])
        grouping_names = self.grouping_names
        udfs = [(name, u.func) for name, u in self.agg_udfs]

        def job(frames):
            import pandas as _pd
            f = frames[0]
            out_rows = []
            if not grouping_names:
                row = {}
                for (name, fn), cols in zip(udfs, arg_names):
                    row[name] = fn(*[f[c] for c in cols])
                out_rows.append(row)
            else:
                for key, group in f.groupby(grouping_names, sort=False,
                                            dropna=False):
                    if not isinstance(key, tuple):
                        key = (key,)
                    row = dict(zip(grouping_names, key))
                    for (name, fn), cols in zip(udfs, arg_names):
                        row[name] = fn(*[group[c] for c in cols])
                    out_rows.append(row)
            return [_pd.DataFrame(out_rows)]

        with _semaphore_released(self.backend, tctx):
            out_tab = _run_job(tctx, job, [table])[0]
        out_schema = T.StructType(tuple(
            T.StructField(a.name, a.data_type, True) for a in self.output))
        yield _from_arrow(out_tab, out_schema, self.backend)

    def simple_string(self):
        keys = ", ".join(self.grouping_names)
        fns = ", ".join(n for n, _ in self.agg_udfs)
        return f"{self.node_name()} [{keys}] aggs=[{fns}]"


class FlatMapCoGroupsInPandasExec(PhysicalPlan):
    """cogroup().applyInPandas: per key group, the user fn receives BOTH
    sides' pandas DataFrames (either may be empty); both children are
    hash-co-partitioned by the planner so groups are complete."""

    def __init__(self, left_names: List[str], right_names: List[str], func,
                 out_schema: T.StructType, left: PhysicalPlan,
                 right: PhysicalPlan, backend=TPU):
        super().__init__(left, right)
        self.backend = backend
        self.left_names = list(left_names)
        self.right_names = list(right_names)
        self.grouping_names = self.left_names  # display
        self.func = func
        self.out_schema = out_schema

    @property
    def output(self):
        from ..expressions.core import AttributeReference
        return [AttributeReference(f.name, f.data_type, True)
                for f in self.out_schema.fields]

    def num_partitions(self):
        return self.children[0].num_partitions()

    def _side_table(self, child: PhysicalPlan, pid: int,
                    tctx: TaskContext):
        """One Arrow table per side; an empty side still carries the
        child's full schema so the user function can touch any column
        (PySpark contract)."""
        import pyarrow as pa
        stctx = TaskContext(pid, tctx.conf, parent=tctx)
        with stctx.as_current():
            batches = list(child.execute(pid, stctx))
        if batches:
            merged = (ColumnarBatch.concat(batches) if len(batches) > 1
                      else batches[0])
            return _to_arrow(merged)
        return pa.schema([pa.field(a.name, T.to_arrow(a.dtype))
                          for a in child.output]).empty_table()

    def execute(self, pid: int, tctx: TaskContext):
        ltab = self._side_table(self.children[0], pid, tctx)
        rtab = self._side_table(self.children[1], pid, tctx)
        if not ltab.num_rows and not rtab.num_rows:
            return
        func = self.func
        lnames, rnames = self.left_names, self.right_names

        def job(fs):
            # group + VALUE-tuple pairing inside the job (worker-side
            # when isolated): two tables cross the pipe, not 2 x groups
            lf, rf = fs
            lgroups, rgroups = {}, {}
            if len(lf):
                for k, g in lf.groupby(lnames, sort=False, dropna=False):
                    lgroups[k if isinstance(k, tuple) else (k,)] = g
            if len(rf):
                for k, g in rf.groupby(rnames, sort=False, dropna=False):
                    rgroups[k if isinstance(k, tuple) else (k,)] = g
            keys = list(dict.fromkeys(list(lgroups) + list(rgroups)))
            out_ = []
            for k in keys:
                o = func(lgroups.get(k, lf.iloc[0:0])
                         .reset_index(drop=True),
                         rgroups.get(k, rf.iloc[0:0])
                         .reset_index(drop=True))
                if o is not None and len(o):
                    out_.append(o)
            return out_

        with _semaphore_released(self.backend, tctx):
            outs = _run_job(tctx, job, [ltab, rtab])
        for out in outs:
            yield _from_arrow(out, self.out_schema, self.backend)

    def simple_string(self):
        keys = ", ".join(self.grouping_names)
        return (f"{self.node_name()} [{keys}] "
                f"{getattr(self.func, '__name__', '<fn>')}")
