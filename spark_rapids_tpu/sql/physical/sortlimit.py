"""Sort exec (reference ``GpuSortExec.scala``: full + out-of-core sort).
Round 1: full in-partition sort (concat batches -> one permutation gather);
the out-of-core split/merge path arrives with the spill framework."""

from __future__ import annotations

from typing import Sequence

import numpy as np

from ...columnar.batch import ColumnarBatch
from ...ops.sorting import sort_permutation
from ..expressions.core import EvalContext, bind_references
from ..plan import SortOrder
from .base import TPU, PhysicalPlan


class SortExec(PhysicalPlan):
    def __init__(self, orders: Sequence[SortOrder], child: PhysicalPlan,
                 backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.orders = list(orders)
        self._bound = [SortOrder(bind_references(o.child, child.output),
                                 o.ascending, o.nulls_first)
                       for o in self.orders]
        from .kernel_cache import exprs_key
        self._fn = self._jit(self._compute, key=(exprs_key(self._bound),))

    @property
    def output(self):
        return self.children[0].output

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        xp = self.xp
        ctx = EvalContext(batch, xp=xp)
        specs = [(o.child.eval(ctx), o.ascending, o.nulls_first)
                 for o in self._bound]
        perm = sort_permutation(xp, specs, batch.row_mask())
        live = xp.arange(batch.capacity, dtype=xp.int32) < batch.num_rows
        cols = tuple(c.gather(perm, live) for c in batch.columns)
        return ColumnarBatch(batch.names, cols, batch.num_rows)

    def execute(self, pid, tctx):
        batches = list(self.children[0].execute(pid, tctx))
        if not batches:
            return
        merged = ColumnarBatch.concat(batches) if len(batches) > 1 else batches[0]
        yield self._fn(merged)

    def simple_string(self):
        return f"{self.node_name()} [{', '.join(o.sql() for o in self.orders)}]"


class TakeOrderedAndProjectExec(PhysicalPlan):
    """ORDER BY + LIMIT fusion (reference composes TopN in the rule,
    ``GpuOverrides.scala:3880-3904``)."""

    def __init__(self, n: int, orders, project_exprs, child, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.n = n
        self._sort = SortExec(orders, child, backend)
        self.project_exprs = project_exprs

    @property
    def output(self):
        if self.project_exprs is None:
            return self.children[0].output
        from .basic import ProjectExec
        return ProjectExec(self.project_exprs, self.children[0],
                           self.backend).output

    def num_partitions(self):
        return 1

    def execute(self, pid, tctx):
        # local top-n per child partition, then merge
        tops = []
        for cpid in range(self.children[0].num_partitions()):
            for b in self._sort.execute(cpid, tctx):
                tops.append(b.sliced(0, min(self.n, b.num_rows_int)))
        if not tops:
            return
        merged = ColumnarBatch.concat(tops) if len(tops) > 1 else tops[0]
        final = self._sort._fn(merged)
        final = final.sliced(0, min(self.n, final.num_rows_int))
        if self.project_exprs is not None:
            from .basic import ProjectExec
            from ..expressions.core import EvalContext
            bound = [bind_references(e, self.children[0].output)
                     for e in self.project_exprs]
            ctx = EvalContext(final, xp=self.xp)
            cols = tuple(e.eval(ctx) for e in bound)
            names = tuple(a.name for a in self.output)
            final = ColumnarBatch(names, cols, final.num_rows)
        yield final
