"""Sort exec (reference ``GpuSortExec.scala``: full + out-of-core sort).

Two paths:

* full sort — concat the partition's batches, one permutation gather;
* out-of-core (``GpuOutOfCoreSortIterator`` analog, ``GpuSortExec.scala:242``)
  — when the input exceeds ``spark.rapids.sql.sort.outOfCore.targetRows``:
  each batch is sorted under the OOM-retry framework and cut into
  target-row SPILLABLE chunks (runs); output is produced by a k-way
  prefix merge that only ever holds one chunk per run on device: the
  first T rows of the union of run-head chunks are globally the smallest
  T rows (each head is its run's prefix), so every merge step emits one
  target-sized sorted batch and advances the consumed runs.
"""

from __future__ import annotations

from collections import deque
from typing import Sequence

import numpy as np

from ...columnar.batch import ColumnarBatch
from ...ops.sorting import sort_permutation
from ..expressions.core import EvalContext, bind_references
from ..plan import SortOrder
from .base import TPU, PhysicalPlan

#: observability for tests: counts of out-of-core engagements
STATS = {"ooc_sorts": 0, "merge_steps": 0}


class SortExec(PhysicalPlan):
    def __init__(self, orders: Sequence[SortOrder], child: PhysicalPlan,
                 backend=TPU, is_global: bool = True):
        super().__init__(child)
        self.backend = backend
        #: False for sortWithinPartitions — a following Limit must NOT
        #: compose into a global TopN over a merely-local sort
        self.is_global = is_global
        self.orders = list(orders)
        self._bound = [SortOrder(bind_references(o.child, child.output),
                                 o.ascending, o.nulls_first)
                       for o in self.orders]
        #: whole-stage sort terminal (docs/whole_stage.md): an absorbed
        #: upstream Filter/Project chain rides the first-touch sort
        #: program (_stage_fn); the pure-sort program (_fn) stays
        #: separate because the out-of-core merge re-sorts batches the
        #: chain already processed (its steps are not idempotent)
        self._pre_steps: tuple = ()
        self._out_attrs = None
        # programs built lazily on first use (whole-stage laziness
        # contract — plan construction registers nothing)
        self._fn_cache = None
        self._stage_fn_cache = None

    @property
    def _fn(self):
        """Pure-sort program: merge-safe (no absorbed steps)."""
        if self._fn_cache is None:
            from .kernel_cache import exprs_key
            self._fn_cache = self._jit(self._compute,
                                       key=(exprs_key(self._bound),))
        return self._fn_cache

    @property
    def _stage_fn(self):
        """First-touch program: absorbed chain + compaction + sort, one
        launch.  Without absorbed steps this IS the pure-sort program."""
        if not self._pre_steps:
            return self._fn
        if self._stage_fn_cache is None:
            self._stage_fn_cache = self._jit(self._stage_compute,
                                             key=self._fuse_sig())
        return self._stage_fn_cache

    def _fuse_sig(self):
        from .kernel_cache import exprs_key
        return (exprs_key(self._bound),
                ("stage",) + tuple(s._fuse_key() for s in self._pre_steps))

    def absorb_pre_steps(self, steps, new_child) -> None:
        """Fuse an upstream Filter/Project chain into this sort's
        first-touch program (fusion.py sort/window terminal).  The chain
        reproduced the schema the orders were bound against, so the bound
        sort keys stay valid; fused filters compact INSIDE the program
        (the sort gather consumes the survivors directly)."""
        self._pre_steps = tuple(steps)
        self._out_attrs = list(steps[-1].output)
        self.children = (new_child,)
        self._fn_cache = None
        self._stage_fn_cache = None

    @property
    def output(self):
        if self._pre_steps:
            return self._out_attrs
        return self.children[0].output

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        xp = self.xp
        ctx = EvalContext(batch, xp=xp)
        specs = [(o.child.eval(ctx), o.ascending, o.nulls_first)
                 for o in self._bound]
        perm = sort_permutation(xp, specs, batch.row_mask())
        live = xp.arange(batch.capacity, dtype=xp.int32) < batch.num_rows
        cols = tuple(c.gather(perm, live) for c in batch.columns)
        return ColumnarBatch(batch.names, cols, batch.num_rows)

    def _stage_compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        """Absorbed chain + compaction + sort, inside one program — the
        compaction's gather and the sort's permutation gather fuse."""
        from .basic import compact_batch
        xp = self.xp
        mask = batch.row_mask()
        for s in self._pre_steps:
            batch, mask = s._fuse_step(batch, mask, xp)
        if self._pre_steps:
            batch = compact_batch(xp, batch, mask)
        return self._compute(batch)

    def execute(self, pid, tctx):
        yield from self.execute_batches(
            list(self.children[0].execute(pid, tctx)), tctx)

    def execute_batches(self, batches, tctx):
        """Sort an already-materialized batch list (WindowExec's stage
        terminal feeds its key-batched fallback from here so the absorbed
        chain still rides the sort program)."""
        if not batches:
            return
        from ...config import SORT_OOC_TARGET_ROWS
        target = int(tctx.conf.get(SORT_OOC_TARGET_ROWS))
        # pull-free conservative sizing: the bound is exact when known,
        # else the padded capacity — engaging out-of-core a bit early is
        # cheaper than one device sync per batch on the tunnel
        total = sum(b.num_rows_bound for b in batches)
        if total > target:
            yield from self._out_of_core(batches, target)
            return
        merged = ColumnarBatch.concat(batches) if len(batches) > 1 else batches[0]
        from .base import count_stage_dispatch
        count_stage_dispatch()
        out = self._stage_fn(merged)
        if self._pre_steps:
            # absorbed filters can drop rows, so the count is no longer
            # host-known — only bounded by the pre-filter total
            out.with_rows_bound(total)
            yield out
            return
        known = getattr(merged, "_nrows_host", None)
        if known is not None:
            out.with_known_rows(known)  # sort permutes, never drops rows
        else:
            bound = getattr(merged, "_nrows_bound", None)
            if bound is not None:
                out.with_rows_bound(bound)
        yield out

    # --- out-of-core path -------------------------------------------------
    def _out_of_core(self, batches, target: int):
        from ...memory.retry import split_spillable_in_half, with_retry
        from ...memory.spill import (ACTIVE_BATCHING_PRIORITY,
                                     SpillableColumnarBatch)
        STATS["ooc_sorts"] += 1

        # phase 1: sort each input under retry; cut sorted runs into
        # target-row spillable chunks (a SplitAndRetryOOM halves an input,
        # which simply yields two smaller sorted runs).  Chunks created
        # before a later failure are closed by the phase-2 finally below.
        spillables = [SpillableColumnarBatch.create(
            b, ACTIVE_BATCHING_PRIORITY) for b in batches
            if b.num_rows_int > 0]
        runs: list = []
        try:
            # first touch runs the STAGE program (absorbed chain + sort);
            # the phase-2 merge below re-sorts already-processed rows and
            # must use the pure-sort program only
            from .base import count_stage_dispatch

            def run_sort(sb):
                count_stage_dispatch()
                return self._stage_fn(sb.get())

            for sorted_b in with_retry(spillables, run_sort,
                                       split_spillable_in_half):
                run: deque = deque()
                n = sorted_b.num_rows_int
                for off in range(0, n, target):
                    piece = sorted_b.sliced(off, min(target, n - off))
                    run.append(SpillableColumnarBatch.create(
                        piece, ACTIVE_BATCHING_PRIORITY))
                if run:
                    runs.append(run)

            if len(runs) == 1:
                # one sorted run: its chunks ARE the output, no merge
                run = runs[0]
                while run:
                    yield run.popleft().get_and_close()
                return
        except BaseException:
            for r in runs:
                for sb in r:
                    sb.close()
            raise

        # phase 2: k-way prefix merge.  Each run contributes a prefix of at
        # least ``target`` rows (or its whole remainder) — that invariant
        # makes the first <=target rows of the sorted union globally the
        # smallest.  Tag prefixes with their run id, sort the union, emit,
        # advance each run by its consumed count.  The finally-close keeps
        # catalog accounting honest when the consumer abandons the
        # generator or a merge step raises (with_retry's ownership model).
        xp = self.xp
        run_col = "__ooc_run__"
        from ... import types as T
        from ...columnar.column import DeviceColumn
        try:
            while runs:
                runs = [r for r in runs if r]
                if not runs:
                    break
                STATS["merge_steps"] += 1
                heads = []
                for ridx, r in enumerate(runs):
                    # top up the prefix to >= target rows (or the whole run)
                    pieces = [r.popleft()]
                    rows = pieces[0].num_rows
                    while rows < target and r:
                        pieces.append(r.popleft())
                        rows += pieces[-1].num_rows
                    got = [p.get_and_close() for p in pieces]
                    hb = ColumnarBatch.concat(got) if len(got) > 1 else got[0]
                    rid = DeviceColumn(
                        T.INT, xp.full(hb.capacity, ridx, dtype=xp.int32),
                        xp.ones(hb.capacity, dtype=bool))
                    heads.append(ColumnarBatch(
                        hb.names + (run_col,), hb.columns + (rid,),
                        hb.num_rows))
                union = (ColumnarBatch.concat(heads) if len(heads) > 1
                         else heads[0])
                count_stage_dispatch()
                merged = self._fn(union)
                e = min(target, merged.num_rows_int)
                emit = merged.sliced(0, e)
                # consumed rows per run (host bincount over emitted prefix)
                rid_sorted = np.asarray(merged.column(run_col).data[:e])
                consumed = np.bincount(rid_sorted, minlength=len(runs))
                survivors = []
                for ridx, (r, head) in enumerate(zip(runs, heads)):
                    c = int(consumed[ridx])
                    n_head = head.num_rows_int
                    if c < n_head:
                        rest = head.sliced(c, n_head - c)
                        names = tuple(n for n in rest.names if n != run_col)
                        cols = tuple(cc for n, cc
                                     in zip(rest.names, rest.columns)
                                     if n != run_col)
                        r.appendleft(SpillableColumnarBatch.create(
                            ColumnarBatch(names, cols, rest.num_rows),
                            ACTIVE_BATCHING_PRIORITY))
                    if r:
                        survivors.append(r)
                runs = survivors
                names = tuple(n for n in emit.names if n != run_col)
                cols = tuple(c for n, c in zip(emit.names, emit.columns)
                             if n != run_col)
                yield ColumnarBatch(names, cols, emit.num_rows)
        finally:
            for r in runs:
                for sb in r:
                    sb.close()

    def simple_string(self):
        s = f"{self.node_name()} [{', '.join(o.sql() for o in self.orders)}]"
        if self._pre_steps:
            chain = " -> ".join(st.node_name() for st in self._pre_steps)
            s += f" [fusedPre: {chain}]"
        return s


class TakeOrderedAndProjectExec(PhysicalPlan):
    """ORDER BY + LIMIT fusion (reference composes TopN in the rule,
    ``GpuOverrides.scala:3880-3904``)."""

    def __init__(self, n: int, orders, project_exprs, child, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.n = n
        self.orders = list(orders)
        self.project_exprs = project_exprs
        self._sort_cache: "SortExec" = None

    @property
    def _sort(self) -> "SortExec":
        """Derived lazily from the CURRENT child: planner passes that
        rewrite ``self.children`` (backend transitions, stage fusion)
        must flow into the internal sort, not a child frozen at
        construction time."""
        child = self.children[0]
        if self._sort_cache is None or \
                self._sort_cache.children[0] is not child:
            self._sort_cache = SortExec(self.orders, child, self.backend)
        return self._sort_cache

    @property
    def output(self):
        if self.project_exprs is None:
            return self.children[0].output
        from .basic import ProjectExec
        return ProjectExec(self.project_exprs, self.children[0],
                           self.backend).output

    def num_partitions(self):
        return 1

    def execute(self, pid, tctx):
        # local top-n per child partition, then merge
        tops = []
        for cpid in range(self.children[0].num_partitions()):
            for b in self._sort.execute(cpid, tctx):
                tops.append(b.sliced(0, min(self.n, b.num_rows_int)))
        if not tops:
            return
        merged = ColumnarBatch.concat(tops) if len(tops) > 1 else tops[0]
        final = self._sort._fn(merged)
        final = final.sliced(0, min(self.n, final.num_rows_int))
        if self.project_exprs is not None:
            from .basic import ProjectExec
            from ..expressions.core import EvalContext
            bound = [bind_references(e, self.children[0].output)
                     for e in self.project_exprs]
            ctx = EvalContext(final, xp=self.xp)
            cols = tuple(e.eval(ctx) for e in bound)
            names = tuple(a.name for a in self.output)
            final = ColumnarBatch(names, cols, final.num_rows)
        yield final
