"""Deferred speculation validation — the tunnel-latency answer to the
two-phase aggregate's group-count sync.

On the TPU tunnel every host pull costs a full network round trip (~65ms)
while async dispatch and even ``block_until_ready`` are sub-millisecond, so
the engine's throughput is set by the NUMBER of host pulls per query, not
by device compute.  The speculative fused aggregate (aggregate.py
``_fused_partial_fn``) already runs group+reduce as one program under a
host-guessed group-table size; this module lets the *validation* of that
guess ride the query's single device→host fetch instead of paying its own
round trip:

* the aggregate registers a :class:`DeferredCheck` carrying the device-side
  observed group count and the speculated size;
* the ``DeviceToHost`` transition bundles all pending device scalars into
  the same ``device_get`` as the result batch (one pull for everything);
* after execution the session validates the fetched counts — a
  mis-speculation (observed > speculated: scatters past the table were
  dropped, the result is wrong) records the corrected size and re-runs the
  query, which then takes the exact path.

Reference analog: none — the reference pays a kernel launch per op and
never speculates; this is a TPU-tunnel-specific design (SURVEY §7 "hardest
risk items": dynamic shapes vs XLA compilation).
"""

from __future__ import annotations

import threading
from typing import Callable, List, Optional

#: observability for tests/metrics.  Increments go through
#: :func:`_bump` — with double-buffered transfers the bundled-fetch count
#: is bumped from the transfer stager thread while the driver may be
#: registering checks, and lost updates would break tests that assert on
#: exact deltas.
STATS = {"registered": 0, "bundled_fetches": 0, "mis_speculations": 0,
         "reruns": 0}
_STATS_LOCK = threading.Lock()


def _bump(key: str, n: int = 1) -> None:
    with _STATS_LOCK:
        STATS[key] += n


def count_bundled_fetch() -> None:
    """A pending speculation scalar rode a result fetch (D2H transition)."""
    _bump("bundled_fetches")


class DeferredCheck:
    """One pending validation: ``ng`` (device scalar) must be <= ``spec``.

    ``on_result(ng_host)`` is invoked exactly once when the value reaches
    the host (bundled into a D2H fetch or pulled at drain time); it records
    the observed size so a re-run speculates correctly.
    """

    __slots__ = ("spec", "ng", "ng_host", "on_result")

    def __init__(self, spec: int, ng, on_result: Callable[[int], None]):
        self.spec = int(spec)
        self.ng = ng
        self.ng_host: Optional[int] = None
        self.on_result = on_result

    def resolve(self, ng_host: int) -> None:
        # under double-buffered transfers two in-flight fetches can both
        # bundle a not-yet-resolved check; first resolution wins (both
        # carry the same device scalar, so the value is identical either
        # way — the lock just keeps on_result to exactly one call)
        with _STATS_LOCK:
            if self.ng_host is not None:
                return
            self.ng_host = int(ng_host)
            self.ng = None  # drop the device ref
        self.on_result(self.ng_host)

    @property
    def failed(self) -> bool:
        return self.ng_host is not None and self.ng_host > self.spec


class _State(threading.local):
    """Per-thread registry: deferral is driven by the session's collect
    loop on its own thread, and concurrent sessions on other threads must
    not steal or wipe each other's pending checks."""

    def __init__(self):
        self.pending: List[DeferredCheck] = []
        self.on = False


_state = _State()


def deferral_enabled() -> bool:
    """Deferred validation is opt-in per execution: only the session's
    pure-collect path enables it (a plan with side effects — writers —
    must never act on unvalidated results)."""
    return _state.on


def set_deferral(on: bool) -> None:
    _state.on = bool(on)


def register(spec: int, ng, on_result: Callable[[int], None]
             ) -> DeferredCheck:
    c = DeferredCheck(spec, ng, on_result)
    _state.pending.append(c)
    _bump("registered")
    return c


def unresolved():
    """Checks whose device scalar has not reached the host yet (for the
    D2H transition to bundle into its fetch).  Same-thread only — the
    driver's collect loop registers, bundles, and drains on one thread."""
    return [c for c in _state.pending if c.ng_host is None]


def drain() -> List[DeferredCheck]:
    """Take this thread's pending checks, resolving any still-device
    values (one bundled pull if needed)."""
    checks = list(_state.pending)
    _state.pending.clear()
    todo = [c for c in checks if c.ng_host is None]
    if todo:
        import jax
        vals = jax.device_get([c.ng for c in todo])
        for c, v in zip(todo, vals):
            c.resolve(int(v))
    return checks


def clear() -> None:
    _state.pending.clear()
