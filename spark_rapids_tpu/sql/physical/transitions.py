"""Backend transitions + batch coalescing (reference
``GpuRowToColumnarExec``/``GpuColumnarToRowExec``/``HostColumnarToGpu``/
``GpuCoalesceBatches``; SURVEY §2.2).

Here both backends are columnar (host = numpy, device = jnp), so transitions
are pure buffer moves: one ``device_put`` per column upload, one fetch per
download — no row format in the middle.

With ``spark.rapids.tpu.transfer.doubleBuffer.enabled`` both transitions
pipeline: a one-slot stager thread carries transfer N+1 while batch N is
consumed downstream (≤ 1 transfer in flight ahead of the consumer — the
reference's stream-overlapped copy model).  The child is pulled on the
CALLING thread (a one-batch lookahead), so thread-local seams —
speculation registration, OOM-injection arming — stay on the task thread;
only the transfer itself moves to the stager.  Exceptions raised in the
stager (device OOM, injected chaos faults) re-raise on the consumer with
their original type via ``Future.result()``.
"""

from __future__ import annotations

from concurrent.futures import ThreadPoolExecutor
from typing import Iterator, List

import numpy as np

from ...columnar.batch import ColumnarBatch
from ...config import TRANSFER_DOUBLE_BUFFER
from ...observability import tracer as _trace
from .base import CPU, TPU, PhysicalPlan, TaskContext


def batch_nbytes(batch: ColumnarBatch) -> int:
    total = 0
    for c in batch.columns:
        for arr in (c.data, c.validity, c.lengths, c.aux):
            if arr is not None:
                total += arr.size * arr.dtype.itemsize
    return total


def _staged(it, transfer, name: str):
    """Shared double-buffer loop: pull batch N+1 from ``it`` on the
    calling thread, dispatch its ``transfer`` on the one-slot stager,
    THEN yield batch N's completed result — ≤ 1 transfer in flight ahead
    of the consumer.  The stager brackets itself on the tracer's exec
    stack so its spans attribute to the owning transition.  While a
    transfer is in flight its input batch is pinned in the retention
    registry (donation-safety: a staged batch is held by two threads)."""
    from ...memory import retention as _ret

    def _carried(item):
        # D2H pairs each batch with its speculation checks — pin the batch
        return item[0] if isinstance(item, tuple) else item

    def run(batch):
        _trace.push_exec(name)
        try:
            return transfer(batch)
        finally:
            _trace.pop_exec()

    from ...serving import lifecycle as _lc
    with ThreadPoolExecutor(max_workers=1,
                            thread_name_prefix=f"srt-{name}") as stager:
        fut = None
        fut_in = None
        try:
            for batch in it:
                # lifecycle poll site `stager`: a cancelled query stops
                # feeding transfers; the one in-flight transfer completes
                # (bounded) and its pin is released in the finally below
                _lc.check_cancel("stager")
                _ret.pin_batch(_carried(batch))
                nxt = stager.submit(run, batch)
                if fut is not None:
                    out = fut.result()
                    prev_in, fut = fut_in, None
                    _ret.unpin_batch(_carried(prev_in))
                    yield out
                fut, fut_in = nxt, batch
            if fut is not None:
                out = fut.result()
                prev_in, fut = fut_in, None
                _ret.unpin_batch(_carried(prev_in))
                yield out
        finally:
            if fut is not None:
                # cancel/error/early-close with a transfer still staged:
                # wait it out (<= one transfer) and release the pin so
                # retention accounting returns to baseline without the
                # GC reaper; its own failure must not mask the original
                try:
                    fut.result()
                except BaseException:  # noqa: BLE001 - original wins
                    pass
                _ret.unpin_batch(_carried(fut_in))


class HostToDeviceExec(PhysicalPlan):
    backend = TPU

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)

    @property
    def output(self):
        return self.children[0].output

    def execute(self, pid, tctx):
        import jax.numpy as jnp

        from ...shims import tree_map
        from ...robustness import faults as _faults

        from ...memory.retention import mark_transient

        def upload(batch):
            nb = batch_nbytes(batch)
            tctx.inc_metric("h2d_bytes", nb)
            _faults.maybe_inject("transfer.h2d", exc=ConnectionError,
                                 bytes=nb)
            # span covers the upload dispatch only, not downstream
            # consumption of the yielded batch
            with _trace.span("h2d", "HostToDevice.upload", bytes=nb):
                # fresh single-owner device buffers: donation-eligible
                return mark_transient(tree_map(jnp.asarray, batch))

        it = self.children[0].execute(pid, tctx)
        if bool(tctx.conf.get(TRANSFER_DOUBLE_BUFFER)):
            tctx.inc_metric("h2dDoubleBuffered", level="DEBUG")
            yield from _staged(it, upload, self.node_name())
            return
        for batch in it:
            yield upload(batch)

    def node_name(self):
        return "HostToDevice"


class DeviceToHostExec(PhysicalPlan):
    backend = CPU

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)

    @property
    def output(self):
        return self.children[0].output

    def execute(self, pid, tctx):
        from ...columnar.prepack import prepacked_device_get
        from ...memory.oom_guard import guard_device_oom
        from . import speculation
        # the fetch is a materialization point: with syncMode=auto a
        # deferred execution-time OOM surfaces HERE, so it runs under the
        # guard's spill-and-retry protocol like any kernel.  The fetch
        # byte-packs the whole batch into ONE device->host transfer, and
        # big batches narrow on device first (columnar/prepack.py)
        fetch = guard_device_oom(prepacked_device_get)

        def fetch_one(batch, pending):
            tctx.inc_metric("d2h_bytes", batch_nbytes(batch))
            # bundle pending speculation scalars into the SAME pull as the
            # result — on the tunnel each separate pull is a ~65ms round
            # trip, and this one was happening anyway
            if pending:
                host_b, vals = fetch((batch, [c.ng for c in pending]))
                for c, v in zip(pending, vals):
                    c.resolve(int(v))
                speculation.count_bundled_fetch()
                return host_b
            return fetch(batch)  # ONE concurrent D2H for all leaves

        it = self.children[0].execute(pid, tctx)
        if bool(tctx.conf.get(TRANSFER_DOUBLE_BUFFER)):
            # the pending-check snapshot must happen on the task thread
            # (speculation state is thread-local), so pair each batch with
            # its checks BEFORE handing it to the stager
            def paired():
                for batch in it:
                    yield batch, speculation.unresolved()
            yield from _staged(paired(),
                               lambda bp: fetch_one(bp[0], bp[1]),
                               self.node_name())
            return
        for batch in it:
            yield fetch_one(batch, speculation.unresolved())

    def node_name(self):
        return "DeviceToHost"


class CoalesceBatchesExec(PhysicalPlan):
    """Accumulate small batches up to a target size before handing them to
    size-sensitive operators (the central batching invariant of the
    reference, ``GpuCoalesceBatches.scala`` TargetSize goal)."""

    def __init__(self, child: PhysicalPlan, target_rows: int = 1 << 20,
                 target_bytes: int = 1 << 30, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.target_rows = target_rows
        self.target_bytes = target_bytes

    @property
    def output(self):
        return self.children[0].output

    def execute(self, pid, tctx):
        pending: List[ColumnarBatch] = []
        rows = 0
        nbytes = 0
        emitted = False
        for batch in self.children[0].execute(pid, tctx):
            n = batch.num_rows_int
            if n == 0:
                continue
            pending.append(batch)
            rows += n
            nbytes += batch_nbytes(batch)
            if rows >= self.target_rows or nbytes >= self.target_bytes:
                emitted = True
                yield (ColumnarBatch.concat(pending) if len(pending) > 1
                       else pending[0])
                pending, rows, nbytes = [], 0, 0
        if pending:
            yield (ColumnarBatch.concat(pending) if len(pending) > 1
                   else pending[0])
        elif not emitted:
            # every input batch was empty (or the child yielded nothing):
            # emit ONE empty batch with the correct schema instead of a
            # zero-batch partition — downstream execs (and the
            # committed-block tracking of the resilient shuffle fetch)
            # must be able to tell "empty partition" from "lost block"
            from .exchange import empty_batch_for
            empty = empty_batch_for(self.output)
            if self.backend == CPU:
                import jax
                empty = jax.device_get(empty)
            yield empty
