"""Backend transitions + batch coalescing (reference
``GpuRowToColumnarExec``/``GpuColumnarToRowExec``/``HostColumnarToGpu``/
``GpuCoalesceBatches``; SURVEY §2.2).

Here both backends are columnar (host = numpy, device = jnp), so transitions
are pure buffer moves: one ``device_put`` per column upload, one fetch per
download — no row format in the middle.
"""

from __future__ import annotations

from typing import Iterator, List

import numpy as np

from ...columnar.batch import ColumnarBatch
from ...observability import tracer as _trace
from .base import CPU, TPU, PhysicalPlan, TaskContext


def batch_nbytes(batch: ColumnarBatch) -> int:
    total = 0
    for c in batch.columns:
        for arr in (c.data, c.validity, c.lengths, c.aux):
            if arr is not None:
                total += arr.size * arr.dtype.itemsize
    return total


class HostToDeviceExec(PhysicalPlan):
    backend = TPU

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)

    @property
    def output(self):
        return self.children[0].output

    def execute(self, pid, tctx):
        import jax.numpy as jnp

        from ...shims import tree_map
        from ...robustness import faults as _faults
        for batch in self.children[0].execute(pid, tctx):
            nb = batch_nbytes(batch)
            tctx.inc_metric("h2d_bytes", nb)
            _faults.maybe_inject("transfer.h2d", exc=ConnectionError,
                                 bytes=nb)
            # span covers the upload dispatch only, not downstream
            # consumption of the yielded batch
            with _trace.span("h2d", "HostToDevice.upload", bytes=nb):
                up = tree_map(jnp.asarray, batch)
            yield up

    def node_name(self):
        return "HostToDevice"


class DeviceToHostExec(PhysicalPlan):
    backend = CPU

    def __init__(self, child: PhysicalPlan):
        super().__init__(child)

    @property
    def output(self):
        return self.children[0].output

    def execute(self, pid, tctx):
        from ...columnar.prepack import prepacked_device_get
        from ...memory.oom_guard import guard_device_oom
        from . import speculation
        # the fetch is a materialization point: with syncMode=auto a
        # deferred execution-time OOM surfaces HERE, so it runs under the
        # guard's spill-and-retry protocol like any kernel.  The fetch
        # byte-packs the whole batch into ONE device->host transfer, and
        # big batches narrow on device first (columnar/prepack.py)
        fetch = guard_device_oom(prepacked_device_get)
        for batch in self.children[0].execute(pid, tctx):
            tctx.inc_metric("d2h_bytes", batch_nbytes(batch))
            # bundle pending speculation scalars into the SAME pull as the
            # result — on the tunnel each separate pull is a ~65ms round
            # trip, and this one was happening anyway
            pending = speculation.unresolved()
            if pending:
                host_b, vals = fetch((batch, [c.ng for c in pending]))
                for c, v in zip(pending, vals):
                    c.resolve(int(v))
                speculation.STATS["bundled_fetches"] += 1
                yield host_b
            else:
                yield fetch(batch)  # ONE concurrent D2H for all leaves

    def node_name(self):
        return "DeviceToHost"


class CoalesceBatchesExec(PhysicalPlan):
    """Accumulate small batches up to a target size before handing them to
    size-sensitive operators (the central batching invariant of the
    reference, ``GpuCoalesceBatches.scala`` TargetSize goal)."""

    def __init__(self, child: PhysicalPlan, target_rows: int = 1 << 20,
                 target_bytes: int = 1 << 30, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.target_rows = target_rows
        self.target_bytes = target_bytes

    @property
    def output(self):
        return self.children[0].output

    def execute(self, pid, tctx):
        pending: List[ColumnarBatch] = []
        rows = 0
        nbytes = 0
        for batch in self.children[0].execute(pid, tctx):
            n = batch.num_rows_int
            if n == 0:
                continue
            pending.append(batch)
            rows += n
            nbytes += batch_nbytes(batch)
            if rows >= self.target_rows or nbytes >= self.target_bytes:
                yield (ColumnarBatch.concat(pending) if len(pending) > 1
                       else pending[0])
                pending, rows, nbytes = [], 0, 0
        if pending:
            yield (ColumnarBatch.concat(pending) if len(pending) > 1
                   else pending[0])
