"""Window exec — the analog of ``GpuWindowExec.scala`` (SURVEY §2.3).

The planner guarantees the child is hash-partitioned on the partition keys
and sorted by (partition, order).  This exec concatenates the partition's
batches (the reference's RequireSingleBatch / double-pass strategy;
``GpuCachedDoublePassWindowIterator:1720``) and computes every window
expression with static-shape kernels:

* segment/peer bounds from boundary flags + cumulative min/max scans,
* frame bounds as per-row [start, end) index ranges (ROWS arithmetic /
  RANGE via order-key searchsorted with a per-segment composite offset),
* aggregations as prefix-sum differences or sparse-table range queries.
"""

from __future__ import annotations

from typing import List, Sequence

import numpy as np

from ... import types as T
from ...columnar.batch import ColumnarBatch
from ...columnar.column import DeviceColumn
from ...ops import window_ops as W
from ...ops.ranks import column_sort_keys
from ..expressions import aggregates as AGG
from ..expressions.core import (Alias, EvalContext, bind_references)
from ..expressions.windows import (CURRENT_ROW, CumeDist, DenseRank, Lag,
                                   Lead, NTile, NthValue, PercentRank, Rank,
                                   RankLike, RowNumber, UNBOUNDED_FOLLOWING,
                                   UNBOUNDED_PRECEDING, WindowExpression,
                                   WindowFrame)
from ..plan import SortOrder
from .base import TPU, PhysicalPlan


def _select_column(xp, mask, a: DeviceColumn, b: DeviceColumn) -> DeviceColumn:
    """Row-wise select: a where mask else b.  Handles the 2-D byte-matrix
    string layout (aligning widths) and fixed-width columns."""
    if a.data is not None and a.data.ndim == 2:
        wa, wb = a.data.shape[1], b.data.shape[1]
        w = max(wa, wb)
        da = xp.pad(a.data, ((0, 0), (0, w - wa))) if wa < w else a.data
        db = xp.pad(b.data, ((0, 0), (0, w - wb))) if wb < w else b.data
        data = xp.where(mask[:, None], da, db)
    elif a.data is not None:
        data = xp.where(mask, a.data, b.data)
    else:
        data = None
    validity = xp.where(mask, a.validity, b.validity)
    lengths = None if a.lengths is None else xp.where(mask, a.lengths,
                                                      b.lengths)
    aux = None if a.aux is None else xp.where(mask, a.aux, b.aux)
    children = tuple(_select_column(xp, mask, ca, cb)
                     for ca, cb in zip(a.children, b.children))
    return DeviceColumn(a.dtype, data, validity, lengths, aux, children)


def _minmax_identity(xp, dtype: T.DataType, is_min: bool):
    if isinstance(dtype, (T.FloatType, T.DoubleType)):
        return xp.inf if is_min else -xp.inf
    info = np.iinfo(dtype.np_dtype)
    return info.max if is_min else info.min


class WindowExec(PhysicalPlan):
    def __init__(self, window_exprs: Sequence[Alias],
                 partition_spec, order_spec: Sequence[SortOrder],
                 child: PhysicalPlan, backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.window_exprs = list(window_exprs)
        self.partition_spec = list(partition_spec)
        self.order_spec = list(order_spec)
        out = child.output
        self._bound_exprs = [
            Alias(bind_references(a.child, out), a.name, a.expr_id)
            for a in self.window_exprs]
        self._bound_parts = [bind_references(e, out)
                             for e in self.partition_spec]
        self._bound_orders = [
            SortOrder(bind_references(o.child, out), o.ascending,
                      o.nulls_first) for o in self.order_spec]
        #: whole-stage window terminal (docs/whole_stage.md): the
        #: planner-inserted partition sort below this node, absorbed by
        #: fuse_stages so single-chunk inputs evaluate chain + sort +
        #: window in ONE program (kept as an exec for the key-batched
        #: large-input fallback)
        self._sorter = None
        self._in_attrs = None
        # programs built lazily on first use (whole-stage laziness
        # contract — plan construction registers nothing)
        self._fn_cache = None
        self._fused_fn_cache = None
        self._boundary_fn_cache = None

    def _win_key(self):
        from .kernel_cache import exprs_key
        return (exprs_key(a.child for a in self._bound_exprs),
                tuple(a.name for a in self.window_exprs),
                exprs_key(self._bound_parts),
                exprs_key(self._bound_orders))

    @property
    def _fn(self):
        if self._fn_cache is None:
            self._fn_cache = self._jit(self._compute, key=self._win_key())
        return self._fn_cache

    @property
    def _fused_fn(self):
        """All-in-one stage-terminal program: absorbed chain + compaction
        + partition sort + window evaluation, one launch.  Correct only
        for a single key-complete chunk (sorting inside the program is
        then exactly the planner's sort) — the caller guarantees it."""
        if self._fused_fn_cache is None:
            def impl(batch):
                return self._compute(self._sorter._stage_compute(batch))
            self._fused_fn_cache = self._jit(
                impl,
                key=("wstage",) + self._win_key() + self._sorter._fuse_sig())
        return self._fused_fn_cache

    def can_absorb_sort(self, sort_exec) -> bool:
        """The sort below must be exactly the partition sort the planner
        inserts for this window — (partition keys asc nulls-first, then
        the order spec) — or absorbing it would change what the window's
        segment scan sees."""
        from .kernel_cache import exprs_key
        want = exprs_key(
            [SortOrder(e, True, True) for e in self._bound_parts]
            + self._bound_orders)
        return exprs_key(sort_exec._bound) == want

    def absorb_sort(self, sort_exec) -> None:
        """Absorb the planner's partition sort (fusion.py window
        terminal).  The sort exec is retained to drive the key-batched
        fallback for inputs too large for one chunk."""
        self._sorter = sort_exec
        self._in_attrs = list(sort_exec.output)
        self.children = tuple(sort_exec.children)
        self._fn_cache = None
        self._fused_fn_cache = None
        self._boundary_fn_cache = None

    @property
    def output(self):
        base = (self._in_attrs if self._sorter is not None
                else list(self.children[0].output))
        return list(base) + [
            a.to_attribute() for a in self.window_exprs]

    # ------------------------------------------------------------------
    def _partition_seg_keys(self, ctx, live):
        """Sort-key words identifying the row's window PARTITION — the
        one recipe shared by the compute kernel and the key-batching cut
        scan, so chunk boundaries can never disagree with segments."""
        xp = ctx.xp
        seg_keys: List = [(~live).astype(xp.int64)]
        for e in self._bound_parts:
            c = e.eval(ctx)
            seg_keys.append((~c.validity).astype(xp.int64))
            seg_keys.extend(column_sort_keys(xp, c))
        return seg_keys

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        xp = self.xp
        ctx = EvalContext(batch, xp=xp)
        n = batch.capacity
        idx = xp.arange(n, dtype=xp.int32)
        live = idx < batch.num_rows

        # --- segment (partition) and peer (order-tie) bounds -----------
        seg_keys = self._partition_seg_keys(ctx, live)
        is_seg_start = W.boundary_flags(xp, seg_keys)
        seg_start, seg_end = W.segment_bounds(xp, is_seg_start)

        order_cols = [o.child.eval(ctx) for o in self._bound_orders]
        peer_keys = list(seg_keys)
        for c in order_cols:
            peer_keys.append((~c.validity).astype(xp.int64))
            peer_keys.extend(column_sort_keys(xp, c))
        is_peer_start = W.boundary_flags(xp, peer_keys)
        peer_start, peer_end = W.segment_bounds(xp, is_peer_start)

        seg_len = seg_end - seg_start
        pos = idx - seg_start

        new_cols = []
        for alias in self._bound_exprs:
            wexpr: WindowExpression = alias.child  # type: ignore
            fn = wexpr.function
            frame = wexpr.spec.effective_frame(fn)
            col = self._eval_window_fn(
                ctx, fn, frame, idx, live, seg_start, seg_end, seg_len, pos,
                peer_start, peer_end, is_peer_start, order_cols)
            new_cols.append(col.mask_dead_rows(live))

        names = tuple(a.name for a in self.output)
        return ColumnarBatch(names, tuple(batch.columns) + tuple(new_cols),
                             batch.num_rows)

    # ------------------------------------------------------------------
    def _frame_bounds(self, frame: WindowFrame, xp, idx, seg_start, seg_end,
                      peer_start, peer_end, order_cols):
        """Per-row [start, end) row-index range for the frame."""
        if frame.frame_type == "rows":
            if frame.lower == UNBOUNDED_PRECEDING:
                fs = seg_start
            else:
                fs = xp.clip(idx + frame.lower, seg_start, seg_end)
            if frame.upper == UNBOUNDED_FOLLOWING:
                fe = seg_end
            else:
                fe = xp.clip(idx + frame.upper + 1, seg_start, seg_end)
            return fs, xp.maximum(fe, fs)

        # RANGE frame
        lo, up = frame.lower, frame.upper
        simple = {UNBOUNDED_PRECEDING: "up", UNBOUNDED_FOLLOWING: "uf",
                  CURRENT_ROW: "cur"}
        if lo in simple and up in simple:
            fs = seg_start if lo == UNBOUNDED_PRECEDING else peer_start
            fe = peer_end if up == CURRENT_ROW else seg_end
            return fs, xp.maximum(fe, fs)

        # numeric RANGE offsets over the single numeric order key.  Integral
        # keys stay in exact int64 arithmetic (epoch-micro timestamps exceed
        # float64's 2^53 integer range); floats use float64.
        oc = order_cols[0]
        asc = self._bound_orders[0].ascending
        integral = not isinstance(oc.dtype, (T.FloatType, T.DoubleType))
        seg_id = xp.cumsum(W.boundary_flags(
            xp, [seg_start.astype(xp.int64)]).astype(xp.int64)) - 1
        if integral:
            v = oc.data.astype(xp.int64)
            v = v if asc else -v
            big = xp.asarray(np.iinfo(np.int64).max, xp.int64)
            vmax = xp.max(xp.where(oc.validity, v, -big))
            vmin = xp.min(xp.where(oc.validity, v, big))
            has_valid = xp.any(oc.validity)
            vmax = xp.where(has_valid, vmax, 0)
            vmin = xp.where(has_valid, vmin, 0)
            pad = (abs(int(lo)) if lo not in simple else 0) + \
                  (abs(int(up)) if up not in simple else 0) + 1
            span = (vmax - vmin) + 2 * pad
            null_v = (vmin - pad) if self._bound_orders[0].nulls_first \
                else (vmax + pad)
            comp = xp.where(oc.validity, v, null_v) + seg_id * span
        else:
            v = oc.data.astype(xp.float64)
            v = v if asc else -v
            vmax = xp.max(xp.where(oc.validity, v, -xp.inf))
            vmin = xp.min(xp.where(oc.validity, v, xp.inf))
            pad = (abs(lo) if lo not in simple else 0) + \
                  (abs(up) if up not in simple else 0) + 1.0
            span = xp.where(xp.isfinite(vmax - vmin), vmax - vmin, 0.0) \
                + 2 * pad
            # null order rows sit at whichever end the sort put them; give
            # them a composite value beyond the live range on that side
            null_v = (vmin - pad) if self._bound_orders[0].nulls_first \
                else (vmax + pad)
            null_v = xp.where(xp.isfinite(null_v), null_v, 0.0)
            comp = xp.where(oc.validity, v, null_v) + \
                seg_id.astype(xp.float64) * span

        if lo == UNBOUNDED_PRECEDING:
            fs = seg_start
        elif lo == CURRENT_ROW:
            fs = peer_start
        else:
            # v is already direction-normalized (negated for desc), so the
            # offset applies unchanged in v-space
            fs = xp.searchsorted(comp, comp + lo, side="left"
                                 ).astype(xp.int32)
            fs = xp.clip(fs, seg_start, seg_end)
        if up == UNBOUNDED_FOLLOWING:
            fe = seg_end
        elif up == CURRENT_ROW:
            fe = peer_end
        else:
            fe = xp.searchsorted(comp, comp + up, side="right"
                                 ).astype(xp.int32)
            fe = xp.clip(fe, seg_start, seg_end)
        # null order rows keep their peer group as the frame
        fs = xp.where(oc.validity, fs, peer_start)
        fe = xp.where(oc.validity, fe, peer_end)
        return fs, xp.maximum(fe, fs)

    # ------------------------------------------------------------------
    def _eval_window_fn(self, ctx, fn, frame, idx, live, seg_start, seg_end,
                        seg_len, pos, peer_start, peer_end, is_peer_start,
                        order_cols):
        xp = self.xp

        if isinstance(fn, RankLike):
            if isinstance(fn, RowNumber):
                return DeviceColumn(T.INT, (pos + 1).astype(xp.int32),
                                    live)
            if isinstance(fn, Rank):
                return DeviceColumn(
                    T.INT, (peer_start - seg_start + 1).astype(xp.int32), live)
            if isinstance(fn, DenseRank):
                cpeer = xp.cumsum(is_peer_start.astype(xp.int32))
                dr = cpeer - cpeer[xp.clip(seg_start, 0, None)] + 1
                return DeviceColumn(T.INT, dr.astype(xp.int32), live)
            if isinstance(fn, PercentRank):
                rank = (peer_start - seg_start).astype(xp.float64)
                denom = xp.maximum(seg_len - 1, 1).astype(xp.float64)
                pr = xp.where(seg_len > 1, rank / denom, 0.0)
                return DeviceColumn(T.DOUBLE, pr, live)
            if isinstance(fn, CumeDist):
                cd = (peer_end - seg_start).astype(xp.float64) / \
                    xp.maximum(seg_len, 1).astype(xp.float64)
                return DeviceColumn(T.DOUBLE, cd, live)
            if isinstance(fn, NTile):
                nt = fn.n
                c = seg_len.astype(xp.int64)
                bs = c // nt
                r = c % nt
                cut = r * (bs + 1)
                p = pos.astype(xp.int64)
                in_big = p < cut
                bucket = xp.where(
                    in_big, p // xp.maximum(bs + 1, 1),
                    r + (p - cut) // xp.maximum(bs, 1))
                return DeviceColumn(T.INT, (bucket + 1).astype(xp.int32),
                                    live)
            raise NotImplementedError(type(fn).__name__)

        if isinstance(fn, (Lead, Lag)):
            val = fn.child.eval(ctx)
            target = idx + fn.offset_sign * fn.offset
            ok = (target >= seg_start) & (target < seg_end)
            out = val.gather(xp.clip(target, 0, idx.shape[0] - 1), ok)
            if fn.default is not None:
                from ..expressions.core import literal_column
                d = literal_column(ctx, val.dtype, fn.default)
                out = _select_column(xp, ok, out, d)
            return out

        fs, fe = self._frame_bounds(frame, xp, idx, seg_start, seg_end,
                                    peer_start, peer_end, order_cols)

        if isinstance(fn, NthValue):
            val = fn.child.eval(ctx)
            if fn.ignore_nulls:
                cs = xp.cumsum(val.validity.astype(xp.int32))
                cspad = xp.concatenate([xp.zeros((1,), xp.int32), cs])
                target_cnt = cspad[fs] + fn.n
                j = xp.searchsorted(cs, target_cnt, side="left"
                                    ).astype(xp.int32)
                ok = j < fe
            else:
                j = fs + fn.n - 1
                ok = j < fe
            return val.gather(xp.clip(j, 0, idx.shape[0] - 1), ok)

        if isinstance(fn, AGG.Count):
            if not fn.children:
                cnt = (fe - fs).astype(xp.int64)
            else:
                val = fn.children[0].eval(ctx)
                cnt = W.frame_count(xp, val.validity, fs, fe)
            return DeviceColumn(T.LONG, cnt, live)

        if isinstance(fn, AGG.Sum):
            val = fn.children[0].eval(ctx)
            dt = fn.data_type
            s = W.frame_sum(xp, val.data, val.validity, fs, fe,
                            out_dtype=dt.np_dtype)
            has = W.frame_count(xp, val.validity, fs, fe) > 0
            return DeviceColumn(dt, s, has)

        if isinstance(fn, AGG.Average):
            val = fn.children[0].eval(ctx)
            s = W.frame_sum(xp, val.data.astype(xp.float64), val.validity,
                            fs, fe, out_dtype=xp.float64)
            c = W.frame_count(xp, val.validity, fs, fe)
            avg = s / xp.maximum(c, 1).astype(xp.float64)
            return DeviceColumn(T.DOUBLE, avg, c > 0)

        if isinstance(fn, (AGG.Min, AGG.Max)):
            val = fn.children[0].eval(ctx)
            is_min = isinstance(fn, AGG.Min)
            ident = _minmax_identity(xp, val.dtype, is_min)
            red = W.frame_min if is_min else W.frame_max
            out, has = red(xp, val.data, val.validity, fs, fe, ident)
            return DeviceColumn(val.dtype, out.astype(val.data.dtype), has)

        if isinstance(fn, AGG._FirstLast):
            val = fn.children[0].eval(ctx)
            is_first = isinstance(fn, AGG.First)
            if fn.ignore_nulls:
                finder = (W.frame_first_valid_index if is_first
                          else W.frame_last_valid_index)
                j, ok = finder(xp, val.validity, fs, fe)
            else:
                j = fs if is_first else fe - 1
                ok = fe > fs
                j = xp.clip(j, 0, idx.shape[0] - 1)
            return val.gather(j, ok)

        raise NotImplementedError(
            f"window function {type(fn).__name__} not supported")

    # ------------------------------------------------------------------
    # --- key-batched out-of-core path ---------------------------------
    def _boundary_fn(self):
        """(last partition start <= limit, first partition start > 0) of
        a sorted batch — the two cut candidates for key-complete
        chunking.  -1 / num_rows when absent."""
        def impl(batch, limit):
            xp = self.xp
            ctx = EvalContext(batch, xp=xp)
            n = batch.capacity
            idx = xp.arange(n, dtype=xp.int32)
            live = idx < batch.num_rows
            is_start = W.boundary_flags(
                xp, self._partition_seg_keys(ctx, live)) & live
            last_le = xp.max(xp.where(is_start & (idx <= limit), idx, -1))
            first_gt = xp.min(xp.where(is_start & (idx > 0), idx,
                                       batch.num_rows))
            return last_le, first_gt
        if self._boundary_fn_cache is None:
            from .kernel_cache import exprs_key
            self._boundary_fn_cache = self._jit(
                impl, key=("wbound", exprs_key(self._bound_parts)))
        return self._boundary_fn_cache

    def _execute_key_batched(self, pid, tctx, target: int, source=None):
        """Process sorted input in key-complete chunks (reference
        ``GpuKeyBatchingIterator.scala``): every chunk holds whole
        partitions and at most ~``target`` rows (grown to the largest
        single partition when one exceeds it), with carried tails held
        spillable between chunks."""
        from ...memory.retry import with_retry
        from ...memory.spill import (ACTIVE_BATCHING_PRIORITY,
                                     ACTIVE_ON_DECK_PRIORITY,
                                     SpillableColumnarBatch)
        boundary = self._boundary_fn()
        carry: List[SpillableColumnarBatch] = []
        carry_rows = 0

        def split_at_partition(sb):
            """SplitAndRetryOOM handler: a head batch holds WHOLE window
            partitions, so cutting at an interior partition boundary
            halves the work without breaking any frame (row-halving, the
            generic splitter, would).  A single-partition head cannot
            split — spill everything and requeue it for a plain retry
            (split_spillable_in_half's unsplittable convention; bounded
            by the retry cap)."""
            b = sb.get()
            m = b.num_rows_int
            last_le, first_gt = boundary(b, np.int32(max(m // 2 - 1, 0)))
            cut = int(last_le)
            if cut <= 0:
                # a hot partition spans past the midpoint: cut right
                # after it instead (same fallback emit_chunks uses)
                cut = int(first_gt)
            if cut <= 0 or cut >= m:
                sb.catalog.spill_all_device()
                return [sb]
            out = [SpillableColumnarBatch.create(
                       b.sliced(0, cut), ACTIVE_ON_DECK_PRIORITY),
                   SpillableColumnarBatch.create(
                       b.sliced(cut, m - cut), ACTIVE_ON_DECK_PRIORITY)]
            sb.close()
            return out

        def run_window(s):
            from .base import count_stage_dispatch
            count_stage_dispatch()
            return self._fn(s.get())

        def process(head):
            sb = SpillableColumnarBatch.create(head,
                                               ACTIVE_ON_DECK_PRIORITY)
            return with_retry([sb], run_window,
                              split=split_at_partition)

        def emit_chunks(final: bool):
            nonlocal carry, carry_rows
            while carry_rows >= target:
                pieces = [sb.get() for sb in carry]
                merged = (ColumnarBatch.concat(pieces)
                          if len(pieces) > 1 else pieces[0])
                m = merged.num_rows_int
                last_le, first_gt = boundary(
                    merged, np.int32(min(target, m - 1)))
                cut = int(last_le)
                if cut <= 0:
                    cut = int(first_gt)  # first partition exceeds target
                if cut <= 0 or cut >= m:
                    # one partition spans the whole carry: grow.  Keep the
                    # CONCATENATED batch as the single carry piece so the
                    # next round doesn't re-merge and re-scan these rows
                    # (a P-row partition would otherwise cost O(P^2))
                    if len(carry) > 1:
                        for sb in carry:
                            sb.close()
                        carry = [SpillableColumnarBatch.create(
                            merged, ACTIVE_BATCHING_PRIORITY)]
                    break
                head = merged.sliced(0, cut)
                tail = merged.sliced(cut, m - cut)
                for sb in carry:
                    sb.close()
                carry = [SpillableColumnarBatch.create(
                    tail, ACTIVE_BATCHING_PRIORITY)]
                carry_rows = m - cut
                tctx.inc_metric("windowKeyBatches")
                yield from process(head)
            if final and carry:
                pieces = [sb.get() for sb in carry]
                merged = (ColumnarBatch.concat(pieces)
                          if len(pieces) > 1 else pieces[0])
                for sb in carry:
                    sb.close()
                carry, carry_rows = [], 0
                tctx.inc_metric("windowKeyBatches")
                yield from process(merged)

        if source is None:
            source = self.children[0].execute(pid, tctx)
        try:
            for batch in source:
                n = batch.num_rows_int
                if n == 0:
                    continue
                carry.append(SpillableColumnarBatch.create(
                    batch, ACTIVE_BATCHING_PRIORITY))
                carry_rows += n
                yield from emit_chunks(final=False)
            yield from emit_chunks(final=True)
        finally:
            for sb in carry:
                sb.close()

    def execute(self, pid, tctx):
        from ...config import WINDOW_BATCH_TARGET_ROWS
        target = int(tctx.conf.get(WINDOW_BATCH_TARGET_ROWS))
        if self._sorter is not None:
            yield from self._execute_stage_terminal(pid, tctx, target)
            return
        if self._bound_parts:
            yield from self._execute_key_batched(pid, tctx, target)
            return
        # no partition keys: every row is one global window partition —
        # key batching cannot cut anywhere
        batches = list(self.children[0].execute(pid, tctx))
        if not batches:
            return
        merged = (ColumnarBatch.concat(batches) if len(batches) > 1
                  else batches[0])
        from .base import count_stage_dispatch
        count_stage_dispatch()
        yield self._fn(merged)

    def _execute_stage_terminal(self, pid, tctx, target: int):
        """Sort/window stage terminal: the absorbed partition sort (and
        any chain absorbed into it) rides the window's program.  A
        single key-complete chunk — the whole input fits ``target`` rows,
        or there are no partition keys to cut on — evaluates chain +
        sort + window in ONE launch; larger inputs run the sort's stage
        program once and feed the sorted stream to the key-complete
        chunker (still dropping every per-op boundary dispatch)."""
        s = self._sorter
        # re-sync like FusedStageExec._execute_terminal: planner rewrites
        # above this node must stay visible to the retained sort
        s.children = self.children
        batches = list(self.children[0].execute(pid, tctx))
        if not batches:
            return
        total = sum(b.num_rows_bound for b in batches)
        if not self._bound_parts or total <= target:
            merged = (ColumnarBatch.concat(batches) if len(batches) > 1
                      else batches[0])
            tctx.inc_metric("windowStageFusedBatches")
            from .base import count_stage_dispatch
            count_stage_dispatch()
            yield self._fused_fn(merged)
            return
        yield from self._execute_key_batched(
            pid, tctx, target, source=s.execute_batches(batches, tctx))

    def simple_string(self):
        s = (f"{self.node_name()} "
             f"[{', '.join(a.child.sql() for a in self.window_exprs)}]")
        if self._sorter is not None:
            s += f" [fusedSort: {self._sorter.simple_string()}]"
        return s


class WindowGroupLimitExec(PhysicalPlan):
    """Rank-limit pushdown (reference: shim ``WindowGroupLimitExec``,
    Spark 3.5+, merged via ``SparkShimImpl.getExecs``): when a filter
    ``rank_like <= k`` sits above a window, each map-side partition only
    needs its per-group top-k rows — everything ranked deeper can never
    pass the filter, whatever the other partitions hold.  The planner
    inserts this BELOW the window's exchange, shrinking shuffle volume;
    the window + filter above still compute exact results.

    Kept rows per (partition-keys) group, ordered by the window order:
    row_number keeps k rows; rank/dense_rank keep every row whose rank
    <= k (ties may keep more).
    """

    def __init__(self, partition_spec, order_spec: Sequence[SortOrder],
                 rank_kind: str, limit: int, child: PhysicalPlan,
                 backend=TPU):
        super().__init__(child)
        self.backend = backend
        self.partition_spec = list(partition_spec)
        self.order_spec = list(order_spec)
        self.rank_kind = rank_kind  # row_number | rank | dense_rank
        self.limit = int(limit)
        out = child.output
        self._bound_parts = [bind_references(e, out)
                             for e in self.partition_spec]
        self._bound_orders = [SortOrder(bind_references(o.child, out),
                                        o.ascending, o.nulls_first)
                              for o in self.order_spec]
        from .kernel_cache import exprs_key
        self._fn = self._jit(
            self._compute,
            key=("wgl", exprs_key(self._bound_parts),
                 exprs_key(self._bound_orders), rank_kind, self.limit))

    @property
    def output(self):
        return self.children[0].output

    def _compute(self, batch: ColumnarBatch) -> ColumnarBatch:
        from ...ops.sorting import sort_permutation
        from .basic import compact_batch
        xp = self.xp
        ctx = EvalContext(batch, xp=xp)
        live0 = batch.row_mask()
        # sort by (partition keys asc, order spec) so groups are contiguous
        specs = [(e.eval(ctx), True, True) for e in self._bound_parts]
        specs += [(o.child.eval(ctx), o.ascending, o.nulls_first)
                  for o in self._bound_orders]
        perm = sort_permutation(xp, specs, live0)
        n = batch.capacity
        valid = xp.arange(n, dtype=xp.int32) < batch.num_rows
        cols = tuple(c.gather(perm, valid) for c in batch.columns)
        sorted_b = ColumnarBatch(batch.names, cols, batch.num_rows)

        sctx = EvalContext(sorted_b, xp=xp)
        idx = xp.arange(n, dtype=xp.int32)
        live = idx < sorted_b.num_rows
        seg_keys: List = [(~live).astype(xp.int64)]
        for e in self._bound_parts:
            c = e.eval(sctx)
            seg_keys.append((~c.validity).astype(xp.int64))
            seg_keys.extend(column_sort_keys(xp, c))
        is_seg_start = W.boundary_flags(xp, seg_keys)
        seg_start, _seg_end = W.segment_bounds(xp, is_seg_start)
        if self.rank_kind == "row_number":
            rank = idx - seg_start + 1
        else:
            peer_keys = list(seg_keys)
            for o in self._bound_orders:
                c = o.child.eval(sctx)
                peer_keys.append((~c.validity).astype(xp.int64))
                peer_keys.extend(column_sort_keys(xp, c))
            is_peer_start = W.boundary_flags(xp, peer_keys)
            peer_start, _pe = W.segment_bounds(xp, is_peer_start)
            if self.rank_kind == "rank":
                rank = peer_start - seg_start + 1
            else:  # dense_rank
                cpeer = xp.cumsum(is_peer_start.astype(xp.int32))
                rank = cpeer - cpeer[xp.clip(seg_start, 0, None)] + 1
        keep = live & (rank <= self.limit)
        return compact_batch(xp, sorted_b, keep)

    def execute(self, pid, tctx):
        for batch in self.children[0].execute(pid, tctx):
            tctx.inc_metric("windowGroupLimitBatches")
            yield self._fn(batch)

    def simple_string(self):
        return (f"{self.node_name()} [{self.rank_kind} <= {self.limit}]")
