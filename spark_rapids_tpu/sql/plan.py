"""Logical plan nodes.

The reference plugs into Spark Catalyst and rewrites *physical* plans
(SURVEY §2.2); standalone, we own the whole stack, so this module is the
Catalyst-equivalent logical algebra the DataFrame API builds, the analyzer
resolves, and the planner lowers to physical execs.  Node set mirrors the
exec coverage in ``GpuOverrides.scala:3805-4184``.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, List, Optional, Sequence, Tuple

from .. import types as T
from .expressions.core import (Alias, AttributeReference, Expression, Literal)


@dataclass(eq=False)
class SortOrder:
    child: Expression
    ascending: bool = True
    nulls_first: Optional[bool] = None  # default: nulls first iff ascending

    def __post_init__(self):
        if self.nulls_first is None:
            self.nulls_first = self.ascending

    def sql(self):
        d = "ASC" if self.ascending else "DESC"
        n = "NULLS FIRST" if self.nulls_first else "NULLS LAST"
        return f"{self.child.sql()} {d} {n}"


class LogicalPlan:
    children: Tuple["LogicalPlan", ...] = ()

    @property
    def output(self) -> List[AttributeReference]:
        raise NotImplementedError(type(self).__name__)

    @property
    def schema(self) -> T.StructType:
        return T.StructType(tuple(
            T.StructField(a.name, a.dtype, a.nullable) for a in self.output))

    def node_name(self) -> str:
        return type(self).__name__

    def simple_string(self) -> str:
        return self.node_name()

    def tree_string(self, level: int = 0) -> str:
        s = "  " * level + ("+- " if level else "") + self.simple_string()
        return "\n".join([s] + [c.tree_string(level + 1) for c in self.children])


@dataclass(eq=False)
class Relation(LogicalPlan):
    """In-memory relation over a pyarrow Table (optionally pre-partitioned)."""
    table: Any = None  # pa.Table
    partitions: Optional[List[Any]] = None  # list of pa.Table

    @property
    def output(self):
        if not hasattr(self, "_output"):
            self._output = [
                AttributeReference(f.name, T.from_arrow(f.type), f.nullable)
                for f in self.table.schema]
        return self._output

    def simple_string(self):
        return f"Relation [{', '.join(a.name for a in self.output)}]"


@dataclass(eq=False)
class CachedRelation(LogicalPlan):
    """df.persist() backing store: the collected result held as COMPRESSED
    parquet bytes, decoded lazily on first scan (the
    ParquetCachedBatchSerializer analog — cached data costs parquet bytes,
    not live arrow/device memory, until it is read again)."""
    blob: bytes = b""
    schema_fields: Tuple = ()

    @property
    def table(self):
        if not hasattr(self, "_table"):
            import io as _io
            import pyarrow.parquet as _pq
            self._table = _pq.read_table(_io.BytesIO(self.blob))
            self._blob_len = len(self.blob)
            self.blob = b""  # decoded form replaces the bytes — never both
        return self._table

    @property
    def output(self):
        return [AttributeReference(f.name, f.data_type, True)
                for f in self.schema_fields]

    def simple_string(self):
        nbytes = len(self.blob) or getattr(self, "_blob_len", 0)
        return (f"CachedRelation [{', '.join(a.name for a in self.output)}] "
                f"({nbytes} parquet bytes)")


@dataclass(eq=False)
class ScanRelation(LogicalPlan):
    """File-source relation (Parquet/ORC/CSV/JSON/Avro)."""
    fmt: str = "parquet"
    paths: Tuple[str, ...] = ()
    read_schema: Optional[T.StructType] = None
    options: dict = field(default_factory=dict)

    @property
    def output(self):
        if not hasattr(self, "_output"):
            if self.read_schema is None:
                from ..io_.registry import infer_schema
                self.read_schema = infer_schema(self.fmt, self.paths,
                                                self.options)
            self._output = [AttributeReference(f.name, f.data_type, f.nullable)
                            for f in self.read_schema.fields]
        return self._output

    def simple_string(self):
        return f"Scan {self.fmt} {list(self.paths)[:1]}"


@dataclass(eq=False)
class Range(LogicalPlan):
    start: int = 0
    end: int = 0
    step: int = 1
    num_slices: int = 1

    @property
    def output(self):
        if not hasattr(self, "_output"):
            self._output = [AttributeReference("id", T.LONG, False)]
        return self._output

    def simple_string(self):
        return f"Range ({self.start}, {self.end}, step={self.step})"


@dataclass(eq=False)
class Project(LogicalPlan):
    exprs: Tuple[Expression, ...] = ()
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        out = []
        for e in self.exprs:
            if isinstance(e, Alias):
                out.append(e.to_attribute())
            elif isinstance(e, AttributeReference):
                out.append(e)
            else:
                out.append(AttributeReference(e.sql(), e.data_type, e.nullable))
        return out

    def simple_string(self):
        return f"Project [{', '.join(e.sql() for e in self.exprs)}]"


@dataclass(eq=False)
class Filter(LogicalPlan):
    condition: Expression = None  # type: ignore
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return self.child.output

    def simple_string(self):
        return f"Filter ({self.condition.sql()})"


@dataclass(eq=False)
class Aggregate(LogicalPlan):
    grouping: Tuple[Expression, ...] = ()
    aggregates: Tuple[Expression, ...] = ()  # output exprs incl. group refs
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        out = []
        for e in self.aggregates:
            if isinstance(e, Alias):
                out.append(e.to_attribute())
            elif isinstance(e, AttributeReference):
                out.append(e)
            else:
                out.append(AttributeReference(e.sql(), e.data_type, e.nullable))
        return out

    def simple_string(self):
        g = ", ".join(e.sql() for e in self.grouping)
        a = ", ".join(e.sql() for e in self.aggregates)
        return f"Aggregate [{g}] [{a}]"


@dataclass(eq=False)
class Sort(LogicalPlan):
    orders: Tuple[SortOrder, ...] = ()
    is_global: bool = True
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return self.child.output

    def simple_string(self):
        return f"Sort [{', '.join(o.sql() for o in self.orders)}] global={self.is_global}"


@dataclass(eq=False)
class Limit(LogicalPlan):
    n: int = 0
    offset: int = 0
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return self.child.output

    def simple_string(self):
        return f"Limit {self.n}"


@dataclass(eq=False)
class Union(LogicalPlan):
    inputs: Tuple[LogicalPlan, ...] = ()

    def __post_init__(self):
        self.children = tuple(self.inputs)

    @property
    def output(self):
        first = self.children[0].output
        return [AttributeReference(a.name, a.dtype,
                                   any(c.output[i].nullable for c in self.children))
                for i, a in enumerate(first)]


@dataclass(eq=False)
class Join(LogicalPlan):
    left: LogicalPlan = None  # type: ignore
    right: LogicalPlan = None  # type: ignore
    how: str = "inner"  # inner|left|right|full|left_semi|left_anti|cross
    left_keys: Tuple[Expression, ...] = ()
    right_keys: Tuple[Expression, ...] = ()
    condition: Optional[Expression] = None  # non-equi residual
    #: the BUILD (right) side carried a broadcast hint
    #: (F.broadcast(df) / df.hint("broadcast")): the join planner skips
    #: the size threshold, like Spark's ResolveHints + JoinSelection
    broadcast_hint: bool = False

    def __post_init__(self):
        self.children = (self.left, self.right)

    @property
    def output(self):
        how = self.how
        lo = list(self.left.output)
        ro = list(self.right.output)
        if how in ("left_semi", "left_anti"):
            return lo
        if how == "left":
            ro = [AttributeReference(a.name, a.dtype, True, a.expr_id) for a in ro]
        if how == "right":
            lo = [AttributeReference(a.name, a.dtype, True, a.expr_id) for a in lo]
        if how == "full":
            lo = [AttributeReference(a.name, a.dtype, True, a.expr_id) for a in lo]
            ro = [AttributeReference(a.name, a.dtype, True, a.expr_id) for a in ro]
        return lo + ro

    def simple_string(self):
        keys = ", ".join(f"{l.sql()}={r.sql()}" for l, r in
                         zip(self.left_keys, self.right_keys))
        return f"Join {self.how} [{keys}]"


@dataclass(eq=False)
class Expand(LogicalPlan):
    projections: Tuple[Tuple[Expression, ...], ...] = ()
    out_attrs: Tuple[AttributeReference, ...] = ()
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return list(self.out_attrs)


@dataclass(eq=False)
class Sample(LogicalPlan):
    lower: float = 0.0
    upper: float = 0.1
    with_replacement: bool = False
    seed: int = 0
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return self.child.output


@dataclass(eq=False)
class Repartition(LogicalPlan):
    num_partitions: int = 0
    exprs: Tuple[Expression, ...] = ()  # empty -> round robin
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return self.child.output


@dataclass(eq=False)
class Generate(LogicalPlan):
    """explode/posexplode over array columns."""
    generator: Expression = None  # type: ignore
    outer: bool = False
    gen_output: Tuple[AttributeReference, ...] = ()
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return list(self.child.output) + list(self.gen_output)


@dataclass(eq=False)
class Window(LogicalPlan):
    """Window operator: child columns plus one output column per window
    expression (Catalyst Window; reference GpuWindowExec SURVEY §2.3).
    ``window_exprs`` are Alias(WindowExpression) sharing one (partition,
    order) spec."""
    window_exprs: Tuple[Alias, ...] = ()
    partition_spec: Tuple[Expression, ...] = ()
    order_spec: Tuple[SortOrder, ...] = ()
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return list(self.child.output) + [
            a.to_attribute() for a in self.window_exprs]

    def simple_string(self):
        return (f"Window [{', '.join(a.child.sql() for a in self.window_exprs)}]")


@dataclass(eq=False)
class MapInPandas(LogicalPlan):
    """mapInPandas: user fn over an iterator of pandas DataFrames
    (reference GpuMapInPandasExec, SURVEY §2.9 Python execs)."""
    func: object = None
    out_schema: "T.StructType" = None  # type: ignore
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return [AttributeReference(f.name, f.data_type, True)
                for f in self.out_schema.fields]


@dataclass(eq=False)
class FlatMapGroupsInPandas(LogicalPlan):
    """groupBy(...).applyInPandas (reference GpuFlatMapGroupsInPandasExec)."""
    grouping: Tuple[Expression, ...] = ()
    func: object = None
    out_schema: "T.StructType" = None  # type: ignore
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        return [AttributeReference(f.name, f.data_type, True)
                for f in self.out_schema.fields]


@dataclass(eq=False)
class AggregateInPandas(LogicalPlan):
    """groupBy(...).agg(grouped-agg pandas UDFs) — one scalar per UDF per
    key group (reference GpuAggregateInPandasExec)."""
    grouping: Tuple[Expression, ...] = ()
    # (output name, GroupedAggPandasUDF) in output order after the keys
    agg_udfs: Tuple = ()
    child: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.child,)

    @property
    def output(self):
        keys = [AttributeReference(getattr(g, "name", g.sql()),
                                   g.data_type, True)
                for g in self.grouping]
        aggs = [AttributeReference(name, u.return_type, True)
                for name, u in self.agg_udfs]
        return keys + aggs


@dataclass(eq=False)
class FlatMapCoGroupsInPandas(LogicalPlan):
    """a.groupBy(k).cogroup(b.groupBy(k)).applyInPandas (reference
    GpuFlatMapCoGroupsInPandasExec)."""
    left_grouping: Tuple[Expression, ...] = ()
    right_grouping: Tuple[Expression, ...] = ()
    func: object = None
    out_schema: "T.StructType" = None  # type: ignore
    left: LogicalPlan = None  # type: ignore
    right: LogicalPlan = None  # type: ignore

    def __post_init__(self):
        self.children = (self.left, self.right)

    @property
    def output(self):
        return [AttributeReference(f.name, f.data_type, True)
                for f in self.out_schema.fields]
