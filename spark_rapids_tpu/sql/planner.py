"""Logical -> physical planning with device placement and transition
insertion (the reference splits this across Catalyst planning +
``GpuOverrides.doConvertPlan`` + ``GpuTransitionOverrides``; SURVEY §3.2)."""

from __future__ import annotations

from typing import List, Optional

from ..config import RapidsConf
from ..parallel.partitioning import (HashPartitioning, RangePartitioning,
                                     RoundRobinPartitioning, SinglePartitioning)
from . import plan as P
from .expressions.core import AttributeReference
from .overrides import PlanMeta, TpuOverrides
from .physical.aggregate import HashAggregateExec
from .physical.base import CPU, TPU, PhysicalPlan
from .physical.basic import (CoalescePartitionsExec, ExpandExec, FilterExec,
                             GlobalLimitExec, InMemoryScanExec, LocalLimitExec,
                             ProjectExec, RangeExec, SampleExec, UnionExec)
from .physical.exchange import BroadcastExchangeExec, ShuffleExchangeExec
from .physical.sortlimit import SortExec, TakeOrderedAndProjectExec
from .physical.transitions import (CoalesceBatchesExec, DeviceToHostExec,
                                   HostToDeviceExec)


class Planner:
    def __init__(self, conf: Optional[RapidsConf] = None):
        self.conf = conf or RapidsConf.get_global()

    # ------------------------------------------------------------------
    def plan(self, logical: P.LogicalPlan) -> PhysicalPlan:
        self._window_group_limits = {}
        parents: dict = {}
        _count_parents(logical, parents, set())
        _annotate_window_group_limits(logical, self._window_group_limits,
                                      parents)
        meta = TpuOverrides.apply(logical, self.conf)
        if self.conf.is_explain_only:
            _force_cpu(meta)
        from ..config import OPTIMIZER_ENABLED
        if bool(self.conf.get(OPTIMIZER_ENABLED)):
            from .optimizer import apply_cost_optimizer
            apply_cost_optimizer(meta, self.conf)
        phys = self._convert(meta)
        phys = _insert_transitions(phys)
        from ..config import FUSION_ENABLED
        if bool(self.conf.get(FUSION_ENABLED)):
            from .physical.fusion import fuse_stages
            phys = fuse_stages(phys, self.conf)
        return phys

    def plan_for_collect(self, logical: P.LogicalPlan) -> PhysicalPlan:
        phys = self.plan(logical)
        if phys.backend == TPU:
            phys = DeviceToHostExec(phys)
        from ..config import FUSION_ENABLED
        if bool(self.conf.get(FUSION_ENABLED)):
            from .physical.collect_fusion import fuse_collect_tail
            phys = fuse_collect_tail(phys)
        # async prefetch boundaries go in LAST (after fuse_stages and the
        # collect-tail fusion) so the fusion passes pattern-match the
        # unwrapped tree; see sql/physical/async_exec.py for the seams
        from ..config import PREFETCH_ENABLED
        if bool(self.conf.get(PREFETCH_ENABLED)):
            from .physical.async_exec import insert_prefetch
            phys = insert_prefetch(phys, self.conf)
        # plan-time fusion coverage counters (wholeStageOps/unfusedOps)
        # fold into last_query_metrics via the collect_metrics walk
        from .physical.fusion import annotate_stage_coverage
        return annotate_stage_coverage(phys)

    # ------------------------------------------------------------------
    def _convert(self, meta: PlanMeta) -> PhysicalPlan:
        node = meta.node
        be = meta.backend
        kids = [self._convert(c) for c in meta.children]

        if isinstance(node, P.Relation):
            parts = node.partitions if node.partitions is not None else [node.table]
            exec_ = InMemoryScanExec(node.output, parts, backend=be)
        elif isinstance(node, P.CachedRelation):
            exec_ = InMemoryScanExec(node.output, [node.table], backend=be)
        elif isinstance(node, P.ScanRelation):
            from ..io_.exec import FileScanExec
            exec_ = FileScanExec(node, backend=be, conf=self.conf)
        elif isinstance(node, P.Range):
            exec_ = RangeExec(node.start, node.end, node.step, node.num_slices,
                              backend=be)
        elif isinstance(node, P.Project):
            exec_ = ProjectExec(node.exprs, kids[0], backend=be)
        elif isinstance(node, P.Filter):
            from ..io_.exec import FileScanExec
            if isinstance(kids[0], FileScanExec):
                # scan-adjacent filter: push prunable conjuncts into the
                # scan for footer-statistics row-group skipping (reference
                # predicate pushdown, GpuParquetScan.scala:2765); the
                # device filter above keeps the full predicate
                from ..io_.pushdown import extract_pushable
                kids[0].pushed_filters = extract_pushable(
                    node.condition, kids[0].output)
            exec_ = FilterExec(node.condition, kids[0], backend=be)
        elif isinstance(node, P.Sample):
            exec_ = SampleExec(node.lower, node.upper, node.seed, kids[0],
                               backend=be)
        elif isinstance(node, P.Expand):
            exec_ = ExpandExec(node.projections, node.out_attrs, kids[0],
                               backend=be)
        elif isinstance(node, P.Union):
            kids = [_coerce_backend(k, kids[0].backend) for k in kids]
            exec_ = UnionExec(kids, backend=kids[0].backend)
        elif isinstance(node, P.Aggregate):
            exec_ = self._plan_aggregate(node, kids[0], be)
        elif isinstance(node, P.Window):
            exec_ = self._plan_window(node, kids[0], be)
        elif isinstance(node, P.Generate):
            from .physical.generate import GenerateExec
            exec_ = GenerateExec(node.generator, node.outer,
                                 node.gen_output, kids[0], backend=be)
        elif isinstance(node, P.Sort):
            exec_ = self._plan_sort(node, kids[0], be)
        elif isinstance(node, P.Limit):
            exec_ = self._plan_limit(node, kids[0], be)
        elif isinstance(node, P.Repartition):
            if node.exprs:
                part = HashPartitioning(node.exprs, node.num_partitions)
            else:
                part = RoundRobinPartitioning(node.num_partitions)
            # USER-requested repartitioning is exempt from AQE coalescing
            # (Spark likewise honors explicit repartition under AQE)
            exec_ = ShuffleExchangeExec(part, kids[0],
                                        backend=kids[0].backend,
                                        coalescible=False)
        elif isinstance(node, P.Join):
            from .physical.join import plan_join
            exec_ = plan_join(node, kids[0], kids[1], be, self.conf)
        elif isinstance(node, P.MapInPandas):
            from .physical.python_execs import MapInPandasExec
            exec_ = MapInPandasExec(node.func, node.out_schema, kids[0],
                                    backend=be)
        elif isinstance(node, P.AggregateInPandas):
            from .physical.python_execs import AggregateInPandasExec
            child = kids[0]
            if child.num_partitions() > 1:
                part = (HashPartitioning(list(node.grouping),
                                         child.num_partitions())
                        if node.grouping else SinglePartitioning())
                child = ShuffleExchangeExec(part, child,
                                            backend=child.backend)
            names = [getattr(g, "name", str(g)) for g in node.grouping]
            exec_ = AggregateInPandasExec(names, list(node.agg_udfs),
                                          child, backend=be)
        elif isinstance(node, P.FlatMapGroupsInPandas):
            from .physical.python_execs import FlatMapGroupsInPandasExec
            child = kids[0]
            if child.num_partitions() > 1:
                # groups must be complete per partition
                child = ShuffleExchangeExec(
                    HashPartitioning(list(node.grouping),
                                     child.num_partitions()),
                    child, backend=child.backend)
            names = [getattr(g, "name", str(g)) for g in node.grouping]
            exec_ = FlatMapGroupsInPandasExec(names, node.func,
                                              node.out_schema, child,
                                              backend=be)
        elif isinstance(node, P.FlatMapCoGroupsInPandas):
            from .physical.python_execs import FlatMapCoGroupsInPandasExec
            lk, rk = kids
            n = max(lk.num_partitions(), rk.num_partitions())
            if n > 1:
                # co-partition BOTH sides identically; never coalesced
                lk = ShuffleExchangeExec(
                    HashPartitioning(list(node.left_grouping), n), lk,
                    backend=lk.backend, coalescible=False)
                rk = ShuffleExchangeExec(
                    HashPartitioning(list(node.right_grouping), n), rk,
                    backend=rk.backend, coalescible=False)
            lnames = [getattr(g, "name", str(g))
                      for g in node.left_grouping]
            rnames = [getattr(g, "name", str(g))
                      for g in node.right_grouping]
            exec_ = FlatMapCoGroupsInPandasExec(lnames, rnames, node.func,
                                                node.out_schema, lk, rk,
                                                backend=be)
        else:
            raise NotImplementedError(
                f"no physical plan for {type(node).__name__}")

        exec_._placement_reasons = list(dict.fromkeys(meta.reasons))
        return exec_

    # ------------------------------------------------------------------
    def _plan_aggregate(self, node: P.Aggregate, child: PhysicalPlan, be):
        from .expressions.aggregates import AggregateFunction
        distinct, regular = _collect_distinct(node)
        if distinct:
            if distinct_rewrite_applies(node, (distinct, regular)):
                inner, outer = self._rewrite_distinct(node, distinct)
                inner_exec = self._plan_aggregate(inner, child, be)
                return self._plan_aggregate(outer, inner_exec, be)
            if _mixed_distinct_applies(node, distinct, regular):
                return self._plan_mixed_distinct(node, child, be, distinct,
                                                 regular)
            if _expand_distinct_applies(node, distinct, regular):
                return self._plan_expand_distinct(node, child, be,
                                                  distinct, regular)
            raise NotImplementedError(UNSUPPORTED_DISTINCT_MSG)
        nparts = child.num_partitions()
        special = any(
            getattr(f, "requires_shuffle_complete", False)
            for e in node.aggregates
            for f in e.collect(lambda x: isinstance(x, AggregateFunction)))
        if special:
            # collect_list/collect_set/approx_percentile: results build
            # from raw rows (no mergeable partial slots) — shuffle rows by
            # key, then ONE complete aggregate per partition
            if nparts > 1:
                part = (HashPartitioning(list(node.grouping), nparts)
                        if node.grouping else SinglePartitioning())
                child = ShuffleExchangeExec(part, child,
                                            backend=child.backend)
            return HashAggregateExec(node.grouping, node.aggregates,
                                     "complete", child, backend=be)
        if nparts <= 1:
            return HashAggregateExec(node.grouping, node.aggregates,
                                     "complete", child, backend=be)
        partial = HashAggregateExec(node.grouping, node.aggregates, "partial",
                                    child, backend=be)
        if node.grouping:
            key_refs = partial.output[:len(node.grouping)]
            part = HashPartitioning(
                key_refs, int(self.conf.shuffle_partitions))
        else:
            part = SinglePartitioning()
        shuffled = ShuffleExchangeExec(part, partial, backend=be)
        return HashAggregateExec(node.grouping, node.aggregates, "final",
                                 shuffled, backend=be)

    def _rewrite_distinct(self, node: P.Aggregate, distinct):
        """count/sum/avg(DISTINCT x[, y...]) GROUP BY k  ->
        (inner dedup aggregate over (k, x, y...), outer aggregate of the
        plain functions over the deduped rows).  Caller has established
        distinct_rewrite_applies(); ``distinct`` is its collected list."""
        from .expressions.aggregates import AggregateExpression
        from .expressions.core import Alias
        dchildren = list(distinct[0].func.children)
        # inner: dedup via group-by over grouping + distinct children
        # (grouping keys are plain attributes — distinct_rewrite_applies
        # guarantees it, so outer outputs rebind by name)
        inner_outs = list(node.grouping)
        dnames = []
        for j, ch in enumerate(dchildren):
            nm = f"__dv{j}"
            dnames.append(nm)
            inner_outs.append(Alias(ch, nm))
        inner = P.Aggregate(tuple(node.grouping) + tuple(dchildren),
                            tuple(inner_outs), node.children[0])
        inner_attrs = inner.output
        key_attrs = inner_attrs[:len(node.grouping)]
        d_attrs = inner_attrs[len(node.grouping):]

        # outer: original outputs with DISTINCT dropped and children
        # rebound to the deduped columns
        def rewrite(e):
            if isinstance(e, AggregateExpression) and e.is_distinct:
                f = e.func.with_children(tuple(d_attrs))
                return AggregateExpression(f, e.mode, False, e.filter)
            if not getattr(e, "children", ()):  # leaf (incl. grouping ref)
                return e
            return e.with_children(tuple(rewrite(c) for c in e.children))

        outer_outs = []
        for e in node.aggregates:
            if isinstance(e, AttributeReference):
                # grouping passthrough: POSITIONAL rebind (name matching
                # would pick the wrong column under duplicate names)
                idx = [j for j, g in enumerate(node.grouping) if g is e
                       or (isinstance(g, AttributeReference)
                           and g.expr_id == e.expr_id)]
                if not idx:
                    raise NotImplementedError(UNSUPPORTED_DISTINCT_MSG)
                outer_outs.append(key_attrs[idx[0]])
            else:
                outer_outs.append(rewrite(e))
        outer = P.Aggregate(tuple(key_attrs), tuple(outer_outs), inner)
        return inner, outer

    def _plan_mixed_distinct(self, node: P.Aggregate, child, be,
                             distinct, regular):
        """Mixed DISTINCT + plain aggregates, e.g.
        ``agg(countDistinct(v), sum(w)) GROUP BY k``:

        1. INNER partial aggregate grouped by (k, v): plain funcs update
           into their mergeable slot layout; one row per (k, v) group.
        2. Hash-exchange the partial rows by k.
        3. OUTER complete aggregate grouped by k: the distinct funcs run
           as PLAIN funcs over the deduped v values, and each plain func
           re-merges its partial slots via PreMergedAggregate — exactly
           the partial->final layering the engine already trusts, just
           under coarser keys (Spark reaches the same result via Expand).
        """
        from .expressions.aggregates import (AggregateExpression,
                                             AggregateFunction,
                                             PreMergedAggregate)
        from .expressions.core import Alias
        dchildren = list(distinct[0].func.children)
        nk, nd = len(node.grouping), len(dchildren)

        # inner: partial agg grouped by keys + distinct children, with the
        # REGULAR funcs as its aggregates (order = their slot order)
        inner_aggs = tuple(Alias(AggregateExpression(f)
                                 if not isinstance(f, AggregateExpression)
                                 else f, f"__r{i}")
                           for i, f in enumerate(regular))
        inner = HashAggregateExec(
            tuple(node.grouping) + tuple(dchildren), inner_aggs, "partial",
            child, backend=be)
        mid = inner
        if child.num_partitions() > 1:
            key_refs = inner.output[:nk]
            part = (HashPartitioning(key_refs,
                                     int(self.conf.shuffle_partitions))
                    if node.grouping else SinglePartitioning())
            exchanged = ShuffleExchangeExec(part, inner, backend=be)
            # different map partitions each hold their own partial row for
            # the same (keys, distinct-values) tuple: a merge-only stage
            # re-groups by the full tuple so the outer's distinct count
            # sees each tuple exactly once (slots stay mergeable)
            mid = HashAggregateExec(
                tuple(node.grouping) + tuple(dchildren), inner_aggs,
                "merge", exchanged, backend=be)

        key_attrs = inner.output[:nk]
        d_attrs = inner.output[nk:nk + nd]
        slot_attrs = inner.output[nk + nd:]
        # slot range per regular func, in inner_aggs order.  The exec
        # DEDUPS semantically identical aggregates into one slot set
        # (HashAggregateExec.register_agg), so identical funcs must map
        # to the SAME range here (no FILTER clauses on this path —
        # _mixed_distinct_applies rejects them)
        ranges = {}
        seen_ranges = {}
        off = 0
        for f in regular:
            base = f.func if isinstance(f, AggregateExpression) else f
            fk = base.semantic_key()
            if fk not in seen_ranges:
                n = len(base.slots())
                seen_ranges[fk] = (off, off + n)
                off += n
            ranges[id(f)] = seen_ranges[fk]

        def rewrite(e):
            if isinstance(e, AggregateExpression):
                if e.is_distinct:
                    return e.func.with_children(tuple(d_attrs))
                lo, hi = ranges[id(e)]
                base = e.func
                return PreMergedAggregate(base, *slot_attrs[lo:hi])
            if isinstance(e, AggregateFunction):
                if id(e) in ranges:
                    lo, hi = ranges[id(e)]
                    return PreMergedAggregate(e, *slot_attrs[lo:hi])
                return e
            if not getattr(e, "children", ()):
                return e
            return e.with_children(tuple(rewrite(c) for c in e.children))

        outer_outs = []
        for e in node.aggregates:
            if isinstance(e, AttributeReference):
                idx = [j for j, g in enumerate(node.grouping) if g is e
                       or (isinstance(g, AttributeReference)
                           and g.expr_id == e.expr_id)]
                if not idx:
                    raise NotImplementedError(UNSUPPORTED_DISTINCT_MSG)
                outer_outs.append(Alias(key_attrs[idx[0]], e.name))
            else:
                outer_outs.append(rewrite(e))
        return HashAggregateExec(tuple(key_attrs), tuple(outer_outs),
                                 "complete", mid, backend=be)

    def _plan_expand_distinct(self, node: P.Aggregate, child, be,
                              distinct, regular):
        """DISTINCT aggregates over SEVERAL child sets (+ optional plain
        aggregates) — Spark's ``RewriteDistinctAggregates`` Expand
        construction (reference executes the resulting ExpandExec via
        ``GpuExpandExec.scala``):

        1. EXPAND each row into m+1 projections: gid 0 carries the
           regular-aggregate inputs (all child columns) and a constant-1
           marker; gid j carries ONLY group j's distinct child
           expressions (everything else typed-NULL).  Grouping keys stay
           live on every projection.
        2. Partial aggregate grouped by (keys, gid, all distinct cols):
           plain funcs with their inputs masked to gid 0, so gid>0 rows
           contribute identity slots.  count(*) counts the marker.
        3. Hash-exchange by keys, merge on the full grouping tuple (each
           (keys, gid, d-tuple) survives exactly once).
        4. Complete aggregate by keys: distinct funcs run as PLAIN funcs
           over their d-columns masked to their own gid (null inputs from
           other gids are ignored by aggregate semantics); plain funcs
           re-merge their slots via PreMergedAggregate.
        """
        from .expressions.aggregates import (AggregateExpression,
                                             AggregateFunction, Count,
                                             PreMergedAggregate)
        from .expressions.conditional import If
        from .expressions.core import Alias, Literal
        from .expressions.predicates import EqualTo
        from .. import types as T

        # distinct groups, gid 1..m in first-seen order
        group_of: dict = {}
        group_children: list = []
        for d in distinct:
            k = tuple(c.semantic_key() for c in d.func.children)
            if k not in group_of:
                group_of[k] = len(group_children) + 1
                group_children.append(list(d.func.children))

        child_attrs = tuple(child.output)
        # grouping keys must stay live on EVERY projection.  Plain-column
        # keys pass through; expression keys are evaluated into their own
        # expand column (the projection still sees all child columns, so
        # the expression computes even on rows whose other outputs are
        # nulled).
        key_ids = {g.expr_id for g in node.grouping
                   if isinstance(g, AttributeReference)}
        gkey_attrs = []
        gkey_exprs = []            # what to project per grouping key
        for i, g in enumerate(node.grouping):
            if isinstance(g, AttributeReference):
                gkey_attrs.append(g)
            else:
                gkey_attrs.append(AttributeReference(
                    f"__gk{i}", g.data_type, True))
            gkey_exprs.append(g)
        extra_keys = [(a, g) for a, g in zip(gkey_attrs, gkey_exprs)
                      if not isinstance(g, AttributeReference)]
        gid_attr = AttributeReference("__did", T.LONG, False)
        marker_attr = AttributeReference("__d0", T.LONG, True)
        dcol_attrs = []
        dcol_pos: dict = {}        # (gid, child_idx) -> index into dcols
        for j, children in enumerate(group_children, start=1):
            for i, c in enumerate(children):
                dcol_pos[(j, i)] = len(dcol_attrs)
                dcol_attrs.append(AttributeReference(
                    f"__d{j}_{i}", c.data_type, True))
        nd = len(dcol_attrs)

        def null_of(dt):
            return Literal(None, dt)

        # child columns stage 1 actually reads: regular-func inputs (the
        # rest project as typed NULLs everywhere — Spark's rewrite also
        # restricts the regular projection to referenced columns)
        used_ids = set(key_ids)
        for f in regular:
            base = f.func if isinstance(f, AggregateExpression) else f
            for c in base.children:
                for a in c.collect(
                        lambda x: isinstance(x, AttributeReference)):
                    used_ids.add(a.expr_id)

        projections = []
        if regular:     # distinct-only queries need no gid-0 projection
            projections.append(
                tuple(a if a.expr_id in used_ids else null_of(a.data_type)
                      for a in child_attrs)
                + tuple(g for _a, g in extra_keys)
                + tuple(null_of(a.data_type) for a in dcol_attrs)
                + (Literal(0, T.LONG), Literal(1, T.LONG)))
        for j, children in enumerate(group_children, start=1):
            row = [a if a.expr_id in key_ids else null_of(a.data_type)
                   for a in child_attrs]
            dvals = [null_of(a.data_type) for a in dcol_attrs]
            for i, c in enumerate(children):
                dvals[dcol_pos[(j, i)]] = c
            projections.append(tuple(row)
                               + tuple(g for _a, g in extra_keys)
                               + tuple(dvals)
                               + (Literal(j, T.LONG), null_of(T.LONG)))
        expand = ExpandExec(
            projections,
            child_attrs + tuple(a for a, _g in extra_keys)
            + tuple(dcol_attrs) + (gid_attr, marker_attr),
            child, backend=be)

        # stage-1 regular funcs: inputs masked to gid 0 (nulls elsewhere
        # make gid>0 rows identity contributions even for literal inputs)
        gid0 = EqualTo(gid_attr, Literal(0, T.LONG))

        def stage1_base(f):
            base = f.func if isinstance(f, AggregateExpression) else f
            if not base.children:
                return Count(marker_attr)      # count(*) over the marker
            return base.with_children(tuple(
                If(gid0, c, null_of(c.data_type)) for c in base.children))

        inner_aggs = tuple(Alias(AggregateExpression(stage1_base(f)),
                                 f"__r{i}")
                           for i, f in enumerate(regular))
        nk = len(node.grouping)
        g1 = tuple(gkey_attrs) + (gid_attr,) + tuple(dcol_attrs)
        inner = HashAggregateExec(g1, inner_aggs, "partial", expand,
                                  backend=be)
        mid = inner
        if child.num_partitions() > 1:
            key_refs = inner.output[:nk]
            part = (HashPartitioning(key_refs,
                                     int(self.conf.shuffle_partitions))
                    if node.grouping else SinglePartitioning())
            exchanged = ShuffleExchangeExec(part, inner, backend=be)
            mid = HashAggregateExec(
                tuple(inner.output[:nk + 1 + nd]), inner_aggs, "merge",
                exchanged, backend=be)

        key_attrs = inner.output[:nk]
        gid_out = inner.output[nk]
        d_out = inner.output[nk + 1:nk + 1 + nd]
        slot_attrs = inner.output[nk + 1 + nd:]

        # slot range per regular func (dedup identical funcs the same way
        # HashAggregateExec.register_agg does)
        ranges = {}
        seen_ranges = {}
        off = 0
        for f in regular:
            fk = stage1_base(f).semantic_key()
            if fk not in seen_ranges:
                n = len(stage1_base(f).slots())
                seen_ranges[fk] = (off, off + n)
                off += n
            ranges[id(f)] = seen_ranges[fk]

        def masked_distinct(e):
            j = group_of[tuple(c.semantic_key() for c in e.func.children)]
            pred = EqualTo(gid_out, Literal(j, T.LONG))
            cols = tuple(
                If(pred, d_out[dcol_pos[(j, i)]],
                   null_of(d_out[dcol_pos[(j, i)]].data_type))
                for i in range(len(e.func.children)))
            return e.func.with_children(cols)

        gkey_by_sem = {g.semantic_key(): key_attrs[i]
                       for i, g in enumerate(gkey_exprs)}

        def rewrite(e):
            if isinstance(e, AggregateExpression):
                if e.is_distinct:
                    return masked_distinct(e)
                lo, hi = ranges[id(e)]
                return PreMergedAggregate(stage1_base(e),
                                          *slot_attrs[lo:hi])
            if isinstance(e, AggregateFunction):
                if id(e) in ranges:
                    lo, hi = ranges[id(e)]
                    return PreMergedAggregate(stage1_base(e),
                                              *slot_attrs[lo:hi])
                return e
            sk = e.semantic_key()
            if sk in gkey_by_sem:     # (sub)expression IS a grouping key
                return gkey_by_sem[sk]
            if not getattr(e, "children", ()):
                return e
            return e.with_children(tuple(rewrite(c) for c in e.children))

        outer_outs = []
        for e in node.aggregates:
            if isinstance(e, AttributeReference):
                idx = [j for j, g in enumerate(node.grouping) if g is e
                       or (isinstance(g, AttributeReference)
                           and g.expr_id == e.expr_id)]
                if not idx:
                    raise NotImplementedError(UNSUPPORTED_DISTINCT_MSG)
                outer_outs.append(Alias(key_attrs[idx[0]], e.name))
            else:
                outer_outs.append(rewrite(e))
        return HashAggregateExec(tuple(key_attrs), tuple(outer_outs),
                                 "complete", mid, backend=be)

    def _plan_window(self, node: P.Window, child: PhysicalPlan, be):
        from ..sql.plan import SortOrder
        from .physical.window import WindowExec, WindowGroupLimitExec
        gl = getattr(self, "_window_group_limits", {}).get(id(node))
        if gl is not None and be == TPU and child.backend == TPU:
            kind, k = gl
            # below the exchange: per-map-partition top-k per group is a
            # superset of the global top-k, so the window+filter above stay
            # exact while the shuffle moves only surviving rows
            child = WindowGroupLimitExec(list(node.partition_spec),
                                         list(node.order_spec), kind, k,
                                         child, backend=be)
        if child.num_partitions() > 1:
            if node.partition_spec:
                part = HashPartitioning(list(node.partition_spec),
                                        child.num_partitions())
            else:
                part = SinglePartitioning()
            child = ShuffleExchangeExec(part, child, backend=be)
        orders = ([SortOrder(e) for e in node.partition_spec]
                  + list(node.order_spec))
        if orders:
            child = SortExec(orders, child, backend=be)
        return WindowExec(node.window_exprs, node.partition_spec,
                          node.order_spec, child, backend=be)

    def _plan_sort(self, node: P.Sort, child: PhysicalPlan, be):
        if node.is_global and child.num_partitions() > 1:
            part = RangePartitioning(node.orders, child.num_partitions())
            child = ShuffleExchangeExec(part, child, backend=be)
        return SortExec(node.orders, child, backend=be,
                        is_global=node.is_global)

    def _plan_limit(self, node: P.Limit, child: PhysicalPlan, be):
        # TopN composition (the reference builds TakeOrderedAndProject in
        # the rule, GpuOverrides.scala:3880-3904): Limit directly over a
        # Sort becomes per-partition top-n + merge, skipping the range
        # exchange a global sort would otherwise need
        if node.offset == 0 and isinstance(child, SortExec) \
                and child.backend == be and child.is_global:
            inner = child.children[0]
            if isinstance(inner, ShuffleExchangeExec) and isinstance(
                    inner.partitioning, RangePartitioning):
                inner = inner.children[0]  # top-n needs no range exchange
            return TakeOrderedAndProjectExec(node.n, child.orders, None,
                                             inner, backend=be)
        local = LocalLimitExec(node.n + node.offset, child, backend=be)
        if child.num_partitions() > 1:
            gathered = ShuffleExchangeExec(SinglePartitioning(), local,
                                           backend=be)
        else:
            gathered = local
        return GlobalLimitExec(node.n, node.offset, gathered, backend=be)


def _force_cpu(meta: PlanMeta):
    meta.backend = "cpu"
    for c in meta.children:
        _force_cpu(c)


def _coerce_backend(plan: PhysicalPlan, backend: str) -> PhysicalPlan:
    if plan.backend == backend:
        return plan
    return HostToDeviceExec(plan) if backend == TPU else DeviceToHostExec(plan)


def _insert_transitions(plan: PhysicalPlan) -> PhysicalPlan:
    new_children = tuple(_insert_transitions(c) for c in plan.children)
    fixed = []
    for c in new_children:
        if c.backend != plan.backend and not isinstance(
                plan, (DeviceToHostExec, HostToDeviceExec)):
            c = HostToDeviceExec(c) if plan.backend == TPU else DeviceToHostExec(c)
        fixed.append(c)
    plan.children = tuple(fixed)
    return plan


def _count_parents(node, counts, seen_edges) -> None:
    """Parent-edge counts per logical node id (the logical plan is a DAG:
    a DataFrame reused in two branches shares subtree objects)."""
    for c in getattr(node, "children", ()):
        edge = (id(node), id(c))
        if edge not in seen_edges:
            seen_edges.add(edge)
            counts[id(c)] = counts.get(id(c), 0) + 1
        _count_parents(c, counts, seen_edges)


def _annotate_window_group_limits(node, out, parents) -> None:
    """Logical pre-pass: mark Window nodes sitting under a rank-limit
    filter (``rank()/row_number()/dense_rank() <= k``) so _plan_window can
    insert a WindowGroupLimitExec below the exchange (reference: Spark
    3.5's WindowGroupLimitExec, accelerated via the version shims and
    merged through ``SparkShimImpl.getExecs``)."""
    from .expressions.core import AttributeReference, Literal
    from .expressions.predicates import (And, EqualTo, LessThan,
                                         LessThanOrEqual)
    from .expressions.windows import (DenseRank, Rank, RowNumber,
                                      WindowExpression)

    for c in getattr(node, "children", ()):
        _annotate_window_group_limits(c, out, parents)
    if not isinstance(node, P.Filter):
        return
    # see through projections that pass the rank column along untouched
    # (withColumn/select insert these between the filter and the window)
    from .expressions.core import Alias
    below = node.child
    projects = []
    while isinstance(below, P.Project):
        projects.append(below)
        below = below.child

    def resolve_name(name):
        """Map a filter-level column name down through the project chain to
        the window-output name (withColumn aliases `_weN` to the user
        name); None if any projection rebuilds it with an expression."""
        for pr in projects:
            nxt = None
            for e in pr.exprs:
                if getattr(e, "name", None) != name:
                    continue
                if isinstance(e, AttributeReference):
                    nxt = e.name
                elif isinstance(e, Alias) and isinstance(
                        e.child, AttributeReference):
                    nxt = e.child.name
                break
            if nxt is None:
                return None
            name = nxt
        return name
    if not isinstance(below, P.Window):
        return
    win = below
    if not win.order_spec:
        return
    # the pushdown drops rows below the window, which is only sound when
    # EVERY consumer of the window (and of each pass-through project) sits
    # behind this rank filter — a shared unfiltered branch must see all rows
    chain_nodes = [win] + projects
    if any(parents.get(id(n), 0) > 1 for n in chain_nodes):
        return

    def conjuncts(e):
        if isinstance(e, And):
            for ch in e.children:
                yield from conjuncts(ch)
        else:
            yield e

    # Spark's InferWindowGroupLimit precondition: EVERY window expression
    # on the node must be rank-like.  A lead()/full-frame aggregate sharing
    # the spec would be computed over the truncated input and produce wrong
    # values on surviving rows.
    rank_outputs = {}
    for a in win.window_exprs:
        we = a.child
        if not isinstance(we, WindowExpression):
            return
        kind = {RowNumber: "row_number", Rank: "rank",
                DenseRank: "dense_rank"}.get(type(we.function))
        if kind is None:
            return
        rank_outputs[a.name] = kind

    for conj in conjuncts(node.condition):
        if not (isinstance(conj, (LessThan, LessThanOrEqual, EqualTo))
                and isinstance(conj.children[0], AttributeReference)
                and isinstance(conj.children[1], Literal)):
            continue
        name = resolve_name(conj.children[0].name)
        lit = conj.children[1].value
        if name is None or name not in rank_outputs \
                or not isinstance(lit, (int,)) or isinstance(lit, bool):
            continue
        k = lit - 1 if isinstance(conj, LessThan) else lit
        if k <= 0:
            continue
        out[id(win)] = (rank_outputs[name], int(k))
        return


UNSUPPORTED_DISTINCT_MSG = (
    "DISTINCT aggregates need non-empty DISTINCT child lists, no FILTER "
    "clauses, and (when mixed with plain aggregates) slot-based "
    "null-ignoring plain functions — first()/last() without ignoreNulls "
    "and collect/percentile aggregates can't share a node with DISTINCT")


def _expand_distinct_applies(node: "P.Aggregate", distinct, regular) -> bool:
    """The Expand plan (multiple DISTINCT child sets) needs: non-empty
    child lists, no FILTER clauses anywhere, slot-based NULL-IGNORING
    regular funcs, and count(*) as the only zero-child regular function.
    Grouping keys may be expressions (evaluated into their own expand
    column).  first()/last() without ignoreNulls contribute EVERY live
    row — including the injected gid>0 rows whose inputs the plan masks
    to NULL — so they must take another path."""
    from .expressions.aggregates import (AggregateExpression, Count,
                                         _FirstLast)
    if any(d.filter is not None for d in distinct):
        return False
    if not all(d.func.children for d in distinct):
        return False
    for f in regular:
        base = f.func if isinstance(f, AggregateExpression) else f
        if getattr(base, "requires_shuffle_complete", False):
            return False
        if isinstance(f, AggregateExpression) and f.filter is not None:
            return False
        if not base.children and not isinstance(base, Count):
            return False
        if isinstance(base, _FirstLast) and not base.ignore_nulls:
            return False
    return True


def _collect_distinct(node: "P.Aggregate"):
    """(distinct AggregateExpressions, regular agg funcs) in the node."""
    from .expressions.aggregates import (AggregateExpression,
                                         AggregateFunction)
    distinct, regular = [], []
    for e in node.aggregates:
        wrapped = e.collect(lambda x: isinstance(x, AggregateExpression))
        for a in wrapped:
            (distinct if a.is_distinct else regular).append(a)
        wrapped_funcs = {id(a.func) for a in wrapped}
        for a in e.collect(lambda x: isinstance(x, AggregateFunction)):
            if id(a) not in wrapped_funcs:
                regular.append(a)  # bare function, never DISTINCT
    return distinct, regular


def _distinct_shape_ok(node: "P.Aggregate", distinct) -> bool:
    """Checks shared by both DISTINCT plans: no FILTER clauses, plain-
    column grouping keys, one shared non-empty DISTINCT child set."""
    if any(d.filter is not None for d in distinct):
        return False
    if not all(isinstance(g, AttributeReference) for g in node.grouping):
        return False
    keys = {tuple(c.semantic_key() for c in d.func.children)
            for d in distinct}
    return len(keys) == 1 and all(d.func.children for d in distinct)


def distinct_rewrite_applies(node: "P.Aggregate",
                             precollected=None):
    """DISTINCT aggregates plan as dedup-then-aggregate when every
    aggregate in the node is DISTINCT over the SAME child expressions
    with no FILTER clause, and the grouping keys are plain columns (the
    common count(DISTINCT x)/sum(DISTINCT x) shapes).  Anything else —
    mixed DISTINCT+plain (Spark's Expand plan), differing children,
    filtered or expression-keyed forms — raises at planning: no engine
    path computes those correctly yet, and a silent non-distinct answer
    is worse than an error."""
    distinct, regular = (precollected if precollected is not None
                         else _collect_distinct(node))
    if not distinct or regular:
        return False
    return _distinct_shape_ok(node, distinct)


def _mixed_distinct_applies(node: "P.Aggregate", distinct, regular) -> bool:
    """The mixed plan needs: one shared DISTINCT child set, no FILTER
    clauses, plain-column grouping keys, and slot-based regular funcs
    (shuffle-complete collect/percentile aggregates have no mergeable
    slots)."""
    from .expressions.aggregates import AggregateExpression
    if not _distinct_shape_ok(node, distinct):
        return False
    for f in regular:
        base = f.func if isinstance(f, AggregateExpression) else f
        if getattr(base, "requires_shuffle_complete", False):
            return False
        if isinstance(f, AggregateExpression) and f.filter is not None:
            return False
    return True
