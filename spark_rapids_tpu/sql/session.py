"""TpuSession — the SparkSession-equivalent entry point (reference:
``SQLPlugin`` + driver/executor plugin init, SURVEY §2.1, recast for a
standalone engine: device init happens lazily on first TPU exec)."""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Sequence

import numpy as np
import pyarrow as pa

from .. import types as T
from ..config import RapidsConf
from . import plan as P
from .dataframe import DataFrame
from .planner import Planner


class SessionConf:
    def __init__(self, conf: RapidsConf):
        self._conf = conf

    def set(self, key: str, value) -> None:
        self._conf.set(key, value)

    def get(self, key: str, default=None):
        return self._conf.get(key, default)


def _aux_stats_snapshot() -> dict:
    """Flat snapshot of the process-wide encoded/prepack/decode counters
    whose per-query deltas fold into last_query_metrics (the robustness
    stats_snapshot pattern)."""
    from ..columnar import encoded as _enc
    from ..columnar import prepack as _pp
    from ..io_ import decode_stats as _ds
    out = dict(_ds.snapshot())
    es = _enc.stats_snapshot()
    out.update({
        "encodedColumnsEncoded": es["columns_encoded"]
        + es["rle_columns_encoded"],
        "encodedColumnsDeclined": es["columns_declined"],
        "encodedMaterializations": es["materializations"],
        "encodedDictFilters": es["dict_filters"],
        "encodedConcatUnified": es["concat_unified"],
        "encodedWireDictInline": es["wire_dict_inline"],
        "encodedWireDictRefs": es["wire_dict_refs"],
        "encodedWireBytesSaved": es["wire_bytes_saved"],
    })
    out.update({
        "prepackBytesOnWire": _pp.STATS["bytes_on_wire"],
        "prepackBytesNaive": _pp.STATS["bytes_naive"],
        "prepackFetches": _pp.STATS["prepacked_fetches"],
    })
    return out


class TpuSession:
    _lock = threading.Lock()
    _active: Optional["TpuSession"] = None
    #: atomic under the GIL (a plain int += under _lock would deadlock:
    #: get_or_create constructs sessions while already holding _lock)
    _session_seq = __import__("itertools").count(1)

    def __init__(self, conf: Optional[RapidsConf] = None, **conf_kwargs):
        base = conf or RapidsConf.get_global()
        self._conf = base.copy(conf_kwargs or None)
        self.conf = SessionConf(self._conf)
        self.last_query_metrics: dict = {}
        #: compact tracer summary of the last traced query (sync count/ms,
        #: compile ms, bytes on the wire); None when tracing was off
        self.last_query_trace_summary: Optional[dict] = None
        #: drain latency of the most recent cancelled/deadline-expired
        #: query (cancel issue -> worker threads unwound), ms; None
        #: until a cancellation happens (serving/lifecycle.py)
        self.last_cancel_latency_ms: Optional[float] = None
        self._temp_views: dict = {}
        #: name -> implementation object (Hive UDF bridge; hiveUDFs.scala
        #: analog — populated by CREATE TEMPORARY FUNCTION or the API)
        self._hive_udfs: dict = {}
        #: stable session identity stamped on every span, metric series
        #: and flight-recorder record (groundwork for per-tenant metrics,
        #: ROADMAP item 1); also exported as a Chrome-trace process label
        import os as _os
        self.session_id = (f"sess-{_os.getpid()}-"
                           f"{next(TpuSession._session_seq)}")
        self._history = None  # lazily built from conf on first record
        #: tenant identity (spark.rapids.tpu.serving.tenant): stamped on
        #: metric series, trace spans and flight-recorder records; the
        #: serving tier's admission queue schedules and budgets by it
        from ..config import SERVING_TENANT
        self.tenant = str(self._conf.get(SERVING_TENANT) or "")
        #: owning ServingEngine when this session runs in serving mode
        #: (set by ServingEngine.session); None = classic single-driver
        self._serving = None
        #: embedded telemetry server (observability/server.py) when
        #: spark.rapids.tpu.telemetry.enabled and this session is NOT
        #: under a ServingEngine (the engine owns the plane there and
        #: forces the conf off for its sessions); stop with
        #: :meth:`close_telemetry` — leak-free by contract
        self.telemetry = None
        from ..config import TELEMETRY_ENABLED
        if bool(self._conf.get(TELEMETRY_ENABLED)):
            self._start_telemetry()

    # ------------------------------------------------------------------
    @classmethod
    def get_or_create(cls, conf=None, **conf_kwargs) -> "TpuSession":
        with cls._lock:
            if cls._active is None or conf is not None or conf_kwargs:
                cls._active = TpuSession(conf, **conf_kwargs)
            return cls._active

    # ------------------------------------------------------------------
    # data sources
    # ------------------------------------------------------------------
    def create_dataframe(self, data, schema=None, num_partitions: int = 1,
                         partitions=None) -> DataFrame:
        table = _to_arrow_table(data, schema)
        if partitions is not None:
            parts = list(partitions)
        else:
            # split through the process-wide dedupe cache: repeated
            # create_dataframe calls over the SAME table object yield the
            # same partition slice objects, so the scan upload cache (and
            # the serving tier's content-keyed result/broadcast caches,
            # which key in-memory leaves by table identity) hit across
            # queries and sessions instead of re-uploading per query
            parts = _split_table_cached(table, num_partitions) \
                if num_partitions > 1 else None
        rel = P.Relation(table, parts)
        return DataFrame(rel, self)

    createDataFrame = create_dataframe

    def range(self, start: int, end: Optional[int] = None, step: int = 1,
              num_slices: int = 1) -> DataFrame:
        if end is None:
            start, end = 0, start
        return DataFrame(P.Range(start, end, step, num_slices), self)

    @property
    def read(self) -> "DataFrameReader":
        return DataFrameReader(self)

    # ------------------------------------------------------------------
    # SQL surface (Catalyst-parser analog; sqlparser.py)
    # ------------------------------------------------------------------
    def sql(self, query: str) -> DataFrame:
        """Run a SQL query over registered temp views — the same planning
        and execution path as the DataFrame API."""
        from .sqlparser import parse_query
        return parse_query(self, query)

    def register_hive_function(self, name: str, impl) -> None:
        """Register a Hive-style function (the CREATE TEMPORARY FUNCTION
        surface): ``impl`` is an object/class with ``return_type`` and
        ``evaluate(*row)`` (row-based, host) or
        ``evaluate_columnar(ctx, *cols)`` (device SPI), or a
        'module.Class' string resolved by import."""
        from .expressions.hive_udf import (_impl_return_type,
                                           resolve_hive_class)
        if isinstance(impl, str):
            impl = resolve_hive_class(impl)
        elif isinstance(impl, type):
            impl = impl()
        _impl_return_type(impl)  # validate the declaration up front
        self._hive_udfs[name.lower()] = impl

    def table(self, name: str) -> DataFrame:
        view = self._temp_views.get(name.lower())
        if view is None:
            raise ValueError(f"table or view not found: {name}")
        return DataFrame(view._plan, self)

    @property
    def catalog(self) -> "Catalog":
        return Catalog(self)

    # ------------------------------------------------------------------
    # execution
    # ------------------------------------------------------------------
    def _execute(self, logical: P.LogicalPlan) -> pa.Table:
        if self._serving is not None:
            # serving mode: no per-query global flag flips (the engine
            # armed them for its lifetime), admission-gated execution,
            # thread-scoped tenant attribution — see _execute_serving
            return self._execute_serving(logical)
        import time as _time
        from ..columnar.convert import device_to_arrow
        from ..config import (METRICS_ENABLED, METRICS_MAX_SERIES,
                              PROFILE_ENABLED, SERVING_RESULT_CACHE_ENABLED,
                              TRACE_BUFFER_EVENTS, TRACE_SINK)
        from ..observability import metrics as OM
        from ..observability import tracer as OT
        from ..robustness import faults as _faults
        from ..robustness import stats_snapshot
        from .physical import speculation
        from .physical.base import PROFILING
        from .physical.kernel_cache import cache_stats
        # cross-query result cache (docs/serving.md): a content-key hit
        # short-circuits the whole query — no flag arming, no execution
        rc_key = None
        if bool(self._conf.get(SERVING_RESULT_CACHE_ENABLED)):
            from ..serving import result_cache as RC
            rc_key, hit = RC.lookup_logical(logical, self._conf)
            if hit is not None:
                self._note_result_cache_hit(hit)
                return hit
        # arm/disarm the seeded chaos registry from this session's conf
        # for the duration of THIS query, restore-on-exit like the
        # tracing flags below (a disabled conf only undoes a conf-driven
        # arming, so tests arming chaos directly keep their schedule)
        prev_chaos = _faults.snapshot_arming()
        _faults.apply_conf(self._conf)
        rob0 = stats_snapshot()
        aux0 = _aux_stats_snapshot()
        profiling = bool(self._conf.get(PROFILE_ENABLED))
        sink = str(self._conf.get(TRACE_SINK) or "").strip()
        # profile.enabled implies an in-memory trace so the profile report
        # carries sync/compile/transfer attribution, not just wall time
        tracing = profiling or bool(sink)
        metrics_on = bool(self._conf.get(METRICS_ENABLED))
        # save/restore the process-wide flags (finally-guarded): a query
        # raising mid-flight, or one session enabling profiling, must not
        # leak the flags into a later query or another session's.  The
        # flags being process-global at all rests on the single-driver
        # model — see PROFILING in physical/base.py.
        prev_prof, prev_trace = PROFILING["on"], OT.TRACING["on"]
        prev_metrics = OM.METRICS["on"]
        PROFILING["on"] = profiling or tracing
        self._query_seq = getattr(self, "_query_seq", 0) + 1
        qctx = self._new_query_ctx()
        if tracing:
            OT.get_tracer().reset(int(self._conf.get(TRACE_BUFFER_EVENTS)),
                                  session=self.session_id)
        OT.TRACING["on"] = tracing
        if metrics_on:
            reg = OM.get_registry()
            reg.max_series = int(self._conf.get(METRICS_MAX_SERIES))
            labels = {"query": self._query_seq,
                      "session": self.session_id}
            if self.tenant:
                labels["tenant"] = self.tenant
            reg.set_default_labels(**labels)
        OM.METRICS["on"] = metrics_on
        cache_stats0 = cache_stats()
        ok = False
        err: Optional[BaseException] = None
        t0 = _time.perf_counter()
        try:
            from ..serving import lifecycle as _lc
            with _lc.installed(qctx):
                out = self._execute_traced(logical, device_to_arrow,
                                           speculation)
            ok = True
            if rc_key is not None:
                from ..serving import result_cache as RC
                RC.store(rc_key, out)
            return out
        except BaseException as e:
            err = e
            raise
        finally:
            duration_s = _time.perf_counter() - t0
            PROFILING["on"] = prev_prof
            OT.TRACING["on"] = prev_trace
            OM.METRICS["on"] = prev_metrics
            _faults.restore_arming(prev_chaos)
            self._finish_query_ctx(qctx)
            self._finish_trace(tracing, sink, cache_stats0, rob0, ok,
                               aux0=aux0, duration_s=duration_s, err=err,
                               metrics_on=metrics_on)

    def _execute_serving(self, logical: P.LogicalPlan) -> pa.Table:
        """Serving-mode execution (docs/serving.md): result-cache
        short-circuit, degraded-engine/quarantine gate, admission slot
        (weighted-fair + tenant budget, cancellable), pressure-aware
        plan degradation, thread-scoped tenant/session attribution on
        metrics and trace spans, shared flight-recorder record — and NO
        per-query global flag churn: tracing/profiling/metrics/chaos
        were armed once by the owning ServingEngine, because N driver
        threads saving and restoring process flags would race each
        other.

        Per-query kernel-cache deltas are deliberately absent here
        (concurrent queries would smear each other's compiles); use the
        engine-scoped registry/cache_stats views instead."""
        import time as _time
        from ..columnar.convert import device_to_arrow
        from ..memory.fatal import FatalDeviceError
        from ..observability import metrics as OM
        from ..observability import tracer as OT
        from ..serving import lifecycle as _lc
        from .physical import speculation
        eng = self._serving
        tenant = self.tenant or "default"
        rc_key = None
        if eng.result_cache_enabled:
            # hits bypass admission entirely: a cached result consumes
            # no slot, no budget, no device time
            from ..serving import result_cache as RC
            rc_key, hit = RC.lookup_logical(logical, self._conf)
            if hit is not None:
                self._note_result_cache_hit(hit)
                return hit
        # poison-query gate: only computed when the engine is degraded
        # or has live quarantine entries — the healthy path never pays
        # for a fingerprint (docs/serving.md "query lifecycle")
        qkey = None
        if eng.is_degraded() or eng.quarantine.size():
            qkey = _lc.quarantine_key(logical, self._conf)
            eng.check_admittable(qkey)
        from ..serving.admission import estimate_query_bytes
        est = estimate_query_bytes(logical)
        self._query_seq = getattr(self, "_query_seq", 0) + 1
        # the lifecycle token exists BEFORE admission so a cancel fired
        # while the query is still queued unblocks the admission wait
        # (and rolls the tenant's WFQ virtual finish time back)
        qctx = self._new_query_ctx()
        t_sub = _time.perf_counter()
        try:
            ticket = eng.admission.acquire(tenant, est, cancel=qctx)
        except BaseException:
            self._finish_query_ctx(qctx)
            raise
        wait_s = _time.perf_counter() - t_sub
        if OT.TRACING["on"] and wait_s > 1e-6:
            OT.get_tracer().complete("admission", f"admit.{tenant}",
                                     t_sub, wait_s, tenant=tenant,
                                     est_bytes=est)
        # pressure-aware graceful degradation: a saturated admission
        # queue shrinks THIS query's plan (kill-switched; lifecycle.py)
        conf = self._conf
        pressure_over = eng.pressure.plan_overrides(eng.admission,
                                                    self._conf)
        if pressure_over:
            conf = self._conf.copy(pressure_over)
        OT.set_thread_context(tenant=tenant, sid=self.session_id)
        if OM.METRICS["on"]:
            OM.get_registry().set_thread_labels(
                tenant=tenant, session=self.session_id,
                query=self._query_seq)
        ok = False
        err: Optional[BaseException] = None
        t0 = _time.perf_counter()
        try:
            with _lc.installed(qctx):
                out = self._execute_traced(logical, device_to_arrow,
                                           speculation, conf=conf)
            ok = True
        except FatalDeviceError as e:
            # poison query: fail ONLY this query, quarantine its plan
            # fingerprint, mark the engine degraded until a probe
            # succeeds — sibling tenants' in-flight queries finish
            err = e
            eng.note_fatal(e, qkey
                           or _lc.quarantine_key(logical, self._conf),
                           tenant=tenant)
            raise
        except BaseException as e:
            err = e
            raise
        finally:
            duration_s = _time.perf_counter() - t0
            OT.clear_thread_context()
            OM.get_registry().clear_thread_labels()
            eng.admission.release(ticket)
            self._finish_query_ctx(qctx)
            self.last_query_trace_summary = None  # engine-scoped trace
            if ok:
                m = self.last_query_metrics
                m["sessionId"] = self.session_id
                m["tenant"] = tenant
                m["admissionWaitMs"] = round(wait_s * 1e3, 3)
                m["admissionEstBytes"] = est
                if pressure_over:
                    m["pressureDegraded"] = 1
            self._record_history(ok, duration_s, err)
            status = "ok" if ok else "failed"
            OM.observe("query_ms", duration_s * 1e3, status=status,
                       tenant=tenant, session=self.session_id)
            OM.inc("queries_total", status=status, tenant=tenant)
            OM.observe("admission_wait_ms", wait_s * 1e3, tenant=tenant)
        if rc_key is not None:
            from ..serving import result_cache as RC
            RC.store(rc_key, out)
        return out

    # ------------------------------------------------------------------
    # query lifecycle (serving/lifecycle.py, docs/robustness.md)
    # ------------------------------------------------------------------
    def _new_query_ctx(self):
        """Create + register the lifecycle token for query
        ``self._query_seq`` (cooperative cancellation + deadline)."""
        from ..config import QUERY_CANCEL_POLL_SITES, QUERY_DEADLINE_MS
        from ..serving import lifecycle as _lc
        qctx = _lc.QueryContext(
            self._query_seq, session_id=self.session_id,
            tenant=self.tenant,
            deadline_ms=int(self._conf.get(QUERY_DEADLINE_MS)),
            poll_sites=_lc.parse_poll_sites(
                self._conf.get(QUERY_CANCEL_POLL_SITES)))
        _lc.register(qctx)
        return qctx

    def _finish_query_ctx(self, qctx) -> None:
        """Unregister the token; when the query was cancelled (or hit
        its deadline), bank the drain latency — cancel issue to worker
        threads unwound — as the ``cancel_latency_ms`` series and a
        ``cancel`` trace span (the bench `lifecycle` phase's p50/p99)."""
        import time as _time
        from ..observability import metrics as OM
        from ..observability import tracer as OT
        from ..serving import lifecycle as _lc
        _lc.unregister(qctx)
        if qctx.cancelled_at is None:
            return
        lat_s = _time.perf_counter() - qctx.cancelled_at
        self.last_cancel_latency_ms = lat_s * 1e3
        OM.observe("cancel_latency_ms", lat_s * 1e3,
                   **({"tenant": self.tenant} if self.tenant else {}))
        if OT.TRACING["on"]:
            OT.get_tracer().complete(
                "cancel", "query.drained", qctx.cancelled_at, lat_s,
                query=qctx.query_id, reason=qctx.reason)

    def cancel(self, query_id: Optional[int] = None,
               reason: str = "cancelled by user") -> int:
        """Cooperatively cancel this session's running query (or the
        specific ``query_id``).  Worker threads observe the token at the
        lifecycle poll sites and unwind within the poll bound, releasing
        the device semaphore, retention pins and prefetch queues; the
        waiting ``collect()`` raises :class:`QueryCancelled`.  Returns
        how many live queries were cancelled (0 = nothing running)."""
        from ..serving import lifecycle as _lc
        return _lc.cancel_session(self.session_id, query_id, reason)

    def _note_result_cache_hit(self, table) -> None:
        """Epilogue for a result served from the cross-query cache: the
        query still leaves metrics + a flight-recorder record (hit
        visibility is the contract CI asserts), just no execution."""
        from ..observability import metrics as OM
        self._query_seq = getattr(self, "_query_seq", 0) + 1
        tenant = self.tenant or ""
        self.last_query_metrics = {
            "resultCacheHit": 1, "sessionId": self.session_id,
            "numOutputRows": int(getattr(table, "num_rows", 0)),
        }
        if tenant:
            self.last_query_metrics["tenant"] = tenant
        self.last_query_trace_summary = None
        self._last_phys = None
        self._record_history(True, 0.0, None)
        OM.inc("result_cache_served_total",
               **({"tenant": tenant} if tenant else {}))

    def _finish_trace(self, tracing: bool, sink: str, cache_stats0: dict,
                      rob0: dict, ok: bool, aux0: Optional[dict] = None,
                      duration_s: float = 0.0,
                      err: Optional[BaseException] = None,
                      metrics_on: bool = False) -> None:
        """Per-query trace epilogue: fold kernel-cache and robustness
        deltas into last_query_metrics, snapshot the tracer (the ring is
        process-wide and resets at the next traced query), build the
        compact summary, append the JSONL event log when the sink is a
        directory, land the query in the flight recorder, and feed the
        whole-query metrics series."""
        from ..robustness import stats_snapshot
        from .physical.kernel_cache import cache_stats
        cs1 = cache_stats()
        if ok:  # on failure last_query_metrics is still the prior query's
            m = self.last_query_metrics
            m["sessionId"] = self.session_id
            if self.tenant:
                m["tenant"] = self.tenant
            for src, dst in (("hits", "kernelCacheHits"),
                             ("misses", "kernelCacheMisses"),
                             ("compiles", "kernelCompiles"),
                             ("compile_ms", "kernelCompileMs"),
                             # total compiled-program launches this query
                             # (whole-stage dispatch evidence)
                             ("dispatches", "deviceDispatches")):
                m[dst] = round(cs1[src] - cache_stats0[src], 3)
            # resilience counters: faults injected, fetch retries, lost
            # blocks recomputed, peers blacklisted — per-query deltas of
            # the process-wide robustness stats
            rob1 = stats_snapshot()
            for k, v0 in rob0.items():
                m[k] = rob1[k] - v0
            # encoded-execution / prepack / device-decode engagement
            # deltas (decode counters only when scans actually ran, so
            # in-memory queries don't carry two dozen zero keys)
            if aux0 is not None:
                aux1 = _aux_stats_snapshot()
                for k, v0 in aux0.items():
                    d = aux1.get(k, v0) - v0
                    if d or not k.endswith(
                            ("Engaged", "Declined")):
                        m[k] = d
        if not tracing:
            self.last_query_trace_summary = None
            # an older traced query's events must not be joined with THIS
            # query's plan by profile_last_query/export_chrome_trace
            self._last_trace_events = None
        else:
            from ..observability import report as OR
            from ..observability import tracer as OT
            tr = OT.get_tracer()
            self._last_trace_events = tr.snapshot()
            self._last_trace_meta = dict(tr.meta(), query=self._query_seq)
            self.last_query_trace_summary = OR.trace_summary(
                self._last_trace_events, tr.counters, tr.dropped_events)
            if ok:
                # a truncated ring can never silently skew doctor
                # attribution: the drop count and how full the ring got
                # ride every traced query's metrics
                self.last_query_metrics["traceDroppedEvents"] = \
                    tr.dropped_events
                self.last_query_metrics["traceRingHighWater"] = \
                    tr.high_water
            if sink and sink != "memory":
                from ..observability import export as OE
                try:
                    OE.write_event_log(
                        OE.event_log_path(sink, self._query_seq),
                        self._last_trace_events, self._last_trace_meta)
                except OSError:  # the sink must never fail the query
                    pass
        self._record_history(ok, duration_s, err)
        if metrics_on:
            from ..observability import metrics as OM
            status = "ok" if ok else "failed"
            OM.get_registry().observe("query_ms", duration_s * 1e3,
                                      status=status)
            OM.get_registry().inc("queries_total", status=status)

    def _record_history(self, ok: bool, duration_s: float,
                        err: Optional[BaseException]) -> None:
        """Land one flight-recorder record (must never fail the query)."""
        from ..config import HISTORY_ENABLED, HISTORY_MAX_QUERIES, \
            HISTORY_PATH
        if not bool(self._conf.get(HISTORY_ENABLED)):
            return
        try:
            from ..observability import history as OH
            if self._history is None:
                # shared per path: concurrent sessions configured with
                # one JSONL ring serialize their appends through a
                # single process-wide instance (docs/serving.md)
                self._history = OH.shared_history(
                    int(self._conf.get(HISTORY_MAX_QUERIES)),
                    str(self._conf.get(HISTORY_PATH) or ""))
            self._history.record(OH.build_record(
                query_id=self._query_seq, session_id=self.session_id,
                ok=ok, duration_ms=duration_s * 1e3,
                phys=getattr(self, "_last_phys", None) if ok else None,
                metrics=self.last_query_metrics if ok else None,
                trace_summary=self.last_query_trace_summary,
                error=f"{type(err).__name__}: {err}" if err else None,
                tenant=self.tenant))
        except Exception:
            pass

    def _execute_traced(self, logical: P.LogicalPlan, device_to_arrow,
                        speculation, conf: Optional[RapidsConf] = None
                        ) -> pa.Table:
        # conf defaults to the session's; the serving path passes a
        # pressure-degraded copy (lifecycle.PressureSignal) so a
        # saturated engine plans smaller without mutating session state
        conf = conf or self._conf
        planner = Planner(conf)
        phys = planner.plan_for_collect(logical)
        # collect has no side effects, so speculative results may be
        # validated AFTER the fetch (zero extra pulls); a mis-speculation
        # recorded the corrected group-table size — re-plan and re-run.
        # Deferral is THREAD-local: under the pipelined execution layer
        # (task.parallelism > 1 / prefetch producer threads) work running
        # off this thread sees deferral OFF and takes the exact paths, so
        # the drain below only ever validates driver-thread speculation —
        # correctness never depends on cross-thread check handoff
        # (docs/async_pipeline.md).
        speculation.clear()
        try:
            oom_retried = False
            attempt = 0
            while True:
                # final attempt runs exact (deferral off) so the loop
                # always terminates with a validated result
                speculation.set_deferral(attempt < 2)
                try:
                    batches = phys.execute_all(conf)
                except Exception as e:
                    # with syncMode=auto a deferred execution-time OOM can
                    # surface at the D2H fetch, where the kernel guard
                    # cannot re-run the producing kernel.  Recovery is a
                    # whole-query retry: the guard already entered its
                    # defensive window (eager per-kernel sync), so the
                    # re-run lands any OOM inside the failing kernel's
                    # own spill-and-retry protocol.
                    from ..memory.oom_guard import is_device_oom
                    from ..memory.retry import RetryOOM, SplitAndRetryOOM
                    retriable = isinstance(e, (RetryOOM, SplitAndRetryOOM)) \
                        or is_device_oom(e)
                    if not retriable or oom_retried:
                        raise
                    oom_retried = True
                    from ..memory.spill import BufferCatalog
                    BufferCatalog.get().spill_all_device()
                    speculation.clear()
                    phys = planner.plan_for_collect(logical)
                    continue
                checks = speculation.drain()
                bad = [c for c in checks if c.failed]
                if not bad or attempt >= 2:
                    break
                attempt += 1
                speculation._bump("mis_speculations", len(bad))
                speculation._bump("reruns")
                phys = planner.plan_for_collect(logical)
        finally:
            speculation.set_deferral(False)
        from .physical.base import collect_metrics
        self.last_query_metrics = collect_metrics(phys)
        self._last_phys = phys
        tables = [device_to_arrow(b) for b in batches if b.num_rows_int > 0]
        arrow_schema = pa.schema([
            pa.field(a.name, T.to_arrow(a.dtype)) for a in logical.output])
        if not tables:
            return arrow_schema.empty_table()
        out = pa.concat_tables([t.cast(arrow_schema) for t in tables])
        return out

    def physical_plan(self, df: DataFrame):
        return Planner(self._conf).plan_for_collect(df._plan)

    def profile_last_query(self) -> str:
        """Per-exec wall-time/batch profile of the most recent collect
        (requires spark.rapids.tpu.profile.enabled during execution).
        With the tracer on (profile.enabled implies it), the report also
        attributes blocking sync/readback time, kernel trace+compile
        time, and H2D/D2H bytes to each exec node."""
        phys = getattr(self, "_last_phys", None)
        if phys is None:
            return "no query executed yet"
        events = getattr(self, "_last_trace_events", None)
        if events:
            from ..observability.report import attribution_table
            meta = getattr(self, "_last_trace_meta", {})
            return attribution_table(phys, events,
                                     int(meta.get("dropped_events", 0)))
        from .physical.base import profile_report
        return profile_report(phys)

    def export_chrome_trace(self, path: str) -> str:
        """Write the last traced query's timeline as Chrome trace-event
        JSON (load in Perfetto / chrome://tracing).  Requires the query to
        have run with spark.rapids.tpu.trace.sink or profile.enabled."""
        events = getattr(self, "_last_trace_events", None)
        if not events:
            raise RuntimeError(
                "no traced query: set spark.rapids.tpu.trace.sink "
                "(or spark.rapids.tpu.profile.enabled) before collect()")
        from ..observability.export import write_chrome_trace
        return write_chrome_trace(path, events,
                                  getattr(self, "_last_trace_meta", None))

    def query_history(self, n: Optional[int] = None) -> List[dict]:
        """Flight-recorder records for this session's queries, oldest
        first (``spark.rapids.tpu.history.enabled``); ``n`` bounds the
        result to the newest n.  The ring may be SHARED (on-disk path /
        serving engine) — filtering by this session's id keeps the view
        per-session either way."""
        if self._history is None:
            return []
        return self._history.tail(n, session=self.session_id)

    def metrics_snapshot(self) -> dict:
        """JSON snapshot of the process-wide metrics registry (series
        recorded while ``spark.rapids.tpu.metrics.enabled`` queries
        ran) — counters, gauges, histograms with p50/p95/p99."""
        from ..observability.metrics import get_registry
        return get_registry().json_snapshot()

    def metrics_prometheus(self) -> str:
        """The metrics registry in Prometheus exposition text format."""
        from ..observability.metrics import get_registry
        return get_registry().prometheus_text()

    def diagnose_last_query(self) -> dict:
        """Ranked bottleneck diagnosis of the most recent traced query
        (observability/doctor.py): named verdict + supporting exec-level
        spans and counters.  Requires the query to have run with
        spark.rapids.tpu.trace.sink or profile.enabled."""
        events = getattr(self, "_last_trace_events", None)
        if not events:
            raise RuntimeError(
                "no traced query: set spark.rapids.tpu.trace.sink "
                "(or spark.rapids.tpu.profile.enabled) before collect()")
        from ..observability import doctor as OD
        meta = getattr(self, "_last_trace_meta", {})
        hist = self.query_history(1)
        wall = hist[-1]["duration_ms"] if hist else None
        return OD.diagnose(events, counters=meta.get("counters"),
                           metrics=self.last_query_metrics,
                           wall_ms=wall,
                           dropped_events=int(
                               meta.get("dropped_events", 0)))

    # --- telemetry plane (observability/server.py) --------------------
    def _start_telemetry(self) -> None:
        from ..config import TELEMETRY_PORT
        from ..observability import slo as OSLO
        from ..observability.server import TelemetryServer
        tracker = OSLO.configure(self._conf)
        self.telemetry = TelemetryServer(
            metrics_text=self.metrics_prometheus,
            healthz=self._telemetry_healthz,
            queries=self.query_history,
            doctor=self._telemetry_doctor,
            slo=lambda: tracker.report(),
            port=int(self._conf.get(TELEMETRY_PORT)))

    def close_telemetry(self) -> None:
        """Stop this session's embedded telemetry server (no-op when it
        never started); leak-free — the serve thread joins and the port
        rebinds."""
        if self.telemetry is not None:
            self.telemetry.close()
            self.telemetry = None

    def _telemetry_healthz(self):
        """(healthy, payload) for a classic session: no engine, so no
        degraded state — liveness plus semaphore saturation."""
        from ..memory.semaphore import TpuSemaphore
        sem = TpuSemaphore.get()
        active = sem.active_tasks()
        return True, {
            "status": "ok", "session": self.session_id,
            "semaphore": {"active": active, "permits": sem.permits,
                          "saturation": round(
                              active / max(1, sem.permits), 4)},
        }

    def _telemetry_doctor(self):
        from ..observability import doctor as OD
        try:
            return {"last": OD.LAST_VERDICT,
                    "query": self.diagnose_last_query()}
        except RuntimeError as e:
            return {"last": OD.LAST_VERDICT, "note": str(e)}

    def explain(self, df: DataFrame, all_ops: bool = True) -> str:
        """Placement report (spark.rapids.sql.explain=ALL equivalent) plus
        the physical tree."""
        from .overrides import TpuOverrides
        meta = TpuOverrides.apply(df._plan, self._conf)
        from ..config import OPTIMIZER_ENABLED
        if bool(self._conf.get(OPTIMIZER_ENABLED)):
            # keep the placement report consistent with the physical plan
            from .optimizer import apply_cost_optimizer
            apply_cost_optimizer(meta, self._conf)
        try:
            phys_str = Planner(self._conf).plan_for_collect(
                df._plan).tree_string()
        except NotImplementedError as e:
            # diagnostics must not crash on unplannable queries (e.g.
            # unsupported DISTINCT shapes) — report the reason instead
            phys_str = f"<unplannable: {e}>"
        return (meta.explain(all_ops) + "\n\nPhysical plan:\n"
                + phys_str)


class DataFrameReader:
    def __init__(self, session: TpuSession):
        self._session = session
        self._options: Dict[str, Any] = {}
        self._schema: Optional[T.StructType] = None

    def option(self, key: str, value) -> "DataFrameReader":
        self._options[key] = value
        return self

    def options(self, **kwargs) -> "DataFrameReader":
        self._options.update(kwargs)
        return self

    def schema(self, s: T.StructType) -> "DataFrameReader":
        self._schema = s
        return self

    def _scan(self, fmt: str, paths) -> DataFrame:
        if isinstance(paths, str):
            paths = [paths]
        rel = P.ScanRelation(fmt, tuple(paths), self._schema,
                             dict(self._options))
        return DataFrame(rel, self._session)

    def parquet(self, *paths) -> DataFrame:
        return self._scan("parquet", list(paths))

    def orc(self, *paths) -> DataFrame:
        return self._scan("orc", list(paths))

    def csv(self, *paths) -> DataFrame:
        return self._scan("csv", list(paths))

    def json(self, *paths) -> DataFrame:
        return self._scan("json", list(paths))

    def avro(self, *paths) -> DataFrame:
        return self._scan("avro", list(paths))

    def format(self, fmt: str):
        reader = self

        class _F:
            def option(self_inner, key, value):
                reader._options[key] = value
                return self_inner

            def load(self_inner, *paths):
                if fmt == "delta":
                    from ..delta import DeltaTable
                    version = reader._options.get("versionAsOf")
                    ts = reader._options.get("timestampAsOf")
                    dt = DeltaTable.forPath(reader._session, paths[0])
                    return dt.toDF(
                        int(version) if version is not None else None,
                        timestamp_ms=_parse_ts_ms(ts, reader._session)
                        if ts is not None else None)
                if fmt == "iceberg":
                    from ..iceberg import IcebergTable
                    it = IcebergTable.for_path(reader._session, paths[0])
                    snap = reader._options.get("snapshot-id")
                    ts = reader._options.get("as-of-timestamp")
                    return it.to_df(
                        snapshot_id=int(snap) if snap is not None else None,
                        as_of_timestamp_ms=int(ts) if ts is not None
                        else None)
                return reader._scan(fmt, list(paths))
        return _F()


def _parse_ts_ms(ts, session=None) -> int:
    """timestampAsOf accepts epoch millis or 'YYYY-MM-DD[ HH:MM:SS]'
    strings.  Date strings parse in the SESSION timezone like Spark
    (spark.sql.session.timeZone), not hardcoded UTC."""
    if isinstance(ts, (int, float)):
        return int(ts)
    import datetime as _dt
    s = str(ts).strip()
    try:
        return int(s)
    except ValueError:
        pass
    tz = _dt.timezone.utc
    if session is not None:
        from ..config import SESSION_TIMEZONE
        name = str(session._conf.get(SESSION_TIMEZONE))
        if name and name.upper() != "UTC":
            from zoneinfo import ZoneInfo
            tz = ZoneInfo(name)
    for fmt in ("%Y-%m-%d %H:%M:%S", "%Y-%m-%d"):
        try:
            d = _dt.datetime.strptime(s, fmt)
            return int(d.replace(tzinfo=tz).timestamp() * 1000)
        except ValueError:
            continue
    raise ValueError(f"cannot parse timestampAsOf value {ts!r}")


def _to_arrow_table(data, schema) -> pa.Table:
    if isinstance(data, pa.Table):
        return data
    if isinstance(data, dict):
        return pa.table(data)
    try:
        import pandas as pd
        if isinstance(data, pd.DataFrame):
            return pa.Table.from_pandas(data, preserve_index=False)
    except ImportError:  # pragma: no cover
        pass
    if isinstance(data, list):
        if schema is None:
            raise ValueError("schema required for list-of-rows input")
        if isinstance(schema, str):
            # DDL string 'name type, name type' (pyspark createDataFrame)
            from .dataframe import _to_struct_type
            schema = _to_struct_type(schema)
        if isinstance(schema, (list, tuple)):
            names = list(schema)
            cols = list(zip(*data)) if data else [[] for _ in names]
            return pa.table({n: list(c) for n, c in zip(names, cols)})
        arrow_schema = pa.schema([
            pa.field(f.name, T.to_arrow(f.data_type), f.nullable)
            for f in schema.fields])
        cols = list(zip(*data)) if data else [[] for _ in schema.fields]
        arrays = [pa.array(list(c), type=fldt.type)
                  for c, fldt in zip(cols, arrow_schema)]
        return pa.Table.from_arrays(arrays, schema=arrow_schema)
    raise TypeError(f"cannot create DataFrame from {type(data)}")


def _split_table(table: pa.Table, n: int) -> List[pa.Table]:
    n = max(1, n)
    rows = table.num_rows
    per = -(-rows // n) if rows else 0
    parts = []
    for i in range(n):
        lo = min(i * per, rows)
        hi = min(lo + per, rows)
        parts.append(table.slice(lo, hi - lo))
    return parts


#: (id(table) -> (weakref(table), {n: [slices]})) — slice identity dedupe
#: (see create_dataframe).  Entries die with their table; slices are
#: zero-copy views, so retaining them costs metadata only.
_SPLIT_CACHE: dict = {}
_SPLIT_LOCK = threading.Lock()


def _split_table_cached(table: pa.Table, n: int) -> List[pa.Table]:
    import weakref
    key = id(table)
    with _SPLIT_LOCK:
        ent = _SPLIT_CACHE.get(key)
        if ent is None or ent[0]() is not table:
            ref = weakref.ref(
                table, lambda _r, k=key: _SPLIT_CACHE.pop(k, None))
            ent = (ref, {})
            _SPLIT_CACHE[key] = ent
        parts = ent[1].get(n)
        if parts is None:
            parts = ent[1][n] = _split_table(table, n)
        return parts


class Catalog:
    """Minimal pyspark-Catalog surface over the session's temp views."""

    def __init__(self, session: TpuSession):
        self._session = session

    def listTables(self) -> List[str]:
        return sorted(self._session._temp_views)

    def tableExists(self, name: str) -> bool:
        return name.lower() in self._session._temp_views

    def dropTempView(self, name: str) -> bool:
        return self._session._temp_views.pop(name.lower(), None) is not None
