"""SQL front-end: text -> logical plans over the existing algebra.

The reference accelerates Spark SQL transparently — every query surface
(``spark.sql(...)``, ``df.filter("a > 1")``, ``selectExpr``) is SQL text
compiled by Catalyst before the plugin ever sees a physical plan
(SURVEY §1 user-visible API; ``Plugin.scala:46-53`` hooks run *after* SQL
parsing).  Standalone, we own that parsing step too: this module is the
Catalyst-parser equivalent, a recursive-descent SQL parser producing the
same ``Column``/``LogicalPlan`` objects the DataFrame API builds, so SQL
text and DataFrame calls share one planning/execution path.

Scope: SELECT [DISTINCT] with expressions/functions/CASE/CAST/window
functions, FROM with joins (INNER/LEFT/RIGHT/FULL/SEMI/ANTI/CROSS, ON and
USING), WHERE, GROUP BY (exprs/ordinals/aliases), HAVING, ORDER BY
(exprs/ordinals/aliases, ASC/DESC, NULLS FIRST/LAST), LIMIT/OFFSET,
UNION [ALL]/EXCEPT/INTERSECT, WITH ctes, subqueries in FROM, temp views,
and direct file relations (``parquet.`/path/to/file```).
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import types as T
from .expressions.aggregates import AggregateExpression, AggregateFunction
from .expressions.core import Alias, AttributeReference, Expression, Literal
from .expressions.windows import (CURRENT_ROW, UNBOUNDED_FOLLOWING,
                                  UNBOUNDED_PRECEDING, WindowFrame,
                                  WindowSpecDefinition, WindowExpression,
                                  WindowFunction)
from .plan import SortOrder


# --------------------------------------------------------------------------
# Lexer
# --------------------------------------------------------------------------

_TOKEN_RE = re.compile(r"""
    (?P<ws>\s+|--[^\n]*)
  | (?P<num>(?:\d+\.\d*|\.\d+|\d+)(?:[eE][+-]?\d+)?[dDlLfF]?)
  | (?P<str>'(?:[^'\\]|\\[\s\S]|'')*')
  | (?P<qident>`[^`]*`|"[^"]*")
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<op><=>|==|!=|<>|<=|>=|\|\||<<|>>>|>>|[-+*/%(),.<>=&|^~])
""", re.VERBOSE)


@dataclass
class Tok:
    kind: str   # num|str|ident|qident|op|eof
    text: str
    pos: int

    @property
    def upper(self) -> str:
        return self.text.upper()


def tokenize(sql: str) -> List[Tok]:
    out: List[Tok] = []
    i = 0
    while i < len(sql):
        m = _TOKEN_RE.match(sql, i)
        if m is None:
            raise SqlParseError(f"unexpected character {sql[i]!r} at {i} in {sql!r}")
        i = m.end()
        kind = m.lastgroup
        if kind == "ws":
            continue
        out.append(Tok(kind, m.group(), m.start()))
    out.append(Tok("eof", "", len(sql)))
    return out


class SqlParseError(ValueError):
    pass


import itertools as _it

#: distinct seeds for non-REPEATABLE TABLESAMPLEs
_SAMPLE_SEEDS = _it.count(0x5EED)


def unescape_sql_string(body: str) -> str:
    """Spark's default string-literal semantics (``unescapeSQLString``,
    ``spark.sql.parser.escapedStringLiterals=false``): backslash escapes
    are processed ('\\\\d' is a 2-char regex escape, '\\n' a newline),
    '' is a quote, \\% and \\_ KEEP their backslash (LIKE escapes), an
    unknown escaped char is the char itself, plus \\uXXXX and 3-digit
    octal forms."""
    out = []
    i = 0
    n = len(body)
    mapped = {"0": "\0", "b": "\b", "n": "\n", "r": "\r", "t": "\t",
              "Z": "\x1a", "\\": "\\", "'": "'", '"': '"'}
    while i < n:
        c = body[i]
        if c == "'" and i + 1 < n and body[i + 1] == "'":
            out.append("'")
            i += 2
            continue
        if c == "\\" and i + 1 < n:
            nx = body[i + 1]
            # 3-digit octal BEFORE the single-char map: '\012' is a
            # newline, not NUL + "12" (Spark checks octal first too)
            oct3 = body[i + 1:i + 4]
            if (len(oct3) == 3 and nx in "0123"
                    and all(ch in "01234567" for ch in oct3)):
                out.append(chr(int(oct3, 8)))
                i += 4
                continue
            if nx in mapped:
                out.append(mapped[nx])
                i += 2
                continue
            if nx in "%_":
                out.append("\\" + nx)
                i += 2
                continue
            hex4 = body[i + 2:i + 6]
            if (nx == "u" and len(hex4) == 4
                    and all(ch in "0123456789abcdefABCDEF"
                            for ch in hex4)):
                out.append(chr(int(hex4, 16)))
                i += 6
                continue
            out.append(nx)
            i += 2
            continue
        out.append(c)
        i += 1
    return "".join(out)


# --------------------------------------------------------------------------
# AST for statements (expressions become live Expression trees immediately)
# --------------------------------------------------------------------------

@dataclass
class Star:
    qualifier: Optional[str] = None


@dataclass
class SelectItem:
    expr: Any           # Expression | Star
    alias: Optional[str] = None


@dataclass
class TableRef:
    name: str                       # view/table name, or format for files
    alias: Optional[str] = None
    path: Optional[str] = None      # direct file relation
    sample: Optional[tuple] = None  # ("percent"|"rows", value, seed)


@dataclass
class SubqueryRef:
    stmt: "Any"
    alias: Optional[str] = None
    sample: Optional[tuple] = None  # ("percent"|"rows", value, seed)


@dataclass
class JoinStep:
    how: str
    right: Any                      # TableRef | SubqueryRef
    on: Optional[Expression] = None
    using: Optional[List[str]] = None


@dataclass
class OrderItem:
    expr: Any                       # Expression | int (ordinal)
    ascending: bool = True
    nulls_first: Optional[bool] = None


@dataclass
class LateralView:
    outer: bool
    func: str
    arg: "Any"
    table_alias: str
    col_aliases: List[str]


@dataclass
class SelectStmt:
    items: List[SelectItem] = field(default_factory=list)
    distinct: bool = False
    from_: Optional[Any] = None     # TableRef | SubqueryRef
    joins: List[JoinStep] = field(default_factory=list)
    where: Optional[Expression] = None
    group_by: List[Any] = field(default_factory=list)   # Expression | int
    group_by_mode: Optional[str] = None           # None|rollup|cube|sets
    grouping_sets_raw: List[List[Any]] = field(default_factory=list)
    having: Optional[Expression] = None
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: Dict[str, "Any"] = field(default_factory=dict)
    #: Hive-style LATERAL VIEW [OUTER] explode(...) alias AS cols —
    #: applied after the FROM/JOIN chain (the common placement)
    lateral_views: List[LateralView] = field(default_factory=list)


@dataclass
class SetOpStmt:
    op: str                         # union|except|intersect
    all: bool
    left: Any
    right: Any
    order_by: List[OrderItem] = field(default_factory=list)
    limit: Optional[int] = None
    offset: Optional[int] = None
    ctes: Dict[str, "Any"] = field(default_factory=dict)


class ExistsSubquery(Expression):
    """WHERE EXISTS (SELECT ...) marker — rewritten by the builder into a
    LEFT SEMI join (NOT EXISTS -> LEFT ANTI), the same lowering Spark's
    RewritePredicateSubquery performs before the reference plugin sees the
    plan (semi/anti joins then run on GpuHashJoin)."""

    children: Tuple[Expression, ...] = ()
    _unresolved = True  # must never reach resolution/execution

    def __init__(self, stmt):
        self.stmt = stmt

    @property
    def data_type(self):
        return T.BOOLEAN

    def sql(self) -> str:
        return "EXISTS(<subquery>)"

    def with_children(self, children):
        return self

    def _key_extras(self):
        return (id(self.stmt),)


class _InnerUnit(Expression):
    """Placeholder for a maximal inner-only subexpression lifted out of a
    mixed correlated EXISTS conjunct (projected as __nq{idx} from the
    subquery and substituted back into the join's residual condition)."""

    children: Tuple[Expression, ...] = ()
    _unresolved = True

    def __init__(self, idx: int):
        self.idx = idx

    @property
    def data_type(self):
        raise TypeError("_InnerUnit must be substituted before typing")

    def sql(self) -> str:
        return f"<inner:{self.idx}>"

    def with_children(self, children):
        return self

    def _key_extras(self):
        return (self.idx,)


class InSubquery(Expression):
    """``expr IN (SELECT ...)`` marker — LEFT SEMI join on equality;
    NOT IN is the null-aware LEFT ANTI form (SQL 3-valued logic: a null
    needle or any null in the subquery result filters the row)."""

    _unresolved = True

    def __init__(self, needle: Expression, stmt):
        self.children = (needle,)
        self.stmt = stmt

    @property
    def data_type(self):
        return T.BOOLEAN

    def sql(self) -> str:
        return f"{self.children[0].sql()} IN (<subquery>)"

    def with_children(self, children):
        return InSubquery(children[0], self.stmt)

    def _key_extras(self):
        return (id(self.stmt),)


class ScalarSubquery(Expression):
    """Uncorrelated ``(SELECT <one value>)`` in an expression position —
    evaluated once at plan-build time into a Literal (the subquery result
    is a single value by definition; Spark's ReuseSubquery evaluates it
    once per query too, just lazily)."""

    children: Tuple[Expression, ...] = ()
    _unresolved = True

    def __init__(self, stmt):
        self.stmt = stmt

    @property
    def data_type(self):
        raise SqlParseError(
            "scalar subquery leaked past build-time evaluation")

    def sql(self) -> str:
        return "(<scalar subquery>)"

    def with_children(self, children):
        return self

    def _key_extras(self):
        return (id(self.stmt),)


class IntervalLiteral(Expression):
    """Parse-time ``INTERVAL 'n' unit`` value — only valid next to +/-
    with a date/timestamp, where _additive folds it into DateAddInterval/
    TimeAdd (the reference's GpuDateAddInterval/GpuTimeAdd literal
    restriction)."""

    children: Tuple[Expression, ...] = ()
    _unresolved = True

    def __init__(self, months: int, days: int, micros: int):
        self.months, self.days, self.micros = months, days, micros

    @property
    def data_type(self):
        raise SqlParseError(
            "INTERVAL literals are only valid in date/timestamp +/- "
            "arithmetic")

    def sql(self) -> str:
        return f"INTERVAL({self.months}mo {self.days}d {self.micros}us)"

    def with_children(self, children):
        return self

    def _key_extras(self):
        return (self.months, self.days, self.micros)


_INTERVAL_UNITS = {
    "year": (12, 0, 0), "years": (12, 0, 0),
    "month": (1, 0, 0), "months": (1, 0, 0),
    "week": (0, 7, 0), "weeks": (0, 7, 0),
    "day": (0, 1, 0), "days": (0, 1, 0),
    "hour": (0, 0, 3_600_000_000), "hours": (0, 0, 3_600_000_000),
    "minute": (0, 0, 60_000_000), "minutes": (0, 0, 60_000_000),
    "second": (0, 0, 1_000_000), "seconds": (0, 0, 1_000_000),
}


class UnresolvedQualified(Expression):
    """``t.a`` — bound to the aliased relation's attribute by the builder.
    Never reaches execution; data_type raises to catch leaks.  Marked
    ``_unresolved`` so the analyzer-lite coercion defers until binding
    (outside session.sql, ``_resolve_expr`` falls back to by-name
    resolution, pyspark ``expr("t.a")`` style)."""

    children: Tuple[Expression, ...] = ()
    _unresolved = True

    def __init__(self, qualifier: str, name: str):
        self.qualifier = qualifier
        self.name = name

    @property
    def data_type(self):
        raise SqlParseError(
            f"unresolved qualified reference {self.qualifier}.{self.name} "
            "(qualified names are only valid inside session.sql queries)")

    def sql(self) -> str:
        return f"{self.qualifier}.{self.name}"

    def with_children(self, children):
        return self

    def _key_extras(self):
        return (self.qualifier, self.name)


# --------------------------------------------------------------------------
# Function registry: SQL name -> callable over Columns
# --------------------------------------------------------------------------

#: public helpers in functions.py that are NOT SQL functions (constructors,
#: decorators, sort helpers) — calling them with SQL args would crash with
#: confusing internal errors instead of "unknown SQL function"
_NON_SQL_FUNCTIONS = {
    "col", "column", "lit", "expr", "expr_fn", "when", "udf", "pandas_udf",
    "device_udf", "broadcast", "asc", "desc", "window",
}


def _function_table():
    from . import functions as F
    tbl: Dict[str, Any] = {}
    for name in dir(F):
        if name.startswith("_") or name in _NON_SQL_FUNCTIONS:
            continue
        fn = getattr(F, name)
        # only functions DEFINED in functions.py — dir() also surfaces its
        # imports (e.g. typing.Optional), which are not SQL functions
        if callable(fn) and not isinstance(fn, type) and \
                getattr(fn, "__module__", None) == F.__name__:
            tbl[name.lower()] = fn
    # SQL spellings that differ from the pyspark function names
    alias = {
        "power": "pow", "ceiling": "ceil", "ln": "log", "ucase": "upper",
        "lcase": "lower", "char_length": "length",
        "character_length": "length", "sign": "signum",
        "day": "dayofmonth", "position": "locate", "ifnull": "nvl",
        "regexp_like": "rlike", "std": "stddev",
        "approx_percentile": "percentile_approx",
        "array_agg": "collect_list",
    }
    for sql_name, py_name in alias.items():
        fn = tbl.get(py_name.lower())
        if fn is not None:
            tbl[sql_name] = fn
    return tbl


#: argument positions that are plain python values in the pyspark function
#: signatures (format strings, pad chars, counts...) — a parsed Literal in
#: one of these positions is unwrapped to its raw value before the call.
_LITERAL_POS: Dict[str, set] = {
    "substring_index": {1, 2}, "instr": {1}, "translate": {1, 2},
    "repeat": {1}, "lpad": {1, 2}, "rpad": {1, 2}, "trim": {1},
    "ltrim": {1}, "rtrim": {1}, "format_number": {1}, "conv": {1, 2},
    "round": {1}, "bround": {1}, "shiftleft": {1}, "shiftright": {1},
    "shiftrightunsigned": {1}, "rlike": {1}, "regexp_like": {1},
    "regexp_replace": {1, 2}, "regexp_extract": {1, 2},
    "regexp_extract_all": {1, 2}, "split": {1, 2}, "str_to_map": {1, 2},
    "get_json_object": {1}, "json_tuple": {1, 2, 3, 4, 5, 6, 7, 8},
    "date_format": {1}, "trunc": {1}, "from_unixtime": {1},
    "unix_timestamp": {1}, "to_unix_timestamp": {1}, "to_timestamp": {1},
    "months_between": {2}, "from_utc_timestamp": {1}, "lead": {1, 2},
    "lag": {1, 2}, "nth_value": {1, 2}, "ntile": {0}, "first": {1},
    "last": {1}, "sort_array": {1}, "like": {1, 2},
    "locate": {0, 2}, "position": {0, 2}, "concat_ws": {0},
    "slice": {1, 2}, "percentile_approx": {1, 2},
    "approx_count_distinct": {1},
}


_FN_TABLE = None


def _functions():
    global _FN_TABLE
    if _FN_TABLE is None:
        _FN_TABLE = _function_table()
    return _FN_TABLE


def _parse_type_tokens(p: "Parser") -> T.DataType:
    name = p.expect_ident().lower()
    if name in ("decimal", "dec", "numeric"):
        prec, scale = 10, 0
        if p.accept_op("("):
            prec = p.expect_int()
            if p.accept_op(","):
                scale = p.expect_int()
            p.expect_op(")")
        return T.DecimalType(prec, scale)
    from .dataframe import _parse_type
    return _parse_type(name)


# --------------------------------------------------------------------------
# Parser
# --------------------------------------------------------------------------

_RESERVED_STOP = {
    "FROM", "WHERE", "GROUP", "HAVING", "ORDER", "LIMIT", "OFFSET", "UNION",
    "EXCEPT", "INTERSECT", "MINUS", "JOIN", "INNER", "LEFT", "RIGHT", "FULL",
    "CROSS", "ON", "USING", "AS", "WHEN", "THEN", "ELSE", "END", "AND", "OR",
    "NOT", "IS", "IN", "BETWEEN", "LIKE", "RLIKE", "ASC", "DESC", "NULLS",
    "BY", "SELECT", "DISTINCT", "ALL", "WITH", "OVER", "PARTITION", "ROWS",
    "RANGE", "PRECEDING", "FOLLOWING", "CURRENT", "UNBOUNDED", "SEMI", "ANTI",
    "LATERAL", "TABLESAMPLE",
}


class Parser:
    def __init__(self, sql: str, udfs: Optional[Dict[str, Any]] = None):
        self.sql = sql
        self.toks = tokenize(sql)
        self.i = 0
        #: session-registered Hive UDFs (name -> impl); consulted before
        #: the builtin function table in _call
        self.udfs = udfs or {}

    # --- token helpers ----------------------------------------------------
    def peek(self, ahead: int = 0) -> Tok:
        return self.toks[min(self.i + ahead, len(self.toks) - 1)]

    def next(self) -> Tok:
        t = self.toks[self.i]
        if t.kind != "eof":
            self.i += 1
        return t

    def at_kw(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "ident" and t.upper in kws

    def accept_kw(self, *kws: str) -> bool:
        if self.at_kw(*kws):
            self.next()
            return True
        return False

    def expect_kw(self, kw: str) -> None:
        if not self.accept_kw(kw):
            raise SqlParseError(
                f"expected {kw} at {self.peek().pos} in {self.sql!r}, "
                f"got {self.peek().text!r}")

    def at_op(self, *ops: str) -> bool:
        t = self.peek()
        return t.kind == "op" and t.text in ops

    def accept_op(self, *ops: str) -> bool:
        if self.at_op(*ops):
            self.next()
            return True
        return False

    def expect_op(self, op: str) -> None:
        if not self.accept_op(op):
            raise SqlParseError(
                f"expected {op!r} at {self.peek().pos} in {self.sql!r}, "
                f"got {self.peek().text!r}")

    def expect_kind(self, kind: str) -> Tok:
        t = self.peek()
        if t.kind != kind:
            raise SqlParseError(
                f"expected {kind} at {t.pos} in {self.sql!r}, got {t.text!r}")
        return self.next()

    def expect_ident(self) -> str:
        t = self.peek()
        if t.kind == "ident":
            return self.next().text
        if t.kind == "qident":
            return self.next().text[1:-1]
        raise SqlParseError(
            f"expected identifier at {t.pos} in {self.sql!r}, got {t.text!r}")

    def expect_int(self) -> int:
        t = self.expect_kind("num")
        if not t.text.isdigit():
            raise SqlParseError(
                f"expected an integer at {t.pos} in {self.sql!r}, "
                f"got {t.text!r}")
        return int(t.text)

    # --- expressions ------------------------------------------------------
    def parse_expression(self) -> Expression:
        return self._or()

    def _or(self) -> Expression:
        from .expressions.predicates import Or
        e = self._and()
        while self.accept_kw("OR"):
            e = Or(e, self._and())
        return e

    def _and(self) -> Expression:
        from .expressions.predicates import And
        e = self._not()
        while self.accept_kw("AND"):
            e = And(e, self._not())
        return e

    def _not(self) -> Expression:
        from .expressions.predicates import Not
        if self.accept_kw("NOT"):
            return Not(self._not())
        return self._predicate()

    def _predicate(self) -> Expression:
        from .expressions import predicates as PR
        from .expressions import strings as STR
        from .expressions import regexp as RXE
        e = self._comparison()
        while True:
            negate = False
            save = self.i
            if self.accept_kw("NOT"):
                negate = True
            if self.accept_kw("BETWEEN"):
                lo = self._comparison()
                self.expect_kw("AND")
                hi = self._comparison()
                e2 = PR.And(self._cmp(PR.GreaterThanOrEqual, e, lo),
                            self._cmp(PR.LessThanOrEqual, e, hi))
            elif self.accept_kw("IN"):
                self.expect_op("(")
                if self.at_kw("SELECT"):
                    q = self._query_term({})
                    self.expect_op(")")
                    e2 = InSubquery(e, q)
                else:
                    vals = [self.parse_expression()]
                    while self.accept_op(","):
                        vals.append(self.parse_expression())
                    self.expect_op(")")
                    e2 = PR.In(e, tuple(vals))
            elif self.accept_kw("LIKE"):
                pat = self._comparison()
                if not isinstance(pat, Literal):
                    raise SqlParseError("LIKE pattern must be a literal")
                e2 = STR.Like(e, pat)
            elif self.accept_kw("RLIKE", "REGEXP"):
                pat = self._comparison()
                if not isinstance(pat, Literal):
                    raise SqlParseError("RLIKE pattern must be a literal")
                e2 = RXE.RLike(e, pat.value)
            elif self.accept_kw("IS"):
                neg2 = self.accept_kw("NOT")
                if self.accept_kw("NULL"):
                    e2 = PR.IsNull(e)
                elif self.accept_kw("DISTINCT"):
                    self.expect_kw("FROM")
                    rhs = self._comparison()
                    e2 = PR.Not(PR.EqualNullSafe(e, rhs))
                elif self.accept_kw("TRUE"):
                    e2 = PR.EqualNullSafe(e, Literal(True))
                elif self.accept_kw("FALSE"):
                    e2 = PR.EqualNullSafe(e, Literal(False))
                else:
                    raise SqlParseError(
                        f"expected NULL/TRUE/FALSE/DISTINCT after IS at "
                        f"{self.peek().pos}")
                if neg2:
                    e2 = PR.Not(e2)
                if negate:
                    raise SqlParseError("NOT IS is not valid SQL")
                e = e2
                continue
            else:
                self.i = save
                return e
            e = PR.Not(e2) if negate else e2

    @staticmethod
    def _cmp(cls, a: Expression, b: Expression) -> Expression:
        from .dataframe import _coerce_pair
        a, b = _coerce_pair(a, b)
        return cls(a, b)

    def _comparison(self) -> Expression:
        from .expressions import predicates as PR
        e = self._bitor()
        ops = {"=": PR.EqualTo, "==": PR.EqualTo, "<": PR.LessThan,
               "<=": PR.LessThanOrEqual, ">": PR.GreaterThan,
               ">=": PR.GreaterThanOrEqual, "<=>": PR.EqualNullSafe}
        t = self.peek()
        if t.kind == "op" and t.text in ops:
            self.next()
            rhs = self._bitor()
            return self._cmp(ops[t.text], e, rhs)
        if t.kind == "op" and t.text in ("!=", "<>"):
            self.next()
            rhs = self._bitor()
            return PR.Not(self._cmp(PR.EqualTo, e, rhs))
        return e

    # value-operator precedence, tightest to loosest (Spark SqlBase.g4):
    #   *,/,%,DIV > +,- > || > <<,>>,>>> > & > ^ > |
    def _bitor(self) -> Expression:
        from .expressions import arithmetic as A
        e = self._bitxor()
        while self.accept_op("|"):
            e = self._arith(A.BitwiseOr, e, self._bitxor())
        return e

    def _bitxor(self) -> Expression:
        from .expressions import arithmetic as A
        e = self._bitand()
        while self.accept_op("^"):
            e = self._arith(A.BitwiseXor, e, self._bitand())
        return e

    def _bitand(self) -> Expression:
        from .expressions import arithmetic as A
        e = self._shift()
        while self.accept_op("&"):
            e = self._arith(A.BitwiseAnd, e, self._shift())
        return e

    def _shift(self) -> Expression:
        from .expressions import arithmetic as A
        e = self._concat()
        while True:
            if self.accept_op("<<"):
                e = A.ShiftLeft(e, self._concat())
            elif self.accept_op(">>>"):
                e = A.ShiftRightUnsigned(e, self._concat())
            elif self.accept_op(">>"):
                e = A.ShiftRight(e, self._concat())
            else:
                return e

    def _concat(self) -> Expression:
        from .expressions import strings as STR
        e = self._additive()
        while self.accept_op("||"):
            e = STR.Concat(_as_string(e), _as_string(self._additive()))
        return e

    def _additive(self) -> Expression:
        from .expressions import arithmetic as A
        e = self._multiplicative()
        while True:
            if self.accept_op("+"):
                e = self._fold_interval(A.Add, e, self._multiplicative())
            elif self.accept_op("-"):
                e = self._fold_interval(A.Subtract, e,
                                        self._multiplicative())
            else:
                return e

    def _fold_interval(self, cls, a: Expression, b: Expression
                       ) -> Expression:
        """date/timestamp +/- INTERVAL folds to DateAddInterval/TimeAdd;
        interval + date commutes; everything else is plain arithmetic."""
        from .expressions import arithmetic as A
        from .expressions.datetime import AddCalendarInterval
        if isinstance(a, IntervalLiteral) and \
                not isinstance(b, IntervalLiteral) and cls is A.Add:
            a, b = b, a
        if isinstance(b, IntervalLiteral):
            if isinstance(a, IntervalLiteral):
                raise SqlParseError("interval +/- interval is not supported")
            sign = 1 if cls is A.Add else -1
            # operand-type dispatch (date vs timestamp, sub-day promotion)
            # happens inside AddCalendarInterval at resolution time
            return AddCalendarInterval(a, months=sign * b.months,
                                       days=sign * b.days,
                                       micros=sign * b.micros)
        if isinstance(a, IntervalLiteral):
            raise SqlParseError(
                "INTERVAL literals are only valid in +/- date arithmetic")
        return self._arith(cls, a, b)

    @staticmethod
    def _arith(cls, a: Expression, b: Expression) -> Expression:
        from .dataframe import _coerce_pair
        a, b = _coerce_pair(a, b)
        return cls(a, b)

    def _multiplicative(self) -> Expression:
        from .expressions import arithmetic as A
        e = self._unary()
        while True:
            if self.accept_op("*"):
                e = self._arith(A.Multiply, e, self._unary())
            elif self.accept_op("/"):
                e = self._arith(A.Divide, e, self._unary())
            elif self.accept_op("%"):
                e = self._arith(A.Remainder, e, self._unary())
            elif self.at_kw("DIV"):
                self.next()
                e = self._arith(A.IntegralDivide, e, self._unary())
            else:
                return e

    def _unary(self) -> Expression:
        from .expressions import arithmetic as A
        if self.accept_op("-"):
            child = self._unary()
            if isinstance(child, Literal) and isinstance(
                    child.value, (int, float)) and not isinstance(
                    child.value, bool):
                return Literal(-child.value, child.dtype)
            return A.UnaryMinus(child)
        if self.accept_op("+"):
            return self._unary()
        if self.accept_op("~"):
            return A.BitwiseNot(self._unary())
        return self._primary()

    def _primary(self) -> Expression:
        from . import functions as F
        t = self.peek()
        if t.kind == "ident" and t.upper == "EXISTS" \
                and self.peek(1).kind == "op" and self.peek(1).text == "(":
            self.next()
            self.expect_op("(")
            q = self._query_term({})
            self.expect_op(")")
            return ExistsSubquery(q)
        if t.kind == "num":
            return self._number(self.next().text)
        if t.kind == "str":
            self.next()
            return Literal(unescape_sql_string(t.text[1:-1]))
        if t.kind == "op" and t.text == "(" and self.peek(1).kind == "ident" \
                and self.peek(1).upper == "SELECT":
            self.next()
            q = self._query_term({})
            self.expect_op(")")
            return ScalarSubquery(q)
        if self.accept_op("("):
            e = self.parse_expression()
            self.expect_op(")")
            return e
        if t.kind == "op" and t.text == "*":
            self.next()
            return Star()           # only valid in select-list / count(*)
        if t.kind in ("ident", "qident"):
            up = t.upper
            if up == "NULL" and t.kind == "ident":
                self.next()
                return Literal(None)
            if up in ("TRUE", "FALSE") and t.kind == "ident":
                self.next()
                return Literal(up == "TRUE")
            if up == "CAST" and t.kind == "ident" and \
                    self.peek(1).kind == "op" and self.peek(1).text == "(":
                return self._cast()
            if up == "CASE" and t.kind == "ident":
                return self._case()
            if up in ("DATE", "TIMESTAMP") and t.kind == "ident" \
                    and self.peek(1).kind == "str":
                # typed literal: DATE '1995-01-01' / TIMESTAMP '...' —
                # the form the TPC-H query texts use everywhere.  Only
                # when a string literal follows: bare `date` stays a
                # valid column name.
                import datetime as _dt
                self.next()
                s = unescape_sql_string(self.next().text[1:-1])
                try:
                    if up == "DATE":
                        return Literal(_dt.date.fromisoformat(s))
                    return Literal(_dt.datetime.fromisoformat(s))
                except ValueError:
                    raise SqlParseError(
                        f"bad {up} literal {s!r}") from None
            if up == "INTERVAL" and t.kind == "ident":
                self.next()
                months = days = micros = 0
                saw = False
                def unit_at(k: int) -> bool:
                    u = self.peek(k)
                    return (u.kind == "ident"
                            and u.text.lower() in _INTERVAL_UNITS)

                while True:
                    # commit to a component only when a UNIT follows the
                    # value — a trailing +/- or number belongs to the
                    # enclosing arithmetic (INTERVAL '1' DAY - x)
                    v = self.peek()
                    if v.kind in ("str", "num") and unit_at(1):
                        self.next()
                        txt = v.text[1:-1] if v.kind == "str" else v.text
                        try:
                            n = int(txt)
                        except ValueError:
                            raise SqlParseError(
                                f"bad INTERVAL value {v.text}") from None
                    elif v.kind == "op" and v.text == "-" \
                            and self.peek(1).kind in ("num", "str") \
                            and unit_at(2):
                        self.next()
                        v2 = self.next()
                        txt = v2.text[1:-1] if v2.kind == "str" else v2.text
                        try:
                            n = -int(txt)
                        except ValueError:
                            raise SqlParseError(
                                f"bad INTERVAL value {v2.text}") from None
                    else:
                        break
                    u = self.next()
                    mo, d, us = _INTERVAL_UNITS[u.text.lower()]
                    months += n * mo
                    days += n * d
                    micros += n * us
                    saw = True
                if not saw:
                    raise SqlParseError("empty INTERVAL literal")
                return IntervalLiteral(months, days, micros)
            name = self.expect_ident()
            # function call?
            if self.at_op("(") and t.kind == "ident":
                return self._call(name)
            # qualified: t.a, t.*
            if self.accept_op("."):
                if self.accept_op("*"):
                    return Star(qualifier=name)
                sub = self.expect_ident()
                return UnresolvedQualified(name, sub)
            return F.col(name).expr
        raise SqlParseError(
            f"unexpected token {t.text!r} at {t.pos} in {self.sql!r}")

    @staticmethod
    def _number(text: str) -> Literal:
        suffix = text[-1] if text[-1] in "dDlLfF" else ""
        if suffix:
            text = text[:-1]
        if (suffix and suffix in "dDfF") or "." in text \
                or "e" in text or "E" in text:
            return Literal(float(text))
        if suffix:                      # 42L — explicit bigint
            return Literal(int(text), T.LONG)
        return Literal(int(text))

    def _cast(self) -> Expression:
        from .expressions.cast import Cast
        self.next()             # CAST
        self.expect_op("(")
        e = self.parse_expression()
        self.expect_kw("AS")
        dt = _parse_type_tokens(self)
        self.expect_op(")")
        return Cast(e, dt)

    def _case(self) -> Expression:
        from .expressions.conditional import CaseWhen
        from .expressions import predicates as PR
        self.next()             # CASE
        subject = None
        if not self.at_kw("WHEN"):
            subject = self.parse_expression()
        branches = []
        while self.accept_kw("WHEN"):
            cond = self.parse_expression()
            if subject is not None:
                cond = self._cmp(PR.EqualTo, subject, cond)
            self.expect_kw("THEN")
            branches.append((cond, self.parse_expression()))
        else_v = None
        if self.accept_kw("ELSE"):
            else_v = self.parse_expression()
        self.expect_kw("END")
        return CaseWhen(branches, else_v)

    def _call(self, name: str) -> Expression:
        from . import functions as F
        from .dataframe import Column
        from .expressions.aggregates import (AggregateExpression, Average,
                                             Count, Max, Min, Sum)
        self.expect_op("(")
        lname = name.lower()
        if lname == "extract":
            # EXTRACT(unit FROM expr) — special syntactic form (SQL
            # standard; TPC-H q7/q8/q9 use extract(year from ...)).
            # Lowered onto the plain field-extraction functions.
            unit_tok = self.next()
            unit = unit_tok.text.lower()
            fn = {"year": "year", "month": "month", "day": "day",
                  "dayofmonth": "day", "hour": "hour", "minute": "minute",
                  "second": "second", "quarter": "quarter",
                  "week": "weekofyear", "dow": "dayofweek",
                  "doy": "dayofyear"}.get(unit)
            if fn is None or unit_tok.kind != "ident":
                raise SqlParseError(
                    f"unsupported EXTRACT unit {unit_tok.text!r}")
            self.expect_kw("FROM")
            arg = self.parse_expression()
            self.expect_op(")")
            from .dataframe import Column as _Col
            res = _functions()[fn](_Col(arg))
            e = res.expr if isinstance(res, _Col) else res
            if unit == "dow":
                # Spark's EXTRACT(DOW) is 0=Sunday..6; dayofweek() is
                # 1=Sunday..7
                from .expressions import arithmetic as A
                e = A.Subtract(e, Literal(1))
            return e
        distinct = False
        if self.accept_kw("DISTINCT"):
            distinct = True
        args: List[Expression] = []
        if not self.at_op(")"):
            args.append(self.parse_expression())
            while self.accept_op(","):
                args.append(self.parse_expression())
        self.expect_op(")")

        if lname == "count" and len(args) == 1 and isinstance(args[0], Star):
            if distinct:
                raise SqlParseError("count(DISTINCT *) is not supported")
            e: Expression = Count()
        elif lname == "count" and distinct:
            e = AggregateExpression(Count(*args), is_distinct=True)
        elif distinct and lname in ("sum", "avg", "mean", "min", "max"):
            base = {"sum": Sum, "avg": Average, "mean": Average,
                    "min": Min, "max": Max}[lname](args[0])
            e = AggregateExpression(base, is_distinct=True)
        elif lname in ("if", "iff"):
            from .expressions.conditional import If
            if len(args) != 3:
                raise SqlParseError("if() takes exactly 3 arguments")
            e = If(args[0], args[1], args[2])
        elif lname == "nullif":
            from .expressions.conditional import CaseWhen
            from .expressions import predicates as PR
            e = CaseWhen([(self._cmp(PR.EqualTo, args[0], args[1]),
                           Literal(None))], args[0])
        elif lname in self.udfs:
            from .expressions.hive_udf import HiveSimpleUDF
            if distinct:
                raise SqlParseError(
                    f"DISTINCT is not supported inside {name}()")
            e = HiveSimpleUDF(lname, self.udfs[lname], *args)
        else:
            fn = _functions().get(lname)
            if fn is None:
                raise SqlParseError(f"unknown SQL function {name!r}")
            if distinct:
                raise SqlParseError(
                    f"DISTINCT is not supported inside {name}()")
            unwrap = _LITERAL_POS.get(lname, ())
            call_args: List[Any] = []
            for idx, a in enumerate(args):
                if idx in unwrap and isinstance(a, Literal):
                    call_args.append(a.value)
                else:
                    call_args.append(Column(a))
            res = fn(*call_args)
            e = res.expr if isinstance(res, Column) else res
        if self.at_kw("OVER"):
            e = self._over(e)
        return e

    def _over(self, fn_expr: Expression) -> Expression:
        self.expect_kw("OVER")
        self.expect_op("(")
        partition: List[Expression] = []
        orders: List[SortOrder] = []
        frame = None
        if self.accept_kw("PARTITION"):
            self.expect_kw("BY")
            partition.append(self.parse_expression())
            while self.accept_op(","):
                partition.append(self.parse_expression())
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            orders.append(self._sort_order())
            while self.accept_op(","):
                orders.append(self._sort_order())
        if self.at_kw("ROWS", "RANGE"):
            mode = self.next().text.lower()
            self.expect_kw("BETWEEN")
            lo = self._frame_bound()
            self.expect_kw("AND")
            hi = self._frame_bound()
            frame = WindowFrame(mode, lo, hi)
        self.expect_op(")")
        spec = WindowSpecDefinition(tuple(partition), tuple(orders), frame)
        if isinstance(fn_expr, Alias):
            return Alias(WindowExpression(fn_expr.child, spec), fn_expr.name)
        return WindowExpression(fn_expr, spec)

    def _frame_bound(self) -> int:
        if self.accept_kw("UNBOUNDED"):
            if self.accept_kw("PRECEDING"):
                return UNBOUNDED_PRECEDING
            self.expect_kw("FOLLOWING")
            return UNBOUNDED_FOLLOWING
        if self.accept_kw("CURRENT"):
            self.expect_kw("ROW")
            return CURRENT_ROW
        sign = -1 if self.accept_op("-") else 1
        n = self.expect_int() * sign
        if self.accept_kw("PRECEDING"):
            return -n
        self.expect_kw("FOLLOWING")
        return n

    def _sort_order(self) -> SortOrder:
        e = self.parse_expression()
        asc = True
        if self.accept_kw("ASC"):
            asc = True
        elif self.accept_kw("DESC"):
            asc = False
        nulls_first = None
        if self.accept_kw("NULLS"):
            if self.accept_kw("FIRST"):
                nulls_first = True
            else:
                self.expect_kw("LAST")
                nulls_first = False
        return SortOrder(e, asc, nulls_first)

    # --- statements -------------------------------------------------------
    def _maybe_function_ddl(self):
        if self.accept_kw("CREATE"):
            replace = False
            if self.accept_kw("OR"):
                self.expect_kw("REPLACE")
                replace = True
            if not self.accept_kw("TEMPORARY", "TEMP"):
                return None
            if self.accept_kw("VIEW"):
                name = self.expect_ident()
                self.expect_kw("AS")
                ctes = self._parse_ctes()
                sub = self._query_term(ctes)
                sub.ctes = ctes
                return CreateTempViewStmt(name, sub, replace)
            if not self.accept_kw("FUNCTION"):
                return None
            name = self.expect_ident()
            self.expect_kw("AS")
            t = self.peek()
            if t.kind != "str":
                raise SqlParseError(
                    f"expected a quoted class path after AS at {t.pos}")
            self.next()
            path = unescape_sql_string(t.text[1:-1])
            return CreateFunctionStmt(name, path, replace)
        if self.accept_kw("DROP"):
            self.accept_kw("TEMPORARY", "TEMP")
            if self.accept_kw("VIEW"):
                if_exists = False
                if self.accept_kw("IF"):
                    self.expect_kw("EXISTS")
                    if_exists = True
                return DropViewStmt(self.expect_ident(), if_exists)
            if not self.accept_kw("FUNCTION"):
                return None
            if_exists = False
            if self.accept_kw("IF"):
                self.expect_kw("EXISTS")
                if_exists = True
            return DropFunctionStmt(self.expect_ident(), if_exists)
        return None

    def _parse_ctes(self):
        ctes: Dict[str, Any] = {}
        if self.accept_kw("WITH"):
            while True:
                name = self.expect_ident()
                self.expect_kw("AS")
                self.expect_op("(")
                sub = self._query_term(ctes)
                self.expect_op(")")
                ctes[name.lower()] = sub
                if not self.accept_op(","):
                    break
        return ctes

    def parse_statement(self):
        # DDL: CREATE [OR REPLACE] TEMPORARY FUNCTION f AS 'module.Class'
        # (the exact shape Spark uses to register Hive UDFs) / DROP
        # TEMPORARY FUNCTION [IF EXISTS] f / SHOW TABLES /
        # DESCRIBE [TABLE] name
        if self.at_kw("SHOW"):
            save = self.i
            self.next()
            if self.accept_kw("TABLES") and self.peek().kind == "eof":
                return ShowTablesStmt()
            self.i = save
        if self.at_kw("DESCRIBE", "DESC"):
            save = self.i
            self.next()
            self.accept_kw("TABLE")
            t = self.peek()
            if t.kind in ("ident", "qident"):
                name = self.expect_ident()
                if self.peek().kind == "eof":
                    return DescribeTableStmt(name)
            self.i = save
        if self.at_kw("CREATE") or self.at_kw("DROP"):
            save = self.i
            stmt = self._maybe_function_ddl()
            if stmt is not None:
                tail = self.peek()
                if tail.kind != "eof":
                    raise SqlParseError(
                        f"unexpected trailing input {tail.text!r} at "
                        f"{tail.pos} in {self.sql!r}")
                return stmt
            self.i = save
        ctes = self._parse_ctes()
        stmt = self._query_term(ctes)
        stmt.ctes = ctes
        tail = self.peek()
        if tail.kind != "eof":
            raise SqlParseError(
                f"unexpected trailing input {tail.text!r} at {tail.pos} "
                f"in {self.sql!r}")
        return stmt

    def _set_op_modifier(self) -> bool:
        is_all = self.accept_kw("ALL")
        if self.accept_kw("DISTINCT") and is_all:
            raise SqlParseError("cannot combine ALL and DISTINCT in a "
                                "set operation")
        return is_all

    def _query_term(self, ctes) -> Any:
        # INTERSECT binds tighter than UNION/EXCEPT (SQL standard)
        left = self._intersect_term(ctes)
        while self.at_kw("UNION", "EXCEPT", "MINUS"):
            op = self.next().upper
            if op == "MINUS":
                op = "EXCEPT"
            is_all = self._set_op_modifier()
            right = self._intersect_term(ctes)
            left = SetOpStmt(op.lower(), is_all, left, right)
        # ORDER BY / LIMIT terminate the whole query term (a set-op branch
        # cannot carry its own trailing clauses without parentheses)
        ob = self._order_by_clause()
        lim, off = self._limit_clause()
        if ob:
            if left.order_by:
                raise SqlParseError("multiple ORDER BY clauses")
            left.order_by = ob
        if lim is not None or off is not None:
            if left.limit is not None or left.offset is not None:
                raise SqlParseError("multiple LIMIT/OFFSET clauses")
            left.limit, left.offset = lim, off
        return left

    def _intersect_term(self, ctes) -> Any:
        left = self._query_primary(ctes)
        while self.at_kw("INTERSECT"):
            self.next()
            is_all = self._set_op_modifier()
            right = self._query_primary(ctes)
            left = SetOpStmt("intersect", is_all, left, right)
        return left

    def _query_primary(self, ctes) -> Any:
        if self.accept_op("("):
            q = self._query_term(ctes)
            self.expect_op(")")
            return q
        return self._select(ctes)

    def _select(self, ctes) -> SelectStmt:
        self.expect_kw("SELECT")
        stmt = SelectStmt()
        if self.accept_kw("DISTINCT"):
            stmt.distinct = True
        else:
            self.accept_kw("ALL")
        stmt.items.append(self._select_item())
        while self.accept_op(","):
            stmt.items.append(self._select_item())
        if self.accept_kw("FROM"):
            stmt.from_ = self._table_ref(ctes)
            while True:
                if self.at_kw("LATERAL"):
                    self.next()
                    self.expect_kw("VIEW")
                    outer = self.accept_kw("OUTER")
                    fname = self.expect_ident().lower()
                    self.expect_op("(")
                    arg = self.parse_expression()
                    self.expect_op(")")
                    talias = self.expect_ident()
                    cols: List[str] = []
                    if self.accept_kw("AS"):
                        cols.append(self.expect_ident())
                        while self.accept_op(","):
                            cols.append(self.expect_ident())
                    stmt.lateral_views.append(
                        LateralView(outer, fname, arg, talias, cols))
                    continue
                step = self._join_step(ctes)
                if step is None:
                    break
                if stmt.lateral_views:
                    # Spark's grammar puts LATERAL VIEW after all joins;
                    # silently joining-then-exploding would reorder the
                    # user's written evaluation, so reject like Spark
                    raise SqlParseError(
                        "JOIN after LATERAL VIEW is not supported — "
                        "put all JOINs before the LATERAL VIEW clauses")
                stmt.joins.append(step)
        if self.accept_kw("WHERE"):
            stmt.where = self.parse_expression()
        if self.accept_kw("GROUP"):
            self.expect_kw("BY")
            self._group_element(stmt)
            while self.accept_op(","):
                self._group_element(stmt)
        if self.accept_kw("HAVING"):
            stmt.having = self.parse_expression()
        # ORDER BY / LIMIT are parsed at the query-term level so they bind
        # to a whole set-operation result, never to its last branch
        return stmt

    def _group_item(self):
        t = self.peek()
        if t.kind == "num" and t.text.isdigit():
            self.next()
            return int(t.text)
        return self.parse_expression()

    def _group_element(self, stmt: "SelectStmt") -> None:
        """One GROUP BY element: a plain item (always-grouped base key)
        or ONE ROLLUP/CUBE/GROUPING SETS construct, mixable with base
        keys (Spark 3 partial grouping: GROUP BY a, ROLLUP(b) =
        {a} x rollup sets)."""
        def one_construct(mode: str):
            if stmt.group_by_mode is not None:
                raise SqlParseError(
                    "only one ROLLUP/CUBE/GROUPING SETS construct is "
                    "supported per GROUP BY")
            stmt.group_by_mode = mode
        if self.at_kw("ROLLUP", "CUBE") and \
                self.peek(1).kind == "op" and self.peek(1).text == "(":
            one_construct(self.peek().upper.lower())
            self.next()
            self.expect_op("(")
            exprs = [self._group_item()]
            while self.accept_op(","):
                exprs.append(self._group_item())
            self.expect_op(")")
            stmt.grouping_sets_raw = [exprs]
            return
        if self.at_kw("GROUPING") and self.peek(1).upper == "SETS" \
                and self.peek(2).kind == "op" and self.peek(2).text == "(":
            one_construct("sets")
            self.next()
            self.next()
            self.expect_op("(")
            while True:
                one: List[Any] = []
                if self.accept_op("("):
                    # parenthesized (possibly empty) key list
                    if not self.accept_op(")"):
                        one.append(self._group_item())
                        while self.accept_op(","):
                            one.append(self._group_item())
                        self.expect_op(")")
                else:
                    one.append(self._group_item())  # bare single key
                stmt.grouping_sets_raw.append(one)
                if not self.accept_op(","):
                    break
            self.expect_op(")")
            return
        stmt.group_by.append(self._group_item())

    def _order_by_clause(self) -> List[OrderItem]:
        out: List[OrderItem] = []
        if self.accept_kw("ORDER"):
            self.expect_kw("BY")
            while True:
                t = self.peek()
                if t.kind == "num" and t.text.isdigit():
                    self.next()
                    e: Any = int(t.text)
                else:
                    e = self.parse_expression()
                asc = True
                if self.accept_kw("DESC"):
                    asc = False
                else:
                    self.accept_kw("ASC")
                nf = None
                if self.accept_kw("NULLS"):
                    if self.accept_kw("FIRST"):
                        nf = True
                    else:
                        self.expect_kw("LAST")
                        nf = False
                out.append(OrderItem(e, asc, nf))
                if not self.accept_op(","):
                    break
        return out

    def _limit_clause(self) -> Tuple[Optional[int], Optional[int]]:
        limit = offset = None
        if self.accept_kw("LIMIT"):
            if self.accept_kw("ALL"):
                limit = None
            else:
                limit = self.expect_int()
        if self.accept_kw("OFFSET"):
            offset = self.expect_int()
        return limit, offset

    def _select_item(self) -> SelectItem:
        e = self.parse_expression()
        alias = None
        if self.accept_kw("AS"):
            alias = self.expect_ident()
        elif (self.peek().kind == "qident"
              or (self.peek().kind == "ident"
                  and self.peek().upper not in _RESERVED_STOP)):
            alias = self.expect_ident()
        return SelectItem(e, alias)

    def _table_ref(self, ctes) -> Any:
        if self.accept_op("("):
            q = self._query_term(ctes)
            self.expect_op(")")
            alias, sample = self._ref_suffix()
            return SubqueryRef(q, alias, sample=sample)
        name = self.expect_ident()
        # direct file relation: parquet.`/path`
        if name.lower() in ("parquet", "orc", "csv", "json", "avro") and \
                self.at_op(".") and self.peek(1).kind == "qident":
            self.next()
            path = self.expect_ident()
            alias, sample = self._ref_suffix()
            return TableRef(name.lower(), alias, path=path, sample=sample)
        alias, sample = self._ref_suffix()
        return TableRef(name, alias, sample=sample)

    def _ref_suffix(self):
        """[alias] [TABLESAMPLE ...] [alias] after a relation — one place
        for all three _table_ref branches."""
        alias = self._table_alias()
        sample = self._maybe_tablesample()
        return alias or self._table_alias(), sample

    def _maybe_tablesample(self):
        """TABLESAMPLE (n PERCENT | n ROWS) [REPEATABLE (seed)] after a
        relation (Spark's sample clause; PERCENT maps to the Sample
        operator, ROWS to a limit)."""
        if not self.accept_kw("TABLESAMPLE"):
            return None
        self.expect_op("(")
        t = self.expect_kind("num")
        try:
            val = float(t.text)
        except ValueError:
            raise SqlParseError(
                f"bad TABLESAMPLE value {t.text!r} at {t.pos} in "
                f"{self.sql!r}") from None
        if self.accept_kw("PERCENT"):
            kind = "percent"
        elif self.accept_kw("ROWS"):
            kind = "rows"
            if not t.text.isdigit():
                raise SqlParseError(
                    f"TABLESAMPLE ROWS expects an integer at {t.pos} in "
                    f"{self.sql!r}, got {t.text!r}")
        else:
            raise SqlParseError(
                "TABLESAMPLE supports 'n PERCENT' and 'n ROWS'")
        self.expect_op(")")
        seed = None  # None = fresh per sample (Spark's non-REPEATABLE)
        if self.accept_kw("REPEATABLE"):
            self.expect_op("(")
            seed = self.expect_int()
            self.expect_op(")")
        return (kind, val, seed)

    def _table_alias(self) -> Optional[str]:
        if self.accept_kw("AS"):
            return self.expect_ident()
        t = self.peek()
        if t.kind == "qident" or (t.kind == "ident"
                                  and t.upper not in _RESERVED_STOP):
            return self.expect_ident()
        return None

    def _join_step(self, ctes) -> Optional[JoinStep]:
        how = None
        if self.accept_op(","):
            how = "cross"
        elif self.at_kw("JOIN"):
            self.next()
            how = "inner"
        elif self.at_kw("INNER") and self.peek(1).upper == "JOIN":
            self.next(); self.next()
            how = "inner"
        elif self.at_kw("CROSS") and self.peek(1).upper == "JOIN":
            self.next(); self.next()
            how = "cross"
        elif self.at_kw("LEFT", "RIGHT", "FULL"):
            side = self.next().upper.lower()
            if self.accept_kw("OUTER"):
                pass
            elif side == "left" and self.accept_kw("SEMI"):
                side = "left_semi"
            elif side == "left" and self.accept_kw("ANTI"):
                side = "left_anti"
            self.expect_kw("JOIN")
            how = {"full": "full"}.get(side, side)
        if how is None:
            return None
        right = self._table_ref(ctes)
        on = None
        using = None
        if self.accept_kw("ON"):
            on = self.parse_expression()
        elif self.accept_kw("USING"):
            self.expect_op("(")
            using = [self.expect_ident()]
            while self.accept_op(","):
                using.append(self.expect_ident())
            self.expect_op(")")
        return JoinStep(how, right, on, using)


# --------------------------------------------------------------------------
# Public expression-string entry points
# --------------------------------------------------------------------------

def _active_udfs():
    """Hive UDFs of the active session — the fallback for surfaces with
    no session in reach (bare F.expr)."""
    from .session import TpuSession
    s = TpuSession._active
    return getattr(s, "_hive_udfs", None) if s is not None else None


def parse_expr(sql: str, udfs=None):
    """``F.expr("...")`` — expression string to a Column (plain column
    names stay unresolved, resolved later against the target frame).
    ``udfs``: the owning session's Hive UDF registry (DataFrame surfaces
    pass their own session's; bare F.expr falls back to the active
    session)."""
    from .dataframe import Column
    p = Parser(sql, udfs=udfs if udfs is not None else _active_udfs())
    e = p.parse_expression()
    alias = None
    if p.accept_kw("AS"):
        alias = p.expect_ident()
    tail = p.peek()
    if tail.kind != "eof":
        raise SqlParseError(
            f"unexpected trailing input {tail.text!r} in expression "
            f"{sql!r}")
    if isinstance(e, Star):
        raise SqlParseError("'*' is only valid in a select list")
    if alias:
        e = Alias(e, alias)
    return Column(e)


def parse_select_item(sql: str, udfs=None):
    """One selectExpr entry: expression with optional alias, or '*'."""
    p = Parser(sql, udfs=udfs if udfs is not None else _active_udfs())
    item = p._select_item()
    tail = p.peek()
    if tail.kind != "eof":
        raise SqlParseError(
            f"unexpected trailing input {tail.text!r} in {sql!r}")
    return item


# --------------------------------------------------------------------------
# Query builder: statement AST -> DataFrame
# --------------------------------------------------------------------------

@dataclass
class CreateFunctionStmt:
    name: str
    class_path: str
    replace: bool = False


@dataclass
class DropFunctionStmt:
    name: str
    if_exists: bool = False


@dataclass
class ShowTablesStmt:
    pass


@dataclass
class CreateTempViewStmt:
    name: str
    stmt: "Any"
    replace: bool = False


@dataclass
class DropViewStmt:
    name: str
    if_exists: bool = False


@dataclass
class DescribeTableStmt:
    name: str


class QueryBuilder:
    """Builds DataFrames from parsed statements against a session's
    temp-view catalog (the Catalyst analyzer+planner front half)."""

    def __init__(self, session):
        self.session = session
        self._subq = 0

    # --- entry ------------------------------------------------------------
    def build(self, stmt, outer_ctes: Optional[Dict[str, Any]] = None):
        ctes = dict(outer_ctes or {})
        ctes.update({k: ("stmt", v) for k, v in stmt.ctes.items()})
        if isinstance(stmt, SetOpStmt):
            return self._build_setop(stmt, ctes)
        return self._build_select(stmt, ctes)

    def _build_setop(self, stmt: SetOpStmt, ctes):
        left = self._build_sub(stmt.left, ctes)
        right = self._build_sub(stmt.right, ctes)
        if stmt.op == "union":
            df = left.union(right)
            if not stmt.all:
                df = df.distinct()
        elif stmt.op == "intersect":
            df = left.intersectAll(right) if stmt.all else \
                left.intersect(right)
        else:
            df = left.exceptAll(right) if stmt.all else left.subtract(right)
        df = self._apply_order_limit(df, stmt.order_by, stmt.limit,
                                     stmt.offset, items=None)
        return df

    def _build_sub(self, stmt, ctes):
        if isinstance(stmt, SetOpStmt):
            return self._build_setop(stmt, ctes)
        return self._build_select(stmt, ctes)

    # --- FROM -------------------------------------------------------------
    def _resolve_relation(self, ref, ctes):
        from .dataframe import DataFrame
        if isinstance(ref, SubqueryRef):
            df = self._build_sub(ref.stmt, ctes)
            self._subq += 1
            alias = ref.alias or f"__subquery{self._subq}"
            return self._apply_sample(self._fresh(df), ref.sample), alias
        assert isinstance(ref, TableRef)
        if ref.path is not None:
            reader = self.session.read
            df = getattr(reader, ref.name)(ref.path)
            return (self._apply_sample(self._fresh(df), ref.sample),
                    ref.alias or ref.name)
        key = ref.name.lower()
        if key in ctes:
            kind, payload = ctes[key]
            df = self._build_sub(payload, ctes) if kind == "stmt" else payload
            return (self._apply_sample(self._fresh(df), ref.sample),
                    ref.alias or ref.name)
        view = self.session._temp_views.get(key)
        if view is None:
            raise SqlParseError(f"table or view not found: {ref.name}")
        df = self._fresh(DataFrame(view._plan, self.session))
        return self._apply_sample(df, ref.sample), ref.alias or ref.name

    @staticmethod
    def _apply_sample(df, sample):
        if sample is None:
            return df
        kind, val, seed = sample
        if kind == "rows":
            return df.limit(int(val))
        if not (0.0 <= val <= 100.0):
            raise SqlParseError(
                f"TABLESAMPLE percentage {val} not in [0, 100]")
        if seed is None:
            # non-REPEATABLE: each sample gets a distinct seed so two
            # samples of the same table in one query are independent
            # (deterministic across reruns — engine-wide determinism)
            seed = next(_SAMPLE_SEEDS)
        return df.sample(val / 100.0, seed=seed)

    def _fresh(self, df):
        """Re-alias every output column under fresh expression ids, so two
        references to the same relation (self-join ``t a JOIN t b``) have
        distinct attributes (Catalyst's deduplicateRelations)."""
        from . import plan as P
        from .dataframe import DataFrame
        exprs = tuple(Alias(a, a.name) for a in df._plan.output)
        return DataFrame(P.Project(exprs, df._plan), self.session)

    # --- scalar subqueries ------------------------------------------------
    def _eval_scalar_expr(self, e: Expression, ctes) -> Expression:
        def repl(x):
            if not isinstance(x, ScalarSubquery):
                return None
            if isinstance(x.stmt, SelectStmt):
                inner_aliases = self._relation_aliases(x.stmt)
                exprs = ([it.expr for it in x.stmt.items
                          if isinstance(it.expr, Expression)]
                         + [e2 for e2 in (x.stmt.where, x.stmt.having)
                            if e2 is not None])
                for e2 in exprs:
                    if e2.collect(lambda n: isinstance(n, UnresolvedQualified)
                                  and n.qualifier.lower()
                                  not in inner_aliases):
                        # correlated: leave the node for the decorrelation
                        # pass in _build_select (grouped-agg LEFT JOIN)
                        return None
            inner = self._build_sub(x.stmt, ctes)
            if len(inner._plan.output) != 1:
                raise SqlParseError(
                    "scalar subquery must return exactly one column")
            attr = inner._plan.output[0]
            rows = inner.limit(2).collect().to_pylist()
            if len(rows) > 1:
                raise SqlParseError(
                    "scalar subquery returned more than one row")
            val = rows[0][attr.name] if rows else None
            return Literal(val, attr.dtype)
        return e.transform(repl)

    def _eval_scalar_subqueries_stmt(self, stmt: SelectStmt, ctes):
        """Replace uncorrelated scalar subqueries in every expression slot
        with their (build-time evaluated) literal value."""
        has = any(
            isinstance(e, Expression)
            and e.collect(lambda x: isinstance(x, ScalarSubquery))
            for e in ([it.expr for it in stmt.items]
                      + [stmt.where, stmt.having]
                      + list(stmt.group_by)
                      + [g for s in stmt.grouping_sets_raw for g in s]
                      + [j.on for j in stmt.joins]
                      + [oi.expr for oi in stmt.order_by])
            if e is not None)
        if not has:
            return stmt
        import dataclasses

        def ev(e):
            if e is None or not isinstance(e, Expression):
                return e
            return self._eval_scalar_expr(e, ctes)

        return dataclasses.replace(
            stmt,
            items=[SelectItem(it.expr if isinstance(it.expr, Star)
                              else ev(it.expr), it.alias)
                   for it in stmt.items],
            where=ev(stmt.where), having=ev(stmt.having),
            group_by=[ev(g) for g in stmt.group_by],
            grouping_sets_raw=[[ev(g) for g in s]
                               for s in stmt.grouping_sets_raw],
            joins=[dataclasses.replace(j, on=ev(j.on))
                   for j in stmt.joins],
            order_by=[dataclasses.replace(oi, expr=ev(oi.expr))
                      for oi in stmt.order_by])

    # --- subquery predicates (EXISTS / IN) --------------------------------
    @staticmethod
    def _relation_aliases(stmt) -> set:
        """Lower-cased relation aliases visible inside a SelectStmt's own
        FROM clause (for telling correlated references apart)."""
        out = set()
        if not isinstance(stmt, SelectStmt):
            return out
        refs = ([stmt.from_] if stmt.from_ is not None else []) \
            + [j.right for j in stmt.joins]
        for r in refs:
            if isinstance(r, TableRef):
                # an alias HIDES the base table name (SQL scoping): outer
                # references to the unaliased name stay outer
                out.add((r.alias or r.name).lower())
            elif isinstance(r, SubqueryRef) and r.alias:
                out.add(r.alias.lower())
        return out

    def _split_correlation(self, q, what: str, allow_mixed: bool = False):
        """Split a subquery's WHERE into ([(outer_expr, inner_expr)],
        [inner-only conjuncts], [mixed conjuncts]) — the decorrelation
        shared by correlated EXISTS and correlated scalar subqueries
        (Spark's RewriteCorrelatedScalarSubquery /
        RewritePredicateSubquery).

        ``allow_mixed`` (EXISTS only): correlated conjuncts that are NOT
        outer=inner equalities (TPC-H q21's ``l2.l_suppkey <>
        l1.l_suppkey``) are returned in the third slot for the caller to
        fold into the semi/anti join's residual condition; without it
        they raise, since the scalar-subquery rewrite needs equality
        keys to group on."""
        from .expressions import predicates as PR
        inner_aliases = self._relation_aliases(q)

        def outer_quals(e):
            return e.collect(
                lambda x: isinstance(x, UnresolvedQualified)
                and x.qualifier.lower() not in inner_aliases)

        corr_pairs = []
        inner_conj = []
        mixed = []
        if isinstance(q, SelectStmt) and q.where is not None:
            for c in _split_and(q.where):
                oq = outer_quals(c)
                if not oq:
                    inner_conj.append(c)
                    continue
                if isinstance(c, PR.EqualTo):
                    a, b = c.children
                    if outer_quals(a) and not outer_quals(b):
                        corr_pairs.append((a, b))
                        continue
                    if outer_quals(b) and not outer_quals(a):
                        corr_pairs.append((b, a))
                        continue
                if allow_mixed:
                    mixed.append(c)
                    continue
                raise SqlParseError(
                    f"{what} supports only AND-connected "
                    f"equality predicates, got {c.sql()!r}")
        return corr_pairs, inner_conj, mixed

    def _rewrite_mixed_conjunct(self, c, q, units):
        """Replace each maximal inner-only subexpression of a mixed
        correlated conjunct with an _InnerUnit placeholder (appending the
        subexpression to ``units`` for the caller to project out of the
        subquery); outer references stay in place for binding against the
        outer frame."""
        inner_aliases = self._relation_aliases(q)

        def has_outer(e):
            return bool(e.collect(
                lambda x: isinstance(x, UnresolvedQualified)
                and x.qualifier.lower() not in inner_aliases))

        def walk(e):
            if not has_outer(e):
                if isinstance(e, Literal):
                    return e
                units.append(e)
                return _InnerUnit(len(units) - 1)
            kids = tuple(walk(ch) for ch in e.children)
            return e.with_children(kids) if kids != e.children else e

        return walk(c)

    def _apply_lateral_view(self, df, lv: "LateralView", scope):
        """One LATERAL VIEW [OUTER] generator step -> a Generate node
        over the running frame (Hive/Spark semantics: generated columns
        join every source row; OUTER keeps rows whose array is
        empty/null).  The view alias resolves qualified references to
        the generated columns."""
        from . import plan as P
        from .dataframe import DataFrame
        from .expressions.collections import Explode, PosExplode
        cls = {"explode": Explode, "explode_outer": Explode,
               "posexplode": PosExplode,
               "posexplode_outer": PosExplode}.get(lv.func)
        if cls is None:
            raise SqlParseError(
                f"unsupported LATERAL VIEW generator {lv.func!r} "
                "(explode/posexplode[_outer])")
        outer = lv.outer or lv.func.endswith("_outer")
        arg = _resolve_or_err(self._bind_quals(lv.arg, scope), df._plan)
        gen = cls(arg)
        attrs = gen.gen_output_attrs()
        if lv.col_aliases:
            if len(lv.col_aliases) != len(attrs):
                raise SqlParseError(
                    f"LATERAL VIEW {lv.func} produces {len(attrs)} "
                    f"column(s); {len(lv.col_aliases)} alias(es) given")
            attrs = [a.renamed(n)
                     for a, n in zip(attrs, lv.col_aliases)]
        plan2 = P.Generate(gen, outer, tuple(attrs), df._plan)
        out = DataFrame(plan2, self.session)
        if lv.table_alias.lower() in scope:
            raise SqlParseError(
                f"duplicate relation alias {lv.table_alias!r}")
        scope[lv.table_alias.lower()] = DataFrame(
            P.Project(tuple(attrs), plan2), self.session)
        return out

    def _decorrelate_scalar_subqueries(self, df, stmt: "SelectStmt",
                                       scope, ctes):
        """Rewrite correlated scalar subqueries in the WHERE clause and
        SELECT list into a grouped-aggregate LEFT JOIN (TPC-H q2/q17
        shape: ``v < (SELECT avg(x) FROM t2 WHERE t2.k = outer.k)``).
        The aggregate-without-GROUP-BY requirement guarantees at most one
        row per correlation key, so the join cannot duplicate outer rows.
        Returns (joined df, stmt with the subquery nodes substituted)."""
        import dataclasses

        from .dataframe import Column
        from .expressions import predicates as PR
        from .expressions.conditional import Coalesce

        visible = list(df._plan.output)  # pre-join schema for SELECT *
        subs = []
        for e in ([it.expr for it in stmt.items
                   if isinstance(it.expr, Expression)]
                  + ([stmt.where] if stmt.where is not None else [])):
            subs.extend(e.collect(
                lambda x: isinstance(x, ScalarSubquery)))
        replacements = {}
        by_semantic = {}  # ReuseSubquery: identical subqueries share a join
        for sq in subs:
            if id(sq) in replacements:
                continue
            q = sq.stmt
            if not isinstance(q, SelectStmt):
                raise SqlParseError(
                    "correlated scalar subquery must be a simple SELECT")
            corr_pairs, inner_conj, _ = self._split_correlation(
                q, "correlated scalar subquery")
            if not corr_pairs:
                # the evaluation pass only leaves a node here when it saw
                # outer references SOMEWHERE (items/where/having); with no
                # WHERE equality to decorrelate on, reject cleanly
                raise SqlParseError(
                    "correlated scalar subquery must correlate through "
                    "AND-connected equality predicates in its WHERE "
                    "clause (correlation in the SELECT list or HAVING "
                    "has no join rewrite)")
            if len(q.items) != 1 or isinstance(q.items[0].expr, Star):
                raise SqlParseError(
                    "scalar subquery must select exactly one expression")
            item = q.items[0].expr
            if not _has_agg(item):
                raise SqlParseError(
                    "correlated scalar subquery must be an aggregate "
                    "(that is what guarantees one value per outer row); "
                    "rewrite other shapes as a join")
            if q.group_by or q.group_by_mode or q.having is not None \
                    or q.limit is not None or q.offset:
                raise SqlParseError(
                    "correlated scalar subquery supports a single "
                    "aggregate over AND-connected equality correlation "
                    "only (no GROUP BY/HAVING/LIMIT)")
            sem = _subquery_semantic_key(q)
            if sem is not None and sem in by_semantic:
                replacements[id(sq)] = by_semantic[sem]
                continue
            is_count = _count_only_agg(item)
            if _has_count(item) and not is_count:
                raise SqlParseError(
                    "COUNT inside a compound correlated scalar subquery "
                    "is not supported (empty groups would need per-outer-"
                    "row evaluation); use a plain count(...) subquery")
            key_items = [SelectItem(ie, f"__ck{i}")
                         for i, (_, ie) in enumerate(corr_pairs)]
            q2 = dataclasses.replace(
                q, where=_and_all(inner_conj),
                items=key_items + [SelectItem(item, "__sval")],
                group_by=[ie for _, ie in corr_pairs],
                order_by=[], distinct=False, limit=None, offset=None)
            inner = self._fresh(self._build_sub(q2, ctes))
            out = inner._plan.output
            keys, val = out[:len(corr_pairs)], out[len(corr_pairs)]
            cond = None
            for (oe, _), k in zip(corr_pairs, keys):
                o = _resolve_or_err(self._bind_quals(oe, scope), df._plan)
                term = PR.EqualTo(o, k)
                cond = term if cond is None else PR.And(cond, term)
            df = df.join(inner, on=Column(cond), how="left")
            rep: Expression = val
            if is_count:
                # the COUNT bug: an empty correlation group has no row in
                # the grouped subquery, but count() over it must be 0
                rep = Coalesce(val, Literal(0))
            replacements[id(sq)] = rep
            if sem is not None:
                by_semantic[sem] = rep
        if not replacements:
            return df, stmt, None

        def repl(x):
            return replacements.get(id(x))

        def item_sub(it):
            if isinstance(it.expr, Star):
                return it
            new = it.expr.transform(repl)
            if it.alias is None and isinstance(it.expr, ScalarSubquery) \
                    and new is not it.expr:
                # Spark names an unaliased scalar subquery column
                # scalarsubquery(); never leak the internal __sval name
                new = Alias(new, "scalarsubquery()")
            return SelectItem(new, it.alias)

        stmt = dataclasses.replace(
            stmt,
            items=[item_sub(it) for it in stmt.items],
            where=(stmt.where.transform(repl)
                   if stmt.where is not None else None))
        return df, stmt, visible

    def _apply_subquery_predicate(self, df, pred, negated: bool,
                                  scope, ctes):
        """Rewrite one EXISTS/IN subquery predicate into a semi/anti join
        (Spark's RewritePredicateSubquery)."""
        from . import functions as F
        from .dataframe import Column
        from .expressions import predicates as PR

        if isinstance(pred, InSubquery):
            inner = self._fresh(self._build_sub(pred.stmt, ctes))
            if len(inner._plan.output) != 1:
                raise SqlParseError(
                    "IN subquery must return exactly one column")
            key = Column(inner._plan.output[0])
            needle = Column(_resolve_or_err(pred.children[0], df._plan))
            if not negated:
                return df.join(inner, on=needle == key, how="left_semi")
            # null-aware NOT IN (3-valued logic): a null needle is
            # disqualified only when the subquery has rows (empty set:
            # NOT IN is TRUE even for null); ANY null in the subquery
            # result disqualifies every row
            df = df.join(inner.limit(1), on=needle.isNull(),
                         how="left_anti")
            nonnull = inner.filter(key.isNotNull())
            df = df.join(nonnull,
                         on=needle == Column(nonnull._plan.output[0]),
                         how="left_anti")
            nulls = inner.filter(key.isNull()).limit(1)
            return df.join(nulls, on=F.lit(True), how="left_anti")

        # EXISTS: extract equality correlation (inner.col = outer.col via
        # outer-alias-qualified references) into join keys
        q = pred.stmt
        corr_pairs, inner_conj, mixed = self._split_correlation(
            q, "correlated EXISTS", allow_mixed=True)
        if corr_pairs or mixed:
            import dataclasses
            if q.group_by or q.having is not None or q.group_by_mode:
                raise SqlParseError(
                    "correlated EXISTS with GROUP BY/HAVING is not "
                    "supported — aggregate in a FROM subquery instead")
            # LIMIT/OFFSET in a correlated EXISTS are per-OUTER-row in SQL
            # semantics; after decorrelation they would apply globally and
            # drop join keys.  LIMIT n>0 is a no-op for EXISTS; LIMIT 0
            # means the subquery is always empty.
            if q.offset:
                raise SqlParseError(
                    "correlated EXISTS with OFFSET is not supported (it "
                    "is per-outer-row and has no join rewrite)")
            limit = q.limit
            # mixed conjuncts (non-equality correlation, TPC-H q21): lift
            # each maximal inner-only subexpression into the projection
            # and fold the rewritten predicate into the join's residual
            # condition — the same plan Spark builds (semi/anti hash join
            # with an extra non-equi condition)
            units: list = []
            mixed_rw = [self._rewrite_mixed_conjunct(c, q, units)
                        for c in mixed]
            q2 = dataclasses.replace(
                q,
                where=_and_all(inner_conj),
                items=[SelectItem(ie, f"__corr{i}")
                       for i, (_, ie) in enumerate(corr_pairs)]
                + [SelectItem(u, f"__nq{i}")
                   for i, u in enumerate(units)],
                order_by=[], distinct=False, limit=None, offset=None)
            if limit is not None and limit <= 0:
                return df.filter(F.lit(negated))
            inner = self._fresh(self._build_sub(q2, ctes))
            unit_outs = inner._plan.output[len(corr_pairs):
                                           len(corr_pairs) + len(units)]
            cond = None
            for i, (oe, _) in enumerate(corr_pairs):
                outer_col = Column(_resolve_or_err(
                    self._bind_quals(oe, scope), df._plan))
                term = outer_col == Column(inner._plan.output[i])
                cond = term if cond is None else cond & term
            for c in mixed_rw:
                bound = c.transform(
                    lambda x: unit_outs[x.idx]
                    if isinstance(x, _InnerUnit) else None)
                term = Column(_resolve_or_err(
                    self._bind_quals(bound, scope), df._plan))
                cond = term if cond is None else cond & term
        else:
            # existence is decided by ONE surviving row
            inner = self._fresh(self._build_sub(q, ctes).limit(1))
            cond = F.lit(True)
        return df.join(inner, on=cond,
                       how="left_anti" if negated else "left_semi")

    def _apply_embedded_subqueries(self, df, conjuncts, scope, ctes):
        """[NOT] IN / EXISTS predicates nested under OR/CASE: the
        existence-join rewrite (Spark's RewritePredicateSubquery
        ExistenceJoin form, reference ``ExistenceJoin.scala``).  Each
        subquery contributes marker columns — a LEFT OUTER join against
        its DISTINCT keys plus, for null-aware IN, a one-row aggregate of
        (count(*), count(key)) cross-joined in — and the predicate node
        is replaced by a boolean expression over the markers with exact
        three-valued semantics:

            IN  =  TRUE   when a key matched
                   FALSE  when the subquery is empty
                   NULL   when the needle is null, or no match and the
                          subquery result contains a null
                   FALSE  otherwise

        so ``NOT (x IN (...))`` filters correctly too.  The helper
        columns are projected away after the filter, restoring the
        pre-rewrite schema."""
        from . import functions as F
        from . import plan as P
        from .dataframe import Column, DataFrame

        visible = tuple(df._plan.output)
        k_counter = [0]

        def attr_by_name(frame, name):
            for a in frame._plan.output:
                if a.name == name:
                    return Column(a)
            raise AssertionError(name)

        def rewrite(e: Expression) -> Expression:
            nonlocal df
            if isinstance(e, InSubquery):
                k = k_counter[0]
                k_counter[0] += 1
                inner = self._fresh(self._build_sub(e.stmt, ctes))
                if len(inner._plan.output) != 1:
                    raise SqlParseError(
                        "IN subquery must return exactly one column")
                key = Column(inner._plan.output[0])
                needle = Column(_resolve_or_err(
                    self._bind_quals(e.children[0], scope), df._plan))
                keys = inner.select(key.alias(f"__exk{k}"),
                                    F.lit(True).alias(f"__exm{k}")
                                    ).distinct()
                flags = inner.agg(
                    F.count(F.lit(1)).alias(f"__exc{k}"),
                    F.count(key).alias(f"__exn{k}"))
                df = df.join(
                    keys, on=needle == Column(keys._plan.output[0]),
                    how="left")
                df = df.crossJoin(flags)
                m = attr_by_name(df, f"__exm{k}")
                cnt = attr_by_name(df, f"__exc{k}")
                cntk = attr_by_name(df, f"__exn{k}")
                null_b = F.lit(None).cast("boolean")
                val = (F.when(m.isNotNull(), F.lit(True))
                       .when(cnt == 0, F.lit(False))
                       .when(needle.isNull(), null_b)
                       .when(cnt > cntk, null_b)
                       .otherwise(F.lit(False)))
                return val.expr
            if isinstance(e, ExistsSubquery):
                k = k_counter[0]
                k_counter[0] += 1
                q = e.stmt
                corr_pairs, inner_conj, mixed = self._split_correlation(
                    q, "correlated EXISTS", allow_mixed=True)
                if mixed:
                    raise SqlParseError(
                        "non-equality-correlated EXISTS is only supported "
                        "as an AND-connected top-level WHERE predicate")
                if corr_pairs:
                    import dataclasses
                    if q.group_by or q.having is not None \
                            or q.group_by_mode:
                        raise SqlParseError(
                            "correlated EXISTS with GROUP BY/HAVING is "
                            "not supported — aggregate in a FROM "
                            "subquery instead")
                    # LIMIT/OFFSET are per-OUTER-row in a correlated
                    # EXISTS; after decorrelation they would apply
                    # globally and drop join keys (same guard as the
                    # top-level rewrite above).  LIMIT n>0 is a no-op for
                    # EXISTS; LIMIT <=0 makes the subquery always empty,
                    # so the marker is constant FALSE.
                    if q.offset:
                        raise SqlParseError(
                            "correlated EXISTS with OFFSET is not "
                            "supported (it is per-outer-row and has no "
                            "join rewrite)")
                    if q.limit is not None and q.limit <= 0:
                        return F.lit(False).expr
                    q2 = dataclasses.replace(
                        q, where=_and_all(inner_conj),
                        items=[SelectItem(ie, f"__exq{k}_{i}")
                               for i, (_, ie) in enumerate(corr_pairs)],
                        order_by=[], distinct=False, limit=None,
                        offset=None)
                    inner = self._fresh(self._build_sub(q2, ctes))
                    keys = inner.select(
                        *[Column(a).alias(f"__exk{k}_{i}")
                          for i, a in enumerate(inner._plan.output)],
                        F.lit(True).alias(f"__exm{k}")).distinct()
                    cond = None
                    for i, (oe, _) in enumerate(corr_pairs):
                        outer_col = Column(_resolve_or_err(
                            self._bind_quals(oe, scope), df._plan))
                        term = outer_col == Column(keys._plan.output[i])
                        cond = term if cond is None else cond & term
                    df = df.join(keys, on=cond, how="left")
                    return attr_by_name(df, f"__exm{k}").isNotNull().expr
                flags = self._fresh(self._build_sub(q, ctes)).limit(1).agg(
                    F.count(F.lit(1)).alias(f"__exc{k}"))
                df = df.crossJoin(flags)
                return (attr_by_name(df, f"__exc{k}") > 0).expr
            if not e.children:
                return e
            return e.with_children(tuple(rewrite(c) for c in e.children))

        new_cond = _and_all([rewrite(c) for c in conjuncts])
        df = DataFrame(P.Filter(_resolve_or_err(new_cond, df._plan),
                                df._plan), self.session)
        return DataFrame(P.Project(visible, df._plan), self.session)

    def _plan_comma_joins(self, stmt: "SelectStmt", ctes, scope):
        """Join planning for a pure comma/CROSS FROM list — the analog of
        Spark's PushPredicateThroughJoin + ReorderJoin, which run before
        the reference plugin sees the plan (its GpuShuffledHashJoinExec
        receives already-planned equi joins).

        Splits the WHERE into conjuncts; pushes single-relation ones
        beneath the joins; uses multi-relation conjuncts as inner-join
        conditions, joining relations in connected order (greedy, driven
        by equality conjuncts) so no unfiltered cross product ever
        materializes; anything unplaceable (subquery predicates,
        ambiguous references) stays in the residual WHERE.  Returns
        (joined df, stmt with the consumed conjuncts removed)."""
        import dataclasses

        from . import plan as P
        from .dataframe import Column, DataFrame
        from .expressions import predicates as PR
        from .functions import _UnresolvedAttribute

        rels: List[str] = []

        def add(ref):
            rdf, ralias = self._resolve_relation(ref, ctes)
            key = ralias.lower()
            if key in scope:
                raise SqlParseError(f"duplicate relation alias {ralias!r}")
            scope[key] = rdf
            rels.append(key)

        add(stmt.from_)
        for step in stmt.joins:
            add(step.right)

        col_owners: Dict[str, set] = {}
        for a in rels:
            for attr in scope[a]._plan.output:
                col_owners.setdefault(attr.name.lower(), set()).add(a)

        def conj_aliases(c):
            """Relations a conjunct references, or None when a reference
            cannot be attributed to exactly one relation (unknown alias,
            ambiguous or missing bare name) — those conjuncts stay in
            the residual WHERE where normal resolution reports errors."""
            out = set()
            for n in c.collect(lambda x: isinstance(
                    x, (UnresolvedQualified, _UnresolvedAttribute))):
                if isinstance(n, UnresolvedQualified):
                    if n.qualifier.lower() not in scope:
                        return None
                    out.add(n.qualifier.lower())
                else:
                    owners = col_owners.get(n.name.lower(), set())
                    if len(owners) != 1:
                        return None
                    out.add(next(iter(owners)))
            return out

        # the _build_select WHERE guards run only on the residual; pushed
        # conjuncts must fail just as cleanly here
        if stmt.where is not None:
            if _has_agg(stmt.where):
                raise SqlParseError(
                    "aggregate functions are not allowed in WHERE")
            if _has_window(stmt.where):
                raise SqlParseError(
                    "window functions are not allowed in WHERE")

        residual: List[Expression] = []
        singles: Dict[str, List[Expression]] = {a: [] for a in rels}
        multis: List[Tuple[Expression, set]] = []
        conjs = _split_and(stmt.where) if stmt.where is not None else []
        for c in conjs:
            if c.collect(lambda x: isinstance(
                    x, (ExistsSubquery, InSubquery, ScalarSubquery))):
                residual.append(c)
                continue
            al = conj_aliases(c)
            if not al:
                residual.append(c)
            elif len(al) == 1:
                singles[next(iter(al))].append(c)
            else:
                multis.append((c, al))

        for a in rels:
            if singles[a]:
                rel = scope[a]
                pred = None
                for c in singles[a]:
                    b = _resolve_or_err(self._bind_quals(c, scope),
                                        rel._plan)
                    pred = b if pred is None else PR.And(pred, b)
                # Filter preserves the child's output attributes, so
                # join conditions bound against the unfiltered plan stay
                # valid
                scope[a] = DataFrame(P.Filter(pred, rel._plan),
                                     self.session)

        joined = {rels[0]}
        df = scope[rels[0]]
        remaining = rels[1:]
        used = [False] * len(multis)
        while remaining:
            pick = None
            for want_eq in (True, False):
                for a in remaining:
                    if any(not used[i] and a in al
                           and al <= joined | {a}
                           and (isinstance(c, PR.EqualTo) or not want_eq)
                           for i, (c, al) in enumerate(multis)):
                        pick = a
                        break
                if pick is not None:
                    break
            connected = pick is not None
            if pick is None:
                pick = remaining[0]
            conds = []
            for i, (c, al) in enumerate(multis):
                if not used[i] and al <= joined | {pick}:
                    used[i] = True
                    conds.append(self._bind_quals(c, scope))
            rdf = scope[pick]
            if connected and conds:
                cond = conds[0]
                for c in conds[1:]:
                    cond = PR.And(cond, c)
                df = df.join(rdf, on=Column(cond), how="inner")
            else:
                df = df.crossJoin(rdf)
                for c in conds:  # subset-covered but disconnected
                    df = df.filter(Column(c))
            joined.add(pick)
            remaining = [a for a in remaining if a != pick]

        residual.extend(c for i, (c, _) in enumerate(multis)
                        if not used[i])
        # SELECT * must see columns in FROM-list order (SQL), not the
        # greedy join order — restore it with a (free) projection
        ordered = tuple(a for r in rels for a in scope[r]._plan.output)
        if ordered != tuple(df._plan.output):
            df = DataFrame(P.Project(ordered, df._plan), self.session)
        return df, dataclasses.replace(stmt, where=_and_all(residual)
                                       if residual else None)

    # --- SELECT -----------------------------------------------------------
    def _build_select(self, stmt: SelectStmt, ctes):
        from . import plan as P
        from .dataframe import Column, DataFrame

        stmt = self._eval_scalar_subqueries_stmt(stmt, ctes)
        for slot, e in ([("SELECT list", it.expr) for it in stmt.items]
                        + [("HAVING", stmt.having)]
                        + [("GROUP BY", g) for g in stmt.group_by]
                        + [("join condition", j.on) for j in stmt.joins]
                        + [("GROUPING SETS", g)
                           for s in stmt.grouping_sets_raw for g in s]
                        + [("ORDER BY", oi.expr) for oi in stmt.order_by]):
            if isinstance(e, Expression) and e.collect(
                    lambda x: isinstance(x, (ExistsSubquery, InSubquery))):
                raise SqlParseError(
                    f"EXISTS/IN subqueries are not supported in the {slot}"
                    " — only as AND-connected WHERE predicates")
        for j in stmt.joins:
            if isinstance(j.on, Expression) and j.on.collect(
                    lambda x: isinstance(x, ScalarSubquery)):
                raise SqlParseError(
                    "correlated scalar subqueries are only supported in "
                    "the WHERE clause and SELECT list (found in join "
                    "condition)")
        scope: Dict[str, Any] = {}      # alias -> DataFrame
        if stmt.from_ is None:
            df = self.session.range(1)
        elif stmt.joins and all(s.how == "cross" and s.on is None
                                and not s.using for s in stmt.joins):
            # comma-FROM (`FROM a, b, c WHERE ...`) — the TPC-H query
            # texts' surface.  Naive left-to-right cross joins explode
            # (part x supplier x partsupp x nation x region before any
            # filter); plan them instead (see _plan_comma_joins).
            df, stmt = self._plan_comma_joins(stmt, ctes, scope)
        else:
            df, alias = self._resolve_relation(stmt.from_, ctes)
            scope[alias.lower()] = df
            for step in stmt.joins:
                rdf, ralias = self._resolve_relation(step.right, ctes)
                if ralias.lower() in scope:
                    raise SqlParseError(
                        f"duplicate relation alias {ralias!r}")
                scope[ralias.lower()] = rdf
                if step.using:
                    df = df.join(rdf, on=list(step.using), how=step.how)
                elif step.on is not None:
                    cond = self._bind_quals(step.on, scope)
                    df = df.join(rdf, on=Column(cond), how=step.how)
                else:
                    if step.how not in ("cross", "inner"):
                        raise SqlParseError(
                            f"{step.how} join requires ON or USING")
                    df = df.crossJoin(rdf)

        for lv in stmt.lateral_views:
            df = self._apply_lateral_view(df, lv, scope)
        df, stmt, star_visible = self._decorrelate_scalar_subqueries(
            df, stmt, scope, ctes)
        for slot, e in ([("HAVING", stmt.having)]
                        + [("GROUP BY", g) for g in stmt.group_by]
                        + [("join condition", j.on) for j in stmt.joins]
                        + [("GROUPING SETS", g)
                           for sset in stmt.grouping_sets_raw for g in sset]
                        + [("ORDER BY", oi.expr) for oi in stmt.order_by]):
            if isinstance(e, Expression) and e.collect(
                    lambda x: isinstance(x, ScalarSubquery)):
                raise SqlParseError(
                    "correlated scalar subqueries are only supported in "
                    f"the WHERE clause and SELECT list (found in {slot})")

        if stmt.where is not None:
            cond = self._bind_quals(stmt.where, scope)
            if _has_agg(cond):
                raise SqlParseError(
                    "aggregate functions are not allowed in WHERE")
            if _has_window(cond):
                raise SqlParseError(
                    "window functions are not allowed in WHERE")
            plain, sub_preds, embedded = _split_subquery_predicates(cond)
            if plain is not None:
                df = DataFrame(P.Filter(_resolve_or_err(plain, df._plan),
                                        df._plan), self.session)
            for pred, negated in sub_preds:
                df = self._apply_subquery_predicate(df, pred, negated,
                                                    scope, ctes)
            if embedded:
                df = self._apply_embedded_subqueries(df, embedded, scope,
                                                     ctes)

        # resolve select list against the (joined, filtered) frame
        items: List[Tuple[str, Expression]] = []
        for it in stmt.items:
            if isinstance(it.expr, Star):
                if it.expr.qualifier is not None:
                    src = scope.get(it.expr.qualifier.lower())
                    if src is None:
                        raise SqlParseError(
                            f"unknown relation {it.expr.qualifier!r} "
                            "for qualified star")
                    for a in src._plan.output:
                        items.append((a.name, a))
                else:
                    # a decorrelation join widened df with internal
                    # __ck*/__sval columns; * sees the pre-join schema
                    for a in (star_visible if star_visible is not None
                              else df._plan.output):
                        items.append((a.name, a))
                continue
            e = self._bind_quals(it.expr, scope)
            e = _resolve_or_err(e, df._plan)
            items.append((it.alias or _auto_name(it.expr, e), e))

        having = None
        if stmt.having is not None:
            having = _resolve_or_err(self._bind_quals(stmt.having, scope),
                                     df._plan)

        aggregating = bool(stmt.group_by) or having is not None or \
            any(_has_agg(e) for _, e in items)

        pre_orders = None
        if aggregating:
            df, items, pre_orders = self._build_aggregate(
                df, stmt, items, having, scope)
        return self._finish(df, items, stmt, scope, pre_orders)

    # --- aggregation ------------------------------------------------------
    def _build_aggregate(self, df, stmt, items, having, scope):
        from . import plan as P
        from .dataframe import DataFrame, _resolve_expr

        # group expressions: ordinals, select aliases, or raw expressions
        def resolve_group(g) -> Expression:
            if isinstance(g, int):
                if not (1 <= g <= len(items)):
                    raise SqlParseError(
                        f"GROUP BY position {g} is out of range")
                ge = items[g - 1][1]
            else:
                ge = self._bind_quals(g, scope)
                try:
                    ge = _resolve_expr(ge, df._plan)
                except KeyError:
                    # select-list alias (GROUP BY alias) — Spark resolves
                    # the child column first, the alias second
                    name = ge.sql().lower() if not isinstance(
                        ge, AttributeReference) else ge.name.lower()
                    match = [e for n, e in items if n.lower() == name]
                    if not match:
                        raise SqlParseError(
                            f"cannot resolve GROUP BY expression "
                            f"{g.sql()!r}") from None
                    ge = match[0]
            if _has_agg(ge):
                raise SqlParseError(
                    "aggregate functions are not allowed in GROUP BY")
            return ge

        # base keys (GROUP BY a, ... before/around any construct) are
        # included in EVERY grouping set (Spark 3 partial grouping sets)
        groups: List[Expression] = [resolve_group(g) for g in stmt.group_by]
        base_idx = frozenset(range(len(groups)))
        explicit_sets = None
        if stmt.group_by_mode:
            from .dataframe import cube_sets, rollup_sets
            keys_seen: Dict[Tuple, int] = {
                g.semantic_key(): i for i, g in enumerate(groups)}

            def key_index(ge: Expression) -> int:
                k = ge.semantic_key()
                if k not in keys_seen:
                    keys_seen[k] = len(groups)
                    groups.append(ge)
                return keys_seen[k]

            if stmt.group_by_mode == "sets":
                # GROUPING SETS ((a,b),(a),()) — keys = union of the sets
                # in first-appearance order; each set selects positions
                explicit_sets = [
                    base_idx | frozenset(key_index(resolve_group(g))
                                         for g in raw)
                    for raw in stmt.grouping_sets_raw]
            else:
                cidx = [key_index(resolve_group(g))
                        for g in stmt.grouping_sets_raw[0]]
                subs = rollup_sets(len(cidx)) \
                    if stmt.group_by_mode == "rollup" else cube_sets(len(cidx))
                explicit_sets = [
                    base_idx | frozenset(cidx[i] for i in s) for s in subs]

        group_keys = [g.semantic_key() for g in groups]
        group_outs: List[Expression] = []
        group_attrs: List[AttributeReference] = []
        gid_out = None
        resolve_marks = None
        if stmt.group_by_mode:
            # shared Expand lowering + grouping()/grouping_id() marker
            # resolution (dataframe.grouping_sets_expand)
            from .dataframe import grouping_mark_resolver, grouping_sets_expand
            expanded, gkeys, (pos_attr, gid_attr) = grouping_sets_expand(
                df._plan, tuple(groups), explicit_sets)
            df = DataFrame(expanded, self.session)
            resolve_marks = grouping_mark_resolver(tuple(groups), gid_attr)
            items = [(n, e.transform(resolve_marks)) for n, e in items]
            if having is not None:
                having = having.transform(resolve_marks)
            for i, g in enumerate(groups):
                name = g.name if isinstance(g, AttributeReference) \
                    else f"__group_{i}"
                a = Alias(gkeys[i], name)
                group_outs.append(a)
                group_attrs.append(a.to_attribute())
            groups = list(gkeys) + [pos_attr, gid_attr]
            gid_out = gid_attr
        else:
            for i, g in enumerate(groups):
                if isinstance(g, AttributeReference):
                    group_outs.append(g)
                    group_attrs.append(g)
                else:
                    a = Alias(g, f"__group_{i}")
                    group_outs.append(a)
                    group_attrs.append(a.to_attribute())

        agg_aliases: Dict[Tuple, Alias] = {}

        def strip(e: Expression) -> Expression:
            for key, attr in zip(group_keys, group_attrs):
                if e.semantic_key() == key:
                    return attr
            if isinstance(e, WindowExpression):
                # windows evaluate AFTER aggregation (Spark's
                # ExtractWindowExpressions over an Aggregate): the window
                # node stays in the post-agg projection; its function's
                # OWN aggregate is the window computation, while nested
                # aggregates and group keys inside it resolve against the
                # Aggregate output (avg(sum(x)) OVER (PARTITION BY
                # grouping(k), ...) — the spec-TPC-DS idiom)
                def strip_fn(fn: Expression) -> Expression:
                    if isinstance(fn, AggregateExpression):
                        return fn.with_children(
                            tuple(strip_fn(c) for c in fn.children))
                    if isinstance(fn, AggregateFunction):
                        return fn.with_children(
                            tuple(strip(c) for c in fn.children))
                    return strip(fn)
                rest = tuple(strip(c) for c in e.children[1:])
                return e.with_children((strip_fn(e.children[0]),) + rest)
            if isinstance(e, (AggregateFunction, AggregateExpression)):
                key = e.semantic_key()
                if key not in agg_aliases:
                    agg_aliases[key] = Alias(e, f"__agg_{len(agg_aliases)}")
                return agg_aliases[key].to_attribute()
            if not e.children:
                return e
            return e.with_children(tuple(strip(c) for c in e.children))

        new_items = [(name, strip(e)) for name, e in items]
        if having is not None and _has_window(having):
            raise SqlParseError(
                "window functions are not allowed in HAVING")
        new_having = strip(having) if having is not None else None

        # ORDER BY must be stripped BEFORE the Aggregate plan is frozen so
        # aggregates that appear only in the sort (ORDER BY sum(x)) get
        # buffer slots too
        pre_orders: List[SortOrder] = []
        out_by_name = {n.lower(): e for n, e in reversed(new_items)}
        for oi in stmt.order_by:
            if isinstance(oi.expr, int):
                if not (1 <= oi.expr <= len(new_items)):
                    raise SqlParseError(
                        f"ORDER BY position {oi.expr} is out of range")
                target = new_items[oi.expr - 1][1]
            else:
                e = oi.expr
                if isinstance(e, AttributeReference) and getattr(
                        e, "_unresolved", False) and \
                        e.name.lower() in out_by_name:
                    target = out_by_name[e.name.lower()]
                else:
                    target = _resolve_or_err(
                        self._bind_quals(e, scope), df._plan)
                    if resolve_marks is not None:
                        # ORDER BY grouping_id()/grouping() in rollup/cube
                        target = target.transform(resolve_marks)
                    target = strip(target)
                    ok_ids = {a.expr_id for a in group_attrs}
                    ok_ids.update(al.expr_id for al in agg_aliases.values())
                    if gid_out is not None:
                        ok_ids.add(gid_out.expr_id)
                    for r in target.references():
                        if r.expr_id not in ok_ids:
                            raise SqlParseError(
                                f"ORDER BY column {r.name!r} must appear in "
                                "GROUP BY or be inside an aggregate "
                                "function")
            pre_orders.append(SortOrder(target, oi.ascending,
                                        oi.nulls_first))

        # every remaining column reference must be a group key or an
        # aggregate result
        allowed = {a.expr_id for a in group_attrs}
        allowed.update(al.expr_id for al in agg_aliases.values())
        if gid_out is not None:
            allowed.add(gid_out.expr_id)
        for name, e in new_items:
            for r in e.references():
                if r.expr_id not in allowed:
                    raise SqlParseError(
                        f"column {r.name!r} must appear in GROUP BY or be "
                        "inside an aggregate function")
        if new_having is not None:
            for r in new_having.references():
                if r.expr_id not in allowed:
                    raise SqlParseError(
                        f"HAVING column {r.name!r} must appear in GROUP BY "
                        "or be inside an aggregate function")

        extra = (gid_out,) if gid_out is not None else ()
        plan = P.Aggregate(tuple(groups),
                           tuple(group_outs) + extra
                           + tuple(agg_aliases.values()),
                           df._plan)
        adf = DataFrame(plan, self.session)
        if new_having is not None:
            adf = DataFrame(P.Filter(new_having, adf._plan), self.session)
        return adf, new_items, pre_orders

    # --- ORDER BY / DISTINCT / LIMIT tail ---------------------------------
    def _finish(self, df, items, stmt: SelectStmt, scope,
                pre_orders: Optional[List[SortOrder]] = None):
        from . import plan as P
        from .dataframe import DataFrame, _resolve_expr

        if pre_orders is not None:
            orders = pre_orders
        else:
            orders = []
            out_by_name = {}
            for n, e in items:
                out_by_name.setdefault(n.lower(), e)
            for oi in stmt.order_by:
                if isinstance(oi.expr, int):
                    if not (1 <= oi.expr <= len(items)):
                        raise SqlParseError(
                            f"ORDER BY position {oi.expr} is out of range")
                    target = items[oi.expr - 1][1]
                else:
                    e = oi.expr
                    name = e.name.lower() if isinstance(
                        e, AttributeReference) and getattr(
                        e, "_unresolved", False) else None
                    if name is not None and name in out_by_name:
                        target = out_by_name[name]
                    else:
                        target = _resolve_or_err(self._bind_quals(e, scope),
                                                 df._plan)
                orders.append(SortOrder(target, oi.ascending,
                                        oi.nulls_first))

        project_exprs = tuple(
            e if (isinstance(e, AttributeReference) and e.name == n)
            else Alias(e, n)
            for n, e in items)
        out_attrs = [pe if isinstance(pe, AttributeReference)
                     else pe.to_attribute() for pe in project_exprs]

        def make_project(exprs, plan):
            # same window/generator extraction hook as DataFrame.select
            from .dataframe import _extract_generators, _extract_windows
            exprs, plan = _extract_generators(tuple(exprs), plan)
            exprs, plan = _extract_windows(tuple(exprs), plan)
            return P.Project(tuple(exprs), plan)

        # rewrite order targets that exactly match a projected expression
        # to reference the projected output (post-projection sort)
        def to_output(e: Expression) -> Optional[Expression]:
            for pe, attr in zip(project_exprs, out_attrs):
                src = pe.child if isinstance(pe, Alias) else pe
                if e.semantic_key() == src.semantic_key():
                    return attr
            return None

        sortable_post = []
        needs_hidden = False
        for so in orders:
            mapped = to_output(so.child)
            if mapped is not None:
                sortable_post.append(SortOrder(mapped, so.ascending,
                                               so.nulls_first))
            else:
                needs_hidden = True
                sortable_post.append(so)

        if stmt.distinct and needs_hidden:
            raise SqlParseError(
                "ORDER BY with SELECT DISTINCT must reference select-list "
                "expressions")

        if not needs_hidden:
            result = DataFrame(make_project(project_exprs, df._plan),
                               self.session)
            if stmt.distinct:
                result = result.distinct()
            if sortable_post:
                result = DataFrame(
                    P.Sort(tuple(sortable_post), True, result._plan),
                    self.session)
        else:
            # project select list + hidden sort keys, sort, project away
            hidden = []
            full_orders = []
            for so in sortable_post:
                if any(so.child.semantic_key() == a.semantic_key()
                       for a in out_attrs):
                    full_orders.append(so)
                    continue
                h = Alias(so.child, f"__sort_{len(hidden)}")
                hidden.append(h)
                full_orders.append(SortOrder(h.to_attribute(), so.ascending,
                                             so.nulls_first))
            wide = DataFrame(
                make_project(project_exprs + tuple(hidden), df._plan),
                self.session)
            sorted_df = DataFrame(P.Sort(tuple(full_orders), True,
                                         wide._plan), self.session)
            result = DataFrame(P.Project(tuple(out_attrs), sorted_df._plan),
                               self.session)

        if stmt.offset:
            lim = stmt.limit if stmt.limit is not None else (1 << 30)
            result = DataFrame(P.Limit(lim, stmt.offset, result._plan),
                               self.session)
        elif stmt.limit is not None:
            result = result.limit(stmt.limit)
        return result

    def _apply_order_limit(self, df, order_by, limit, offset, items):
        from . import plan as P
        from .dataframe import DataFrame
        if order_by:
            orders = []
            attrs = df._plan.output
            for oi in order_by:
                if isinstance(oi.expr, int):
                    if not (1 <= oi.expr <= len(attrs)):
                        raise SqlParseError(
                            f"ORDER BY position {oi.expr} is out of range")
                    target: Expression = attrs[oi.expr - 1]
                else:
                    target = _resolve_or_err(oi.expr, df._plan)
                orders.append(SortOrder(target, oi.ascending,
                                        oi.nulls_first))
            df = DataFrame(P.Sort(tuple(orders), True, df._plan),
                           self.session)
        if offset:
            lim = limit if limit is not None else (1 << 30)
            df = DataFrame(P.Limit(lim, offset, df._plan), self.session)
        elif limit is not None:
            df = df.limit(limit)
        return df

    # --- qualified-name binding ------------------------------------------
    def _bind_quals(self, e: Expression, scope) -> Expression:
        if isinstance(e, Star):
            raise SqlParseError("'*' is only valid in a select list")

        def walk(node: Expression) -> Expression:
            if isinstance(node, UnresolvedQualified):
                src = scope.get(node.qualifier.lower())
                if src is None:
                    raise SqlParseError(
                        f"unknown relation alias {node.qualifier!r} "
                        f"(known: {sorted(scope)})")
                for a in src._plan.output:
                    if a.name.lower() == node.name.lower():
                        return a
                raise SqlParseError(
                    f"column {node.name!r} not found in relation "
                    f"{node.qualifier!r}")
            if not node.children:
                return node
            return node.with_children(tuple(walk(c) for c in node.children))
        return walk(e)


def _as_string(e: Expression) -> Expression:
    """Implicit cast for the ``||`` operator (Spark casts both concat
    operands to string).  Unresolved refs keep the cast — string->string
    casting is the identity."""
    from .expressions.cast import Cast
    try:
        if e.data_type == T.STRING:
            return e
    except (NotImplementedError, SqlParseError):
        pass
    return Cast(e, T.STRING)


def _resolve_or_err(e: Expression, plan) -> Expression:
    """Name resolution with the module's error contract (SqlParseError,
    never a bare KeyError)."""
    from .dataframe import _resolve_expr
    try:
        return _resolve_expr(e, plan)
    except KeyError as exc:
        raise SqlParseError(str(exc.args[0]) if exc.args else str(exc)) \
            from None


def _split_and(e: Expression) -> List[Expression]:
    """Flatten a conjunction tree into its AND-connected conjuncts."""
    from .expressions.predicates import And
    if isinstance(e, And):
        return _split_and(e.children[0]) + _split_and(e.children[1])
    return [e]


def _and_all(conjuncts: Sequence[Expression]) -> Optional[Expression]:
    from .expressions.predicates import And
    out = None
    for c in conjuncts:
        out = c if out is None else And(out, c)
    return out


def _split_subquery_predicates(cond: Expression):
    """(plain_condition_or_None, [(marker, negated)], [embedded]) from a
    WHERE tree.  Top-level AND-connected markers get the efficient
    semi/anti join rewrite; conjuncts with subqueries embedded deeper
    (under OR, inside CASE/NOT) go to ``embedded`` for the existence-join
    rewrite (reference ``ExistenceJoin.scala``)."""
    from .expressions.predicates import Not
    plain: List[Expression] = []
    subs = []
    embedded: List[Expression] = []
    for c in _split_and(cond):
        inner = c.children[0] if isinstance(c, Not) else c
        if isinstance(inner, (ExistsSubquery, InSubquery)):
            subs.append((inner, isinstance(c, Not)))
            continue
        if c.collect(lambda x: isinstance(x, (ExistsSubquery, InSubquery))):
            embedded.append(c)
            continue
        plain.append(c)
    return _and_all(plain), subs, embedded


def _has_window(e: Expression) -> bool:
    return bool(e.collect(lambda n: isinstance(n, WindowExpression)))


def _has_agg(e: Expression) -> bool:
    """True if e contains a grouping aggregate (sum() OVER (...) is a
    window computation, not an aggregation — don't descend into specs)."""
    if isinstance(e, WindowExpression):
        return False
    if isinstance(e, (AggregateFunction, AggregateExpression)):
        return True
    return any(_has_agg(c) for c in e.children)


def _subquery_semantic_key(q):
    """Hashable identity for a correlated scalar subquery over simple
    table FROMs (ReuseSubquery analog); None = don't dedupe."""
    rels = []
    refs = ([q.from_] if q.from_ is not None else []) \
        + [j.right for j in q.joins]
    for r in refs:
        if not isinstance(r, TableRef) or r.path is not None:
            return None
        rels.append((r.name.lower(), (r.alias or "").lower()))
    try:
        return (tuple(rels),
                tuple((j.how,
                       j.on.sql() if isinstance(j.on, Expression) else "",
                       tuple(j.using or ()))
                      for j in q.joins),
                tuple(it.alias or "" for it in q.items),
                tuple(it.expr.sql() for it in q.items
                      if isinstance(it.expr, Expression)),
                q.where.sql() if q.where is not None else "")
    except Exception:
        return None


def _has_count(e: Expression) -> bool:
    from .expressions.aggregates import Count
    return bool(e.collect(lambda n: isinstance(n, Count)))


def _count_only_agg(e: Expression) -> bool:
    """e IS a bare count aggregate (possibly wrapped in the
    AggregateExpression distinct marker) — the shape whose empty-group
    result must be 0, not NULL, after decorrelation."""
    from .expressions.aggregates import AggregateExpression, Count
    if isinstance(e, Count):
        return True
    return isinstance(e, AggregateExpression) and isinstance(e.func, Count)


def _auto_name(raw: Expression, resolved: Expression) -> str:
    if isinstance(resolved, AttributeReference):
        return resolved.name
    if isinstance(resolved, Alias):
        return resolved.name
    return raw.sql()


def parse_query(session, sql: str):
    """``session.sql(...)`` entry point."""
    stmt = Parser(sql, udfs=getattr(session, "_hive_udfs", None)
                  ).parse_statement()
    if isinstance(stmt, CreateFunctionStmt):
        if not stmt.replace and stmt.name.lower() in session._hive_udfs:
            raise ValueError(
                f"function {stmt.name!r} already exists (use CREATE OR "
                f"REPLACE TEMPORARY FUNCTION)")
        session.register_hive_function(stmt.name, stmt.class_path)
        return session.create_dataframe(_empty_ddl_result())
    if isinstance(stmt, DropFunctionStmt):
        if session._hive_udfs.pop(stmt.name.lower(), None) is None \
                and not stmt.if_exists:
            raise ValueError(f"function not found: {stmt.name}")
        return session.create_dataframe(_empty_ddl_result())
    if isinstance(stmt, CreateTempViewStmt):
        if not stmt.replace and stmt.name.lower() in session._temp_views:
            raise ValueError(
                f"temp view {stmt.name!r} already exists (use CREATE OR "
                f"REPLACE TEMP VIEW)")
        df = QueryBuilder(session).build(stmt.stmt)
        df.createOrReplaceTempView(stmt.name)
        return session.create_dataframe(_empty_ddl_result())
    if isinstance(stmt, DropViewStmt):
        if session._temp_views.pop(stmt.name.lower(), None) is None \
                and not stmt.if_exists:
            raise ValueError(f"view not found: {stmt.name}")
        return session.create_dataframe(_empty_ddl_result())
    if isinstance(stmt, ShowTablesStmt):
        import pyarrow as pa
        names = sorted(session._temp_views)
        return session.create_dataframe(pa.table({
            "namespace": pa.array([""] * len(names), pa.string()),
            "tableName": pa.array(names, pa.string()),
            "isTemporary": pa.array([True] * len(names), pa.bool_()),
        }))
    if isinstance(stmt, DescribeTableStmt):
        import pyarrow as pa
        # session.table() is THE catalog resolution (same lookup, same
        # error) — don't fork it here
        attrs = session.table(stmt.name)._plan.output
        return session.create_dataframe(pa.table({
            "col_name": pa.array([a.name for a in attrs], pa.string()),
            "data_type": pa.array([a.dtype.simple_string() for a in attrs],
                                  pa.string()),
            "comment": pa.array([None] * len(attrs), pa.string()),
        }))
    return QueryBuilder(session).build(stmt)


def _empty_ddl_result():
    import pyarrow as pa
    return pa.schema([]).empty_table()
