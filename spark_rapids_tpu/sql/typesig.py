"""TypeSig — declarative per-op type-support matrix (reference
``TypeChecks.scala`` 2441 LoC: powers tagging, docs and the tools CSVs).

A TypeSig names which logical types an operator/expression supports on the
accelerator.  Checks produce human-readable reasons used by explain() and
the fallback tagging, exactly like the reference's ``willNotWorkOnGpu``.
"""

from __future__ import annotations

from typing import Iterable, List, Optional, Set, Type

from .. import types as T

_ALL_BASIC = (T.BooleanType, T.ByteType, T.ShortType, T.IntegerType,
              T.LongType, T.FloatType, T.DoubleType, T.StringType,
              T.BinaryType, T.DateType, T.TimestampType, T.DecimalType,
              T.NullType)


class TypeSig:
    def __init__(self, classes: Iterable[type], nested: Optional["TypeSig"] = None,
                 note: str = ""):
        self.classes = tuple(classes)
        self.nested = nested
        self.note = note

    def __add__(self, other: "TypeSig") -> "TypeSig":
        return TypeSig(tuple(set(self.classes + other.classes)),
                       self.nested or other.nested)

    def supports(self, dt: T.DataType) -> Optional[str]:
        """None if supported, else a reason string."""
        if isinstance(dt, T.StructType):
            if T.StructType not in self.classes:
                return f"{dt.simple_string()} is not supported"
            inner = self.nested or self
            for f in dt.fields:
                r = inner.supports(f.data_type)
                if r:
                    return r
            return None
        if isinstance(dt, (T.ArrayType, T.MapType)):
            if type(dt) not in self.classes:
                return f"{dt.simple_string()} is not supported"
            inner = self.nested or self
            if isinstance(dt, T.ArrayType):
                return inner.supports(dt.element_type)
            return (inner.supports(dt.key_type)
                    or inner.supports(dt.value_type))
        if isinstance(dt, self.classes):
            return None
        return f"{dt.simple_string()} is not supported"


def sig(*classes) -> TypeSig:
    return TypeSig(classes)


BOOLEAN = sig(T.BooleanType)
INTEGRAL = sig(T.ByteType, T.ShortType, T.IntegerType, T.LongType)
FP = sig(T.FloatType, T.DoubleType)
DECIMAL = sig(T.DecimalType)
NUMERIC = INTEGRAL + FP + DECIMAL
STRING = sig(T.StringType)
BINARY = sig(T.BinaryType)
DATETIME = sig(T.DateType, T.TimestampType)
NULL = sig(T.NullType)
ORDERABLE = NUMERIC + STRING + DATETIME + BOOLEAN + NULL
COMPARABLE = ORDERABLE
BASIC = TypeSig(_ALL_BASIC)
STRUCT = sig(T.StructType)
# device layout supports arrays/maps of basic (and struct-of-basic) element
# types via the padded row-block layout (columnar/column.py)
_NESTABLE = TypeSig(_ALL_BASIC + (T.StructType, T.ArrayType, T.MapType))
ALL_DEVICE = BASIC + TypeSig((T.StructType, T.ArrayType, T.MapType),
                             nested=_NESTABLE)
# host engine supports everything incl. arrays/maps
EVERYTHING = ALL_DEVICE + TypeSig((T.ArrayType, T.MapType),
                                  nested=TypeSig(_ALL_BASIC + (T.ArrayType,
                                                               T.StructType,
                                                               T.MapType)))


class ExprSig:
    """Per-expression INPUT/OUTPUT type matrices — the shape of the
    reference's per-context TypeChecks rows (``TypeChecks.scala``: each
    ExprChecks declares param and result sigs; tagging, docs and the
    tools CSVs all read the same data)."""

    def __init__(self, input: TypeSig, output: Optional[TypeSig] = None,
                 note: str = ""):
        self.input = input
        self.output = output if output is not None else input
        self.note = note


#: the fallback for expressions with no EXPR_SIGS entry — ONE place
#: defines it, so tagging and the generated docs/CSVs cannot diverge
DEFAULT_EXPR_SIG: "ExprSig" = None  # set below (needs ALL_DEVICE)


#: the documented type categories, in the reference's column order
#: (supported_ops.md), with a representative instance per category used
#: to evaluate a TypeSig into an S/NS matrix row
MATRIX_CATEGORIES = [
    ("BOOLEAN", T.BOOLEAN), ("BYTE", T.BYTE), ("SHORT", T.SHORT),
    ("INT", T.INT), ("LONG", T.LONG), ("FLOAT", T.FLOAT),
    ("DOUBLE", T.DOUBLE), ("DECIMAL", T.DecimalType(18, 2)),
    # 128-bit decimals are a distinct support axis (round 4: chunked
    # int64 device kernels for agg/add/sub/mul/cast; precision-dependent
    # shapes like wide division still tag to the host dynamically)
    ("DECIMAL128", T.DecimalType(38, 6)),
    ("STRING", T.STRING), ("BINARY", T.BINARY), ("DATE", T.DATE),
    ("TIMESTAMP", T.TIMESTAMP), ("NULL", T.NULL),
    ("ARRAY", T.ArrayType(T.INT)), ("MAP", T.MapType(T.STRING, T.INT)),
    ("STRUCT", T.StructType((T.StructField("f", T.INT, True),))),
]


def matrix_row(ts: TypeSig) -> List[str]:
    """S/NS cell per MATRIX_CATEGORIES column for one TypeSig."""
    return ["S" if ts.supports(inst) is None else "NS"
            for _, inst in MATRIX_CATEGORIES]


DEFAULT_EXPR_SIG = ExprSig(ALL_DEVICE)
