"""User-facing window spec builder — mirrors ``pyspark.sql.Window`` so
reference workloads port unchanged (SURVEY §1 user-visible API)."""

from __future__ import annotations

from typing import Sequence

from .expressions.core import Expression, resolve_expression
from .expressions.windows import (CURRENT_ROW, UNBOUNDED_FOLLOWING,
                                  UNBOUNDED_PRECEDING, WindowFrame,
                                  WindowSpecDefinition)
from .plan import SortOrder


def _orders(cols) -> list:
    out = []
    for c in cols:
        e = c.expr if hasattr(c, "expr") else c
        if isinstance(e, SortOrder):
            out.append(e)
        elif isinstance(e, Expression):
            so = getattr(e, "_sort_order", None)
            out.append(so if so is not None else SortOrder(e))
        else:
            out.append(SortOrder(resolve_expression(e)))
    return out


class WindowSpec:
    def __init__(self, partition=(), order=(), frame=None):
        self._partition = tuple(partition)
        self._order = tuple(order)
        self._frame = frame

    def partitionBy(self, *cols) -> "WindowSpec":
        exprs = []
        for c in cols:
            from .dataframe import Column
            if isinstance(c, str):
                from .functions import col as col_fn
                c = col_fn(c)
            exprs.append(c.expr if isinstance(c, Column) else
                         resolve_expression(c))
        return WindowSpec(exprs, self._order, self._frame)

    def orderBy(self, *cols) -> "WindowSpec":
        cols = [(_str_col(c) if isinstance(c, str) else c) for c in cols]
        return WindowSpec(self._partition, _orders(cols), self._frame)

    def rowsBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self._partition, self._order,
                          WindowFrame("rows", int(start), int(end)))

    def rangeBetween(self, start: int, end: int) -> "WindowSpec":
        return WindowSpec(self._partition, self._order,
                          WindowFrame("range", int(start), int(end)))

    def to_definition(self) -> WindowSpecDefinition:
        return WindowSpecDefinition(self._partition, self._order, self._frame)


def _str_col(name: str):
    from .functions import col
    return col(name)


class Window:
    unboundedPreceding = UNBOUNDED_PRECEDING
    unboundedFollowing = UNBOUNDED_FOLLOWING
    currentRow = CURRENT_ROW

    @staticmethod
    def partitionBy(*cols) -> WindowSpec:
        return WindowSpec().partitionBy(*cols)

    @staticmethod
    def orderBy(*cols) -> WindowSpec:
        return WindowSpec().orderBy(*cols)

    @staticmethod
    def rowsBetween(start: int, end: int) -> WindowSpec:
        return WindowSpec().rowsBetween(start, end)

    @staticmethod
    def rangeBetween(start: int, end: int) -> WindowSpec:
        return WindowSpec().rangeBetween(start, end)
