"""Test tooling shipped with the framework — the analog of the reference's
``integration_tests`` datagen layer (``data_gen.py:38-751`` design) and the
``datagen/`` scale-data module."""

from .asserts import (assert_equal_with_pandas,
                      assert_tpu_and_cpu_are_equal_collect,
                      assert_tpu_fallback_collect, run_with_cpu_and_tpu)
from .datagen import (ArrayGen, BooleanGen, ByteGen, DataGen, DateGen,
                      DecimalGen, DoubleGen, FloatGen, IntegerGen, LongGen,
                      MapGen, ShortGen, StringGen, StructGen, TimestampGen,
                      gen_table)

__all__ = [
    "DataGen", "BooleanGen", "ByteGen", "ShortGen", "IntegerGen", "LongGen",
    "FloatGen", "DoubleGen", "DecimalGen", "StringGen", "DateGen",
    "TimestampGen", "ArrayGen", "MapGen", "StructGen", "gen_table",
    "assert_tpu_and_cpu_are_equal_collect", "assert_tpu_fallback_collect",
    "assert_equal_with_pandas", "run_with_cpu_and_tpu",
]
