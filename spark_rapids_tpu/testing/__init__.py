"""Test tooling shipped with the framework — the analog of the reference's
``integration_tests`` datagen layer (``data_gen.py:38-751`` design) and the
``datagen/`` scale-data module."""

from .datagen import (ArrayGen, BooleanGen, ByteGen, DataGen, DateGen,
                      DecimalGen, DoubleGen, FloatGen, IntegerGen, LongGen,
                      MapGen, ShortGen, StringGen, StructGen, TimestampGen,
                      gen_table)

__all__ = [
    "DataGen", "BooleanGen", "ByteGen", "ShortGen", "IntegerGen", "LongGen",
    "FloatGen", "DoubleGen", "DecimalGen", "StringGen", "DateGen",
    "TimestampGen", "ArrayGen", "MapGen", "StructGen", "gen_table",
]
