"""Public assertion framework — the analog of the reference's
``integration_tests/src/main/python/asserts.py`` tier-1 harness
(``assert_gpu_and_cpu_are_equal_collect`` at ``asserts.py:560``,
``assert_gpu_fallback_collect`` at ``:422``, ``run_with_cpu_and_gpu`` at
``:525``; sessions toggled like ``spark_session.py:112-118``).

Philosophy preserved: run the same query with acceleration ON and OFF and
require equal results.  The OFF path executes eagerly under numpy — a
different code path from the jitted device kernels — and callers can add
a pandas oracle for full independence (``assert_equal_with_pandas``).
"""

from __future__ import annotations

import math
from typing import Callable, Optional, Sequence

__all__ = [
    "assert_tpu_and_cpu_are_equal_collect",
    "assert_tpu_fallback_collect",
    "run_with_cpu_and_tpu",
    "assert_equal_with_pandas",
]


def _norm(rows):
    out = []
    for r in rows:
        nr = {}
        for k, v in r.items():
            if isinstance(v, float):
                nr[k] = "NaN" if math.isnan(v) else round(v, 9)
            else:
                nr[k] = v
        out.append(nr)
    return out


def _sorted_rows(rows, sort_by):
    if not sort_by:
        return rows
    return sorted(rows, key=lambda r: tuple(
        (r[k] is None, str(r[k])) for k in sort_by))


def run_with_cpu_and_tpu(df):
    """Collect ``df`` twice — acceleration on, then off — and return
    (tpu_table, cpu_table)."""
    sess = df._session
    tpu = df.collect()
    old = sess.conf.get("spark.rapids.sql.enabled")
    sess.conf.set("spark.rapids.sql.enabled", False)
    try:
        cpu = df.collect()
    finally:
        sess.conf.set("spark.rapids.sql.enabled", old)
    return tpu, cpu


def assert_tpu_and_cpu_are_equal_collect(df, sort_by: Optional[
        Sequence[str]] = None):
    """The tier-1 equality assertion; returns the accelerated result."""
    tpu, cpu = run_with_cpu_and_tpu(df)
    t = _sorted_rows(tpu.to_pylist(), sort_by)
    c = _sorted_rows(cpu.to_pylist(), sort_by)
    assert _norm(t) == _norm(c), "TPU and CPU results differ"
    return tpu


def assert_tpu_fallback_collect(df, fallback_exec: str):
    """Assert the query RUNS but the named exec did NOT place on the
    device (the reference's assert_gpu_fallback_collect): the physical
    plan must contain a Cpu- node for it."""
    sess = df._session
    plan = sess.physical_plan(df).tree_string()
    assert f"Cpu{fallback_exec}" in plan, (
        f"expected {fallback_exec} to fall back to CPU; plan:\n{plan}")
    return df.collect()


def assert_equal_with_pandas(df, expected, sort_by: Optional[
        Sequence[str]] = None, rtol: float = 1e-7):
    """Compare a query result against an independently computed pandas
    frame (the genuinely independent oracle the reference gets from CPU
    Spark)."""
    import numpy as np

    got = df.collect().to_pandas()
    exp = expected.reset_index(drop=True)
    if sort_by:
        got = got.sort_values(list(sort_by)).reset_index(drop=True)
        exp = exp.sort_values(list(sort_by)).reset_index(drop=True)
    assert list(got.columns) == list(exp.columns), (
        f"column mismatch: {list(got.columns)} vs {list(exp.columns)}")
    assert len(got) == len(exp), f"row count {len(got)} vs {len(exp)}"
    for col in got.columns:
        g, e = got[col].to_numpy(), exp[col].to_numpy()
        if g.dtype.kind == "f" or e.dtype.kind == "f":
            ga, ea = g.astype(float), e.astype(float)
            nan_equal = np.isnan(ga) == np.isnan(ea)
            ok = nan_equal & (np.isnan(ga) | np.isclose(ga, ea, rtol=rtol))
            assert ok.all(), f"column {col} differs"
        else:
            assert (pd_isna_eq(g, e)), f"column {col} differs"


def pd_isna_eq(g, e) -> bool:
    import pandas as pd
    gs, es = pd.Series(g), pd.Series(e)
    both_na = gs.isna() & es.isna()
    return bool((both_na | (gs == es)).all())
