"""Chaos soak — the degraded-conditions proof rig (docs/robustness.md).

Runs a small TPC-H-ish query suite twice over identical data: once
fault-free, once under a seeded random fault schedule (shuffle fetch
failures, permanently destroyed shuffle blocks, torn spill-disk I/O,
injected retryable OOMs), and asserts the chaos run's results are
BIT-IDENTICAL to the clean run's — the paper's transparent-acceleration
promise must survive data-movement failure, not just the happy path
(arXiv:2508.04701's correctness-under-degradation argument;
arXiv:2508.05029 treats data-movement failure as a first-class concern).

The schedule is deterministic (robustness/faults.py): a given
(seed, sites, probability) either passes forever or fails forever, so CI
can pin one.

Run standalone:  python -m spark_rapids_tpu.testing.chaos [rows]
                     [--seed N] [--trace /path/trace.json]
CI runs it in ci/run_ci.sh with two primary fault sites armed and
validates the exported trace carries ``fault``-category spans.
"""

from __future__ import annotations

import json
import sys
import tempfile
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

#: the default armed schedule: every site with a built-in recovery
#: protocol that this suite's query shapes actually traverse
DEFAULT_SITES = ("shuffle.fetch:0.25,shuffle.block.lost:0.12,"
                 "spill.disk_read:0.25,spill.disk_write:0.25,"
                 "memory.oom.retry:0.25")


def _q_agg(sess, t, F):
    df = sess.create_dataframe(t["fact"], num_partitions=4)
    return (df.groupBy("q").agg(F.sum(F.col("v")).alias("sv"),
                                F.count("*").alias("c"))
            .orderBy("q").collect())


def _q_join_agg(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    dim = sess.create_dataframe(t["dim"], num_partitions=2)
    return (fact.join(dim, on="k", how="inner")
            .groupBy("cat").agg(F.count("*").alias("n"),
                                F.sum(fact.v).alias("sv"))
            .orderBy("cat").collect())


def _q_left_join(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    dim = sess.create_dataframe(t["dim"], num_partitions=2)
    return (fact.join(dim, on="k", how="left").filter(fact.q >= 90)
            .select(fact.k, fact.v, dim.w)
            .orderBy("k", "v").collect())


def _q_sort(sess, t, F):
    # out-of-core sort (targetRows is forced small below): spillable runs
    # + k-way merge give the spill/OOM fault sites real traffic
    df = sess.create_dataframe(t["fact"], num_partitions=4)
    return (df.orderBy(df.v.desc_nulls_first(), "k")
            .select("k", "v", "q").collect())


def _q_enc_str_join(sess, t, F):
    # low-cardinality STRING-keyed filter+join+group: the shape the
    # encoded columnar path (docs/encoded_columns.md) rewrites — dict
    # filter on the scan, code-space join probe, group-by on codes, and
    # encoded frames (narrowed codes + dictionaries) over the serializing
    # shuffle plane.  Kept LAST in QUERIES so the exported chaos trace
    # carries its encode spans alongside the fault spans.
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    cdim = sess.create_dataframe(t["cdim"], num_partitions=2)
    return (fact.filter(F.col("ck") <= "cat_11")
            .join(cdim, on="ck", how="inner")
            .groupBy("ck").agg(F.count("*").alias("n"),
                               F.sum(fact.v).alias("sv"))
            .orderBy("ck").collect())


QUERIES: List[Tuple[str, Callable]] = [
    ("agg", _q_agg),
    ("join_agg", _q_join_agg),
    ("left_join", _q_left_join),
    ("ooc_sort", _q_sort),
    ("enc_str_join", _q_enc_str_join),
]


def augment_tables(t: dict) -> dict:
    """Add the low-cardinality string key column (and its dimension) the
    `enc_str_join` query needs, IN PLACE and idempotently — callers that
    reuse one tables dict across runs (the pipeline rig's timing loop,
    test fixtures) keep stable table identities, so the engine's upload
    cache still amortizes."""
    if "cdim" not in t:
        rng = np.random.default_rng(5)
        cats = [f"cat_{i:02d}" for i in range(16)]
        n = t["fact"].num_rows
        t["fact"] = t["fact"].append_column(
            "ck", pa.array([cats[i] for i in rng.integers(0, 16, n)]))
        t["cdim"] = pa.table({"ck": pa.array(cats),
                              "cw": np.arange(float(len(cats)))})
    return t


def _soak_tables(rows: int) -> dict:
    """scaletest tables + the dictionary-encoded string key columns so
    the suite traverses the encoded paths."""
    from .scaletest import build_tables
    return augment_tables(dict(build_tables(rows)))


def _canonical(table: pa.Table) -> pd.DataFrame:
    df = table.to_pandas()
    return df.sort_values(list(df.columns), kind="mergesort") \
        .reset_index(drop=True)


def _base_conf(tmp: str) -> Dict[str, object]:
    """Shared clean/chaos session confs: the serializing (resident-off)
    shuffle plane so block fetches actually happen, a small out-of-core
    sort target so the spill tier sees traffic, and an
    environment-independent codec."""
    return {
        "spark.rapids.shuffle.localDeviceResident.enabled": False,
        "spark.rapids.shuffle.compression.codec": "none",
        "spark.rapids.sql.sort.outOfCore.targetRows": 2048,
        "spark.rapids.memory.spillDir": tmp,
        # shuffled (not broadcast) joins: both join inputs ride exchanges
        "spark.rapids.sql.autoBroadcastJoinThreshold": 1,
    }


def run_soak(rows: int = 20_000, seed: int = 11,
             sites: str = DEFAULT_SITES,
             queries: Optional[List[str]] = None,
             trace_path: Optional[str] = None,
             strict: bool = True,
             pipeline: bool = False,
             encoded: bool = False,
             whole_stage: bool = False,
             coalesce: bool = False) -> dict:
    """Returns the soak report; raises AssertionError on any parity or
    counter-visibility failure.  ``strict=False`` (reduced smoke runs)
    keeps the bit-parity and faults-injected asserts but skips the
    per-site coverage floor (small row counts may not traverse every
    armed site).

    ``pipeline=True`` runs the CHAOS session under the async execution
    layer (task.parallelism=4 + prefetch queues + double-buffered
    transfers, concurrentGpuTasks left at 1 so semaphore contention —
    ``sem_wait`` spans — is guaranteed) while the clean run stays serial:
    injected faults must recover bit-identically even when they surface
    on prefetch producer / transfer stager / pool worker threads.

    ``encoded=True`` runs the CHAOS session with encoded columnar
    execution ON while the clean run stays on the RAW path
    (``spark.rapids.tpu.sql.encoded.enabled=false``): encoded shuffle
    frames (narrowed codes + dictionaries/refs) must survive fetch
    retries, destroyed blocks, and lost-block recompute bit-identically
    to the raw clean run — the ISSUE 6 acceptance leg.

    ``whole_stage=True`` runs the CHAOS session with whole-stage fusion +
    buffer donation forced ON while the clean run disables fusion
    entirely (``spark.rapids.tpu.sql.fusion.enabled=false``, the serial
    unfused per-op baseline): fused stage programs, absorbed aggregate /
    probe terminals, and the donation-safety guard must stay
    bit-identical under injected data-movement faults — the ISSUE 7
    acceptance leg (docs/whole_stage.md).

    ``coalesce=True`` additionally arms the ISSUE 14 dispatch set on the
    CHAOS session — the small-batch dispatch coalescer, the sort/window
    stage terminals, and the fused single-program join probe — against
    the same serial unfused clean baseline: coalesced batch-of-batches
    launches and fused terminals must recover bit-identically under
    injected faults."""
    import spark_rapids_tpu as srt
    from ..config import RapidsConf
    from ..memory.spill import BufferCatalog
    from ..robustness import disarm_chaos
    from ..robustness.faults import SITE_STATS
    from ..sql import functions as F
    tables = _soak_tables(rows)
    tmp = tempfile.mkdtemp(prefix="srt-chaos-")
    selected = [(n, fn) for n, fn in QUERIES
                if queries is None or n in queries]
    from ..sql.session import TpuSession
    prev_active = TpuSession._active

    # tiny host spill budget: an injected RetryOOM's spill_all_device
    # overflows straight to the DISK tier, so spill.disk_read/write see
    # real traffic.  Shared by both runs (the tier move is value-exact,
    # so the clean run's results are unaffected).
    BufferCatalog.reset(RapidsConf({
        "spark.rapids.memory.host.spillStorageSize": 1,
        "spark.rapids.memory.spillDir": tmp,
    }))
    try:
        clean_conf = dict(_base_conf(tmp))
        if encoded:
            # clean baseline on the RAW path: the soak then proves
            # encoded-under-faults == raw-without-faults, not just
            # encoded == encoded
            clean_conf["spark.rapids.tpu.sql.encoded.enabled"] = False
        if whole_stage or coalesce:
            # clean baseline fully UNFUSED: the soak proves
            # fused-and-donating-under-faults == per-op-without-faults
            clean_conf["spark.rapids.tpu.sql.fusion.enabled"] = False
        if coalesce:
            clean_conf.update({
                "spark.rapids.tpu.sql.dispatch.coalesce.enabled": False,
                "spark.rapids.tpu.sql.join.fusedProbe.enabled": False,
                "spark.rapids.tpu.sql.wholeStage.sortWindowTerminal"
                ".enabled": False,
            })
        clean_sess = srt.session(conf=RapidsConf.get_global().copy(
            clean_conf))
        clean: Dict[str, pd.DataFrame] = {}
        for name, fn in selected:
            clean[name] = _canonical(fn(clean_sess, tables, F))

        chaos_conf = dict(_base_conf(tmp))
        chaos_conf.update({
            "spark.rapids.tpu.chaos.enabled": True,
            "spark.rapids.tpu.chaos.seed": seed,
            "spark.rapids.tpu.chaos.sites": sites,
            "spark.rapids.tpu.shuffle.fetch.backoffMs": 1,
        })
        if encoded:
            chaos_conf["spark.rapids.tpu.sql.encoded.enabled"] = True
        if whole_stage or coalesce:
            chaos_conf.update({
                "spark.rapids.tpu.sql.fusion.enabled": True,
                "spark.rapids.tpu.sql.wholeStage.enabled": True,
                "spark.rapids.tpu.sql.wholeStage.donation.enabled": True,
            })
        if coalesce:
            chaos_conf.update({
                "spark.rapids.tpu.sql.dispatch.coalesce.enabled": True,
                # small cap so groups actually form at soak row counts
                "spark.rapids.tpu.sql.dispatch.coalesce.maxBatches": 4,
                "spark.rapids.tpu.sql.join.fusedProbe.enabled": True,
                "spark.rapids.tpu.sql.wholeStage.sortWindowTerminal"
                ".enabled": True,
            })
        if pipeline:
            chaos_conf.update({
                "spark.rapids.tpu.task.parallelism": 4,
                "spark.rapids.tpu.prefetch.enabled": True,
                "spark.rapids.tpu.prefetch.depth": 2,
                "spark.rapids.tpu.transfer.doubleBuffer.enabled": True,
                # permits intentionally BELOW the pool width: the soak
                # doubles as the sem_wait-span source for CI's
                # check_trace --require-cat sem_wait validation
                "spark.rapids.sql.concurrentGpuTasks": 1,
            })
        if trace_path:
            chaos_conf["spark.rapids.tpu.profile.enabled"] = True
        chaos_sess = srt.session(conf=RapidsConf.get_global().copy(
            chaos_conf))

        counters = {"faultsInjected": 0, "shuffleFetchRetries": 0,
                    "shuffleBlocksRecomputed": 0, "peersBlacklisted": 0}
        by_site: Dict[str, int] = {}
        per_query = {}
        mismatches = []
        exported_has_encode = False
        for name, fn in selected:
            site0 = dict(SITE_STATS)
            got = _canonical(fn(chaos_sess, tables, F))
            m = chaos_sess.last_query_metrics
            q = {k: int(m.get(k, 0)) for k in counters}
            for k in counters:
                counters[k] += q[k]
            # per-site coverage: the monotonic totals survive the
            # query-scoped registry (re-armed per query, gone at query end)
            for site, n in SITE_STATS.items():
                d = n - site0.get(site, 0)
                if d:
                    by_site[site] = by_site.get(site, 0) + d
            per_query[name] = q
            try:
                pd.testing.assert_frame_equal(got, clean[name],
                                              check_exact=True)
            except AssertionError as e:
                mismatches.append(f"{name}: {e}")
            if trace_path and q["faultsInjected"] > 0:
                # keep the last trace carrying fault spans, preferring
                # one that ALSO carries encode spans (scan-side encode
                # fires only on each table's first upload, so later
                # queries' traces lack cat `encode` — CI's encoded leg
                # validates both categories in one export)
                has_enc = int(m.get("encodedColumnsEncoded", 0)) > 0
                if has_enc or not exported_has_encode:
                    chaos_sess.export_chrome_trace(trace_path)
                    exported_has_encode = exported_has_encode or has_enc

        report = {
            "rows": rows, "seed": seed, "sites": sites,
            "pipeline": pipeline, "encoded": encoded,
            "whole_stage": whole_stage, "coalesce": coalesce,
            "queries": per_query, "counters": counters,
            "faults_by_site": by_site,
            "bit_identical": not mismatches,
        }
        assert not mismatches, \
            "chaos run diverged from the fault-free run:\n" + \
            "\n".join(mismatches)
        assert counters["faultsInjected"] > 0, report
        assert counters["shuffleFetchRetries"] > 0, report
        if strict:
            assert counters["shuffleBlocksRecomputed"] > 0, report
            assert by_site.get("shuffle.fetch", 0) > 0, report
            assert by_site.get("spill.disk_read", 0) > 0, report
        return report
    finally:
        disarm_chaos()
        BufferCatalog.reset()
        # don't leave the chaos-confed session as the cached active one:
        # a later bare ``srt.session()`` would inherit it and re-arm
        # chaos on its next query
        TpuSession._active = prev_active


def run_multi_session_soak(rows: int = 12_000, seed: int = 11,
                           sites: str = DEFAULT_SITES,
                           tenants: int = 2,
                           queries: Optional[List[str]] = None,
                           trace_path: Optional[str] = None) -> dict:
    """Multi-tenant chaos soak (docs/serving.md): ``tenants`` serving
    sessions run the TPC-H-ish suite CONCURRENTLY through one
    ServingEngine while the seeded fault registry is armed engine-scoped
    — every tenant's results must be bit-identical to the serial clean
    run.  This is the serving tier's correctness floor: admission
    interleaving, shared caches (kernel/broadcast/upload), and fault
    recovery on N driver threads at once must not perturb a single bit.

    The per-site coverage floor stays with the serial soak (fault
    ordinals shift under thread interleaving, like the --pipeline leg);
    here the asserts are bit-parity, fault visibility, per-tenant
    history attribution, and admission accounting for every tenant."""
    import threading

    import spark_rapids_tpu as srt  # noqa: F401 - engine init path
    from ..config import RapidsConf
    from ..memory.spill import BufferCatalog
    from ..robustness import disarm_chaos, stats_snapshot
    from ..serving import ServingEngine
    from ..sql import functions as F
    from ..sql.session import TpuSession
    tables = _soak_tables(rows)
    tmp = tempfile.mkdtemp(prefix="srt-mtchaos-")
    selected = [(n, fn) for n, fn in QUERIES
                if queries is None or n in queries]
    prev_active = TpuSession._active
    BufferCatalog.reset(RapidsConf({
        "spark.rapids.memory.host.spillStorageSize": 1,
        "spark.rapids.memory.spillDir": tmp,
    }))
    eng = None
    try:
        clean_sess = srt.session(conf=RapidsConf.get_global().copy(
            _base_conf(tmp)))
        clean: Dict[str, pd.DataFrame] = {}
        for name, fn in selected:
            clean[name] = _canonical(fn(clean_sess, tables, F))

        eng_conf = dict(_base_conf(tmp))
        eng_conf.update({
            "spark.rapids.tpu.chaos.enabled": True,
            "spark.rapids.tpu.chaos.seed": seed,
            "spark.rapids.tpu.chaos.sites": sites,
            "spark.rapids.tpu.shuffle.fetch.backoffMs": 1,
            "spark.rapids.tpu.serving.maxConcurrentQueries": max(
                2, tenants),
            # result sharing OFF: every tenant must EXECUTE every query
            # under faults — a cache hit would prove nothing
            "spark.rapids.tpu.serving.resultCache.enabled": False,
            "spark.rapids.tpu.serving.broadcastShare.enabled": True,
        })
        if trace_path:
            eng_conf["spark.rapids.tpu.profile.enabled"] = True
        rob0 = stats_snapshot()
        eng = ServingEngine(conf=RapidsConf.get_global().copy(eng_conf))
        results: Dict[str, Dict[str, pd.DataFrame]] = {}
        errors: Dict[str, BaseException] = {}

        def run_tenant(tname: str) -> None:
            try:
                sess = eng.session(tenant=tname)
                got = {}
                for name, fn in selected:
                    got[name] = _canonical(fn(sess, tables, F))
                results[tname] = got
            except BaseException as e:  # noqa: BLE001 - reported below
                errors[tname] = e

        threads = [threading.Thread(target=run_tenant,
                                    args=(f"tenant{i}",),
                                    name=f"srt-tenant{i}")
                   for i in range(tenants)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors, f"tenant queries raised: {errors}"
        rob1 = stats_snapshot()
        faults = rob1["faultsInjected"] - rob0["faultsInjected"]
        mismatches = []
        for tname, got in sorted(results.items()):
            for name, frame in got.items():
                try:
                    pd.testing.assert_frame_equal(frame, clean[name],
                                                  check_exact=True)
                except AssertionError as e:
                    mismatches.append(f"{tname}/{name}: {e}")
        if trace_path:
            eng.export_chrome_trace(trace_path)
        adm = eng.admission_stats()
        hist = eng.query_history()
        per_tenant_hist = {t: len(eng.query_history(tenant=t))
                           for t in sorted(results)}
        report = {
            "rows": rows, "seed": seed, "sites": sites,
            "tenants": tenants, "faults_injected": faults,
            "queries_per_tenant": len(selected),
            "bit_identical": not mismatches,
            "admission": adm,
            "history_records": len(hist),
            "history_per_tenant": per_tenant_hist,
        }
        assert not mismatches, \
            "multi-session chaos run diverged from the clean run:\n" + \
            "\n".join(mismatches)
        assert faults > 0, report
        # every tenant's queries must be attributed in the shared ring
        for t, n in per_tenant_hist.items():
            assert n == len(selected), (t, n, report)
        assert adm["admitted"] == tenants * len(selected), report
        return report
    finally:
        if eng is not None:
            eng.close()
        disarm_chaos()
        BufferCatalog.reset()
        TpuSession._active = prev_active


def main() -> None:
    import os

    # the ambient sitecustomize may force the axon TPU tunnel; this rig
    # runs on the host platform unless told otherwise (scaletest.main
    # does the same)
    plat = os.environ.get("SRT_SCALE_PLATFORM", "cpu")
    if plat == "cpu":
        from spark_rapids_tpu import pin_host_platform
        pin_host_platform()
    argv = sys.argv[1:]
    trace_path = None
    seed = 11
    pipeline = False
    encoded = False
    whole_stage = False
    multi_session = False
    if "--multi-session" in argv:
        # multi-tenant soak: >=2 serving sessions run the suite
        # concurrently through one ServingEngine under engine-scoped
        # chaos; every tenant bit-identical to the serial clean run
        # (ISSUE 9 acceptance — docs/serving.md)
        multi_session = True
        argv.remove("--multi-session")
    coalesce = False
    if "--coalesce" in argv:
        # dispatch soak: chaos session with the coalescer, sort/window
        # stage terminals, and the fused join probe armed vs the serial
        # unfused clean baseline (ISSUE 14 acceptance: bit-identical
        # under faults with the dispatch set on)
        coalesce = True
        argv.remove("--coalesce")
    if "--whole-stage" in argv:
        # whole-stage soak: chaos session with fusion + donation forced
        # on vs a fully UNFUSED serial clean baseline (ISSUE 7
        # acceptance: bit-identical under faults with whole-stage on)
        whole_stage = True
        argv.remove("--whole-stage")
    if "--encoded" in argv:
        # encoded soak: chaos session runs with encoded columnar
        # execution ON against a RAW clean baseline (ISSUE 6 acceptance:
        # bit-identical under faults with encoding enabled)
        encoded = True
        argv.remove("--encoded")
    if "--pipeline" in argv:
        # pipelined soak: chaos session under parallelism=4 + prefetch +
        # double-buffered transfers vs the SERIAL clean run.  The
        # per-site coverage floor is owned by the serial soak (ordinal
        # assignment shifts with thread interleaving), so this leg runs
        # strict=False — bit-parity and fault-visibility asserts remain.
        pipeline = True
        argv.remove("--pipeline")
    if "--trace" in argv:
        i = argv.index("--trace")
        trace_path = argv[i + 1]
        argv = argv[:i] + argv[i + 2:]
    if "--seed" in argv:
        i = argv.index("--seed")
        seed = int(argv[i + 1])
        argv = argv[:i] + argv[i + 2:]
    rows = int(argv[0]) if argv else 20_000
    if multi_session:
        report = run_multi_session_soak(rows, seed=seed,
                                        trace_path=trace_path)
        print(json.dumps(report, indent=2))
        print(f"CHAOS SOAK PASSED: {report['tenants']} concurrent "
              f"tenants bit-identical under "
              f"{report['faults_injected']} injected faults")
        return
    report = run_soak(rows, seed=seed, trace_path=trace_path,
                      strict=not pipeline, pipeline=pipeline,
                      encoded=encoded, whole_stage=whole_stage,
                      coalesce=coalesce)
    print(json.dumps(report, indent=2))
    mode = ("pipelined " if pipeline else "") + \
        ("encoded " if encoded else "") + \
        ("whole-stage " if whole_stage else "") + \
        ("coalesce-armed " if coalesce else "")
    print(f"CHAOS SOAK PASSED: {mode}results bit-identical under "
          f"{report['counters']['faultsInjected']} injected faults")


if __name__ == "__main__":
    main()
