"""Process-kill chaos harness for the pod-scale fault domain.

Spawns a REAL N-process shuffle topology (one driver-side registry +
manager, N child executors over the TCP plane — the same scaffolding CI
step 13 uses for trace stitching), then injects process-level faults at
seeded points and asserts the cluster still produces bit-identical
results with bounded recovery latency:

  sigkill    SIGKILL one executor mid-query.  Survivors ride the
             ConnectionError retry path into lineage recompute while the
             failure detector declares the peer dead (proactive
             recompute + dead-peer failover for later fetches).
  zombie     SIGSTOP one executor past its dead-declaration, re-register
             its executor id (epoch bump — the "replacement" landing on
             the same endpoint), then SIGCONT the original.  The revived
             zombie still serves — at its OLD epoch — and every response
             must be refused as StaleBlockEpoch (zero stale blocks
             consumed), with recompute keeping results bit-identical.
  partition  SIGSTOP one executor (an asymmetric partition: frozen, not
             gone).  Survivors query only AFTER dead-declaration, so
             every fetch takes the dead-skip fast path (PeerDead ->
             recompute) without ever touching the frozen socket.

Determinism: map outputs are a pure function of (seed, map_id), so the
registered lineage callbacks regenerate byte-identical data and the
result digest — sorted (k, v) rows hashed — must match the in-process
``expected_digest`` exactly in every scenario.

Recovery latency is measured on the driver (SIGKILL/SIGSTOP ->
failure-detector dead-declaration) and in the survivors (self-timed
degraded query + tracer-summed recompute spans) and banked as a
``fault_recovery`` record that rides the bench artifact contract
(tools/bench_diff.py diffs it like any other metric group).

Run standalone:  python tools/chaos_cluster.py --procs 3 --scenario all
"""

from __future__ import annotations

import hashlib
import json
import os
import select
import signal
import subprocess
import sys
import tempfile
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
import pyarrow as pa

SHUFFLE_ID = 23
#: child stdout protocol: READY <endpoint> once up, then one
#: RESULT <digest> <elapsed_ms> <json-stats> line per "query" command
READY, RESULT = "READY", "RESULT"


# ---------------------------------------------------------------------------
# deterministic data plane: map output = f(seed, map_id), nothing else
# ---------------------------------------------------------------------------

def make_map_arrays(seed: int, map_id: int,
                    rows: int) -> Tuple[np.ndarray, np.ndarray]:
    rng = np.random.default_rng(seed * 1009 + map_id)
    k = rng.integers(0, 64, rows).astype(np.int64)
    v = rng.random(rows)
    return k, v


def make_map_pieces(seed: int, map_id: int, rows: int, nparts: int):
    """The per-reduce-partition device batches map task ``map_id``
    publishes; partition r takes the rows with k % nparts == r."""
    from ..columnar.convert import arrow_to_device
    k, v = make_map_arrays(seed, map_id, rows)
    pieces = []
    for r in range(nparts):
        mask = (k % nparts) == r
        t = pa.table({"k": k[mask], "v": v[mask]})
        pieces.append(arrow_to_device(t) if t.num_rows else None)
    return pieces


def _digest(ks: List[np.ndarray], vs: List[np.ndarray]) -> str:
    k = np.concatenate(ks) if ks else np.empty(0, np.int64)
    v = np.concatenate(vs) if vs else np.empty(0, np.float64)
    order = np.lexsort((v, k))
    return hashlib.sha256(
        k[order].astype("<i8").tobytes()
        + v[order].astype("<f8").tobytes()).hexdigest()


def expected_digest(seed: int, num_maps: int, rows: int) -> str:
    """The bit-identical ground truth, computed with no cluster at all."""
    ks, vs = [], []
    for m in range(num_maps):
        k, v = make_map_arrays(seed, m, rows)
        ks.append(k)
        vs.append(v)
    return _digest(ks, vs)


def read_digest(mgr, num_maps: int, nparts: int) -> str:
    """Read every reduce partition through ``mgr`` and digest the rows
    (sorted, so frame arrival order never affects parity)."""
    from ..columnar.convert import device_to_arrow
    ks, vs = [], []
    for r in range(nparts):
        b = mgr.read_reduce_partition(SHUFFLE_ID, num_maps, r)
        if b is None:
            continue
        t = device_to_arrow(b)
        ks.append(np.asarray(t.column("k").to_numpy(), np.int64))
        vs.append(np.asarray(t.column("v").to_numpy(), np.float64))
    return _digest(ks, vs)


# ---------------------------------------------------------------------------
# child executor process
# ---------------------------------------------------------------------------

def child_main() -> None:
    """Executor subprocess entry (``tools/chaos_cluster.py`` and the CI
    step exec ``python -c "...child_main()" '<json-config>'``).  Writes
    its map output, registers the lineage callback (any map regenerates
    from the seed), then answers "query" commands on stdin until "exit"."""
    cfg = json.loads(sys.argv[1])
    plat = os.environ.get("SRT_CHAOS_PLATFORM", "cpu")
    if plat == "cpu":
        from .. import pin_host_platform
        pin_host_platform()
    import spark_rapids_tpu as srt
    from ..observability import tracer as OT
    from ..observability.export import write_event_log
    from ..robustness.failure_detector import STATS as FD_STATS
    from ..shuffle.manager import FETCH_STATS, ShuffleManager

    eid = cfg["executor_id"]
    seed, rows = int(cfg["seed"]), int(cfg["rows"])
    num_maps, nparts = int(cfg["num_maps"]), int(cfg["nparts"])
    OT.get_tracer().reset(session=eid)
    OT.TRACING["on"] = True
    conf = srt.RapidsConf.get_global().copy(dict({
        "spark.rapids.shuffle.mode": "ICI",
        "spark.rapids.shuffle.transport.type": "TCP",
        "spark.rapids.shuffle.tcp.native.enabled": False,
        "spark.rapids.shuffle.tcp.driverEndpoint": cfg["driver"],
        "spark.rapids.memory.spillDir":
            tempfile.mkdtemp(prefix=f"srt-chaos-{eid}-"),
    }, **cfg.get("conf", {})))
    m = ShuffleManager(conf, executor_id=eid)
    m.write_map_output(SHUFFLE_ID, int(cfg["map_id"]),
                       make_map_pieces(seed, int(cfg["map_id"]), rows,
                                       nparts))
    # lineage: ANY map regenerates from the seed, so a survivor recovers
    # a dead peer's output without the dead peer
    m.register_recompute(
        SHUFFLE_ID,
        lambda mid: m.write_map_output(
            SHUFFLE_ID, mid, make_map_pieces(seed, mid, rows, nparts)))
    print(f"{READY} {getattr(m.transport, 'endpoint', 'local')}",
          flush=True)

    def stat_snap() -> Dict[str, int]:
        s = {k: int(v) for k, v in FETCH_STATS.items()}
        s.update({f"fd_{k}": int(v) for k, v in FD_STATS.items()})
        return s

    def recompute_us() -> float:
        # the fault-cat spans the recompute path emits carry dur in us
        return sum(e.get("dur", 0.0) for e in OT.get_tracer().snapshot()
                   if e.get("name") == "shuffle.recompute")

    for line in sys.stdin:
        cmd = line.strip().split()
        if not cmd:
            continue
        if cmd[0] == "query":
            # "query N": N back-to-back full reduce reads, so a fault
            # injected mid-stream hits some iterations pre-fault (remote
            # fetches) and some post-fault (recovery paths); every
            # iteration must produce the same digest
            n = int(cmd[1]) if len(cmd) > 1 else 1
            before = stat_snap()
            rc0 = recompute_us()
            t0 = time.monotonic()
            digests = {read_digest(m, num_maps, nparts)
                       for _ in range(n)}
            ms = (time.monotonic() - t0) * 1e3
            digest = digests.pop() if len(digests) == 1 else \
                "DIVERGED:" + ",".join(sorted(digests))
            delta = {k: v - before[k] for k, v in stat_snap().items()
                     if v != before[k]}
            delta["iters"] = n
            delta["recompute_ms"] = round(
                (recompute_us() - rc0) / 1e3, 3)
            print(f"{RESULT} {digest} {ms:.1f} {json.dumps(delta)}",
                  flush=True)
        elif cmd[0] == "exit":
            tr = OT.get_tracer()
            write_event_log(cfg["elog"], tr.snapshot(), tr.meta())
            m.close()
            break


# ---------------------------------------------------------------------------
# driver-side cluster
# ---------------------------------------------------------------------------

class _Child:
    def __init__(self, proc: subprocess.Popen, eid: str, elog: str):
        self.proc, self.executor_id, self.elog = proc, eid, elog
        self.endpoint = ""

    def send(self, cmd: str) -> None:
        self.proc.stdin.write(cmd + "\n")
        self.proc.stdin.flush()

    def readline(self, timeout_s: float) -> str:
        deadline = time.monotonic() + timeout_s
        buf = self.proc.stdout
        while time.monotonic() < deadline:
            r, _, _ = select.select([buf], [], [],
                                    max(0.0, deadline - time.monotonic()))
            if r:
                line = buf.readline()
                if line:
                    return line.strip()
                break                      # EOF: child died
        raise TimeoutError(
            f"{self.executor_id}: no reply within {timeout_s}s")


class ChaosCluster:
    """One registry + driver manager + N child executors, all armed
    (fast heartbeats, short suspect/dead windows) so detection fits a
    test budget.  ``victim_heartbeat=False`` disarms the LAST child's
    heartbeat loop — the zombie candidate registers once (epoch 1) and
    never re-registers, so a post-mortem epoch bump fences it out."""

    #: armed fault-domain confs shared by driver + children
    ARMED = {
        "spark.rapids.tpu.peers.heartbeatMs": 100,
        "spark.rapids.tpu.peers.suspectMs": 400,
        "spark.rapids.tpu.peers.deadMs": 900,
        "spark.rapids.tpu.shuffle.fetch.maxRetries": 6,
        "spark.rapids.tpu.shuffle.fetch.backoffMs": 25,
        "spark.rapids.tpu.shuffle.fetch.deadlineMs": 20_000,
    }

    def __init__(self, nprocs: int = 3, seed: int = 7, rows: int = 512,
                 out_dir: Optional[str] = None,
                 victim_heartbeat: bool = True):
        assert nprocs >= 2, "need at least one survivor"
        import spark_rapids_tpu as srt
        from ..observability import tracer as OT
        from ..shuffle.manager import ShuffleManager
        from ..shuffle.tcp import TcpHeartbeatServer
        self.nprocs, self.seed, self.rows = nprocs, seed, rows
        self.nparts = nprocs
        self.out = out_dir or tempfile.mkdtemp(prefix="srt-chaos-cluster-")
        os.makedirs(self.out, exist_ok=True)
        # generous registry timeout: scenarios drive expiry
        # DETERMINISTICALLY via expire_victim() instead of racing a
        # wall-clock window (the zombie candidate never heartbeats at
        # all and must stay registered until the fault point)
        self.registry = TcpHeartbeatServer(heartbeat_timeout_s=30.0)
        OT.get_tracer().reset(session="chaos-driver")
        OT.TRACING["on"] = True
        self.children: List[_Child] = []
        for i in range(nprocs):
            eid = f"chaos-exec-{i}"
            conf = dict(self.ARMED)
            if i == nprocs - 1 and not victim_heartbeat:
                conf["spark.rapids.tpu.peers.heartbeatMs"] = 0
            elog = os.path.join(self.out, f"{eid}.jsonl")
            cfg = {"executor_id": eid, "driver": self.registry.endpoint,
                   "elog": elog, "seed": seed, "rows": rows, "map_id": i,
                   "num_maps": nprocs, "nparts": self.nparts,
                   "conf": conf}
            proc = subprocess.Popen(
                [sys.executable, "-c",
                 "from spark_rapids_tpu.testing.chaos_cluster import "
                 "child_main; child_main()", json.dumps(cfg)],
                stdin=subprocess.PIPE, stdout=subprocess.PIPE,
                stderr=sys.stderr, text=True,
                env=dict(os.environ, JAX_PLATFORMS="cpu"))
            self.children.append(_Child(proc, eid, elog))
        for c in self.children:
            line = c.readline(120.0)
            assert line.startswith(READY), (c.executor_id, line)
            c.endpoint = line.split(None, 1)[1]
        conf = srt.RapidsConf.get_global().copy(dict({
            "spark.rapids.shuffle.mode": "ICI",
            "spark.rapids.shuffle.transport.type": "TCP",
            "spark.rapids.shuffle.tcp.native.enabled": False,
            "spark.rapids.shuffle.tcp.driverEndpoint":
                self.registry.endpoint,
            "spark.rapids.memory.spillDir":
                tempfile.mkdtemp(prefix="srt-chaos-driver-"),
        }, **self.ARMED))
        self.driver = ShuffleManager(conf, executor_id="chaos-driver")
        self.driver.register_recompute(
            SHUFFLE_ID,
            lambda mid: self.driver.write_map_output(
                SHUFFLE_ID, mid,
                make_map_pieces(seed, mid, rows, self.nparts)))
        self.victim = self.children[-1]
        self.survivors = self.children[:-1]

    # -- fault primitives ------------------------------------------------
    def kill_victim(self) -> None:
        self.victim.proc.send_signal(signal.SIGKILL)

    def stop_victim(self) -> None:
        self.victim.proc.send_signal(signal.SIGSTOP)

    def cont_victim(self) -> None:
        self.victim.proc.send_signal(signal.SIGCONT)

    def expire_victim(self) -> None:
        """Deterministic registry expiry (instead of waiting out the
        heartbeat timeout): the victim drops from the peer list NOW and
        the silence clock starts for every armed detector."""
        self.registry.expire_now(self.victim.executor_id)

    def wait_dead(self, timeout_s: float = 15.0) -> float:
        """Block until the DRIVER's detector declares the victim dead;
        returns the wait in ms (the detection half of recovery)."""
        t0 = time.monotonic()
        while time.monotonic() - t0 < timeout_s:
            if self.driver.detector.is_dead(self.victim.executor_id):
                return (time.monotonic() - t0) * 1e3
            time.sleep(0.005)
        raise TimeoutError(
            f"victim {self.victim.executor_id} not declared dead within "
            f"{timeout_s}s: {self.driver.detector.snapshot()}")

    def register_replacement(self) -> int:
        """The fencing bump: re-register the victim's executor id (the
        'replacement' coming up on the same endpoint).  Returns the new
        epoch every requester will fence against."""
        from ..shuffle.tcp import TcpHeartbeatClient
        cl = TcpHeartbeatClient(self.registry.endpoint)
        cl.register(self.victim.executor_id, self.victim.endpoint)
        return self.registry.epoch_of(self.victim.executor_id)

    # -- query plane -----------------------------------------------------
    def query(self, children: Optional[List[_Child]] = None,
              timeout_s: float = 120.0, iters: int = 1) -> List[dict]:
        """Issue ``iters`` back-to-back full reduce reads on every given
        child (all in-flight concurrently), parse the RESULT lines."""
        targets = self.children if children is None else children
        for c in targets:
            c.send(f"query {iters}")
        out = []
        for c in targets:
            line = c.readline(timeout_s)
            assert line.startswith(RESULT), (c.executor_id, line)
            _, digest, ms, stats = line.split(None, 3)
            out.append({"executor_id": c.executor_id, "digest": digest,
                        "query_ms": float(ms),
                        "stats": json.loads(stats)})
        return out

    def driver_digest(self) -> str:
        return read_digest(self.driver, self.nprocs, self.nparts)

    # -- teardown --------------------------------------------------------
    def close(self) -> List[str]:
        """Graceful exit for every still-running child (dumping its
        event log), then driver + registry teardown.  Returns the event
        logs that exist (a SIGKILLed victim never writes one)."""
        from ..observability import tracer as OT
        from ..observability.export import write_event_log
        for c in self.children:
            if c.proc.poll() is None:
                try:
                    c.proc.send_signal(signal.SIGCONT)  # un-freeze first
                    c.send("exit")
                except (BrokenPipeError, OSError):
                    pass
        for c in self.children:
            try:
                c.proc.wait(timeout=30)
            except subprocess.TimeoutExpired:
                c.proc.kill()
                c.proc.wait(timeout=10)
        driver_log = os.path.join(self.out, "chaos-driver.jsonl")
        tr = OT.get_tracer()
        write_event_log(driver_log, tr.snapshot(), tr.meta())
        self.driver.close()
        self.registry.close()
        return [driver_log] + [c.elog for c in self.children
                               if os.path.exists(c.elog)]


def _seeded_delay_ms(seed: int, tag: str) -> int:
    """Deterministic mid-query fault point derived from the seed (the
    same spirit as robustness/faults.py's seeded decisions)."""
    import zlib
    return 20 + (zlib.crc32(f"{seed}:{tag}".encode()) % 200)


# ---------------------------------------------------------------------------
# scenarios
# ---------------------------------------------------------------------------

def run_sigkill(nprocs: int = 3, seed: int = 7, rows: int = 512,
                out_dir: Optional[str] = None) -> dict:
    """SIGKILL one executor mid-query: survivors must converge on the
    bit-identical digest via retry -> recompute while the detector
    declares the peer dead."""
    exp = expected_digest(seed, nprocs, rows)
    cl = ChaosCluster(nprocs, seed, rows, out_dir)
    try:
        clean = cl.query() + [{"executor_id": "chaos-driver",
                               "digest": cl.driver_digest(),
                               "query_ms": 0.0, "stats": {}}]
        assert all(r["digest"] == exp for r in clean), clean
        clean_ms = max(r["query_ms"] for r in clean)

        # degraded round: a sustained query stream (the kill must land
        # MID-query, with iterations still left to recover)
        for c in cl.survivors:
            c.send("query 40")
        time.sleep(_seeded_delay_ms(seed, "peer.kill") / 1e3)
        cl.kill_victim()
        cl.expire_victim()   # the registry timeout, made deterministic
        # the survivors are already computing; poll the driver's
        # detector FIRST so detection_ms really measures kill -> dead
        detection_ms = cl.wait_dead()
        degraded = []
        for c in cl.survivors:
            line = c.readline(120.0)
            assert line.startswith(RESULT), (c.executor_id, line)
            _, digest, ms, stats = line.split(None, 3)
            degraded.append({"executor_id": c.executor_id,
                             "digest": digest, "query_ms": float(ms),
                             "stats": json.loads(stats)})
        assert all(r["digest"] == exp for r in degraded), degraded
        recomputes = sum(r["stats"].get("recomputed", 0)
                         + r["stats"].get("proactive_recomputes", 0)
                         for r in degraded)
        assert recomputes > 0, degraded
        logs = cl.close()
        return {"scenario": "sigkill", "ok": True, "expected": exp,
                "procs": nprocs, "seed": seed,
                "clean_query_ms": round(clean_ms, 1),
                "degraded_query_ms": round(
                    max(r["query_ms"] for r in degraded), 1),
                "detection_ms": round(detection_ms, 1),
                "recompute_ms": round(sum(
                    r["stats"].get("recompute_ms", 0.0)
                    for r in degraded), 1),
                "blocks_recomputed": recomputes,
                "event_logs": logs}
    except BaseException:
        cl.close()
        raise


def run_zombie(nprocs: int = 3, seed: int = 7, rows: int = 512,
               out_dir: Optional[str] = None) -> dict:
    """The epoch-fencing proof: a SIGSTOPped executor outlives its
    dead-declaration AND its replacement's registration, then comes
    back serving at the old epoch.  Every one of its responses must be
    refused (StaleBlockEpoch -> recompute) — zero stale blocks consumed,
    digest still bit-identical."""
    exp = expected_digest(seed, nprocs, rows)
    cl = ChaosCluster(nprocs, seed, rows, out_dir, victim_heartbeat=False)
    try:
        # clean parity through the DRIVER only: the survivors must not
        # fetch yet, or the proactive-recompute path would republish the
        # victim's maps locally and the degraded round would never reach
        # the zombie's socket.  Their armed heartbeat loops warm every
        # peer epoch to 1 regardless.
        t0 = time.monotonic()
        assert cl.driver_digest() == exp
        clean_ms = (time.monotonic() - t0) * 1e3

        time.sleep(_seeded_delay_ms(seed, "peer.stall") / 1e3)
        cl.stop_victim()
        t_stop = time.monotonic()
        cl.expire_victim()
        detection_ms = cl.wait_dead()
        fenced_epoch = cl.register_replacement()
        assert fenced_epoch >= 2, fenced_epoch
        cl.cont_victim()                # the zombie serves again...

        degraded = cl.query(cl.survivors)
        assert all(r["digest"] == exp for r in degraded), degraded
        stale = sum(r["stats"].get("stale_epoch", 0) for r in degraded)
        assert stale > 0, ("zombie was never fenced", degraded)
        logs = cl.close()
        return {"scenario": "zombie", "ok": True, "expected": exp,
                "procs": nprocs, "seed": seed,
                "fenced_epoch": fenced_epoch,
                "stale_epochs_refused": stale,
                "clean_query_ms": round(clean_ms, 1),
                "degraded_query_ms": round(
                    max(r["query_ms"] for r in degraded), 1),
                "detection_ms": round(detection_ms, 1),
                "recompute_ms": round(sum(
                    r["stats"].get("recompute_ms", 0.0)
                    for r in degraded), 1),
                "event_logs": logs,
                "_t_stop": t_stop}
    except BaseException:
        cl.close()
        raise


def run_partition(nprocs: int = 3, seed: int = 7, rows: int = 512,
                  out_dir: Optional[str] = None) -> dict:
    """Asymmetric partition (frozen peer): after dead-declaration every
    fetch takes the dead-skip fast path — PeerDead straight to
    recompute, no socket ever touched, no retry budget burned."""
    exp = expected_digest(seed, nprocs, rows)
    cl = ChaosCluster(nprocs, seed, rows, out_dir)
    try:
        # driver-only clean parity (same reasoning as run_zombie: keep
        # the survivors' local stores cold so the degraded round proves
        # the dead-skip failover, not the proactive-recompute cache)
        t0 = time.monotonic()
        assert cl.driver_digest() == exp
        clean_ms = (time.monotonic() - t0) * 1e3
        cl.stop_victim()
        t_stop = time.monotonic()
        cl.expire_victim()
        detection_ms = cl.wait_dead()
        degraded = cl.query(cl.survivors)
        assert all(r["digest"] == exp for r in degraded), degraded
        failovers = sum(r["stats"].get("dead_failovers", 0)
                        + r["stats"].get("recomputed", 0)
                        + r["stats"].get("proactive_recomputes", 0)
                        for r in degraded)
        assert failovers > 0, degraded
        logs = cl.close()
        return {"scenario": "partition", "ok": True, "expected": exp,
                "procs": nprocs, "seed": seed,
                "detection_ms": round(detection_ms, 1),
                "degraded_query_ms": round(
                    max(r["query_ms"] for r in degraded), 1),
                "clean_query_ms": round(clean_ms, 1),
                "dead_failovers": failovers,
                "event_logs": logs, "_t_stop": t_stop}
    except BaseException:
        cl.close()
        raise


SCENARIOS = {"sigkill": run_sigkill, "zombie": run_zombie,
             "partition": run_partition}


def run_suite(scenarios: List[str], nprocs: int = 3, seed: int = 7,
              rows: int = 512, out_dir: Optional[str] = None) -> dict:
    """Run the asked scenarios and fold their latencies into one
    ``fault_recovery`` record (the bench-artifact phase the perf ledger
    banks beside the throughput phases)."""
    results = []
    for name in scenarios:
        sub = os.path.join(out_dir, name) if out_dir else None
        results.append(SCENARIOS[name](nprocs, seed, rows, sub))
    phase = {}
    for r in results:
        for k in ("detection_ms", "recompute_ms", "degraded_query_ms",
                  "clean_query_ms", "stale_epochs_refused",
                  "blocks_recomputed"):
            if k in r:
                phase[f"{r['scenario']}_{k}"] = r[k]
    detections = [r["detection_ms"] for r in results
                  if "detection_ms" in r]
    return {
        # a bare bench result record (tools/bench_diff.py load_artifact):
        # the headline value is the WORST failure-detection latency —
        # the bound every recovery path waits behind
        "metric": "fault_recovery_detection_ms",
        "value": max(detections) if detections else 0.0,
        "extra_metrics": {"fault_recovery": phase},
        "fault_recovery": phase,
        "scenarios": [{k: v for k, v in r.items()
                       if not k.startswith("_")} for r in results],
        "ok": all(r["ok"] for r in results)}
