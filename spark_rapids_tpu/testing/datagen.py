"""Composable, seeded random data generators — an original implementation
of the reference's integration-test datagen design (``data_gen.py:38-751``:
per-type generators with nullability, special values, and nesting) used by
the independent-oracle test harness (engine vs pandas, not engine-vs-own-
numpy-backend, which shares bugs by construction — VERDICT r1 weak #6).

Every generator is deterministic under a seed and produces a pyarrow array;
``gen_table`` assembles a full table.  Special values (extreme ints, NaN,
±inf, ±0.0, empty strings, epoch boundaries) are mixed in at a fixed rate
so boundary behavior is exercised at every scale.
"""

from __future__ import annotations

import datetime as _dt
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa


class DataGen:
    """Base: generates a pyarrow array of length n; subclasses implement
    ``_values(rng, n)`` returning a python list or numpy array."""

    arrow_type: pa.DataType = None  # type: ignore

    def __init__(self, nullable: bool = True, null_rate: float = 0.08,
                 special_rate: float = 0.05):
        self.nullable = nullable
        self.null_rate = null_rate if nullable else 0.0
        self.special_rate = special_rate

    # --- interface --------------------------------------------------------
    def _values(self, rng: np.random.Generator, n: int) -> List:
        raise NotImplementedError

    def _specials(self) -> List:
        return []

    def gen(self, rng: np.random.Generator, n: int) -> pa.Array:
        vals = list(self._values(rng, n))
        specials = self._specials()
        if specials and self.special_rate > 0:
            hits = rng.random(n) < self.special_rate
            picks = rng.integers(0, len(specials), n)
            for i in np.nonzero(hits)[0]:
                vals[i] = specials[int(picks[i])]
        if self.null_rate > 0:
            nulls = rng.random(n) < self.null_rate
            for i in np.nonzero(nulls)[0]:
                vals[i] = None
        return pa.array(vals, type=self.arrow_type)


class BooleanGen(DataGen):
    arrow_type = pa.bool_()

    def _values(self, rng, n):
        return rng.integers(0, 2, n).astype(bool).tolist()


class _IntGen(DataGen):
    _lo = _hi = 0

    def __init__(self, min_val: Optional[int] = None,
                 max_val: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.min_val = self._lo if min_val is None else min_val
        self.max_val = self._hi if max_val is None else max_val

    def _values(self, rng, n):
        return rng.integers(self.min_val, self.max_val + 1, n,
                            dtype=np.int64).tolist()

    def _specials(self):
        return [self.min_val, self.max_val, 0, 1, -1]


class ByteGen(_IntGen):
    arrow_type = pa.int8()
    _lo, _hi = -128, 127


class ShortGen(_IntGen):
    arrow_type = pa.int16()
    _lo, _hi = -(1 << 15), (1 << 15) - 1


class IntegerGen(_IntGen):
    arrow_type = pa.int32()
    _lo, _hi = -(1 << 31), (1 << 31) - 1


class LongGen(_IntGen):
    arrow_type = pa.int64()
    _lo, _hi = -(1 << 63), (1 << 63) - 1


class FloatGen(DataGen):
    arrow_type = pa.float32()

    def __init__(self, no_nans: bool = False, no_extremes: bool = False,
                 **kw):
        super().__init__(**kw)
        self.no_nans = no_nans
        self.no_extremes = no_extremes  # drop ±max (sums overflow to ±inf
        # in an order-dependent way, poisoning aggregation oracles)

    def _values(self, rng, n):
        return ((rng.random(n) - 0.5) * 2e6).astype(np.float32).tolist()

    def _specials(self):
        base = [0.0, -0.0, 1.0, -1.0, 1.17549435e-38]
        if not self.no_extremes:
            base += [3.4028235e38, -3.4028235e38]
        if not self.no_nans:
            base += [float("nan"), float("inf"), float("-inf")]
        return base


class DoubleGen(DataGen):
    arrow_type = pa.float64()

    def __init__(self, no_nans: bool = False, no_extremes: bool = False,
                 **kw):
        super().__init__(**kw)
        self.no_nans = no_nans
        self.no_extremes = no_extremes

    def _values(self, rng, n):
        return ((rng.random(n) - 0.5) * 2e12).tolist()

    def _specials(self):
        base = [0.0, -0.0, 1.0, -1.0, 2.2250738585072014e-308]
        if not self.no_extremes:
            base += [1.7976931348623157e308, -1.7976931348623157e308]
        if not self.no_nans:
            base += [float("nan"), float("inf"), float("-inf")]
        return base


class DecimalGen(DataGen):
    def __init__(self, precision: int = 18, scale: int = 2, **kw):
        super().__init__(**kw)
        self.precision, self.scale = precision, scale
        self.arrow_type = pa.decimal128(precision, scale)
        self._m = 10 ** (precision - scale) - 1

    def _values(self, rng, n):
        from decimal import Decimal
        unscaled = rng.integers(-self._m, self._m, n)
        q = Decimal(1).scaleb(-self.scale)
        return [(Decimal(int(u)) * q) for u in unscaled]

    def _specials(self):
        from decimal import Decimal
        q = Decimal(1).scaleb(-self.scale)
        return [Decimal(0) * q, Decimal(self._m) * q, Decimal(-self._m) * q]


_DEFAULT_CHARS = ("abcdefghijklmnopqrstuvwxyz"
                  "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789 _-.,:/@")


class StringGen(DataGen):
    arrow_type = pa.string()

    def __init__(self, charset: str = _DEFAULT_CHARS, min_len: int = 0,
                 max_len: int = 24, **kw):
        super().__init__(**kw)
        self.charset = charset
        self.min_len, self.max_len = min_len, max_len

    def _values(self, rng, n):
        lens = rng.integers(self.min_len, self.max_len + 1, n)
        chars = rng.integers(0, len(self.charset), int(lens.sum()))
        out, pos = [], 0
        for ln in lens:
            out.append("".join(self.charset[c]
                               for c in chars[pos:pos + ln]))
            pos += ln
        return out

    def _specials(self):
        return ["", " ", "NULL", "null", "0", "-1", "a" * self.max_len,
                "é中ñ"[:max(self.max_len, 1)]]


class DateGen(DataGen):
    arrow_type = pa.date32()

    def _values(self, rng, n):
        days = rng.integers(-25000, 25000, n)  # ~1901..2106
        epoch = _dt.date(1970, 1, 1)
        return [epoch + _dt.timedelta(days=int(d)) for d in days]

    def _specials(self):
        return [_dt.date(1970, 1, 1), _dt.date(2000, 2, 29),
                _dt.date(1969, 12, 31), _dt.date(2038, 1, 19)]


class TimestampGen(DataGen):
    arrow_type = pa.timestamp("us", tz="UTC")

    def _values(self, rng, n):
        micros = rng.integers(-2_000_000_000_000_000, 4_000_000_000_000_000,
                              n)
        return micros.tolist()

    def gen(self, rng, n):  # micros -> arrow timestamps directly
        vals = list(self._values(rng, n))
        if self.null_rate > 0:
            nulls = rng.random(n) < self.null_rate
            for i in np.nonzero(nulls)[0]:
                vals[i] = None
        return pa.array(vals, type=self.arrow_type)


class ArrayGen(DataGen):
    def __init__(self, child: DataGen, min_len: int = 0, max_len: int = 6,
                 **kw):
        super().__init__(**kw)
        self.child = child
        self.min_len, self.max_len = min_len, max_len
        self.arrow_type = pa.list_(child.arrow_type)

    def _values(self, rng, n):
        lens = rng.integers(self.min_len, self.max_len + 1, n)
        flat = self.child.gen(rng, int(lens.sum())).to_pylist()
        out, pos = [], 0
        for ln in lens:
            out.append(flat[pos:pos + ln])
            pos += ln
        return out


class MapGen(DataGen):
    def __init__(self, key: Optional[DataGen] = None,
                 value: Optional[DataGen] = None, max_len: int = 4, **kw):
        super().__init__(**kw)
        self.key = key or StringGen(min_len=1, max_len=6, nullable=False)
        self.value = value or LongGen(min_val=-1000, max_val=1000)
        self.max_len = max_len
        self.arrow_type = pa.map_(self.key.arrow_type, self.value.arrow_type)

    def _values(self, rng, n):
        lens = rng.integers(0, self.max_len + 1, n)
        total = int(lens.sum())
        keys = self.key.gen(rng, total).to_pylist()
        vals = self.value.gen(rng, total).to_pylist()
        out, pos = [], 0
        for ln in lens:
            # map keys must be unique per row
            seen, items = set(), []
            for k, v in zip(keys[pos:pos + ln], vals[pos:pos + ln]):
                if k not in seen:
                    seen.add(k)
                    items.append((k, v))
            out.append(items)
            pos += ln
        return out


class StructGen(DataGen):
    def __init__(self, fields: Sequence[Tuple[str, DataGen]], **kw):
        super().__init__(**kw)
        self.fields = list(fields)
        self.arrow_type = pa.struct(
            [pa.field(n, g.arrow_type) for n, g in self.fields])

    def _values(self, rng, n):
        cols = {name: g.gen(rng, n).to_pylist() for name, g in self.fields}
        return [{name: cols[name][i] for name, _ in self.fields}
                for i in range(n)]


def gen_table(gens: Dict[str, DataGen], n: int, seed: int = 0) -> pa.Table:
    """Deterministic table: one independent rng stream per column so adding
    a column never perturbs the others (reference datagen invariant)."""
    arrays, names = [], []
    for i, (name, g) in enumerate(gens.items()):
        rng = np.random.default_rng([seed, i])
        arrays.append(g.gen(rng, n))
        names.append(name)
    return pa.table(dict(zip(names, arrays)))
