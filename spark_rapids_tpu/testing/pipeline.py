"""Pipelined-execution rig — parity and speedup evidence for the async
execution layer (docs/async_pipeline.md).

Runs the same TPC-H-ish multi-partition suite the chaos soak uses
(testing/chaos.py QUERIES over scaletest.build_tables data) twice: once
on the serial engine, once with the parallel partition scheduler +
prefetch queues + double-buffered transfers, asserts the results are
BIT-IDENTICAL, and reports the wall-clock delta.  Used by

* bench.py           — the banked ``pipeline_*`` artifact metrics
  (pipeline-off vs pipeline-on, ISSUE 5 acceptance evidence),
* tests/test_async_pipeline.py — the parity matrix, and
* ad hoc:  python -m spark_rapids_tpu.testing.pipeline [rows]

On a single-core XLA:CPU host the speedup is bounded by how much real
blocking (file/network I/O, device round trips) the workload has to
hide; on the TPU tunnel every transfer is a ~65ms network round trip
(docs/perf_notes.md), which is exactly what the overlap reclaims.
"""

from __future__ import annotations

import time
from typing import Dict, Optional

import pandas as pd


def pipeline_conf(parallelism: int = 4, prefetch: bool = True,
                  depth: int = 2, double_buffer: bool = True
                  ) -> Dict[str, object]:
    """Conf overrides enabling the three pipeline features.  Device
    admission (concurrentGpuTasks) follows the scheduler width so the
    pool can actually overlap; set it lower to measure admission
    contention (sem_wait spans)."""
    return {
        "spark.rapids.tpu.task.parallelism": parallelism,
        "spark.rapids.sql.concurrentGpuTasks": max(1, parallelism),
        "spark.rapids.tpu.prefetch.enabled": prefetch,
        "spark.rapids.tpu.prefetch.depth": depth,
        "spark.rapids.tpu.transfer.doubleBuffer.enabled": double_buffer,
    }


def _suite_conf() -> Dict[str, object]:
    # shuffled (not broadcast) joins so the exchanges see real traffic —
    # same shape the chaos soak exercises
    return {"spark.rapids.sql.autoBroadcastJoinThreshold": 1}


def run_suite(sess, tables) -> Dict[str, pd.DataFrame]:
    """Canonicalized result frames for every suite query."""
    from ..sql import functions as F
    from .chaos import QUERIES, _canonical, augment_tables
    tables = augment_tables(tables)
    return {name: _canonical(fn(sess, tables, F)) for name, fn in QUERIES}


def measure(rows: int = 120_000, repeats: int = 2,
            parallelism: int = 4,
            tables: Optional[dict] = None) -> dict:
    """Serial vs pipelined wall clock over the suite with a bit-parity
    assert; returns the banked-artifact record."""
    import spark_rapids_tpu as srt
    from ..config import RapidsConf
    from .scaletest import build_tables
    if tables is None:
        tables = build_tables(rows)

    def timed(sess):
        run_suite(sess, tables)  # warm: compiles + upload cache
        best, last = None, None
        for _ in range(max(1, repeats)):
            t0 = time.perf_counter()
            last = run_suite(sess, tables)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        return best, last

    base = RapidsConf.get_global()
    off_sess = srt.session(conf=base.copy(_suite_conf()))
    off_s, off_res = timed(off_sess)

    on_conf = dict(_suite_conf())
    on_conf.update(pipeline_conf(parallelism=parallelism))
    on_sess = srt.session(conf=base.copy(on_conf))
    on_s, on_res = timed(on_sess)

    mismatches = []
    for name in off_res:
        try:
            pd.testing.assert_frame_equal(on_res[name], off_res[name],
                                          check_exact=True)
        except AssertionError as e:
            mismatches.append(f"{name}: {e}")
    assert not mismatches, \
        "pipelined run diverged from the serial run:\n" + \
        "\n".join(mismatches)

    return {
        "pipeline_rows": rows,
        "pipeline_queries": len(off_res),
        "pipeline_parallelism": parallelism,
        "pipeline_off_seconds": round(off_s, 4),
        "pipeline_on_seconds": round(on_s, 4),
        "pipeline_speedup": round(off_s / max(on_s, 1e-9), 3),
        "pipeline_bit_identical": True,
    }


def main() -> None:
    import json
    import os
    import sys
    plat = os.environ.get("SRT_SCALE_PLATFORM", "cpu")
    if plat == "cpu":
        from spark_rapids_tpu import pin_host_platform
        pin_host_platform()
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 120_000
    print(json.dumps(measure(rows), indent=2))


if __name__ == "__main__":
    main()
