"""Shared scale-rig runner plumbing (used by tpch_queries and
tpcds_queries so the caching idiom exists exactly once)."""

from __future__ import annotations

from typing import Callable, Dict

import pyarrow as pa


class ViewCache:
    """Registers a table set as temp views once per (session, tables)
    pair.  Strong refs compared with ``is``: id() of a freed object can
    be recycled by the allocator, which would alias a stale cache hit and
    skip registration on a fresh session."""

    def __init__(self, register: Callable):
        self._register = register
        self._sess = None
        self._tables = None

    def ensure(self, sess, tables: Dict[str, pa.Table]) -> None:
        if self._sess is not sess or self._tables is not tables:
            self._register(sess, tables)
            self._sess, self._tables = sess, tables
