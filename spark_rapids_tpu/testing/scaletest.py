"""Scale-test harness — the analog of the reference's
``integration_tests/.../scaletest/QuerySpecs.scala`` + ``datagen/``
(SURVEY §4 tier 4): a deterministic query suite over generated join/agg/
window-shaped data with controllable scale, each query checked against a
pandas oracle and timed.

Run standalone:  python -m spark_rapids_tpu.testing.scaletest [rows]
(CI runs it small through tests/test_scale.py; crank ``rows`` for a rig.)
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from .datagen import (DoubleGen, IntegerGen, LongGen, StringGen, gen_table)
from . import tpcds_queries as _TDS
from . import tpch_queries as _TQ


def build_tables(rows: int, seed: int = 17) -> Dict[str, pa.Table]:
    """fact + two dimensions with skewed keys (the reference's datagen
    controls cardinality/skew the same way)."""
    rng = np.random.default_rng(seed)
    # skew: 20% of fact rows land on 1% of keys
    n_keys = max(rows // 100, 10)
    hot = rng.integers(0, max(n_keys // 100, 1), rows // 5)
    cold = rng.integers(0, n_keys, rows - rows // 5)
    keys = np.concatenate([hot, cold])
    rng.shuffle(keys)
    fact = gen_table({
        "v": DoubleGen(no_nans=True, no_extremes=True),
        "q": IntegerGen(0, 100, nullable=False),
        "s": StringGen(max_len=12),
    }, rows, seed=seed)
    fact = fact.append_column("k", pa.array(keys, type=pa.int64()))
    dim = gen_table({
        "w": DoubleGen(no_nans=True, no_extremes=True, nullable=False),
        "cat": IntegerGen(0, 8, nullable=False),
    }, n_keys, seed=seed + 1)
    dim = dim.append_column("k", pa.array(np.arange(n_keys),
                                          type=pa.int64()))
    return {"fact": fact, "dim": dim}


def _q1(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    got = (fact.filter(fact.q < 50)
           .groupBy("q").agg(F.sum(fact.v).alias("sv"),
                             F.count("*").alias("c"))
           .orderBy("q").collect().to_pandas())
    pdf = t["fact"].to_pandas()
    pdf = pdf[pdf.q < 50]
    exp = pdf.groupby("q").agg(sv=("v", "sum"), c=("q", "size")).reset_index()
    assert np.array_equal(got["q"], exp["q"])
    assert np.allclose(got["sv"].fillna(0), exp["sv"].fillna(0))
    assert np.array_equal(got["c"], exp["c"])


def _q2(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    dim = sess.create_dataframe(t["dim"], num_partitions=2)
    got = (fact.join(dim, on="k", how="inner")
           .groupBy("cat").agg(F.count("*").alias("n"),
                               F.sum(fact.v).alias("sv"))
           .orderBy("cat").collect().to_pandas())
    exp = (t["fact"].to_pandas().merge(t["dim"].to_pandas(), on="k")
           .groupby("cat").agg(n=("k", "size"), sv=("v", "sum"))
           .reset_index())
    assert np.array_equal(got["cat"], exp["cat"])
    assert np.array_equal(got["n"], exp["n"])
    assert np.allclose(got["sv"].fillna(0), exp["sv"].fillna(0))


def _q3(sess, t, F):
    """skewed join: the hot keys stress partition balance."""
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    dim = sess.create_dataframe(t["dim"], num_partitions=2)
    got = (fact.join(dim, on="k", how="left")
           .filter(fact.q >= 90).select(fact.k, fact.v, dim.w)
           .orderBy("k", "v").collect().to_pandas())
    pdf = t["fact"].to_pandas()
    exp = (pdf[pdf.q >= 90].merge(t["dim"].to_pandas(), on="k", how="left")
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert len(got) == len(exp)
    assert np.array_equal(got["k"], exp["k"])
    gw, ew = got["w"].to_numpy(), exp["w"].to_numpy()
    m = ~np.isnan(ew)
    assert np.allclose(gw[m], ew[m]) and np.isnan(gw[~m]).all()


def _q4(sess, t, F):
    from ..sql.window_api import Window
    fact = sess.create_dataframe(t["fact"], num_partitions=2)
    w = Window.partitionBy("q").orderBy("v")
    got = (fact.select(fact.q, fact.v,
                       F.row_number().over(w).alias("rn"))
           .filter(F.col("rn") <= 3)
           .collect().to_pandas())
    pdf = t["fact"].to_pandas().dropna(subset=["v"])
    exp = (pdf.sort_values(["q", "v"]).groupby("q").head(3))
    # row_number over possibly-null v: compare counts per q
    got_counts = got.groupby("q").size()
    exp_counts = exp.groupby("q").size()
    assert got_counts.max() <= 3  # the rn<=3 filter actually filtered
    for q in exp_counts.index:
        assert got_counts.get(q, 0) >= min(3, exp_counts[q]) - 1


def _q5(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    got = (fact.orderBy(fact.v.desc_nulls_first(), "k")
           .select(fact.k, fact.v).collect().to_pandas())
    assert len(got) == t["fact"].num_rows
    vals = got["v"].to_numpy()
    nn = vals[~np.isnan(vals)]
    assert np.all(np.diff(nn) <= 1e-12)  # descending


def _q6(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    got = (fact.select(F.upper(fact.s).alias("u"),
                       F.length(fact.s).alias("ln"))
           .filter(F.col("ln") > 4).count())
    pdf = t["fact"].to_pandas()
    exp = int((pdf.s.str.len() > 4).sum())
    assert got == exp


def build_tpch_tables(rows: int, seed: int = 23) -> Dict[str, pa.Table]:
    """Full 8-table TPC-H set (round 4: the 22-query suite needs
    supplier/partsupp/nation/region and the full column complement —
    ``tpch_queries.build_tables`` owns the schema now)."""
    from .tpch_queries import build_tables
    return build_tables(rows, seed)


def _q1_oracle_check(got, lineitem_table):
    """Shared pandas oracle for TPC-H q1 (DataFrame-API and SQL forms)."""
    import datetime
    pdf = lineitem_table.to_pandas()
    pdf = pdf[pdf.l_shipdate <= datetime.date(1998, 9, 2)]
    dp = pdf.l_extendedprice * (1.0 - pdf.l_discount)
    ch = dp * (1.0 + pdf.l_tax)
    exp = (pd.DataFrame({
        "rf": pdf.l_returnflag, "ls": pdf.l_linestatus,
        "q": pdf.l_quantity, "p": pdf.l_extendedprice, "dp": dp,
        "ch": ch, "d": pdf.l_discount})
        .groupby(["rf", "ls"])
        .agg(sum_qty=("q", "sum"), sum_base_price=("p", "sum"),
             sum_disc_price=("dp", "sum"), sum_charge=("ch", "sum"),
             avg_qty=("q", "mean"), avg_price=("p", "mean"),
             avg_disc=("d", "mean"), count_order=("q", "size"))
        .sort_index().reset_index())
    assert list(got["l_returnflag"]) == list(exp["rf"])
    assert list(got["l_linestatus"]) == list(exp["ls"])
    for col in ("sum_qty", "sum_base_price", "sum_disc_price",
                "sum_charge", "avg_qty", "avg_price", "avg_disc"):
        assert np.allclose(got[col], exp[col]), col
    assert np.array_equal(got["count_order"], exp["count_order"])


def _q6_oracle_check(got, lineitem_table):
    """Shared pandas oracle for TPC-H q6 (DataFrame-API and SQL forms)."""
    import datetime
    pdf = lineitem_table.to_pandas()
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    m = ((pdf.l_shipdate >= lo) & (pdf.l_shipdate < hi)
         & (pdf.l_discount >= 0.05) & (pdf.l_discount <= 0.07)
         & (pdf.l_quantity < 24.0))
    exp = float((pdf.l_extendedprice[m] * pdf.l_discount[m]).sum())
    assert np.allclose(got["revenue"].fillna(0.0), exp)


def _tpch_q1(sess, t, F):
    """TPC-H q1: pricing summary report (BASELINE milestone 2)."""
    import datetime
    li = sess.create_dataframe(t["lineitem"], num_partitions=4)
    cutoff = datetime.date(1998, 9, 2)
    got = (li.filter(li.l_shipdate <= F.lit(cutoff))
           .withColumn("disc_price",
                       li.l_extendedprice * (1.0 - li.l_discount))
           .withColumn("charge", li.l_extendedprice
                       * (1.0 - li.l_discount) * (1.0 + li.l_tax))
           .groupBy("l_returnflag", "l_linestatus")
           .agg(F.sum(F.col("l_quantity")).alias("sum_qty"),
                F.sum(F.col("l_extendedprice")).alias("sum_base_price"),
                F.sum(F.col("disc_price")).alias("sum_disc_price"),
                F.sum(F.col("charge")).alias("sum_charge"),
                F.avg(F.col("l_quantity")).alias("avg_qty"),
                F.avg(F.col("l_extendedprice")).alias("avg_price"),
                F.avg(F.col("l_discount")).alias("avg_disc"),
                F.count("*").alias("count_order"))
           .orderBy("l_returnflag", "l_linestatus")
           .collect().to_pandas())
    _q1_oracle_check(got, t["lineitem"])


def _tpch_q6(sess, t, F):
    """TPC-H q6: forecast revenue change (BASELINE milestone 2)."""
    import datetime
    li = sess.create_dataframe(t["lineitem"], num_partitions=4)
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    got = (li.filter((li.l_shipdate >= F.lit(lo))
                     & (li.l_shipdate < F.lit(hi))
                     & (li.l_discount >= 0.05) & (li.l_discount <= 0.07)
                     & (li.l_quantity < 24.0))
           .agg(F.sum(F.col("l_extendedprice") * F.col("l_discount"))
                .alias("revenue"))
           .collect().to_pandas())
    _q6_oracle_check(got, t["lineitem"])


def _tpch_q4(sess, t, F):
    """TPC-H q4 shape: EXISTS subquery as a LEFT SEMI join (late lineitems
    per order), priority counts — exercises the semi-join planning path on
    a benchmark query (reference: semi joins via GpuHashJoin)."""
    import datetime
    lo, hi = datetime.date(1993, 7, 1), datetime.date(1993, 10, 1)
    o = sess.create_dataframe(t["orders"], num_partitions=4)
    li = sess.create_dataframe(t["lineitem"], num_partitions=4)
    late = li.filter(li.l_commitdate < li.l_receiptdate)
    got = (o.filter((o.o_orderdate >= F.lit(lo)) & (o.o_orderdate < F.lit(hi)))
           .join(late, o.o_orderkey == late.l_orderkey, how="left_semi")
           .groupBy("o_orderpriority")
           .agg(F.count("*").alias("order_count"))
           .orderBy("o_orderpriority")
           .collect().to_pandas())
    op = t["orders"].to_pandas()
    lp = t["lineitem"].to_pandas()
    late_keys = set(lp.l_orderkey[lp.l_commitdate < lp.l_receiptdate])
    op = op[(op.o_orderdate >= lo) & (op.o_orderdate < hi)
            & op.o_orderkey.isin(late_keys)]
    exp = (op.groupby("o_orderpriority").size()
           .sort_index().reset_index(name="order_count"))
    assert list(got["o_orderpriority"]) == list(exp["o_orderpriority"])
    assert np.array_equal(got["order_count"], exp["order_count"])


def _tpch_q14(sess, t, F):
    """TPC-H q14 shape: join + conditional aggregation (CASE WHEN p_type
    LIKE 'PROMO%') — promo revenue percentage."""
    import datetime
    lo, hi = datetime.date(1995, 9, 1), datetime.date(1995, 10, 1)
    li = sess.create_dataframe(t["lineitem"], num_partitions=4)
    p = sess.create_dataframe(t["part"], num_partitions=2)
    j = (li.filter((li.l_shipdate >= F.lit(lo)) & (li.l_shipdate < F.lit(hi)))
         .join(p, li.l_partkey == p.p_partkey))
    rev = j.l_extendedprice * (1.0 - j.l_discount)
    got = (j.agg((F.sum(F.when(j.p_type.startswith("PROMO"), rev)
                        .otherwise(0.0)) * 100.0
                  / F.sum(rev)).alias("promo_revenue"))
           .collect().to_pandas())
    lp = t["lineitem"].to_pandas()
    pp = t["part"].to_pandas()
    m = (lp.l_shipdate >= lo) & (lp.l_shipdate < hi)
    jp = lp[m].merge(pp, left_on="l_partkey", right_on="p_partkey")
    r = jp.l_extendedprice * (1.0 - jp.l_discount)
    promo = r[jp.p_type.str.startswith("PROMO")].sum()
    exp = 100.0 * promo / r.sum()
    assert np.allclose(got["promo_revenue"].fillna(0.0), exp)


#: TPC-H q1 as SQL text, exactly the spec's form (the cutoff is interval
#: arithmetic: DATE '1998-12-01' - INTERVAL '90' DAY = 1998-09-02)
_TPCH_Q1_SQL = """
SELECT l_returnflag, l_linestatus,
       sum(l_quantity) AS sum_qty,
       sum(l_extendedprice) AS sum_base_price,
       sum(l_extendedprice * (1 - l_discount)) AS sum_disc_price,
       sum(l_extendedprice * (1 - l_discount) * (1 + l_tax)) AS sum_charge,
       avg(l_quantity) AS avg_qty,
       avg(l_extendedprice) AS avg_price,
       avg(l_discount) AS avg_disc,
       count(*) AS count_order
FROM lineitem
WHERE l_shipdate <= CAST('1998-12-01' AS date) - INTERVAL '90' DAY
GROUP BY l_returnflag, l_linestatus
ORDER BY l_returnflag, l_linestatus
"""

_TPCH_Q6_SQL = """
SELECT sum(l_extendedprice * l_discount) AS revenue
FROM lineitem
WHERE l_shipdate >= CAST('1994-01-01' AS date)
  AND l_shipdate < CAST('1995-01-01' AS date)
  AND l_discount BETWEEN 0.05 AND 0.07
  AND l_quantity < 24
"""


_TPCH_Q4_SQL = """
SELECT o_orderpriority, count(*) AS order_count
FROM orders
WHERE o_orderdate >= CAST('1993-07-01' AS date)
  AND o_orderdate < CAST('1993-10-01' AS date)
  AND EXISTS (
    SELECT 1 FROM lineitem
    WHERE lineitem.l_orderkey = orders.o_orderkey
      AND lineitem.l_commitdate < lineitem.l_receiptdate)
GROUP BY o_orderpriority
ORDER BY o_orderpriority
"""


def _tpch_q4_sql(sess, t, F):
    """TPC-H q4 in its REAL spec form — correlated EXISTS rewritten to a
    left-semi join (Spark RewritePredicateSubquery)."""
    import datetime
    sess.create_dataframe(t["orders"], num_partitions=4) \
        .createOrReplaceTempView("orders")
    sess.create_dataframe(t["lineitem"], num_partitions=4) \
        .createOrReplaceTempView("lineitem")
    got = sess.sql(_TPCH_Q4_SQL).collect().to_pandas()
    op = t["orders"].to_pandas()
    lp = t["lineitem"].to_pandas()
    lo, hi = datetime.date(1993, 7, 1), datetime.date(1993, 10, 1)
    late = set(lp.l_orderkey[lp.l_commitdate < lp.l_receiptdate])
    op = op[(op.o_orderdate >= lo) & (op.o_orderdate < hi)
            & op.o_orderkey.isin(late)]
    exp = (op.groupby("o_orderpriority").size()
           .sort_index().reset_index(name="order_count"))
    assert list(got["o_orderpriority"]) == list(exp["o_orderpriority"])
    assert np.array_equal(got["order_count"], exp["order_count"])


_TPCH_Q22_SQL = """
SELECT cntrycode, count(*) AS numcust, sum(c_acctbal) AS totacctbal
FROM (SELECT substring(c_phone, 1, 2) AS cntrycode, c_acctbal, c_custkey
      FROM customer
      WHERE substring(c_phone, 1, 2) IN ('13', '31', '23', '29', '30')
        AND c_acctbal > (SELECT avg(c_acctbal) FROM customer
                         WHERE c_acctbal > 0.00
                           AND substring(c_phone, 1, 2)
                               IN ('13', '31', '23', '29', '30'))) custsale
WHERE NOT EXISTS (SELECT 1 FROM orders
                  WHERE orders.o_custkey = custsale.c_custkey)
GROUP BY cntrycode
ORDER BY cntrycode
"""


def _tpch_q22_sql(sess, t, F):
    """TPC-H q22 shape (global sales opportunity): IN-list + scalar
    subquery + correlated NOT EXISTS + FROM subquery + group/sort, all
    from SQL text — the full new-subquery machinery on one benchmark
    query."""
    sess.create_dataframe(t["customer"], num_partitions=4) \
        .createOrReplaceTempView("customer")
    sess.create_dataframe(t["orders"], num_partitions=4) \
        .createOrReplaceTempView("orders")
    got = sess.sql(_TPCH_Q22_SQL).collect().to_pandas()
    cp = t["customer"].to_pandas()
    op = t["orders"].to_pandas()
    codes = {"13", "31", "23", "29", "30"}
    cc = cp.c_phone.str[:2]
    sel = cp[cc.isin(codes)]
    avg_bal = cp.c_acctbal[(cp.c_acctbal > 0.0) & cc.isin(codes)].mean()
    sel = sel[sel.c_acctbal > avg_bal]
    sel = sel[~sel.c_custkey.isin(set(op.o_custkey))]
    exp = (sel.assign(cntrycode=sel.c_phone.str[:2])
           .groupby("cntrycode")
           .agg(numcust=("c_acctbal", "size"),
                totacctbal=("c_acctbal", "sum"))
           .sort_index().reset_index())
    assert list(got["cntrycode"]) == list(exp["cntrycode"])
    assert np.array_equal(got["numcust"], exp["numcust"])
    assert np.allclose(got["totacctbal"], exp["totacctbal"])


def _tpch_q1_sql(sess, t, F):
    """TPC-H q1 executed from SQL text — the reference's actual query
    surface (Spark SQL in; SURVEY §1) — checked against a pandas oracle."""
    sess.create_dataframe(t["lineitem"], num_partitions=4) \
        .createOrReplaceTempView("lineitem")
    got = sess.sql(_TPCH_Q1_SQL).collect().to_pandas()
    _q1_oracle_check(got, t["lineitem"])


def _tpch_q6_sql(sess, t, F):
    """TPC-H q6 from SQL text, pandas-oracle checked."""
    sess.create_dataframe(t["lineitem"], num_partitions=4) \
        .createOrReplaceTempView("lineitem")
    got = sess.sql(_TPCH_Q6_SQL).collect().to_pandas()
    _q6_oracle_check(got, t["lineitem"])


def _tpch_q17_sql(sess, t, F):
    """TPC-H q17 shape: correlated scalar subquery (avg quantity per
    part) decorrelated into a grouped-agg LEFT JOIN, pandas-checked."""
    li = t["lineitem"]
    sess.create_dataframe(li, num_partitions=4) \
        .createOrReplaceTempView("lineitem")
    got = sess.sql(
        "SELECT sum(l.l_extendedprice) / 7.0 AS avg_yearly "
        "FROM lineitem l "
        "WHERE l.l_quantity < (SELECT 0.2 * avg(l2.l_quantity) "
        "FROM lineitem l2 WHERE l2.l_partkey = l.l_partkey)"
    ).collect().to_pylist()[0]["avg_yearly"]
    pdf = li.to_pandas()
    th = pdf.groupby("l_partkey").l_quantity.mean() * 0.2
    exp = pdf[pdf.l_quantity < pdf.l_partkey.map(th)] \
        .l_extendedprice.sum() / 7.0
    assert abs(got - exp) <= 1e-9 * max(abs(exp), 1.0), (got, exp)


def build_tpcds_tables(rows: int, seed: int = 31):
    """Delegates to the full star schema (``tpcds_queries.build_tables``
    owns it now — a column-superset of the round-3 5-table subset, so
    existing callers keep working)."""
    return _TDS.build_tables(rows, seed)


def _tpcds_q3(sess, t, F):
    """TPC-DS q3 shape: star join store_sales x date_dim x item with a
    manufacturer + month filter, grouped revenue by (year, brand)."""
    ss = sess.create_dataframe(t["store_sales"], num_partitions=4)
    dd = sess.create_dataframe(t["date_dim"], num_partitions=2)
    it = sess.create_dataframe(t["item"], num_partitions=2)
    got = (ss.join(dd, ss.ss_sold_date_sk == dd.d_date_sk)
           .join(it, ss.ss_item_sk == it.i_item_sk)
           .filter((it.i_manufact_id == 7) & (dd.d_moy == 11))
           .groupBy("d_year", "i_brand_id")
           .agg(F.sum(F.col("ss_ext_sales_price")).alias("sum_agg"))
           .orderBy("d_year", "i_brand_id")
           .collect().to_pandas())
    pdf = (t["store_sales"].to_pandas()
           .merge(t["date_dim"].to_pandas(), left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
           .merge(t["item"].to_pandas(), left_on="ss_item_sk",
                  right_on="i_item_sk"))
    pdf = pdf[(pdf.i_manufact_id == 7) & (pdf.d_moy == 11)]
    exp = (pdf.groupby(["d_year", "i_brand_id"])
           .agg(sum_agg=("ss_ext_sales_price", "sum"))
           .sort_index().reset_index())
    assert np.array_equal(got["d_year"], exp["d_year"])
    assert np.array_equal(got["i_brand_id"], exp["i_brand_id"])
    assert np.allclose(got["sum_agg"], exp["sum_agg"])


def _tpcds_q7(sess, t, F):
    """TPC-DS q7 shape: 4-way star join (store_sales x cdemo x date x
    item x promotion) with demographic + promo-channel filters, four AVGs
    by item (BASELINE config 3)."""
    ss = sess.create_dataframe(t["store_sales"], num_partitions=4)
    cd = sess.create_dataframe(t["customer_demographics"], num_partitions=2)
    dd = sess.create_dataframe(t["date_dim"], num_partitions=2)
    it = sess.create_dataframe(t["item"], num_partitions=2)
    pr = sess.create_dataframe(t["promotion"], num_partitions=2)
    got = (ss.join(cd, ss.ss_cdemo_sk == cd.cd_demo_sk)
           .join(dd, ss.ss_sold_date_sk == dd.d_date_sk)
           .join(it, ss.ss_item_sk == it.i_item_sk)
           .join(pr, ss.ss_promo_sk == pr.p_promo_sk)
           .filter((cd.cd_gender == "M")
                   & (cd.cd_marital_status == "S")
                   & (cd.cd_education_status == "College")
                   & ((pr.p_channel_email == "N")
                      | (pr.p_channel_event == "N"))
                   & (dd.d_year == 2000))
           .groupBy("i_item_sk")
           .agg(F.avg(F.col("ss_quantity")).alias("agg1"),
                F.avg(F.col("ss_list_price")).alias("agg2"),
                F.avg(F.col("ss_coupon_amt")).alias("agg3"),
                F.avg(F.col("ss_ext_sales_price")).alias("agg4"))
           .orderBy("i_item_sk")
           .collect().to_pandas())
    pdf = (t["store_sales"].to_pandas()
           .merge(t["customer_demographics"].to_pandas(),
                  left_on="ss_cdemo_sk", right_on="cd_demo_sk")
           .merge(t["date_dim"].to_pandas(), left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
           .merge(t["item"].to_pandas(), left_on="ss_item_sk",
                  right_on="i_item_sk")
           .merge(t["promotion"].to_pandas(), left_on="ss_promo_sk",
                  right_on="p_promo_sk"))
    pdf = pdf[(pdf.cd_gender == "M") & (pdf.cd_marital_status == "S")
              & (pdf.cd_education_status == "College")
              & ((pdf.p_channel_email == "N") | (pdf.p_channel_event == "N"))
              & (pdf.d_year == 2000)]
    exp = (pdf.groupby("i_item_sk")
           .agg(agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_ext_sales_price", "mean"))
           .sort_index().reset_index())
    assert np.array_equal(got["i_item_sk"], exp["i_item_sk"])
    for c in ("agg1", "agg2", "agg3", "agg4"):
        assert np.allclose(got[c], exp[c]), c


def _tpcds_q19(sess, t, F):
    """TPC-DS q19 shape: brand revenue for a (year, month) window with a
    manager filter — join order stresses the broadcast-vs-shuffle
    decision (BASELINE config 3)."""
    ss = sess.create_dataframe(t["store_sales"], num_partitions=4)
    dd = sess.create_dataframe(t["date_dim"], num_partitions=2)
    it = sess.create_dataframe(t["item"], num_partitions=2)
    got = (dd.join(ss, ss.ss_sold_date_sk == dd.d_date_sk)
           .join(it, ss.ss_item_sk == it.i_item_sk)
           .filter((it.i_manager_id == 8) & (dd.d_moy == 11)
                   & (dd.d_year == 1999))
           .groupBy("i_brand_id")
           .agg(F.sum(F.col("ss_ext_sales_price")).alias("ext_price"))
           .orderBy(F.col("ext_price").desc(), "i_brand_id")
           .collect().to_pandas())
    pdf = (t["store_sales"].to_pandas()
           .merge(t["date_dim"].to_pandas(), left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
           .merge(t["item"].to_pandas(), left_on="ss_item_sk",
                  right_on="i_item_sk"))
    pdf = pdf[(pdf.i_manager_id == 8) & (pdf.d_moy == 11)
              & (pdf.d_year == 1999)]
    exp = (pdf.groupby("i_brand_id")
           .agg(ext_price=("ss_ext_sales_price", "sum")).reset_index()
           .sort_values(["ext_price", "i_brand_id"],
                        ascending=[False, True]).reset_index(drop=True))
    assert np.array_equal(got["i_brand_id"], exp["i_brand_id"])
    assert np.allclose(got["ext_price"], exp["ext_price"])


def _tpcds_q42(sess, t, F):
    """TPC-DS q42 shape: (year, category) revenue for one month
    (BASELINE config 3)."""
    ss = sess.create_dataframe(t["store_sales"], num_partitions=4)
    dd = sess.create_dataframe(t["date_dim"], num_partitions=2)
    it = sess.create_dataframe(t["item"], num_partitions=2)
    got = (dd.join(ss, ss.ss_sold_date_sk == dd.d_date_sk)
           .join(it, ss.ss_item_sk == it.i_item_sk)
           .filter((dd.d_moy == 12) & (dd.d_year == 2000))
           .groupBy("d_year", "i_category_id")
           .agg(F.sum(F.col("ss_ext_sales_price")).alias("total"))
           .orderBy(F.col("total").desc(), "d_year", "i_category_id")
           .collect().to_pandas())
    pdf = (t["store_sales"].to_pandas()
           .merge(t["date_dim"].to_pandas(), left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
           .merge(t["item"].to_pandas(), left_on="ss_item_sk",
                  right_on="i_item_sk"))
    pdf = pdf[(pdf.d_moy == 12) & (pdf.d_year == 2000)]
    exp = (pdf.groupby(["d_year", "i_category_id"])
           .agg(total=("ss_ext_sales_price", "sum")).reset_index()
           .sort_values(["total", "d_year", "i_category_id"],
                        ascending=[False, True, True])
           .reset_index(drop=True))
    assert np.array_equal(got["i_category_id"], exp["i_category_id"])
    assert np.allclose(got["total"], exp["total"])


def _tpcds_q89_window(sess, t, F):
    """TPC-DS q89 shape: monthly category revenue ranked by a window over
    the star join (avg over the category partition; rows where the month
    deviates most from the category average) — the window-over-join shape
    the per-table micro queries don't cover."""
    from ..sql.window_api import Window
    ss = sess.create_dataframe(t["store_sales"], num_partitions=4)
    dd = sess.create_dataframe(t["date_dim"], num_partitions=2)
    it = sess.create_dataframe(t["item"], num_partitions=2)
    monthly = (dd.join(ss, ss.ss_sold_date_sk == dd.d_date_sk)
               .join(it, ss.ss_item_sk == it.i_item_sk)
               .filter(dd.d_year == 2000)
               .groupBy("i_category_id", "d_moy")
               .agg(F.sum(F.col("ss_ext_sales_price")).alias("rev")))
    w = Window.partitionBy("i_category_id")
    got = (monthly
           .withColumn("avg_rev", F.avg(F.col("rev")).over(w))
           .filter(F.col("rev") > F.col("avg_rev"))
           .orderBy("i_category_id", "d_moy")
           .collect().to_pandas())
    pdf = (t["store_sales"].to_pandas()
           .merge(t["date_dim"].to_pandas(), left_on="ss_sold_date_sk",
                  right_on="d_date_sk")
           .merge(t["item"].to_pandas(), left_on="ss_item_sk",
                  right_on="i_item_sk"))
    pdf = pdf[pdf.d_year == 2000]
    m = (pdf.groupby(["i_category_id", "d_moy"])
         .agg(rev=("ss_ext_sales_price", "sum")).reset_index())
    m["avg_rev"] = m.groupby("i_category_id").rev.transform("mean")
    exp = (m[m.rev > m.avg_rev]
           .sort_values(["i_category_id", "d_moy"])
           .reset_index(drop=True))
    assert len(got) == len(exp)
    assert np.array_equal(got["i_category_id"], exp["i_category_id"])
    assert np.array_equal(got["d_moy"], exp["d_moy"])
    assert np.allclose(got["rev"], exp["rev"])
    assert np.allclose(got["avg_rev"], exp["avg_rev"])



QUERIES: List[Tuple[str, Callable]] = [
    ("q1_filter_agg", _q1),
    ("q2_join_agg", _q2),
    ("q3_skewed_left_join", _q3),
    ("q4_window_topn", _q4),
    ("q5_global_sort", _q5),
    ("q6_strings", _q6),
    ("tpch_q1", _tpch_q1),
    ("tpch_q4_semi_join", _tpch_q4),
    ("tpch_q6", _tpch_q6),
    ("tpch_q14_promo_case", _tpch_q14),
    ("tpch_q1_sql", _tpch_q1_sql),
    ("tpch_q4_sql_exists", _tpch_q4_sql),
    ("tpch_q22_sql_subqueries", _tpch_q22_sql),
    ("tpch_q6_sql", _tpch_q6_sql),
    ("tpch_q17_corr_scalar", _tpch_q17_sql),
    # round 4: the 16 queries completing TPC-H 22 (tpch_queries.py)
    *[(f"tpch_{name}_full", _TQ.make_runner(sql, oracle))
      for name, sql, oracle in _TQ.QUERY_SET],
    ("tpcds_q3_star_join", _tpcds_q3),
    ("tpcds_q7_star4_avgs", _tpcds_q7),
    ("tpcds_q19_brand_rev", _tpcds_q19),
    ("tpcds_q42_cat_rev", _tpcds_q42),
    ("tpcds_q89_window_join", _tpcds_q89_window),
    # round 4: 12 more TPC-DS spec-SQL shapes (tpcds_queries.py)
    *[(f"tpcds_{name}", _TDS.make_runner(sql, oracle))
      for name, sql, oracle in _TDS.QUERY_SET],
]

#: table-set builders per query prefix (run_suite routes each query to
#: the tables it expects)
_TABLE_SETS = {"tpch": build_tpch_tables, "tpcds": _TDS.build_tables}


def iter_suite(rows: int, queries=None, tables=None, sess=None,
               extra_tables=None):
    """Per-query streaming driver over :data:`QUERIES` with amortized
    tables/session: yields each report record as its query completes, or
    an ``{"query", "error"}`` record for a failing query.  The one
    iteration loop `main()` and bench.py's suite child both consume."""
    import spark_rapids_tpu as srt
    tables = tables if tables is not None else build_tables(rows)
    extra = extra_tables if extra_tables is not None else {}
    sess = sess or srt.session()
    for name, _fn in QUERIES:
        if queries and name not in queries:
            continue
        try:
            rep = run_suite(rows, queries=[name], tables=tables,
                            sess=sess, extra_tables=extra)
        except Exception as e:
            yield {"query": name,
                   "error": f"{type(e).__name__}: {e}"[:200]}
            continue
        for entry in rep:
            yield entry


#: re-export — the recipe lives at engine level (kernel_cache) so the
#: test conftest does not have to import the whole 60-query rig module
#: just to clear two caches
from ..sql.physical.kernel_cache import (  # noqa: E402
    release_compiled_programs)


class _RecordingTables(dict):
    """Table dict that records which tables a query touches, so the rig
    can report bytes-scanned per query instead of the whole set."""

    def __init__(self, base):
        super().__init__(base)
        self.accessed: set = set()

    def __getitem__(self, key):
        self.accessed.add(key)
        return super().__getitem__(key)

    def get(self, key, default=None):
        if key in self:
            self.accessed.add(key)
        return super().get(key, default)


def run_suite(rows: int = 50_000, queries=None, tables=None,
              sess=None, extra_tables=None) -> List[dict]:
    """Runs the selected queries; pass ``tables``/``sess``/
    ``extra_tables`` (a mutable dict, filled with the per-prefix TPC
    table sets on first use) to amortize datagen and session setup
    across calls.  ``seconds`` includes compile plus the pandas oracle
    check; ``warm_seconds`` is the second run with compiles amortized —
    the number to compare across rigs."""
    import spark_rapids_tpu as srt
    from ..sql import functions as F
    base_tables = tables if tables is not None else build_tables(rows)
    extra: Dict[str, Dict[str, pa.Table]] = (
        extra_tables if extra_tables is not None else {})
    sess = sess or srt.session()
    report = []
    for name, fn in QUERIES:
        if queries and name not in queries:
            continue
        prefix = name.split("_", 1)[0]
        if prefix in _TABLE_SETS:
            if prefix not in extra:
                extra[prefix] = _TABLE_SETS[prefix](rows)
            t = extra[prefix]
        else:
            t = base_tables
        rec = _RecordingTables(t)
        try:
            t0 = time.perf_counter()
            fn(sess, rec, F)
            total = time.perf_counter() - t0
            t0 = time.perf_counter()
            fn(sess, rec, F)  # warm again; compile amortized
            warm = time.perf_counter() - t0
        finally:
            # ALSO on failure: a raising query must not leak its
            # compiled programs toward the JIT-region crash
            release_compiled_programs()
        report.append({"query": name,
                       "seconds": round(total, 3),
                       "warm_seconds": round(warm, 3),
                       "rows": rows,
                       # bytes of the tables the query actually touched
                       # (warm_seconds also includes the pandas oracle
                       # re-check, so derived GB/s stays conservative)
                       "tables_bytes": sum(t[k].nbytes
                                           for k in rec.accessed)})
    return report


def scan_engagement_report(rows: int = 20_000, tmpdir=None) -> dict:
    """File-scan leg of the rig (VERDICT round 5, Weak #7): write the
    fact table to parquet AND ORC (ORC with dictionary encoding on, the
    encoded-retention shape), scan each back with a filter+agg, and
    return the device-decode engagement scoreboard per format from the
    queries' ``last_query_metrics``.  A regression that silently declines
    every file to the host pyarrow path still returns bit-correct
    results — this record is what makes it VISIBLE (test_encoded asserts
    ``files_engaged >= 1`` for both formats)."""
    import os
    import shutil
    import tempfile

    import pyarrow.orc as pa_orc
    import pyarrow.parquet as pq

    import spark_rapids_tpu as srt
    from ..io_ import decode_stats as DS
    from ..sql import functions as F
    own = tmpdir is None
    tmpdir = tmpdir or tempfile.mkdtemp(prefix="srt_scan_rig_")
    try:
        fact = build_tables(max(rows, 1000))["fact"]
        sess = srt.session()
        out: Dict[str, dict] = {}
        for fmt in ("parquet", "orc"):
            path = os.path.join(tmpdir, f"fact.{fmt}")
            if fmt == "parquet":
                pq.write_table(fact, path)
            else:
                pa_orc.write_table(fact, path,
                                   dictionary_key_size_threshold=1.0)
            q = (getattr(sess.read, fmt)(path)
                 .filter(F.col("q") < 50).groupBy("q")
                 .agg(F.count("*").alias("c"),
                      F.sum(F.col("v")).alias("sv")))
            q.collect()
            m = sess.last_query_metrics
            out[fmt] = {
                "files_engaged": int(m.get(f"{fmt}DecodeFilesEngaged", 0)),
                "files_declined": int(
                    m.get(f"{fmt}DecodeFilesDeclined", 0)),
                "bytes_engaged": int(m.get(f"{fmt}DecodeBytesEngaged", 0)),
                "columns_encoded": int(m.get("encodedColumnsEncoded", 0)),
            }
        out["decode_stats"] = DS.report()
        return out
    finally:
        if own:
            shutil.rmtree(tmpdir, ignore_errors=True)


def main() -> None:
    import json
    import os
    import sys

    # the ambient sitecustomize forces the axon TPU tunnel via jax.config
    # (env vars can't override it) and a hung tunnel would block this rig
    # forever — flip the CONFIG to the host platform unless the caller
    # explicitly asks for the chip (SRT_SCALE_PLATFORM=axon)
    plat = os.environ.get("SRT_SCALE_PLATFORM", "cpu")
    if plat == "cpu":
        from spark_rapids_tpu import pin_host_platform
        pin_host_platform()  # also drops the CPU-hazardous compile cache
    elif plat:
        import jax
        jax.config.update("jax_platforms", plat)
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    # stream per query (amortized tables/session) so a timeout or crash
    # still leaves the completed queries' evidence on stdout
    failed = 0
    for entry in iter_suite(rows):
        if "error" in entry:
            failed += 1
        print(json.dumps(entry), flush=True)
    # device-decode engagement leg: the rig report must show the
    # parquet/ORC scans actually ENGAGING the device decoders
    scan = scan_engagement_report(min(rows, 20_000))
    print(json.dumps({"scan_engagement": scan}), flush=True)
    for fmt in ("parquet", "orc"):
        if scan[fmt]["files_engaged"] < 1:
            print(json.dumps({"error": f"{fmt} scan did not engage the "
                              f"device decoder", "scan": scan[fmt]}),
                  flush=True)
            failed += 1
    if failed:
        raise SystemExit(1)


if __name__ == "__main__":
    main()
