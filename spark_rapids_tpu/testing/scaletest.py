"""Scale-test harness — the analog of the reference's
``integration_tests/.../scaletest/QuerySpecs.scala`` + ``datagen/``
(SURVEY §4 tier 4): a deterministic query suite over generated join/agg/
window-shaped data with controllable scale, each query checked against a
pandas oracle and timed.

Run standalone:  python -m spark_rapids_tpu.testing.scaletest [rows]
(CI runs it small through tests/test_scale.py; crank ``rows`` for a rig.)
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

from .datagen import (DoubleGen, IntegerGen, LongGen, StringGen, gen_table)


def build_tables(rows: int, seed: int = 17) -> Dict[str, pa.Table]:
    """fact + two dimensions with skewed keys (the reference's datagen
    controls cardinality/skew the same way)."""
    rng = np.random.default_rng(seed)
    # skew: 20% of fact rows land on 1% of keys
    n_keys = max(rows // 100, 10)
    hot = rng.integers(0, max(n_keys // 100, 1), rows // 5)
    cold = rng.integers(0, n_keys, rows - rows // 5)
    keys = np.concatenate([hot, cold])
    rng.shuffle(keys)
    fact = gen_table({
        "v": DoubleGen(no_nans=True, no_extremes=True),
        "q": IntegerGen(0, 100, nullable=False),
        "s": StringGen(max_len=12),
    }, rows, seed=seed)
    fact = fact.append_column("k", pa.array(keys, type=pa.int64()))
    dim = gen_table({
        "w": DoubleGen(no_nans=True, no_extremes=True, nullable=False),
        "cat": IntegerGen(0, 8, nullable=False),
    }, n_keys, seed=seed + 1)
    dim = dim.append_column("k", pa.array(np.arange(n_keys),
                                          type=pa.int64()))
    return {"fact": fact, "dim": dim}


def _q1(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    got = (fact.filter(fact.q < 50)
           .groupBy("q").agg(F.sum(fact.v).alias("sv"),
                             F.count("*").alias("c"))
           .orderBy("q").collect().to_pandas())
    pdf = t["fact"].to_pandas()
    pdf = pdf[pdf.q < 50]
    exp = pdf.groupby("q").agg(sv=("v", "sum"), c=("q", "size")).reset_index()
    assert np.array_equal(got["q"], exp["q"])
    assert np.allclose(got["sv"].fillna(0), exp["sv"].fillna(0))
    assert np.array_equal(got["c"], exp["c"])


def _q2(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    dim = sess.create_dataframe(t["dim"], num_partitions=2)
    got = (fact.join(dim, on="k", how="inner")
           .groupBy("cat").agg(F.count("*").alias("n"),
                               F.sum(fact.v).alias("sv"))
           .orderBy("cat").collect().to_pandas())
    exp = (t["fact"].to_pandas().merge(t["dim"].to_pandas(), on="k")
           .groupby("cat").agg(n=("k", "size"), sv=("v", "sum"))
           .reset_index())
    assert np.array_equal(got["cat"], exp["cat"])
    assert np.array_equal(got["n"], exp["n"])
    assert np.allclose(got["sv"].fillna(0), exp["sv"].fillna(0))


def _q3(sess, t, F):
    """skewed join: the hot keys stress partition balance."""
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    dim = sess.create_dataframe(t["dim"], num_partitions=2)
    got = (fact.join(dim, on="k", how="left")
           .filter(fact.q >= 90).select(fact.k, fact.v, dim.w)
           .orderBy("k", "v").collect().to_pandas())
    pdf = t["fact"].to_pandas()
    exp = (pdf[pdf.q >= 90].merge(t["dim"].to_pandas(), on="k", how="left")
           .sort_values(["k", "v"]).reset_index(drop=True))
    assert len(got) == len(exp)
    assert np.array_equal(got["k"], exp["k"])
    gw, ew = got["w"].to_numpy(), exp["w"].to_numpy()
    m = ~np.isnan(ew)
    assert np.allclose(gw[m], ew[m]) and np.isnan(gw[~m]).all()


def _q4(sess, t, F):
    from ..sql.window_api import Window
    fact = sess.create_dataframe(t["fact"], num_partitions=2)
    w = Window.partitionBy("q").orderBy("v")
    got = (fact.select(fact.q, fact.v,
                       F.row_number().over(w).alias("rn"))
           .filter(F.col("rn") <= 3)
           .collect().to_pandas())
    pdf = t["fact"].to_pandas().dropna(subset=["v"])
    exp = (pdf.sort_values(["q", "v"]).groupby("q").head(3))
    # row_number over possibly-null v: compare counts per q
    got_counts = got.groupby("q").size()
    exp_counts = exp.groupby("q").size()
    assert got_counts.max() <= 3  # the rn<=3 filter actually filtered
    for q in exp_counts.index:
        assert got_counts.get(q, 0) >= min(3, exp_counts[q]) - 1


def _q5(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    got = (fact.orderBy(fact.v.desc_nulls_first(), "k")
           .select(fact.k, fact.v).collect().to_pandas())
    assert len(got) == t["fact"].num_rows
    vals = got["v"].to_numpy()
    nn = vals[~np.isnan(vals)]
    assert np.all(np.diff(nn) <= 1e-12)  # descending


def _q6(sess, t, F):
    fact = sess.create_dataframe(t["fact"], num_partitions=4)
    got = (fact.select(F.upper(fact.s).alias("u"),
                       F.length(fact.s).alias("ln"))
           .filter(F.col("ln") > 4).count())
    pdf = t["fact"].to_pandas()
    exp = int((pdf.s.str.len() > 4).sum())
    assert got == exp


QUERIES: List[Tuple[str, Callable]] = [
    ("q1_filter_agg", _q1),
    ("q2_join_agg", _q2),
    ("q3_skewed_left_join", _q3),
    ("q4_window_topn", _q4),
    ("q5_global_sort", _q5),
    ("q6_strings", _q6),
]


def run_suite(rows: int = 50_000, queries=None, tables=None,
              sess=None) -> List[dict]:
    """Runs the selected queries; pass ``tables``/``sess`` to amortize
    datagen and session setup across calls.  ``seconds`` includes compile
    plus the pandas oracle check; ``warm_seconds`` is the second run with
    compiles amortized — the number to compare across rigs."""
    import spark_rapids_tpu as srt
    from ..sql import functions as F
    t = tables if tables is not None else build_tables(rows)
    sess = sess or srt.session()
    report = []
    for name, fn in QUERIES:
        if queries and name not in queries:
            continue
        t0 = time.perf_counter()
        fn(sess, t, F)
        total = time.perf_counter() - t0
        t0 = time.perf_counter()
        fn(sess, t, F)  # warm engine + oracle again; compile amortized
        warm = time.perf_counter() - t0
        report.append({"query": name,
                       "seconds": round(total, 3),
                       "warm_seconds": round(warm, 3),
                       "rows": rows})
    return report


if __name__ == "__main__":
    import json
    import sys
    rows = int(sys.argv[1]) if len(sys.argv) > 1 else 50_000
    for entry in run_suite(rows):
        print(json.dumps(entry))
