"""TPC-DS breadth for the scale rig (VERDICT r3 missing #3 follow-up).

The reference's milestone ladder ends at full TPC-DS (BASELINE configs
3-4) and its scale suite spans join/agg/window shapes
(``integration_tests/.../scaletest/QuerySpecs.scala``).  Round 3 carried
5 TPC-DS shapes; this module adds 11 more in their REAL spec SQL form —
comma FROM star joins, derived tables, window-over-aggregate via
subquery, multi-alias dimension reuse, cross-joined scalar-subquery
blocks (q88), HAVING-range ticket analyses (q34/q73) — each checked
against an independent pandas oracle.

``build_tables`` is a superset of round 3's ``build_tpcds_tables``: the
original columns keep their names so the existing q3/q7/q19/q42/q89
runners work unchanged; new dimensions (store, household_demographics,
time_dim, customer, customer_address) and fact columns extend the star.
Filter constants are the spec's where possible, tuned only so scaled-down
data keeps results non-empty (plan-shape coverage is the point).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

_BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000"]
_CITIES = ["Fairview", "Midway", "Oakdale", "Springdale", "Riverside",
           "Centerville", "Glendale", "Marion"]
_COUNTIES = ["C1", "C2", "C3", "C4"]
_STORE_NAMES = ["ese", "ought", "able", "pri", "bar"]
_FIRST = ["Ann", "Bob", "Cara", "Dev", "Eli", "Fay", "Gus", "Hana"]
_LAST = ["Ames", "Brown", "Cole", "Diaz", "Egan", "Ford", "Gray", "Hale"]
_STATES = ["CA", "WA", "GA", "TX", "NY", "OH", "FL", "MI"]
_ZIPS = [f"{z:05d}" for z in
         (85669, 86197, 88274, 83405, 80348, 81891, 60099, 90831,
          73065, 24128, 41904, 12477, 31678, 56557, 62544, 29741,
          48933, 74330, 95315, 67853)]
_SM_TYPES = ["EXPRESS", "OVERNIGHT", "REGULAR", "TWO DAY", "LIBRARY"]
_WH_NAMES = ["Conventional childr", "Important issues liv",
             "Doors canno", "Bad cards must make", "Rooms cook"]


def build_tables(rows: int, seed: int = 31) -> Dict[str, pa.Table]:
    rng = np.random.default_rng(seed)
    n_items = max(rows // 50, 20)
    n_dates = 365 * 5
    n_cd = 200
    n_promo = 50
    n_stores = 12
    n_hd = 144
    n_times = 24 * 12            # 5-minute buckets
    n_cust = max(rows // 20, 50)
    n_addr = max(n_cust // 2, 25)

    day = np.arange(n_dates)
    date_dim = pa.table({
        "d_date_sk": pa.array(day, type=pa.int64()),
        "d_year": pa.array(1998 + (day // 365), type=pa.int32()),
        "d_moy": pa.array(1 + (day % 365) // 31 % 12, type=pa.int32()),
        "d_dom": pa.array(1 + day % 28, type=pa.int32()),
        "d_dow": pa.array(day % 7, type=pa.int32()),
        "d_qoy": pa.array(1 + ((day % 365) // 92) % 4, type=pa.int32()),
    })
    item = pa.table({
        "i_item_sk": pa.array(np.arange(n_items), type=pa.int64()),
        "i_manufact_id": pa.array(rng.integers(0, 100, n_items),
                                  type=pa.int32()),
        "i_brand_id": pa.array(rng.integers(0, 40, n_items),
                               type=pa.int32()),
        "i_category_id": pa.array(rng.integers(0, 10, n_items),
                                  type=pa.int32()),
        "i_manager_id": pa.array(rng.integers(0, 100, n_items),
                                 type=pa.int32()),
        "i_brand": pa.array([f"brand#{b}" for b in
                             rng.integers(0, 40, n_items)]),
        "i_item_id": pa.array([f"ITEM{k:08d}" for k in range(n_items)]),
        "i_class_id": pa.array(rng.integers(0, 16, n_items),
                               type=pa.int32()),
        "i_current_price": pa.array(np.round(rng.random(n_items) * 99, 2)),
    })
    customer_demographics = pa.table({
        "cd_demo_sk": pa.array(np.arange(n_cd), type=pa.int64()),
        "cd_gender": pa.array(rng.choice(["M", "F"], n_cd)),
        "cd_marital_status": pa.array(rng.choice(["S", "M", "D", "W"],
                                                 n_cd)),
        "cd_education_status": pa.array(rng.choice(
            ["College", "Primary", "Secondary", "Advanced Degree"], n_cd)),
    })
    promotion = pa.table({
        "p_promo_sk": pa.array(np.arange(n_promo), type=pa.int64()),
        "p_channel_email": pa.array(rng.choice(["Y", "N"], n_promo)),
        "p_channel_event": pa.array(rng.choice(["Y", "N"], n_promo)),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(n_stores), type=pa.int64()),
        "s_store_name": pa.array(rng.choice(_STORE_NAMES, n_stores)),
        "s_city": pa.array(rng.choice(_CITIES, n_stores)),
        "s_county": pa.array(rng.choice(_COUNTIES, n_stores)),
        "s_number_employees": pa.array(rng.integers(150, 350, n_stores),
                                       type=pa.int32()),
    })
    household_demographics = pa.table({
        "hd_demo_sk": pa.array(np.arange(n_hd), type=pa.int64()),
        "hd_dep_count": pa.array(rng.integers(0, 10, n_hd),
                                 type=pa.int32()),
        "hd_vehicle_count": pa.array(rng.integers(0, 5, n_hd),
                                     type=pa.int32()),
        "hd_buy_potential": pa.array(rng.choice(_BUY_POTENTIAL, n_hd)),
    })
    tmark = np.arange(n_times)
    time_dim = pa.table({
        "t_time_sk": pa.array(tmark, type=pa.int64()),
        "t_hour": pa.array(tmark // 12, type=pa.int32()),
        "t_minute": pa.array((tmark % 12) * 5, type=pa.int32()),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(n_cust), type=pa.int64()),
        "c_first_name": pa.array(rng.choice(_FIRST, n_cust)),
        "c_last_name": pa.array(rng.choice(_LAST, n_cust)),
        "c_current_addr_sk": pa.array(rng.integers(0, n_addr, n_cust),
                                      type=pa.int64()),
        "c_current_cdemo_sk": pa.array(rng.integers(0, n_cd, n_cust),
                                       type=pa.int64()),
    })
    customer_address = pa.table({
        "ca_address_sk": pa.array(np.arange(n_addr), type=pa.int64()),
        "ca_city": pa.array(rng.choice(_CITIES, n_addr)),
        "ca_county": pa.array(rng.choice(_COUNTIES, n_addr)),
        "ca_state": pa.array(rng.choice(_STATES, n_addr)),
        "ca_zip": pa.array(rng.choice(_ZIPS, n_addr)),
    })
    n_wh = 5
    warehouse = pa.table({
        "w_warehouse_sk": pa.array(np.arange(n_wh), type=pa.int64()),
        "w_warehouse_name": pa.array(_WH_NAMES[:n_wh]),
        # deterministic round-robin, NOT rng.choice: q94/q95 filter on
        # w_state = 'CA' and a seed that drew no CA warehouse would
        # empty them at every scale
        "w_state": pa.array([_STATES[i % len(_STATES)]
                             for i in range(n_wh)]),
    })
    n_sm = len(_SM_TYPES)
    ship_mode = pa.table({
        "sm_ship_mode_sk": pa.array(np.arange(n_sm), type=pa.int64()),
        "sm_type": pa.array(_SM_TYPES),
    })

    # ticket-coherent fact generation: a ticket (basket) shares ONE
    # date/time/store/hdemo/customer/addr across its line items — the
    # property q34/q68/q73/q79's per-ticket count/sum semantics rely on
    # (per-row-independent dims would scatter each ticket across filters
    # and leave count-range predicates empty)
    n_tickets = max(rows // 8, 10)
    tk_date = rng.integers(0, n_dates, n_tickets)
    tk_time = rng.integers(0, n_times, n_tickets)
    tk_store = rng.integers(0, n_stores, n_tickets)
    tk_hd = rng.integers(0, n_hd, n_tickets)
    tk_cust = rng.integers(0, n_cust, n_tickets)
    tk_addr = rng.integers(0, n_addr, n_tickets)
    ticket = rng.integers(0, n_tickets, rows)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(tk_date[ticket], type=pa.int64()),
        "ss_item_sk": pa.array(rng.integers(0, n_items, rows),
                               type=pa.int64()),
        "ss_ext_sales_price": pa.array(
            np.round(rng.random(rows) * 1000, 2)),
        "ss_cdemo_sk": pa.array(rng.integers(0, n_cd, rows),
                                type=pa.int64()),
        "ss_promo_sk": pa.array(rng.integers(0, n_promo, rows),
                                type=pa.int64()),
        "ss_quantity": pa.array(rng.integers(1, 100, rows),
                                type=pa.int32()),
        "ss_list_price": pa.array(np.round(rng.random(rows) * 200, 2)),
        "ss_coupon_amt": pa.array(np.round(rng.random(rows) * 50, 2)),
        "ss_store_sk": pa.array(tk_store[ticket], type=pa.int64()),
        "ss_hdemo_sk": pa.array(tk_hd[ticket], type=pa.int64()),
        "ss_sold_time_sk": pa.array(tk_time[ticket], type=pa.int64()),
        "ss_ticket_number": pa.array(ticket, type=pa.int64()),
        "ss_customer_sk": pa.array(tk_cust[ticket], type=pa.int64()),
        "ss_addr_sk": pa.array(tk_addr[ticket], type=pa.int64()),
        "ss_net_profit": pa.array(np.round(rng.random(rows) * 100 - 20, 2)),
        "ss_sales_price": pa.array(np.round(rng.random(rows) * 150, 2)),
        "ss_ext_list_price": pa.array(np.round(rng.random(rows) * 250, 2)),
        "ss_ext_tax": pa.array(np.round(rng.random(rows) * 30, 2)),
    })
    # cross-channel facts (round 5): catalog_sales/web_sales share the
    # customer and item keyspaces with store_sales so the INTERSECT/
    # EXCEPT/FULL-OUTER channel queries (q38/q87/q97/q11/q60...) produce
    # non-degenerate overlaps; store_returns derives from store_sales rows
    # so ticket+item joins (q93) and per-store return totals (q1) hit.
    n_cs = max(rows // 2, 20)
    catalog_sales = pa.table({
        "cs_sold_date_sk": pa.array(rng.integers(0, n_dates, n_cs),
                                    type=pa.int64()),
        "cs_bill_customer_sk": pa.array(rng.integers(0, n_cust, n_cs),
                                        type=pa.int64()),
        "cs_item_sk": pa.array(rng.integers(0, n_items, n_cs),
                               type=pa.int64()),
        "cs_quantity": pa.array(rng.integers(1, 100, n_cs),
                                type=pa.int32()),
        "cs_list_price": pa.array(np.round(rng.random(n_cs) * 200, 2)),
        "cs_ext_sales_price": pa.array(np.round(rng.random(n_cs) * 1000,
                                                2)),
        "cs_sales_price": pa.array(np.round(rng.random(n_cs) * 600, 2)),
        "cs_net_profit": pa.array(np.round(rng.random(n_cs) * 120 - 25,
                                           2)),
        "cs_sold_time_sk": pa.array(rng.integers(0, n_times, n_cs),
                                    type=pa.int64()),
        "cs_order_number": pa.array(
            rng.integers(0, max(n_cs // 3, 8), n_cs), type=pa.int64()),
        "cs_warehouse_sk": pa.array(rng.integers(0, n_wh, n_cs),
                                    type=pa.int64()),
        "cs_cdemo_sk": pa.array(rng.integers(0, n_cd, n_cs),
                                type=pa.int64()),
        "cs_promo_sk": pa.array(rng.integers(0, n_promo, n_cs),
                                type=pa.int64()),
    })
    n_cr = max(n_cs // 5, 8)
    cr_idx = rng.choice(n_cs, size=n_cr, replace=False)
    catalog_returns = pa.table({
        "cr_order_number": pa.array(
            np.asarray(catalog_sales.column("cs_order_number"))[cr_idx],
            type=pa.int64()),
        "cr_item_sk": pa.array(
            np.asarray(catalog_sales.column("cs_item_sk"))[cr_idx],
            type=pa.int64()),
        "cr_refunded_cash": pa.array(np.round(rng.random(n_cr) * 80, 2)),
    })
    n_inv = max(rows // 2, 40)
    # inventory concentrates on 50 items so per-(warehouse,item,month)
    # groups hold several samples — q39's stddev/mean needs group sizes
    # > 1 (stddev_samp of a singleton is NULL and the group drops)
    inv_items = min(n_items, 50)
    inventory = pa.table({
        "inv_date_sk": pa.array(rng.integers(800, 1100, n_inv),
                                type=pa.int64()),
        "inv_item_sk": pa.array(rng.integers(0, inv_items, n_inv),
                                type=pa.int64()),
        "inv_warehouse_sk": pa.array(rng.integers(0, n_wh, n_inv),
                                     type=pa.int64()),
        "inv_quantity_on_hand": pa.array(rng.integers(0, 1000, n_inv),
                                         type=pa.int32()),
    })
    n_ws = max(rows // 3, 20)
    ws_sold = rng.integers(0, n_dates, n_ws)
    n_orders = max(n_ws // 3, 8)
    web_sales = pa.table({
        "ws_sold_date_sk": pa.array(ws_sold, type=pa.int64()),
        "ws_bill_customer_sk": pa.array(rng.integers(0, n_cust, n_ws),
                                        type=pa.int64()),
        "ws_item_sk": pa.array(rng.integers(0, n_items, n_ws),
                               type=pa.int64()),
        "ws_quantity": pa.array(rng.integers(1, 100, n_ws),
                                type=pa.int32()),
        "ws_list_price": pa.array(np.round(rng.random(n_ws) * 200, 2)),
        "ws_ext_sales_price": pa.array(np.round(rng.random(n_ws) * 1000,
                                                2)),
        # shipping lag spreads across the 30/60/90/120-day bucket edges
        # (q62's CASE counts need every bucket populated)
        "ws_ship_date_sk": pa.array(
            np.minimum(ws_sold + rng.integers(1, 140, n_ws), n_dates - 1),
            type=pa.int64()),
        "ws_sold_time_sk": pa.array(rng.integers(0, n_times, n_ws),
                                    type=pa.int64()),
        "ws_order_number": pa.array(rng.integers(0, n_orders, n_ws),
                                    type=pa.int64()),
        "ws_warehouse_sk": pa.array(rng.integers(0, n_wh, n_ws),
                                    type=pa.int64()),
        "ws_ship_mode_sk": pa.array(rng.integers(0, n_sm, n_ws),
                                    type=pa.int64()),
        "ws_ship_hdemo_sk": pa.array(rng.integers(0, n_hd, n_ws),
                                     type=pa.int64()),
        "ws_ext_discount_amt": pa.array(np.round(rng.random(n_ws) * 80,
                                                 2)),
        "ws_ext_ship_cost": pa.array(np.round(rng.random(n_ws) * 40, 2)),
        "ws_net_profit": pa.array(np.round(rng.random(n_ws) * 110 - 20,
                                           2)),
    })
    n_wr = max(n_orders // 4, 4)
    web_returns = pa.table({
        "wr_order_number": pa.array(
            rng.choice(n_orders, size=n_wr, replace=False),
            type=pa.int64()),
        "wr_return_amt": pa.array(np.round(rng.random(n_wr) * 200, 2)),
    })
    n_sr = max(rows // 5, 10)
    ret_idx = rng.choice(rows, size=n_sr, replace=False)
    store_returns = pa.table({
        "sr_returned_date_sk": pa.array(rng.integers(0, n_dates, n_sr),
                                        type=pa.int64()),
        "sr_customer_sk": pa.array(
            np.asarray(store_sales.column("ss_customer_sk"))[ret_idx],
            type=pa.int64()),
        "sr_store_sk": pa.array(
            np.asarray(store_sales.column("ss_store_sk"))[ret_idx],
            type=pa.int64()),
        "sr_item_sk": pa.array(
            np.asarray(store_sales.column("ss_item_sk"))[ret_idx],
            type=pa.int64()),
        "sr_ticket_number": pa.array(
            np.asarray(store_sales.column("ss_ticket_number"))[ret_idx],
            type=pa.int64()),
        "sr_return_amt": pa.array(np.round(rng.random(n_sr) * 300, 2)),
        "sr_net_loss": pa.array(np.round(rng.random(n_sr) * 90, 2)),
    })
    # round-5 wave 5 extensions, drawn from a SEPARATE rng and appended
    # to the already-built tables so every earlier draw — and therefore
    # every existing table's bytes and every tuned oracle constant —
    # stays identical.  store.s_state is deterministic round-robin like
    # warehouse.w_state (rank/rollup queries must see every state at
    # every scale).
    rng2 = np.random.default_rng(seed + 101)
    store = store.append_column(
        "s_state", pa.array([_STATES[i % len(_STATES)]
                             for i in range(n_stores)]))
    n_cc = 6
    call_center = pa.table({
        "cc_call_center_sk": pa.array(np.arange(n_cc), type=pa.int64()),
        "cc_name": pa.array([f"call center {i}" for i in range(n_cc)]),
    })
    cs_sold = np.asarray(catalog_sales.column("cs_sold_date_sk"))
    catalog_sales = catalog_sales.append_column(
        "cs_ship_date_sk", pa.array(
            np.minimum(cs_sold + rng2.integers(1, 140, n_cs), n_dates - 1),
            type=pa.int64()))
    catalog_sales = catalog_sales.append_column(
        "cs_ship_mode_sk", pa.array(rng2.integers(0, n_sm, n_cs),
                                    type=pa.int64()))
    catalog_sales = catalog_sales.append_column(
        "cs_call_center_sk", pa.array(rng2.integers(0, n_cc, n_cs),
                                      type=pa.int64()))
    return {
        "store_sales": store_sales, "date_dim": date_dim, "item": item,
        "customer_demographics": customer_demographics,
        "promotion": promotion, "store": store,
        "household_demographics": household_demographics,
        "time_dim": time_dim, "customer": customer,
        "customer_address": customer_address,
        "catalog_sales": catalog_sales, "web_sales": web_sales,
        "store_returns": store_returns, "warehouse": warehouse,
        "ship_mode": ship_mode, "web_returns": web_returns,
        "catalog_returns": catalog_returns, "inventory": inventory,
        "call_center": call_center,
    }


# ---------------------------------------------------------------------------
# oracle helpers
# ---------------------------------------------------------------------------

def _sorted_frames(got: pd.DataFrame, exp: pd.DataFrame):
    """Sort both frames by the non-float columns first (every query here
    projects a unique non-float key set, so these fully determine row
    order), with rounded floats as inert tiebreakers."""
    def prep(df):
        df = df.copy()
        df.columns = list(range(len(df.columns)))
        keys = {}
        for c in df.columns:
            if df[c].dtype.kind not in "fc":
                keys[f"a{c}"] = df[c]
        for c in df.columns:
            if df[c].dtype.kind in "fc":
                keys[f"z{c}"] = df[c].astype(float).round(3)
        key_df = pd.DataFrame(keys)
        order = key_df.sort_values(list(key_df.columns),
                                   na_position="first").index
        return df.loc[order].reset_index(drop=True)
    return prep(got), prep(exp)


def _assert_rows(got: pd.DataFrame, exp: pd.DataFrame):
    """Order-insensitive frame equality with float tolerance (ORDER BY
    columns in these queries are not total orders, so row order between
    engines is not comparable — the multiset is)."""
    assert len(got) == len(exp), f"{len(got)} rows != {len(exp)}"
    assert len(got.columns) == len(exp.columns)
    assert len(exp) > 0, "oracle produced empty result — tune constants"
    g, e = _sorted_frames(got, exp)
    for c in g.columns:
        if g[c].dtype.kind == "f" or e[c].dtype.kind == "f":
            assert np.allclose(g[c].astype(float).fillna(np.nan),
                               e[c].astype(float).fillna(np.nan),
                               rtol=1e-6, atol=1e-6, equal_nan=True), c
        else:
            ga = np.asarray(g[c].astype(object).values)
            ea = np.asarray(e[c].astype(object).values)
            gm, em = pd.isna(ga), pd.isna(ea)
            # isna-masked equality: fillna('\0') is dtype-dependent under
            # pandas-3 str columns (object-cast NaN fills to '')
            assert (gm == em).all(), c
            assert (ga[~gm] == ea[~em]).all(), c


#: to_pandas results per table-set, STRONG-ref keyed by identity (the
#: strong ref makes id() recycling impossible; the rig passes one table
#: dict per suite, so at most one entry is live)
_pd_cache = [None, None]         # [tables_dict, {name: DataFrame}]


def _pd(t: Dict[str, pa.Table], name: str) -> pd.DataFrame:
    if _pd_cache[0] is not t:
        _pd_cache[0] = t
        _pd_cache[1] = {}
    cache = _pd_cache[1]
    if name not in cache:
        cache[name] = t[name].to_pandas()
    return cache[name].copy()


def _merged(t: Dict[str, pa.Table], with_: List[str]) -> pd.DataFrame:
    """store_sales joined to the requested dims, pandas-side (cached
    conversions: oracle pandas work lands in warm_seconds otherwise)."""
    keys = {
        "date_dim": ("ss_sold_date_sk", "d_date_sk"),
        "item": ("ss_item_sk", "i_item_sk"),
        "store": ("ss_store_sk", "s_store_sk"),
        "household_demographics": ("ss_hdemo_sk", "hd_demo_sk"),
        "time_dim": ("ss_sold_time_sk", "t_time_sk"),
        "customer": ("ss_customer_sk", "c_customer_sk"),
        "customer_demographics": ("ss_cdemo_sk", "cd_demo_sk"),
        "customer_address": ("ss_addr_sk", "ca_address_sk"),
    }
    pdf = _pd(t, "store_sales")
    for name in with_:
        l, r = keys[name]
        pdf = pdf.merge(_pd(t, name), left_on=l, right_on=r)
    return pdf


# ---------------------------------------------------------------------------
# queries: (name, sql, oracle(got_pdf, tables))
# ---------------------------------------------------------------------------

def _oracle_q34(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics"])
    pdf = pdf[((pdf.d_dom.between(1, 3)) | (pdf.d_dom.between(25, 28)))
              & (pdf.hd_buy_potential == "1001-5000")
              & (pdf.hd_vehicle_count > 0)
              & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_county == "C1")]
    dn = (pdf.groupby(["ss_ticket_number", "ss_customer_sk"])
          .size().reset_index(name="cnt"))
    dn = dn[dn.cnt.between(2, 20)]
    cust = _pd(t, "customer")
    exp = dn.merge(cust, left_on="ss_customer_sk",
                   right_on="c_customer_sk")[
        ["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    _assert_rows(got, exp)


_Q34 = """
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) AS cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (d_dom BETWEEN 1 AND 3 OR d_dom BETWEEN 25 AND 28)
        AND hd_buy_potential = '1001-5000' AND hd_vehicle_count > 0
        AND d_year IN (1998, 1999, 2000) AND s_county = 'C1'
      GROUP BY ss_ticket_number, ss_customer_sk) dn, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 2 AND 20
ORDER BY c_last_name, c_first_name, ss_ticket_number DESC
"""


def _oracle_q52(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[(pdf.i_manager_id <= 10) & (pdf.d_moy == 11)
              & (pdf.d_year == 2000)]
    exp = (pdf.groupby(["d_year", "i_brand_id"])
           .agg(ext_price=("ss_ext_sales_price", "sum")).reset_index())
    _assert_rows(got, exp)


_Q52 = """
SELECT d_year, i_brand_id, sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id <= 10 AND d_moy = 11 AND d_year = 2000
GROUP BY d_year, i_brand_id
ORDER BY d_year, ext_price DESC
"""


def _oracle_q53(got, t):
    pdf = _merged(t, ["item", "date_dim", "store"])
    pdf = pdf[pdf.d_qoy.isin([1, 2]) & (pdf.i_class_id < 8)]
    grouped = (pdf.groupby(["i_manufact_id", "d_qoy"])
               .agg(sum_sales=("ss_sales_price", "sum")).reset_index())
    grouped["avg_quarterly_sales"] = grouped.groupby(
        "i_manufact_id")["sum_sales"].transform("mean")
    exp = grouped[["i_manufact_id", "d_qoy", "sum_sales",
                   "avg_quarterly_sales"]]
    _assert_rows(got, exp)


_Q53 = """
SELECT i_manufact_id, d_qoy, sum_sales,
       avg(sum_sales) OVER (PARTITION BY i_manufact_id)
         AS avg_quarterly_sales
FROM (SELECT i_manufact_id, d_qoy, sum(ss_sales_price) AS sum_sales
      FROM item, store_sales, date_dim, store
      WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk AND d_qoy IN (1, 2)
        AND i_class_id < 8
      GROUP BY i_manufact_id, d_qoy) tmp1
ORDER BY avg_quarterly_sales, sum_sales, i_manufact_id
"""


def _oracle_q55(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[(pdf.i_manager_id.between(20, 40)) & (pdf.d_moy == 11)
              & (pdf.d_year == 1999)]
    exp = (pdf.groupby(["i_brand", "i_brand_id"])
           .agg(ext_price=("ss_ext_sales_price", "sum")).reset_index())
    exp = exp[["i_brand_id", "i_brand", "ext_price"]]
    _assert_rows(got, exp)


_Q55 = """
SELECT i_brand_id, i_brand, sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id BETWEEN 20 AND 40 AND d_moy = 11 AND d_year = 1999
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, i_brand_id
"""


def _oracle_q68(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics",
                      "customer_address"])
    pdf = pdf[(pdf.d_dom.between(1, 2))
              & ((pdf.hd_dep_count == 4) | (pdf.hd_vehicle_count == 3))
              & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_city.isin(["Fairview", "Midway"]))]
    dn = (pdf.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                       "ca_city"])
          .agg(extended_price=("ss_ext_sales_price", "sum"),
               list_price=("ss_ext_list_price", "sum"),
               extended_tax=("ss_ext_tax", "sum")).reset_index()
          .rename(columns={"ca_city": "bought_city"}))
    cust = _pd(t, "customer")
    addr = _pd(t, "customer_address")
    exp = (dn.merge(cust, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
           .merge(addr, left_on="c_current_addr_sk",
                  right_on="ca_address_sk"))
    exp = exp[exp.ca_city != exp.bought_city][
        ["c_last_name", "c_first_name", "ca_city", "bought_city",
         "ss_ticket_number", "ss_addr_sk", "extended_price",
         "extended_tax", "list_price"]]
    _assert_rows(got, exp)


_Q68 = """
SELECT c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, ss_addr_sk, extended_price, extended_tax,
       list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ss_addr_sk,
             ca_city AS bought_city,
             sum(ss_ext_sales_price) AS extended_price,
             sum(ss_ext_list_price) AS list_price,
             sum(ss_ext_tax) AS extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk AND ss_addr_sk = ca_address_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_year IN (1998, 1999, 2000)
        AND s_city IN ('Fairview', 'Midway')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
"""


def _oracle_q73(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics"])
    pdf = pdf[(pdf.d_dom.between(1, 2))
              & (pdf.hd_buy_potential.isin(["501-1000", ">10000"]))
              & (pdf.hd_vehicle_count > 0)
              & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_county.isin(["C1", "C2"]))]
    dn = (pdf.groupby(["ss_ticket_number", "ss_customer_sk"])
          .size().reset_index(name="cnt"))
    dn = dn[dn.cnt.between(1, 5)]
    cust = _pd(t, "customer")
    exp = dn.merge(cust, left_on="ss_customer_sk",
                   right_on="c_customer_sk")[
        ["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    _assert_rows(got, exp)


_Q73 = """
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) AS cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk AND d_dom BETWEEN 1 AND 2
        AND hd_buy_potential IN ('501-1000', '>10000')
        AND hd_vehicle_count > 0 AND d_year IN (1998, 1999, 2000)
        AND s_county IN ('C1', 'C2')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name
"""


def _oracle_q79(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics"])
    pdf = pdf[((pdf.hd_dep_count == 6) | (pdf.hd_vehicle_count > 2))
              & (pdf.d_dow == 1) & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_number_employees.between(200, 295))]
    ms = (pdf.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                       "s_city"])
          .agg(amt=("ss_coupon_amt", "sum"),
               profit=("ss_net_profit", "sum")).reset_index())
    cust = _pd(t, "customer")
    exp = ms.merge(cust, left_on="ss_customer_sk",
                   right_on="c_customer_sk")
    exp["city30"] = exp.s_city.str[:30]
    exp = exp[["c_last_name", "c_first_name", "city30",
               "ss_ticket_number", "ss_addr_sk", "amt", "profit"]]
    _assert_rows(got, exp)


_Q79 = """
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) AS city30,
       ss_ticket_number, ss_addr_sk, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city,
             sum(ss_coupon_amt) AS amt, sum(ss_net_profit) AS profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
        AND d_dow = 1 AND d_year IN (1998, 1999, 2000)
        AND s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city30, profit
"""


def _count_bucket(t, h0, m0, m1, dep):
    pdf = _merged(t, ["household_demographics", "time_dim", "store"])
    pdf = pdf[(pdf.t_hour == h0) & (pdf.t_minute >= m0)
              & (pdf.t_minute < m1) & (pdf.hd_dep_count == dep)
              & (pdf.s_store_name == "ese")]
    return len(pdf)


def _oracle_q88(got, t):
    exp = pd.DataFrame({
        "h8_30_to_9": [_count_bucket(t, 8, 30, 60, 3)],
        "h9_to_9_30": [_count_bucket(t, 9, 0, 30, 3)],
        "h9_30_to_10": [_count_bucket(t, 9, 30, 60, 3)],
        "h10_to_10_30": [_count_bucket(t, 10, 0, 30, 3)],
    })
    _assert_rows(got, exp)


def _q88_block(alias, hour, m0, m1):
    cmp_m = f"t_minute >= {m0} AND t_minute < {m1}"
    return (f"(SELECT count(*) AS {alias} "
            f"FROM store_sales, household_demographics, time_dim, store "
            f"WHERE ss_sold_time_sk = t_time_sk "
            f"AND ss_hdemo_sk = hd_demo_sk AND ss_store_sk = s_store_sk "
            f"AND t_hour = {hour} AND {cmp_m} "
            f"AND hd_dep_count = 3 AND s_store_name = 'ese')")


_Q88 = f"""
SELECT * FROM
 {_q88_block('h8_30_to_9', 8, 30, 60)} s1,
 {_q88_block('h9_to_9_30', 9, 0, 30)} s2,
 {_q88_block('h9_30_to_10', 9, 30, 60)} s3,
 {_q88_block('h10_to_10_30', 10, 0, 30)} s4
"""


def _oracle_q96(got, t):
    pdf = _merged(t, ["household_demographics", "time_dim", "store"])
    pdf = pdf[(pdf.t_hour == 20) & (pdf.t_minute >= 30)
              & (pdf.hd_dep_count == 7) & (pdf.s_store_name == "ese")]
    _assert_rows(got, pd.DataFrame({"cnt": [len(pdf)]}))


_Q96 = """
SELECT count(*) AS cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk AND t_hour = 20 AND t_minute >= 30
  AND hd_dep_count = 7 AND s_store_name = 'ese'
"""


def _oracle_q98(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[pdf.i_category_id.isin([1, 2, 3]) & (pdf.d_year == 1999)]
    grouped = (pdf.groupby(["i_item_id", "i_category_id", "i_class_id",
                            "i_current_price"])
               .agg(itemrevenue=("ss_ext_sales_price", "sum"))
               .reset_index())
    grouped["revenueratio"] = (grouped.itemrevenue * 100 /
                               grouped.groupby("i_class_id")["itemrevenue"]
                               .transform("sum"))
    _assert_rows(got, grouped)


_Q98 = """
SELECT i_item_id, i_category_id, i_class_id, i_current_price,
       itemrevenue,
       itemrevenue * 100 / sum(itemrevenue)
         OVER (PARTITION BY i_class_id) AS revenueratio
FROM (SELECT i_item_id, i_category_id, i_class_id, i_current_price,
             sum(ss_ext_sales_price) AS itemrevenue
      FROM store_sales, item, date_dim
      WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND i_category_id IN (1, 2, 3) AND d_year = 1999
      GROUP BY i_item_id, i_category_id, i_class_id,
               i_current_price) grouped
ORDER BY i_category_id, i_class_id, i_item_id, revenueratio
"""


def _oracle_q42(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[(pdf.i_manager_id <= 15) & (pdf.d_moy == 12)
              & (pdf.d_year == 2000)]
    exp = (pdf.groupby(["d_year", "i_category_id"])
           .agg(s=("ss_ext_sales_price", "sum")).reset_index())
    _assert_rows(got, exp)


_Q42_SQL = """
SELECT d_year, i_category_id, sum(ss_ext_sales_price) AS s
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id <= 15 AND d_moy = 12 AND d_year = 2000
GROUP BY d_year, i_category_id
ORDER BY s DESC, d_year, i_category_id
"""


def _oracle_q59ish(got, t):
    """Weekly revenue by (store, dow) with a self-comparison ratio across
    two year halves — the q59 shape reduced to one join level."""
    pdf = _merged(t, ["date_dim", "store"])
    h1 = pdf[pdf.d_year == 1998]
    h2 = pdf[pdf.d_year == 1999]
    a = (h1.groupby(["s_store_name", "d_dow"])
         .agg(rev1=("ss_ext_sales_price", "sum")).reset_index())
    b = (h2.groupby(["s_store_name", "d_dow"])
         .agg(rev2=("ss_ext_sales_price", "sum")).reset_index())
    exp = a.merge(b, on=["s_store_name", "d_dow"])
    exp["ratio"] = exp.rev2 / exp.rev1
    _assert_rows(got, exp)


_Q59ISH = """
SELECT y1.s_store_name, y1.d_dow, y1.rev1, y2.rev2,
       y2.rev2 / y1.rev1 AS ratio
FROM (SELECT s_store_name, d_dow, sum(ss_ext_sales_price) AS rev1
      FROM store_sales, date_dim, store
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND d_year = 1998
      GROUP BY s_store_name, d_dow) y1,
     (SELECT s_store_name, d_dow, sum(ss_ext_sales_price) AS rev2
      FROM store_sales, date_dim, store
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND d_year = 1999
      GROUP BY s_store_name, d_dow) y2
WHERE y1.s_store_name = y2.s_store_name AND y1.d_dow = y2.d_dow
ORDER BY y1.s_store_name, y1.d_dow
"""


# ---------------------------------------------------------------------------
# round-5 additions: multi-CTE / set-operation / subquery planner stress
# (VERDICT r4 #5 — the TPC-DS stragglers that exercise INTERSECT/EXCEPT,
# FULL OUTER JOIN, CTE self-joins, correlated subqueries, EXISTS chains
# and ROLLUP rather than re-covering star joins)
# ---------------------------------------------------------------------------

def _channel_customers(t, fact, cust_col, date_col, year):
    """Distinct (last, first, customer_sk) triples active in a channel.
    customer_sk keeps the domain customer-sized: the 8x8 name-pair pool
    saturates at rig scale, which would let a no-op INTERSECT or an
    always-empty EXCEPT pass undetected."""
    f = _pd(t, fact)
    f = f[f[date_col].map(
        _pd(t, "date_dim").set_index("d_date_sk")["d_year"]) == year]
    cust = _pd(t, "customer")
    m = f.merge(cust, left_on=cust_col, right_on="c_customer_sk")
    return set(zip(m.c_last_name, m.c_first_name, m.c_customer_sk))


def _oracle_q38(got, t):
    s = _channel_customers(t, "store_sales", "ss_customer_sk",
                           "ss_sold_date_sk", 1999)
    c = _channel_customers(t, "catalog_sales", "cs_bill_customer_sk",
                           "cs_sold_date_sk", 1999)
    w = _channel_customers(t, "web_sales", "ws_bill_customer_sk",
                           "ws_sold_date_sk", 1999)
    exp = pd.DataFrame({"num": [len(s & c & w)]})
    _assert_rows(got, exp)


_Q38 = """
SELECT count(*) AS num FROM (
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM store_sales, date_dim, customer
  WHERE ss_sold_date_sk = d_date_sk AND ss_customer_sk = c_customer_sk
    AND d_year = 1999
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM catalog_sales, date_dim, customer
  WHERE cs_sold_date_sk = d_date_sk AND cs_bill_customer_sk = c_customer_sk
    AND d_year = 1999
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM web_sales, date_dim, customer
  WHERE ws_sold_date_sk = d_date_sk AND ws_bill_customer_sk = c_customer_sk
    AND d_year = 1999
) hot_cust
"""


def _oracle_q87(got, t):
    s = _channel_customers(t, "store_sales", "ss_customer_sk",
                           "ss_sold_date_sk", 1999)
    c = _channel_customers(t, "catalog_sales", "cs_bill_customer_sk",
                           "cs_sold_date_sk", 1999)
    w = _channel_customers(t, "web_sales", "ws_bill_customer_sk",
                           "ws_sold_date_sk", 1999)
    exp = pd.DataFrame({"num": [len(s - c - w)]})
    _assert_rows(got, exp)


_Q87 = """
SELECT count(*) AS num FROM (
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM store_sales, date_dim, customer
  WHERE ss_sold_date_sk = d_date_sk AND ss_customer_sk = c_customer_sk
    AND d_year = 1999
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM catalog_sales, date_dim, customer
  WHERE cs_sold_date_sk = d_date_sk AND cs_bill_customer_sk = c_customer_sk
    AND d_year = 1999
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM web_sales, date_dim, customer
  WHERE ws_sold_date_sk = d_date_sk AND ws_bill_customer_sk = c_customer_sk
    AND d_year = 1999
) cool_cust
"""


def _channel_pairs(t, fact, cust_col, item_col, date_col, year):
    f = _pd(t, fact)
    f = f[f[date_col].map(
        _pd(t, "date_dim").set_index("d_date_sk")["d_year"]) == year]
    return f[[cust_col, item_col]].drop_duplicates()


def _oracle_q97(got, t):
    s = _channel_pairs(t, "store_sales", "ss_customer_sk", "ss_item_sk",
                       "ss_sold_date_sk", 1999)
    c = _channel_pairs(t, "catalog_sales", "cs_bill_customer_sk",
                       "cs_item_sk", "cs_sold_date_sk", 1999)
    m = s.merge(c, left_on=["ss_customer_sk", "ss_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"],
                how="outer", indicator=True)
    exp = pd.DataFrame({
        "store_only": [int((m._merge == "left_only").sum())],
        "catalog_only": [int((m._merge == "right_only").sum())],
        "store_and_catalog": [int((m._merge == "both").sum())],
    })
    _assert_rows(got, exp)


_Q97 = """
WITH ssci AS (
  SELECT ss_customer_sk AS customer_sk, ss_item_sk AS item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_year = 1999
  GROUP BY ss_customer_sk, ss_item_sk),
csci AS (
  SELECT cs_bill_customer_sk AS customer_sk, cs_item_sk AS item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk AND d_year = 1999
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NULL THEN 1 ELSE 0 END)
         AS store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
         AS catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
         AS store_and_catalog
FROM ssci FULL OUTER JOIN csci
  ON (ssci.customer_sk = csci.customer_sk
      AND ssci.item_sk = csci.item_sk)
"""


def _year_totals(t, fact, cust_col, date_col, price_col):
    f = _pd(t, fact)
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    f = f.assign(dyear=f[date_col].map(dd))
    return (f.groupby([cust_col, "dyear"])[price_col].sum()
            .reset_index().rename(columns={cust_col: "customer_sk",
                                           price_col: "year_total"}))


def _oracle_q11(got, t):
    s = _year_totals(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price")
    w = _year_totals(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price")

    def year(df, y):
        return df[df.dyear == y].set_index("customer_sk")["year_total"]
    sf, ss2 = year(s, 1999), year(s, 2000)
    wf, ws2 = year(w, 1999), year(w, 2000)
    idx = sf.index.intersection(ss2.index).intersection(
        wf.index).intersection(ws2.index)
    idx = idx[(sf[idx] > 0) & (wf[idx] > 0)]
    keep = idx[(ws2[idx] / wf[idx]) > (ss2[idx] / sf[idx])]
    exp = pd.DataFrame({"customer_sk": sorted(keep)})
    _assert_rows(got, exp)


_Q11 = """
WITH year_total AS (
  SELECT ss_customer_sk AS customer_sk, d_year AS dyear,
         sum(ss_ext_sales_price) AS year_total, 's' AS sale_type
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
  GROUP BY ss_customer_sk, d_year
  UNION ALL
  SELECT ws_bill_customer_sk, d_year, sum(ws_ext_sales_price), 'w'
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk
  GROUP BY ws_bill_customer_sk, d_year)
SELECT t_s_secyear.customer_sk
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_sk = t_s_firstyear.customer_sk
  AND t_s_firstyear.customer_sk = t_w_secyear.customer_sk
  AND t_s_firstyear.customer_sk = t_w_firstyear.customer_sk
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 1999 AND t_s_secyear.dyear = 2000
  AND t_w_firstyear.dyear = 1999 AND t_w_secyear.dyear = 2000
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND t_w_secyear.year_total / t_w_firstyear.year_total
      > t_s_secyear.year_total / t_s_firstyear.year_total
ORDER BY t_s_secyear.customer_sk
"""


def _oracle_q31(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")
    addr = _pd(t, "customer_address")
    ss = _merged(t, ["customer_address"])
    ss = ss.assign(d_qoy=ss.ss_sold_date_sk.map(dd.d_qoy),
                   d_year=ss.ss_sold_date_sk.map(dd.d_year))
    ssg = (ss[ss.d_year == 2000].groupby(["ca_county", "d_qoy"])
           ["ss_ext_sales_price"].sum())
    ws = _pd(t, "web_sales").merge(
        _pd(t, "customer"), left_on="ws_bill_customer_sk",
        right_on="c_customer_sk").merge(
        addr, left_on="c_current_addr_sk", right_on="ca_address_sk")
    ws = ws.assign(d_qoy=ws.ws_sold_date_sk.map(dd.d_qoy),
                   d_year=ws.ws_sold_date_sk.map(dd.d_year))
    wsg = (ws[ws.d_year == 2000].groupby(["ca_county", "d_qoy"])
           ["ws_ext_sales_price"].sum())
    rows = []
    for county in addr.ca_county.unique():
        try:
            sg = ssg[(county, 2)] / ssg[(county, 1)]
            wg = wsg[(county, 2)] / wsg[(county, 1)]
        except KeyError:
            continue
        rows.append((county, sg, wg, 1 if wg > sg else 0))
    exp = pd.DataFrame(rows, columns=["ca_county", "store_growth",
                                      "web_growth", "web_faster"])
    _assert_rows(got, exp)


_Q31 = """
WITH ss AS (
  SELECT ca_county, d_qoy, d_year,
         sum(ss_ext_sales_price) AS store_sales_total
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
ws AS (
  SELECT ca_county, d_qoy, d_year,
         sum(ws_ext_sales_price) AS web_sales_total
  FROM web_sales, date_dim, customer, customer_address
  WHERE ws_sold_date_sk = d_date_sk
    AND ws_bill_customer_sk = c_customer_sk
    AND c_current_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county,
       ss2.store_sales_total / ss1.store_sales_total AS store_growth,
       ws2.web_sales_total / ws1.web_sales_total AS web_growth,
       CASE WHEN ws2.web_sales_total / ws1.web_sales_total
                 > ss2.store_sales_total / ss1.store_sales_total
            THEN 1 ELSE 0 END AS web_faster
FROM ss ss1, ss ss2, ws ws1, ws ws2
WHERE ss1.ca_county = ss2.ca_county AND ss1.ca_county = ws1.ca_county
  AND ss1.ca_county = ws2.ca_county
  AND ss1.d_qoy = 1 AND ss2.d_qoy = 2 AND ws1.d_qoy = 1 AND ws2.d_qoy = 2
  AND ss1.d_year = 2000 AND ss2.d_year = 2000
  AND ws1.d_year = 2000 AND ws2.d_year = 2000
ORDER BY ss1.ca_county
"""


def _oracle_q60(got, t):
    item = _pd(t, "item")
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]

    def chan(fact, item_col, date_col, price):
        f = _pd(t, fact)
        f = f[f[date_col].map(dd) == 1999]
        m = f.merge(item, left_on=item_col, right_on="i_item_sk")
        m = m[m.i_category_id == 3]
        return m.groupby("i_item_id")[price].sum()
    tot = (chan("store_sales", "ss_item_sk", "ss_sold_date_sk",
                "ss_ext_sales_price")
           .add(chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                     "cs_ext_sales_price"), fill_value=0)
           .add(chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
                     "ws_ext_sales_price"), fill_value=0))
    exp = tot.reset_index()
    exp.columns = ["i_item_id", "total_sales"]
    _assert_rows(got, exp)


_Q60 = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
    AND i_category_id = 3 AND d_year = 1999
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, item
  WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
    AND i_category_id = 3 AND d_year = 1999
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, item
  WHERE ws_sold_date_sk = d_date_sk AND ws_item_sk = i_item_sk
    AND i_category_id = 3 AND d_year = 1999
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
"""


def _oracle_q1(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    sr = _pd(t, "store_returns")
    sr = sr[sr.sr_returned_date_sk.map(dd) == 2000]
    ctr = (sr.groupby(["sr_customer_sk", "sr_store_sk"])["sr_return_amt"]
           .sum().reset_index(name="ctr_total_return"))
    avg = (ctr.groupby("sr_store_sk")["ctr_total_return"].mean() * 1.2)
    ctr = ctr[ctr.ctr_total_return > ctr.sr_store_sk.map(avg)]
    store = _pd(t, "store")
    keep_stores = set(store[store.s_county == "C1"].s_store_sk)
    ctr = ctr[ctr.sr_store_sk.isin(keep_stores)]
    cust = _pd(t, "customer")
    exp = ctr.merge(cust, left_on="sr_customer_sk",
                    right_on="c_customer_sk")[
        ["c_customer_sk", "c_first_name", "c_last_name"]]
    _assert_rows(got, exp)


_Q1 = """
WITH customer_total_return AS (
  SELECT sr_customer_sk AS ctr_customer_sk, sr_store_sk AS ctr_store_sk,
         sum(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_sk, c_first_name, c_last_name
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return >
      (SELECT avg(ctr_total_return) * 1.2
       FROM customer_total_return ctr2
       WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk AND s_county = 'C1'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_sk
"""


def _oracle_q93(got, t):
    ss = _pd(t, "store_sales")
    sr = _pd(t, "store_returns")[["sr_ticket_number", "sr_item_sk",
                                  "sr_return_amt"]]
    m = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"], how="left")
    act = np.where(m.sr_ticket_number.notna(),
                   m.ss_sales_price * (m.ss_quantity - 1),
                   m.ss_sales_price * m.ss_quantity)
    exp = (pd.DataFrame({"ss_customer_sk": m.ss_customer_sk,
                         "act_sales": act})
           .groupby("ss_customer_sk")["act_sales"].sum()
           .reset_index(name="sumsales"))
    _assert_rows(got, exp)


_Q93 = """
SELECT ss_customer_sk, sum(act_sales) AS sumsales
FROM (SELECT ss_customer_sk,
             CASE WHEN sr_ticket_number IS NOT NULL
                  THEN ss_sales_price * (ss_quantity - 1)
                  ELSE ss_sales_price * ss_quantity END AS act_sales
      FROM store_sales LEFT JOIN store_returns
        ON sr_ticket_number = ss_ticket_number
       AND sr_item_sk = ss_item_sk) t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
"""


def _oracle_q69(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]

    def active(fact, cust_col, date_col):
        f = _pd(t, fact)
        return set(f[f[date_col].map(dd) == 2000][cust_col])
    s = active("store_sales", "ss_customer_sk", "ss_sold_date_sk")
    w = active("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk")
    c = active("catalog_sales", "cs_bill_customer_sk", "cs_sold_date_sk")
    cust = _pd(t, "customer")
    addr = _pd(t, "customer_address")
    cd = _pd(t, "customer_demographics")
    m = cust.merge(addr, left_on="c_current_addr_sk",
                   right_on="ca_address_sk")
    m = m[m.ca_county.isin(["C1", "C2"])]
    m = m[m.c_customer_sk.isin(s - w - c)]
    m = m.merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    exp = (m.groupby(["cd_gender", "cd_marital_status",
                      "cd_education_status"])
           .size().reset_index(name="cnt"))
    _assert_rows(got, exp)


_Q69 = """
SELECT cd_gender, cd_marital_status, cd_education_status,
       count(*) AS cnt
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('C1', 'C2')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2000)
  AND NOT EXISTS (SELECT * FROM web_sales, date_dim
                  WHERE c.c_customer_sk = ws_bill_customer_sk
                    AND ws_sold_date_sk = d_date_sk AND d_year = 2000)
  AND NOT EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_bill_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2000)
GROUP BY cd_gender, cd_marital_status, cd_education_status
ORDER BY cd_gender, cd_marital_status, cd_education_status
"""


def _oracle_q65(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    ss = _pd(t, "store_sales")
    ss = ss[ss.ss_sold_date_sk.map(dd) == 1999]
    sa = (ss.groupby(["ss_store_sk", "ss_item_sk"])["ss_sales_price"]
          .sum().reset_index(name="revenue"))
    ave = sa.groupby("ss_store_sk")["revenue"].mean()
    sa = sa[sa.revenue <= 0.5 * sa.ss_store_sk.map(ave)]
    store = _pd(t, "store")
    item = _pd(t, "item")
    exp = (sa.merge(store, left_on="ss_store_sk", right_on="s_store_sk")
           .merge(item, left_on="ss_item_sk", right_on="i_item_sk")[
               ["s_store_name", "i_item_id", "revenue"]])
    _assert_rows(got, exp)


_Q65 = """
WITH sa AS (
  SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) AS revenue
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_year = 1999
  GROUP BY ss_store_sk, ss_item_sk),
sc AS (
  SELECT ss_store_sk, avg(revenue) AS ave FROM sa GROUP BY ss_store_sk)
SELECT s_store_name, i_item_id, sa.revenue
FROM store, item, sa, sc
WHERE sa.ss_store_sk = sc.ss_store_sk AND sa.revenue <= 0.5 * sc.ave
  AND s_store_sk = sa.ss_store_sk AND i_item_sk = sa.ss_item_sk
ORDER BY s_store_name, i_item_id
"""


def _oracle_q2ish(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")
    ws = _pd(t, "web_sales")
    cs = _pd(t, "catalog_sales")
    frames = [
        pd.DataFrame({"d_year": ws.ws_sold_date_sk.map(dd.d_year),
                      "d_dow": ws.ws_sold_date_sk.map(dd.d_dow),
                      "sales_price": ws.ws_ext_sales_price}),
        pd.DataFrame({"d_year": cs.cs_sold_date_sk.map(dd.d_year),
                      "d_dow": cs.cs_sold_date_sk.map(dd.d_dow),
                      "sales_price": cs.cs_ext_sales_price}),
    ]
    allc = pd.concat(frames)
    exp = (allc.groupby(["d_year", "d_dow"])["sales_price"].sum()
           .reset_index(name="total"))
    _assert_rows(got, exp)


_Q2ISH = """
WITH wscs AS (
  SELECT d_year, d_dow, ws_ext_sales_price AS sales_price
  FROM web_sales, date_dim WHERE ws_sold_date_sk = d_date_sk
  UNION ALL
  SELECT d_year, d_dow, cs_ext_sales_price
  FROM catalog_sales, date_dim WHERE cs_sold_date_sk = d_date_sk)
SELECT d_year, d_dow, sum(sales_price) AS total
FROM wscs GROUP BY d_year, d_dow ORDER BY d_year, d_dow
"""


def _oracle_q27(got, t):
    pdf = _merged(t, ["customer_demographics", "date_dim", "store",
                      "item"])
    pdf = pdf[(pdf.cd_gender == "M") & (pdf.cd_marital_status == "S")
              & (pdf.cd_education_status == "College")
              & (pdf.d_year == 2000)]

    def level(keys):
        if keys:
            g = pdf.groupby(keys).agg(
                agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean")).reset_index()
        else:
            g = pd.DataFrame({"agg1": [pdf.ss_quantity.mean()],
                              "agg2": [pdf.ss_list_price.mean()],
                              "agg3": [pdf.ss_coupon_amt.mean()],
                              "agg4": [pdf.ss_sales_price.mean()]})
        for col in ("i_item_id", "s_county"):
            if col not in g.columns:
                # np.nan (not None): pandas-3 str-dtype concat coerces
                # None to '' but keeps nan as missing
                g[col] = np.nan
        return g[["i_item_id", "s_county", "agg1", "agg2", "agg3",
                  "agg4"]]
    exp = pd.concat([level(["i_item_id", "s_county"]),
                     level(["i_item_id"]), level([])], ignore_index=True)
    _assert_rows(got, exp)


_Q27 = """
SELECT i_item_id, s_county, avg(ss_quantity) AS agg1,
       avg(ss_list_price) AS agg2, avg(ss_coupon_amt) AS agg3,
       avg(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk AND ss_item_sk = i_item_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College' AND d_year = 2000
GROUP BY ROLLUP(i_item_id, s_county)
ORDER BY i_item_id, s_county
"""


# ---------------------------------------------------------------------------
# round-5 wave 2: shipping/returns/promotion shapes over the extended star
# (warehouse, ship_mode, web_returns; zip/state address attributes; time-
# keyed catalog/web facts).  New plan shapes vs wave 1: fact-fact-fact
# chain joins (q25), IN-subquery channel CTEs (q33), date-lag CASE
# buckets (q50/q62), scalar-block ratio cross joins (q61/q90), correlated
# threshold subqueries (q92), and DISTINCT-count over a non-equi
# correlated EXISTS self-join (q94).
# ---------------------------------------------------------------------------

def _oracle_q15(got, t):
    dd = _pd(t, "date_dim")
    cs = (_pd(t, "catalog_sales")
          .merge(_pd(t, "customer"), left_on="cs_bill_customer_sk",
                 right_on="c_customer_sk")
          .merge(_pd(t, "customer_address"), left_on="c_current_addr_sk",
                 right_on="ca_address_sk")
          .merge(dd, left_on="cs_sold_date_sk", right_on="d_date_sk"))
    cs = cs[(cs.d_qoy == 1) & (cs.d_year == 2000)
            & (cs.ca_zip.str[:5].isin(_ZIPS[:5])
               | cs.ca_state.isin(["CA", "WA", "GA"])
               | (cs.cs_sales_price > 500))]
    exp = (cs.groupby("ca_zip")["cs_sales_price"].sum()
           .reset_index(name="total"))
    _assert_rows(got, exp)


_Q15 = f"""
SELECT ca_zip, sum(cs_sales_price) AS total
FROM catalog_sales, customer, customer_address, date_dim
WHERE cs_bill_customer_sk = c_customer_sk
  AND c_current_addr_sk = ca_address_sk
  AND (substr(ca_zip, 1, 5) IN ({", ".join(repr(z) for z in _ZIPS[:5])})
       OR ca_state IN ('CA', 'WA', 'GA') OR cs_sales_price > 500)
  AND cs_sold_date_sk = d_date_sk AND d_qoy = 1 AND d_year = 2000
GROUP BY ca_zip
ORDER BY ca_zip
"""


def _oracle_q25(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    ss = _pd(t, "store_sales")
    ss = ss[ss.ss_sold_date_sk.map(dd) == 2000]
    sr = _pd(t, "store_returns")
    sr = sr[sr.sr_returned_date_sk.map(dd).isin([2000, 2001])]
    cs = _pd(t, "catalog_sales")
    cs = cs[cs.cs_sold_date_sk.map(dd).isin([2000, 2001])]
    m = ss.merge(sr, left_on=["ss_customer_sk", "ss_item_sk",
                              "ss_ticket_number"],
                 right_on=["sr_customer_sk", "sr_item_sk",
                           "sr_ticket_number"])
    m = m.merge(cs, left_on=["sr_customer_sk", "sr_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"])
    m = (m.merge(_pd(t, "item"), left_on="ss_item_sk",
                 right_on="i_item_sk")
         .merge(_pd(t, "store"), left_on="ss_store_sk",
                right_on="s_store_sk"))
    exp = (m.groupby(["i_item_id", "s_store_name"])
           .agg(store_profit=("ss_net_profit", "sum"),
                return_loss=("sr_net_loss", "sum"),
                catalog_profit=("cs_net_profit", "sum")).reset_index())
    _assert_rows(got, exp)


_Q25 = """
SELECT i_item_id, s_store_name,
       sum(ss_net_profit) AS store_profit,
       sum(sr_net_loss) AS return_loss,
       sum(cs_net_profit) AS catalog_profit
FROM store_sales, store_returns, catalog_sales, date_dim d1, date_dim d2,
     date_dim d3, item, store
WHERE d1.d_year = 2000 AND d1.d_date_sk = ss_sold_date_sk
  AND i_item_sk = ss_item_sk AND s_store_sk = ss_store_sk
  AND ss_customer_sk = sr_customer_sk AND ss_item_sk = sr_item_sk
  AND ss_ticket_number = sr_ticket_number
  AND sr_returned_date_sk = d2.d_date_sk AND d2.d_year IN (2000, 2001)
  AND sr_customer_sk = cs_bill_customer_sk AND sr_item_sk = cs_item_sk
  AND cs_sold_date_sk = d3.d_date_sk AND d3.d_year IN (2000, 2001)
GROUP BY i_item_id, s_store_name
ORDER BY i_item_id, s_store_name
"""


def _oracle_q33(got, t):
    item = _pd(t, "item")
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    manufacts = set(item[item.i_category_id == 3].i_manufact_id)

    def chan(fact, item_col, date_col, price):
        f = _pd(t, fact)
        f = f[f[date_col].map(dd) == 1999]
        m = f.merge(item, left_on=item_col, right_on="i_item_sk")
        m = m[m.i_manufact_id.isin(manufacts)]
        return m.groupby("i_manufact_id")[price].sum()
    tot = (chan("store_sales", "ss_item_sk", "ss_sold_date_sk",
                "ss_ext_sales_price")
           .add(chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                     "cs_ext_sales_price"), fill_value=0)
           .add(chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
                     "ws_ext_sales_price"), fill_value=0))
    exp = tot.reset_index()
    exp.columns = ["i_manufact_id", "total_sales"]
    _assert_rows(got, exp)


def _q33_chan(fact, item_col, date_col, price):
    return f"""
  SELECT i_manufact_id, sum({price}) AS total_sales
  FROM {fact}, date_dim, item
  WHERE {date_col} = d_date_sk AND {item_col} = i_item_sk
    AND i_manufact_id IN (SELECT i_manufact_id FROM item
                          WHERE i_category_id = 3)
    AND d_year = 1999
  GROUP BY i_manufact_id"""


_Q33 = f"""
WITH ss AS ({_q33_chan('store_sales', 'ss_item_sk', 'ss_sold_date_sk',
                       'ss_ext_sales_price')}),
cs AS ({_q33_chan('catalog_sales', 'cs_item_sk', 'cs_sold_date_sk',
                  'cs_ext_sales_price')}),
ws AS ({_q33_chan('web_sales', 'ws_item_sk', 'ws_sold_date_sk',
                  'ws_ext_sales_price')})
SELECT i_manufact_id, sum(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_manufact_id
ORDER BY i_manufact_id
"""


#: the 30/60/90/120-day lag buckets shared by q50 (return lag) and q62
#: (ship lag) — one definition each for the SQL CASE chain and the
#: oracle columns so a bucket-edge tweak cannot desynchronize them
_LAG_EDGES = [(None, 30, "d30"), (30, 60, "d60"), (60, 90, "d90"),
              (90, 120, "d120"), (120, None, "dmore")]


def _lag_bucket_sql(lag_expr: str) -> str:
    parts = []
    for lo, hi, name in _LAG_EDGES:
        conds = []
        if lo is not None:
            conds.append(f"{lag_expr} > {lo}")
        if hi is not None:
            conds.append(f"{lag_expr} <= {hi}")
        parts.append(f"  sum(CASE WHEN {' AND '.join(conds)}\n"
                     f"           THEN 1 ELSE 0 END) AS {name}")
    return ",\n".join(parts)


def _lag_bucket_agg(m: pd.DataFrame, lag: pd.Series, keys: List[str]):
    cols = {}
    for lo, hi, name in _LAG_EDGES:
        mask = pd.Series(True, index=lag.index)
        if lo is not None:
            mask &= lag > lo
        if hi is not None:
            mask &= lag <= hi
        cols[name] = mask.astype(int)
    return (m.assign(**cols).groupby(keys)
            [[name for _, _, name in _LAG_EDGES]].sum().reset_index())


def _oracle_q50(got, t):
    dd = _pd(t, "date_dim")
    ss = _pd(t, "store_sales")
    sr = _pd(t, "store_returns")
    m = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk",
                              "ss_customer_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk",
                           "sr_customer_sk"])
    m = m.merge(dd, left_on="sr_returned_date_sk", right_on="d_date_sk")
    m = m[m.d_year == 2000]
    m = m.merge(_pd(t, "store"), left_on="ss_store_sk",
                right_on="s_store_sk")
    exp = _lag_bucket_agg(m, m.sr_returned_date_sk - m.ss_sold_date_sk,
                          ["s_store_name"])
    _assert_rows(got, exp)


_Q50 = f"""
SELECT s_store_name,
{_lag_bucket_sql('sr_returned_date_sk - ss_sold_date_sk')}
FROM store_sales, store_returns, store, date_dim d2
WHERE ss_ticket_number = sr_ticket_number AND ss_item_sk = sr_item_sk
  AND ss_customer_sk = sr_customer_sk
  AND sr_returned_date_sk = d2.d_date_sk AND d2.d_year = 2000
  AND ss_store_sk = s_store_sk
GROUP BY s_store_name
ORDER BY s_store_name
"""


def _oracle_q61(got, t):
    base = _merged(t, ["date_dim", "store", "customer", "item"])
    base = base.merge(_pd(t, "customer_address"),
                      left_on="c_current_addr_sk",
                      right_on="ca_address_sk")
    base = base[(base.d_year == 2000) & (base.s_county == "C1")
                & (base.ca_county.isin(["C1", "C2"]))
                & (base.i_category_id == 3)]
    promo = base.merge(_pd(t, "promotion"), left_on="ss_promo_sk",
                       right_on="p_promo_sk")
    promo = promo[(promo.p_channel_email == "Y")
                  | (promo.p_channel_event == "Y")]
    p, tot = promo.ss_ext_sales_price.sum(), base.ss_ext_sales_price.sum()
    exp = pd.DataFrame({"promotions": [p], "total": [tot],
                        "ratio": [p / tot * 100]})
    _assert_rows(got, exp)


_Q61_COMMON = """
  FROM store_sales{extra_tables}, store, date_dim, customer,
       customer_address, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
    AND ss_customer_sk = c_customer_sk
    AND ca_address_sk = c_current_addr_sk AND ss_item_sk = i_item_sk
    AND s_county = 'C1' AND ca_county IN ('C1', 'C2')
    AND i_category_id = 3 AND d_year = 2000"""

_Q61 = f"""
SELECT promotions, total, promotions / total * 100 AS ratio
FROM (SELECT sum(ss_ext_sales_price) AS promotions
  {_Q61_COMMON.format(extra_tables=', promotion')}
    AND ss_promo_sk = p_promo_sk
    AND (p_channel_email = 'Y' OR p_channel_event = 'Y')) promotional,
 (SELECT sum(ss_ext_sales_price) AS total
  {_Q61_COMMON.format(extra_tables='')}) all_sales
"""


def _oracle_q62(got, t):
    ws = _pd(t, "web_sales")
    m = (ws.merge(_pd(t, "warehouse"), left_on="ws_warehouse_sk",
                  right_on="w_warehouse_sk")
         .merge(_pd(t, "ship_mode"), left_on="ws_ship_mode_sk",
                right_on="sm_ship_mode_sk")
         .merge(_pd(t, "date_dim"), left_on="ws_ship_date_sk",
                right_on="d_date_sk"))
    m = m[m.d_year == 2000]
    m = m.assign(wname=m.w_warehouse_name.str[:20])
    exp = _lag_bucket_agg(m, m.ws_ship_date_sk - m.ws_sold_date_sk,
                          ["wname", "sm_type"])
    _assert_rows(got, exp)


_Q62 = f"""
SELECT substr(w_warehouse_name, 1, 20) AS wname, sm_type,
{_lag_bucket_sql('ws_ship_date_sk - ws_sold_date_sk')}
FROM web_sales, warehouse, ship_mode, date_dim
WHERE ws_ship_date_sk = d_date_sk AND d_year = 2000
  AND ws_warehouse_sk = w_warehouse_sk
  AND ws_ship_mode_sk = sm_ship_mode_sk
GROUP BY substr(w_warehouse_name, 1, 20), sm_type
ORDER BY wname, sm_type
"""


def _oracle_q71(got, t):
    item = _pd(t, "item")
    item = item[item.i_manager_id <= 20]
    dd = _pd(t, "date_dim")
    td = _pd(t, "time_dim")

    def chan(fact, item_col, date_col, time_col, price):
        f = _pd(t, fact)
        m = f.merge(dd, left_on=date_col, right_on="d_date_sk")
        m = m[(m.d_moy == 11) & (m.d_year == 1999)]
        return pd.DataFrame({"price": m[price], "item_sk": m[item_col],
                             "time_sk": m[time_col]})
    allc = pd.concat([
        chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
             "ws_sold_time_sk", "ws_ext_sales_price"),
        chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
             "cs_sold_time_sk", "cs_ext_sales_price"),
        chan("store_sales", "ss_item_sk", "ss_sold_date_sk",
             "ss_sold_time_sk", "ss_ext_sales_price")])
    m = (allc.merge(item, left_on="item_sk", right_on="i_item_sk")
         .merge(td, left_on="time_sk", right_on="t_time_sk"))
    m = m[m.t_hour.between(8, 10)]
    exp = (m.groupby(["i_brand_id", "i_brand", "t_hour", "t_minute"])
           ["price"].sum().reset_index(name="ext_price"))
    exp = exp[["i_brand_id", "i_brand", "t_hour", "t_minute",
               "ext_price"]]
    _assert_rows(got, exp)


_Q71 = """
SELECT i_brand_id, i_brand, t_hour, t_minute,
       sum(ext_price) AS ext_price
FROM item,
 (SELECT ws_ext_sales_price AS ext_price, ws_item_sk AS sold_item_sk,
         ws_sold_time_sk AS time_sk
  FROM web_sales, date_dim
  WHERE d_date_sk = ws_sold_date_sk AND d_moy = 11 AND d_year = 1999
  UNION ALL
  SELECT cs_ext_sales_price, cs_item_sk, cs_sold_time_sk
  FROM catalog_sales, date_dim
  WHERE d_date_sk = cs_sold_date_sk AND d_moy = 11 AND d_year = 1999
  UNION ALL
  SELECT ss_ext_sales_price, ss_item_sk, ss_sold_time_sk
  FROM store_sales, date_dim
  WHERE d_date_sk = ss_sold_date_sk AND d_moy = 11
    AND d_year = 1999) tmp,
 time_dim
WHERE sold_item_sk = i_item_sk AND i_manager_id <= 20
  AND time_sk = t_time_sk AND t_hour BETWEEN 8 AND 10
GROUP BY i_brand_id, i_brand, t_hour, t_minute
ORDER BY ext_price DESC, i_brand_id, t_hour, t_minute
"""


def _q90_count(t, h0, h1):
    ws = _pd(t, "web_sales")
    m = (ws.merge(_pd(t, "household_demographics"),
                  left_on="ws_ship_hdemo_sk", right_on="hd_demo_sk")
         .merge(_pd(t, "time_dim"), left_on="ws_sold_time_sk",
                right_on="t_time_sk"))
    return len(m[(m.t_hour.between(h0, h1)) & (m.hd_dep_count == 3)])


def _oracle_q90(got, t):
    amc, pmc = _q90_count(t, 7, 9), _q90_count(t, 17, 19)
    exp = pd.DataFrame({"am_pm_ratio": [amc * 1.0 / pmc]})
    _assert_rows(got, exp)


def _q90_block(alias, h0, h1):
    return (f"(SELECT count(*) AS {alias} "
            f"FROM web_sales, household_demographics, time_dim "
            f"WHERE ws_ship_hdemo_sk = hd_demo_sk "
            f"AND ws_sold_time_sk = t_time_sk "
            f"AND t_hour BETWEEN {h0} AND {h1} "
            f"AND hd_dep_count = 3)")


_Q90 = f"""
SELECT amc * 1.0 / pmc AS am_pm_ratio
FROM {_q90_block('amc', 7, 9)} at, {_q90_block('pmc', 17, 19)} pt
"""


def _oracle_q92(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    ws = _pd(t, "web_sales")
    ws = ws[ws.ws_sold_date_sk.map(dd) == 2000]
    item = _pd(t, "item")
    thresh = (ws.groupby("ws_item_sk")["ws_ext_discount_amt"]
              .mean() * 1.3)
    m = ws.merge(item, left_on="ws_item_sk", right_on="i_item_sk")
    m = m[m.i_manufact_id <= 30]
    m = m[m.ws_ext_discount_amt > m.ws_item_sk.map(thresh)]
    exp = pd.DataFrame({"excess": [m.ws_ext_discount_amt.sum()]})
    _assert_rows(got, exp)


_Q92 = """
SELECT sum(ws_ext_discount_amt) AS excess
FROM web_sales ws1, item, date_dim
WHERE i_item_sk = ws1.ws_item_sk AND i_manufact_id <= 30
  AND ws1.ws_sold_date_sk = d_date_sk AND d_year = 2000
  AND ws1.ws_ext_discount_amt >
      (SELECT 1.3 * avg(ws_ext_discount_amt)
       FROM web_sales ws2, date_dim d2
       WHERE ws2.ws_item_sk = ws1.ws_item_sk
         AND ws2.ws_sold_date_sk = d2.d_date_sk AND d2.d_year = 2000)
"""


def _ws_order_stats(t, returned_polarity: bool):
    """Shared q94/q95 oracle: multi-warehouse CA-shipped year-2000 web
    orders, kept (q95) or excluded (q94) by web_returns membership;
    returns the (order_count, shipping, profit) frame with SQL's
    sum-over-zero-rows-is-NULL semantics."""
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    ws = _pd(t, "web_sales")
    wh_per_order = ws.groupby("ws_order_number")["ws_warehouse_sk"] \
        .nunique()
    returned = set(_pd(t, "web_returns").wr_order_number)
    m = ws[ws.ws_ship_date_sk.map(dd) == 2000]
    m = m.merge(_pd(t, "warehouse"), left_on="ws_warehouse_sk",
                right_on="w_warehouse_sk")
    m = m[m.w_state == "CA"]
    m = m[m.ws_order_number.map(wh_per_order) > 1]
    is_ret = m.ws_order_number.isin(returned)
    m = m[is_ret] if returned_polarity else m[~is_ret]
    return pd.DataFrame({
        "order_count": [m.ws_order_number.nunique()],
        "total_shipping_cost": [m.ws_ext_ship_cost.sum()
                                if len(m) else np.nan],
        "total_net_profit": [m.ws_net_profit.sum()
                             if len(m) else np.nan],
    })


def _oracle_q94(got, t):
    _assert_rows(got, _ws_order_stats(t, returned_polarity=False))


_Q94 = """
SELECT count(DISTINCT ws_order_number) AS order_count,
       sum(ws_ext_ship_cost) AS total_shipping_cost,
       sum(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, warehouse
WHERE ws1.ws_ship_date_sk = d_date_sk AND d_year = 2000
  AND ws1.ws_warehouse_sk = w_warehouse_sk AND w_state = 'CA'
  AND EXISTS (SELECT * FROM web_sales ws2
              WHERE ws1.ws_order_number = ws2.ws_order_number
                AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
  AND NOT EXISTS (SELECT * FROM web_returns wr1
                  WHERE ws1.ws_order_number = wr1.wr_order_number)
"""


# ---------------------------------------------------------------------------
# round-5 wave 3: inventory / catalog-returns shapes.  New plan stress:
# HAVING on a ratio of conditional sums (q21), inventory semi-join window
# (q37), LEFT JOIN on a composite key + coalesce in a CASE split (q40),
# day-of-week CASE pivot (q43), OR-of-ANDs with join predicates inside
# the disjunction — the common-conjunct factoring path (q48), and
# CTE-backed IN-subquery chains over a self-join (q95).
# ---------------------------------------------------------------------------

def _oracle_q21(got, t):
    inv = _pd(t, "inventory")
    inv = inv[(inv.inv_date_sk >= 840) & (inv.inv_date_sk <= 960)]
    m = (inv.merge(_pd(t, "warehouse"), left_on="inv_warehouse_sk",
                   right_on="w_warehouse_sk")
         .merge(_pd(t, "item"), left_on="inv_item_sk",
                right_on="i_item_sk"))
    m = m.assign(
        before=np.where(m.inv_date_sk < 900, m.inv_quantity_on_hand, 0),
        after=np.where(m.inv_date_sk >= 900, m.inv_quantity_on_hand, 0))
    g = (m.groupby(["w_warehouse_name", "i_item_id"])
         .agg(inv_before=("before", "sum"),
              inv_after=("after", "sum")).reset_index())
    exp = g[(g.inv_before > 0) & (g.inv_after * 3 >= g.inv_before * 2)
            & (g.inv_after * 2 <= g.inv_before * 3)]
    _assert_rows(got, exp)


_Q21 = """
SELECT w_warehouse_name, i_item_id,
       sum(CASE WHEN inv_date_sk < 900
                THEN inv_quantity_on_hand ELSE 0 END) AS inv_before,
       sum(CASE WHEN inv_date_sk >= 900
                THEN inv_quantity_on_hand ELSE 0 END) AS inv_after
FROM inventory, warehouse, item, date_dim
WHERE inv_item_sk = i_item_sk AND inv_warehouse_sk = w_warehouse_sk
  AND inv_date_sk = d_date_sk AND d_date_sk BETWEEN 840 AND 960
GROUP BY w_warehouse_name, i_item_id
HAVING sum(CASE WHEN inv_date_sk < 900
                THEN inv_quantity_on_hand ELSE 0 END) > 0
   AND sum(CASE WHEN inv_date_sk >= 900
                THEN inv_quantity_on_hand ELSE 0 END) * 3
       >= sum(CASE WHEN inv_date_sk < 900
                   THEN inv_quantity_on_hand ELSE 0 END) * 2
   AND sum(CASE WHEN inv_date_sk >= 900
                THEN inv_quantity_on_hand ELSE 0 END) * 2
       <= sum(CASE WHEN inv_date_sk < 900
                   THEN inv_quantity_on_hand ELSE 0 END) * 3
ORDER BY w_warehouse_name, i_item_id
"""


def _oracle_q37(got, t):
    item = _pd(t, "item")
    item = item[item.i_current_price.between(20, 50)
                & (item.i_manufact_id <= 40)]
    inv = _pd(t, "inventory")
    inv = inv[(inv.inv_date_sk.between(900, 960))
              & (inv.inv_quantity_on_hand.between(100, 500))]
    cs_items = set(_pd(t, "catalog_sales").cs_item_sk)
    m = item[item.i_item_sk.isin(set(inv.inv_item_sk)) &
             item.i_item_sk.isin(cs_items)]
    exp = (m[["i_item_id", "i_current_price"]].drop_duplicates())
    _assert_rows(got, exp)


_Q37 = """
SELECT i_item_id, i_current_price
FROM item, inventory, date_dim, catalog_sales
WHERE i_current_price BETWEEN 20 AND 50 AND i_manufact_id <= 40
  AND inv_item_sk = i_item_sk AND d_date_sk = inv_date_sk
  AND d_date_sk BETWEEN 900 AND 960
  AND inv_quantity_on_hand BETWEEN 100 AND 500
  AND cs_item_sk = i_item_sk
GROUP BY i_item_id, i_current_price
ORDER BY i_item_id
"""


def _oracle_q40(got, t):
    cs = _pd(t, "catalog_sales")
    cr = _pd(t, "catalog_returns")
    m = cs.merge(cr, left_on=["cs_order_number", "cs_item_sk"],
                 right_on=["cr_order_number", "cr_item_sk"], how="left")
    m = (m.merge(_pd(t, "warehouse"), left_on="cs_warehouse_sk",
                 right_on="w_warehouse_sk")
         .merge(_pd(t, "item"), left_on="cs_item_sk",
                right_on="i_item_sk"))
    m = m[m.i_current_price.between(20, 70)
          & m.cs_sold_date_sk.between(840, 960)]
    net = m.cs_sales_price - m.cr_refunded_cash.fillna(0.0)
    m = m.assign(before=np.where(m.cs_sold_date_sk < 900, net, 0.0),
                 after=np.where(m.cs_sold_date_sk >= 900, net, 0.0))
    exp = (m.groupby(["w_state", "i_item_id"])
           .agg(sales_before=("before", "sum"),
                sales_after=("after", "sum")).reset_index())
    _assert_rows(got, exp)


_Q40 = """
SELECT w_state, i_item_id,
  sum(CASE WHEN cs_sold_date_sk < 900
           THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
           ELSE 0 END) AS sales_before,
  sum(CASE WHEN cs_sold_date_sk >= 900
           THEN cs_sales_price - coalesce(cr_refunded_cash, 0)
           ELSE 0 END) AS sales_after
FROM catalog_sales LEFT JOIN catalog_returns
  ON (cs_order_number = cr_order_number AND cs_item_sk = cr_item_sk),
  warehouse, item, date_dim
WHERE i_current_price BETWEEN 20 AND 70 AND i_item_sk = cs_item_sk
  AND cs_warehouse_sk = w_warehouse_sk AND cs_sold_date_sk = d_date_sk
  AND d_date_sk BETWEEN 840 AND 960
GROUP BY w_state, i_item_id
ORDER BY w_state, i_item_id
"""


def _oracle_q43(got, t):
    pdf = _merged(t, ["date_dim", "store"])
    pdf = pdf[pdf.d_year == 2000]
    cols = {}
    for d, nm in enumerate(("sun", "mon", "tue", "wed", "thu", "fri",
                            "sat")):
        cols[f"{nm}_sales"] = np.where(pdf.d_dow == d,
                                       pdf.ss_sales_price, 0.0)
    exp = (pd.DataFrame({"s_store_name": pdf.s_store_name, **cols})
           .groupby("s_store_name").sum().reset_index())
    _assert_rows(got, exp)


_Q43 = """
SELECT s_store_name,
  sum(CASE WHEN d_dow = 0 THEN ss_sales_price ELSE 0 END) AS sun_sales,
  sum(CASE WHEN d_dow = 1 THEN ss_sales_price ELSE 0 END) AS mon_sales,
  sum(CASE WHEN d_dow = 2 THEN ss_sales_price ELSE 0 END) AS tue_sales,
  sum(CASE WHEN d_dow = 3 THEN ss_sales_price ELSE 0 END) AS wed_sales,
  sum(CASE WHEN d_dow = 4 THEN ss_sales_price ELSE 0 END) AS thu_sales,
  sum(CASE WHEN d_dow = 5 THEN ss_sales_price ELSE 0 END) AS fri_sales,
  sum(CASE WHEN d_dow = 6 THEN ss_sales_price ELSE 0 END) AS sat_sales
FROM date_dim, store_sales, store
WHERE d_date_sk = ss_sold_date_sk AND s_store_sk = ss_store_sk
  AND d_year = 2000
GROUP BY s_store_name
ORDER BY s_store_name
"""


def _oracle_q48(got, t):
    ss = _pd(t, "store_sales")
    cd = _pd(t, "customer_demographics")
    ca = _pd(t, "customer_address")
    m = (ss.merge(_pd(t, "store"), left_on="ss_store_sk",
                  right_on="s_store_sk")
         .merge(_pd(t, "date_dim"), left_on="ss_sold_date_sk",
                right_on="d_date_sk")
         .merge(cd, left_on="ss_cdemo_sk", right_on="cd_demo_sk")
         .merge(ca, left_on="ss_addr_sk", right_on="ca_address_sk"))
    m = m[m.d_year == 2000]
    c1 = ((m.cd_marital_status == "M")
          & (m.cd_education_status == "Advanced Degree")
          & m.ss_sales_price.between(100.0, 150.0))
    c2 = ((m.cd_marital_status == "S")
          & (m.cd_education_status == "College")
          & m.ss_sales_price.between(50.0, 100.0))
    c3 = ((m.cd_marital_status == "W")
          & (m.cd_education_status == "Secondary")
          & m.ss_sales_price.between(0.0, 50.0))
    a1 = m.ca_state.isin(["CA", "WA"]) & m.ss_net_profit.between(0, 50)
    a2 = m.ca_state.isin(["GA", "TX"]) & m.ss_net_profit.between(50, 80)
    a3 = m.ca_state.isin(["NY", "OH"]) & m.ss_net_profit.between(-20, 20)
    m = m[(c1 | c2 | c3) & (a1 | a2 | a3)]
    exp = pd.DataFrame({"total_quantity": [int(m.ss_quantity.sum())]})
    _assert_rows(got, exp)


_Q48 = """
SELECT sum(ss_quantity) AS total_quantity
FROM store_sales, store, customer_demographics, customer_address,
     date_dim
WHERE s_store_sk = ss_store_sk AND ss_sold_date_sk = d_date_sk
  AND d_year = 2000
  AND ((cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'M'
        AND cd_education_status = 'Advanced Degree'
        AND ss_sales_price BETWEEN 100.00 AND 150.00)
    OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'S'
        AND cd_education_status = 'College'
        AND ss_sales_price BETWEEN 50.00 AND 100.00)
    OR (cd_demo_sk = ss_cdemo_sk AND cd_marital_status = 'W'
        AND cd_education_status = 'Secondary'
        AND ss_sales_price BETWEEN 0.00 AND 50.00))
  AND ((ss_addr_sk = ca_address_sk AND ca_state IN ('CA', 'WA')
        AND ss_net_profit BETWEEN 0 AND 50)
    OR (ss_addr_sk = ca_address_sk AND ca_state IN ('GA', 'TX')
        AND ss_net_profit BETWEEN 50 AND 80)
    OR (ss_addr_sk = ca_address_sk AND ca_state IN ('NY', 'OH')
        AND ss_net_profit BETWEEN -20 AND 20))
"""


def _oracle_q95(got, t):
    # q95's second IN keeps only orders that appear in web_returns (the
    # join to ws_wh re-asserts multi-warehouse): inverted polarity vs q94
    _assert_rows(got, _ws_order_stats(t, returned_polarity=True))


_Q95 = """
WITH ws_wh AS (
  SELECT ws1.ws_order_number
  FROM web_sales ws1, web_sales ws2
  WHERE ws1.ws_order_number = ws2.ws_order_number
    AND ws1.ws_warehouse_sk <> ws2.ws_warehouse_sk)
SELECT count(DISTINCT ws_order_number) AS order_count,
       sum(ws_ext_ship_cost) AS total_shipping_cost,
       sum(ws_net_profit) AS total_net_profit
FROM web_sales ws1, date_dim, warehouse
WHERE ws1.ws_ship_date_sk = d_date_sk AND d_year = 2000
  AND ws1.ws_warehouse_sk = w_warehouse_sk AND w_state = 'CA'
  AND ws1.ws_order_number IN (SELECT ws_order_number FROM ws_wh)
  AND ws1.ws_order_number IN (SELECT wr_order_number
                              FROM web_returns, ws_wh
                              WHERE wr_order_number = ws_wh.ws_order_number)
"""


# ---------------------------------------------------------------------------
# round-5 wave 4: catalog demographics (q26), inventory coefficient-of-
# variation with STDDEV_SAMP + month self-join (q39), three-channel
# revenue-band join over a thrice-reused CTE (q58), 3-level ROLLUP over
# the catalog star (q18 shape).
# ---------------------------------------------------------------------------

def _oracle_q26(got, t):
    m = (_pd(t, "catalog_sales")
         .merge(_pd(t, "customer_demographics"), left_on="cs_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(_pd(t, "date_dim"), left_on="cs_sold_date_sk",
                right_on="d_date_sk")
         .merge(_pd(t, "item"), left_on="cs_item_sk",
                right_on="i_item_sk")
         .merge(_pd(t, "promotion"), left_on="cs_promo_sk",
                right_on="p_promo_sk"))
    m = m[(m.cd_gender == "F") & (m.cd_marital_status == "S")
          & (m.cd_education_status == "College")
          & ((m.p_channel_email == "N") | (m.p_channel_event == "N"))
          & (m.d_year == 2000)]
    exp = (m.groupby("i_item_id")
           .agg(agg1=("cs_quantity", "mean"),
                agg2=("cs_list_price", "mean"),
                agg3=("cs_sales_price", "mean")).reset_index())
    _assert_rows(got, exp)


_Q26 = """
SELECT i_item_id, avg(cs_quantity) AS agg1,
       avg(cs_list_price) AS agg2, avg(cs_sales_price) AS agg3
FROM catalog_sales, customer_demographics, date_dim, item, promotion
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_cdemo_sk = cd_demo_sk AND cs_promo_sk = p_promo_sk
  AND cd_gender = 'F' AND cd_marital_status = 'S'
  AND cd_education_status = 'College'
  AND (p_channel_email = 'N' OR p_channel_event = 'N')
  AND d_year = 2000
GROUP BY i_item_id
ORDER BY i_item_id
"""


def _inv_cov(t, moy):
    m = (_pd(t, "inventory")
         .merge(_pd(t, "date_dim"), left_on="inv_date_sk",
                right_on="d_date_sk"))
    m = m[(m.d_year == 2000) & (m.d_moy == moy)]
    g = (m.groupby(["inv_warehouse_sk", "inv_item_sk"])
         ["inv_quantity_on_hand"].agg(["mean", "std"]).reset_index())
    g = g[g["std"] / g["mean"] > 0.5]
    g["cov"] = g["std"] / g["mean"]
    return g


def _oracle_q39(got, t):
    a, b = _inv_cov(t, 4), _inv_cov(t, 5)
    exp = a.merge(b, on=["inv_warehouse_sk", "inv_item_sk"],
                  suffixes=("_1", "_2"))[
        ["inv_warehouse_sk", "inv_item_sk", "mean_1", "cov_1",
         "mean_2", "cov_2"]]
    _assert_rows(got, exp)


def _q39_cte(moy):
    return f"""
  SELECT inv_warehouse_sk AS w, inv_item_sk AS i,
         avg(inv_quantity_on_hand) AS qty_mean,
         stddev_samp(inv_quantity_on_hand)
           / avg(inv_quantity_on_hand) AS qty_cov
  FROM inventory, date_dim
  WHERE inv_date_sk = d_date_sk AND d_year = 2000 AND d_moy = {moy}
  GROUP BY inv_warehouse_sk, inv_item_sk
  HAVING stddev_samp(inv_quantity_on_hand)
           / avg(inv_quantity_on_hand) > 0.5"""


_Q39 = f"""
WITH inv1 AS ({_q39_cte(4)}), inv2 AS ({_q39_cte(5)})
SELECT inv1.w, inv1.i, inv1.qty_mean AS mean_1, inv1.qty_cov AS cov_1,
       inv2.qty_mean AS mean_2, inv2.qty_cov AS cov_2
FROM inv1, inv2
WHERE inv1.w = inv2.w AND inv1.i = inv2.i
ORDER BY inv1.w, inv1.i
"""


def _oracle_q58(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    item = _pd(t, "item")

    def chan(fact, item_col, date_col, price):
        f = _pd(t, fact)
        f = f[f[date_col].map(dd) == 1999]
        m = f.merge(item, left_on=item_col, right_on="i_item_sk")
        return m.groupby("i_item_id")[price].sum()
    ss = chan("store_sales", "ss_item_sk", "ss_sold_date_sk",
              "ss_ext_sales_price")
    cs = chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
              "cs_ext_sales_price")
    ws = chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
              "ws_ext_sales_price")
    j = (ss.rename("ss_rev").to_frame()
         .join(cs.rename("cs_rev"), how="inner")
         .join(ws.rename("ws_rev"), how="inner"))
    avg = (j.ss_rev + j.cs_rev + j.ws_rev) / 3.0
    keep = ((j.ss_rev.between(0.5 * avg, 2.0 * avg))
            & (j.cs_rev.between(0.5 * avg, 2.0 * avg))
            & (j.ws_rev.between(0.5 * avg, 2.0 * avg)))
    exp = j[keep].reset_index()
    exp["average"] = avg[keep].values
    _assert_rows(got, exp)


def _q58_cte(alias, fact, item_col, date_col, price):
    return f"""
{alias} AS (
  SELECT i_item_id AS item_id, sum({price}) AS revenue
  FROM {fact}, item, date_dim
  WHERE {item_col} = i_item_sk AND {date_col} = d_date_sk
    AND d_year = 1999
  GROUP BY i_item_id)"""


_Q58 = f"""
WITH {_q58_cte('ss_items', 'store_sales', 'ss_item_sk',
               'ss_sold_date_sk', 'ss_ext_sales_price')},
{_q58_cte('cs_items', 'catalog_sales', 'cs_item_sk', 'cs_sold_date_sk',
          'cs_ext_sales_price')},
{_q58_cte('ws_items', 'web_sales', 'ws_item_sk', 'ws_sold_date_sk',
          'ws_ext_sales_price')}
SELECT ss_items.item_id, ss_items.revenue AS ss_rev,
       cs_items.revenue AS cs_rev, ws_items.revenue AS ws_rev,
       (ss_items.revenue + cs_items.revenue + ws_items.revenue) / 3
         AS average
FROM ss_items, cs_items, ws_items
WHERE ss_items.item_id = cs_items.item_id
  AND ss_items.item_id = ws_items.item_id
  AND ss_items.revenue BETWEEN
      0.5 * (ss_items.revenue + cs_items.revenue + ws_items.revenue) / 3
      AND 2.0 * (ss_items.revenue + cs_items.revenue + ws_items.revenue) / 3
  AND cs_items.revenue BETWEEN
      0.5 * (ss_items.revenue + cs_items.revenue + ws_items.revenue) / 3
      AND 2.0 * (ss_items.revenue + cs_items.revenue + ws_items.revenue) / 3
  AND ws_items.revenue BETWEEN
      0.5 * (ss_items.revenue + cs_items.revenue + ws_items.revenue) / 3
      AND 2.0 * (ss_items.revenue + cs_items.revenue + ws_items.revenue) / 3
ORDER BY ss_items.item_id
"""


def _oracle_q18(got, t):
    m = (_pd(t, "catalog_sales")
         .merge(_pd(t, "customer_demographics"), left_on="cs_cdemo_sk",
                right_on="cd_demo_sk")
         .merge(_pd(t, "customer"), left_on="cs_bill_customer_sk",
                right_on="c_customer_sk")
         .merge(_pd(t, "customer_address"), left_on="c_current_addr_sk",
                right_on="ca_address_sk")
         .merge(_pd(t, "date_dim"), left_on="cs_sold_date_sk",
                right_on="d_date_sk")
         .merge(_pd(t, "item"), left_on="cs_item_sk",
                right_on="i_item_sk"))
    m = m[(m.cd_gender == "F") & (m.cd_education_status == "College")
          & (m.d_year == 2000)]

    def level(keys):
        if keys:
            g = (m.groupby(keys)
                 .agg(agg1=("cs_quantity", "mean"),
                      agg2=("cs_list_price", "mean")).reset_index())
        else:
            g = pd.DataFrame({"agg1": [m.cs_quantity.mean()],
                              "agg2": [m.cs_list_price.mean()]})
        for col in ("i_item_id", "ca_state", "ca_county"):
            if col not in g.columns:
                g[col] = np.nan
        return g[["i_item_id", "ca_state", "ca_county", "agg1", "agg2"]]
    exp = pd.concat([level(["i_item_id", "ca_state", "ca_county"]),
                     level(["i_item_id", "ca_state"]),
                     level(["i_item_id"]), level([])],
                    ignore_index=True)
    _assert_rows(got, exp)


_Q18 = """
SELECT i_item_id, ca_state, ca_county,
       avg(cs_quantity) AS agg1, avg(cs_list_price) AS agg2
FROM catalog_sales, customer_demographics, customer, customer_address,
     date_dim, item
WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
  AND cs_bill_customer_sk = c_customer_sk
  AND cs_cdemo_sk = cd_demo_sk
  AND c_current_addr_sk = ca_address_sk
  AND cd_gender = 'F' AND cd_education_status = 'College'
  AND d_year = 2000
GROUP BY ROLLUP(i_item_id, ca_state, ca_county)
ORDER BY i_item_id, ca_state, ca_county
"""


#: (name, sql, oracle) — consumed by scaletest.QUERIES via make_runner
QUERY_SET: List[Tuple[str, str, Callable]] = [
    ("q34_ticket_counts", _Q34, _oracle_q34),
    ("q42_category_rev_sql", _Q42_SQL, _oracle_q42),
    ("q52_brand_rev", _Q52, _oracle_q52),
    ("q53_manufact_window", _Q53, _oracle_q53),
    ("q55_brand_rev_mgr", _Q55, _oracle_q55),
    ("q59_weekly_ratio", _Q59ISH, _oracle_q59ish),
    ("q68_city_tickets", _Q68, _oracle_q68),
    ("q73_ticket_counts", _Q73, _oracle_q73),
    ("q79_amt_profit", _Q79, _oracle_q79),
    ("q88_time_buckets", _Q88, _oracle_q88),
    ("q96_time_count", _Q96, _oracle_q96),
    ("q98_revenue_ratio", _Q98, _oracle_q98),
    # round 5: multi-CTE / set-op / subquery planner stress
    ("q1_returns_corr_subq", _Q1, _oracle_q1),
    ("q2_weekly_channels", _Q2ISH, _oracle_q2ish),
    ("q11_yoy_ratio", _Q11, _oracle_q11),
    ("q27_rollup", _Q27, _oracle_q27),
    ("q31_county_growth", _Q31, _oracle_q31),
    ("q38_intersect", _Q38, _oracle_q38),
    ("q60_three_channels", _Q60, _oracle_q60),
    ("q65_low_revenue", _Q65, _oracle_q65),
    ("q69_channel_gap", _Q69, _oracle_q69),
    ("q87_except", _Q87, _oracle_q87),
    ("q93_returns_net", _Q93, _oracle_q93),
    ("q97_full_outer", _Q97, _oracle_q97),
    # round-5 wave 2: shipping/returns/promotion shapes
    ("q15_zip_or_filter", _Q15, _oracle_q15),
    ("q25_fact_chain", _Q25, _oracle_q25),
    ("q33_in_subq_channels", _Q33, _oracle_q33),
    ("q50_return_lag", _Q50, _oracle_q50),
    ("q61_promo_ratio", _Q61, _oracle_q61),
    ("q62_ship_lag", _Q62, _oracle_q62),
    ("q71_brand_time", _Q71, _oracle_q71),
    ("q90_am_pm", _Q90, _oracle_q90),
    ("q92_excess_discount", _Q92, _oracle_q92),
    ("q94_multi_warehouse", _Q94, _oracle_q94),
    # round-5 wave 3: inventory / catalog-returns shapes
    ("q21_inventory_ratio", _Q21, _oracle_q21),
    ("q37_inventory_window", _Q37, _oracle_q37),
    ("q40_returns_split", _Q40, _oracle_q40),
    ("q43_dow_pivot", _Q43, _oracle_q43),
    ("q48_or_of_ands", _Q48, _oracle_q48),
    ("q95_cte_in_chains", _Q95, _oracle_q95),
    # round-5 wave 4: catalog demographics / inventory CoV / revenue bands
    ("q18_rollup3", _Q18, _oracle_q18),
    ("q26_catalog_demo", _Q26, _oracle_q26),
    ("q39_inventory_cov", _Q39, _oracle_q39),
    ("q58_revenue_bands", _Q58, _oracle_q58),
]


def register_views(sess, t: Dict[str, pa.Table]) -> None:
    parts = {"store_sales": 4}
    for name, tbl in t.items():
        sess.create_dataframe(
            tbl, num_partitions=parts.get(name, 2)
        ).createOrReplaceTempView(name)


from .rig_util import ViewCache  # noqa: E402  (needs register_views)

_views = ViewCache(register_views)


def make_runner(sql: str, oracle: Callable) -> Callable:
    """Adapt one query to the scaletest (sess, tables, F) protocol."""
    def run(sess, t, F):
        _views.ensure(sess, t)
        got = sess.sql(sql).collect().to_pandas()
        oracle(got, t)
    return run
