"""TPC-DS breadth for the scale rig (VERDICT r3 missing #3 follow-up).

The reference's milestone ladder ends at full TPC-DS (BASELINE configs
3-4) and its scale suite spans join/agg/window shapes
(``integration_tests/.../scaletest/QuerySpecs.scala``).  Round 3 carried
5 TPC-DS shapes; this module adds 11 more in their REAL spec SQL form —
comma FROM star joins, derived tables, window-over-aggregate via
subquery, multi-alias dimension reuse, cross-joined scalar-subquery
blocks (q88), HAVING-range ticket analyses (q34/q73) — each checked
against an independent pandas oracle.

``build_tables`` is a superset of round 3's ``build_tpcds_tables``: the
original columns keep their names so the existing q3/q7/q19/q42/q89
runners work unchanged; new dimensions (store, household_demographics,
time_dim, customer, customer_address) and fact columns extend the star.
Filter constants are the spec's where possible, tuned only so scaled-down
data keeps results non-empty (plan-shape coverage is the point).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa

# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

_BUY_POTENTIAL = ["0-500", "501-1000", "1001-5000", "5001-10000", ">10000"]
_CITIES = ["Fairview", "Midway", "Oakdale", "Springdale", "Riverside",
           "Centerville", "Glendale", "Marion"]
_COUNTIES = ["C1", "C2", "C3", "C4"]
_STORE_NAMES = ["ese", "ought", "able", "pri", "bar"]
_FIRST = ["Ann", "Bob", "Cara", "Dev", "Eli", "Fay", "Gus", "Hana"]
_LAST = ["Ames", "Brown", "Cole", "Diaz", "Egan", "Ford", "Gray", "Hale"]


def build_tables(rows: int, seed: int = 31) -> Dict[str, pa.Table]:
    rng = np.random.default_rng(seed)
    n_items = max(rows // 50, 20)
    n_dates = 365 * 5
    n_cd = 200
    n_promo = 50
    n_stores = 12
    n_hd = 144
    n_times = 24 * 12            # 5-minute buckets
    n_cust = max(rows // 20, 50)
    n_addr = max(n_cust // 2, 25)

    day = np.arange(n_dates)
    date_dim = pa.table({
        "d_date_sk": pa.array(day, type=pa.int64()),
        "d_year": pa.array(1998 + (day // 365), type=pa.int32()),
        "d_moy": pa.array(1 + (day % 365) // 31 % 12, type=pa.int32()),
        "d_dom": pa.array(1 + day % 28, type=pa.int32()),
        "d_dow": pa.array(day % 7, type=pa.int32()),
        "d_qoy": pa.array(1 + ((day % 365) // 92) % 4, type=pa.int32()),
    })
    item = pa.table({
        "i_item_sk": pa.array(np.arange(n_items), type=pa.int64()),
        "i_manufact_id": pa.array(rng.integers(0, 100, n_items),
                                  type=pa.int32()),
        "i_brand_id": pa.array(rng.integers(0, 40, n_items),
                               type=pa.int32()),
        "i_category_id": pa.array(rng.integers(0, 10, n_items),
                                  type=pa.int32()),
        "i_manager_id": pa.array(rng.integers(0, 100, n_items),
                                 type=pa.int32()),
        "i_brand": pa.array([f"brand#{b}" for b in
                             rng.integers(0, 40, n_items)]),
        "i_item_id": pa.array([f"ITEM{k:08d}" for k in range(n_items)]),
        "i_class_id": pa.array(rng.integers(0, 16, n_items),
                               type=pa.int32()),
        "i_current_price": pa.array(np.round(rng.random(n_items) * 99, 2)),
    })
    customer_demographics = pa.table({
        "cd_demo_sk": pa.array(np.arange(n_cd), type=pa.int64()),
        "cd_gender": pa.array(rng.choice(["M", "F"], n_cd)),
        "cd_marital_status": pa.array(rng.choice(["S", "M", "D", "W"],
                                                 n_cd)),
        "cd_education_status": pa.array(rng.choice(
            ["College", "Primary", "Secondary", "Advanced Degree"], n_cd)),
    })
    promotion = pa.table({
        "p_promo_sk": pa.array(np.arange(n_promo), type=pa.int64()),
        "p_channel_email": pa.array(rng.choice(["Y", "N"], n_promo)),
        "p_channel_event": pa.array(rng.choice(["Y", "N"], n_promo)),
    })
    store = pa.table({
        "s_store_sk": pa.array(np.arange(n_stores), type=pa.int64()),
        "s_store_name": pa.array(rng.choice(_STORE_NAMES, n_stores)),
        "s_city": pa.array(rng.choice(_CITIES, n_stores)),
        "s_county": pa.array(rng.choice(_COUNTIES, n_stores)),
        "s_number_employees": pa.array(rng.integers(150, 350, n_stores),
                                       type=pa.int32()),
    })
    household_demographics = pa.table({
        "hd_demo_sk": pa.array(np.arange(n_hd), type=pa.int64()),
        "hd_dep_count": pa.array(rng.integers(0, 10, n_hd),
                                 type=pa.int32()),
        "hd_vehicle_count": pa.array(rng.integers(0, 5, n_hd),
                                     type=pa.int32()),
        "hd_buy_potential": pa.array(rng.choice(_BUY_POTENTIAL, n_hd)),
    })
    tmark = np.arange(n_times)
    time_dim = pa.table({
        "t_time_sk": pa.array(tmark, type=pa.int64()),
        "t_hour": pa.array(tmark // 12, type=pa.int32()),
        "t_minute": pa.array((tmark % 12) * 5, type=pa.int32()),
    })
    customer = pa.table({
        "c_customer_sk": pa.array(np.arange(n_cust), type=pa.int64()),
        "c_first_name": pa.array(rng.choice(_FIRST, n_cust)),
        "c_last_name": pa.array(rng.choice(_LAST, n_cust)),
        "c_current_addr_sk": pa.array(rng.integers(0, n_addr, n_cust),
                                      type=pa.int64()),
        "c_current_cdemo_sk": pa.array(rng.integers(0, n_cd, n_cust),
                                       type=pa.int64()),
    })
    customer_address = pa.table({
        "ca_address_sk": pa.array(np.arange(n_addr), type=pa.int64()),
        "ca_city": pa.array(rng.choice(_CITIES, n_addr)),
        "ca_county": pa.array(rng.choice(_COUNTIES, n_addr)),
    })

    # ticket-coherent fact generation: a ticket (basket) shares ONE
    # date/time/store/hdemo/customer/addr across its line items — the
    # property q34/q68/q73/q79's per-ticket count/sum semantics rely on
    # (per-row-independent dims would scatter each ticket across filters
    # and leave count-range predicates empty)
    n_tickets = max(rows // 8, 10)
    tk_date = rng.integers(0, n_dates, n_tickets)
    tk_time = rng.integers(0, n_times, n_tickets)
    tk_store = rng.integers(0, n_stores, n_tickets)
    tk_hd = rng.integers(0, n_hd, n_tickets)
    tk_cust = rng.integers(0, n_cust, n_tickets)
    tk_addr = rng.integers(0, n_addr, n_tickets)
    ticket = rng.integers(0, n_tickets, rows)
    store_sales = pa.table({
        "ss_sold_date_sk": pa.array(tk_date[ticket], type=pa.int64()),
        "ss_item_sk": pa.array(rng.integers(0, n_items, rows),
                               type=pa.int64()),
        "ss_ext_sales_price": pa.array(
            np.round(rng.random(rows) * 1000, 2)),
        "ss_cdemo_sk": pa.array(rng.integers(0, n_cd, rows),
                                type=pa.int64()),
        "ss_promo_sk": pa.array(rng.integers(0, n_promo, rows),
                                type=pa.int64()),
        "ss_quantity": pa.array(rng.integers(1, 100, rows),
                                type=pa.int32()),
        "ss_list_price": pa.array(np.round(rng.random(rows) * 200, 2)),
        "ss_coupon_amt": pa.array(np.round(rng.random(rows) * 50, 2)),
        "ss_store_sk": pa.array(tk_store[ticket], type=pa.int64()),
        "ss_hdemo_sk": pa.array(tk_hd[ticket], type=pa.int64()),
        "ss_sold_time_sk": pa.array(tk_time[ticket], type=pa.int64()),
        "ss_ticket_number": pa.array(ticket, type=pa.int64()),
        "ss_customer_sk": pa.array(tk_cust[ticket], type=pa.int64()),
        "ss_addr_sk": pa.array(tk_addr[ticket], type=pa.int64()),
        "ss_net_profit": pa.array(np.round(rng.random(rows) * 100 - 20, 2)),
        "ss_sales_price": pa.array(np.round(rng.random(rows) * 150, 2)),
        "ss_ext_list_price": pa.array(np.round(rng.random(rows) * 250, 2)),
        "ss_ext_tax": pa.array(np.round(rng.random(rows) * 30, 2)),
    })
    # cross-channel facts (round 5): catalog_sales/web_sales share the
    # customer and item keyspaces with store_sales so the INTERSECT/
    # EXCEPT/FULL-OUTER channel queries (q38/q87/q97/q11/q60...) produce
    # non-degenerate overlaps; store_returns derives from store_sales rows
    # so ticket+item joins (q93) and per-store return totals (q1) hit.
    n_cs = max(rows // 2, 20)
    catalog_sales = pa.table({
        "cs_sold_date_sk": pa.array(rng.integers(0, n_dates, n_cs),
                                    type=pa.int64()),
        "cs_bill_customer_sk": pa.array(rng.integers(0, n_cust, n_cs),
                                        type=pa.int64()),
        "cs_item_sk": pa.array(rng.integers(0, n_items, n_cs),
                               type=pa.int64()),
        "cs_quantity": pa.array(rng.integers(1, 100, n_cs),
                                type=pa.int32()),
        "cs_list_price": pa.array(np.round(rng.random(n_cs) * 200, 2)),
        "cs_ext_sales_price": pa.array(np.round(rng.random(n_cs) * 1000,
                                                2)),
    })
    n_ws = max(rows // 3, 20)
    web_sales = pa.table({
        "ws_sold_date_sk": pa.array(rng.integers(0, n_dates, n_ws),
                                    type=pa.int64()),
        "ws_bill_customer_sk": pa.array(rng.integers(0, n_cust, n_ws),
                                        type=pa.int64()),
        "ws_item_sk": pa.array(rng.integers(0, n_items, n_ws),
                               type=pa.int64()),
        "ws_quantity": pa.array(rng.integers(1, 100, n_ws),
                                type=pa.int32()),
        "ws_list_price": pa.array(np.round(rng.random(n_ws) * 200, 2)),
        "ws_ext_sales_price": pa.array(np.round(rng.random(n_ws) * 1000,
                                                2)),
    })
    n_sr = max(rows // 5, 10)
    ret_idx = rng.choice(rows, size=n_sr, replace=False)
    store_returns = pa.table({
        "sr_returned_date_sk": pa.array(rng.integers(0, n_dates, n_sr),
                                        type=pa.int64()),
        "sr_customer_sk": pa.array(
            np.asarray(store_sales.column("ss_customer_sk"))[ret_idx],
            type=pa.int64()),
        "sr_store_sk": pa.array(
            np.asarray(store_sales.column("ss_store_sk"))[ret_idx],
            type=pa.int64()),
        "sr_item_sk": pa.array(
            np.asarray(store_sales.column("ss_item_sk"))[ret_idx],
            type=pa.int64()),
        "sr_ticket_number": pa.array(
            np.asarray(store_sales.column("ss_ticket_number"))[ret_idx],
            type=pa.int64()),
        "sr_return_amt": pa.array(np.round(rng.random(n_sr) * 300, 2)),
    })
    return {
        "store_sales": store_sales, "date_dim": date_dim, "item": item,
        "customer_demographics": customer_demographics,
        "promotion": promotion, "store": store,
        "household_demographics": household_demographics,
        "time_dim": time_dim, "customer": customer,
        "customer_address": customer_address,
        "catalog_sales": catalog_sales, "web_sales": web_sales,
        "store_returns": store_returns,
    }


# ---------------------------------------------------------------------------
# oracle helpers
# ---------------------------------------------------------------------------

def _sorted_frames(got: pd.DataFrame, exp: pd.DataFrame):
    """Sort both frames by the non-float columns first (every query here
    projects a unique non-float key set, so these fully determine row
    order), with rounded floats as inert tiebreakers."""
    def prep(df):
        df = df.copy()
        df.columns = list(range(len(df.columns)))
        keys = {}
        for c in df.columns:
            if df[c].dtype.kind not in "fc":
                keys[f"a{c}"] = df[c]
        for c in df.columns:
            if df[c].dtype.kind in "fc":
                keys[f"z{c}"] = df[c].astype(float).round(3)
        key_df = pd.DataFrame(keys)
        order = key_df.sort_values(list(key_df.columns),
                                   na_position="first").index
        return df.loc[order].reset_index(drop=True)
    return prep(got), prep(exp)


def _assert_rows(got: pd.DataFrame, exp: pd.DataFrame):
    """Order-insensitive frame equality with float tolerance (ORDER BY
    columns in these queries are not total orders, so row order between
    engines is not comparable — the multiset is)."""
    assert len(got) == len(exp), f"{len(got)} rows != {len(exp)}"
    assert len(got.columns) == len(exp.columns)
    assert len(exp) > 0, "oracle produced empty result — tune constants"
    g, e = _sorted_frames(got, exp)
    for c in g.columns:
        if g[c].dtype.kind == "f" or e[c].dtype.kind == "f":
            assert np.allclose(g[c].astype(float).fillna(np.nan),
                               e[c].astype(float).fillna(np.nan),
                               rtol=1e-6, atol=1e-6, equal_nan=True), c
        else:
            ga = np.asarray(g[c].astype(object).values)
            ea = np.asarray(e[c].astype(object).values)
            gm, em = pd.isna(ga), pd.isna(ea)
            # isna-masked equality: fillna('\0') is dtype-dependent under
            # pandas-3 str columns (object-cast NaN fills to '')
            assert (gm == em).all(), c
            assert (ga[~gm] == ea[~em]).all(), c


#: to_pandas results per table-set, STRONG-ref keyed by identity (the
#: strong ref makes id() recycling impossible; the rig passes one table
#: dict per suite, so at most one entry is live)
_pd_cache = [None, None]         # [tables_dict, {name: DataFrame}]


def _pd(t: Dict[str, pa.Table], name: str) -> pd.DataFrame:
    if _pd_cache[0] is not t:
        _pd_cache[0] = t
        _pd_cache[1] = {}
    cache = _pd_cache[1]
    if name not in cache:
        cache[name] = t[name].to_pandas()
    return cache[name].copy()


def _merged(t: Dict[str, pa.Table], with_: List[str]) -> pd.DataFrame:
    """store_sales joined to the requested dims, pandas-side (cached
    conversions: oracle pandas work lands in warm_seconds otherwise)."""
    keys = {
        "date_dim": ("ss_sold_date_sk", "d_date_sk"),
        "item": ("ss_item_sk", "i_item_sk"),
        "store": ("ss_store_sk", "s_store_sk"),
        "household_demographics": ("ss_hdemo_sk", "hd_demo_sk"),
        "time_dim": ("ss_sold_time_sk", "t_time_sk"),
        "customer": ("ss_customer_sk", "c_customer_sk"),
        "customer_demographics": ("ss_cdemo_sk", "cd_demo_sk"),
        "customer_address": ("ss_addr_sk", "ca_address_sk"),
    }
    pdf = _pd(t, "store_sales")
    for name in with_:
        l, r = keys[name]
        pdf = pdf.merge(_pd(t, name), left_on=l, right_on=r)
    return pdf


# ---------------------------------------------------------------------------
# queries: (name, sql, oracle(got_pdf, tables))
# ---------------------------------------------------------------------------

def _oracle_q34(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics"])
    pdf = pdf[((pdf.d_dom.between(1, 3)) | (pdf.d_dom.between(25, 28)))
              & (pdf.hd_buy_potential == "1001-5000")
              & (pdf.hd_vehicle_count > 0)
              & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_county == "C1")]
    dn = (pdf.groupby(["ss_ticket_number", "ss_customer_sk"])
          .size().reset_index(name="cnt"))
    dn = dn[dn.cnt.between(2, 20)]
    cust = _pd(t, "customer")
    exp = dn.merge(cust, left_on="ss_customer_sk",
                   right_on="c_customer_sk")[
        ["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    _assert_rows(got, exp)


_Q34 = """
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) AS cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (d_dom BETWEEN 1 AND 3 OR d_dom BETWEEN 25 AND 28)
        AND hd_buy_potential = '1001-5000' AND hd_vehicle_count > 0
        AND d_year IN (1998, 1999, 2000) AND s_county = 'C1'
      GROUP BY ss_ticket_number, ss_customer_sk) dn, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 2 AND 20
ORDER BY c_last_name, c_first_name, ss_ticket_number DESC
"""


def _oracle_q52(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[(pdf.i_manager_id <= 10) & (pdf.d_moy == 11)
              & (pdf.d_year == 2000)]
    exp = (pdf.groupby(["d_year", "i_brand_id"])
           .agg(ext_price=("ss_ext_sales_price", "sum")).reset_index())
    _assert_rows(got, exp)


_Q52 = """
SELECT d_year, i_brand_id, sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id <= 10 AND d_moy = 11 AND d_year = 2000
GROUP BY d_year, i_brand_id
ORDER BY d_year, ext_price DESC
"""


def _oracle_q53(got, t):
    pdf = _merged(t, ["item", "date_dim", "store"])
    pdf = pdf[pdf.d_qoy.isin([1, 2]) & (pdf.i_class_id < 8)]
    grouped = (pdf.groupby(["i_manufact_id", "d_qoy"])
               .agg(sum_sales=("ss_sales_price", "sum")).reset_index())
    grouped["avg_quarterly_sales"] = grouped.groupby(
        "i_manufact_id")["sum_sales"].transform("mean")
    exp = grouped[["i_manufact_id", "d_qoy", "sum_sales",
                   "avg_quarterly_sales"]]
    _assert_rows(got, exp)


_Q53 = """
SELECT i_manufact_id, d_qoy, sum_sales,
       avg(sum_sales) OVER (PARTITION BY i_manufact_id)
         AS avg_quarterly_sales
FROM (SELECT i_manufact_id, d_qoy, sum(ss_sales_price) AS sum_sales
      FROM item, store_sales, date_dim, store
      WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND ss_store_sk = s_store_sk AND d_qoy IN (1, 2)
        AND i_class_id < 8
      GROUP BY i_manufact_id, d_qoy) tmp1
ORDER BY avg_quarterly_sales, sum_sales, i_manufact_id
"""


def _oracle_q55(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[(pdf.i_manager_id.between(20, 40)) & (pdf.d_moy == 11)
              & (pdf.d_year == 1999)]
    exp = (pdf.groupby(["i_brand", "i_brand_id"])
           .agg(ext_price=("ss_ext_sales_price", "sum")).reset_index())
    exp = exp[["i_brand_id", "i_brand", "ext_price"]]
    _assert_rows(got, exp)


_Q55 = """
SELECT i_brand_id, i_brand, sum(ss_ext_sales_price) AS ext_price
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id BETWEEN 20 AND 40 AND d_moy = 11 AND d_year = 1999
GROUP BY i_brand, i_brand_id
ORDER BY ext_price DESC, i_brand_id
"""


def _oracle_q68(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics",
                      "customer_address"])
    pdf = pdf[(pdf.d_dom.between(1, 2))
              & ((pdf.hd_dep_count == 4) | (pdf.hd_vehicle_count == 3))
              & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_city.isin(["Fairview", "Midway"]))]
    dn = (pdf.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                       "ca_city"])
          .agg(extended_price=("ss_ext_sales_price", "sum"),
               list_price=("ss_ext_list_price", "sum"),
               extended_tax=("ss_ext_tax", "sum")).reset_index()
          .rename(columns={"ca_city": "bought_city"}))
    cust = _pd(t, "customer")
    addr = _pd(t, "customer_address")
    exp = (dn.merge(cust, left_on="ss_customer_sk",
                    right_on="c_customer_sk")
           .merge(addr, left_on="c_current_addr_sk",
                  right_on="ca_address_sk"))
    exp = exp[exp.ca_city != exp.bought_city][
        ["c_last_name", "c_first_name", "ca_city", "bought_city",
         "ss_ticket_number", "ss_addr_sk", "extended_price",
         "extended_tax", "list_price"]]
    _assert_rows(got, exp)


_Q68 = """
SELECT c_last_name, c_first_name, current_addr.ca_city, bought_city,
       ss_ticket_number, ss_addr_sk, extended_price, extended_tax,
       list_price
FROM (SELECT ss_ticket_number, ss_customer_sk, ss_addr_sk,
             ca_city AS bought_city,
             sum(ss_ext_sales_price) AS extended_price,
             sum(ss_ext_list_price) AS list_price,
             sum(ss_ext_tax) AS extended_tax
      FROM store_sales, date_dim, store, household_demographics,
           customer_address
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk AND ss_addr_sk = ca_address_sk
        AND d_dom BETWEEN 1 AND 2
        AND (hd_dep_count = 4 OR hd_vehicle_count = 3)
        AND d_year IN (1998, 1999, 2000)
        AND s_city IN ('Fairview', 'Midway')
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, ca_city) dn,
     customer, customer_address current_addr
WHERE ss_customer_sk = c_customer_sk
  AND customer.c_current_addr_sk = current_addr.ca_address_sk
  AND current_addr.ca_city <> bought_city
ORDER BY c_last_name, ss_ticket_number
"""


def _oracle_q73(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics"])
    pdf = pdf[(pdf.d_dom.between(1, 2))
              & (pdf.hd_buy_potential.isin(["501-1000", ">10000"]))
              & (pdf.hd_vehicle_count > 0)
              & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_county.isin(["C1", "C2"]))]
    dn = (pdf.groupby(["ss_ticket_number", "ss_customer_sk"])
          .size().reset_index(name="cnt"))
    dn = dn[dn.cnt.between(1, 5)]
    cust = _pd(t, "customer")
    exp = dn.merge(cust, left_on="ss_customer_sk",
                   right_on="c_customer_sk")[
        ["c_last_name", "c_first_name", "ss_ticket_number", "cnt"]]
    _assert_rows(got, exp)


_Q73 = """
SELECT c_last_name, c_first_name, ss_ticket_number, cnt
FROM (SELECT ss_ticket_number, ss_customer_sk, count(*) AS cnt
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk AND d_dom BETWEEN 1 AND 2
        AND hd_buy_potential IN ('501-1000', '>10000')
        AND hd_vehicle_count > 0 AND d_year IN (1998, 1999, 2000)
        AND s_county IN ('C1', 'C2')
      GROUP BY ss_ticket_number, ss_customer_sk) dj, customer
WHERE ss_customer_sk = c_customer_sk AND cnt BETWEEN 1 AND 5
ORDER BY cnt DESC, c_last_name
"""


def _oracle_q79(got, t):
    pdf = _merged(t, ["date_dim", "store", "household_demographics"])
    pdf = pdf[((pdf.hd_dep_count == 6) | (pdf.hd_vehicle_count > 2))
              & (pdf.d_dow == 1) & (pdf.d_year.isin([1998, 1999, 2000]))
              & (pdf.s_number_employees.between(200, 295))]
    ms = (pdf.groupby(["ss_ticket_number", "ss_customer_sk", "ss_addr_sk",
                       "s_city"])
          .agg(amt=("ss_coupon_amt", "sum"),
               profit=("ss_net_profit", "sum")).reset_index())
    cust = _pd(t, "customer")
    exp = ms.merge(cust, left_on="ss_customer_sk",
                   right_on="c_customer_sk")
    exp["city30"] = exp.s_city.str[:30]
    exp = exp[["c_last_name", "c_first_name", "city30",
               "ss_ticket_number", "ss_addr_sk", "amt", "profit"]]
    _assert_rows(got, exp)


_Q79 = """
SELECT c_last_name, c_first_name, substr(s_city, 1, 30) AS city30,
       ss_ticket_number, ss_addr_sk, amt, profit
FROM (SELECT ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city,
             sum(ss_coupon_amt) AS amt, sum(ss_net_profit) AS profit
      FROM store_sales, date_dim, store, household_demographics
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND ss_hdemo_sk = hd_demo_sk
        AND (hd_dep_count = 6 OR hd_vehicle_count > 2)
        AND d_dow = 1 AND d_year IN (1998, 1999, 2000)
        AND s_number_employees BETWEEN 200 AND 295
      GROUP BY ss_ticket_number, ss_customer_sk, ss_addr_sk, s_city) ms,
     customer
WHERE ss_customer_sk = c_customer_sk
ORDER BY c_last_name, c_first_name, city30, profit
"""


def _count_bucket(t, h0, m0, m1, dep):
    pdf = _merged(t, ["household_demographics", "time_dim", "store"])
    pdf = pdf[(pdf.t_hour == h0) & (pdf.t_minute >= m0)
              & (pdf.t_minute < m1) & (pdf.hd_dep_count == dep)
              & (pdf.s_store_name == "ese")]
    return len(pdf)


def _oracle_q88(got, t):
    exp = pd.DataFrame({
        "h8_30_to_9": [_count_bucket(t, 8, 30, 60, 3)],
        "h9_to_9_30": [_count_bucket(t, 9, 0, 30, 3)],
        "h9_30_to_10": [_count_bucket(t, 9, 30, 60, 3)],
        "h10_to_10_30": [_count_bucket(t, 10, 0, 30, 3)],
    })
    _assert_rows(got, exp)


def _q88_block(alias, hour, m0, m1):
    cmp_m = f"t_minute >= {m0} AND t_minute < {m1}"
    return (f"(SELECT count(*) AS {alias} "
            f"FROM store_sales, household_demographics, time_dim, store "
            f"WHERE ss_sold_time_sk = t_time_sk "
            f"AND ss_hdemo_sk = hd_demo_sk AND ss_store_sk = s_store_sk "
            f"AND t_hour = {hour} AND {cmp_m} "
            f"AND hd_dep_count = 3 AND s_store_name = 'ese')")


_Q88 = f"""
SELECT * FROM
 {_q88_block('h8_30_to_9', 8, 30, 60)} s1,
 {_q88_block('h9_to_9_30', 9, 0, 30)} s2,
 {_q88_block('h9_30_to_10', 9, 30, 60)} s3,
 {_q88_block('h10_to_10_30', 10, 0, 30)} s4
"""


def _oracle_q96(got, t):
    pdf = _merged(t, ["household_demographics", "time_dim", "store"])
    pdf = pdf[(pdf.t_hour == 20) & (pdf.t_minute >= 30)
              & (pdf.hd_dep_count == 7) & (pdf.s_store_name == "ese")]
    _assert_rows(got, pd.DataFrame({"cnt": [len(pdf)]}))


_Q96 = """
SELECT count(*) AS cnt
FROM store_sales, household_demographics, time_dim, store
WHERE ss_sold_time_sk = t_time_sk AND ss_hdemo_sk = hd_demo_sk
  AND ss_store_sk = s_store_sk AND t_hour = 20 AND t_minute >= 30
  AND hd_dep_count = 7 AND s_store_name = 'ese'
"""


def _oracle_q98(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[pdf.i_category_id.isin([1, 2, 3]) & (pdf.d_year == 1999)]
    grouped = (pdf.groupby(["i_item_id", "i_category_id", "i_class_id",
                            "i_current_price"])
               .agg(itemrevenue=("ss_ext_sales_price", "sum"))
               .reset_index())
    grouped["revenueratio"] = (grouped.itemrevenue * 100 /
                               grouped.groupby("i_class_id")["itemrevenue"]
                               .transform("sum"))
    _assert_rows(got, grouped)


_Q98 = """
SELECT i_item_id, i_category_id, i_class_id, i_current_price,
       itemrevenue,
       itemrevenue * 100 / sum(itemrevenue)
         OVER (PARTITION BY i_class_id) AS revenueratio
FROM (SELECT i_item_id, i_category_id, i_class_id, i_current_price,
             sum(ss_ext_sales_price) AS itemrevenue
      FROM store_sales, item, date_dim
      WHERE ss_item_sk = i_item_sk AND ss_sold_date_sk = d_date_sk
        AND i_category_id IN (1, 2, 3) AND d_year = 1999
      GROUP BY i_item_id, i_category_id, i_class_id,
               i_current_price) grouped
ORDER BY i_category_id, i_class_id, i_item_id, revenueratio
"""


def _oracle_q42(got, t):
    pdf = _merged(t, ["date_dim", "item"])
    pdf = pdf[(pdf.i_manager_id <= 15) & (pdf.d_moy == 12)
              & (pdf.d_year == 2000)]
    exp = (pdf.groupby(["d_year", "i_category_id"])
           .agg(s=("ss_ext_sales_price", "sum")).reset_index())
    _assert_rows(got, exp)


_Q42_SQL = """
SELECT d_year, i_category_id, sum(ss_ext_sales_price) AS s
FROM date_dim, store_sales, item
WHERE d_date_sk = ss_sold_date_sk AND ss_item_sk = i_item_sk
  AND i_manager_id <= 15 AND d_moy = 12 AND d_year = 2000
GROUP BY d_year, i_category_id
ORDER BY s DESC, d_year, i_category_id
"""


def _oracle_q59ish(got, t):
    """Weekly revenue by (store, dow) with a self-comparison ratio across
    two year halves — the q59 shape reduced to one join level."""
    pdf = _merged(t, ["date_dim", "store"])
    h1 = pdf[pdf.d_year == 1998]
    h2 = pdf[pdf.d_year == 1999]
    a = (h1.groupby(["s_store_name", "d_dow"])
         .agg(rev1=("ss_ext_sales_price", "sum")).reset_index())
    b = (h2.groupby(["s_store_name", "d_dow"])
         .agg(rev2=("ss_ext_sales_price", "sum")).reset_index())
    exp = a.merge(b, on=["s_store_name", "d_dow"])
    exp["ratio"] = exp.rev2 / exp.rev1
    _assert_rows(got, exp)


_Q59ISH = """
SELECT y1.s_store_name, y1.d_dow, y1.rev1, y2.rev2,
       y2.rev2 / y1.rev1 AS ratio
FROM (SELECT s_store_name, d_dow, sum(ss_ext_sales_price) AS rev1
      FROM store_sales, date_dim, store
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND d_year = 1998
      GROUP BY s_store_name, d_dow) y1,
     (SELECT s_store_name, d_dow, sum(ss_ext_sales_price) AS rev2
      FROM store_sales, date_dim, store
      WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
        AND d_year = 1999
      GROUP BY s_store_name, d_dow) y2
WHERE y1.s_store_name = y2.s_store_name AND y1.d_dow = y2.d_dow
ORDER BY y1.s_store_name, y1.d_dow
"""


# ---------------------------------------------------------------------------
# round-5 additions: multi-CTE / set-operation / subquery planner stress
# (VERDICT r4 #5 — the TPC-DS stragglers that exercise INTERSECT/EXCEPT,
# FULL OUTER JOIN, CTE self-joins, correlated subqueries, EXISTS chains
# and ROLLUP rather than re-covering star joins)
# ---------------------------------------------------------------------------

def _channel_customers(t, fact, cust_col, date_col, year):
    """Distinct (last, first, customer_sk) triples active in a channel.
    customer_sk keeps the domain customer-sized: the 8x8 name-pair pool
    saturates at rig scale, which would let a no-op INTERSECT or an
    always-empty EXCEPT pass undetected."""
    f = _pd(t, fact)
    f = f[f[date_col].map(
        _pd(t, "date_dim").set_index("d_date_sk")["d_year"]) == year]
    cust = _pd(t, "customer")
    m = f.merge(cust, left_on=cust_col, right_on="c_customer_sk")
    return set(zip(m.c_last_name, m.c_first_name, m.c_customer_sk))


def _oracle_q38(got, t):
    s = _channel_customers(t, "store_sales", "ss_customer_sk",
                           "ss_sold_date_sk", 1999)
    c = _channel_customers(t, "catalog_sales", "cs_bill_customer_sk",
                           "cs_sold_date_sk", 1999)
    w = _channel_customers(t, "web_sales", "ws_bill_customer_sk",
                           "ws_sold_date_sk", 1999)
    exp = pd.DataFrame({"num": [len(s & c & w)]})
    _assert_rows(got, exp)


_Q38 = """
SELECT count(*) AS num FROM (
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM store_sales, date_dim, customer
  WHERE ss_sold_date_sk = d_date_sk AND ss_customer_sk = c_customer_sk
    AND d_year = 1999
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM catalog_sales, date_dim, customer
  WHERE cs_sold_date_sk = d_date_sk AND cs_bill_customer_sk = c_customer_sk
    AND d_year = 1999
  INTERSECT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM web_sales, date_dim, customer
  WHERE ws_sold_date_sk = d_date_sk AND ws_bill_customer_sk = c_customer_sk
    AND d_year = 1999
) hot_cust
"""


def _oracle_q87(got, t):
    s = _channel_customers(t, "store_sales", "ss_customer_sk",
                           "ss_sold_date_sk", 1999)
    c = _channel_customers(t, "catalog_sales", "cs_bill_customer_sk",
                           "cs_sold_date_sk", 1999)
    w = _channel_customers(t, "web_sales", "ws_bill_customer_sk",
                           "ws_sold_date_sk", 1999)
    exp = pd.DataFrame({"num": [len(s - c - w)]})
    _assert_rows(got, exp)


_Q87 = """
SELECT count(*) AS num FROM (
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM store_sales, date_dim, customer
  WHERE ss_sold_date_sk = d_date_sk AND ss_customer_sk = c_customer_sk
    AND d_year = 1999
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM catalog_sales, date_dim, customer
  WHERE cs_sold_date_sk = d_date_sk AND cs_bill_customer_sk = c_customer_sk
    AND d_year = 1999
  EXCEPT
  SELECT DISTINCT c_last_name, c_first_name, c_customer_sk
  FROM web_sales, date_dim, customer
  WHERE ws_sold_date_sk = d_date_sk AND ws_bill_customer_sk = c_customer_sk
    AND d_year = 1999
) cool_cust
"""


def _channel_pairs(t, fact, cust_col, item_col, date_col, year):
    f = _pd(t, fact)
    f = f[f[date_col].map(
        _pd(t, "date_dim").set_index("d_date_sk")["d_year"]) == year]
    return f[[cust_col, item_col]].drop_duplicates()


def _oracle_q97(got, t):
    s = _channel_pairs(t, "store_sales", "ss_customer_sk", "ss_item_sk",
                       "ss_sold_date_sk", 1999)
    c = _channel_pairs(t, "catalog_sales", "cs_bill_customer_sk",
                       "cs_item_sk", "cs_sold_date_sk", 1999)
    m = s.merge(c, left_on=["ss_customer_sk", "ss_item_sk"],
                right_on=["cs_bill_customer_sk", "cs_item_sk"],
                how="outer", indicator=True)
    exp = pd.DataFrame({
        "store_only": [int((m._merge == "left_only").sum())],
        "catalog_only": [int((m._merge == "right_only").sum())],
        "store_and_catalog": [int((m._merge == "both").sum())],
    })
    _assert_rows(got, exp)


_Q97 = """
WITH ssci AS (
  SELECT ss_customer_sk AS customer_sk, ss_item_sk AS item_sk
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_year = 1999
  GROUP BY ss_customer_sk, ss_item_sk),
csci AS (
  SELECT cs_bill_customer_sk AS customer_sk, cs_item_sk AS item_sk
  FROM catalog_sales, date_dim
  WHERE cs_sold_date_sk = d_date_sk AND d_year = 1999
  GROUP BY cs_bill_customer_sk, cs_item_sk)
SELECT sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NULL THEN 1 ELSE 0 END)
         AS store_only,
       sum(CASE WHEN ssci.customer_sk IS NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
         AS catalog_only,
       sum(CASE WHEN ssci.customer_sk IS NOT NULL
                 AND csci.customer_sk IS NOT NULL THEN 1 ELSE 0 END)
         AS store_and_catalog
FROM ssci FULL OUTER JOIN csci
  ON (ssci.customer_sk = csci.customer_sk
      AND ssci.item_sk = csci.item_sk)
"""


def _year_totals(t, fact, cust_col, date_col, price_col):
    f = _pd(t, fact)
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    f = f.assign(dyear=f[date_col].map(dd))
    return (f.groupby([cust_col, "dyear"])[price_col].sum()
            .reset_index().rename(columns={cust_col: "customer_sk",
                                           price_col: "year_total"}))


def _oracle_q11(got, t):
    s = _year_totals(t, "store_sales", "ss_customer_sk",
                     "ss_sold_date_sk", "ss_ext_sales_price")
    w = _year_totals(t, "web_sales", "ws_bill_customer_sk",
                     "ws_sold_date_sk", "ws_ext_sales_price")

    def year(df, y):
        return df[df.dyear == y].set_index("customer_sk")["year_total"]
    sf, ss2 = year(s, 1999), year(s, 2000)
    wf, ws2 = year(w, 1999), year(w, 2000)
    idx = sf.index.intersection(ss2.index).intersection(
        wf.index).intersection(ws2.index)
    idx = idx[(sf[idx] > 0) & (wf[idx] > 0)]
    keep = idx[(ws2[idx] / wf[idx]) > (ss2[idx] / sf[idx])]
    exp = pd.DataFrame({"customer_sk": sorted(keep)})
    _assert_rows(got, exp)


_Q11 = """
WITH year_total AS (
  SELECT ss_customer_sk AS customer_sk, d_year AS dyear,
         sum(ss_ext_sales_price) AS year_total, 's' AS sale_type
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk
  GROUP BY ss_customer_sk, d_year
  UNION ALL
  SELECT ws_bill_customer_sk, d_year, sum(ws_ext_sales_price), 'w'
  FROM web_sales, date_dim
  WHERE ws_sold_date_sk = d_date_sk
  GROUP BY ws_bill_customer_sk, d_year)
SELECT t_s_secyear.customer_sk
FROM year_total t_s_firstyear, year_total t_s_secyear,
     year_total t_w_firstyear, year_total t_w_secyear
WHERE t_s_secyear.customer_sk = t_s_firstyear.customer_sk
  AND t_s_firstyear.customer_sk = t_w_secyear.customer_sk
  AND t_s_firstyear.customer_sk = t_w_firstyear.customer_sk
  AND t_s_firstyear.sale_type = 's' AND t_w_firstyear.sale_type = 'w'
  AND t_s_secyear.sale_type = 's' AND t_w_secyear.sale_type = 'w'
  AND t_s_firstyear.dyear = 1999 AND t_s_secyear.dyear = 2000
  AND t_w_firstyear.dyear = 1999 AND t_w_secyear.dyear = 2000
  AND t_s_firstyear.year_total > 0 AND t_w_firstyear.year_total > 0
  AND t_w_secyear.year_total / t_w_firstyear.year_total
      > t_s_secyear.year_total / t_s_firstyear.year_total
ORDER BY t_s_secyear.customer_sk
"""


def _oracle_q31(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")
    addr = _pd(t, "customer_address")
    ss = _merged(t, ["customer_address"])
    ss = ss.assign(d_qoy=ss.ss_sold_date_sk.map(dd.d_qoy),
                   d_year=ss.ss_sold_date_sk.map(dd.d_year))
    ssg = (ss[ss.d_year == 2000].groupby(["ca_county", "d_qoy"])
           ["ss_ext_sales_price"].sum())
    ws = _pd(t, "web_sales").merge(
        _pd(t, "customer"), left_on="ws_bill_customer_sk",
        right_on="c_customer_sk").merge(
        addr, left_on="c_current_addr_sk", right_on="ca_address_sk")
    ws = ws.assign(d_qoy=ws.ws_sold_date_sk.map(dd.d_qoy),
                   d_year=ws.ws_sold_date_sk.map(dd.d_year))
    wsg = (ws[ws.d_year == 2000].groupby(["ca_county", "d_qoy"])
           ["ws_ext_sales_price"].sum())
    rows = []
    for county in addr.ca_county.unique():
        try:
            sg = ssg[(county, 2)] / ssg[(county, 1)]
            wg = wsg[(county, 2)] / wsg[(county, 1)]
        except KeyError:
            continue
        rows.append((county, sg, wg, 1 if wg > sg else 0))
    exp = pd.DataFrame(rows, columns=["ca_county", "store_growth",
                                      "web_growth", "web_faster"])
    _assert_rows(got, exp)


_Q31 = """
WITH ss AS (
  SELECT ca_county, d_qoy, d_year,
         sum(ss_ext_sales_price) AS store_sales_total
  FROM store_sales, date_dim, customer_address
  WHERE ss_sold_date_sk = d_date_sk AND ss_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year),
ws AS (
  SELECT ca_county, d_qoy, d_year,
         sum(ws_ext_sales_price) AS web_sales_total
  FROM web_sales, date_dim, customer, customer_address
  WHERE ws_sold_date_sk = d_date_sk
    AND ws_bill_customer_sk = c_customer_sk
    AND c_current_addr_sk = ca_address_sk
  GROUP BY ca_county, d_qoy, d_year)
SELECT ss1.ca_county,
       ss2.store_sales_total / ss1.store_sales_total AS store_growth,
       ws2.web_sales_total / ws1.web_sales_total AS web_growth,
       CASE WHEN ws2.web_sales_total / ws1.web_sales_total
                 > ss2.store_sales_total / ss1.store_sales_total
            THEN 1 ELSE 0 END AS web_faster
FROM ss ss1, ss ss2, ws ws1, ws ws2
WHERE ss1.ca_county = ss2.ca_county AND ss1.ca_county = ws1.ca_county
  AND ss1.ca_county = ws2.ca_county
  AND ss1.d_qoy = 1 AND ss2.d_qoy = 2 AND ws1.d_qoy = 1 AND ws2.d_qoy = 2
  AND ss1.d_year = 2000 AND ss2.d_year = 2000
  AND ws1.d_year = 2000 AND ws2.d_year = 2000
ORDER BY ss1.ca_county
"""


def _oracle_q60(got, t):
    item = _pd(t, "item")
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]

    def chan(fact, item_col, date_col, price):
        f = _pd(t, fact)
        f = f[f[date_col].map(dd) == 1999]
        m = f.merge(item, left_on=item_col, right_on="i_item_sk")
        m = m[m.i_category_id == 3]
        return m.groupby("i_item_id")[price].sum()
    tot = (chan("store_sales", "ss_item_sk", "ss_sold_date_sk",
                "ss_ext_sales_price")
           .add(chan("catalog_sales", "cs_item_sk", "cs_sold_date_sk",
                     "cs_ext_sales_price"), fill_value=0)
           .add(chan("web_sales", "ws_item_sk", "ws_sold_date_sk",
                     "ws_ext_sales_price"), fill_value=0))
    exp = tot.reset_index()
    exp.columns = ["i_item_id", "total_sales"]
    _assert_rows(got, exp)


_Q60 = """
WITH ss AS (
  SELECT i_item_id, sum(ss_ext_sales_price) AS total_sales
  FROM store_sales, date_dim, item
  WHERE ss_sold_date_sk = d_date_sk AND ss_item_sk = i_item_sk
    AND i_category_id = 3 AND d_year = 1999
  GROUP BY i_item_id),
cs AS (
  SELECT i_item_id, sum(cs_ext_sales_price) AS total_sales
  FROM catalog_sales, date_dim, item
  WHERE cs_sold_date_sk = d_date_sk AND cs_item_sk = i_item_sk
    AND i_category_id = 3 AND d_year = 1999
  GROUP BY i_item_id),
ws AS (
  SELECT i_item_id, sum(ws_ext_sales_price) AS total_sales
  FROM web_sales, date_dim, item
  WHERE ws_sold_date_sk = d_date_sk AND ws_item_sk = i_item_sk
    AND i_category_id = 3 AND d_year = 1999
  GROUP BY i_item_id)
SELECT i_item_id, sum(total_sales) AS total_sales
FROM (SELECT * FROM ss UNION ALL SELECT * FROM cs
      UNION ALL SELECT * FROM ws) tmp1
GROUP BY i_item_id
ORDER BY i_item_id, total_sales
"""


def _oracle_q1(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    sr = _pd(t, "store_returns")
    sr = sr[sr.sr_returned_date_sk.map(dd) == 2000]
    ctr = (sr.groupby(["sr_customer_sk", "sr_store_sk"])["sr_return_amt"]
           .sum().reset_index(name="ctr_total_return"))
    avg = (ctr.groupby("sr_store_sk")["ctr_total_return"].mean() * 1.2)
    ctr = ctr[ctr.ctr_total_return > ctr.sr_store_sk.map(avg)]
    store = _pd(t, "store")
    keep_stores = set(store[store.s_county == "C1"].s_store_sk)
    ctr = ctr[ctr.sr_store_sk.isin(keep_stores)]
    cust = _pd(t, "customer")
    exp = ctr.merge(cust, left_on="sr_customer_sk",
                    right_on="c_customer_sk")[
        ["c_customer_sk", "c_first_name", "c_last_name"]]
    _assert_rows(got, exp)


_Q1 = """
WITH customer_total_return AS (
  SELECT sr_customer_sk AS ctr_customer_sk, sr_store_sk AS ctr_store_sk,
         sum(sr_return_amt) AS ctr_total_return
  FROM store_returns, date_dim
  WHERE sr_returned_date_sk = d_date_sk AND d_year = 2000
  GROUP BY sr_customer_sk, sr_store_sk)
SELECT c_customer_sk, c_first_name, c_last_name
FROM customer_total_return ctr1, store, customer
WHERE ctr1.ctr_total_return >
      (SELECT avg(ctr_total_return) * 1.2
       FROM customer_total_return ctr2
       WHERE ctr1.ctr_store_sk = ctr2.ctr_store_sk)
  AND s_store_sk = ctr1.ctr_store_sk AND s_county = 'C1'
  AND ctr1.ctr_customer_sk = c_customer_sk
ORDER BY c_customer_sk
"""


def _oracle_q93(got, t):
    ss = _pd(t, "store_sales")
    sr = _pd(t, "store_returns")[["sr_ticket_number", "sr_item_sk",
                                  "sr_return_amt"]]
    m = ss.merge(sr, left_on=["ss_ticket_number", "ss_item_sk"],
                 right_on=["sr_ticket_number", "sr_item_sk"], how="left")
    act = np.where(m.sr_ticket_number.notna(),
                   m.ss_sales_price * (m.ss_quantity - 1),
                   m.ss_sales_price * m.ss_quantity)
    exp = (pd.DataFrame({"ss_customer_sk": m.ss_customer_sk,
                         "act_sales": act})
           .groupby("ss_customer_sk")["act_sales"].sum()
           .reset_index(name="sumsales"))
    _assert_rows(got, exp)


_Q93 = """
SELECT ss_customer_sk, sum(act_sales) AS sumsales
FROM (SELECT ss_customer_sk,
             CASE WHEN sr_ticket_number IS NOT NULL
                  THEN ss_sales_price * (ss_quantity - 1)
                  ELSE ss_sales_price * ss_quantity END AS act_sales
      FROM store_sales LEFT JOIN store_returns
        ON sr_ticket_number = ss_ticket_number
       AND sr_item_sk = ss_item_sk) t
GROUP BY ss_customer_sk
ORDER BY sumsales, ss_customer_sk
"""


def _oracle_q69(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]

    def active(fact, cust_col, date_col):
        f = _pd(t, fact)
        return set(f[f[date_col].map(dd) == 2000][cust_col])
    s = active("store_sales", "ss_customer_sk", "ss_sold_date_sk")
    w = active("web_sales", "ws_bill_customer_sk", "ws_sold_date_sk")
    c = active("catalog_sales", "cs_bill_customer_sk", "cs_sold_date_sk")
    cust = _pd(t, "customer")
    addr = _pd(t, "customer_address")
    cd = _pd(t, "customer_demographics")
    m = cust.merge(addr, left_on="c_current_addr_sk",
                   right_on="ca_address_sk")
    m = m[m.ca_county.isin(["C1", "C2"])]
    m = m[m.c_customer_sk.isin(s - w - c)]
    m = m.merge(cd, left_on="c_current_cdemo_sk", right_on="cd_demo_sk")
    exp = (m.groupby(["cd_gender", "cd_marital_status",
                      "cd_education_status"])
           .size().reset_index(name="cnt"))
    _assert_rows(got, exp)


_Q69 = """
SELECT cd_gender, cd_marital_status, cd_education_status,
       count(*) AS cnt
FROM customer c, customer_address ca, customer_demographics
WHERE c.c_current_addr_sk = ca.ca_address_sk
  AND ca_county IN ('C1', 'C2')
  AND cd_demo_sk = c.c_current_cdemo_sk
  AND EXISTS (SELECT * FROM store_sales, date_dim
              WHERE c.c_customer_sk = ss_customer_sk
                AND ss_sold_date_sk = d_date_sk AND d_year = 2000)
  AND NOT EXISTS (SELECT * FROM web_sales, date_dim
                  WHERE c.c_customer_sk = ws_bill_customer_sk
                    AND ws_sold_date_sk = d_date_sk AND d_year = 2000)
  AND NOT EXISTS (SELECT * FROM catalog_sales, date_dim
                  WHERE c.c_customer_sk = cs_bill_customer_sk
                    AND cs_sold_date_sk = d_date_sk AND d_year = 2000)
GROUP BY cd_gender, cd_marital_status, cd_education_status
ORDER BY cd_gender, cd_marital_status, cd_education_status
"""


def _oracle_q65(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")["d_year"]
    ss = _pd(t, "store_sales")
    ss = ss[ss.ss_sold_date_sk.map(dd) == 1999]
    sa = (ss.groupby(["ss_store_sk", "ss_item_sk"])["ss_sales_price"]
          .sum().reset_index(name="revenue"))
    ave = sa.groupby("ss_store_sk")["revenue"].mean()
    sa = sa[sa.revenue <= 0.5 * sa.ss_store_sk.map(ave)]
    store = _pd(t, "store")
    item = _pd(t, "item")
    exp = (sa.merge(store, left_on="ss_store_sk", right_on="s_store_sk")
           .merge(item, left_on="ss_item_sk", right_on="i_item_sk")[
               ["s_store_name", "i_item_id", "revenue"]])
    _assert_rows(got, exp)


_Q65 = """
WITH sa AS (
  SELECT ss_store_sk, ss_item_sk, sum(ss_sales_price) AS revenue
  FROM store_sales, date_dim
  WHERE ss_sold_date_sk = d_date_sk AND d_year = 1999
  GROUP BY ss_store_sk, ss_item_sk),
sc AS (
  SELECT ss_store_sk, avg(revenue) AS ave FROM sa GROUP BY ss_store_sk)
SELECT s_store_name, i_item_id, sa.revenue
FROM store, item, sa, sc
WHERE sa.ss_store_sk = sc.ss_store_sk AND sa.revenue <= 0.5 * sc.ave
  AND s_store_sk = sa.ss_store_sk AND i_item_sk = sa.ss_item_sk
ORDER BY s_store_name, i_item_id
"""


def _oracle_q2ish(got, t):
    dd = _pd(t, "date_dim").set_index("d_date_sk")
    ws = _pd(t, "web_sales")
    cs = _pd(t, "catalog_sales")
    frames = [
        pd.DataFrame({"d_year": ws.ws_sold_date_sk.map(dd.d_year),
                      "d_dow": ws.ws_sold_date_sk.map(dd.d_dow),
                      "sales_price": ws.ws_ext_sales_price}),
        pd.DataFrame({"d_year": cs.cs_sold_date_sk.map(dd.d_year),
                      "d_dow": cs.cs_sold_date_sk.map(dd.d_dow),
                      "sales_price": cs.cs_ext_sales_price}),
    ]
    allc = pd.concat(frames)
    exp = (allc.groupby(["d_year", "d_dow"])["sales_price"].sum()
           .reset_index(name="total"))
    _assert_rows(got, exp)


_Q2ISH = """
WITH wscs AS (
  SELECT d_year, d_dow, ws_ext_sales_price AS sales_price
  FROM web_sales, date_dim WHERE ws_sold_date_sk = d_date_sk
  UNION ALL
  SELECT d_year, d_dow, cs_ext_sales_price
  FROM catalog_sales, date_dim WHERE cs_sold_date_sk = d_date_sk)
SELECT d_year, d_dow, sum(sales_price) AS total
FROM wscs GROUP BY d_year, d_dow ORDER BY d_year, d_dow
"""


def _oracle_q27(got, t):
    pdf = _merged(t, ["customer_demographics", "date_dim", "store",
                      "item"])
    pdf = pdf[(pdf.cd_gender == "M") & (pdf.cd_marital_status == "S")
              & (pdf.cd_education_status == "College")
              & (pdf.d_year == 2000)]

    def level(keys):
        if keys:
            g = pdf.groupby(keys).agg(
                agg1=("ss_quantity", "mean"),
                agg2=("ss_list_price", "mean"),
                agg3=("ss_coupon_amt", "mean"),
                agg4=("ss_sales_price", "mean")).reset_index()
        else:
            g = pd.DataFrame({"agg1": [pdf.ss_quantity.mean()],
                              "agg2": [pdf.ss_list_price.mean()],
                              "agg3": [pdf.ss_coupon_amt.mean()],
                              "agg4": [pdf.ss_sales_price.mean()]})
        for col in ("i_item_id", "s_county"):
            if col not in g.columns:
                # np.nan (not None): pandas-3 str-dtype concat coerces
                # None to '' but keeps nan as missing
                g[col] = np.nan
        return g[["i_item_id", "s_county", "agg1", "agg2", "agg3",
                  "agg4"]]
    exp = pd.concat([level(["i_item_id", "s_county"]),
                     level(["i_item_id"]), level([])], ignore_index=True)
    _assert_rows(got, exp)


_Q27 = """
SELECT i_item_id, s_county, avg(ss_quantity) AS agg1,
       avg(ss_list_price) AS agg2, avg(ss_coupon_amt) AS agg3,
       avg(ss_sales_price) AS agg4
FROM store_sales, customer_demographics, date_dim, store, item
WHERE ss_sold_date_sk = d_date_sk AND ss_store_sk = s_store_sk
  AND ss_cdemo_sk = cd_demo_sk AND ss_item_sk = i_item_sk
  AND cd_gender = 'M' AND cd_marital_status = 'S'
  AND cd_education_status = 'College' AND d_year = 2000
GROUP BY ROLLUP(i_item_id, s_county)
ORDER BY i_item_id, s_county
"""


#: (name, sql, oracle) — consumed by scaletest.QUERIES via make_runner
QUERY_SET: List[Tuple[str, str, Callable]] = [
    ("q34_ticket_counts", _Q34, _oracle_q34),
    ("q42_category_rev_sql", _Q42_SQL, _oracle_q42),
    ("q52_brand_rev", _Q52, _oracle_q52),
    ("q53_manufact_window", _Q53, _oracle_q53),
    ("q55_brand_rev_mgr", _Q55, _oracle_q55),
    ("q59_weekly_ratio", _Q59ISH, _oracle_q59ish),
    ("q68_city_tickets", _Q68, _oracle_q68),
    ("q73_ticket_counts", _Q73, _oracle_q73),
    ("q79_amt_profit", _Q79, _oracle_q79),
    ("q88_time_buckets", _Q88, _oracle_q88),
    ("q96_time_count", _Q96, _oracle_q96),
    ("q98_revenue_ratio", _Q98, _oracle_q98),
    # round 5: multi-CTE / set-op / subquery planner stress
    ("q1_returns_corr_subq", _Q1, _oracle_q1),
    ("q2_weekly_channels", _Q2ISH, _oracle_q2ish),
    ("q11_yoy_ratio", _Q11, _oracle_q11),
    ("q27_rollup", _Q27, _oracle_q27),
    ("q31_county_growth", _Q31, _oracle_q31),
    ("q38_intersect", _Q38, _oracle_q38),
    ("q60_three_channels", _Q60, _oracle_q60),
    ("q65_low_revenue", _Q65, _oracle_q65),
    ("q69_channel_gap", _Q69, _oracle_q69),
    ("q87_except", _Q87, _oracle_q87),
    ("q93_returns_net", _Q93, _oracle_q93),
    ("q97_full_outer", _Q97, _oracle_q97),
]


def register_views(sess, t: Dict[str, pa.Table]) -> None:
    parts = {"store_sales": 4}
    for name, tbl in t.items():
        sess.create_dataframe(
            tbl, num_partitions=parts.get(name, 2)
        ).createOrReplaceTempView(name)


from .rig_util import ViewCache  # noqa: E402  (needs register_views)

_views = ViewCache(register_views)


def make_runner(sql: str, oracle: Callable) -> Callable:
    """Adapt one query to the scaletest (sess, tables, F) protocol."""
    def run(sess, t, F):
        _views.ensure(sess, t)
        got = sess.sql(sql).collect().to_pandas()
        oracle(got, t)
    return run
