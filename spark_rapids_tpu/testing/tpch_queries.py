"""Full TPC-H schema + the 16 queries completing the 22-query suite.

The reference ships a q1-q38 scale suite (``integration_tests/.../
scaletest/QuerySpecs.scala``) and its milestone ladder ends at full
TPC-DS/TPC-H (BASELINE configs 3-4).  ``scaletest.py`` carried 6 TPC-H
shapes through round 3; this module adds the remaining 16 (q2 q3 q5 q7
q8 q9 q10 q11 q12 q13 q15 q16 q18 q19 q20 q21) in their REAL spec SQL
form — multi-table comma FROM, correlated/scalar/IN subqueries, CTEs,
typed date literals, extract(), mixed-predicate EXISTS — each checked
against an independent pandas oracle.

``build_tables`` generates the full 8-table schema.  Column
distributions for the tables that existed in round 3 (lineitem, orders,
customer, part) are IDENTICAL to the old ``build_tpch_tables`` so the
existing q1/q4/q6/q14/q17/q22 oracles keep passing; new columns and the
supplier/partsupp/nation/region tables extend them.

Query predicates are the spec's, with constants tuned only where the
scaled-down value ranges would return empty results (the point is
covering the plan shapes, and a non-empty result is what actually
exercises them).
"""

from __future__ import annotations

import datetime
from typing import Callable, Dict, List, Tuple

import numpy as np
import pandas as pd
import pyarrow as pa


# ---------------------------------------------------------------------------
# schema
# ---------------------------------------------------------------------------

#: the 25 spec nations with their spec region keys (region 0..4 =
#: AFRICA, AMERICA, ASIA, EUROPE, MIDDLE EAST)
_NATIONS = [
    ("ALGERIA", 0), ("ARGENTINA", 1), ("BRAZIL", 1), ("CANADA", 1),
    ("EGYPT", 4), ("ETHIOPIA", 0), ("FRANCE", 3), ("GERMANY", 3),
    ("INDIA", 2), ("INDONESIA", 2), ("IRAN", 4), ("IRAQ", 4),
    ("JAPAN", 2), ("JORDAN", 4), ("KENYA", 0), ("MOROCCO", 0),
    ("MOZAMBIQUE", 0), ("PERU", 1), ("CHINA", 2), ("ROMANIA", 3),
    ("SAUDI ARABIA", 4), ("VIETNAM", 2), ("RUSSIA", 3),
    ("UNITED KINGDOM", 3), ("UNITED STATES", 1),
]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]

_SEGMENTS = ["AUTOMOBILE", "BUILDING", "FURNITURE", "MACHINERY",
             "HOUSEHOLD"]
_SHIPMODES = ["REG AIR", "AIR", "RAIL", "SHIP", "TRUCK", "MAIL", "FOB"]
_SHIPINSTRUCT = ["DELIVER IN PERSON", "COLLECT COD", "NONE",
                 "TAKE BACK RETURN"]
_CONTAINERS = ["SM CASE", "SM BOX", "SM PACK", "SM PKG",
               "MED BAG", "MED BOX", "MED PKG", "MED PACK",
               "LG CASE", "LG BOX", "LG PACK", "LG PKG",
               "JUMBO BAG", "JUMBO BOX", "WRAP CASE", "WRAP BOX"]
_P_TYPES = ["PROMO BURNISHED COPPER", "PROMO PLATED BRASS",
            "STANDARD POLISHED TIN", "ECONOMY ANODIZED STEEL",
            "MEDIUM BRUSHED NICKEL"]
_COLORS = ["almond", "antique", "aquamarine", "azure", "beige", "bisque",
           "blanched", "blue", "blush", "brown", "burlywood", "burnished",
           "chartreuse", "chiffon", "chocolate", "coral", "cornflower",
           "cream", "cyan", "dark", "deep", "dim", "dodger", "drab",
           "firebrick", "floral", "forest", "frosted", "gainsboro",
           "ghost", "gold", "goldenrod", "green", "grey", "honeydew",
           "hot", "hotpink", "indian", "ivory", "khaki", "lace",
           "lavender", "lawn", "lemon", "light", "lime", "linen"]


def build_tables(rows: int, seed: int = 23) -> Dict[str, pa.Table]:
    """Full 8-table TPC-H schema, scaled by ``rows`` (= lineitem rows).

    The lineitem/orders/customer/part columns that existed in round 3
    keep their value distributions (exact draws differ — the rng stream
    interleaves the new columns); every scale-rig oracle recomputes from
    the generated tables, so nothing depends on exact values."""
    rng = np.random.default_rng(seed)
    base = np.datetime64("1992-01-01")
    n_ord = max(rows // 4, 1)
    n_cust = max(rows // 8, 1)
    n_part = max(rows // 8, 1)
    n_supp = max(rows // 600, 10)

    ship = base + rng.integers(0, 2526, rows).astype("timedelta64[D]")
    l_orderkey = rng.integers(0, n_ord, rows)
    l_partkey = rng.integers(0, n_part, rows)
    lineitem = pa.table({
        "l_quantity": pa.array(rng.integers(1, 51, rows).astype(np.float64)),
        "l_extendedprice": pa.array(np.round(rng.random(rows) * 104949 + 901,
                                             2)),
        "l_discount": pa.array(np.round(rng.integers(0, 11, rows) * 0.01,
                                        2)),
        "l_tax": pa.array(np.round(rng.integers(0, 9, rows) * 0.01, 2)),
        "l_returnflag": pa.array(rng.choice(["A", "N", "R"], rows)),
        "l_linestatus": pa.array(rng.choice(["O", "F"], rows)),
        "l_shipdate": pa.array(ship.astype("datetime64[D]")),
        "l_orderkey": pa.array(l_orderkey),
        "l_partkey": pa.array(l_partkey),
        "l_commitdate": pa.array(
            (ship + rng.integers(-30, 31, rows).astype("timedelta64[D]"))
            .astype("datetime64[D]")),
        "l_receiptdate": pa.array(
            (ship + rng.integers(1, 31, rows).astype("timedelta64[D]"))
            .astype("datetime64[D]")),
        # q5/q7/q8/q9/q15/q20/q21 columns: supplier FK rides the partsupp
        # relation (each part has 4 candidate suppliers) so lineitem
        # (l_partkey, l_suppkey) pairs hit partsupp rows for q9/q20
        "l_suppkey": pa.array((l_partkey
                               + rng.integers(0, 4, rows)
                               * max(n_supp // 4, 1)) % n_supp),
        "l_shipmode": pa.array(rng.choice(_SHIPMODES, rows)),
        "l_shipinstruct": pa.array(rng.choice(_SHIPINSTRUCT, rows)),
    })

    odate = base + rng.integers(0, 2406, n_ord).astype("timedelta64[D]")
    # ~1.5% of order comments carry the q13 exclusion pattern
    ocm = rng.choice(["carefully final deposits", "furiously even asymptot",
                      "quickly regular pinto beans", "ironic packages wake",
                      "express special packages requests",
                      "blithely bold theodolites"],
                     n_ord, p=[0.24, 0.24, 0.24, 0.2, 0.015, 0.065])
    orders = pa.table({
        "o_orderkey": pa.array(np.arange(n_ord)),
        "o_custkey": pa.array(rng.integers(0, 2 * n_cust, n_ord)),
        "o_orderdate": pa.array(odate.astype("datetime64[D]")),
        "o_orderpriority": pa.array(rng.choice(
            ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"],
            n_ord)),
        "o_totalprice": pa.array(np.round(rng.random(n_ord) * 450000 + 850,
                                          2)),
        "o_shippriority": pa.array(np.zeros(n_ord, dtype=np.int32)),
        "o_orderstatus": pa.array(rng.choice(["F", "O", "P"], n_ord,
                                             p=[0.49, 0.49, 0.02])),
        "o_comment": pa.array(ocm),
    })

    cc = rng.integers(10, 35, n_cust)
    customer = pa.table({
        "c_custkey": pa.array(np.arange(n_cust)),
        "c_phone": pa.array([f"{c}-{rng.integers(100, 999)}-"
                             f"{rng.integers(1000, 9999)}"
                             for c in cc]),
        "c_acctbal": pa.array(np.round(rng.random(n_cust) * 10998.99
                                       - 999.99, 2)),
        "c_name": pa.array([f"Customer#{i:09d}" for i in range(n_cust)]),
        "c_address": pa.array([f"addr {i % 997}" for i in range(n_cust)]),
        "c_nationkey": pa.array(rng.integers(0, 25, n_cust)),
        "c_mktsegment": pa.array(rng.choice(_SEGMENTS, n_cust)),
        "c_comment": pa.array(rng.choice(
            ["ironic ideas", "silent accounts", "bold requests"], n_cust)),
    })

    part = pa.table({
        "p_partkey": pa.array(np.arange(n_part)),
        "p_type": pa.array(rng.choice(_P_TYPES, n_part)),
        "p_name": pa.array([" ".join(rng.choice(_COLORS, 2, replace=False))
                            for _ in range(n_part)]),
        "p_brand": pa.array([f"Brand#{m}{n}" for m, n in
                             zip(rng.integers(1, 6, n_part),
                                 rng.integers(1, 6, n_part))]),
        "p_container": pa.array(rng.choice(_CONTAINERS, n_part)),
        "p_size": pa.array(rng.integers(1, 51, n_part)),
        "p_mfgr": pa.array([f"Manufacturer#{m}"
                            for m in rng.integers(1, 6, n_part)]),
        "p_retailprice": pa.array(np.round(900 + rng.random(n_part) * 1200,
                                           2)),
    })

    scm = rng.choice(["blithely regular packages", "furiously final ideas",
                      "slyly ironic Customer deposits Complaints haggle",
                      "carefully even theodolites"],
                     n_supp, p=[0.4, 0.35, 0.05, 0.2])
    supplier = pa.table({
        "s_suppkey": pa.array(np.arange(n_supp)),
        "s_name": pa.array([f"Supplier#{i:09d}" for i in range(n_supp)]),
        "s_address": pa.array([f"saddr {i % 499}" for i in range(n_supp)]),
        "s_nationkey": pa.array(rng.integers(0, 25, n_supp)),
        "s_phone": pa.array([f"{10 + i % 25}-{100 + i % 900}-0000"
                             for i in range(n_supp)]),
        "s_acctbal": pa.array(np.round(rng.random(n_supp) * 10998.99
                                       - 999.99, 2)),
        "s_comment": pa.array(scm),
    })

    ps_partkey = np.repeat(np.arange(n_part), 4)
    ps_suppkey = (ps_partkey + np.tile(np.arange(4), n_part)
                  * max(n_supp // 4, 1)) % n_supp
    partsupp = pa.table({
        "ps_partkey": pa.array(ps_partkey),
        "ps_suppkey": pa.array(ps_suppkey),
        "ps_availqty": pa.array(rng.integers(1, 10000, 4 * n_part)),
        "ps_supplycost": pa.array(np.round(rng.random(4 * n_part) * 999 + 1,
                                           2)),
    })

    nation = pa.table({
        "n_nationkey": pa.array(np.arange(25)),
        "n_name": pa.array([n for n, _ in _NATIONS]),
        "n_regionkey": pa.array(np.array([r for _, r in _NATIONS])),
    })
    region = pa.table({
        "r_regionkey": pa.array(np.arange(5)),
        "r_name": pa.array(_REGIONS),
    })
    return {"lineitem": lineitem, "orders": orders, "part": part,
            "customer": customer, "supplier": supplier,
            "partsupp": partsupp, "nation": nation, "region": region}


def register_views(sess, t: Dict[str, pa.Table], parts: int = 4) -> None:
    for name, tab in t.items():
        sess.create_dataframe(tab, num_partitions=parts) \
            .createOrReplaceTempView(name)


def _pandas(t: Dict[str, pa.Table]) -> Dict[str, pd.DataFrame]:
    return {k: v.to_pandas() for k, v in t.items()}


def _check_ordered(got, exp, float_cols, exact_cols, limit=None):
    """Compare engine output to the oracle frame (already sorted the same
    way).  With a LIMIT, sort-key ties make the exact row set ambiguous,
    so assert the row count and the ordered FLOAT sort columns (allclose)
    plus membership of exact columns in the oracle."""
    if limit is not None:
        exp_n = min(limit, len(exp))
        assert len(got) == exp_n, (len(got), exp_n)
        exp = exp.head(limit)
        for c in float_cols:
            assert np.allclose(np.asarray(got[c], dtype=np.float64),
                               np.asarray(exp[c], dtype=np.float64)), c
        for c in exact_cols:
            # ties may permute rows within equal sort keys
            assert set(got[c]) <= set(np.asarray(exp[c])) \
                or list(got[c]) == list(exp[c]), c
        return
    assert len(got) == len(exp), (len(got), len(exp))
    for c in exact_cols:
        assert list(got[c]) == list(exp[c]), c
    for c in float_cols:
        assert np.allclose(np.asarray(got[c], dtype=np.float64),
                           np.asarray(exp[c], dtype=np.float64)), c


# ---------------------------------------------------------------------------
# the 16 queries
# ---------------------------------------------------------------------------

Q2 = """
SELECT s.s_acctbal, s.s_name, n.n_name, p.p_partkey, p.p_mfgr
FROM part p, supplier s, partsupp ps, nation n, region r
WHERE p.p_partkey = ps.ps_partkey AND s.s_suppkey = ps.ps_suppkey
  AND p.p_size = 15 AND p.p_type LIKE '%BRASS'
  AND s.s_nationkey = n.n_nationkey AND n.n_regionkey = r.r_regionkey
  AND r.r_name = 'EUROPE'
  AND ps.ps_supplycost = (SELECT min(ps2.ps_supplycost)
                          FROM partsupp ps2, supplier s2, nation n2,
                               region r2
                          WHERE ps2.ps_partkey = p.p_partkey
                            AND s2.s_suppkey = ps2.ps_suppkey
                            AND s2.s_nationkey = n2.n_nationkey
                            AND n2.n_regionkey = r2.r_regionkey
                            AND r2.r_name = 'EUROPE')
ORDER BY s.s_acctbal DESC, n.n_name, s.s_name, p.p_partkey
LIMIT 100
"""


def q2_oracle(got, p):
    m = p["part"].merge(p["partsupp"], left_on="p_partkey",
                        right_on="ps_partkey") \
        .merge(p["supplier"], left_on="ps_suppkey", right_on="s_suppkey") \
        .merge(p["nation"], left_on="s_nationkey", right_on="n_nationkey") \
        .merge(p["region"], left_on="n_regionkey", right_on="r_regionkey")
    m = m[(m.r_name == "EUROPE")]
    mins = m.groupby("p_partkey").ps_supplycost.min()
    sel = m[(m.p_size == 15) & m.p_type.str.endswith("BRASS")
            & (m.ps_supplycost == m.p_partkey.map(mins))]
    exp = sel.sort_values(["s_acctbal", "n_name", "s_name", "p_partkey"],
                          ascending=[False, True, True, True])
    _check_ordered(got, exp, ["s_acctbal"], ["p_partkey"], limit=100)


Q3 = """
SELECT l.l_orderkey,
       sum(l.l_extendedprice * (1 - l.l_discount)) AS revenue,
       o.o_orderdate, o.o_shippriority
FROM customer c, orders o, lineitem l
WHERE c.c_mktsegment = 'BUILDING' AND c.c_custkey = o.o_custkey
  AND l.l_orderkey = o.o_orderkey
  AND o.o_orderdate < date '1995-03-15' AND l.l_shipdate > date '1995-03-15'
GROUP BY l.l_orderkey, o.o_orderdate, o.o_shippriority
ORDER BY revenue DESC, o.o_orderdate
LIMIT 10
"""


def q3_oracle(got, p):
    m = p["customer"].merge(p["orders"], left_on="c_custkey",
                            right_on="o_custkey") \
        .merge(p["lineitem"], left_on="o_orderkey", right_on="l_orderkey")
    cut = datetime.date(1995, 3, 15)
    m = m[(m.c_mktsegment == "BUILDING") & (m.o_orderdate < cut)
          & (m.l_shipdate > cut)]
    m = m.assign(rev=m.l_extendedprice * (1 - m.l_discount))
    exp = (m.groupby(["l_orderkey", "o_orderdate", "o_shippriority"])
           .rev.sum().reset_index(name="revenue")
           .sort_values(["revenue", "o_orderdate"],
                        ascending=[False, True]))
    _check_ordered(got, exp, ["revenue"], ["l_orderkey"], limit=10)


Q5 = """
SELECT n.n_name, sum(l.l_extendedprice * (1 - l.l_discount)) AS revenue
FROM customer c, orders o, lineitem l, supplier s, nation n, region r
WHERE c.c_custkey = o.o_custkey AND l.l_orderkey = o.o_orderkey
  AND l.l_suppkey = s.s_suppkey AND c.c_nationkey = s.s_nationkey
  AND s.s_nationkey = n.n_nationkey AND n.n_regionkey = r.r_regionkey
  AND r.r_name = 'ASIA'
  AND o.o_orderdate >= date '1994-01-01'
  AND o.o_orderdate < date '1995-01-01'
GROUP BY n.n_name
ORDER BY revenue DESC
"""


def q5_oracle(got, p):
    m = p["customer"].merge(p["orders"], left_on="c_custkey",
                            right_on="o_custkey") \
        .merge(p["lineitem"], left_on="o_orderkey", right_on="l_orderkey") \
        .merge(p["supplier"], left_on="l_suppkey", right_on="s_suppkey")
    m = m[m.c_nationkey == m.s_nationkey]
    m = m.merge(p["nation"], left_on="s_nationkey", right_on="n_nationkey") \
        .merge(p["region"], left_on="n_regionkey", right_on="r_regionkey")
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    m = m[(m.r_name == "ASIA") & (m.o_orderdate >= lo)
          & (m.o_orderdate < hi)]
    m = m.assign(rev=m.l_extendedprice * (1 - m.l_discount))
    exp = (m.groupby("n_name").rev.sum().reset_index(name="revenue")
           .sort_values("revenue", ascending=False))
    _check_ordered(got, exp, ["revenue"], ["n_name"])


Q7 = """
SELECT supp_nation, cust_nation, l_year, sum(volume) AS revenue
FROM (SELECT n1.n_name AS supp_nation, n2.n_name AS cust_nation,
             year(l.l_shipdate) AS l_year,
             l.l_extendedprice * (1 - l.l_discount) AS volume
      FROM supplier s, lineitem l, orders o, customer c,
           nation n1, nation n2
      WHERE s.s_suppkey = l.l_suppkey AND o.o_orderkey = l.l_orderkey
        AND c.c_custkey = o.o_custkey
        AND s.s_nationkey = n1.n_nationkey
        AND c.c_nationkey = n2.n_nationkey
        AND ((n1.n_name = 'FRANCE' AND n2.n_name = 'GERMANY')
             OR (n1.n_name = 'GERMANY' AND n2.n_name = 'FRANCE'))
        AND l.l_shipdate BETWEEN date '1995-01-01' AND date '1996-12-31'
     ) shipping
GROUP BY supp_nation, cust_nation, l_year
ORDER BY supp_nation, cust_nation, l_year
"""


def q7_oracle(got, p):
    n = p["nation"]
    m = p["supplier"].merge(p["lineitem"], left_on="s_suppkey",
                            right_on="l_suppkey") \
        .merge(p["orders"], left_on="l_orderkey", right_on="o_orderkey") \
        .merge(p["customer"], left_on="o_custkey", right_on="c_custkey") \
        .merge(n.add_suffix("_1"), left_on="s_nationkey",
               right_on="n_nationkey_1") \
        .merge(n.add_suffix("_2"), left_on="c_nationkey",
               right_on="n_nationkey_2")
    lo, hi = datetime.date(1995, 1, 1), datetime.date(1996, 12, 31)
    m = m[(m.l_shipdate >= lo) & (m.l_shipdate <= hi)
          & (((m.n_name_1 == "FRANCE") & (m.n_name_2 == "GERMANY"))
             | ((m.n_name_1 == "GERMANY") & (m.n_name_2 == "FRANCE")))]
    m = m.assign(l_year=m.l_shipdate.map(lambda d: d.year),
                 volume=m.l_extendedprice * (1 - m.l_discount))
    exp = (m.groupby(["n_name_1", "n_name_2", "l_year"])
           .volume.sum().reset_index(name="revenue")
           .rename(columns={"n_name_1": "supp_nation",
                            "n_name_2": "cust_nation"})
           .sort_values(["supp_nation", "cust_nation", "l_year"]))
    _check_ordered(got, exp, ["revenue"],
                   ["supp_nation", "cust_nation", "l_year"])


Q8 = """
SELECT o_year,
       sum(CASE WHEN nation = 'BRAZIL' THEN volume ELSE 0 END)
           / sum(volume) AS mkt_share
FROM (SELECT year(o.o_orderdate) AS o_year,
             l.l_extendedprice * (1 - l.l_discount) AS volume,
             n2.n_name AS nation
      FROM part p, supplier s, lineitem l, orders o, customer c,
           nation n1, nation n2, region r
      WHERE p.p_partkey = l.l_partkey AND s.s_suppkey = l.l_suppkey
        AND l.l_orderkey = o.o_orderkey AND o.o_custkey = c.c_custkey
        AND c.c_nationkey = n1.n_nationkey
        AND n1.n_regionkey = r.r_regionkey AND r.r_name = 'AMERICA'
        AND s.s_nationkey = n2.n_nationkey
        AND o.o_orderdate BETWEEN date '1995-01-01' AND date '1996-12-31'
        AND p.p_type = 'ECONOMY ANODIZED STEEL') all_nations
GROUP BY o_year
ORDER BY o_year
"""


def q8_oracle(got, p):
    n = p["nation"]
    m = p["part"].merge(p["lineitem"], left_on="p_partkey",
                        right_on="l_partkey") \
        .merge(p["supplier"], left_on="l_suppkey", right_on="s_suppkey") \
        .merge(p["orders"], left_on="l_orderkey", right_on="o_orderkey") \
        .merge(p["customer"], left_on="o_custkey", right_on="c_custkey") \
        .merge(n.add_suffix("_1"), left_on="c_nationkey",
               right_on="n_nationkey_1") \
        .merge(p["region"], left_on="n_regionkey_1",
               right_on="r_regionkey") \
        .merge(n.add_suffix("_2"), left_on="s_nationkey",
               right_on="n_nationkey_2")
    lo, hi = datetime.date(1995, 1, 1), datetime.date(1996, 12, 31)
    m = m[(m.r_name == "AMERICA") & (m.o_orderdate >= lo)
          & (m.o_orderdate <= hi)
          & (m.p_type == "ECONOMY ANODIZED STEEL")]
    m = m.assign(o_year=m.o_orderdate.map(lambda d: d.year),
                 volume=m.l_extendedprice * (1 - m.l_discount))
    g = m.groupby("o_year").apply(
        lambda x: x.volume[x.n_name_2 == "BRAZIL"].sum()
        / x.volume.sum(), include_groups=False)
    exp = g.reset_index(name="mkt_share").sort_values("o_year")
    _check_ordered(got, exp, ["mkt_share"], ["o_year"])


Q9 = """
SELECT nation, o_year, sum(amount) AS sum_profit
FROM (SELECT n.n_name AS nation, year(o.o_orderdate) AS o_year,
             l.l_extendedprice * (1 - l.l_discount)
               - ps.ps_supplycost * l.l_quantity AS amount
      FROM part p, supplier s, lineitem l, partsupp ps, orders o, nation n
      WHERE s.s_suppkey = l.l_suppkey
        AND ps.ps_suppkey = l.l_suppkey AND ps.ps_partkey = l.l_partkey
        AND p.p_partkey = l.l_partkey AND o.o_orderkey = l.l_orderkey
        AND s.s_nationkey = n.n_nationkey
        AND p.p_name LIKE '%green%') profit
GROUP BY nation, o_year
ORDER BY nation, o_year DESC
"""


def q9_oracle(got, p):
    m = p["lineitem"].merge(p["part"], left_on="l_partkey",
                            right_on="p_partkey") \
        .merge(p["supplier"], left_on="l_suppkey", right_on="s_suppkey") \
        .merge(p["partsupp"],
               left_on=["l_partkey", "l_suppkey"],
               right_on=["ps_partkey", "ps_suppkey"]) \
        .merge(p["orders"], left_on="l_orderkey", right_on="o_orderkey") \
        .merge(p["nation"], left_on="s_nationkey", right_on="n_nationkey")
    m = m[m.p_name.str.contains("green")]
    m = m.assign(o_year=m.o_orderdate.map(lambda d: d.year),
                 amount=m.l_extendedprice * (1 - m.l_discount)
                 - m.ps_supplycost * m.l_quantity)
    exp = (m.groupby(["n_name", "o_year"]).amount.sum()
           .reset_index(name="sum_profit")
           .rename(columns={"n_name": "nation"})
           .sort_values(["nation", "o_year"], ascending=[True, False]))
    _check_ordered(got, exp, ["sum_profit"], ["nation", "o_year"])


Q10 = """
SELECT c.c_custkey, c.c_name,
       sum(l.l_extendedprice * (1 - l.l_discount)) AS revenue,
       c.c_acctbal, n.n_name
FROM customer c, orders o, lineitem l, nation n
WHERE c.c_custkey = o.o_custkey AND l.l_orderkey = o.o_orderkey
  AND o.o_orderdate >= date '1993-10-01'
  AND o.o_orderdate < date '1994-01-01'
  AND l.l_returnflag = 'R' AND c.c_nationkey = n.n_nationkey
GROUP BY c.c_custkey, c.c_name, c.c_acctbal, n.n_name
ORDER BY revenue DESC
LIMIT 20
"""


def q10_oracle(got, p):
    m = p["customer"].merge(p["orders"], left_on="c_custkey",
                            right_on="o_custkey") \
        .merge(p["lineitem"], left_on="o_orderkey", right_on="l_orderkey") \
        .merge(p["nation"], left_on="c_nationkey", right_on="n_nationkey")
    lo, hi = datetime.date(1993, 10, 1), datetime.date(1994, 1, 1)
    m = m[(m.o_orderdate >= lo) & (m.o_orderdate < hi)
          & (m.l_returnflag == "R")]
    m = m.assign(rev=m.l_extendedprice * (1 - m.l_discount))
    exp = (m.groupby(["c_custkey", "c_name", "c_acctbal", "n_name"])
           .rev.sum().reset_index(name="revenue")
           .sort_values("revenue", ascending=False))
    _check_ordered(got, exp, ["revenue"], ["c_custkey"], limit=20)


Q11 = """
SELECT ps.ps_partkey, sum(ps.ps_supplycost * ps.ps_availqty) AS value
FROM partsupp ps, supplier s, nation n
WHERE ps.ps_suppkey = s.s_suppkey AND s.s_nationkey = n.n_nationkey
  AND n.n_name = 'GERMANY'
GROUP BY ps.ps_partkey
HAVING sum(ps.ps_supplycost * ps.ps_availqty) >
       (SELECT sum(ps2.ps_supplycost * ps2.ps_availqty) * 0.005
        FROM partsupp ps2, supplier s2, nation n2
        WHERE ps2.ps_suppkey = s2.s_suppkey
          AND s2.s_nationkey = n2.n_nationkey AND n2.n_name = 'GERMANY')
ORDER BY value DESC
"""


def q11_oracle(got, p):
    m = p["partsupp"].merge(p["supplier"], left_on="ps_suppkey",
                            right_on="s_suppkey") \
        .merge(p["nation"], left_on="s_nationkey", right_on="n_nationkey")
    m = m[m.n_name == "GERMANY"]
    m = m.assign(v=m.ps_supplycost * m.ps_availqty)
    g = m.groupby("ps_partkey").v.sum()
    exp = (g[g > g.sum() * 0.005].reset_index(name="value")
           .sort_values("value", ascending=False))
    _check_ordered(got, exp, ["value"], ["ps_partkey"])


Q12 = """
SELECT l.l_shipmode,
       sum(CASE WHEN o.o_orderpriority = '1-URGENT'
                  OR o.o_orderpriority = '2-HIGH'
                THEN 1 ELSE 0 END) AS high_line_count,
       sum(CASE WHEN o.o_orderpriority <> '1-URGENT'
                 AND o.o_orderpriority <> '2-HIGH'
                THEN 1 ELSE 0 END) AS low_line_count
FROM orders o, lineitem l
WHERE o.o_orderkey = l.l_orderkey AND l.l_shipmode IN ('MAIL', 'SHIP')
  AND l.l_commitdate < l.l_receiptdate AND l.l_shipdate < l.l_commitdate
  AND l.l_receiptdate >= date '1994-01-01'
  AND l.l_receiptdate < date '1995-01-01'
GROUP BY l.l_shipmode
ORDER BY l.l_shipmode
"""


def q12_oracle(got, p):
    m = p["orders"].merge(p["lineitem"], left_on="o_orderkey",
                          right_on="l_orderkey")
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    m = m[m.l_shipmode.isin(["MAIL", "SHIP"])
          & (m.l_commitdate < m.l_receiptdate)
          & (m.l_shipdate < m.l_commitdate)
          & (m.l_receiptdate >= lo) & (m.l_receiptdate < hi)]
    hi_p = m.o_orderpriority.isin(["1-URGENT", "2-HIGH"])
    exp = (pd.DataFrame({"l_shipmode": m.l_shipmode, "hi": hi_p})
           .groupby("l_shipmode")
           .agg(high_line_count=("hi", "sum"),
                low_line_count=("hi", lambda s: int((~s).sum())))
           .sort_index().reset_index())
    _check_ordered(got, exp, [], ["l_shipmode", "high_line_count",
                                  "low_line_count"])


Q13 = """
SELECT c_count, count(*) AS custdist
FROM (SELECT c.c_custkey AS c_custkey, count(o.o_orderkey) AS c_count
      FROM customer c LEFT JOIN
           (SELECT * FROM orders
            WHERE o_comment NOT LIKE '%special%requests%') o
           ON c.c_custkey = o.o_custkey
      GROUP BY c.c_custkey) c_orders
GROUP BY c_count
ORDER BY custdist DESC, c_count DESC
"""


def q13_oracle(got, p):
    o = p["orders"]
    o = o[~o.o_comment.str.match(".*special.*requests.*")]
    counts = o.groupby("o_custkey").size()
    per_cust = p["customer"].c_custkey.map(counts).fillna(0).astype(int)
    exp = (per_cust.value_counts().rename_axis("c_count")
           .reset_index(name="custdist")
           .sort_values(["custdist", "c_count"], ascending=[False, False]))
    _check_ordered(got, exp, [], ["c_count", "custdist"])


Q15 = """
WITH revenue AS
  (SELECT l_suppkey AS supplier_no,
          sum(l_extendedprice * (1 - l_discount)) AS total_revenue
   FROM lineitem
   WHERE l_shipdate >= date '1996-01-01' AND l_shipdate < date '1996-04-01'
   GROUP BY l_suppkey)
SELECT s.s_suppkey, s.s_name, total_revenue
FROM supplier s, revenue
WHERE s.s_suppkey = supplier_no
  AND total_revenue = (SELECT max(total_revenue) FROM revenue)
ORDER BY s.s_suppkey
"""


def q15_oracle(got, p):
    li = p["lineitem"]
    lo, hi = datetime.date(1996, 1, 1), datetime.date(1996, 4, 1)
    li = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)]
    rev = (li.l_extendedprice * (1 - li.l_discount)) \
        .groupby(li.l_suppkey).sum()
    best = rev[rev == rev.max()].reset_index()
    best.columns = ["s_suppkey", "total_revenue"]
    exp = best.merge(p["supplier"], on="s_suppkey").sort_values("s_suppkey")
    _check_ordered(got, exp, ["total_revenue"], ["s_suppkey"])


Q16 = """
SELECT p.p_brand, p.p_type, p.p_size,
       count(DISTINCT ps.ps_suppkey) AS supplier_cnt
FROM partsupp ps, part p
WHERE p.p_partkey = ps.ps_partkey AND p.p_brand <> 'Brand#45'
  AND p.p_type NOT LIKE 'MEDIUM POLISHED%'
  AND p.p_size IN (49, 14, 23, 45, 19, 3, 36, 9)
  AND ps.ps_suppkey NOT IN (SELECT s_suppkey FROM supplier
                            WHERE s_comment LIKE '%Customer%Complaints%')
GROUP BY p.p_brand, p.p_type, p.p_size
ORDER BY supplier_cnt DESC, p.p_brand, p.p_type, p.p_size
"""


def q16_oracle(got, p):
    bad = set(p["supplier"].s_suppkey[
        p["supplier"].s_comment.str.match(".*Customer.*Complaints.*")])
    m = p["partsupp"].merge(p["part"], left_on="ps_partkey",
                            right_on="p_partkey")
    m = m[(m.p_brand != "Brand#45")
          & ~m.p_type.str.startswith("MEDIUM POLISHED")
          & m.p_size.isin([49, 14, 23, 45, 19, 3, 36, 9])
          & ~m.ps_suppkey.isin(bad)]
    exp = (m.groupby(["p_brand", "p_type", "p_size"])
           .ps_suppkey.nunique().reset_index(name="supplier_cnt")
           .sort_values(["supplier_cnt", "p_brand", "p_type", "p_size"],
                        ascending=[False, True, True, True]))
    _check_ordered(got, exp, [], ["p_brand", "p_type", "p_size",
                                  "supplier_cnt"])


Q18 = """
SELECT c.c_name, c.c_custkey, o.o_orderkey, o.o_orderdate,
       o.o_totalprice, sum(l.l_quantity) AS total_qty
FROM customer c, orders o, lineitem l
WHERE o.o_orderkey IN (SELECT l_orderkey FROM lineitem
                       GROUP BY l_orderkey HAVING sum(l_quantity) > 180)
  AND c.c_custkey = o.o_custkey AND o.o_orderkey = l.l_orderkey
GROUP BY c.c_name, c.c_custkey, o.o_orderkey, o.o_orderdate, o.o_totalprice
ORDER BY o.o_totalprice DESC, o.o_orderdate
LIMIT 100
"""


def q18_oracle(got, p):
    li = p["lineitem"]
    big = li.groupby("l_orderkey").l_quantity.sum()
    big = set(big[big > 180].index)
    m = p["customer"].merge(p["orders"], left_on="c_custkey",
                            right_on="o_custkey") \
        .merge(li, left_on="o_orderkey", right_on="l_orderkey")
    m = m[m.o_orderkey.isin(big)]
    exp = (m.groupby(["c_name", "c_custkey", "o_orderkey", "o_orderdate",
                      "o_totalprice"])
           .l_quantity.sum().reset_index(name="total_qty")
           .sort_values(["o_totalprice", "o_orderdate"],
                        ascending=[False, True]))
    _check_ordered(got, exp, ["o_totalprice"], ["o_orderkey"], limit=100)


Q19 = """
SELECT sum(l.l_extendedprice * (1 - l.l_discount)) AS revenue
FROM lineitem l, part p
WHERE (p.p_partkey = l.l_partkey AND p.p_brand = 'Brand#12'
       AND p.p_container IN ('SM CASE', 'SM BOX', 'SM PACK', 'SM PKG')
       AND l.l_quantity >= 1 AND l.l_quantity <= 11
       AND p.p_size BETWEEN 1 AND 5
       AND l.l_shipmode IN ('AIR', 'REG AIR')
       AND l.l_shipinstruct = 'DELIVER IN PERSON')
   OR (p.p_partkey = l.l_partkey AND p.p_brand = 'Brand#23'
       AND p.p_container IN ('MED BAG', 'MED BOX', 'MED PKG', 'MED PACK')
       AND l.l_quantity >= 10 AND l.l_quantity <= 20
       AND p.p_size BETWEEN 1 AND 10
       AND l.l_shipmode IN ('AIR', 'REG AIR')
       AND l.l_shipinstruct = 'DELIVER IN PERSON')
   OR (p.p_partkey = l.l_partkey AND p.p_brand = 'Brand#34'
       AND p.p_container IN ('LG CASE', 'LG BOX', 'LG PACK', 'LG PKG')
       AND l.l_quantity >= 20 AND l.l_quantity <= 30
       AND p.p_size BETWEEN 1 AND 15
       AND l.l_shipmode IN ('AIR', 'REG AIR')
       AND l.l_shipinstruct = 'DELIVER IN PERSON')
"""


def q19_oracle(got, p):
    m = p["lineitem"].merge(p["part"], left_on="l_partkey",
                            right_on="p_partkey")
    common = (m.l_shipmode.isin(["AIR", "REG AIR"])
              & (m.l_shipinstruct == "DELIVER IN PERSON"))
    b1 = ((m.p_brand == "Brand#12")
          & m.p_container.isin(["SM CASE", "SM BOX", "SM PACK", "SM PKG"])
          & (m.l_quantity >= 1) & (m.l_quantity <= 11)
          & (m.p_size >= 1) & (m.p_size <= 5))
    b2 = ((m.p_brand == "Brand#23")
          & m.p_container.isin(["MED BAG", "MED BOX", "MED PKG",
                                "MED PACK"])
          & (m.l_quantity >= 10) & (m.l_quantity <= 20)
          & (m.p_size >= 1) & (m.p_size <= 10))
    b3 = ((m.p_brand == "Brand#34")
          & m.p_container.isin(["LG CASE", "LG BOX", "LG PACK", "LG PKG"])
          & (m.l_quantity >= 20) & (m.l_quantity <= 30)
          & (m.p_size >= 1) & (m.p_size <= 15))
    sel = m[common & (b1 | b2 | b3)]
    exp = float((sel.l_extendedprice * (1 - sel.l_discount)).sum())
    val = got["revenue"].iloc[0]
    val = 0.0 if pd.isna(val) else float(val)
    assert abs(val - exp) <= 1e-6 * max(abs(exp), 1.0), (val, exp)


Q20 = """
SELECT s.s_name, s.s_address
FROM supplier s, nation n
WHERE s.s_suppkey IN
      (SELECT ps.ps_suppkey FROM partsupp ps
       WHERE ps.ps_partkey IN (SELECT p_partkey FROM part
                               WHERE p_name LIKE 'forest%')
         AND ps.ps_availqty > (SELECT 0.5 * sum(l.l_quantity)
                               FROM lineitem l
                               WHERE l.l_partkey = ps.ps_partkey
                                 AND l.l_suppkey = ps.ps_suppkey
                                 AND l.l_shipdate >= date '1994-01-01'
                                 AND l.l_shipdate < date '1995-01-01'))
  AND s.s_nationkey = n.n_nationkey AND n.n_name = 'CANADA'
ORDER BY s.s_name
"""


def q20_oracle(got, p):
    li = p["lineitem"]
    lo, hi = datetime.date(1994, 1, 1), datetime.date(1995, 1, 1)
    li = li[(li.l_shipdate >= lo) & (li.l_shipdate < hi)]
    half = li.groupby(["l_partkey", "l_suppkey"]).l_quantity.sum() * 0.5
    forest = set(p["part"].p_partkey[
        p["part"].p_name.str.startswith("forest")])
    ps = p["partsupp"]
    ps = ps[ps.ps_partkey.isin(forest)]
    key = list(zip(ps.ps_partkey, ps.ps_suppkey))
    th = pd.Series([half.get(k, np.nan) for k in key], index=ps.index)
    good = set(ps.ps_suppkey[ps.ps_availqty > th])
    s = p["supplier"].merge(p["nation"], left_on="s_nationkey",
                            right_on="n_nationkey")
    exp = s[(s.n_name == "CANADA") & s.s_suppkey.isin(good)] \
        .sort_values("s_name")
    _check_ordered(got, exp, [], ["s_name", "s_address"])


Q21 = """
SELECT s.s_name, count(*) AS numwait
FROM supplier s, lineitem l1, orders o, nation n
WHERE s.s_suppkey = l1.l_suppkey AND o.o_orderkey = l1.l_orderkey
  AND o.o_orderstatus = 'F' AND l1.l_receiptdate > l1.l_commitdate
  AND EXISTS (SELECT 1 FROM lineitem l2
              WHERE l2.l_orderkey = l1.l_orderkey
                AND l2.l_suppkey <> l1.l_suppkey)
  AND NOT EXISTS (SELECT 1 FROM lineitem l3
                  WHERE l3.l_orderkey = l1.l_orderkey
                    AND l3.l_suppkey <> l1.l_suppkey
                    AND l3.l_receiptdate > l3.l_commitdate)
  AND s.s_nationkey = n.n_nationkey AND n.n_name = 'SAUDI ARABIA'
GROUP BY s.s_name
ORDER BY numwait DESC, s.s_name
LIMIT 100
"""


def q21_oracle(got, p):
    li = p["lineitem"]
    late = li[li.l_receiptdate > li.l_commitdate]
    # orders with >1 distinct supplier / >1 distinct LATE supplier
    nsupp = li.groupby("l_orderkey").l_suppkey.nunique()
    nlate = late.groupby("l_orderkey").l_suppkey.nunique()
    m = p["supplier"].merge(late, left_on="s_suppkey",
                            right_on="l_suppkey") \
        .merge(p["orders"], left_on="l_orderkey", right_on="o_orderkey") \
        .merge(p["nation"], left_on="s_nationkey", right_on="n_nationkey")
    m = m[(m.o_orderstatus == "F") & (m.n_name == "SAUDI ARABIA")]
    multi = m.l_orderkey.map(nsupp) > 1
    # NOT EXISTS other-late-supplier: this supplier is the ONLY late one
    only_late = m.l_orderkey.map(nlate) == 1
    sel = m[multi & only_late]
    exp = (sel.groupby("s_name").size().reset_index(name="numwait")
           .sort_values(["numwait", "s_name"], ascending=[False, True]))
    _check_ordered(got, exp, [], ["s_name", "numwait"], limit=100)


#: name -> (sql, oracle); names align with the spec numbering
QUERY_SET: List[Tuple[str, str, Callable]] = [
    ("q2", Q2, q2_oracle), ("q3", Q3, q3_oracle), ("q5", Q5, q5_oracle),
    ("q7", Q7, q7_oracle), ("q8", Q8, q8_oracle), ("q9", Q9, q9_oracle),
    ("q10", Q10, q10_oracle), ("q11", Q11, q11_oracle),
    ("q12", Q12, q12_oracle), ("q13", Q13, q13_oracle),
    ("q15", Q15, q15_oracle), ("q16", Q16, q16_oracle),
    ("q18", Q18, q18_oracle), ("q19", Q19, q19_oracle),
    ("q20", Q20, q20_oracle), ("q21", Q21, q21_oracle),
]


#: single-entry caches — run_suite calls each runner twice (cold+warm)
#: over one shared table set; re-registering 8 views and re-converting 8
#: tables to pandas inside every timed run would land in warm_seconds,
#: the number the rig compares across machines
from .rig_util import ViewCache

_views = ViewCache(register_views)
_pandas_cache: list = [None]  # (id(t), {name: DataFrame})


def make_runner(sql: str, oracle: Callable) -> Callable:
    """Adapt one query to the scaletest (sess, tables, F) protocol."""
    def run(sess, t, F):
        _views.ensure(sess, t)
        if _pandas_cache[0] is None or _pandas_cache[0][0] is not t:
            _pandas_cache[0] = (t, _pandas(t))
        got = sess.sql(sql).collect().to_pandas()
        oracle(got, _pandas_cache[0][1])
    return run
