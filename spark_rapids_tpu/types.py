"""Spark-compatible logical data types for the TPU columnar engine.

Mirrors the type surface the reference plugin supports (see reference
``sql-plugin/.../TypeChecks.scala`` TypeSig enumeration): BOOLEAN, BYTE, SHORT,
INT, LONG, FLOAT, DOUBLE, DATE, TIMESTAMP, STRING, BINARY, DECIMAL, NULL,
ARRAY, STRUCT, MAP.  On TPU the physical carrier for each type is a JAX dtype
(column layout documented in ``columnar/column.py``).
"""

from __future__ import annotations

import datetime as _dt
from dataclasses import dataclass, field
from typing import Any, List, Optional, Tuple

import numpy as np

__all__ = [
    "DataType", "BooleanType", "ByteType", "ShortType", "IntegerType",
    "LongType", "FloatType", "DoubleType", "StringType", "BinaryType",
    "DateType", "TimestampType", "DecimalType", "NullType", "ArrayType",
    "StructField", "StructType", "MapType", "from_arrow", "to_arrow",
    "BOOLEAN", "BYTE", "SHORT", "INT", "LONG", "FLOAT", "DOUBLE", "STRING",
    "BINARY", "DATE", "TIMESTAMP", "NULL",
    "is_numeric", "is_integral", "is_floating", "common_type",
    "numeric_promote",
]


class DataType:
    """Base class for all logical types."""

    #: numpy dtype used as the physical device carrier (None = layout-special)
    np_dtype: Optional[np.dtype] = None

    def simple_string(self) -> str:
        return type(self).__name__.replace("Type", "").lower()

    def __repr__(self) -> str:  # pragma: no cover - debug aid
        return self.simple_string()

    def __eq__(self, other: Any) -> bool:
        return type(self) is type(other)

    def __hash__(self) -> int:
        return hash(type(self).__name__)

    @property
    def is_nested(self) -> bool:
        return isinstance(self, (ArrayType, StructType, MapType))


class NumericType(DataType):
    pass


class IntegralType(NumericType):
    pass


class FractionalType(NumericType):
    pass


class BooleanType(DataType):
    np_dtype = np.dtype(np.bool_)


class ByteType(IntegralType):
    np_dtype = np.dtype(np.int8)
    min_value, max_value = -(2 ** 7), 2 ** 7 - 1

    def simple_string(self) -> str:
        return "tinyint"


class ShortType(IntegralType):
    np_dtype = np.dtype(np.int16)
    min_value, max_value = -(2 ** 15), 2 ** 15 - 1

    def simple_string(self) -> str:
        return "smallint"


class IntegerType(IntegralType):
    np_dtype = np.dtype(np.int32)
    min_value, max_value = -(2 ** 31), 2 ** 31 - 1

    def simple_string(self) -> str:
        return "int"


class LongType(IntegralType):
    np_dtype = np.dtype(np.int64)
    min_value, max_value = -(2 ** 63), 2 ** 63 - 1

    def simple_string(self) -> str:
        return "bigint"


class FloatType(FractionalType):
    np_dtype = np.dtype(np.float32)


class DoubleType(FractionalType):
    np_dtype = np.dtype(np.float64)


class StringType(DataType):
    # physical layout: uint8 byte matrix + int32 lengths (see column.py)
    np_dtype = np.dtype(np.uint8)


class BinaryType(DataType):
    np_dtype = np.dtype(np.uint8)


class DateType(DataType):
    """Days since epoch, int32 carrier (Spark DateType semantics)."""
    np_dtype = np.dtype(np.int32)


class TimestampType(DataType):
    """Microseconds since epoch UTC, int64 carrier (Spark TimestampType)."""
    np_dtype = np.dtype(np.int64)


class NullType(DataType):
    np_dtype = np.dtype(np.int8)

    def simple_string(self) -> str:
        return "void"


@dataclass(frozen=True)
class DecimalType(FractionalType):
    """Spark decimal(p, s).  Carrier is a scaled int64 for precision <= 18
    (DECIMAL_64); precision 19-38 uses a (hi, lo) int64 pair column
    (DECIMAL_128), mirroring the reference's decimal-128 support
    (reference ``Aggregation128Utils``/``DecimalUtils`` JNI kernels)."""
    precision: int = 10
    scale: int = 0

    MAX_PRECISION = 38
    MAX_LONG_DIGITS = 18

    def __post_init__(self):
        if not (0 < self.precision <= self.MAX_PRECISION):
            raise ValueError(f"decimal precision out of range: {self.precision}")
        if not (0 <= self.scale <= self.precision):
            raise ValueError(
                f"decimal scale {self.scale} out of range for precision {self.precision}")

    @property
    def np_dtype(self):  # type: ignore[override]
        return np.dtype(np.int64)

    @property
    def is_long_backed(self) -> bool:
        return self.precision <= self.MAX_LONG_DIGITS

    def simple_string(self) -> str:
        return f"decimal({self.precision},{self.scale})"

    def __hash__(self) -> int:
        return hash(("decimal", self.precision, self.scale))

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, DecimalType)
                and other.precision == self.precision and other.scale == self.scale)

    @staticmethod
    def bounded(precision: int, scale: int) -> "DecimalType":
        return DecimalType(min(precision, DecimalType.MAX_PRECISION),
                           min(scale, DecimalType.MAX_PRECISION))


@dataclass(frozen=True)
class ArrayType(DataType):
    element_type: DataType = field(default_factory=lambda: NullType())
    contains_null: bool = True

    def simple_string(self) -> str:
        return f"array<{self.element_type.simple_string()}>"

    def __hash__(self) -> int:
        return hash(("array", self.element_type))

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, ArrayType) and other.element_type == self.element_type


@dataclass(frozen=True)
class StructField:
    name: str
    data_type: DataType
    nullable: bool = True


@dataclass(frozen=True)
class StructType(DataType):
    fields: Tuple[StructField, ...] = ()

    def __init__(self, fields=()):
        object.__setattr__(self, "fields", tuple(fields))

    @property
    def names(self) -> List[str]:
        return [f.name for f in self.fields]

    def field_index(self, name: str) -> int:
        for i, f in enumerate(self.fields):
            if f.name == name:
                return i
        raise KeyError(name)

    def add(self, name: str, dt: DataType, nullable: bool = True) -> "StructType":
        return StructType(self.fields + (StructField(name, dt, nullable),))

    def simple_string(self) -> str:
        inner = ",".join(f"{f.name}:{f.data_type.simple_string()}" for f in self.fields)
        return f"struct<{inner}>"

    def __hash__(self) -> int:
        return hash(("struct", self.fields))

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, StructType) and other.fields == self.fields

    def __len__(self) -> int:
        return len(self.fields)

    def __iter__(self):
        return iter(self.fields)


@dataclass(frozen=True)
class MapType(DataType):
    key_type: DataType = field(default_factory=lambda: NullType())
    value_type: DataType = field(default_factory=lambda: NullType())
    value_contains_null: bool = True

    def simple_string(self) -> str:
        return (f"map<{self.key_type.simple_string()},"
                f"{self.value_type.simple_string()}>")

    def __hash__(self) -> int:
        return hash(("map", self.key_type, self.value_type))

    def __eq__(self, other: Any) -> bool:
        return (isinstance(other, MapType) and other.key_type == self.key_type
                and other.value_type == self.value_type)


# Singletons for the common scalar types
BOOLEAN = BooleanType()
BYTE = ByteType()
SHORT = ShortType()
INT = IntegerType()
LONG = LongType()
FLOAT = FloatType()
DOUBLE = DoubleType()
STRING = StringType()
BINARY = BinaryType()
DATE = DateType()
TIMESTAMP = TimestampType()
NULL = NullType()

_INTEGRAL_ORDER = [ByteType(), ShortType(), IntegerType(), LongType()]


def is_numeric(dt: DataType) -> bool:
    return isinstance(dt, NumericType)


def is_integral(dt: DataType) -> bool:
    return isinstance(dt, IntegralType)


def is_floating(dt: DataType) -> bool:
    return isinstance(dt, (FloatType, DoubleType))


def numeric_promote(a: DataType, b: DataType) -> DataType:
    """Binary arithmetic result type following Spark's numeric precedence
    byte < short < int < long < float < double (decimal handled separately)."""
    if isinstance(a, DecimalType) or isinstance(b, DecimalType):
        da = a if isinstance(a, DecimalType) else _decimal_for_integral(a)
        db = b if isinstance(b, DecimalType) else _decimal_for_integral(b)
        if da is None or db is None:  # decimal with float → double
            return DOUBLE
        p = max(da.precision - da.scale, db.precision - db.scale) + max(da.scale, db.scale)
        s = max(da.scale, db.scale)
        return DecimalType.bounded(p, s)
    if isinstance(a, DoubleType) or isinstance(b, DoubleType):
        return DOUBLE
    if isinstance(a, FloatType) or isinstance(b, FloatType):
        return FLOAT
    ia = _INTEGRAL_ORDER.index(a) if a in _INTEGRAL_ORDER else None
    ib = _INTEGRAL_ORDER.index(b) if b in _INTEGRAL_ORDER else None
    if ia is None or ib is None:
        raise TypeError(f"cannot promote {a} and {b}")
    return _INTEGRAL_ORDER[max(ia, ib)]


def _decimal_for_integral(dt: DataType) -> Optional[DecimalType]:
    if isinstance(dt, ByteType):
        return DecimalType(3, 0)
    if isinstance(dt, ShortType):
        return DecimalType(5, 0)
    if isinstance(dt, IntegerType):
        return DecimalType(10, 0)
    if isinstance(dt, LongType):
        return DecimalType(20, 0)
    return None


def common_type(a: DataType, b: DataType) -> Optional[DataType]:
    """Least common type for comparisons/conditionals (subset of Spark's
    TypeCoercion.findTightestCommonType)."""
    if a == b:
        return a
    if isinstance(a, NullType):
        return b
    if isinstance(b, NullType):
        return a
    if is_numeric(a) and is_numeric(b):
        return numeric_promote(a, b)
    if isinstance(a, StringType) or isinstance(b, StringType):
        return STRING
    if isinstance(a, DateType) and isinstance(b, TimestampType):
        return TIMESTAMP
    if isinstance(a, TimestampType) and isinstance(b, DateType):
        return TIMESTAMP
    return None


def from_arrow(at) -> DataType:
    """Map a pyarrow type to the engine's logical type."""
    import pyarrow as pa
    if pa.types.is_boolean(at):
        return BOOLEAN
    if pa.types.is_int8(at):
        return BYTE
    if pa.types.is_int16(at):
        return SHORT
    if pa.types.is_int32(at):
        return INT
    if pa.types.is_int64(at):
        return LONG
    if pa.types.is_uint8(at):
        return SHORT
    if pa.types.is_uint16(at):
        return INT
    if pa.types.is_uint32(at) or pa.types.is_uint64(at):
        return LONG
    if pa.types.is_float32(at):
        return FLOAT
    if pa.types.is_float64(at):
        return DOUBLE
    if pa.types.is_string(at) or pa.types.is_large_string(at):
        return STRING
    if pa.types.is_binary(at) or pa.types.is_large_binary(at):
        return BINARY
    if pa.types.is_date32(at):
        return DATE
    if pa.types.is_timestamp(at):
        return TIMESTAMP
    if pa.types.is_decimal(at):
        return DecimalType(at.precision, at.scale)
    if pa.types.is_null(at):
        return NULL
    if pa.types.is_list(at) or pa.types.is_large_list(at):
        return ArrayType(from_arrow(at.value_type))
    if pa.types.is_struct(at):
        return StructType(tuple(StructField(f.name, from_arrow(f.type), f.nullable)
                                for f in at))
    if pa.types.is_map(at):
        return MapType(from_arrow(at.key_type), from_arrow(at.item_type))
    raise TypeError(f"unsupported arrow type {at}")


def to_arrow(dt: DataType):
    import pyarrow as pa
    if isinstance(dt, BooleanType):
        return pa.bool_()
    if isinstance(dt, ByteType):
        return pa.int8()
    if isinstance(dt, ShortType):
        return pa.int16()
    if isinstance(dt, IntegerType):
        return pa.int32()
    if isinstance(dt, LongType):
        return pa.int64()
    if isinstance(dt, FloatType):
        return pa.float32()
    if isinstance(dt, DoubleType):
        return pa.float64()
    if isinstance(dt, StringType):
        return pa.string()
    if isinstance(dt, BinaryType):
        return pa.binary()
    if isinstance(dt, DateType):
        return pa.date32()
    if isinstance(dt, TimestampType):
        return pa.timestamp("us", tz="UTC")
    if isinstance(dt, DecimalType):
        return pa.decimal128(dt.precision, dt.scale)
    if isinstance(dt, NullType):
        return pa.null()
    if isinstance(dt, ArrayType):
        return pa.list_(to_arrow(dt.element_type))
    if isinstance(dt, StructType):
        return pa.struct([pa.field(f.name, to_arrow(f.data_type), f.nullable)
                          for f in dt.fields])
    if isinstance(dt, MapType):
        return pa.map_(to_arrow(dt.key_type), to_arrow(dt.value_type))
    raise TypeError(f"unsupported type {dt}")


def python_value_type(v: Any) -> DataType:
    """Infer the logical type of a Python literal (Spark Literal inference)."""
    if v is None:
        return NULL
    if isinstance(v, bool):
        return BOOLEAN
    if isinstance(v, int):
        return INT if IntegerType.min_value <= v <= IntegerType.max_value else LONG
    if isinstance(v, float):
        return DOUBLE
    if isinstance(v, str):
        return STRING
    if isinstance(v, (bytes, bytearray)):
        return BINARY
    if isinstance(v, _dt.datetime):
        return TIMESTAMP
    if isinstance(v, _dt.date):
        return DATE
    import decimal
    if isinstance(v, decimal.Decimal):
        sign, digits, exp = v.as_tuple()
        if exp >= 0:
            return DecimalType(len(digits) + exp, 0)
        scale = -exp
        precision = max(len(digits), scale + 1)
        return DecimalType(precision, scale)
    if isinstance(v, (list, tuple)):
        et = python_value_type(v[0]) if v else NULL
        return ArrayType(et)
    raise TypeError(f"cannot infer literal type for {type(v)}")
