"""Test bootstrap: run everything on a virtual 8-device CPU mesh so
multi-chip sharding logic is exercised without TPU hardware (the driver
separately dry-runs the multichip path).

Note: the ambient environment registers the "axon" real-TPU tunnel backend
from sitecustomize and forces ``jax_platforms=axon,cpu`` via jax.config (so
env vars can't override it).  Tests must flip the *config* back to cpu before
any backend initializes, or the first jax.devices() blocks on the tunnel.
"""

import os

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "1")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_enable_x64", True)

import numpy as np  # noqa: E402
import pyarrow as pa  # noqa: E402
import pytest  # noqa: E402


#: the <3-minute smoke tier (`pytest -m quick`): one module per major
#: layer — columnar model, expressions, SQL front-end+planner, joins,
#: memory/spill/retry, native lib.  Everything else is marked slow; the
#: full matrix runs in ci/run_ci.sh.
QUICK_MODULES = {
    "test_columnar", "test_expressions", "test_sql", "test_joins",
    "test_join_fastpath",
    "test_memory", "test_native", "test_cross_slice", "test_hive_udf",
    # observability tracer: tier-1 per ISSUE 3 (trace regressions must
    # surface in the quick gate, not only in full CI)
    "test_tracer",
    # robustness: chaos-schedule determinism + the resilient shuffle
    # fetch protocol (retry/deadline/blacklist/recompute) are tier-1 per
    # ISSUE 4 — a silent regression here only shows up under failure
    "test_chaos", "test_shuffle",
    # both jax ShimProviders exercised end-to-end every CI run — the
    # parallel-world guarantee (VERDICT r3 #8)
    "test_shims",
    # pipelined async execution (ISSUE 5): scheduler/prefetch/transfer
    # bit-parity and exception propagation are tier-1 — a silent
    # ordering or queue-hang regression must surface in the quick gate
    "test_async_pipeline",
    # encoded columnar execution (ISSUE 6): representation round-trips,
    # op parity encoded-on vs -off, the encoded wire format, and the
    # kill-switch reversion are tier-1 — an encoding bug is silent data
    # corruption, not a crash
    "test_encoded",
    # whole-stage XLA compilation (ISSUE 7): terminal stage formation,
    # fused-vs-killswitched bit parity, and the donation-safety guard
    # are tier-1 — a fusion or donation bug is silent data corruption
    "test_whole_stage",
    # performance flight recorder (ISSUE 8): metrics-registry accounting
    # under the parallel scheduler, doctor verdicts on known injected
    # bottlenecks, and the bench_diff evidence gate are tier-1 — wrong
    # attribution silently misdirects every perf decision downstream
    "test_metrics_registry", "test_doctor",
    # multi-tenant serving (ISSUE 9): weighted-fair admission, tenant
    # budgets, the cross-query result/broadcast sharing tiers and the
    # generation-safe kernel-cache clear are tier-1 — a sharing bug is
    # silent cross-tenant data corruption, an admission bug is silent
    # starvation
    "test_serving",
    # query lifecycle (ISSUE 10): the cancellation race matrix
    # (semaphore/retention/queue accounting at every poll site), the
    # WFQ vft rollback, pressure degradation and the poison-query
    # quarantine are tier-1 — a cancel leak is a slow engine death, a
    # quarantine bug re-kills the device
    "test_lifecycle",
    # the telemetry plane is pure-stdlib and loopback-local (embedded
    # HTTP server, SLO arithmetic, wire trace stitching) — fast, and a
    # regression here blinds every production scrape target
    "test_telemetry",
    # dispatch budgets (ISSUE 14): per-shape launch counts, the fused
    # join probe's <=1-readback contract, and dispatch-coalescer parity
    # are tier-1 — a launch-count regression is a silent perf cliff on
    # the tunnel that no correctness test would ever fail
    "test_dispatch_budget",
    # pod-scale fault domain (ISSUE 19): the phi-accrual detector state
    # machine, epoch fencing, speculative fetch and the blacklist
    # generation race are tier-1 — a regression here is silent data
    # loss that only manifests when a peer actually dies
    "test_failure_detector",
    # perf sentry (ISSUE 18): probe classification, evidence-ledger
    # append-only/torn-line safety, live-over-stale baseline resolution
    # and the /sentry route contract are tier-1 — a sentry regression
    # silently starves every future round of live evidence
    "test_sentry",
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.get_closest_marker("slow"):
            continue     # an explicit slow mark wins over module tiering
        mod = item.module.__name__.rsplit(".", 1)[-1] if item.module else ""
        item.add_marker(pytest.mark.quick if mod in QUICK_MODULES
                        else pytest.mark.slow)


def release_compiled_caches():
    """Free XLA executables (per test module here; scaletest.run_suite
    does the same per query) — accumulated compiled-code state segfaults
    the XLA:CPU JIT inside backend_compile_and_load past a few hundred
    programs (reproduced repeatedly, never in isolation).  Engine-level
    import: pulling in the whole scale rig here would turn any rig-corpus
    import error into a suite-wide teardown failure."""
    from spark_rapids_tpu.sql.physical.kernel_cache import (
        release_compiled_programs)
    release_compiled_programs()


@pytest.fixture(scope="module", autouse=True)
def _release_compiled_programs():
    """Free XLA executables between test modules (see
    release_compiled_caches); modules recompile their shared kernels,
    which is noise next to the crash it prevents."""
    yield
    release_compiled_caches()


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture()
def session():
    import spark_rapids_tpu as srt
    s = srt.session()
    yield s
