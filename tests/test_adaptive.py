"""AQE (runtime broadcast-vs-shuffle re-decision) + cost-based optimizer
(reference GpuOverrides.scala:4392-4452 AQE integration,
CostBasedOptimizer.scala:54)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical.join import AdaptiveJoinExec
from spark_rapids_tpu.sql.planner import Planner


def _find(p, cls):
    if isinstance(p, cls):
        return p
    for c in p.children:
        f = _find(c, cls)
        if f is not None:
            return f
    return None


def _tables(rng, n=20000):
    left = pa.table({"k": rng.integers(0, 1000, n), "v": rng.random(n)})
    right = pa.table({"k": pa.array(np.arange(n) % 1000, type=pa.int64()),
                      "w": pa.array(rng.random(n))})
    return left, right


def test_aqe_switches_misestimated_join_to_broadcast(rng):
    """Static estimate (~320KB relation) refuses broadcast under a 50KB
    threshold, but the filtered build side is ~8 rows at runtime — AQE
    provably picks a different plan than the static planner."""
    left, right = _tables(rng)
    sess = srt.session(
        **{"spark.rapids.sql.autoBroadcastJoinThreshold": 50_000})
    l = sess.create_dataframe(left, num_partitions=4)
    r = sess.create_dataframe(right, num_partitions=4)
    rf = r.filter(r.k < 8).groupBy("k").agg(F.max(r.w).alias("w"))
    q = l.join(rf, on="k", how="inner").select(l.k, l.v, rf.w)

    phys = Planner(sess._conf).plan_for_collect(q._plan)
    aqe = _find(phys, AdaptiveJoinExec)
    assert aqe is not None and aqe.chosen_strategy is None
    out = phys.execute_all(sess._conf)
    assert aqe.chosen_strategy == "broadcast"
    exp = (left.to_pandas().merge(
        right.to_pandas().query("k < 8").groupby("k")
        .agg(w=("w", "max")).reset_index(), on="k"))
    assert sum(b.num_rows_int for b in out) == len(exp)


def test_aqe_keeps_shuffle_for_big_build(rng):
    left, right = _tables(rng)
    sess = srt.session(
        **{"spark.rapids.sql.autoBroadcastJoinThreshold": 50_000})
    l = sess.create_dataframe(left, num_partitions=4)
    r = sess.create_dataframe(right, num_partitions=4)
    q = l.join(r, on="k", how="inner").select(l.k, l.v, r.w)
    phys = Planner(sess._conf).plan_for_collect(q._plan)
    aqe = _find(phys, AdaptiveJoinExec)
    assert aqe is not None
    out = phys.execute_all(sess._conf)
    assert aqe.chosen_strategy == "shuffle"
    exp = left.to_pandas().merge(right.to_pandas(), on="k")
    assert sum(b.num_rows_int for b in out) == len(exp)


def test_aqe_disabled_plans_statically(rng):
    left, right = _tables(rng)
    sess = srt.session(**{
        "spark.sql.adaptive.enabled": False,
        "spark.rapids.sql.autoBroadcastJoinThreshold": 50_000})
    l = sess.create_dataframe(left, num_partitions=4)
    r = sess.create_dataframe(right, num_partitions=4)
    q = l.join(r, on="k", how="inner")
    phys = Planner(sess._conf).plan_for_collect(q._plan)
    assert _find(phys, AdaptiveJoinExec) is None


def test_aqe_result_equivalence(rng):
    """Same query, AQE on vs off — identical results."""
    left, right = _tables(rng, n=5000)
    res = {}
    for flag in (True, False):
        sess = srt.session(**{
            "spark.sql.adaptive.enabled": flag,
            "spark.rapids.sql.autoBroadcastJoinThreshold": 10_000})
        l = sess.create_dataframe(left, num_partitions=4)
        r = sess.create_dataframe(right, num_partitions=4)
        rf = r.filter(r.k < 50)
        got = (l.join(rf, on="k", how="left_semi")
               .orderBy("k", "v").collect().to_pandas())
        res[flag] = got
    assert np.array_equal(res[True]["k"], res[False]["k"])
    assert np.allclose(res[True]["v"], res[False]["v"])


def test_cost_optimizer_demotes_when_device_expensive():
    t = pa.table({"a": list(range(100)), "b": [float(i) for i in range(100)]})
    sess = srt.session(**{
        "spark.rapids.sql.optimizer.enabled": True,
        "spark.rapids.sql.optimizer.gpu.exec.default": 100.0})
    try:
        df = sess.create_dataframe(t)
        q = df.select((df.a + 1).alias("a1"))
        rep = sess.explain(q)
        assert "CpuProject" in rep and "cost-based optimizer" in rep
        out = q.collect().to_pylist()
        assert out[5]["a1"] == 6
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.enabled": False,
                       "spark.rapids.sql.optimizer.gpu.exec.default": 0.0001})


def test_cost_optimizer_keeps_device_when_cheap():
    t = pa.table({"a": list(range(100))})
    sess = srt.session(**{"spark.rapids.sql.optimizer.enabled": True})
    try:
        df = sess.create_dataframe(t)
        rep = sess.explain(df.select((df.a + 1).alias("a1")))
        assert "TpuProject" in rep
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.enabled": False})


def test_cost_optimizer_off_by_default():
    t = pa.table({"a": list(range(10))})
    sess = srt.session(**{
        "spark.rapids.sql.optimizer.gpu.exec.default": 100.0})
    try:
        df = sess.create_dataframe(t)
        rep = sess.explain(df.select((df.a + 1).alias("a1")))
        assert "TpuProject" in rep  # optimizer disabled -> no demotion
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.gpu.exec.default": 0.0001})


def test_cost_optimizer_unknown_stats_keep_device(tmp_path):
    """File scans have no row statistics; unknown stats must not demote
    (0 >= 0 would flip every file-based query to the host)."""
    import pyarrow.parquet as pq
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": list(range(50))}), p)
    sess = srt.session(**{"spark.rapids.sql.optimizer.enabled": True})
    try:
        df = sess.read.parquet(p)
        rep = sess.explain(df.select((df.a + 1).alias("a1")))
        assert "CpuProject" not in rep
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.enabled": False})


def test_skew_split_at_exchange(rng):
    """AQE skew handling (GpuCustomShuffleReaderExec skewed-partition
    specs): a hot-key reduce partition is re-sliced into median-sized
    chunks at materialization, the shuffled hash join probes chunk by
    chunk, results still match pandas, and the OOM-retry path never
    fires (VERDICT r3 #3 done-criteria)."""
    from spark_rapids_tpu.memory import oom_guard
    from spark_rapids_tpu.sql.physical import exchange as EX

    n, n_keys = 120_000, 400
    # 50% of probe rows land on ONE key -> one reduce partition ~50x the
    # median
    hot = np.zeros(n // 2, dtype=np.int64)
    cold = rng.integers(1, n_keys, n - n // 2)
    keys = np.concatenate([hot, cold])
    rng.shuffle(keys)
    fact = pa.table({"k": pa.array(keys), "v": rng.random(n)})
    dim = pa.table({"k": pa.array(np.arange(n_keys, dtype=np.int64)),
                    "w": rng.random(n_keys)})
    sess = srt.session(**{
        "spark.rapids.sql.autoBroadcastJoinThreshold": -1,
        "spark.sql.adaptive.skewJoin.skewedPartitionRowsThreshold": 2000,
    })
    try:
        f = sess.create_dataframe(fact, num_partitions=4)
        d = sess.create_dataframe(dim, num_partitions=2)
        splits0 = EX.STATS["skew_splits"]
        oom0 = oom_guard.STATS["oom_caught"]
        got = (f.join(d, on="k", how="inner")
               .groupBy("k").agg(F.sum(F.col("v")).alias("sv"),
                                 F.count("*").alias("c"))
               .orderBy("k").collect().to_pandas())
        assert EX.STATS["skew_splits"] > splits0, "skew split did not fire"
        assert EX.STATS["skew_chunks"] > 0
        assert oom_guard.STATS["oom_caught"] == oom0
        m = fact.to_pandas().merge(dim.to_pandas(), on="k")
        exp = (m.groupby("k").agg(sv=("v", "sum"), c=("v", "size"))
               .sort_index().reset_index())
        assert np.array_equal(got["k"], exp["k"])
        assert np.array_equal(got["c"], exp["c"])
        assert np.allclose(got["sv"], exp["sv"])
    finally:
        sess.conf.set(
            "spark.sql.adaptive.skewJoin.skewedPartitionRowsThreshold",
            1 << 17)
        sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold",
                      10 * 1024 * 1024)


def test_skew_split_kill_switch(rng):
    from spark_rapids_tpu.sql.physical import exchange as EX
    n = 60_000
    keys = np.concatenate([np.zeros(n // 2, dtype=np.int64),
                           rng.integers(1, 200, n - n // 2)])
    fact = pa.table({"k": pa.array(keys), "v": rng.random(n)})
    dim = pa.table({"k": pa.array(np.arange(200, dtype=np.int64)),
                    "w": rng.random(200)})
    sess = srt.session(**{
        "spark.rapids.sql.autoBroadcastJoinThreshold": -1,
        "spark.sql.adaptive.skewJoin.enabled": False,
        "spark.sql.adaptive.skewJoin.skewedPartitionRowsThreshold": 2000,
    })
    try:
        f = sess.create_dataframe(fact, num_partitions=4)
        d = sess.create_dataframe(dim, num_partitions=2)
        splits0 = EX.STATS["skew_splits"]
        n_got = f.join(d, on="k", how="inner").count()
        assert EX.STATS["skew_splits"] == splits0
        assert n_got == n
    finally:
        sess.conf.set("spark.sql.adaptive.skewJoin.enabled", True)
        sess.conf.set(
            "spark.sql.adaptive.skewJoin.skewedPartitionRowsThreshold",
            1 << 17)
        sess.conf.set("spark.rapids.sql.autoBroadcastJoinThreshold",
                      10 * 1024 * 1024)
