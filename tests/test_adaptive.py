"""AQE (runtime broadcast-vs-shuffle re-decision) + cost-based optimizer
(reference GpuOverrides.scala:4392-4452 AQE integration,
CostBasedOptimizer.scala:54)."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical.join import AdaptiveJoinExec
from spark_rapids_tpu.sql.planner import Planner


def _find(p, cls):
    if isinstance(p, cls):
        return p
    for c in p.children:
        f = _find(c, cls)
        if f is not None:
            return f
    return None


def _tables(rng, n=20000):
    left = pa.table({"k": rng.integers(0, 1000, n), "v": rng.random(n)})
    right = pa.table({"k": pa.array(np.arange(n) % 1000, type=pa.int64()),
                      "w": pa.array(rng.random(n))})
    return left, right


def test_aqe_switches_misestimated_join_to_broadcast(rng):
    """Static estimate (~320KB relation) refuses broadcast under a 50KB
    threshold, but the filtered build side is ~8 rows at runtime — AQE
    provably picks a different plan than the static planner."""
    left, right = _tables(rng)
    sess = srt.session(
        **{"spark.rapids.sql.autoBroadcastJoinThreshold": 50_000})
    l = sess.create_dataframe(left, num_partitions=4)
    r = sess.create_dataframe(right, num_partitions=4)
    rf = r.filter(r.k < 8).groupBy("k").agg(F.max(r.w).alias("w"))
    q = l.join(rf, on="k", how="inner").select(l.k, l.v, rf.w)

    phys = Planner(sess._conf).plan_for_collect(q._plan)
    aqe = _find(phys, AdaptiveJoinExec)
    assert aqe is not None and aqe.chosen_strategy is None
    out = phys.execute_all(sess._conf)
    assert aqe.chosen_strategy == "broadcast"
    exp = (left.to_pandas().merge(
        right.to_pandas().query("k < 8").groupby("k")
        .agg(w=("w", "max")).reset_index(), on="k"))
    assert sum(b.num_rows_int for b in out) == len(exp)


def test_aqe_keeps_shuffle_for_big_build(rng):
    left, right = _tables(rng)
    sess = srt.session(
        **{"spark.rapids.sql.autoBroadcastJoinThreshold": 50_000})
    l = sess.create_dataframe(left, num_partitions=4)
    r = sess.create_dataframe(right, num_partitions=4)
    q = l.join(r, on="k", how="inner").select(l.k, l.v, r.w)
    phys = Planner(sess._conf).plan_for_collect(q._plan)
    aqe = _find(phys, AdaptiveJoinExec)
    assert aqe is not None
    out = phys.execute_all(sess._conf)
    assert aqe.chosen_strategy == "shuffle"
    exp = left.to_pandas().merge(right.to_pandas(), on="k")
    assert sum(b.num_rows_int for b in out) == len(exp)


def test_aqe_disabled_plans_statically(rng):
    left, right = _tables(rng)
    sess = srt.session(**{
        "spark.sql.adaptive.enabled": False,
        "spark.rapids.sql.autoBroadcastJoinThreshold": 50_000})
    l = sess.create_dataframe(left, num_partitions=4)
    r = sess.create_dataframe(right, num_partitions=4)
    q = l.join(r, on="k", how="inner")
    phys = Planner(sess._conf).plan_for_collect(q._plan)
    assert _find(phys, AdaptiveJoinExec) is None


def test_aqe_result_equivalence(rng):
    """Same query, AQE on vs off — identical results."""
    left, right = _tables(rng, n=5000)
    res = {}
    for flag in (True, False):
        sess = srt.session(**{
            "spark.sql.adaptive.enabled": flag,
            "spark.rapids.sql.autoBroadcastJoinThreshold": 10_000})
        l = sess.create_dataframe(left, num_partitions=4)
        r = sess.create_dataframe(right, num_partitions=4)
        rf = r.filter(r.k < 50)
        got = (l.join(rf, on="k", how="left_semi")
               .orderBy("k", "v").collect().to_pandas())
        res[flag] = got
    assert np.array_equal(res[True]["k"], res[False]["k"])
    assert np.allclose(res[True]["v"], res[False]["v"])


def test_cost_optimizer_demotes_when_device_expensive():
    t = pa.table({"a": list(range(100)), "b": [float(i) for i in range(100)]})
    sess = srt.session(**{
        "spark.rapids.sql.optimizer.enabled": True,
        "spark.rapids.sql.optimizer.gpu.exec.default": 100.0})
    try:
        df = sess.create_dataframe(t)
        q = df.select((df.a + 1).alias("a1"))
        rep = sess.explain(q)
        assert "CpuProject" in rep and "cost-based optimizer" in rep
        out = q.collect().to_pylist()
        assert out[5]["a1"] == 6
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.enabled": False,
                       "spark.rapids.sql.optimizer.gpu.exec.default": 0.0001})


def test_cost_optimizer_keeps_device_when_cheap():
    t = pa.table({"a": list(range(100))})
    sess = srt.session(**{"spark.rapids.sql.optimizer.enabled": True})
    try:
        df = sess.create_dataframe(t)
        rep = sess.explain(df.select((df.a + 1).alias("a1")))
        assert "TpuProject" in rep
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.enabled": False})


def test_cost_optimizer_off_by_default():
    t = pa.table({"a": list(range(10))})
    sess = srt.session(**{
        "spark.rapids.sql.optimizer.gpu.exec.default": 100.0})
    try:
        df = sess.create_dataframe(t)
        rep = sess.explain(df.select((df.a + 1).alias("a1")))
        assert "TpuProject" in rep  # optimizer disabled -> no demotion
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.gpu.exec.default": 0.0001})


def test_cost_optimizer_unknown_stats_keep_device(tmp_path):
    """File scans have no row statistics; unknown stats must not demote
    (0 >= 0 would flip every file-based query to the host)."""
    import pyarrow.parquet as pq
    p = str(tmp_path / "t.parquet")
    pq.write_table(pa.table({"a": list(range(50))}), p)
    sess = srt.session(**{"spark.rapids.sql.optimizer.enabled": True})
    try:
        df = sess.read.parquet(p)
        rep = sess.explain(df.select((df.a + 1).alias("a1")))
        assert "CpuProject" not in rep
    finally:
        srt.session(**{"spark.rapids.sql.optimizer.enabled": False})
