"""Pipelined async execution (ISSUE 5): bit-parity across the scheduler /
prefetch / double-buffer matrix, exception propagation through prefetch
queues, tracer visibility (sem_wait + queue-depth), the CoalesceBatches
empty-partition contract, and plan-shape reversion when disabled."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.sql import functions as F


ROWS = 4000


@pytest.fixture(scope="module")
def tables():
    from spark_rapids_tpu.testing.scaletest import build_tables
    return build_tables(ROWS)


def _suite(sess, tables):
    """The TPC-H-ish multi-partition suite (the chaos soak's queries),
    canonicalized for exact comparison."""
    from spark_rapids_tpu.testing.pipeline import run_suite
    return run_suite(sess, tables)


def _sess(**overrides):
    base = {"spark.rapids.sql.autoBroadcastJoinThreshold": 1}
    base.update(overrides)
    return srt.session(conf=RapidsConf.get_global().copy(base))


def test_bit_parity_matrix(tables):
    """parallelism {1,4} x prefetch on/off x double-buffer on/off: every
    combination returns BIT-identical results to the serial engine."""
    serial = _suite(_sess(), tables)
    for par in (1, 4):
        for prefetch in (False, True):
            for dbl in (False, True):
                if par == 1 and not prefetch and not dbl:
                    continue  # that's the baseline itself
                sess = _sess(**{
                    "spark.rapids.tpu.task.parallelism": par,
                    "spark.rapids.tpu.prefetch.enabled": prefetch,
                    "spark.rapids.tpu.transfer.doubleBuffer.enabled": dbl,
                })
                got = _suite(sess, tables)
                for name, frame in serial.items():
                    pd.testing.assert_frame_equal(
                        got[name], frame, check_exact=True), \
                        (par, prefetch, dbl, name)


def test_prefetch_preserves_exception_type(tables):
    """A chaos shuffle.fetch fault below a prefetch queue surfaces to the
    caller as ShuffleFetchFailed — original type, no queue hang."""
    from spark_rapids_tpu.shuffle import ShuffleFetchFailed
    sess = _sess(**{
        "spark.rapids.tpu.task.parallelism": 4,
        "spark.rapids.tpu.prefetch.enabled": True,
        "spark.rapids.tpu.transfer.doubleBuffer.enabled": True,
        # fetches must actually traverse the fetch path, and every
        # traversal (including recompute re-reads) must fail fast
        "spark.rapids.shuffle.localDeviceResident.enabled": False,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.seed": 3,
        "spark.rapids.tpu.chaos.sites": "shuffle.fetch:1.0",
        "spark.rapids.tpu.shuffle.fetch.maxRetries": 1,
        "spark.rapids.tpu.shuffle.fetch.backoffMs": 1,
        "spark.rapids.tpu.shuffle.fetch.deadlineMs": 400,
    })
    df = sess.create_dataframe(tables["fact"], num_partitions=4)
    q = df.groupBy("q").agg(F.sum(F.col("v")).alias("sv")).orderBy("q")
    with pytest.raises(ShuffleFetchFailed):
        q.collect()


def test_injected_oom_recovers_under_pipeline(tables):
    """memory.oom.retry faults injected while the pipeline is on still
    ride the spill-and-retry protocol to a correct answer."""
    clean = _suite(_sess(), tables)
    sess = _sess(**{
        "spark.rapids.tpu.task.parallelism": 4,
        "spark.rapids.tpu.prefetch.enabled": True,
        "spark.rapids.tpu.chaos.enabled": True,
        "spark.rapids.tpu.chaos.seed": 5,
        "spark.rapids.tpu.chaos.sites": "memory.oom.retry:0.2",
    })
    got = _suite(sess, tables)
    for name, frame in clean.items():
        pd.testing.assert_frame_equal(got[name], frame, check_exact=True)


def test_tracer_sem_wait_and_queue_metrics(tables):
    """With the pipeline on and tracing enabled, the trace carries
    sem_wait spans (pool contention on the 1-permit semaphore) and
    queue-wait spans, and last_query_metrics carries the prefetch
    queue counters."""
    sess = _sess(**{
        "spark.rapids.tpu.task.parallelism": 4,
        "spark.rapids.sql.concurrentGpuTasks": 1,
        "spark.rapids.tpu.prefetch.enabled": True,
        "spark.rapids.tpu.profile.enabled": True,
    })
    df = sess.create_dataframe(tables["fact"], num_partitions=4)
    (df.groupBy("q").agg(F.sum(F.col("v")).alias("sv"))
       .orderBy("q").collect())
    m = sess.last_query_metrics
    assert m.get("prefetchBatches", 0) > 0, m
    assert "prefetchWaitMs" in m, m
    assert m.get("semaphoreWaitTime", 0) > 0, m
    events = sess._last_trace_events
    cats = {e["cat"] for e in events}
    assert "sem_wait" in cats, cats
    assert "queue" in cats, cats
    # queue spans carry the observed depth for the report
    qev = [e for e in events if e["cat"] == "queue"]
    assert all("depth" in e.get("args", {}) for e in qev), qev[:3]
    # the summary still builds with the new category present
    assert sess.last_query_trace_summary is not None


def test_double_buffer_transfer_accounting(tables):
    """Double-buffered transitions move the same bytes as the serial
    path — the stager changes WHEN transfers run, not what they carry."""
    q = lambda s: (s.create_dataframe(tables["fact"], num_partitions=2)
                   .filter(F.col("q") < 50)
                   .select("q", "v").collect())
    s1 = _sess()
    q(s1)
    m1 = s1.last_query_metrics
    s2 = _sess(**{"spark.rapids.tpu.transfer.doubleBuffer.enabled": True})
    q(s2)
    m2 = s2.last_query_metrics
    assert m2.get("d2h_bytes") == m1.get("d2h_bytes"), (m1, m2)
    assert m2.get("h2d_bytes") == m1.get("h2d_bytes"), (m1, m2)


def test_prefetch_off_keeps_plan_shape(tables):
    """Defaults revert to today's behavior: no AsyncPrefetch nodes in the
    plan unless the conf enables them."""
    sess_off = _sess()
    df = sess_off.create_dataframe(tables["fact"], num_partitions=2)
    q = df.groupBy("q").agg(F.count("*").alias("c"))
    assert "AsyncPrefetch" not in sess_off.physical_plan(q).tree_string()
    sess_on = _sess(**{"spark.rapids.tpu.prefetch.enabled": True})
    df2 = sess_on.create_dataframe(tables["fact"], num_partitions=2)
    q2 = df2.groupBy("q").agg(F.count("*").alias("c"))
    assert "AsyncPrefetch" in sess_on.physical_plan(q2).tree_string()


def test_prefetch_early_close_cancels_producer(tables):
    """An early-closed consumer (limit) cancels the producer thread
    instead of leaving it blocked on a full queue."""
    import threading
    before = {t.name for t in threading.enumerate()}
    sess = _sess(**{"spark.rapids.tpu.prefetch.enabled": True,
                    "spark.rapids.tpu.prefetch.depth": 1})
    df = sess.create_dataframe(tables["fact"], num_partitions=4)
    out = df.select("q", "v").limit(5).collect()
    assert out.num_rows == 5
    import time
    deadline = time.time() + 5.0
    while time.time() < deadline:
        leaked = [t.name for t in threading.enumerate()
                  if t.name.startswith("srt-prefetch")
                  and t.name not in before and t.is_alive()]
        if not leaked:
            break
        time.sleep(0.05)
    assert not leaked, leaked


def test_coalesce_empty_input_yields_schema_batch():
    """CoalesceBatchesExec over an all-empty partition emits ONE empty
    batch with the correct schema instead of a zero-batch partition
    (indistinguishable from a lost block post-PR4)."""
    from spark_rapids_tpu.sql.physical.base import TaskContext
    from spark_rapids_tpu.sql.physical.basic import InMemoryScanExec
    from spark_rapids_tpu.sql.physical.transitions import (
        CoalesceBatchesExec)
    from spark_rapids_tpu.sql.expressions.core import AttributeReference
    from spark_rapids_tpu import types as T
    empty = pa.table({"a": pa.array([], type=pa.int64()),
                      "b": pa.array([], type=pa.float64())})
    attrs = [AttributeReference("a", T.LONG, True),
             AttributeReference("b", T.DOUBLE, True)]
    scan = InMemoryScanExec(attrs, [empty])
    co = CoalesceBatchesExec(scan)
    tctx = TaskContext(0, RapidsConf.get_global())
    out = list(co.execute(0, tctx))
    assert len(out) == 1
    assert out[0].num_rows_int == 0
    assert list(out[0].names) == ["a", "b"]
    # non-empty inputs are untouched by the fix
    full = pa.table({"a": [1, 2], "b": [0.5, 0.25]})
    scan2 = InMemoryScanExec(attrs, [full])
    out2 = list(CoalesceBatchesExec(scan2).execute(0, tctx))
    assert sum(b.num_rows_int for b in out2) == 2


def test_parallel_scheduler_preserves_partition_order(tables):
    """Cross-partition result order matches the serial engine even when
    partitions complete out of order (execute_all assembles by pid)."""
    serial = _sess().create_dataframe(
        tables["fact"], num_partitions=4).select("k", "v").collect()
    par = _sess(**{"spark.rapids.tpu.task.parallelism": 4}) \
        .create_dataframe(tables["fact"], num_partitions=4) \
        .select("k", "v").collect()
    assert serial.equals(par)
