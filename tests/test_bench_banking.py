"""bench.py incremental shape banking — a watchdog cut or dead tunnel
must never again lose measured-but-unemitted numbers (ISSUE 2 satellite;
r4/r5 lost join/window/sort/resident-delta figures exactly this way)."""

import importlib.util
import json
import os

import pytest


@pytest.fixture()
def bench(monkeypatch):
    # bench.py parses sys.argv at import; give it a clean one
    monkeypatch.setattr("sys.argv", ["bench.py"])
    path = os.path.join(os.path.dirname(__file__), "..", "bench.py")
    spec = importlib.util.spec_from_file_location("_bench_under_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bank_partial_roundtrip_atomic(bench, tmp_path, monkeypatch):
    p = str(tmp_path / "partial.json")
    monkeypatch.setenv("BENCH_PARTIAL_PATH", p)
    bench._result.update(value=1234, rows=1000, platform="tpu")
    bench._result.setdefault("extra_metrics", {})["join_rows_per_sec"] = 99
    bench._bank_partial()
    rec = bench._read_partial(p)
    assert rec["value"] == 1234 and rec["rows"] == 1000
    assert rec["extra_metrics"]["join_rows_per_sec"] == 99
    assert rec["partial_banked_at"]
    # atomic: no tmp droppings
    assert os.listdir(tmp_path) == ["partial.json"]
    # incremental: a later shape overwrites with the richer snapshot
    bench._result["extra_metrics"]["window_rows_per_sec"] = 55
    bench._bank_partial()
    rec = bench._read_partial(p)
    assert rec["extra_metrics"]["window_rows_per_sec"] == 55


def test_bank_partial_disabled_without_path(bench, monkeypatch, tmp_path):
    monkeypatch.delenv("BENCH_PARTIAL_PATH", raising=False)
    bench._bank_partial()  # must be a no-op, not an error


def test_recover_partials_prefers_newest_and_grafts(bench, tmp_path):
    old = tmp_path / "partial_1_device1.json"
    new = tmp_path / "partial_1_device2.json"
    old.write_text(json.dumps({
        "value": 100, "rows": 10, "platform": "tpu",
        "extra_metrics": {"sort_rows_per_sec": 7,
                          "join_rows_per_sec": 1}}) + "\n")
    new.write_text(json.dumps({
        "value": 200, "rows": 20, "platform": "tpu",
        "extra_metrics": {"join_rows_per_sec": 2}}) + "\n")
    got = bench._recover_partials([str(old), str(new)])
    assert got["value"] == 200
    # newest wins per key; missing keys graft from older attempts
    assert got["extra_metrics"]["join_rows_per_sec"] == 2
    assert got["extra_metrics"]["sort_rows_per_sec"] == 7


def test_recover_partials_ignores_cpu_and_unfinished(bench, tmp_path):
    a = tmp_path / "partial_1_device1.json"
    a.write_text(json.dumps({"value": 5, "rows": 5, "platform": "cpu"}))
    b = tmp_path / "partial_1_device2.json"
    b.write_text(json.dumps({"platform": "tpu"}))  # nothing banked yet
    assert bench._recover_partials([str(a), str(b)]) is None
