"""Device string<->number/date/bool casts (CastStrings analog,
ops/cast_strings.py; reference com.nvidia.spark.rapids.jni.CastStrings
consumed by GpuCast.scala).  Spark non-ANSI semantics: bad input -> NULL,
overflow -> NULL, whitespace trimmed."""

import datetime as D
import math

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt


@pytest.fixture()
def sess():
    return srt.session()


def test_string_to_long_edge_cases(sess):
    df = sess.create_dataframe(pa.table({"s": [
        "123", " -456 ", "+7", "9223372036854775807",
        "9223372036854775808", "-9223372036854775808", "12.5", "abc",
        "", None, "  42  ", "-", "+", "1 2"]}))
    q = df.select(df.s.cast("bigint").alias("l"))
    assert "host" not in sess.explain(q)  # device kernel, no fallback
    got = q.collect()["l"].to_pylist()
    assert got == [123, -456, 7, 9223372036854775807, None,
                   -9223372036854775808, None, None, None, None, 42,
                   None, None, None]


def test_string_to_narrow_ints_overflow_nulls(sess):
    df = sess.create_dataframe(pa.table({"s": ["127", "128", "-128",
                                               "-129", "32768", "70000"]}))
    got_b = df.select(df.s.cast("tinyint").alias("v")).collect()["v"]
    assert got_b.to_pylist() == [127, None, -128, None, None, None]
    got_s = df.select(df.s.cast("smallint").alias("v")).collect()["v"]
    assert got_s.to_pylist() == [127, 128, -128, -129, None, None]


def test_string_to_double_forms(sess):
    df = sess.create_dataframe(pa.table({"s": [
        "1.5", "-2.25e2", "1e-3", "Infinity", "-inf", "NaN", ".5", "5.",
        "1e", "x", None, "  3.14  ", "1.2.3", "2E+4"]}))
    got = df.select(df.s.cast("double").alias("d")).collect()["d"] \
        .to_pylist()
    assert got[0] == 1.5 and got[1] == -225.0
    assert abs(got[2] - 1e-3) < 1e-18
    assert got[3] == math.inf and got[4] == -math.inf
    assert math.isnan(got[5])
    assert got[6] == 0.5 and got[7] == 5.0
    assert got[8] is None and got[9] is None and got[10] is None
    assert abs(got[11] - 3.14) < 1e-15
    assert got[12] is None and got[13] == 2e4


def test_string_to_date(sess):
    df = sess.create_dataframe(pa.table({"s": [
        "2024-02-29", "2023-02-29", "2024-1-5", "2024", "2024-06",
        "2024-13-01", "1969-12-31", "0001-01-01", "bad", None]}))
    got = df.select(df.s.cast("date").alias("d")).collect()["d"] \
        .to_pylist()
    assert got == [D.date(2024, 2, 29), None, D.date(2024, 1, 5),
                   D.date(2024, 1, 1), D.date(2024, 6, 1), None,
                   D.date(1969, 12, 31), D.date(1, 1, 1), None, None]


def test_string_to_boolean(sess):
    df = sess.create_dataframe(pa.table({"s": [
        "true", "F", "YES", "0", "1", "n", "maybe", " t ", None]}))
    got = df.select(df.s.cast("boolean").alias("b")).collect()["b"] \
        .to_pylist()
    assert got == [True, False, True, False, True, False, None, True,
                   None]


def test_integral_to_string_roundtrip(sess):
    vals = [0, 5, -17, 9223372036854775807, -9223372036854775808, None,
            1000000, -1]
    df = sess.create_dataframe(pa.table({
        "l": pa.array(vals, type=pa.int64())}))
    got = df.select(df.l.cast("string").alias("s")).collect()["s"] \
        .to_pylist()
    assert got == [None if v is None else str(v) for v in vals]
    # and parse back
    back = (df.select(df.l.cast("string").cast("bigint").alias("v"))
            .collect()["v"].to_pylist())
    assert back == vals


def test_bool_to_string(sess):
    df = sess.create_dataframe(pa.table({"b": [True, False, None]}))
    got = df.select(df.b.cast("string").alias("s")).collect()["s"] \
        .to_pylist()
    assert got == ["true", "false", None]


def test_long_parse_fuzz_vs_python(sess):
    rng = np.random.default_rng(9)
    vals = [str(int(v)) for v in
            rng.integers(-(2**62), 2**62, 3000)]
    extras = ["  %d " % v for v in rng.integers(-10**9, 10**9, 100)]
    df = sess.create_dataframe(pa.table({"s": vals + extras}))
    got = df.select(df.s.cast("bigint").alias("l")).collect()["l"] \
        .to_pylist()
    want = [int(s) for s in vals + extras]
    assert got == want


def test_double_parse_fuzz_vs_python(sess):
    rng = np.random.default_rng(10)
    nums = rng.random(2000) * 10.0 ** rng.integers(-10, 10, 2000)
    strs = [f"{v:.12g}" for v in nums]
    df = sess.create_dataframe(pa.table({"s": strs}))
    got = np.array(df.select(df.s.cast("double").alias("d"))
                   .collect()["d"].to_pylist())
    want = np.array([float(s) for s in strs])
    # positional digit accumulation: one rounding per digit, so allow
    # a few ULPs of drift against the exact libc parse
    rel = np.abs(got - want) / np.maximum(np.abs(want), 1e-300)
    assert rel.max() < 1e-13, rel.max()


def test_string_to_date_with_time_suffix(sess):
    """Spark's stringToDate accepts a trailing time section."""
    df = sess.create_dataframe(pa.table({"s": [
        "2020-03-18T12:03:17", "2020-03-18 12:03:17",
        "2020-03-18Tjunk", "2020-03-18"]}))
    got = df.select(df.s.cast("date").alias("d")).collect()["d"] \
        .to_pylist()
    assert got == [D.date(2020, 3, 18)] * 4


def test_string_to_long_leading_zeros(sess):
    df = sess.create_dataframe(pa.table({"s": [
        "00000000000000000001", "0000000000000000000000",
        "-000000000000000000009223372036854775807", "007"]}))
    got = df.select(df.s.cast("bigint").alias("l")).collect()["l"] \
        .to_pylist()
    assert got == [1, 0, -9223372036854775807, 7]


def test_string_to_timestamp(sess):
    df = sess.create_dataframe(pa.table({"s": [
        "2024-03-18 12:03:17", "2024-03-18T00:00:00.5",
        "2024-03-18T23:59:59.123456", "2024-03-18", "2024-03-18 25:00:00",
        "2024-03-18 12:03", "2024-03-18 12", "bad", None]}))
    q = df.select(df.s.cast("timestamp").alias("t"))
    assert "host" not in sess.explain(q)
    got = [None if v is None else v.replace(tzinfo=None)
           for v in q.collect()["t"].to_pylist()]  # engine runs UTC
    assert got[0] == D.datetime(2024, 3, 18, 12, 3, 17)
    assert got[1] == D.datetime(2024, 3, 18, 0, 0, 0, 500000)
    assert got[2] == D.datetime(2024, 3, 18, 23, 59, 59, 123456)
    assert got[3] == D.datetime(2024, 3, 18)  # bare date
    assert got[4] is None          # hour out of range
    assert got[5] == D.datetime(2024, 3, 18, 12, 3)
    assert got[6] is None          # bare hour not accepted (Spark)
    assert got[7] is None and got[8] is None


def test_string_to_timestamp_zones(sess):
    df = sess.create_dataframe(pa.table({"s": [
        "2024-03-18T12:03:17Z", "2024-03-18 12:03:17+01:00",
        "2024-03-18 12:03:17-05:30", "2024-03-18 12:03:17 UTC",
        "2024-03-18 12:03:17 GMT", "2024-03-18 12:03:17.",
        "2024-03-18 12:x5", "2024-03-18 12:03:17 Mars"]}))
    got = [None if v is None else v.replace(tzinfo=None)
           for v in df.select(df.s.cast("timestamp").alias("t"))
           .collect()["t"].to_pylist()]
    assert got[0] == D.datetime(2024, 3, 18, 12, 3, 17)
    assert got[1] == D.datetime(2024, 3, 18, 11, 3, 17)  # +01:00 -> UTC
    assert got[2] == D.datetime(2024, 3, 18, 17, 33, 17)
    assert got[3] == D.datetime(2024, 3, 18, 12, 3, 17)
    assert got[4] == D.datetime(2024, 3, 18, 12, 3, 17)
    assert got[5] == D.datetime(2024, 3, 18, 12, 3, 17)  # trailing dot
    assert got[6] is None   # malformed minute: NULL, never zero-filled
    assert got[7] is None   # named region zone: unsupported -> NULL


def test_string_to_decimal(sess):
    import decimal as DEC
    df = sess.create_dataframe(pa.table({"s": [
        "12.34", "-0.005", "1e2", "2.5e-1", "12.345", "12.344",
        "99999999.99", "100000000.00", "0", ".5", "abc", None,
        "  7.1  "]}))
    q = df.select(df.s.cast("decimal(10,2)").alias("d"))
    assert "host" not in sess.explain(q)
    got = q.collect()["d"].to_pylist()
    D2 = lambda s: DEC.Decimal(s)
    assert got == [D2("12.34"), D2("-0.01"), D2("100.00"), D2("0.25"),
                   D2("12.35"), D2("12.34"), D2("99999999.99"), None,
                   D2("0.00"), D2("0.50"), None, None, D2("7.10")]


def test_decimal_to_string(sess):
    import decimal as DEC
    df = sess.create_dataframe(pa.table({"d": pa.array(
        [DEC.Decimal("12.34"), DEC.Decimal("-0.05"), DEC.Decimal("0.00"),
         DEC.Decimal("-123456.78"), None], type=pa.decimal128(10, 2))}))
    q = df.select(df.d.cast("string").alias("s"))
    assert "host" not in sess.explain(q)
    got = q.collect()["s"].to_pylist()
    assert got == ["12.34", "-0.05", "0.00", "-123456.78", None]


def test_decimal_string_roundtrip_fuzz(sess):
    import decimal as DEC
    rng = np.random.default_rng(12)
    vals = [DEC.Decimal(int(v)) / 100 for v in
            rng.integers(-10**12, 10**12, 2000)]
    df = sess.create_dataframe(pa.table({"d": pa.array(
        vals, type=pa.decimal128(14, 2))}))
    back = (df.select(df.d.cast("string").cast("decimal(14,2)").alias("r"))
            .collect()["r"].to_pylist())
    assert back == vals


def test_host_and_device_string_casts_agree(sess):
    """The numpy host path runs the SAME byte-matrix parsers, so host
    fallback and device placement return identical rows (the reference's
    CPU/GPU-identical contract)."""
    strs = ["2024-03-18T12:03", "2024-03-18 12:03:17+01:00", "12.5",
            " -7 ", "1e3", "9223372036854775808", "2024-02-30", "t",
            None, "  3.25  "]
    t = pa.table({"s": strs})

    def run(s):
        df = s.create_dataframe(t)
        return (df.select(df.s.cast("timestamp").alias("ts"),
                          df.s.cast("bigint").alias("l"),
                          df.s.cast("double").alias("d"),
                          df.s.cast("boolean").alias("b"))
                .collect().to_pylist())
    try:
        dev = run(srt.session())
        host = run(srt.session(**{"spark.rapids.sql.enabled": False}))
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})
    assert dev == host


def test_timestamp_cast_non_utc_session_falls_back(sess):
    s = srt.session(**{"spark.sql.session.timeZone": "America/New_York"})
    try:
        df = s.create_dataframe(pa.table({"s": ["2024-03-18 12:00:00"]}))
        rep = s.explain(df.select(df.s.cast("timestamp").alias("t")))
        assert "timezone" in rep
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})
