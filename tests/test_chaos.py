"""Chaos harness tests — seeded schedule determinism, per-site arming,
zero state when off, OOM-site unification with memory/retry.py, conf
surface, tracer integration, and a small end-to-end soak (bit-identical
results under injected faults)."""

import socket
import time

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.robustness import (CHAOS, InjectedFault, arm_chaos,
                                         disarm_chaos, fault_type,
                                         get_registry, injected_counts,
                                         maybe_inject, should_fire)
from spark_rapids_tpu.robustness.faults import (ChaosRegistry, _decision,
                                                apply_conf)


@pytest.fixture(autouse=True)
def _always_disarm():
    yield
    disarm_chaos()


def _schedule(reg: ChaosRegistry, site: str, n: int):
    return [reg.decide(site) for _ in range(n)]


def test_seeded_schedule_deterministic():
    a = ChaosRegistry(seed=42, sites="shuffle.fetch:0.3", probability=0.3)
    b = ChaosRegistry(seed=42, sites="shuffle.fetch:0.3", probability=0.3)
    sa = _schedule(a, "shuffle.fetch", 300)
    sb = _schedule(b, "shuffle.fetch", 300)
    assert sa == sb
    assert any(sa)              # 300 draws at p=0.3 inject
    c = ChaosRegistry(seed=43, sites="shuffle.fetch:0.3")
    assert _schedule(c, "shuffle.fetch", 300) != sa


def test_schedule_independent_across_sites():
    """Arming/traversing site A must not shift site B's schedule: each
    site consumes its own ordinal stream."""
    a = ChaosRegistry(seed=7, sites="shuffle.fetch:0.4,spill.disk_read:0.4")
    interleaved = []
    for i in range(200):
        a.decide("spill.disk_read")        # extra traversals of B
        interleaved.append(a.decide("shuffle.fetch"))
    b = ChaosRegistry(seed=7, sites="shuffle.fetch:0.4")
    assert _schedule(b, "shuffle.fetch", 200) == interleaved


def test_per_site_arming():
    arm_chaos(seed=1, sites="spill.disk_read:1.0")
    # unarmed site: never fires, consumes no ordinals
    for _ in range(50):
        maybe_inject("shuffle.fetch", exc=ConnectionError)
    assert get_registry().hits.get("shuffle.fetch", 0) == 0
    # armed at p=1.0: always fires, with the site-appropriate type
    with pytest.raises(OSError) as ei:
        maybe_inject("spill.disk_read", exc=OSError)
    assert isinstance(ei.value, InjectedFault)
    assert injected_counts() == {"spill.disk_read": 1}


def test_zero_state_when_off():
    assert CHAOS["on"] is False
    assert get_registry() is None
    # the disabled chokepoint is a no-op (one dict lookup)
    for _ in range(100):
        maybe_inject("shuffle.fetch", exc=ConnectionError)
        assert not should_fire("shuffle.block.lost")
    assert injected_counts() == {}


def test_injected_fault_type_mixes_in():
    t = fault_type(ConnectionError)
    e = t("boom")
    assert isinstance(e, ConnectionError) and isinstance(e, InjectedFault)
    # cached: same class object per base
    assert fault_type(ConnectionError) is t


def test_injected_fault_is_never_fatal():
    from spark_rapids_tpu.memory.fatal import is_fatal_device_error
    assert not is_fatal_device_error(fault_type(RuntimeError)("injected"))


def test_oom_site_unification():
    """Arming memory.oom.retry through the chaos surface injects a
    RetryOOM that rides the standard spill-and-retry protocol — the old
    count-based hook's behavior from the unified conf."""
    from spark_rapids_tpu.columnar.convert import arrow_to_device
    from spark_rapids_tpu.memory.retry import with_retry
    from spark_rapids_tpu.memory.spill import (BufferCatalog,
                                               SpillableColumnarBatch)
    # a seed whose schedule injects the FIRST attempt and passes the
    # retry (searched deterministically, not hardcoded magic)
    seed = next(s for s in range(1000)
                if _decision(s, "memory.oom.retry", 0) < 0.5
                and _decision(s, "memory.oom.retry", 1) >= 0.5)
    arm_chaos(seed=seed, sites="memory.oom.retry:0.5")
    cat = BufferCatalog.get()
    spills0 = cat.spill_count
    sb = SpillableColumnarBatch.create(
        arrow_to_device(pa.table({"x": np.arange(100)})))
    out = list(with_retry([sb], lambda s: s.get().num_rows_int))
    assert out == [100]
    assert injected_counts() == {"memory.oom.retry": 1}
    assert cat.spill_count >= spills0     # the RetryOOM spilled + retried


def test_conf_surface_arms_and_disarms():
    conf = RapidsConf({"spark.rapids.tpu.chaos.enabled": True,
                       "spark.rapids.tpu.chaos.seed": 5,
                       "spark.rapids.tpu.chaos.sites": "shuffle.fetch:0.2",
                       "spark.rapids.tpu.chaos.probability": 0.9})
    apply_conf(conf)
    reg = get_registry()
    assert CHAOS["on"] and reg is not None and reg.seed == 5
    assert reg.site_probability("shuffle.fetch") == 0.2
    assert reg.site_probability("spill.disk_read") == 0.0
    # a conf with chaos disabled undoes the conf-driven arming...
    apply_conf(RapidsConf())
    assert not CHAOS["on"]
    # ...but never a manual (test-driven) arming
    arm_chaos(seed=1, sites="shuffle.fetch")
    apply_conf(RapidsConf())
    assert CHAOS["on"]


def test_fault_spans_reach_tracer():
    from spark_rapids_tpu.observability import tracer as OT
    from spark_rapids_tpu.shuffle.transport import (BlockId, LocalTransport,
                                                    PeerInfo,
                                                    ShuffleFetchFailed)
    arm_chaos(seed=0, sites="shuffle.fetch:1.0")
    OT.get_tracer().reset()
    prev = OT.TRACING["on"]
    OT.TRACING["on"] = True
    try:
        with pytest.raises(ShuffleFetchFailed):
            LocalTransport().fetch(PeerInfo("e", "local"), BlockId(1, 0, 0))
    finally:
        OT.TRACING["on"] = prev
    evs = [e for e in OT.get_tracer().snapshot() if e["cat"] == "fault"]
    assert evs and evs[0]["name"] == "fault.shuffle.fetch"
    assert OT.get_tracer().counters.get("faultsInjected") == 1


def test_metrics_visible_and_zero_without_chaos():
    sess = srt.session()
    t = sess.create_dataframe(pa.table({"k": [1, 2, 1]}), num_partitions=2)
    t.groupBy("k").count().collect()
    m = sess.last_query_metrics
    for key in ("faultsInjected", "shuffleFetchRetries",
                "shuffleBlocksRecomputed", "peersBlacklisted"):
        assert m[key] == 0


def test_chaos_soak_smoke():
    """Small end-to-end soak: seeded faults on the shuffle fetch path,
    results bit-identical to the fault-free run, counters visible in
    last_query_metrics (the full soak runs in CI with all sites)."""
    from spark_rapids_tpu.testing.chaos import run_soak
    report = run_soak(rows=4000, seed=11, queries=["agg", "join_agg"],
                      strict=False)
    assert report["bit_identical"]
    assert report["counters"]["faultsInjected"] > 0
    assert report["counters"]["shuffleFetchRetries"] > 0
