"""collect_list / collect_set / approx_percentile — shuffle-complete
aggregates (reference cuDF collect aggregations via
AggregateFunctions.scala and GpuApproximatePercentile)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def test_collect_list_basic(sess):
    df = sess.create_dataframe(pa.table({
        "k": ["a", "a", "a", "b", "b"],
        "v": [3.0, 1.0, 3.0, 5.0, 4.0]}), num_partitions=2)
    out = (df.groupBy("k").agg(F.collect_list(df.v).alias("l"))
           .orderBy("k").collect().to_pylist())
    assert sorted(out[0]["l"]) == [1.0, 3.0, 3.0]
    assert sorted(out[1]["l"]) == [4.0, 5.0]


def test_collect_set_dedups(sess):
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 1, 1, 2],
        "v": [7, 7, 8, 7, 9]}), num_partitions=3)
    out = (df.groupBy("k").agg(F.collect_set(df.v).alias("s"))
           .orderBy("k").collect().to_pylist())
    assert sorted(out[0]["s"]) == [7, 8]
    assert out[1]["s"] == [9]


def test_collect_skips_nulls(sess):
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 1],
        "v": pa.array([1.0, None, 2.0], type=pa.float64())}))
    out = df.groupBy("k").agg(
        F.collect_list(df.v).alias("l"),
        F.collect_set(df.v).alias("s")).collect().to_pylist()
    assert sorted(out[0]["l"]) == [1.0, 2.0]
    assert sorted(out[0]["s"]) == [1.0, 2.0]


def test_collect_strings(sess):
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 2], "v": ["xx", "yy", "zz"]}), num_partitions=2)
    out = (df.groupBy("k").agg(F.collect_set(df.v).alias("s"))
           .orderBy("k").collect().to_pylist())
    assert sorted(out[0]["s"]) == ["xx", "yy"]
    assert out[1]["s"] == ["zz"]


def test_collect_at_scale_vs_pandas(sess):
    rng = np.random.default_rng(3)
    n = 20_000
    t = pa.table({"g": rng.integers(0, 100, n),
                  "v": rng.integers(0, 50, n)})
    df = sess.create_dataframe(t, num_partitions=4)
    out = (df.groupBy("g").agg(F.collect_list(df.v).alias("l"),
                               F.collect_set(df.v).alias("s"))
           .collect().to_pandas().set_index("g"))
    pdf = t.to_pandas()
    want_counts = pdf.groupby("g")["v"].count()
    want_sets = pdf.groupby("g")["v"].agg(lambda s: sorted(set(s)))
    for g in want_counts.index:
        assert len(out.loc[g, "l"]) == want_counts[g]
        assert sorted(out.loc[g, "s"]) == list(want_sets[g])
        # multiset equality for the list
        assert sorted(out.loc[g, "l"]) == sorted(
            pdf[pdf.g == g]["v"].tolist())


def test_global_collect_list(sess):
    df = sess.create_dataframe(pa.table({"v": [1, 2, 3]}),
                               num_partitions=2)
    out = df.agg(F.collect_list(df.v).alias("l")).collect().to_pylist()
    assert sorted(out[0]["l"]) == [1, 2, 3]


def test_percentile_approx_scalar_and_array(sess):
    rng = np.random.default_rng(11)
    n = 5_000
    t = pa.table({"g": rng.integers(0, 8, n), "v": rng.random(n)})
    df = sess.create_dataframe(t, num_partitions=3)
    out = (df.groupBy("g")
           .agg(F.percentile_approx(df.v, 0.5).alias("p50"),
                F.percentile_approx(df.v, [0.25, 0.75]).alias("pq"))
           .collect().to_pandas().set_index("g"))
    pdf = t.to_pandas()
    for g, grp in pdf.groupby("g"):
        vals = np.sort(grp["v"].values)
        cnt = len(vals)
        def spark_pct(p):
            return vals[max(int(np.ceil(p * cnt)) - 1, 0)]
        assert out.loc[g, "p50"] == spark_pct(0.5)
        assert list(out.loc[g, "pq"]) == [spark_pct(0.25), spark_pct(0.75)]


def test_percentile_mixed_with_builtin_aggs(sess):
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 2, 2, 2], "v": [1.0, 3.0, 10.0, 20.0, 30.0]}),
        num_partitions=2)
    out = (df.groupBy("k")
           .agg(F.sum(F.col("v")).alias("s"),
                F.percentile_approx(df.v, 0.5).alias("p"),
                F.collect_list(df.v).alias("l"))
           .orderBy("k").collect().to_pylist())
    assert out[0]["s"] == 4.0 and out[0]["p"] == 1.0
    assert out[1]["s"] == 60.0 and out[1]["p"] == 20.0
    assert sorted(out[1]["l"]) == [10.0, 20.0, 30.0]


def test_collect_cpu_oracle_agrees(sess):
    """Device path vs the independent numpy engine."""
    rng = np.random.default_rng(5)
    n = 2_000
    t = pa.table({"g": rng.integers(0, 20, n),
                  "v": rng.integers(-100, 100, n)})
    q = lambda s: (s.create_dataframe(t, num_partitions=2).groupBy("g")
                   .agg(F.collect_set(F.col("v")).alias("s"),
                        F.percentile_approx(F.col("v"), 0.5).alias("p"))
                   .collect().to_pandas().set_index("g").sort_index())
    try:
        a = q(srt.session())
        b = q(srt.session(**{"spark.rapids.sql.enabled": False}))
    finally:
        srt.session(**{"spark.rapids.sql.enabled": True})
    assert (a["p"].values == b["p"].values).all()
    for g in a.index:
        assert sorted(a.loc[g, "s"]) == sorted(b.loc[g, "s"])


def test_global_collect_over_empty_input(sess):
    df = sess.create_dataframe(pa.table({
        "v": pa.array([], type=pa.float64())}))
    out = df.agg(F.collect_list(df.v).alias("l"),
                 F.percentile_approx(df.v, 0.5).alias("p")).collect()
    assert out.num_rows == 1
    assert out["l"].to_pylist() == [[]]
    assert out["p"].to_pylist() == [None]


def test_grouped_collect_over_empty_input(sess):
    df = sess.create_dataframe(pa.table({
        "k": pa.array([], type=pa.int64()),
        "v": pa.array([], type=pa.float64())}))
    out = df.groupBy("k").agg(F.collect_list(df.v).alias("l")).collect()
    assert out.num_rows == 0


def test_percentile_non_numeric_falls_back_to_host(sess):
    df = sess.create_dataframe(pa.table({"k": [1], "s": ["x"]}))
    q = df.groupBy("k").agg(
        F.percentile_approx(F.col("s"), 0.5).alias("p"))
    assert "CpuHashAggregate" in sess.explain(q)
    # and the host engine still answers (single string = its own median)
    assert q.collect().to_pylist() == [{"k": 1, "p": "x"}]


def test_compound_agg_expression_global(sess):
    """Arithmetic AROUND aggregates (sum(a)*100/sum(b)) must evaluate the
    whole tree, not just the first aggregate (TPC-H q14 shape)."""
    import numpy as np
    rng = np.random.default_rng(11)
    n = 5000
    t = pa.table({"a": rng.random(n), "b": rng.random(n) + 0.5})
    df = sess.create_dataframe(t)
    got = df.agg((F.sum(df.a) * 100.0 / F.sum(df.b)).alias("r")) \
            .collect().to_pylist()[0]["r"]
    pdf = t.to_pandas()
    assert np.isclose(got, 100.0 * pdf.a.sum() / pdf.b.sum())


def test_compound_agg_expression_grouped_with_key(sess):
    """Compound outputs may also reference grouping expressions."""
    import numpy as np
    rng = np.random.default_rng(12)
    n = 4000
    t = pa.table({"k": rng.integers(0, 6, n), "v": rng.random(n)})
    df = sess.create_dataframe(t)
    got = (df.groupBy("k")
           .agg((F.sum(df.v) / F.count("*")).alias("mean_v"),
                (F.max(df.v) - F.min(df.v)).alias("range_v"),
                (F.col("k") * 1000 + F.count("*")).alias("k_tag"))
           .orderBy("k").collect().to_pandas())
    pdf = t.to_pandas().groupby("k").agg(
        mean_v=("v", "mean"), range_v=("v", lambda s: s.max() - s.min()),
        c=("v", "size")).reset_index()
    assert np.allclose(got["mean_v"], pdf["mean_v"])
    assert np.allclose(got["range_v"], pdf["range_v"])
    assert np.array_equal(got["k_tag"], pdf["k"] * 1000 + pdf["c"])


def test_compound_agg_mixed_with_collect_list(sess):
    """Compound outputs must stay correct when the node also carries a
    shuffle-complete aggregate (routes through _execute_special)."""
    import numpy as np
    rng = np.random.default_rng(13)
    n = 2000
    t = pa.table({"k": rng.integers(0, 4, n), "v": rng.random(n)})
    df = sess.create_dataframe(t)
    got = (df.groupBy("k")
           .agg(F.collect_list(df.v).alias("lst"),
                (F.sum(df.v) / F.count("*")).alias("mean_v"))
           .orderBy("k").collect().to_pandas())
    pdf = t.to_pandas().groupby("k").agg(
        mean_v=("v", "mean"), c=("v", "size")).reset_index()
    assert np.allclose(got["mean_v"], pdf["mean_v"])
    assert [len(x) for x in got["lst"]] == list(pdf["c"])


def test_mixed_distinct_with_duplicate_regular_aggs(sess):
    """Duplicate regular aggregates dedup to ONE slot set; the mixed
    DISTINCT planner path must map both outputs to the same slot range."""
    import numpy as np
    rng = np.random.default_rng(31)
    n = 6000
    t = pa.table({"k": rng.integers(0, 5, n), "v": rng.integers(0, 50, n),
                  "w": rng.random(n)})
    df = sess.create_dataframe(t, num_partitions=4)
    got = (df.groupBy("k")
           .agg(F.countDistinct("v").alias("d"), F.sum(df.w).alias("a"),
                F.sum(df.w).alias("b"), F.max(df.w).alias("m"))
           .orderBy("k").collect().to_pandas())
    pdf = t.to_pandas().groupby("k").agg(
        d=("v", "nunique"), a=("w", "sum"), m=("w", "max")).reset_index()
    assert np.array_equal(got["d"], pdf["d"])
    assert np.allclose(got["a"], pdf["a"])
    assert np.allclose(got["b"], pdf["a"])
    assert np.allclose(got["m"], pdf["m"])


def test_multi_set_distinct_expand_plan(sess):
    """DISTINCT aggregates over SEVERAL child sets plus plain aggregates:
    Spark's RewriteDistinctAggregates Expand construction
    (planner._plan_expand_distinct; reference GpuExpandExec.scala)."""
    import numpy as np
    rng = np.random.default_rng(7)
    n = 8000
    t = pa.table({
        "k": pa.array(rng.integers(0, 6, n), pa.int64()),
        "a": pa.array([None if x % 9 == 0 else int(x)
                       for x in rng.integers(0, 60, n)], pa.int64()),
        "b": pa.array(rng.integers(0, 25, n), pa.int64()),
        "w": pa.array(rng.random(n)),
    })
    df = sess.create_dataframe(t, num_partitions=4)
    got = (df.groupBy("k")
           .agg(F.countDistinct("a").alias("ca"),
                F.countDistinct("b").alias("cb"),
                F.sum_distinct(F.col("b")).alias("sb"),
                F.sum(df.w).alias("sw"),
                F.count("*").alias("n"))
           .orderBy("k").collect().to_pandas())
    pdf = t.to_pandas().groupby("k").agg(
        ca=("a", "nunique"), cb=("b", "nunique"),
        sb=("b", lambda s: s.dropna().unique().sum()),
        sw=("w", "sum"), n=("k", "size")).reset_index()
    assert np.array_equal(got["ca"], pdf["ca"])
    assert np.array_equal(got["cb"], pdf["cb"])
    assert np.array_equal(got["sb"], pdf["sb"])
    assert np.allclose(got["sw"], pdf["sw"])
    assert np.array_equal(got["n"], pdf["n"])


def test_multi_set_distinct_global_and_sql(sess):
    """Global (ungrouped) multi-set DISTINCT and the SQL surface."""
    import numpy as np
    rng = np.random.default_rng(8)
    n = 3000
    t = pa.table({
        "a": pa.array(rng.integers(0, 30, n), pa.int64()),
        "s": pa.array([f"x{v}" for v in rng.integers(0, 11, n)]),
        "w": pa.array(rng.random(n)),
    })
    sess.create_dataframe(t, num_partitions=3).createOrReplaceTempView(
        "md_t")
    got = sess.sql(
        "SELECT count(DISTINCT a) ca, count(DISTINCT s) cs, "
        "avg(w) aw, count(*) n FROM md_t").collect().to_pandas()
    pdf = t.to_pandas()
    assert int(got["ca"][0]) == pdf.a.nunique()
    assert int(got["cs"][0]) == pdf.s.nunique()
    assert abs(float(got["aw"][0]) - pdf.w.mean()) < 1e-9
    assert int(got["n"][0]) == n
