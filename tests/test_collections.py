"""Collection/struct/map expression + explode tests — reference coverage
model: integration_tests array_test.py / map_test.py / struct_test.py /
collection_ops_test.py / generate_expr_test.py."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def arr_df(sess):
    t = pa.table({
        "u": pa.array(range(6), type=pa.int64()),
        "a": pa.array([[1, 2, 3], [], None, [4, 4, 5], [None, 7], [9]],
                      type=pa.list_(pa.int64())),
        "b": pa.array([[3, 9], [1], [2], [4], [7, 8], []],
                      type=pa.list_(pa.int64())),
        "s": pa.array([["x", "yy"], ["zzz"], None, [], ["x", "x"], ["q"]],
                      type=pa.list_(pa.string())),
        "m": pa.array([{"k1": 1, "k2": 2}, {}, None, {"k3": 3},
                       {"k1": 9}, {"z": 0}],
                      type=pa.map_(pa.string(), pa.int64())),
        "v": pa.array([10, 20, 30, 40, 50, 60], type=pa.int64()),
    })
    return sess.create_dataframe(t), t


def run_both(df, sort_col="u"):
    sess = df._session
    a = df.collect()
    sess.conf.set("spark.rapids.sql.enabled", False)
    try:
        b = df.collect()
    finally:
        sess.conf.set("spark.rapids.sql.enabled", True)
    assert a.to_pylist() == b.to_pylist(), "device/host mismatch"
    return a


def test_size_and_item_access(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u, F.size(df.a).alias("sz"),
        F.get(df.a, F.lit(1)).alias("i1"),
        F.element_at(df.a, 1).alias("e1"),
        F.element_at(df.a, -1).alias("em1"),
        F.element_at(df.m, "k1").alias("mk"),
    )).to_pylist()
    assert [r["sz"] for r in out] == [3, 0, -1, 3, 2, 1]
    assert [r["i1"] for r in out] == [2, None, None, 4, 7, None]
    assert [r["e1"] for r in out] == [1, None, None, 4, None, 9]
    assert [r["em1"] for r in out] == [3, None, None, 5, 7, 9]
    assert [r["mk"] for r in out] == [1, None, None, None, 9, None]


def test_contains_position_minmax(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u, F.array_contains(df.a, F.lit(4)).alias("c4"),
        F.array_position(df.a, F.lit(4)).alias("p4"),
        F.array_min(df.a).alias("mn"), F.array_max(df.a).alias("mx"),
    )).to_pylist()
    # a: [1,2,3], [], None, [4,4,5], [None,7], [9]
    assert [r["c4"] for r in out] == [False, False, None, True, None, False]
    assert [r["p4"] for r in out] == [0, 0, None, 1, 0, 0]
    assert [r["mn"] for r in out] == [1, None, None, 4, 7, 9]
    assert [r["mx"] for r in out] == [3, None, None, 5, 7, 9]


def test_string_array_contains(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u, F.array_contains(df.s, F.lit("x")).alias("cx"))).to_pylist()
    assert [r["cx"] for r in out] == [True, False, None, False, True, False]


def test_sort_distinct_remove(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u, F.sort_array(df.a).alias("sorted"),
        F.sort_array(df.a, asc=False).alias("rsorted"),
        F.array_distinct(df.a).alias("dist"),
        F.array_remove(df.a, F.lit(4)).alias("rm4"),
    )).to_pylist()
    assert [r["sorted"] for r in out] == [
        [1, 2, 3], [], None, [4, 4, 5], [None, 7], [9]]
    assert [r["rsorted"] for r in out] == [
        [3, 2, 1], [], None, [5, 4, 4], [7, None], [9]]
    assert [r["dist"] for r in out] == [
        [1, 2, 3], [], None, [4, 5], [None, 7], [9]]
    assert [r["rm4"] for r in out] == [
        [1, 2, 3], [], None, [5], [None, 7], [9]]


def test_set_ops(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u,
        F.arrays_overlap(df.a, df.b).alias("ov"),
        F.array_intersect(df.a, df.b).alias("ix"),
        F.array_except(df.a, df.b).alias("ex"),
        F.array_union(df.a, df.b).alias("un"),
    )).to_pylist()
    # a: [1,2,3] b: [3,9] -> overlap True, intersect [3], except [1,2]
    assert out[0]["ov"] is True
    assert out[0]["ix"] == [3]
    assert out[0]["ex"] == [1, 2]
    assert out[0]["un"] == [1, 2, 3, 9]
    assert out[1]["ov"] is False and out[1]["ix"] == [] \
        and out[1]["un"] == [1]
    assert out[3]["ix"] == [4] and out[3]["ex"] == [5] \
        and out[3]["un"] == [4, 5]


def test_create_repeat_slice_reverse_zip(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u,
        F.array(df.v, df.v + 1, F.lit(0)).alias("mk"),
        F.array_repeat(df.v, 3).alias("rep"),
        F.slice(df.a, 1, 2).alias("sl"),
        F.slice(df.a, -2, 2).alias("sl2"),
    )).to_pylist()
    assert out[0]["mk"] == [10, 11, 0]
    assert out[2]["rep"] == [30, 30, 30]
    assert out[0]["sl"] == [1, 2]
    assert out[3]["sl2"] == [4, 5]


def test_sequence(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u, F.sequence(F.lit(1), df.u + 1).alias("sq"))).to_pylist()
    assert [r["sq"] for r in out] == [
        list(range(1, k + 2)) for k in range(6)]


def test_struct_ops(sess):
    df, t = arr_df(sess)
    q = df.select(df.u, F.struct(df.u, df.v).alias("st"))
    out = run_both(q).to_pylist()
    assert out[0]["st"] == {"u": 0, "v": 10}
    q2 = q.select(q.u, q.st.getField("v").alias("vv")) \
        if hasattr(q.st, "getField") else None
    # GetStructField via expression API
    from spark_rapids_tpu.sql.expressions.collections import GetStructField
    from spark_rapids_tpu.sql.dataframe import Column
    q3 = q.select(q.u, Column(GetStructField(q.st.expr, 1, "v")).alias("vv"))
    out3 = run_both(q3).to_pylist()
    assert [r["vv"] for r in out3] == [10, 20, 30, 40, 50, 60]


def test_map_ops(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u, F.map_keys(df.m).alias("mk"), F.map_values(df.m).alias("mv"),
        F.map_entries(df.m).alias("me"),
        F.create_map("a", df.v, "b", df.u).alias("cm"),
    )).to_pylist()
    assert out[0]["mk"] == ["k1", "k2"]
    assert out[0]["mv"] == [1, 2]
    assert out[0]["me"] == [{"key": "k1", "value": 1},
                            {"key": "k2", "value": 2}]
    assert dict(out[0]["cm"]) == {"a": 10, "b": 0}
    assert out[2]["mk"] is None


def test_higher_order_functions(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u,
        F.transform(df.a, lambda x: x * 2).alias("x2"),
        F.transform(df.a, lambda x, i: x + i).alias("xi"),
        F.filter(df.a, lambda x: x > 2).alias("gt2"),
        F.exists(df.a, lambda x: x == 4).alias("h4"),
        F.forall(df.a, lambda x: x < 100).alias("all"),
    )).to_pylist()
    assert [r["x2"] for r in out] == [
        [2, 4, 6], [], None, [8, 8, 10], [None, 14], [18]]
    assert [r["xi"] for r in out] == [
        [1, 3, 5], [], None, [4, 5, 7], [None, 8], [9]]
    assert [r["gt2"] for r in out] == [[3], [], None, [4, 4, 5], [7], [9]]
    assert [r["h4"] for r in out] == [False, False, None, True, None, False]
    # forall: null element -> null predicate -> null result (3-valued logic)
    assert [r["all"] for r in out] == [True, True, None, True, None, True]


def test_map_higher_order(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u,
        F.transform_values(df.m, lambda k, v: v * 10).alias("tv"),
        F.map_filter(df.m, lambda k, v: v > 1).alias("mf"),
    )).to_pylist()
    assert dict(out[0]["tv"]) == {"k1": 10, "k2": 20}
    assert dict(out[0]["mf"]) == {"k2": 2}
    assert out[2]["tv"] is None


def test_explode(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(df.u, F.explode(df.a).alias("e")),
                   sort_col=None).to_pylist()
    exp = []
    for u, arr in zip(t.column("u").to_pylist(), t.column("a").to_pylist()):
        for x in (arr or []):
            exp.append({"u": u, "e": x})
    assert out == exp


def test_explode_outer_and_pos(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(df.u, F.explode_outer(df.a).alias("e"))
                   ).to_pylist()
    exp = []
    for u, arr in zip(t.column("u").to_pylist(), t.column("a").to_pylist()):
        if not arr:
            exp.append({"u": u, "e": None})
        else:
            for x in arr:
                exp.append({"u": u, "e": x})
    assert out == exp

    out2 = run_both(df.select(df.u, F.posexplode(df.a))).to_pylist()
    exp2 = []
    for u, arr in zip(t.column("u").to_pylist(), t.column("a").to_pylist()):
        for i, x in enumerate(arr or []):
            exp2.append({"u": u, "pos": i, "col": x})
    assert out2 == exp2


def test_explode_map(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(df.u, F.explode(df.m))).to_pylist()
    exp = []
    for u, m in zip(t.column("u").to_pylist(), t.column("m").to_pylist()):
        for k, v in (m or []):
            exp.append({"u": u, "key": k, "value": v})
    assert out == exp


def test_explode_then_aggregate(sess):
    """Pipeline: explode -> groupBy, validating downstream composition."""
    df, t = arr_df(sess)
    q = (df.select(df.u, F.explode(df.a).alias("e"))
         .groupBy("e").agg(F.count("*").alias("c")))
    out = {r["e"]: r["c"] for r in run_both(q, sort_col=None).to_pylist()}
    flat = [x for arr in t.column("a").to_pylist() if arr for x in arr]
    exp = pd.Series([x for x in flat if x is not None]).value_counts()
    for k, v in exp.items():
        assert out[k] == v
    if None in flat:
        assert out.get(None) == flat.count(None)


def test_lambda_outer_column_reference(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u, F.transform(df.a, lambda x: x + df.v).alias("xv"))).to_pylist()
    assert out[0]["xv"] == [11, 12, 13]
    assert out[3]["xv"] == [44, 44, 45]
    assert out[4]["xv"] == [None, 57]


def test_posexplode_outer_null_pos(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(df.u, F.posexplode_outer(df.a))).to_pylist()
    for r in out:
        if r["u"] in (1, 2):  # empty and null arrays
            assert r["pos"] is None and r["col"] is None


def test_sort_array_int64_precision(sess):
    big = 9007199254740993  # 2**53 + 1: collapses under float64
    t = pa.table({"u": [0], "a": pa.array([[big, big - 1]],
                                          type=pa.list_(pa.int64()))})
    df = sess.create_dataframe(t)
    out = run_both(df.select(df.u, F.sort_array(df.a).alias("s"))).to_pylist()
    assert out[0]["s"] == [big - 1, big]


def test_arrays_zip_field_names(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(df.u,
                             F.arrays_zip(df.a, df.b).alias("z"))).to_pylist()
    assert out[0]["z"][0] == {"a": 1, "b": 3}


def test_posexplode_alias_rejected(sess):
    df, t = arr_df(sess)
    with pytest.raises(ValueError):
        df.select(F.posexplode(df.a).alias("z"))


def test_empty_array_literal(sess):
    df, t = arr_df(sess)
    out = run_both(df.select(df.u, F.array().alias("e"))).to_pylist()
    assert all(r["e"] == [] for r in out)


def test_slice_out_of_range_returns_empty(sess):
    """ADVICE r1: |start| > length must give an EMPTY array (not NULL),
    matching Spark; start=0 / negative length stay NULL (kernels cannot
    raise per-row — documented divergence)."""
    df, t = arr_df(sess)
    out = run_both(df.select(
        df.u,
        F.slice(df.a, -10, 2).alias("neg_far"),
        F.slice(df.a, 10, 2).alias("pos_far"),
        F.slice(df.a, 0, 2).alias("zero_start"),
        F.slice(df.a, 1, -1).alias("neg_len"),
    )).to_pylist()
    for r in out:
        if r["u"] == 2:  # null array row stays null
            continue
        assert r["neg_far"] == [], r
        assert r["pos_far"] == [], r
        assert r["zero_start"] is None, r
        assert r["neg_len"] is None, r


# --- flatten (GpuFlatten, collectionOperations.scala) ----------------------

def test_flatten_basic(sess):
    t = pa.table({"a": pa.array([[[1, 2], [3]], [[4]], [], [[5, 6], None],
                                 None],
                                type=pa.list_(pa.list_(pa.int64())))})
    df = sess.create_dataframe(t)
    out = df.select(F.flatten(df.a).alias("f")).collect()
    assert out["f"].to_pylist() == [[1, 2, 3], [4], [], None, None]


def test_flatten_strings(sess):
    t = pa.table({"a": pa.array([[["x"], ["yy", "z"]], [[]]],
                                type=pa.list_(pa.list_(pa.string())))})
    df = sess.create_dataframe(t)
    out = df.select(F.flatten(df.a).alias("f")).collect()
    assert out["f"].to_pylist() == [["x", "yy", "z"], []]


def test_map_concat(sess):
    t = pa.table({
        "m1": pa.array([{"a": 1}, {"b": 2}, None],
                       type=pa.map_(pa.string(), pa.int64())),
        "m2": pa.array([{"c": 3}, {}, {"d": 4}],
                       type=pa.map_(pa.string(), pa.int64()))})
    df = sess.create_dataframe(t)
    out = df.select(F.map_concat(df.m1, df.m2).alias("m")).collect()
    assert out["m"].to_pylist() == [[("a", 1), ("c", 3)], [("b", 2)], None]


def test_get_array_struct_fields(sess):
    from spark_rapids_tpu.sql.expressions.collections import \
        GetArrayStructFields
    from spark_rapids_tpu.sql.dataframe import Column
    t = pa.table({"a": pa.array(
        [[{"x": 1, "y": "p"}, {"x": None, "y": "q"}], [], None],
        type=pa.list_(pa.struct([("x", pa.int64()), ("y", pa.string())])))})
    df = sess.create_dataframe(t)
    out = df.select(
        Column(GetArrayStructFields(df.a.expr, 1, "y")).alias("ys"),
        Column(GetArrayStructFields(df.a.expr, 0, "x")).alias("xs"),
    ).collect()
    assert out["ys"].to_pylist() == [["p", "q"], [], None]
    assert out["xs"].to_pylist() == [[1, None], [], None]
