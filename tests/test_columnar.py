"""Round-trip and layout tests for the columnar batch model."""

import datetime
import decimal

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar import (ColumnarBatch, arrow_to_device,
                                       bucket_capacity, bucket_width,
                                       device_to_arrow, scalar_column)


def roundtrip(table: pa.Table) -> pa.Table:
    return device_to_arrow(arrow_to_device(table))


def assert_tables_equal(a: pa.Table, b: pa.Table):
    assert a.schema.names == b.schema.names
    for name in a.schema.names:
        ca, cb = a.column(name), b.column(name)
        assert ca.to_pylist() == cb.to_pylist(), name


def test_bucketing():
    assert bucket_capacity(0) == 8
    assert bucket_capacity(8) == 8
    assert bucket_capacity(9) == 16
    assert bucket_capacity(1000) == 1024
    assert bucket_width(0) == 4
    assert bucket_width(5) == 8


def test_fixed_width_roundtrip():
    t = pa.table({
        "i32": pa.array([1, 2, None, -4], type=pa.int32()),
        "i64": pa.array([10, None, 30, 40], type=pa.int64()),
        "f64": pa.array([1.5, None, float("nan"), -0.0]),
        "b": pa.array([True, False, None, True]),
        "i8": pa.array([1, -1, None, 127], type=pa.int8()),
    })
    out = roundtrip(t)
    assert out.column("i32").to_pylist() == [1, 2, None, -4]
    assert out.column("i64").to_pylist() == [10, None, 30, 40]
    assert out.column("b").to_pylist() == [True, False, None, True]
    f = out.column("f64").to_pylist()
    assert f[0] == 1.5 and f[1] is None and np.isnan(f[2]) and f[3] == -0.0


def test_string_roundtrip():
    vals = ["hello", "", None, "日本語テキスト", "x" * 100]
    t = pa.table({"s": pa.array(vals)})
    out = roundtrip(t)
    assert out.column("s").to_pylist() == vals


def test_binary_roundtrip():
    vals = [b"\x00\x01", b"", None, b"abcdef"]
    t = pa.table({"b": pa.array(vals, type=pa.binary())})
    out = roundtrip(t)
    assert out.column("b").to_pylist() == vals


def test_date_timestamp_roundtrip():
    d = [datetime.date(2020, 1, 1), None, datetime.date(1969, 12, 31)]
    ts = [datetime.datetime(2021, 6, 1, 12, 30, 15, 123456,
                            tzinfo=datetime.timezone.utc), None,
          datetime.datetime(1960, 1, 1, tzinfo=datetime.timezone.utc)]
    t = pa.table({"d": pa.array(d, type=pa.date32()),
                  "ts": pa.array(ts, type=pa.timestamp("us", tz="UTC"))})
    out = roundtrip(t)
    assert out.column("d").to_pylist() == d
    assert out.column("ts").to_pylist() == ts


def test_decimal_roundtrip():
    vals = [decimal.Decimal("123.45"), None, decimal.Decimal("-0.01"),
            decimal.Decimal("99999999.99")]
    t = pa.table({"dec": pa.array(vals, type=pa.decimal128(10, 2))})
    out = roundtrip(t)
    assert out.column("dec").to_pylist() == vals


def test_decimal128_roundtrip():
    vals = [decimal.Decimal("12345678901234567890123.456"), None,
            decimal.Decimal("-98765432109876543210.999")]
    t = pa.table({"dec": pa.array(vals, type=pa.decimal128(30, 3))})
    out = roundtrip(t)
    assert out.column("dec").to_pylist() == vals


def test_struct_roundtrip():
    vals = [{"a": 1, "b": "x"}, None, {"a": None, "b": "z"}]
    t = pa.table({"st": pa.array(vals, type=pa.struct(
        [("a", pa.int64()), ("b", pa.string())]))})
    out = roundtrip(t)
    assert out.column("st").to_pylist() == vals


def test_slice_and_concat():
    t = pa.table({"x": pa.array(range(100), type=pa.int64()),
                  "s": pa.array([f"v{i}" for i in range(100)])})
    b = arrow_to_device(t)
    s1 = b.sliced(0, 40)
    s2 = b.sliced(40, 60)
    assert s1.num_rows_int == 40 and s2.num_rows_int == 60
    cat = ColumnarBatch.concat([s1, s2])
    assert_tables_equal(device_to_arrow(cat), t)


def test_scalar_column():
    c = scalar_column(__import__("spark_rapids_tpu").STRING, "abc", 16)
    assert c.capacity == 16
    import spark_rapids_tpu.columnar.convert as cv
    arr = cv.device_column_to_arrow(c, 3)
    assert arr.to_pylist() == ["abc", "abc", "abc"]


def test_empty_table():
    t = pa.table({"x": pa.array([], type=pa.int64()),
                  "s": pa.array([], type=pa.string())})
    out = roundtrip(t)
    assert out.num_rows == 0


def test_sliced_arrow_string_input():
    # regression: offsets buffer not starting at 0 (sliced arrays)
    import spark_rapids_tpu.columnar.convert as cv
    arr = pa.array(["aa", "bbb", "cccc", "dd"]).slice(1)
    col = cv.arrow_to_device_column(arr, 8)
    assert cv.device_column_to_arrow(col, 3).to_pylist() == ["bbb", "cccc", "dd"]


def test_list_column_host_object_roundtrip():
    # nested arrays ride as host object columns (CPU fallback path)
    vals = [[1, 2], None, [3]]
    t = pa.table({"l": pa.array(vals)})
    assert roundtrip(t).column("l").to_pylist() == vals


def test_object_column_concat_and_repad():
    # host nested columns must survive concat/slice/repad (code-review regression)
    vals = [[1, 2], None, [3], [4, 5, 6]]
    b = arrow_to_device(pa.table({"l": pa.array(vals)}))
    cat = ColumnarBatch.concat([b.sliced(0, 2), b.sliced(2, 2)])
    assert device_to_arrow(cat).column("l").to_pylist() == vals
    assert device_to_arrow(b.repadded(16)).column("l").to_pylist() == vals
    with pytest.raises(ValueError):
        ColumnarBatch.concat([])


# ---------------------------------------------------------------------------
# ragged-string width-class splitting (VERDICT r2 weak #5)
# ---------------------------------------------------------------------------

class TestRaggedStringSplit:
    def test_split_keeps_footprint_near_data_size(self):
        """20k 1-byte strings + 3 10KB strings: unsplit the padded matrix
        is cap(32768) x width(16384) = 512MB; split it must stay within a
        few MB."""
        import spark_rapids_tpu as srt
        from spark_rapids_tpu.columnar.convert import split_ragged_strings
        from spark_rapids_tpu.sql.physical.transitions import batch_nbytes
        n = 20_000
        vals = ["a"] * n + ["x" * 10_240] * 3
        t = pa.table({"s": vals, "v": list(range(n + 3))})
        pieces = split_ragged_strings(t, 16 << 20)
        assert len(pieces) == 2
        assert pieces[0].num_rows == n and pieces[1].num_rows == 3
        # end-to-end through the scan: batches stay small
        from spark_rapids_tpu.sql.physical.basic import _cached_upload
        batches = _cached_upload(t, "tpu")
        assert len(batches) == 2
        total = sum(batch_nbytes(b) for b in batches)
        assert total < 8 << 20, f"padded footprint {total} bytes"

    def test_split_results_identical(self):
        """Query results match the host oracle after splitting (order-
        insensitive)."""
        import spark_rapids_tpu as srt
        from spark_rapids_tpu.sql import functions as F
        rng = np.random.default_rng(0)
        n = 20_000
        vals = ["k" + str(int(i)) for i in rng.integers(0, 50, n)]
        vals += ["L" * 9_000, "L" * 8_000]
        t = pa.table({"s": vals, "v": list(range(len(vals)))})
        sess = srt.session()
        df = sess.create_dataframe(t)
        got = (df.withColumn("ln", F.length(df.s))
               .groupBy("ln").count().orderBy("ln")
               .collect().to_pandas())
        pdf = t.to_pandas()
        exp = (pdf.assign(ln=pdf.s.str.len()).groupby("ln").size()
               .reset_index(name="count").sort_values("ln"))
        assert np.array_equal(got["ln"].values, exp["ln"].values)
        assert np.array_equal(got["count"].values, exp["count"].values)

    def test_uniform_strings_not_split(self):
        from spark_rapids_tpu.columnar.convert import split_ragged_strings
        t = pa.table({"s": ["abc"] * 10_000})
        assert len(split_ragged_strings(t, 16 << 20)) == 1


class TestLexSort64Split:
    """lex_sort splits 64-bit keys into (hi int32, lo uint32) comparator
    pairs on the jnp path (TPU x64-rewrite perf); order and stability
    must exactly match the numpy oracle."""

    def test_matches_numpy_incl_extremes(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.ops.ranks import lex_sort
        rng = np.random.default_rng(1)
        n = 20_000
        cases = [
            [rng.integers(-2**62, 2**62, n)],
            [rng.integers(-5, 5, n), rng.integers(-2**62, 2**62, n)],
            [rng.integers(0, 2**63, n).astype(np.uint64)],
            [np.array([np.iinfo(np.int64).min, -1, 0, 1,
                       np.iinfo(np.int64).max, 2**32, -2**32,
                       2**32 - 1, -(2**32) - 1] * 9)],
        ]
        for keys in cases:
            _, s_np = lex_sort(np, [np.asarray(k) for k in keys])
            _, s_j = lex_sort(jnp, [jnp.asarray(k) for k in keys])
            for a, b in zip(s_np, s_j):
                assert np.array_equal(np.asarray(a), np.asarray(b))

    def test_stability_on_ties(self):
        import jax.numpy as jnp

        from spark_rapids_tpu.ops.ranks import lex_sort
        k = jnp.asarray(np.array([3, 1, 3, 1, 3, 1] * 100,
                                 dtype=np.int64))
        perm, _ = lex_sort(jnp, [k])
        p = np.asarray(perm)
        ones = p[:300]   # rows with key 1, in original order
        assert np.all(np.diff(ones) > 0)
