"""Two-tier (ICI + DCN) shuffle: a 2-slice exchange where each slice
reads only the reduce partitions it owns, pulling the peer slice's
contributions over the TCP (DCN) plane while its own blocks stay on the
local (ICI-tier) store (SURVEY §2.8; reference UCX transport SPI + peer
registry — VERDICT r2 missing #8)."""

import numpy as np
import pyarrow as pa
import pytest

from spark_rapids_tpu.columnar.convert import arrow_to_device
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.parallel.topology import SliceTopology
from spark_rapids_tpu.shuffle import manager as M
from spark_rapids_tpu.shuffle.manager import ShuffleManager
from spark_rapids_tpu.shuffle.transport import ShuffleHeartbeatManager
from spark_rapids_tpu.shuffle.tcp import TcpShuffleTransport


def test_topology_ownership():
    t = SliceTopology(4, 1)
    owners = [t.owner_of(r, 8) for r in range(8)]
    assert owners == [0, 0, 1, 1, 2, 2, 3, 3]
    assert t.local_partitions(8) == [2, 3]
    # uneven split: ceil-block ownership covers every partition
    t2 = SliceTopology(3, 2)
    assert sorted(sum(([r for r in range(7)
                        if SliceTopology(3, s).is_local(r, 7)]
                      for s in range(3)), [])) == list(range(7))


def test_from_conf_single_slice_is_none():
    assert SliceTopology.from_conf(RapidsConf.get_global()) is None


def _mk_batch(vals):
    return arrow_to_device(pa.table({"v": pa.array(vals, pa.int64())}))


def test_two_slice_exchange_over_dcn():
    """Each slice holds one map task's output for ALL 4 reduce
    partitions; topology says slice 0 owns partitions {0,1} and slice 1
    owns {2,3}.  Each slice reduces ONLY its own partitions: its own
    map's blocks come off the local store (ICI tier), the peer's blocks
    cross the TCP (DCN) plane."""
    registry = ShuffleHeartbeatManager()
    confs, mgrs = [], []
    try:
        for sid in (0, 1):
            conf = RapidsConf.get_global().copy({
                "spark.rapids.shuffle.mode": "ICI",
                "spark.rapids.shuffle.topology.numSlices": 2,
                "spark.rapids.shuffle.topology.sliceId": sid,
            })
            t = TcpShuffleTransport(f"slice-{sid}")
            m = ShuffleManager(conf, transport=t,
                               executor_id=f"slice-{sid}",
                               heartbeats=registry)
            confs.append(conf)
            mgrs.append(m)
        nt = 4
        sid0, sid1 = mgrs
        assert sid0.topology.multi_slice
        assert sid0.topology.local_partitions(nt) == [0, 1]
        assert sid1.topology.local_partitions(nt) == [2, 3]

        # map side: slice s's map task m=s produced rows 100*s + 10*r + i
        # for each target partition r
        shuffle_id = 77
        for s, mgr in enumerate(mgrs):
            pieces = [_mk_batch([100 * s + 10 * r + i for i in range(3)])
                      for r in range(nt)]
            mgr.write_map_output(shuffle_id, s, pieces)

        M.TIER_STATS.update(local_blocks=0, dcn_fetches=0)
        got = {}
        for mgr in mgrs:
            for r in mgr.topology.local_partitions(nt):
                b = mgr.read_reduce_partition(shuffle_id, len(mgrs), r)
                assert b is not None
                import jax
                host = jax.device_get(b)
                from spark_rapids_tpu.columnar.convert import device_to_arrow
                vals = device_to_arrow(host).column("v").to_pylist()
                got[r] = sorted(vals)
        # completeness: partition r holds both slices' contributions
        for r in range(nt):
            assert got[r] == sorted([10 * r + i for i in range(3)]
                                    + [100 + 10 * r + i for i in range(3)])
        # tier accounting: each slice served its own 2 blocks locally and
        # pulled 2 from the peer over the TCP plane
        assert M.TIER_STATS["local_blocks"] == 4
        assert M.TIER_STATS["dcn_fetches"] == 4
    finally:
        for m in mgrs:
            m.close()


def test_exchange_materializes_only_local_partitions():
    """Engine-level routing: with a 2-slice topology configured, a
    planned exchange in THIS process assembles only the partitions its
    slice owns — the peer slice's partitions stay empty here (their
    blocks remain published for the peer to pull over DCN)."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F
    import pandas as pd

    sess = srt.session(**{
        "spark.rapids.shuffle.topology.numSlices": 2,
        "spark.rapids.shuffle.topology.sliceId": 0,
        "spark.sql.adaptive.enabled": False,  # keep nt partitions
    })
    try:
        n, G = 50_000, 1_000
        rng = np.random.default_rng(0)
        t = pa.table({"k": rng.integers(0, G, n), "v": rng.random(n)})
        df = sess.create_dataframe(t, num_partitions=4)
        got = (df.groupBy("k").agg(F.sum(F.col("v")).alias("s"))
               .collect().to_pandas())
        # slice 0 produced a strict, correct subset: every returned group
        # matches the oracle, but the peer slice's share is absent
        exp = t.to_pandas().groupby("k").agg(s=("v", "sum"))
        assert 0 < len(got) < G
        for _, row in got.head(50).iterrows():
            assert abs(exp.loc[row["k"], "s"] - row["s"]) < 1e-9
    finally:
        srt.session(**{"spark.rapids.shuffle.topology.numSlices": 1,
                       "spark.sql.adaptive.enabled": True})


def test_multi_slice_map_ids_namespaced():
    """Engine exchanges namespace map ids by slice (base = sliceId *
    num_maps) so two slices' blocks for the same shuffle never collide —
    the condition that lets a reducing slice see BOTH slices'
    contributions (review r3 finding)."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql import functions as F
    from spark_rapids_tpu.shuffle.manager import get_shuffle_manager
    sess = srt.session(**{
        "spark.rapids.shuffle.topology.numSlices": 2,
        "spark.rapids.shuffle.topology.sliceId": 1,
        "spark.sql.adaptive.enabled": False})
    try:
        rng = np.random.default_rng(2)
        t = pa.table({"k": rng.integers(0, 500, 20_000),
                      "v": rng.random(20_000)})
        df = sess.create_dataframe(t, num_partitions=4)
        df.groupBy("k").agg(F.sum(F.col("v")).alias("s")).collect()
        mgr = get_shuffle_manager(sess._conf)
        ids = {b.map_id for b in mgr._files}
        assert ids, "no blocks published"
        # slice 1's bases are num_maps*1 per exchange (4 and 8 here) — no
        # id may sit in slice 0's namespace [0, num_maps)
        assert min(ids) >= 4, sorted(ids)
        # deferred cleanup keeps blocks for the peer's TTL window
        assert mgr._pending_cleanup
    finally:
        srt.session(**{"spark.rapids.shuffle.topology.numSlices": 1,
                       "spark.sql.adaptive.enabled": True})
