"""End-to-end DataFrame API tests — the engine's equivalent of the
reference's integration suite philosophy: every query runs on the TPU path
(virtual CPU devices) AND the host engine (spark.rapids.sql.enabled=false)
and must produce identical results."""

import math

import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def assert_tpu_and_cpu_equal(df, sort_by=None):
    """Run with acceleration on and off; compare (reference asserts.py
    assert_gpu_and_cpu_are_equal_collect)."""
    sess = df._session
    tpu = df.collect()
    old = sess.conf.get("spark.rapids.sql.enabled")
    sess.conf.set("spark.rapids.sql.enabled", False)
    try:
        cpu = df.collect()
    finally:
        sess.conf.set("spark.rapids.sql.enabled", old)
    t, c = tpu.to_pylist(), cpu.to_pylist()
    if sort_by:
        key = lambda r: tuple((r[k] is None, r[k]) for k in sort_by)
        t, c = sorted(t, key=key), sorted(c, key=key)
    assert _norm(t) == _norm(c)
    return tpu


def _norm(rows):
    out = []
    for r in rows:
        nr = {}
        for k, v in r.items():
            if isinstance(v, float):
                nr[k] = "NaN" if math.isnan(v) else round(v, 9)
            else:
                nr[k] = v
        out.append(nr)
    return out


def test_select_filter_project(sess):
    df = sess.create_dataframe(pa.table({
        "a": pa.array([1, 2, 3, 4, None], type=pa.int64()),
        "b": pa.array([10.0, 20.0, None, 40.0, 50.0]),
    }))
    out = df.filter(df.a > 1).select(
        (df.a * 2).alias("a2"),
        (df.b + df.a).alias("ab")).collect()
    assert out.column("a2").to_pylist() == [4, 6, 8]
    assert out.column("ab").to_pylist() == [22.0, None, 44.0]
    assert_tpu_and_cpu_equal(df.filter(df.a > 1).select((df.a * 2).alias("x")))


def test_fcol_and_arith_coercion(sess):
    df = sess.create_dataframe(pa.table({
        "i": pa.array([1, 2, 3], type=pa.int32()),
        "l": pa.array([10, 20, 30], type=pa.int64())}))
    out = df.select((F.col("i") + F.col("l")).alias("s"),
                    (F.col("i") / 2).alias("d")).collect()
    assert out.column("s").to_pylist() == [11, 22, 33]
    assert out.column("d").to_pylist() == [0.5, 1.0, 1.5]


def test_groupby_agg(sess):
    df = sess.create_dataframe(pa.table({
        "k": pa.array(["a", "b", "a", None, "b", "a"]),
        "v": pa.array([1, 2, 3, 4, None, 6], type=pa.int64()),
    }))
    out = df.groupBy("k").agg(
        F.sum(df.v).alias("s"), F.count(df.v).alias("c"),
        F.avg(df.v).alias("m"), F.min(df.v).alias("lo"),
        F.max(df.v).alias("hi")).collect()
    rows = {r["k"]: r for r in out.to_pylist()}
    assert rows["a"]["s"] == 10 and rows["a"]["c"] == 3
    assert rows["b"]["s"] == 2 and rows["b"]["c"] == 1
    assert rows[None]["s"] == 4 and rows[None]["c"] == 1
    assert rows["a"]["m"] == pytest.approx(10 / 3)
    assert rows["b"]["lo"] == 2 and rows["b"]["hi"] == 2
    assert_tpu_and_cpu_equal(
        df.groupBy("k").agg(F.sum(df.v).alias("s")), sort_by=["k"])


def test_global_agg(sess):
    df = sess.create_dataframe(pa.table({
        "v": pa.array([1, 2, 3, None], type=pa.int64())}))
    out = df.agg(F.sum(df.v).alias("s"), F.count("*").alias("n"),
                 F.count(df.v).alias("nv")).collect()
    assert out.to_pylist() == [{"s": 6, "n": 4, "nv": 3}]


def test_global_agg_empty_input(sess):
    df = sess.create_dataframe(pa.table({
        "v": pa.array([], type=pa.int64())}))
    out = df.agg(F.sum(df.v).alias("s"), F.count("*").alias("n")).collect()
    assert out.to_pylist() == [{"s": None, "n": 0}]


def test_count_action(sess):
    df = sess.create_dataframe(pa.table({"x": pa.array(range(100))}))
    assert df.count() == 100
    assert df.filter(df.x < 10).count() == 10


def test_orderby(sess):
    df = sess.create_dataframe(pa.table({
        "x": pa.array([3.0, 1.0, None, float("nan"), 2.0]),
        "s": pa.array(["c", "a", "n", "nan", "b"])}))
    out = df.orderBy(df.x).collect()
    vals = out.column("s").to_pylist()
    assert vals == ["n", "a", "b", "c", "nan"]  # nulls first, NaN largest
    out = df.orderBy(df.x.desc_nulls_first()).collect()
    assert out.column("s").to_pylist() == ["n", "nan", "c", "b", "a"]


def test_orderby_strings(sess):
    df = sess.create_dataframe(pa.table({
        "s": pa.array(["banana", "apple", None, "app", "cherry", ""])}))
    out = df.orderBy(df.s).collect()
    assert out.column("s").to_pylist() == [None, "", "app", "apple", "banana",
                                           "cherry"]


def test_limit_union_distinct(sess):
    df = sess.create_dataframe(pa.table({"x": pa.array([1, 2, 3] * 10,
                                                       type=pa.int64())}))
    assert df.limit(5).count() == 5
    assert df.union(df).count() == 60
    d = df.distinct().collect().column("x").to_pylist()
    assert sorted(d) == [1, 2, 3]


def test_withcolumn_drop_rename(sess):
    df = sess.create_dataframe(pa.table({"a": pa.array([1, 2], type=pa.int64())}))
    out = df.withColumn("b", df.a * 10).withColumnRenamed("a", "aa")
    assert out.columns == ["aa", "b"]
    assert out.collect().column("b").to_pylist() == [10, 20]
    assert out.drop("b").columns == ["aa"]


def test_multi_partition_agg(sess):
    df = sess.create_dataframe(pa.table({
        "k": pa.array([i % 7 for i in range(1000)], type=pa.int64()),
        "v": pa.array(list(range(1000)), type=pa.int64())}),
        num_partitions=4)
    out = df.groupBy("k").agg(F.sum(df.v).alias("s")).collect()
    expected = {}
    for i in range(1000):
        expected[i % 7] = expected.get(i % 7, 0) + i
    got = {r["k"]: r["s"] for r in out.to_pylist()}
    assert got == expected


def test_repartition_roundtrip(sess):
    df = sess.create_dataframe(pa.table({
        "x": pa.array(range(100), type=pa.int64())}))
    out = df.repartition(5).collect()
    assert sorted(out.column("x").to_pylist()) == list(range(100))
    out = df.repartition(4, F.col("x")).collect()
    assert sorted(out.column("x").to_pylist()) == list(range(100))


def test_global_sort_multi_partition(sess):
    import random
    vals = list(range(500))
    random.Random(7).shuffle(vals)
    df = sess.create_dataframe(pa.table({"x": pa.array(vals, type=pa.int64())}),
                               num_partitions=4)
    out = df.orderBy("x").collect()
    assert out.column("x").to_pylist() == sorted(vals)


def test_range(sess):
    df = sess.range(10)
    assert df.collect().column("id").to_pylist() == list(range(10))
    df = sess.range(3, 30, 3, num_slices=2)
    assert sorted(df.collect().column("id").to_pylist()) == list(range(3, 30, 3))


def test_explain_placement(sess):
    df = sess.create_dataframe(pa.table({"x": pa.array([1, 2], type=pa.int64())}))
    q = df.filter(df.x > 1)
    s = sess.explain(q, all_ops=True)
    assert "will run on TPU" in s
    assert "Physical plan" in s


def test_explain_fallback_reason(sess):
    # FormatNumber is a host-exact op (reference marks it incompat)
    df = sess.create_dataframe(pa.table({
        "x": pa.array([1234.5, 6.7])}))
    from spark_rapids_tpu.sql.expressions.strings import FormatNumber
    from spark_rapids_tpu.sql.dataframe import Column
    q = df.select(Column(FormatNumber(df.x.expr, F.lit(2).expr)).alias("s"))
    s = sess.explain(q)
    assert "cannot run on TPU" in s
    assert "host" in s
    out = q.collect()
    assert out.column("s").to_pylist() == ["1,234.50", "6.70"]


def test_sql_disabled_conf(sess):
    df = sess.create_dataframe(pa.table({"x": pa.array([1], type=pa.int64())}))
    sess.conf.set("spark.rapids.sql.enabled", False)
    try:
        s = sess.explain(df.filter(df.x > 0))
        assert "spark.rapids.sql.enabled is false" in s
        assert df.filter(df.x > 0).count() == 1
    finally:
        sess.conf.set("spark.rapids.sql.enabled", True)


def test_when_otherwise_case(sess):
    df = sess.create_dataframe(pa.table({
        "x": pa.array([1, 5, None], type=pa.int64())}))
    out = df.select(
        F.when(df.x > 3, F.lit("big")).when(df.x > 0, F.lit("small"))
        .otherwise(F.lit("null")).alias("c")).collect()
    assert out.column("c").to_pylist() == ["small", "big", "null"]


def test_distinct_on_floats_and_strings(sess):
    df = sess.create_dataframe(pa.table({
        "x": pa.array([1.0, -0.0, 0.0, float("nan"), float("nan"), None]),
    }))
    vals = df.distinct().collect().column("x").to_pylist()
    # -0.0 == 0.0 and NaN == NaN for grouping -> {0.0, 1.0, NaN, None}
    assert len(vals) == 4


def test_first_last(sess):
    df = sess.create_dataframe(pa.table({
        "k": pa.array(["a", "a", "b"]),
        "v": pa.array([None, 2, 3], type=pa.int64())}))
    out = df.groupBy("k").agg(F.first(df.v).alias("f"),
                              F.first(df.v, ignorenulls=True).alias("fn"),
                              F.last(df.v).alias("l")).collect()
    rows = {r["k"]: r for r in out.to_pylist()}
    assert rows["a"]["f"] is None and rows["a"]["fn"] == 2
    assert rows["a"]["l"] == 2 and rows["b"]["f"] == 3


def test_stddev_var(sess):
    import statistics
    vals = [1.0, 2.0, 3.0, 4.0, 10.0]
    df = sess.create_dataframe(pa.table({"v": pa.array(vals)}))
    out = df.agg(F.stddev(df.v).alias("sd"),
                 F.var_pop(df.v).alias("vp")).collect().to_pylist()[0]
    assert out["sd"] == pytest.approx(statistics.stdev(vals))
    assert out["vp"] == pytest.approx(statistics.pvariance(vals))


def test_sample(sess):
    df = sess.create_dataframe(pa.table({"x": pa.array(range(1000))}))
    n = df.sample(0.1, seed=42).count()
    assert 50 < n < 200


def test_dropduplicates_subset(sess):
    df = sess.create_dataframe(pa.table({
        "k": pa.array([1, 1, 2], type=pa.int64()),
        "v": pa.array(["x", "y", "z"])}))
    out = df.dropDuplicates(["k"]).collect()
    assert out.num_rows == 2


# --- pivot (PivotFirst lowering) -------------------------------------------

def test_pivot_infers_values():
    sess = srt.session()
    df = sess.create_dataframe(pa.table({
        "y": [1, 1, 2, 2, 2], "q": ["a", "b", "a", "a", "b"],
        "v": [10.0, 20.0, 30.0, 5.0, 40.0]}), num_partitions=2)
    out = (df.groupBy("y").pivot("q").agg(F.sum(F.col("v")))
           .orderBy("y").collect().to_pylist())
    assert out == [{"y": 1, "a": 10.0, "b": 20.0},
                   {"y": 2, "a": 35.0, "b": 40.0}]


def test_pivot_explicit_values_multi_agg():
    sess = srt.session()
    df = sess.create_dataframe(pa.table({
        "y": [1, 1, 2], "q": ["a", "b", "a"], "v": [1.0, 2.0, 3.0]}))
    out = (df.groupBy("y").pivot("q", ["a"])
           .agg(F.sum(F.col("v")).alias("s"), F.count("*").alias("c"))
           .orderBy("y").collect().to_pylist())
    assert out == [{"y": 1, "a_s": 1.0, "a_c": 1},
                   {"y": 2, "a_s": 3.0, "a_c": 1}]


def test_pivot_missing_combination_is_null():
    sess = srt.session()
    df = sess.create_dataframe(pa.table({
        "y": [1, 2], "q": ["a", "b"], "v": [1.0, 2.0]}))
    out = (df.groupBy("y").pivot("q", ["a", "b"]).agg(F.sum(F.col("v")))
           .orderBy("y").collect().to_pylist())
    assert out == [{"y": 1, "a": 1.0, "b": None},
                   {"y": 2, "a": None, "b": 2.0}]


# --- describe / summary (pyspark API parity) -------------------------------

def test_describe_and_summary():
    sess = srt.session()
    df = sess.create_dataframe(pa.table({
        "x": [1.0, 2.0, 3.0, 4.0], "s": ["a", "b", "c", "d"],
        "y": [10, 20, 30, 40]}))
    d = {r["summary"]: r for r in df.describe().collect().to_pylist()}
    assert d["count"]["x"] == "4" and d["count"]["y"] == "4"
    assert d["mean"]["x"] == "2.5" and d["min"]["y"] == "10"
    assert d["max"]["x"] == "4.0"
    assert "s" not in d["count"]  # non-numeric columns excluded
    sm = {r["summary"]: r for r in df.summary().collect().to_pylist()}
    assert sm["50%"]["x"] == "2.0" and sm["75%"]["y"] == "30"
    # explicit stats selection
    only = df.summary("min", "max").collect().to_pylist()
    assert [r["summary"] for r in only] == ["min", "max"]


def test_approx_count_distinct_and_avg_distinct():
    sess = srt.session()
    df = sess.create_dataframe(pa.table({
        "k": [1, 1, 2, 2], "v": [5.0, 5.0, 7.0, 9.0]}), num_partitions=2)
    out = (df.groupBy("k")
           .agg(F.approx_count_distinct(F.col("v")).alias("c"),
                F.avgDistinct(F.col("v")).alias("a"))
           .orderBy("k").collect().to_pylist())
    assert out == [{"k": 1, "c": 1, "a": 5.0}, {"k": 2, "c": 2, "a": 8.0}]


class TestSetOperations:
    """INTERSECT/EXCEPT [ALL] (Spark's ReplaceSetOps rewrites; the ALL
    variants replicate multiplicities the way RewriteIntersectAll/
    RewriteExceptAll do, with NULLs comparing equal)."""

    def _frames(self, sess):
        l = sess.create_dataframe(pa.table(
            {"a": [1, 2, 2, 3, 3, 3, None],
             "b": ["x", "y", "y", "z", "z", "z", None]}))
        r = sess.create_dataframe(pa.table(
            {"a": [2, 3, 3, 9, None], "b": ["y", "z", "z", "q", None]}))
        return l, r

    @staticmethod
    def _rows(df):
        p = df.collect().to_pandas()
        return sorted(map(tuple,
                          p.where(p.notna(), None).itertuples(index=False)),
                      key=str)

    def test_intersect_distinct(self, session):
        l, r = self._frames(session)
        got = self._rows(l.intersect(r))
        assert len(got) == 3  # (2,y), (3,z), (null,null)

    def test_subtract(self, session):
        l, r = self._frames(session)
        assert self._rows(l.subtract(r)) == [(1, "x")]

    def test_intersect_all_multiplicities(self, session):
        l, r = self._frames(session)
        got = self._rows(l.intersectAll(r))
        # min multiplicities: (2,y)x1, (3,z)x2, (null,null)x1
        assert len(got) == 4
        assert sum(1 for t in got if t[0] == 3.0) == 2

    def test_except_all_multiplicities(self, session):
        l, r = self._frames(session)
        got = self._rows(l.exceptAll(r))
        assert got == [(1, "x"), (2, "y"), (3, "z")]

    def test_schema_mismatch_rejected(self, session):
        l, _ = self._frames(session)
        other = session.create_dataframe(pa.table({"c": [1]}))
        with pytest.raises(ValueError, match="identical schemas"):
            l.intersect(other)

    def test_replicate_rows_expression_registered(self):
        from spark_rapids_tpu.sql.expressions.registry import \
            EXPRESSION_REGISTRY
        assert "ReplicateRows" in EXPRESSION_REGISTRY
        assert "DynamicPruningExpression" in EXPRESSION_REGISTRY


def test_pivot_first_expression_direct():
    """PivotFirst used directly as an aggregate (the reference's
    GpuPivotFirst, GpuOverrides.scala:2098): one array slot per pivot
    value, first non-null value wins, missing slots null."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql.dataframe import Column
    from spark_rapids_tpu.sql.expressions.aggregates import PivotFirst
    sess = srt.session()
    t = pa.table({"y": [2024, 2024, 2024, 2025, 2025],
                  "q": ["a", "b", "a", "b", "b"],
                  "v": [1.0, 2.0, 9.0, 3.0, 4.0]})
    df = sess.create_dataframe(t)
    pf = PivotFirst(df._col("q").expr, df._col("v").expr, ["a", "b", "c"])
    out = (df.groupBy("y").agg(Column(pf).alias("p"))
           .orderBy("y").collect().to_pylist())
    assert out[0]["y"] == 2024 and out[0]["p"] == [1.0, 2.0, None]
    assert out[1]["y"] == 2025 and out[1]["p"] == [None, 3.0, None]


def test_pivot_first_string_values():
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql.dataframe import Column
    from spark_rapids_tpu.sql.expressions.aggregates import PivotFirst
    sess = srt.session()
    t = pa.table({"g": [1, 1, 2], "q": ["x", "y", "x"],
                  "s": ["hello", "world", "tpu"]})
    df = sess.create_dataframe(t)
    pf = PivotFirst(df._col("q").expr, df._col("s").expr, ["x", "y"])
    out = (df.groupBy("g").agg(Column(pf).alias("p"))
           .orderBy("g").collect().to_pylist())
    assert out[0]["p"] == ["hello", "world"]
    assert out[1]["p"] == ["tpu", None]


def test_pivot_first_multi_partition_merge():
    """The value slots merge by 'first VALID partial' (merge_valid_only),
    not 'first partial' — a partial with no matching pivot row must not
    shadow a later partial's value (review r4 finding)."""
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql.dataframe import Column
    from spark_rapids_tpu.sql.expressions.aggregates import PivotFirst
    sess = srt.session()
    n = 50
    t = pa.table({"g": [1] * n, "q": ["b"] * (n - 2) + ["a", "b"],
                  "v": [0.0] * (n - 2) + [99.0, 0.0]})
    df = sess.create_dataframe(t, num_partitions=4)
    out = (df.groupBy("g")
           .agg(Column(PivotFirst(df._col("q").expr, df._col("v").expr,
                                  ["a", "b"])).alias("p"))
           .collect().to_pylist())
    assert out[0]["p"] == [99.0, 0.0]


def test_pivot_first_nested_value_rejected():
    import pytest as _pytest
    import spark_rapids_tpu as srt
    from spark_rapids_tpu.sql.dataframe import Column
    from spark_rapids_tpu.sql.expressions.aggregates import PivotFirst
    sess = srt.session()
    t = pa.table({"g": [1], "q": ["a"],
                  "v": pa.array([[1, 2]], type=pa.list_(pa.int64()))})
    df = sess.create_dataframe(t)
    q = df.groupBy("g").agg(Column(PivotFirst(
        df._col("q").expr, df._col("v").expr, ["a"])).alias("p"))
    with _pytest.raises(ValueError, match="project a flat value"):
        q.collect()
    with _pytest.raises(ValueError, match="at least one"):
        PivotFirst(df._col("q").expr, df._col("g").expr, [])
