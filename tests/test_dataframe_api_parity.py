"""pyspark API-parity batch: na/stat accessors, unionByName, unpivot,
randomSplit, toDF/transform/colRegex/tail, crosstab/freqItems — thin
compositions over existing execs, oracle-checked against pandas."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


@pytest.fixture()
def data():
    rng = np.random.default_rng(9)
    n = 4000
    v = pa.array([None if i % 7 == 0 else float(x)
                  for i, x in enumerate(rng.random(n))])
    return pa.table({"k": rng.integers(0, 4, n), "v": v,
                     "w": rng.random(n),
                     "s": [f"s{i % 3}" for i in range(n)]})


def test_na_fill_drop_replace(sess, data):
    df = sess.create_dataframe(data)
    pdf = data.to_pandas()
    assert df.fillna(0.0).filter(F.col("v").isNull()).count() == 0
    assert df.na.drop(subset=["v"]).count() == int(pdf.v.notna().sum())
    r = (df.replace(0, 99, subset=["k"]).groupBy("k")
         .agg(F.count("*").alias("c")).collect().to_pandas())
    assert 0 not in set(r["k"]) and 99 in set(r["k"])
    # dict form + how=all
    assert df.na.fill({"v": 1.5}).filter(F.col("v").isNull()).count() == 0
    assert df.na.drop(how="all", subset=["v", "w"]).count() == len(pdf)


def test_union_by_name(sess, data):
    n = data.num_rows
    df = sess.create_dataframe(data)
    d2 = df.select(F.col("w"), F.col("k"), F.col("v"), F.col("s"))
    assert df.unionByName(d2).count() == 2 * n
    um = df.select("k", "v").unionByName(
        d2.select("k", "w"), allowMissingColumns=True)
    assert um.count() == 2 * n
    assert set(um.collect().column_names) == {"k", "v", "w"}
    with pytest.raises(ValueError):
        df.select("k", "v").unionByName(d2.select("k", "w"))


def test_todf_transform_colregex_tail(sess, data):
    df = sess.create_dataframe(data)
    assert df.toDF("a", "b", "c", "d").collect().column_names \
        == ["a", "b", "c", "d"]
    assert df.transform(lambda d: d.limit(5)).count() == 5
    assert [c.expr.name for c in df.colRegex("`[kv]`")] == ["k", "v"]
    assert len(df.tail(3)) == 3


def test_random_split_partitions_rows(sess, data):
    n = data.num_rows
    df = sess.create_dataframe(data)
    a, b = df.randomSplit([0.7, 0.3], seed=5)
    ca, cb = a.count(), b.count()
    assert ca + cb == n
    assert 0.6 * n < ca < 0.8 * n


def test_unpivot_matches_pandas(sess, data):
    n = data.num_rows
    df = sess.create_dataframe(data)
    pdf = data.to_pandas()
    up = df.unpivot(["k"], ["v", "w"]).collect().to_pandas()
    assert len(up) == 2 * n
    assert set(up["variable"]) == {"v", "w"}
    assert np.allclose(sorted(up[up.variable == "w"]["value"]),
                       sorted(pdf["w"]))


def test_stat_functions(sess, data):
    df = sess.create_dataframe(data)
    pdf = data.to_pandas()
    sub = pdf[["v", "w"]].dropna()
    assert np.isclose(df.stat.corr("v", "w"), sub.v.corr(sub.w), atol=1e-9)
    assert np.isclose(df.stat.cov("v", "w"), sub.v.cov(sub.w), atol=1e-9)
    q = df.approxQuantile("w", [0.25, 0.5, 0.75], 0.0)
    assert q[0] < q[1] < q[2]
    ct = df.crosstab("k", "s").collect().to_pandas()
    assert len(ct) == 4
    assert ct.drop(columns=["k_s"]).to_numpy().sum() == len(pdf)
    fi = df.freqItems(["k"], 0.1).collect().to_pylist()[0]
    assert set(fi["k_freqItems"]) == {0, 1, 2, 3}


def test_api_parity_edge_cases(sess, data):
    import math
    df = sess.create_dataframe(data)
    # invalid how rejected; unpivot with no value columns rejected
    with pytest.raises(ValueError):
        df.na.drop(how="bogus")
    with pytest.raises(ValueError):
        df.select("k").unpivot("k")
    # sample covariance undefined at n=1 (Spark: null)
    one = sess.create_dataframe(pa.table({"x": [1.0], "y": [2.0]}))
    assert math.isnan(one.stat.cov("x", "y"))
    # crosstab: NULL key labeled 'null', distinct from a real 0 key
    t2 = pa.table({"k": pa.array([1, 1, None, 0], type=pa.int64()),
                   "s": ["a", "b", "a", "a"]})
    ct = sess.create_dataframe(t2).stat.crosstab("k", "s") \
        .collect().to_pandas()
    assert {"null", "0", "1"} <= set(ct["k_s"])


def test_foreach_partition_sees_each_partition(sess):
    df = sess.create_dataframe(pa.table({"x": np.arange(100)}),
                               num_partitions=4)
    calls = []
    df.foreachPartition(lambda it: calls.append(len(list(it))))
    assert len(calls) == 4 and sum(calls) == 100


def test_sql_rollup_order_by_grouping_id(sess, data):
    sess.create_dataframe(data).createOrReplaceTempView("t_ob")
    got = sess.sql(
        "SELECT k, sum(w) AS sw FROM t_ob GROUP BY ROLLUP(k) "
        "ORDER BY grouping_id(), k").collect().to_pandas()
    assert np.isclose(got["sw"].iloc[-1], data.to_pandas().w.sum())
