"""Datetime expression tests vs pandas/python datetime oracles — reference
coverage model: integration_tests date_time_test.py."""

import datetime as dt

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def date_df(sess, n=400, seed=11):
    rng = np.random.default_rng(seed)
    days = rng.integers(-30000, 40000, n)  # ~1888..2079
    micros = days * 86_400_000_000 + rng.integers(0, 86_400_000_000, n)
    t = pa.table({
        "d": pa.array(days.astype("int32"), type=pa.date32()),
        "ts": pa.array(micros, type=pa.timestamp("us")),
        "n": pa.array(rng.integers(-100, 100, n), type=pa.int32()),
        "u": pa.array(np.arange(n), type=pa.int64()),
    })
    return sess.create_dataframe(t), t.to_pandas()


def run_both(df, sort_col="u"):
    sess = df._session
    a = df.collect().to_pandas().sort_values(sort_col).reset_index(drop=True)
    sess.conf.set("spark.rapids.sql.enabled", False)
    try:
        b = df.collect().to_pandas().sort_values(sort_col).reset_index(drop=True)
    finally:
        sess.conf.set("spark.rapids.sql.enabled", True)
    pd.testing.assert_frame_equal(a, b, check_dtype=False)
    return a


def test_date_fields(sess):
    df, pdf = date_df(sess)
    out = run_both(df.select(
        df.u,
        F.year(df.d).alias("y"), F.month(df.d).alias("m"),
        F.dayofmonth(df.d).alias("dom"), F.dayofweek(df.d).alias("dow"),
        F.weekday(df.d).alias("wd"), F.dayofyear(df.d).alias("doy"),
        F.quarter(df.d).alias("q"), F.weekofyear(df.d).alias("woy"),
        F.last_day(df.d).alias("ld"),
    ))
    ser = pdf["d"].map(pd.Timestamp)
    assert (out["y"] == ser.dt.year.values).all()
    assert (out["m"] == ser.dt.month.values).all()
    assert (out["dom"] == ser.dt.day.values).all()
    # Spark dayofweek: 1=Sunday; pandas dayofweek: 0=Monday
    assert (out["dow"] == ((ser.dt.dayofweek.values + 1) % 7) + 1).all()
    assert (out["wd"] == ser.dt.dayofweek.values).all()
    assert (out["doy"] == ser.dt.dayofyear.values).all()
    assert (out["q"] == ser.dt.quarter.values).all()
    assert (out["woy"] == ser.dt.isocalendar().week.values).all()
    exp_ld = ser + pd.offsets.MonthEnd(0)
    exp_ld = ser.where(ser == exp_ld, exp_ld)
    assert (pd.to_datetime(out["ld"]).values == exp_ld.values).all()


def test_time_fields(sess):
    df, pdf = date_df(sess)
    out = run_both(df.select(
        df.u, F.hour(df.ts).alias("h"), F.minute(df.ts).alias("mi"),
        F.second(df.ts).alias("s")))
    ser = pdf["ts"]
    assert (out["h"] == ser.dt.hour.values).all()
    assert (out["mi"] == ser.dt.minute.values).all()
    assert (out["s"] == ser.dt.second.values).all()


def test_date_arithmetic(sess):
    df, pdf = date_df(sess)
    out = run_both(df.select(
        df.u,
        F.date_add(df.d, 30).alias("p30"),
        F.date_sub(df.d, 15).alias("m15"),
        F.datediff(df.d, F.lit(dt.date(2020, 1, 1))).alias("dd"),
        F.add_months(df.d, df.n).alias("am"),
    ))
    ser = pdf["d"].map(pd.Timestamp)
    assert (pd.to_datetime(out["p30"]).values ==
            (ser + pd.Timedelta(days=30)).values).all()
    assert (pd.to_datetime(out["m15"]).values ==
            (ser - pd.Timedelta(days=15)).values).all()
    exp_dd = (ser - pd.Timestamp("2020-01-01")).dt.days
    assert (out["dd"] == exp_dd.values).all()
    exp_am = ser + pdf["n"].map(lambda k: pd.DateOffset(months=int(k)))
    assert (pd.to_datetime(out["am"]).values == exp_am.values).all()


def test_trunc(sess):
    df, pdf = date_df(sess)
    out = run_both(df.select(
        df.u, F.trunc(df.d, "year").alias("ty"),
        F.trunc(df.d, "month").alias("tm"),
        F.trunc(df.d, "week").alias("tw"),
        F.trunc(df.d, "quarter").alias("tq")))
    ser = pdf["d"].map(pd.Timestamp)
    assert (pd.to_datetime(out["ty"]).values ==
            ser.dt.to_period("Y").dt.start_time.values).all()
    assert (pd.to_datetime(out["tm"]).values ==
            ser.dt.to_period("M").dt.start_time.values).all()
    assert (pd.to_datetime(out["tw"]).values ==
            ser.dt.to_period("W").dt.start_time.values).all()
    assert (pd.to_datetime(out["tq"]).values ==
            ser.dt.to_period("Q").dt.start_time.values).all()


def test_format_and_parse_roundtrip(sess):
    df, pdf = date_df(sess)
    out = run_both(df.select(
        df.u,
        F.date_format(df.ts, "yyyy-MM-dd HH:mm:ss").alias("s"),
        F.unix_timestamp(F.date_format(df.ts, "yyyy-MM-dd HH:mm:ss"))
         .alias("back"),
    ))
    exp = pdf["ts"].dt.strftime("%Y-%m-%d %H:%M:%S")
    # negative years not representable in strftime; restrict to CE dates
    ok = pdf["ts"].dt.year >= 1
    assert (out.loc[ok.values, "s"] == exp[ok].values).all()
    exp_secs = pdf["ts"].astype("int64") // 1_000_000
    assert (out.loc[ok.values, "back"] ==
            (exp_secs[ok]).values).all()


def test_epoch_conversions(sess):
    df, pdf = date_df(sess)
    out = run_both(df.select(
        df.u,
        F.unix_micros(df.ts).alias("um"),
        F.to_unix_timestamp(df.ts).alias("uts"),
        F.timestamp_seconds(F.to_unix_timestamp(df.ts)).alias("rt"),
    ))
    exp_um = pdf["ts"].astype("int64")
    assert (out["um"] == exp_um.values).all()
    assert (out["uts"] == (exp_um // 1_000_000).values).all()
    exp_rt = (exp_um // 1_000_000) * 1_000_000
    assert (out["rt"].astype("int64") // 1000 * 1000 ==
            (exp_rt // 1000 * 1000).values).all()


def test_from_utc_timestamp_fixed_offset(sess):
    df, pdf = date_df(sess, n=50)
    out = run_both(df.select(
        df.u, F.from_utc_timestamp(df.ts, "+05:30").alias("ist")))
    exp = pdf["ts"] + pd.Timedelta(hours=5, minutes=30)
    assert (out["ist"].values == exp.values).all()


def test_parse_invalid_strings_yield_null(sess):
    t = pa.table({"s": ["2021-03-04 05:06:07", "not a date",
                        "2021-13-04 05:06:07", "2021-02-30 00:00:00", None],
                  "u": list(range(5))})
    df = sess.create_dataframe(t)
    out = run_both(df.select(df.u, F.unix_timestamp(df.s).alias("ts")))
    vals = out["ts"].tolist()
    assert vals[0] == 1614834367
    assert all(pd.isna(v) for v in vals[1:])


def test_nonutc_timezone_falls_back(sess):
    df, _ = date_df(sess, n=20)
    sess.conf.set("spark.sql.session.timeZone", "America/New_York")
    try:
        q = df.select(df.u, F.hour(df.ts).alias("h"))
        report = sess.explain(q)
        assert "not UTC" in report
    finally:
        sess.conf.set("spark.sql.session.timeZone", "UTC")


def test_to_timestamp_flexible_default(sess):
    t = pa.table({"s": ["2021-03-04", "2021-03-04 05:06:07",
                        "2021-03-04T05:06:07.123456", "garbage", None],
                  "u": list(range(5))})
    df = sess.create_dataframe(t)
    out = run_both(df.select(df.u, F.to_timestamp(df.s).alias("ts")))
    vals = out["ts"].tolist()
    assert vals[0] == pd.Timestamp("2021-03-04", tz="UTC")
    assert vals[1] == pd.Timestamp("2021-03-04 05:06:07", tz="UTC")
    assert vals[2] == pd.Timestamp("2021-03-04 05:06:07.123456", tz="UTC")
    assert pd.isna(vals[3]) and pd.isna(vals[4])


def test_time_only_pattern_epoch_base(sess):
    t = pa.table({"s": ["05:06:07"], "u": [0]})
    df = sess.create_dataframe(t)
    out = run_both(df.select(
        df.u, F.unix_timestamp(df.s, "HH:mm:ss").alias("ts")))
    assert out["ts"].tolist() == [5 * 3600 + 6 * 60 + 7]
