"""Decimal128 end-to-end aggregation (VERDICT r3 #4).

The reference aggregates decimal(19-38) on device via
``Aggregation128Utils`` chunked-int32 extraction
(``AggregateFunctions.scala:902``); this engine's analog lives in
``ops/decimal128.py`` (chunked int64 XLA programs) and is wired into
Sum/Average, string casts, and MakeDecimal.  Every test here checks
against exact Python ``decimal`` arithmetic — an independent oracle."""

import decimal
from decimal import Decimal as D

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.sql import functions as F

decimal.getcontext().prec = 80


@pytest.fixture(scope="module")
def sess():
    return srt.session()


def _rand_decimals(rng, n, precision, scale, null_rate=0.1):
    digits = precision
    vals = []
    for _ in range(n):
        if rng.random() < null_rate:
            vals.append(None)
            continue
        ndig = int(rng.integers(1, digits + 1))
        mag = int("".join(rng.choice(list("0123456789"), ndig)) or "0")
        if mag > 10 ** precision - 1:
            mag = mag % (10 ** precision)
        sign = -1 if rng.random() < 0.5 else 1
        vals.append(D(sign * mag).scaleb(-scale))
    return vals


@pytest.mark.parametrize("precision,scale", [(20, 2), (30, 6), (38, 10)])
def test_sum_avg_vs_python_decimal(sess, precision, scale):
    rng = np.random.default_rng(precision)
    n, n_keys = 4000, 37
    vals = _rand_decimals(rng, n, precision - 2, scale)
    keys = rng.integers(0, n_keys, n)
    t = pa.table({"k": pa.array(keys, type=pa.int64()),
                  "d": pa.array(vals, type=pa.decimal128(precision, scale))})
    df = sess.create_dataframe(t, num_partitions=4)
    got = (df.groupBy("k")
           .agg(F.sum(F.col("d")).alias("s"), F.avg(F.col("d")).alias("a"),
                F.count(F.col("d")).alias("c"))
           .orderBy("k").collect().to_pylist())
    by_key = {}
    for k, v in zip(keys, vals):
        if v is not None:
            by_key.setdefault(int(k), []).append(v)
    sum_prec = min(precision + 10, 38)
    for row in got:
        grp = by_key.get(row["k"], [])
        if not grp:
            assert row["s"] is None and row["a"] is None
            continue
        exp_sum = sum(grp)
        if abs(int(exp_sum.scaleb(scale))) > 10 ** sum_prec - 1:
            exp_sum = None  # overflows the sum's decimal type -> null
        assert row["s"] == exp_sum, (row["k"], row["s"], exp_sum)
        q = D(1).scaleb(-(scale + 4))
        exp_avg = (sum(grp) / len(grp)).quantize(
            q, rounding=decimal.ROUND_HALF_UP)
        if abs(int(exp_avg.scaleb(scale + 4))) > 10 ** 38 - 1:
            exp_avg = None  # result precision capped at 38 -> null
        assert row["a"] == exp_avg, (row["k"], row["a"], exp_avg)
        assert row["c"] == len(grp)


def test_two_phase_shuffled_aggregation(sess):
    """Partial buffers (the four chunk sums) must merge exactly across a
    real shuffle — the distributed two-phase path, not the fused
    complete-mode one."""
    rng = np.random.default_rng(7)
    n = 6000
    vals = _rand_decimals(rng, n, 24, 3, null_rate=0.05)
    keys = rng.integers(0, 500, n)
    t = pa.table({"k": pa.array(keys, type=pa.int64()),
                  "d": pa.array(vals, type=pa.decimal128(26, 3))})
    df = sess.create_dataframe(t, num_partitions=5)
    got = (df.repartition(5, "k").groupBy("k")
           .agg(F.sum(F.col("d")).alias("s"))
           .orderBy("k").collect().to_pandas())
    by_key = {}
    for k, v in zip(keys, vals):
        if v is not None:
            by_key.setdefault(int(k), D(0))
            by_key[int(k)] += v
    for _, row in got.iterrows():
        exp = by_key.get(int(row["k"]))
        if exp is None:
            assert row["s"] is None
        else:
            assert row["s"] == exp, (row["k"], row["s"], exp)


def test_sum_overflow_nulls_group(sess):
    vals = [D("9" * 37).scaleb(-2)] * 50   # 50 * ~1e35 > 10^38-1? no:
    # 50 * (10^37-1) ~ 5e38 > 10^38-1 -> overflow
    t = pa.table({"k": pa.array([1] * 50, type=pa.int64()),
                  "d": pa.array(vals, type=pa.decimal128(38, 2))})
    got = (sess.create_dataframe(t).groupBy("k")
           .agg(F.sum(F.col("d")).alias("s")).collect().to_pylist())
    assert got[0]["s"] is None


def test_long_backed_input_dec128_result(sess):
    """sum(decimal(12,2)) -> decimal(22,2): long-backed input must
    sign-extend into the high word before chunking."""
    rng = np.random.default_rng(3)
    vals = [D(int(rng.integers(-10**11, 10**11))).scaleb(-2)
            for _ in range(3000)]
    keys = rng.integers(0, 11, 3000)
    t = pa.table({"k": pa.array(keys, type=pa.int64()),
                  "d": pa.array(vals, type=pa.decimal128(12, 2))})
    got = (sess.create_dataframe(t, num_partitions=3).groupBy("k")
           .agg(F.sum(F.col("d")).alias("s")).orderBy("k")
           .collect().to_pylist())
    for row in got:
        exp = sum(v for k, v in zip(keys, vals) if int(k) == row["k"])
        assert row["s"] == exp


def test_cast_string_to_decimal128_fuzz(sess):
    rng = np.random.default_rng(9)
    strs = []
    for _ in range(2000):
        ndig = int(rng.integers(1, 40))
        mag = "".join(rng.choice(list("0123456789"), ndig))
        dot = int(rng.integers(0, len(mag) + 1))
        s = (mag[:dot] + "." + mag[dot:]) if dot < len(mag) else mag
        if rng.random() < 0.5:
            s = "-" + s
        if rng.random() < 0.2:
            s = s + f"e{int(rng.integers(-10, 10))}"
        strs.append(s)
    strs += ["", " ", ".", "1..2", "++1", "1e", None, "0", "-0.0"]
    t = pa.table({"s": pa.array(strs, type=pa.string())})
    df = sess.create_dataframe(t, num_partitions=2)
    q = df.select(F.col("s").cast(T.DecimalType(38, 6)).alias("d"))
    assert "cannot run" not in sess.explain(q)
    got = [r["d"] for r in q.collect().to_pylist()]
    for s, g in zip(strs, got):
        if s is None:
            assert g is None
            continue
        try:
            v = D(s.strip())
        except decimal.InvalidOperation:
            assert g is None, (s, g)
            continue
        u = int(v.scaleb(6).quantize(0, rounding=decimal.ROUND_HALF_UP))
        exp = D(u).scaleb(-6) if abs(u) <= 10 ** 38 - 1 else None
        assert g == exp, (s, g, exp)


def test_unscaled_value_still_rejects_dec128(sess):
    """UnscaledValue returns LONG by contract; decimal128 cannot fit —
    the device must keep rejecting it (it would truncate), like the
    reference where only long-backed decimals reach GpuUnscaledValue."""
    from spark_rapids_tpu.sql.expressions.arithmetic import UnscaledValue
    from spark_rapids_tpu.sql import functions as F2
    t = pa.table({"d": pa.array([D("1.23")], type=pa.decimal128(25, 2))})
    df = sess.create_dataframe(t)
    col = df._col("d")
    expr = UnscaledValue(col.expr)
    assert expr.tag_for_device() is not None


def test_make_decimal_128(sess):
    from spark_rapids_tpu.sql.expressions.arithmetic import MakeDecimal
    from spark_rapids_tpu.sql.dataframe import Column
    rng = np.random.default_rng(4)
    raw = [int(x) for x in rng.integers(-10**18, 10**18, 500)]
    t = pa.table({"v": pa.array(raw, type=pa.int64())})
    df = sess.create_dataframe(t)
    out = df.select(Column(MakeDecimal(df._col("v").expr, 28, 4))
                    .alias("d")).collect().to_pylist()
    for r, row in zip(raw, out):
        assert row["d"] == D(r).scaleb(-4)


def test_arithmetic_dec128_vs_python(sess):
    """+/-/* run on device with chunked 128-bit kernels; / falls to the
    host's exact Python-int path — all checked against decimal."""
    rng = np.random.default_rng(11)
    n = 1500
    a_vals = [D(int(rng.integers(-10**15, 10**15))
               * int(rng.integers(1, 10**7))).scaleb(-2) for _ in range(n)]
    b_vals = [D(int(rng.integers(-10**15, 10**15))
               * int(rng.integers(1, 10**7)) + 1).scaleb(-2)
              for _ in range(n)]
    t = pa.table({"a": pa.array(a_vals, type=pa.decimal128(25, 2)),
                  "b": pa.array(b_vals, type=pa.decimal128(25, 2))})
    df = sess.create_dataframe(t, num_partitions=2)
    got = df.select((df.a + df.b).alias("s"), (df.a - df.b).alias("d"),
                    (df.a * df.b).alias("m")).collect().to_pylist()
    for row, x, y in zip(got, a_vals, b_vals):
        assert row["s"] == x + y
        assert row["d"] == x - y
        p = x * y  # result decimal(38, 4): overflow -> null
        exp = p if abs(int(p.scaleb(4))) <= 10 ** 38 - 1 else None
        assert row["m"] == exp, (x, y, row["m"], exp)


def test_divide_dec128_host_exact(sess):
    a, b = D("12345678901234567890123.45"), D("98765432109876543210987.65")
    t = pa.table({"a": pa.array([a], type=pa.decimal128(25, 2)),
                  "b": pa.array([b], type=pa.decimal128(25, 2))})
    df = sess.create_dataframe(t)
    q = df.select((df.a / df.b).alias("r"))
    assert "cannot run" in sess.explain(q)  # tagged to the host path
    got = q.collect().to_pylist()[0]["r"]
    scale = got.as_tuple().exponent * -1
    exp = (a / b).quantize(D(1).scaleb(-scale),
                           rounding=decimal.ROUND_HALF_UP)
    assert got == exp


def test_shuffled_group_by_dec128_key(sess):
    """Hash partitioning over a decimal128 key (murmur3/xxhash64 over the
    minimal two's-complement bytes, like Spark's BigInteger.toByteArray
    path) — previously raised NotImplementedError."""
    k1, k2 = D("1" + "0" * 20 + ".00"), D("-2.00")
    t = pa.table({"k": pa.array([k1] * 300 + [k2] * 200,
                                type=pa.decimal128(25, 2)),
                  "v": np.arange(500, dtype=np.float64)})
    df = sess.create_dataframe(t, num_partitions=4).repartition(4, "k")
    got = df.groupBy("k").agg(F.count("*").alias("c")).collect().to_pylist()
    assert sorted((str(r["k"]), r["c"]) for r in got) == \
        [("-2.00", 200), (str(k1), 300)]


def test_dec128_hash_byte_matrix_minimal():
    """The device byte-matrix equals Python's minimal signed to_bytes
    (== Java BigInteger.toByteArray) for 500+ random + edge values."""
    from spark_rapids_tpu.columnar.column import DeviceColumn
    from spark_rapids_tpu.sql.expressions.hashing import _dec128_byte_matrix
    rng = np.random.default_rng(0)
    vals = [0, -1, 1, 127, 128, -128, -129, 255, 10**20, -10**20,
            10**37, -(10**37), 2**64, -(2**64), 2**95 + 12345]
    vals += [int(rng.integers(-2**62, 2**62)) * int(rng.integers(1, 2**60))
             for _ in range(500)]

    def words(v):
        u = v & ((1 << 128) - 1)
        lo, hi = u & ((1 << 64) - 1), u >> 64
        return (lo - (1 << 64) if lo >= (1 << 63) else lo,
                hi - (1 << 64) if hi >= (1 << 63) else hi)

    lo = np.array([words(v)[0] for v in vals], dtype=np.int64)
    hi = np.array([words(v)[1] for v in vals], dtype=np.int64)
    col = DeviceColumn(T.DecimalType(38, 0), lo,
                       np.ones(len(vals), bool), aux=hi)
    chars, lengths = _dec128_byte_matrix(np, col)
    for i, v in enumerate(vals):
        n = max((v.bit_length() // 8) + 1, 1) if v >= 0 \
            else ((v + 1).bit_length() // 8) + 1
        assert bytes(chars[i, :lengths[i]]) == v.to_bytes(n, "big",
                                                          signed=True), v
