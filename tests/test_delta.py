"""Delta-analog ACID table layer (reference delta-lake/ module: txn log,
snapshot reads, time travel, DELETE/UPDATE/MERGE, OPTIMIZE ZORDER,
VACUUM, optimistic concurrency)."""

import os

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.delta import DeltaLog, DeltaTable
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def make_table(sess, path, n=100):
    t = pa.table({"id": pa.array(range(n), type=pa.int64()),
                  "v": pa.array([float(i) * 1.5 for i in range(n)]),
                  "s": [f"row{i:03d}" for i in range(n)]})
    df = sess.create_dataframe(t)
    return DeltaTable.create(sess, str(path), df), t


def test_create_read_roundtrip(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "t1")
    got = dt.toDF().orderBy("id").collect()
    assert got.num_rows == 100
    assert got["id"].to_pylist() == list(range(100))
    assert DeltaTable.is_delta_table(str(tmp_path / "t1"))
    assert not DeltaTable.is_delta_table(str(tmp_path))


def test_append_and_time_travel(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "t2")
    more = sess.create_dataframe(pa.table({
        "id": pa.array(range(100, 150), type=pa.int64()),
        "v": pa.array([0.0] * 50), "s": ["x"] * 50}))
    dt.write_df(more, mode="append")
    assert dt.toDF().count() == 150
    # version 0 still shows the original 100 rows
    assert dt.toDF(version=0).count() == 100
    # reader API: format("delta") + versionAsOf
    df_v0 = (sess.read.format("delta").option("versionAsOf", 0)
             .load(str(tmp_path / "t2")))
    assert df_v0.count() == 100
    df_now = sess.read.format("delta").load(str(tmp_path / "t2"))
    assert df_now.count() == 150


def test_writer_format_delta(sess, tmp_path):
    t = pa.table({"a": [1, 2, 3]})
    df = sess.create_dataframe(t)
    df.write.format("delta").save(str(tmp_path / "t3"))
    df.write.format("delta").mode("append").save(str(tmp_path / "t3"))
    assert DeltaTable.forPath(sess, str(tmp_path / "t3")).toDF().count() == 6
    df.write.format("delta").mode("overwrite").save(str(tmp_path / "t3"))
    assert DeltaTable.forPath(sess, str(tmp_path / "t3")).toDF().count() == 3


def test_delete(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "t4")
    n = dt.delete(lambda df: df.id < 30)
    assert n == 30
    got = dt.toDF().orderBy("id").collect()
    assert got.num_rows == 70
    assert got["id"].to_pylist() == list(range(30, 100))
    # history records the operations
    ops = [h["operation"] for h in dt.history()]
    assert ops[0] == "DELETE"


def test_update(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "t5")
    n = dt.update(lambda df: df.id >= 95, set={"v": lambda df: df.v * 0.0})
    assert n == 5
    got = dt.toDF().orderBy("id").collect().to_pandas()
    assert (got[got.id >= 95]["v"] == 0.0).all()
    assert (got[got.id < 95]["v"] != 0.0).sum() > 90


def test_merge_update_and_insert(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "t6", n=50)
    src = sess.create_dataframe(pa.table({
        "id": pa.array([10, 20, 99, 100], type=pa.int64()),
        "v": pa.array([-1.0, -2.0, -3.0, -4.0]),
        "s": ["u10", "u20", "n99", "n100"]}))
    stats = (dt.merge(src, on=["id"])
             .whenMatchedUpdate(set={"v": F.lit(-7.0)})
             .whenNotMatchedInsertAll()
             .execute())
    assert stats["updated"] == 2 and stats["inserted"] == 2
    got = dt.toDF().orderBy("id").collect().to_pandas()
    assert len(got) == 52
    assert got[got.id == 10]["v"].iloc[0] == -7.0
    assert got[got.id == 20]["v"].iloc[0] == -7.0
    assert got[got.id == 99]["v"].iloc[0] == -3.0
    assert got[got.id == 100]["v"].iloc[0] == -4.0


def test_merge_delete(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "t7", n=30)
    src = sess.create_dataframe(pa.table({
        "id": pa.array([5, 6, 7], type=pa.int64())}))
    stats = dt.merge(src, on=["id"]).whenMatchedDelete().execute()
    assert stats["deleted"] == 3
    ids = dt.toDF().collect()["id"].to_pylist()
    assert 5 not in ids and 6 not in ids and 7 not in ids
    assert len(ids) == 27


def test_optimize_zorder_and_vacuum(sess, tmp_path):
    path = tmp_path / "t8"
    dt, t = make_table(sess, path, n=200)
    # append more files so OPTIMIZE has something to compact
    for k in range(3):
        dt.write_df(sess.create_dataframe(pa.table({
            "id": pa.array(range(200 + k * 10, 210 + k * 10),
                           type=pa.int64()),
            "v": pa.array([1.0] * 10), "s": ["a"] * 10})))
    before = len(dt.log.snapshot().file_paths)
    assert before == 4
    compacted = dt.optimize_zorder(["id", "v"], target_files=1)
    assert compacted == 4
    snap = dt.log.snapshot()
    assert len(snap.file_paths) == 1
    # contents unchanged
    got = dt.toDF().orderBy("id").collect()
    assert got.num_rows == 230
    # old files are unreferenced now; vacuum removes them from disk
    removed = dt.vacuum()
    assert len(removed) == 4
    assert dt.toDF().count() == 230


def test_zorder_clusters_rows(sess, tmp_path):
    """Rows close on the z-curve of (x, y) land close in row order."""
    from spark_rapids_tpu.delta.zorder import zorder_indices
    rng = np.random.default_rng(0)
    t = pa.table({"x": rng.integers(0, 100, 1000),
                  "y": rng.integers(0, 100, 1000)})
    order = zorder_indices(t, ["x", "y"])
    clustered = t.take(pa.array(order)).to_pandas()
    # quadrant purity: the first quarter of rows must be dominated by the
    # low-x/low-y quadrant (a random order would give ~25%)
    q = clustered.iloc[:250]
    frac = ((q.x < 50) & (q.y < 50)).mean()
    assert frac > 0.8, frac


def test_concurrent_append_both_commit(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "t9", n=10)
    log2 = DeltaLog(str(tmp_path / "t9"))
    # two writers race an append: both must land (blind appends never
    # conflict, OptimisticTransaction semantics)
    a = sess.create_dataframe(pa.table({
        "id": pa.array([100], type=pa.int64()), "v": [1.0], "s": ["a"]}))
    b = sess.create_dataframe(pa.table({
        "id": pa.array([101], type=pa.int64()), "v": [2.0], "s": ["b"]}))
    dt.write_df(a)
    DeltaTable(sess, str(tmp_path / "t9")).write_df(b)
    assert dt.toDF().count() == 12
    versions = dt.log.versions()
    assert versions == sorted(set(versions))


def test_cache_parquet_serializer(sess):
    """df.persist() holds compressed parquet bytes, decoded on re-read
    (ParquetCachedBatchSerializer analog)."""
    import spark_rapids_tpu.sql.plan as P
    t = pa.table({"a": list(range(1000)),
                  "s": [f"value-{i % 13}" for i in range(1000)]})
    df = sess.create_dataframe(t)
    cached = df.filter(df.a < 500).cache()
    assert isinstance(cached._plan, P.CachedRelation)
    assert len(cached._plan.blob) > 0
    got = cached.orderBy("a").collect()
    assert got.num_rows == 500
    assert got["a"].to_pylist() == list(range(500))
    # cached frame is re-queryable through the engine
    assert cached.filter(cached.a >= 250).count() == 250


def test_hive_text_roundtrip(sess, tmp_path):
    """hive-text: ^A-delimited headerless files (GpuHiveTextFileFormat)."""
    t = pa.table({"a": pa.array([1, 2, 3], type=pa.int64()),
                  "b": ["x", "y", "z"]})
    df = sess.create_dataframe(t)
    out = str(tmp_path / "hive_tbl")
    df.write.format("hivetext").mode("overwrite").save(out)
    got = (sess.read.format("hivetext").load(out)
           .orderBy("_c0").collect())
    assert got.num_rows == 3
    assert got["_c0"].to_pylist() == [1, 2, 3]
    assert got["_c1"].to_pylist() == ["x", "y", "z"]
    # raw file uses the ^A delimiter
    import glob
    files = glob.glob(out + "/*.txt")
    assert files, "no hive-text data files written"
    raw = open(files[0], "rb").read()
    assert b"\x01" in raw


def test_delete_preserves_null_condition_rows(sess, tmp_path):
    """SQL three-valued logic: DELETE WHERE v > 5 must NOT delete rows
    whose v is NULL (review r2 finding)."""
    t = pa.table({"id": pa.array([1, 2, 3], type=pa.int64()),
                  "v": pa.array([10.0, None, 1.0], type=pa.float64())})
    dt = DeltaTable.create(sess, str(tmp_path / "tn"),
                           sess.create_dataframe(t))
    n = dt.delete(lambda df: df.v > 5)
    assert n == 1
    got = dt.toDF().orderBy("id").collect().to_pandas()
    assert got["id"].tolist() == [2, 3]  # the NULL row survives


def test_merge_duplicate_source_keys_raises(sess, tmp_path):
    dt, t = make_table(sess, tmp_path / "td", n=5)
    src = sess.create_dataframe(pa.table({
        "id": pa.array([2, 2], type=pa.int64()),
        "v": [0.0, 1.0], "s": ["a", "b"]}))
    with pytest.raises(ValueError, match="duplicate"):
        dt.merge(src, on=["id"]).whenMatchedUpdate(
            set={"v": F.lit(0.0)}).execute()


def test_delta_save_modes(sess, tmp_path):
    p = str(tmp_path / "tm")
    df = sess.create_dataframe(pa.table({"a": [1, 2]}))
    df.write.format("delta").save(p)
    with pytest.raises(FileExistsError):
        df.write.format("delta").save(p)  # default errorifexists
    df.write.format("delta").mode("ignore").save(p)  # no-op
    assert DeltaTable.forPath(sess, p).toDF().count() == 2


def test_delta_partitioned_write(sess, tmp_path):
    p = str(tmp_path / "tp")
    t = pa.table({"g": ["x", "y", "x", "y"], "v": [1, 2, 3, 4]})
    df = sess.create_dataframe(t)
    df.write.format("delta").partitionBy("g").save(p)
    snap = DeltaTable.forPath(sess, p).log.snapshot()
    assert snap.partition_columns == ("g",)
    assert all("g=" in f for f in snap.file_paths)
    got = DeltaTable.forPath(sess, p).toDF().orderBy("v").collect()
    assert got["v"].to_pylist() == [1, 2, 3, 4]
    with pytest.raises(KeyError):
        df.write.format("delta").mode("overwrite") \
            .partitionBy("nope").save(p)


def test_explicit_repartition_not_coalesced(sess):
    """User repartition(n) is exempt from AQE partition coalescing."""
    t = pa.table({"a": list(range(1000))})
    df = sess.create_dataframe(t).repartition(4)

    def mapper(it):
        pdfs = list(it)
        yield pd.DataFrame({"n": [float(sum(len(p) for p in pdfs))]})
    counts = df.mapInPandas(mapper, "n double").collect()["n"].to_pylist()
    assert len(counts) == 4, counts  # one output per partition
    assert sum(counts) == 1000


def test_concurrent_delete_conflict_detected(sess, tmp_path):
    """Two DELETEs from the same snapshot: the second must raise instead
    of silently resurrecting the first one's deleted rows."""
    from spark_rapids_tpu.delta import ConcurrentModificationException
    from spark_rapids_tpu.delta.log import remove_action
    dt, t = make_table(sess, tmp_path / "tc", n=20)
    snap = dt.log.snapshot()
    # writer B commits a non-append first (from the same snapshot)
    dt.log.commit([remove_action(snap.file_paths[0])], "DELETE",
                  read_version=snap.version)
    # writer A (stale read_version) must now fail its non-append commit
    with pytest.raises(ConcurrentModificationException):
        dt.log.commit([remove_action(snap.file_paths[0])], "DELETE",
                      read_version=snap.version)


# ---------------------------------------------------------------------------
# round-2 late additions: stats/skipping, checkpoints, evolution, constraints
# ---------------------------------------------------------------------------

def test_add_actions_carry_stats(sess, tmp_path):
    dt, _ = make_table(sess, tmp_path / "t", n=50)
    snap = dt.log.snapshot()
    (path,) = snap.file_paths
    st = snap.files[path].stats
    assert st["numRecords"] == 50
    assert st["minValues"]["id"] == 0 and st["maxValues"]["id"] == 49
    assert st["minValues"]["s"] == "row000"
    assert st["nullCount"]["v"] == 0


def test_data_skipping_limits_rewritten_files(sess, tmp_path):
    dt, _ = make_table(sess, tmp_path / "t", n=10)
    # three more files with disjoint id ranges
    for lo in (100, 200, 300):
        t = pa.table({"id": pa.array(range(lo, lo + 10), type=pa.int64()),
                      "v": [1.0] * 10, "s": ["x"] * 10})
        dt.write_df(sess.create_dataframe(t))
    snap = dt.log.snapshot()
    assert len(snap.file_paths) == 4
    dummy = sess.create_dataframe(dt.toDF().collect().slice(0, 0))
    matching = dt._files_matching(snap, dummy.id >= 300)
    assert len(matching) == 1
    # delete touches only the matching file; others keep their add files
    before = set(snap.file_paths)
    deleted = dt.delete(lambda df: df.id >= 300)
    assert deleted == 10
    after = set(dt.log.snapshot().file_paths)
    assert len(before - after) == 1  # exactly one file rewritten/removed
    assert dt.toDF().count() == 30


def test_checkpoint_written_and_replayed(sess, tmp_path):
    dt, _ = make_table(sess, tmp_path / "t", n=4)
    for i in range(12):  # cross the checkpoint interval (10)
        t = pa.table({"id": pa.array([1000 + i], type=pa.int64()),
                      "v": [0.5], "s": ["a"]})
        dt.write_df(sess.create_dataframe(t))
    ck = dt.log.last_checkpoint_version()
    assert ck is not None and ck >= 10
    assert os.path.exists(dt.log._checkpoint_file(ck))
    # snapshot built via checkpoint replay equals full-log replay
    snap = dt.log.snapshot()
    assert dt.toDF().count() == 4 + 12
    # time travel before the checkpoint still works (full replay path)
    assert dt.toDF(version=0).count() == 4
    assert snap.schema is not None


def test_schema_evolution_merge_schema(sess, tmp_path):
    dt, _ = make_table(sess, tmp_path / "t", n=3)
    t2 = pa.table({"id": pa.array([10, 11], type=pa.int64()),
                   "v": [1.0, 2.0], "s": ["a", "b"],
                   "extra": pa.array([7, 8], type=pa.int64())})
    with pytest.raises(ValueError):
        dt.write_df(sess.create_dataframe(t2))
    dt.write_df(sess.create_dataframe(t2), merge_schema=True)
    out = dt.toDF().collect().to_pandas().sort_values("id")
    assert list(out.columns) == ["id", "v", "s", "extra"]
    assert out["extra"].isna().sum() == 3  # old rows null-filled
    assert set(out["extra"].dropna()) == {7, 8}


def test_constraints_enforced(sess, tmp_path):
    dt, _ = make_table(sess, tmp_path / "t", n=5)
    dt.add_check_constraint("v_nonneg", "v", ">=", 0.0)
    dt.add_not_null_constraint("s")
    bad = pa.table({"id": pa.array([99], type=pa.int64()),
                    "v": [-1.0], "s": ["z"]})
    with pytest.raises(ValueError, match="CHECK constraint"):
        dt.write_df(sess.create_dataframe(bad))
    bad2 = pa.table({"id": pa.array([99], type=pa.int64()),
                     "v": [1.0], "s": pa.array([None], type=pa.string())})
    with pytest.raises(ValueError, match="NOT NULL"):
        dt.write_df(sess.create_dataframe(bad2))
    ok = pa.table({"id": pa.array([99], type=pa.int64()),
                   "v": [1.0], "s": ["ok"]})
    dt.write_df(sess.create_dataframe(ok))
    assert dt.toDF().count() == 6
    # NULL check-column value passes (three-valued CHECK semantics)
    nullv = pa.table({"id": pa.array([100], type=pa.int64()),
                      "v": pa.array([None], type=pa.float64()),
                      "s": ["n"]})
    dt.write_df(sess.create_dataframe(nullv))
    assert dt.toDF().count() == 7
    # UPDATE violating the constraint is rejected
    with pytest.raises(ValueError, match="CHECK constraint"):
        dt.update(lambda df: df.id == 99, {"v": -5.0})
