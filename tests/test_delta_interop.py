"""Interop: read Delta tables the engine did NOT write (VERDICT r2 #5).

Fixtures under tests/golden/delta/ are composed by tools/make_golden_delta.py
straight from the public Delta transaction-log protocol — real-format
actions (protocol / metaData with schemaString / add with partitionValues
and JSON stats / remove) over snappy parquet written by pyarrow."""

import os

import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.delta import DeltaTable

GOLDEN = os.path.join(os.path.dirname(__file__), "golden", "delta")


@pytest.fixture()
def sess():
    return srt.session()


def test_foreign_unpartitioned_snapshot(sess):
    t = DeltaTable.forPath(sess, os.path.join(GOLDEN, "people"))
    got = t.toDF().collect().to_pandas().sort_values("id")
    # v2 = after DELETE WHERE score < 7: ids 1,2,3 (file0) + 6 (rewrite)
    assert list(got["id"]) == [1, 2, 3, 6]
    assert list(got["name"]) == ["ada", "bob", "cat", "eve"]
    assert got[got.id == 1].score.iloc[0] == 9.5


def test_foreign_time_travel(sess):
    t = DeltaTable.forPath(sess, os.path.join(GOLDEN, "people"))
    v0 = t.toDF(version=0).collect().to_pandas().sort_values("id")
    assert list(v0["id"]) == [1, 2, 3, 4, 5]
    assert v0["name"].isna().sum() == 1  # null survives the round trip
    v1 = t.toDF(version=1).collect().to_pandas().sort_values("id")
    assert list(v1["id"]) == [1, 2, 3, 4, 5, 6, 7]


def test_foreign_reader_api(sess):
    df = (sess.read.format("delta").option("versionAsOf", 1)
          .load(os.path.join(GOLDEN, "people")))
    assert df.count() == 7


def test_foreign_partitioned_injects_partition_values(sess):
    """Real Delta partition columns live ONLY in add.partitionValues;
    the reader must materialize them as constants per file."""
    t = DeltaTable.forPath(sess, os.path.join(GOLDEN, "events"))
    got = t.toDF().collect().to_pandas()
    assert len(got) == 8
    assert set(got["day"]) == {"2025-01-01", "2025-01-02"}
    assert (got.groupby("day").size() == 4).all()
    assert got["ts"].notna().all() and got["kind"].notna().all()


def test_foreign_stats_populate_file_metadata(sess):
    """Real stats JSON (numRecords/minValues/...) must feed the snapshot's
    per-file record counts even though the engine's own writer uses a
    different top-level field."""
    t = DeltaTable.forPath(sess, os.path.join(GOLDEN, "people"))
    snap = t.log.snapshot(0)
    assert sorted(a.num_records for a in snap.files.values()) == [2, 3]
    assert all(a.stats and "minValues" in a.stats
               for a in snap.files.values())


def test_unsupported_protocol_fails_loudly(sess):
    """minReaderVersion=3 (deletion vectors): silently ignoring the
    protocol action would return deleted rows — must raise instead."""
    t = DeltaTable.forPath(sess, os.path.join(GOLDEN, "unsupported_dv"))
    with pytest.raises(ValueError, match="minReaderVersion"):
        t.toDF()


def test_engine_written_tables_still_read(sess, tmp_path):
    """The engine's native action form keeps working alongside the
    foreign form."""
    import pyarrow as pa
    df = sess.create_dataframe(pa.table({"a": [1, 2, 3]}))
    t = DeltaTable.create(sess, str(tmp_path / "own"), df)
    assert t.toDF().count() == 3


def test_foreign_partitioned_survives_checkpoint(sess, tmp_path):
    """Checkpoints must carry partitionValues — a checkpointed foreign
    partitioned table read back with null partition columns would be
    silent corruption."""
    import shutil
    work = str(tmp_path / "events")
    shutil.copytree(os.path.join(GOLDEN, "events"), work)
    t = DeltaTable.forPath(sess, work)
    t.log.write_checkpoint()
    got = (DeltaTable.forPath(sess, work).toDF()
           .collect().to_pandas())
    assert got["day"].notna().all()
    assert set(got["day"]) == {"2025-01-01", "2025-01-02"}


def test_foreign_partitioned_dml_preserves_partition_values(sess, tmp_path):
    """DELETE on a foreign partitioned table rewrites touched files; the
    surviving rows must keep their partition values."""
    import shutil
    work = str(tmp_path / "events")
    shutil.copytree(os.path.join(GOLDEN, "events"), work)
    t = DeltaTable.forPath(sess, work)
    before = t.toDF().collect().to_pandas()
    kinds = before.groupby("day").kind.apply(list).to_dict()
    n_clicks = int((before.kind == "click").sum())
    deleted = t.delete(lambda df: df.kind == "click")
    assert deleted == n_clicks
    after = t.toDF().collect().to_pandas()
    assert after["day"].notna().all()
    assert (after.kind == "view").all()
    assert len(after) == int((before.kind == "view").sum())


def test_foreign_checkpoint_layout_detected(sess, tmp_path):
    """A Spark-style columnar checkpoint (no `action` column) must be
    skipped in favor of JSON replay, not crash."""
    import pyarrow as pa_
    import pyarrow.parquet as pq_
    import shutil
    work = str(tmp_path / "people")
    shutil.copytree(os.path.join(GOLDEN, "people"), work)
    t = DeltaTable.forPath(sess, work)
    # fake a foreign columnar checkpoint at the tip
    v = t.log.latest_version()
    pq_.write_table(pa_.table({"add": [None], "remove": [None]},
                              schema=pa_.schema([("add", pa_.string()),
                                                 ("remove", pa_.string())])),
                    os.path.join(work, "_delta_log",
                                 f"{v:020d}.checkpoint.parquet"))
    import json as _json
    with open(os.path.join(work, "_delta_log", "_last_checkpoint"),
              "w") as fh:
        _json.dump({"version": v, "size": 2}, fh)
    got = DeltaTable.forPath(sess, work).toDF().collect().to_pandas()
    assert sorted(got["id"]) == [1, 2, 3, 6]


def test_timestamp_as_of_time_travel(sess, tmp_path):
    """timestampAsOf resolves the latest commit at-or-before the given
    time (Spark's rule); earlier-than-first-commit errors like Delta."""
    import json as _json
    import shutil
    work = str(tmp_path / "people")
    shutil.copytree(os.path.join(GOLDEN, "people"), work)
    # give the three commits distinct, known timestamps
    logd = os.path.join(work, "_delta_log")
    for v, ts in [(0, 1_000_000), (1, 2_000_000), (2, 3_000_000)]:
        p = os.path.join(logd, f"{v:020d}.json")
        lines = [_json.loads(ln) for ln in open(p)]
        for a in lines:
            if "commitInfo" in a:
                a["commitInfo"]["timestamp"] = ts
        with open(p, "w") as fh:
            for a in lines:
                fh.write(_json.dumps(a) + "\n")
    t = DeltaTable.forPath(sess, work)
    assert t.toDF(timestamp_ms=1_500_000).count() == 5   # v0
    assert t.toDF(timestamp_ms=2_000_000).count() == 7   # v1 (inclusive)
    assert t.toDF(timestamp_ms=9_999_999).count() == 4   # v2 (latest)
    with pytest.raises(ValueError, match="before the earliest"):
        t.toDF(timestamp_ms=999)
    # reader-option surface, date-string form (far future => latest)
    df = (sess.read.format("delta").option("timestampAsOf", "2030-01-01")
          .load(work))
    assert df.count() == 4
    with pytest.raises(ValueError, match="not both"):
        t.toDF(version=1, timestamp_ms=2_000_000)


def test_timestamp_as_of_monotonic_adjustment(sess, tmp_path):
    """Out-of-order commit timestamps (clock skew) and commitInfo-less
    commits: timestamps adjust to be non-decreasing before the search,
    like Delta."""
    import json as _json
    import shutil
    work = str(tmp_path / "people")
    shutil.copytree(os.path.join(GOLDEN, "people"), work)
    logd = os.path.join(work, "_delta_log")
    # v0: 1000, v1: 3000, v2: 2000 (skewed) -> adjusted [1000, 3000, 3000]
    for v, ts in [(0, 1000), (1, 3000), (2, 2000)]:
        p = os.path.join(logd, f"{v:020d}.json")
        lines = [_json.loads(ln) for ln in open(p)]
        for a in lines:
            if "commitInfo" in a:
                a["commitInfo"]["timestamp"] = ts
        with open(p, "w") as fh:
            for a in lines:
                fh.write(_json.dumps(a) + "\n")
    t = DeltaTable.forPath(sess, work)
    assert t.toDF(timestamp_ms=2500).count() == 5   # v0 only (v1 adj 3000)
    assert t.toDF(timestamp_ms=3000).count() == 4   # v2 (adjusted 3000)


def test_timestamp_as_of_no_commit_info_uses_file_mtime(sess, tmp_path):
    """commitInfo is optional in the protocol; a foreign writer may omit
    it entirely.  The commit file's mtime then stands in for its
    timestamp (Delta's DeltaHistoryManager rule) — previously such
    commits were treated as timestamp 0, resolving ANY timestampAsOf to
    the latest version (advisor r3)."""
    import json as _json
    import shutil
    work = str(tmp_path / "people")
    shutil.copytree(os.path.join(GOLDEN, "people"), work)
    logd = os.path.join(work, "_delta_log")
    for v, ts in [(0, 1_000_000), (1, 2_000_000), (2, 3_000_000)]:
        p = os.path.join(logd, f"{v:020d}.json")
        lines = [_json.loads(ln) for ln in open(p)
                 if "commitInfo" not in ln]
        with open(p, "w") as fh:
            for a in lines:
                fh.write(_json.dumps(a) + "\n")
        os.utime(p, (ts / 1000, ts / 1000))
    t = DeltaTable.forPath(sess, work)
    assert t.toDF(timestamp_ms=1_500_000).count() == 5   # v0
    assert t.toDF(timestamp_ms=2_000_000).count() == 7   # v1 (inclusive)
    with pytest.raises(ValueError, match="before the earliest"):
        t.toDF(timestamp_ms=999_999)
