"""Device-side CSV parse (io_/device_csv.py) — oracle-equal against the
host pyarrow reader; every out-of-envelope shape must DECLINE (return
None), never mis-parse.  Reference: ``GpuCSVScan.scala:355``."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.io_.device_csv import decode_file
from spark_rapids_tpu.columnar import device_to_arrow


class _F:
    def __init__(self, name, dtype):
        self.name = name
        self.dtype = dtype


def _decode(path, fields, options=None):
    return decode_file(str(path), options or {"header": "true"}, fields)


def test_basic_types(tmp_path):
    p = tmp_path / "t.csv"
    p.write_text(
        "i,f,s,b,d\n"
        "1,1.5,alpha,true,2020-01-31\n"
        "-42,2.25e3,beta,false,1999-12-01\n"
        ",,,,\n"
        "7,-0.125,,TRUE,2024-02-29\n")
    fields = [_F("i", T.LongType()), _F("f", T.DoubleType()),
              _F("s", T.StringType()), _F("b", T.BooleanType()),
              _F("d", T.DateType())]
    b = _decode(p, fields)
    assert b is not None
    got = device_to_arrow(b)
    assert got.column("i").to_pylist() == [1, -42, None, 7]
    assert got.column("f").to_pylist() == [1.5, 2250.0, None, -0.125]
    assert got.column("s").to_pylist() == ["alpha", "beta", None, None]
    assert got.column("b").to_pylist() == [True, False, None, True]
    import datetime
    assert got.column("d").to_pylist() == [
        datetime.date(2020, 1, 31), datetime.date(1999, 12, 1), None,
        datetime.date(2024, 2, 29)]


def test_int_widths_and_bounds(tmp_path):
    p = tmp_path / "w.csv"
    p.write_text("a,b\n127,32767\n-128,-32768\n")
    fields = [_F("a", T.ByteType()), _F("b", T.ShortType())]
    got = device_to_arrow(_decode(p, fields))
    assert got.column("a").to_pylist() == [127, -128]
    assert got.column("b").to_pylist() == [32767, -32768]
    # out-of-range for the plan type -> decline (sample-inference drift)
    p2 = tmp_path / "w2.csv"
    p2.write_text("a\n127\n300\n")
    assert _decode(p2, [_F("a", T.ByteType())]) is None


def test_parse_failure_declines_not_nulls(tmp_path):
    p = tmp_path / "bad.csv"
    p.write_text("i\n1\n2\nnot-a-number\n")
    assert _decode(p, [_F("i", T.LongType())]) is None


@pytest.mark.parametrize("content", [
    b'a,b\n"q",2\n',               # quoted field
    b"a,b\r\n1,2\r\n",             # CRLF
    b"a,b\n1\n",                   # ragged row
    b"a,b\n1,2\n\n3,4\n",          # blank interior line
    b"\xef\xbb\xbfa,b\n1,2\n",     # BOM (raw bytes)
])
def test_out_of_envelope_declines(tmp_path, content):
    p = tmp_path / "d.csv"
    p.write_bytes(content)
    assert _decode(p, [_F("a", T.LongType()),
                       _F("b", T.LongType())]) is None


def test_custom_separator_and_headerless(tmp_path):
    p = tmp_path / "h.csv"
    p.write_text("1|x\n2|y\n")
    fields = [_F("_c0", T.LongType()), _F("_c1", T.StringType())]
    got = device_to_arrow(decode_file(
        str(p), {"header": "false", "sep": "|"}, fields))
    assert got.column("_c0").to_pylist() == [1, 2]
    assert got.column("_c1").to_pylist() == ["x", "y"]


def test_hive_text_ctrl_a(tmp_path):
    p = tmp_path / "hive.txt"
    p.write_bytes(b"5\x01alpha\n6\x01beta\n")
    fields = [_F("k", T.LongType()), _F("v", T.StringType())]
    got = device_to_arrow(decode_file(
        str(p), {"header": "false", "sep": "\x01"}, fields))
    assert got.column("k").to_pylist() == [5, 6]
    assert got.column("v").to_pylist() == ["alpha", "beta"]


def test_no_trailing_newline_and_utf8(tmp_path):
    p = tmp_path / "nt.csv"
    p.write_bytes("s,v\ncafé,1\nüber,2".encode("utf-8"))
    fields = [_F("s", T.StringType()), _F("v", T.LongType())]
    got = device_to_arrow(_decode(p, fields))
    assert got.column("s").to_pylist() == ["café", "über"]
    assert got.column("v").to_pylist() == [1, 2]


def test_full_engine_csv_scan_device(tmp_path):
    """Session read.csv rides the device parse by default; results equal
    the host pipeline's and the metric proves engagement."""
    rng = np.random.default_rng(4)
    n = 5000
    lines = ["k,v,s"]
    for i in range(n):
        lines.append(f"{rng.integers(0, 50)},{rng.random():.6f},tag-{i % 7}")
    p = tmp_path / "big.csv"
    p.write_text("\n".join(lines) + "\n")
    sess = srt.session()
    from spark_rapids_tpu.sql import functions as F
    q = (sess.read.csv(str(p)).groupBy("s")
         .agg(F.count("*").alias("n"), F.sum(F.col("v")).alias("sv"))
         .orderBy("s"))
    got = {r["s"]: r for r in q.collect().to_pylist()}
    m = sess.last_query_metrics
    assert m.get("csvDeviceDecodedFiles", 0) >= 1, m
    import pandas as pd
    pdf = pd.read_csv(p)
    exp = pdf.groupby("s").agg(n=("v", "count"), sv=("v", "sum"))
    assert len(got) == len(exp)
    for s, row in exp.iterrows():
        assert got[s]["n"] == int(row["n"])
        assert abs(got[s]["sv"] - row["sv"]) < 1e-6
    # off-switch exercises the host path with equal results
    sess2 = srt.session(**{
        "spark.rapids.sql.format.csv.deviceDecode.enabled": "false"})
    got2 = sess2.read.csv(str(p)).orderBy("k", "s", "v").collect()
    want = sess.read.csv(str(p)).orderBy("k", "s", "v").collect()
    for c in want.column_names:
        a = want.column(c).to_pylist()
        b = got2.column(c).to_pylist()
        if c == "v":
            # parse_double is the engine's CAST parser — documented to
            # sit within 1 ULP of strtod on some literals
            assert np.allclose(a, b, rtol=1e-12), c
        else:
            assert a == b, c
