"""Device-side JSON-lines parse (io_/device_json.py) — oracle-equal
against the host pyarrow reader; every out-of-envelope shape must
DECLINE (return None), never mis-parse.  Reference: ``GpuJsonScan`` via
``GpuTextBasedPartitionReader.scala``."""

import datetime

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import device_to_arrow
from spark_rapids_tpu.io_.device_json import decode_file


class _F:
    def __init__(self, name, dtype):
        self.name = name
        self.dtype = dtype


def _decode(path, fields, options=None):
    return decode_file(str(path), options or {}, fields)


def test_basic_types(tmp_path):
    p = tmp_path / "t.json"
    p.write_text(
        '{"i": 1, "f": 1.5, "s": "alpha", "b": true, "d": "2020-01-31"}\n'
        '{"i": -42, "f": 2.25e3, "s": "beta", "b": false,'
        ' "d": "1999-12-01"}\n'
        '{"i": null, "f": null, "s": null, "b": null, "d": null}\n'
        '{"i": 7, "f": -0.125, "b": true, "d": "2024-02-29"}\n')
    fields = [_F("i", T.LongType()), _F("f", T.DoubleType()),
              _F("s", T.StringType()), _F("b", T.BooleanType()),
              _F("d", T.DateType())]
    b = _decode(p, fields)
    assert b is not None
    got = device_to_arrow(b)
    assert got.column("i").to_pylist() == [1, -42, None, 7]
    assert got.column("f").to_pylist() == [1.5, 2250.0, None, -0.125]
    assert got.column("s").to_pylist() == ["alpha", "beta", None, None]
    assert got.column("b").to_pylist() == [True, False, None, True]
    assert got.column("d").to_pylist() == [
        datetime.date(2020, 1, 31), datetime.date(1999, 12, 1), None,
        datetime.date(2024, 2, 29)]


def test_key_order_and_extra_keys(tmp_path):
    p = tmp_path / "k.json"
    p.write_text(
        '{"a": 1, "b": 2, "zzz": 9}\n'
        '{"b": 20, "a": 10}\n'
        '{}\n')
    fields = [_F("a", T.LongType()), _F("b", T.LongType())]
    got = device_to_arrow(_decode(p, fields))
    assert got.column("a").to_pylist() == [1, 10, None]
    assert got.column("b").to_pylist() == [2, 20, None]


def test_strings_with_structural_chars(tmp_path):
    p = tmp_path / "s.json"
    p.write_text(
        '{"s": "x:y, {z}", "n": 1}\n'
        '{"s": "", "n": 2}\n'
        '{"s": "it\'s [fine]", "n": 3}\n')
    fields = [_F("s", T.StringType()), _F("n", T.LongType())]
    got = device_to_arrow(_decode(p, fields))
    assert got.column("s").to_pylist() == ["x:y, {z}", "", "it's [fine]"]
    assert got.column("n").to_pylist() == [1, 2, 3]


def test_whitespace_and_empty_objects(tmp_path):
    p = tmp_path / "w.json"
    p.write_text('{"a":1,"b":  2 }\n{  }\n{"b":3}\n')
    fields = [_F("a", T.LongType()), _F("b", T.LongType())]
    got = device_to_arrow(_decode(p, fields))
    assert got.column("a").to_pylist() == [1, None, None]
    assert got.column("b").to_pylist() == [2, None, 3]


def test_int_widths_and_timestamp(tmp_path):
    p = tmp_path / "w.json"
    p.write_text(
        '{"a": 127, "t": "2021-06-01 12:34:56"}\n'
        '{"a": -128, "t": "1970-01-01 00:00:00"}\n')
    fields = [_F("a", T.ByteType()), _F("t", T.TimestampType())]
    got = device_to_arrow(_decode(p, fields))
    assert got.column("a").to_pylist() == [127, -128]
    assert [t.replace(tzinfo=None) for t in got.column("t").to_pylist()] \
        == [datetime.datetime(2021, 6, 1, 12, 34, 56),
            datetime.datetime(1970, 1, 1)]
    # out-of-range for the plan type -> decline (inference drift)
    p2 = tmp_path / "w2.json"
    p2.write_text('{"a": 127}\n{"a": 300}\n')
    assert _decode(p2, [_F("a", T.ByteType())]) is None


def test_decimal(tmp_path):
    p = tmp_path / "d.json"
    p.write_text('{"x": 12.34}\n{"x": -0.05}\n{"x": null}\n')
    dt = T.DecimalType(9, 2)
    got = device_to_arrow(_decode(p, [_F("x", dt)]))
    import decimal
    assert got.column("x").to_pylist() == [
        decimal.Decimal("12.34"), decimal.Decimal("-0.05"), None]


def test_wrong_token_class_declines(tmp_path):
    # quoted number for a long column: Jackson calls it corrupt -> host
    p = tmp_path / "q.json"
    p.write_text('{"a": "1"}\n')
    assert _decode(p, [_F("a", T.LongType())]) is None
    # bare number for a string column -> host
    p2 = tmp_path / "q2.json"
    p2.write_text('{"s": 5}\n')
    assert _decode(p2, [_F("s", T.StringType())]) is None
    # parse failure against plan type -> decline, never null-fill
    p3 = tmp_path / "q3.json"
    p3.write_text('{"a": 1}\n{"a": 1.5}\n')
    assert _decode(p3, [_F("a", T.LongType())]) is None


@pytest.mark.parametrize("content", [
    b'{"a": "x\\ny"}\n',            # escape sequence
    b'{"a": {"b": 1}}\n',           # nested object
    b'{"a": [1, 2]}\n',             # array
    b"{'a': 1}\n",                  # single-quote syntax
    b'{"a": 1}\r\n',                # CRLF
    b'{"a": 1}\n\n{"a": 2}\n',      # blank interior line
    b'\xef\xbb\xbf{"a": 1}\n',      # BOM
    b'{"a": 1,}\n',                 # trailing comma
    b'{"a": 1 "b": 2}\n',           # missing comma
    b'{"a": }\n',                   # empty value
    b'{"a": tru}\n',                # bad literal
    b'{"a": 1} \n',                 # padding outside braces
    b'[{"a": 1}]\n',                # top-level array
    b'{"a": 1, "a": 2}\n',          # duplicate key
    b'{"a": "unterminated}\n',      # unbalanced quote
])
def test_out_of_envelope_declines(tmp_path, content):
    p = tmp_path / "d.json"
    p.write_bytes(content)
    assert _decode(p, [_F("a", T.LongType()),
                       _F("b", T.LongType())]) is None


@pytest.mark.parametrize("tok", ["-inf", "-Infinity", "Infinity", "NaN",
                                 "-INFINITY", "1f", "0x10"])
def test_non_numeric_number_tokens_decline(tmp_path, tok):
    """The cast parsers are deliberately permissive (Spark CAST accepts
    'Infinity'); the JSON number envelope must keep such tokens on the
    host where the oracle errors — never a device mis-parse."""
    p = tmp_path / "n.json"
    p.write_text('{"x": %s}\n' % tok)
    assert _decode(p, [_F("x", T.DoubleType())]) is None


def test_options_decline(tmp_path):
    p = tmp_path / "o.json"
    p.write_text('{"a": 1}\n')
    f = [_F("a", T.LongType())]
    assert _decode(p, f, {"multiLine": "true"}) is None
    assert _decode(p, f, {"primitivesAsString": "true"}) is None


def test_engine_end_to_end_oracle(tmp_path):
    """Through the session read path: device decode must agree with the
    pyarrow oracle and the engagement metric must fire."""
    import pyarrow.json as pjson
    sess = srt.session()
    rng = np.random.default_rng(11)
    n = 500
    path = tmp_path / "e.json"
    with open(path, "w") as f:
        for k in range(n):
            parts = []
            if k % 7:
                parts.append(f'"i": {int(rng.integers(-10**9, 10**9))}')
            parts.append(f'"f": {float(rng.random()):.6f}')
            parts.append(f'"s": "v-{k}"' if k % 3 else '"s": null')
            parts.append(f'"b": {"true" if k % 2 else "false"}')
            f.write("{" + ", ".join(parts) + "}\n")
    exp = pjson.read_json(str(path))
    got = sess.read.json(str(path)).collect()
    assert got.num_rows == n
    for col in ("i", "s", "b"):
        assert got.column(col).to_pylist() == \
            exp.column(col).to_pylist(), col
    # string->double conversion may differ from pyarrow's by 1 ulp
    assert np.allclose(got.column("f").to_pylist(),
                       exp.column("f").to_pylist(), rtol=1e-12)
    m = sess.last_query_metrics
    assert m.get("jsonDeviceDecodedFiles", 0) >= 1, m


@pytest.mark.parametrize("tok", ["12.", "-.5", "1.e3", ".5", "1e", "1e+",
                                 "5.e-2", "--1", "1.2.3", "1e2e3", "-"])
def test_malformed_number_grammar_declines(tmp_path, tok):
    """Number tokens must match -?\\d+(\\.\\d+)?([eE][+-]?\\d+)? (leading
    zeros allowed — the documented permissive edge); anything else parses
    permissively on device but errors in the host oracle, so decline."""
    p = tmp_path / "g.json"
    p.write_text('{"x": %s}\n' % tok)
    assert _decode(p, [_F("x", T.DoubleType())]) is None


@pytest.mark.parametrize("tok,val", [
    ("12.5", 12.5), ("-0.5e3", -500.0), ("007", 7.0), ("-00.25", -0.25),
    ("1E+2", 100.0), ("0.5e-1", 0.05), ("1e2", 100.0)])
def test_valid_number_grammar_parses(tmp_path, tok, val):
    p = tmp_path / "gv.json"
    p.write_text('{"x": %s}\n' % tok)
    b = _decode(p, [_F("x", T.DoubleType())])
    assert b is not None
    assert device_to_arrow(b).column("x").to_pylist() == [val]


def test_duplicate_key_outside_schema_declines(tmp_path):
    """Duplicate keys make the row host-semantics-dependent even when the
    duplicated key is pruned from the plan schema."""
    p = tmp_path / "dup.json"
    p.write_text('{"a": 1, "b": 2, "b": 3}\n')
    assert _decode(p, [_F("a", T.LongType())]) is None
    # same key on different rows is fine
    p2 = tmp_path / "dup2.json"
    p2.write_text('{"a": 1, "b": 2}\n{"a": 2, "b": 3}\n')
    b = _decode(p2, [_F("a", T.LongType())])
    assert b is not None
    assert device_to_arrow(b).column("a").to_pylist() == [1, 2]


def test_same_prefix_keys_not_confused_as_duplicates(tmp_path):
    p = tmp_path / "pref.json"
    p.write_text('{"ab": 1, "a": 2, "abc": 3}\n')
    b = _decode(p, [_F("a", T.LongType()), _F("ab", T.LongType())])
    assert b is not None
    got = device_to_arrow(b)
    assert got.column("a").to_pylist() == [2]
    assert got.column("ab").to_pylist() == [1]
