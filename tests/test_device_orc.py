"""Device-side ORC decode (io_/device_orc.py) — oracle-equal against
pyarrow across types, encodings, null patterns, compressions and stripe
layouts; per-column decline-to-host for out-of-envelope shapes.
Reference: ``GpuOrcScan.scala:893`` (``Table.readORC`` device decode)."""

import numpy as np
import pyarrow as pa
import pyarrow.orc as orc
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.columnar import device_to_arrow
from spark_rapids_tpu.io_.device_orc import decode_file


def _assert_table_equal(t: pa.Table, back: pa.Table):
    assert back.num_rows == t.num_rows
    for c in t.column_names:
        a = t.column(c).combine_chunks()
        b = back.column(c).combine_chunks()
        if pa.types.is_timestamp(a.type):
            # engine normalizes timestamps to us/UTC (Spark semantics)
            a = a.cast(pa.timestamp("us", tz="UTC"))
            b = b.cast(pa.timestamp("us", tz="UTC"))
        assert a.equals(b), (c, a.to_pylist()[:5], b.to_pylist()[:5])


def _roundtrip(t: pa.Table, tmp_path, expect_device=True, **writer_kwargs):
    path = str(tmp_path / "t.orc")
    orc.write_table(t, path, **writer_kwargs)
    batch = decode_file(path)
    if not expect_device:
        assert batch is None
        return None
    assert batch is not None, "decode declined the whole file"
    _assert_table_equal(t, device_to_arrow(batch))
    return batch


def _rich_table(n: int, seed: int = 0, null_p: float = 0.0) -> pa.Table:
    rng = np.random.default_rng(seed)
    mask = (rng.random(n) < null_p) if null_p else None
    def arr(v, **kw):
        return pa.array(v, mask=mask, **kw)
    return pa.table({
        "i64": arr(rng.integers(-10**15, 10**15, n)),
        "i32": arr(rng.integers(-2**31, 2**31 - 1, n).astype(np.int32)),
        "i16": arr(rng.integers(-30000, 30000, n).astype(np.int16)),
        "i8": arr(rng.integers(-128, 128, n).astype(np.int8)),
        "seq": pa.array(np.arange(n, dtype=np.int64)),       # DELTA
        "desc": pa.array(np.arange(n, 0, -1).astype(np.int64)),
        "const": arr(np.full(n, 7, dtype=np.int64)),         # SHORT_REPEAT
        "f64": arr(rng.random(n)),
        "f32": arr(rng.random(n).astype(np.float32)),
        "b": arr(rng.random(n) < 0.5),
        "d": pa.array(rng.integers(-10000, 20000, n).astype("int32"),
                      type=pa.date32()),
        "s": pa.array([None if (mask is not None and mask[i])
                       else f"row-{i % 53}-{'x' * (i % 17)}"
                       for i in range(n)]),
        "bin": pa.array([None if (mask is not None and mask[i])
                         else bytes([i % 256, (i * 7) % 256])
                         for i in range(n)], type=pa.binary()),
    })


def test_basic_all_types(tmp_path):
    _roundtrip(_rich_table(5000), tmp_path)


def test_nulls_everywhere(tmp_path):
    _roundtrip(_rich_table(8000, seed=1, null_p=0.2), tmp_path)


@pytest.mark.parametrize("comp", ["uncompressed", "zlib", "zstd", "snappy"])
def test_compressions(tmp_path, comp):
    _roundtrip(_rich_table(4000, seed=2, null_p=0.1), tmp_path,
               compression=comp)


def test_multi_stripe_unaligned(tmp_path):
    """Stripe row counts not multiples of 8 exercise the per-stripe
    PRESENT/boolean bit-stream restart mapping."""
    t = _rich_table(30011, seed=3, null_p=0.15)
    batch = _roundtrip(t, tmp_path, stripe_size=65536, batch_size=997,
                       compression="zlib")
    assert batch.num_rows_int == 30011


def test_dictionary_strings(tmp_path):
    rng = np.random.default_rng(4)
    n = 20000
    t = pa.table({
        "cat": pa.array([f"cat-{i}" for i in rng.integers(0, 40, n)]),
        "v": pa.array(rng.integers(0, 1000, n)),
    })
    path = str(tmp_path / "d.orc")
    orc.write_table(t, path, dictionary_key_size_threshold=0.9,
                    stripe_size=65536)
    batch = decode_file(path)
    assert batch is not None
    _assert_table_equal(t, device_to_arrow(batch))


def test_out_of_envelope_columns_decline_per_column(tmp_path):
    rng = np.random.default_rng(5)
    n = 2000
    t = pa.table({
        "ts": pa.array(rng.integers(0, 10**15, n), type=pa.timestamp("us")),
        "dec": pa.array([None] * n, type=pa.decimal128(10, 2)),
        "lst": pa.array([[1, 2]] * n, type=pa.list_(pa.int64())),
        "i": pa.array(rng.integers(0, 100, n)),
        "s": pa.array([f"k{i % 9}" for i in range(n)]),
    })
    path = str(tmp_path / "m.orc")
    orc.write_table(t, path)

    class _Ctx:
        metrics: dict = {}
        def inc_metric(self, k, v=1):
            self.metrics[k] = self.metrics.get(k, 0) + v

    ctx = _Ctx()
    batch = decode_file(path, tctx=ctx)
    assert batch is not None
    _assert_table_equal(t, device_to_arrow(batch))
    assert ctx.metrics.get("orcDeviceDecodedColumns", 0) >= 2
    assert ctx.metrics.get("orcHostDecodedColumns", 0) >= 3


def test_empty_and_single_row(tmp_path):
    p1 = str(tmp_path / "e.orc")
    orc.write_table(pa.table({"i": pa.array([], type=pa.int64())}), p1)
    assert decode_file(p1) is None  # no rows -> host path trivially
    p2 = str(tmp_path / "one.orc")
    orc.write_table(pa.table({"i": pa.array([42]), "s": pa.array(["x"])}),
                    p2)
    b = decode_file(p2)
    assert b is not None
    got = device_to_arrow(b)
    assert got.column("i").to_pylist() == [42]
    assert got.column("s").to_pylist() == ["x"]


def test_stripe_subset(tmp_path):
    t = _rich_table(20000, seed=6, null_p=0.1)
    path = str(tmp_path / "s.orc")
    orc.write_table(t, path, stripe_size=65536, compression="zlib")
    f = orc.ORCFile(path)
    assert f.nstripes > 1
    b = decode_file(path, stripes=[0])
    assert b is not None
    first = pa.Table.from_batches([f.read_stripe(0)])
    _assert_table_equal(first, device_to_arrow(b))


def test_scan_exec_end_to_end(tmp_path):
    """Full engine path: session reads ORC, device decode on by default,
    results equal the host pipeline's."""
    t = _rich_table(12000, seed=7, null_p=0.1)
    path = str(tmp_path / "scan.orc")
    orc.write_table(t, path, compression="zlib", stripe_size=131072)
    sess = srt.session()
    df = sess.read.orc(path)
    got = df.collect()
    _assert_table_equal(t, got)
    # explicit off-switch exercises the host pipeline for comparison
    from spark_rapids_tpu.config import RapidsConf
    conf = RapidsConf.get_global().copy(
        {"spark.rapids.sql.format.orc.deviceDecode.enabled": "false"})
    sess2 = srt.session(conf=conf)
    got2 = sess2.read.orc(path).collect()
    _assert_table_equal(t, got2)


def test_extreme_int_widths(tmp_path):
    """Values spanning the full int64 range force 64-bit DIRECT packing."""
    rng = np.random.default_rng(8)
    vals = np.concatenate([
        rng.integers(-2**62, 2**62, 503),
        np.array([np.iinfo(np.int64).min + 1, np.iinfo(np.int64).max]),
    ])
    t = pa.table({"i": pa.array(vals)})
    _roundtrip(t, tmp_path)


def test_empty_strings_and_wide(tmp_path):
    n = 3000
    t = pa.table({
        "s": pa.array(["" if i % 3 == 0 else "y" * (i % 120)
                       for i in range(n)]),
        "i": pa.array(np.arange(n, dtype=np.int64)),
    })
    _roundtrip(t, tmp_path, compression="zstd")
