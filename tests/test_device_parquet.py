"""Device-side parquet decode (io_/device_parquet.py) vs the pyarrow
oracle: every supported (dtype x encoding x codec x page-version x nulls)
combination must produce a batch identical to uploading pyarrow's own
decode, and unsupported shapes must fall back per column, not per file."""

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq
import pytest

from spark_rapids_tpu.columnar.convert import arrow_to_device, device_to_arrow
from spark_rapids_tpu.io_.device_parquet import decode_file


def _rng(seed=0):
    return np.random.default_rng(seed)


def _with_nulls(arr, frac, rng):
    if frac <= 0:
        return arr
    mask = rng.random(len(arr)) < frac
    return pa.array([None if m else v for m, v in
                     zip(mask, arr.to_pylist())], type=arr.type)


def _mixed_table(n=5000, null_frac=0.15, seed=7):
    rng = _rng(seed)
    cols = {
        "i32": pa.array(rng.integers(-2**31, 2**31 - 1, n), pa.int32()),
        "i64": pa.array(rng.integers(-2**62, 2**62, n), pa.int64()),
        "i8": pa.array(rng.integers(-128, 127, n).astype(np.int8)),
        "i16": pa.array(rng.integers(-2**15, 2**15 - 1, n).astype(np.int16)),
        "f32": pa.array(rng.standard_normal(n).astype(np.float32)),
        "f64": pa.array(rng.standard_normal(n) * 1e12),
        "b": pa.array(rng.random(n) < 0.5),
        "s": pa.array([f"row-{i % 97}" for i in range(n)]),
        "d": pa.array(rng.integers(0, 20000, n).astype(np.int32),
                      pa.date32()),
        "ts": pa.array(rng.integers(0, 2**45, n), pa.timestamp("us")),
    }
    return pa.table({k: _with_nulls(v, null_frac, rng)
                     for k, v in cols.items()})


def _check_file(tmp_path, table, name="t.parquet", **write_kwargs):
    path = str(tmp_path / name)
    pq.write_table(table, path, **write_kwargs)
    batch = decode_file(path)
    assert batch is not None, "no column took the device path"
    got = device_to_arrow(batch)
    want = device_to_arrow(arrow_to_device(pq.read_table(path)))
    assert got.schema.names == want.schema.names
    for c in want.schema.names:
        assert got.column(c).to_pylist() == want.column(c).to_pylist(), c
    return batch


@pytest.mark.quick
def test_plain_roundtrip(tmp_path):
    _check_file(tmp_path, _mixed_table(), use_dictionary=False)


@pytest.mark.quick
def test_dictionary_roundtrip(tmp_path):
    _check_file(tmp_path, _mixed_table(), use_dictionary=True)


@pytest.mark.parametrize("codec", ["snappy", "zstd", "gzip", "none"])
def test_codecs(tmp_path, codec):
    _check_file(tmp_path, _mixed_table(n=2000), compression=codec)


@pytest.mark.parametrize("version", ["1.0", "2.4", "2.6"])
def test_format_versions(tmp_path, version):
    _check_file(tmp_path, _mixed_table(n=2000), version=version)


def test_data_page_v2(tmp_path):
    _check_file(tmp_path, _mixed_table(n=3000),
                data_page_version="2.0")


def test_data_page_v2_uncompressed(tmp_path):
    _check_file(tmp_path, _mixed_table(n=1000),
                data_page_version="2.0", compression="none")


def test_multiple_row_groups(tmp_path):
    _check_file(tmp_path, _mixed_table(n=10_000), row_group_size=1024)


def test_multiple_pages_per_chunk(tmp_path):
    # tiny data pages force many pages (and hybrid runs) per column chunk
    _check_file(tmp_path, _mixed_table(n=20_000),
                data_page_size=1024, use_dictionary=False)


def test_dictionary_many_row_groups(tmp_path):
    # one writer => per-group dictionaries are prefixes of the same stream
    _check_file(tmp_path, _mixed_table(n=8000), row_group_size=1000,
                use_dictionary=True)


def test_divergent_dictionaries_remap_on_device(tmp_path):
    """Per-row-group dictionaries in first-occurrence order diverge for
    random data; the union+remap path must keep every column on device."""
    rng = _rng(23)
    n = 12_000
    t = pa.table({
        "i": pa.array(rng.integers(0, 500, n), pa.int32()),
        "s": pa.array([f"val-{v}" for v in
                       rng.integers(0, 300, n)]),
        "f": pa.array(rng.integers(0, 200, n).astype(np.float64)),
    })
    path = str(tmp_path / "dd.parquet")
    pq.write_table(t, path, row_group_size=997, use_dictionary=True)

    class Ctx:
        metrics = {}

        def inc_metric(self, k, v=1):
            self.metrics[k] = self.metrics.get(k, 0) + v

    ctx = Ctx()
    batch = decode_file(path, tctx=ctx)
    assert ctx.metrics.get("parquetDeviceDecodedColumns", 0) == 3
    assert not ctx.metrics.get("parquetHostDecodedColumns", 0)
    got = device_to_arrow(batch)
    want = device_to_arrow(arrow_to_device(pq.read_table(path)))
    for c in want.schema.names:
        assert got.column(c).to_pylist() == want.column(c).to_pylist(), c


def test_ragged_string_dictionary_declines_whole_file(tmp_path):
    """One huge dictionary entry would blow the dense string matrix; the
    file must decline the DEVICE path entirely (host split_for_upload is
    table-level, so per-column fallback would rebuild the same matrix)."""
    t = pa.table({
        "i": pa.array(list(range(4000)), pa.int64()),
        "s": pa.array((["x" * 9000] + ["short"] * 999) * 4),
    })
    path = str(tmp_path / "rag.parquet")
    pq.write_table(t, path)

    class Conf:
        def get(self, key):
            return 1 << 20          # 1MB ragged threshold

    assert decode_file(path, conf=Conf()) is None


def test_no_nulls_required_columns(tmp_path):
    t = _mixed_table(n=1500, null_frac=0.0)
    # declare non-nullable so max_def == 0 (no def levels at all)
    fields = [pa.field(f.name, f.type, nullable=False) for f in t.schema]
    t = t.cast(pa.schema(fields))
    _check_file(tmp_path, t)


def test_all_null_column(tmp_path):
    t = pa.table({
        "x": pa.array([None] * 500, pa.int64()),
        "y": pa.array(list(range(500)), pa.int32()),
    })
    _check_file(tmp_path, t)


def test_empty_file(tmp_path):
    t = pa.table({"x": pa.array([], pa.int64())})
    path = str(tmp_path / "e.parquet")
    pq.write_table(t, path)
    # zero row groups -> engine host path; decode_file declines cleanly
    assert decode_file(path) is None or \
        device_to_arrow(decode_file(path)).num_rows == 0


def test_row_group_subset(tmp_path):
    t = _mixed_table(n=6000)
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path, row_group_size=1000)
    batch = decode_file(path, row_groups=[1, 3, 5])
    got = device_to_arrow(batch)
    want = device_to_arrow(arrow_to_device(
        pq.ParquetFile(path).read_row_groups([1, 3, 5])))
    for c in want.schema.names:
        assert got.column(c).to_pylist() == want.column(c).to_pylist(), c


@pytest.mark.parametrize("storage", ["integer", "flba"])
def test_decimal_columns(tmp_path, storage):
    """Decimals decode on device in BOTH parquet storages: INT32/INT64
    (store_decimal_as_integer) and the default FIXED_LEN_BYTE_ARRAY
    big-endian two's complement, incl. precision > 18 into the engine's
    (lo=data, hi=aux) 128-bit layout."""
    import decimal
    rng = _rng(3)
    vals = [decimal.Decimal(int(v)).scaleb(-2)
            for v in rng.integers(-10**9, 10**9, 800)]
    vals = [None if i % 13 == 0 else v for i, v in enumerate(vals)]
    big = [None if v is None else v * (10 ** 12) for v in vals]
    cols = {
        "d9": pa.array(vals, pa.decimal128(9, 2)),
        "d18": pa.array(vals, pa.decimal128(18, 2)),
    }
    if storage == "flba":
        cols["d30"] = pa.array(big, pa.decimal128(30, 2))
        cols["dneg"] = pa.array(
            [None if v is None else -v for v in big],
            pa.decimal128(30, 2))
    t = pa.table(cols)
    path = str(tmp_path / f"d_{storage}.parquet")
    pq.write_table(t, path,
                   store_decimal_as_integer=(storage == "integer"))

    class Ctx:
        metrics = {}

        def inc_metric(self, k, v=1):
            self.metrics[k] = self.metrics.get(k, 0) + v

    ctx = Ctx()
    batch = decode_file(path, tctx=ctx)
    assert batch is not None
    assert ctx.metrics.get("parquetDeviceDecodedColumns", 0) == len(cols)
    got = device_to_arrow(batch)
    want = device_to_arrow(arrow_to_device(pq.read_table(path)))
    for c in want.schema.names:
        assert got.column(c).to_pylist() == want.column(c).to_pylist(), c


def test_decimal_flba_plain_pages(tmp_path):
    """PLAIN (non-dictionary) FLBA decimals exercise the byte-expansion
    kernel rather than the dictionary gather."""
    import decimal
    rng = _rng(9)
    vals = [decimal.Decimal(int(v)) * decimal.Decimal("0.001")
            for v in rng.integers(-10**15, 10**15, 600)]
    t = pa.table({"x": pa.array(vals, pa.decimal128(25, 3))})
    path = str(tmp_path / "dp.parquet")
    pq.write_table(t, path, use_dictionary=False)
    batch = decode_file(path)
    assert batch is not None
    got = device_to_arrow(batch)
    want = device_to_arrow(arrow_to_device(pq.read_table(path)))
    assert got.column("x").to_pylist() == want.column("x").to_pylist()


def test_nested_column_falls_back_per_column(tmp_path):
    t = pa.table({
        "flat": pa.array(list(range(400)), pa.int64()),
        "lst": pa.array([[i, i + 1] for i in range(400)],
                        pa.list_(pa.int32())),
    })
    path = str(tmp_path / "n.parquet")
    pq.write_table(t, path)

    class Ctx:
        metrics = {}

        def inc_metric(self, k, v=1):
            self.metrics[k] = self.metrics.get(k, 0) + v

    ctx = Ctx()
    batch = decode_file(path, tctx=ctx)
    assert batch is not None
    assert ctx.metrics.get("parquetDeviceDecodedColumns", 0) >= 1
    assert ctx.metrics.get("parquetHostDecodedColumns", 0) >= 1
    got = device_to_arrow(batch)
    want = device_to_arrow(arrow_to_device(pq.read_table(path)))
    for c in want.schema.names:
        assert got.column(c).to_pylist() == want.column(c).to_pylist(), c


def test_timestamp_millis(tmp_path):
    rng = _rng(11)
    t = pa.table({"ts": pa.array(rng.integers(0, 2**40, 700),
                                 pa.timestamp("ms"))})
    _check_file(tmp_path, t)


def test_float_specials(tmp_path):
    vals = [0.0, -0.0, np.inf, -np.inf, np.nan, 1e-300, -1e300,
            np.finfo(np.float64).max, np.finfo(np.float64).min] * 50
    t = pa.table({"f": pa.array(vals, pa.float64()),
                  "g": pa.array([np.float32(v) for v in vals],
                                pa.float32())})
    path = str(tmp_path / "f.parquet")
    pq.write_table(t, path, use_dictionary=False)
    batch = decode_file(path)
    got = device_to_arrow(batch)
    want = device_to_arrow(arrow_to_device(pq.read_table(path)))
    for c in ("f", "g"):
        g = got.column(c).to_pylist()
        w = want.column(c).to_pylist()
        for a, b in zip(g, w):
            if b is None or (b != b):          # null or NaN
                assert a is None or a != a
            else:
                assert a == b, (c, a, b)


@pytest.mark.quick
def test_scan_exec_uses_device_decode(tmp_path):
    """End-to-end: session.read.parquet equality with the flag on vs off,
    and the device-decode metric fires."""
    import spark_rapids_tpu as srt

    t = _mixed_table(n=3000)
    path = str(tmp_path / "scan.parquet")
    pq.write_table(t, path, row_group_size=512)
    sess = srt.session()
    on = sess.read.parquet(path).orderBy("i32").collect().to_pandas()
    m = sess.last_query_metrics
    assert m.get("parquetDeviceDecodedColumns", 0) > 0, m
    sess.conf.set(
        "spark.rapids.sql.format.parquet.deviceDecode.enabled", "false")
    try:
        off = sess.read.parquet(path).orderBy("i32").collect().to_pandas()
    finally:
        sess.conf.set(
            "spark.rapids.sql.format.parquet.deviceDecode.enabled", "true")
    import pandas as pd
    pd.testing.assert_frame_equal(on, off)


# --------------------------------------------------------------------------
# round 5: PLAIN (non-dictionary) BYTE_ARRAY strings on device
# --------------------------------------------------------------------------

def _plain_string_metric(tmp_path, table, **kw):
    path = str(tmp_path / "ps.parquet")
    pq.write_table(table, path, use_dictionary=False, **kw)

    class _Ctx:
        metrics: dict = {}

        def inc_metric(self, k, v=1):
            self.metrics[k] = self.metrics.get(k, 0) + v

    ctx = _Ctx()
    batch = decode_file(path, tctx=ctx)
    assert batch is not None
    got = device_to_arrow(batch)
    want = pq.read_table(path)
    for c in want.schema.names:
        assert got.column(c).to_pylist() == want.column(c).to_pylist(), c
    return ctx.metrics


def test_plain_strings_device(tmp_path):
    rng = _rng(11)
    n = 8000
    t = pa.table({
        "s": pa.array([f"plain-{i % 211}-{'x' * (i % 13)}"
                       for i in range(n)]),
        "v": pa.array(rng.random(n)),
    })
    m = _plain_string_metric(tmp_path, t)
    assert m.get("parquetDeviceDecodedColumns", 0) == 2, m


def test_plain_strings_with_nulls_and_empties(tmp_path):
    rng = _rng(12)
    n = 6000
    vals = [None if rng.random() < 0.2
            else ("" if rng.random() < 0.2 else f"v{i}")
            for i in range(n)]
    t = pa.table({"s": pa.array(vals, pa.string())})
    m = _plain_string_metric(tmp_path, t)
    assert m.get("parquetDeviceDecodedColumns", 0) == 1, m


def test_plain_strings_multi_row_group_compressed(tmp_path):
    n = 20000
    t = pa.table({
        "s": pa.array([f"key-{i % 37:04d}" for i in range(n)]),
        "k": pa.array(np.arange(n, dtype=np.int64)),
    })
    m = _plain_string_metric(tmp_path, t, row_group_size=3000,
                             compression="zstd")
    assert m.get("parquetDeviceDecodedColumns", 0) == 2, m


def test_byte_array_walk_native_matches_python():
    from spark_rapids_tpu import native
    import struct as _s
    rng = _rng(13)
    vals = [bytes(rng.integers(0, 256, rng.integers(0, 20)).astype(
        np.uint8)) for _ in range(500)]
    raw = b"".join(_s.pack("<I", len(v)) + v for v in vals)
    data = np.frombuffer(raw, np.uint8)
    out = native.byte_array_walk(data, len(vals))
    if out is None:
        pytest.skip("native lib unavailable")
    starts, lens = out
    pos = 0
    for i, v in enumerate(vals):
        pos += 4
        assert starts[i] == pos and lens[i] == len(v), i
        pos += len(v)
    # truncation must raise, not overrun
    with pytest.raises(ValueError):
        native.byte_array_walk(data[:-1], len(vals))
