"""Per-shape DISPATCH-BUDGET regression suite (ISSUE 14): the perf model
is launches-per-batch, and a silent regression there never fails a
correctness test — so each canonical shape pins its compiled-program
launch budget, fused vs kill-switched, and asserts bit parity between
the two.  Also covers the fused join probe's readback budget (<= 1
blocking host fetch per probe batch, hit AND overflow paths) and the
dispatch coalescer (N same-signature small batches -> ONE launch).
"""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.sql import functions as F
from spark_rapids_tpu.sql.physical import join as J
from spark_rapids_tpu.sql.physical import kernel_cache as kc
from spark_rapids_tpu.sql.window_api import Window as W

ROWS = 3000

#: ISSUE 14 acceptance: a probe batch costs at most this many launches
#: end to end on the fused path (the pre-fusion baseline was ~107)
JOIN_LAUNCH_BUDGET = 12


def _tables():
    rng = np.random.default_rng(29)
    fact = pa.table({
        "k": rng.integers(0, 9, ROWS).astype(np.int64),
        "q": rng.integers(0, 100, ROWS).astype(np.int64),
        "v": rng.random(ROWS),
        "fk": rng.integers(0, 160, ROWS).astype(np.int64),
    })
    dim = pa.table({"pk": np.arange(0, 160, 2, dtype=np.int64),
                    "w": rng.random(80)})
    return fact, dim


FACT, DIM = _tables()


def _session(fused=True, encoded=False, coalesce=True, **extra):
    over = {
        "spark.rapids.tpu.sql.fusion.enabled": fused,
        "spark.rapids.tpu.sql.wholeStage.enabled": fused,
        "spark.rapids.tpu.sql.wholeStage.sortWindowTerminal.enabled":
            fused,
        "spark.rapids.tpu.sql.join.fusedProbe.enabled": fused,
        "spark.rapids.tpu.sql.encoded.enabled": encoded,
        "spark.rapids.tpu.sql.dispatch.coalesce.enabled": coalesce,
    }
    over.update(extra)
    return srt.session(conf=RapidsConf.get_global().copy(over))


def _canon(table: pa.Table) -> pd.DataFrame:
    df = table.to_pandas()
    return df.sort_values(list(df.columns), kind="mergesort") \
        .reset_index(drop=True)


def _q_join(sess):
    f = sess.create_dataframe(FACT, num_partitions=2)
    d = sess.create_dataframe(DIM)
    return (f.filter(F.col("q") < 70)
            .withColumn("y", F.col("v") * 3.0)
            .join(d, f.fk == d.pk, "inner"))


def _q_sort(sess):
    f = sess.create_dataframe(FACT)
    return (f.filter(F.col("q") < 70)
            .withColumn("y", F.col("v") * 2.0)
            .orderBy("k", "y"))


def _q_window(sess):
    f = sess.create_dataframe(FACT)
    w = W.partitionBy("k").orderBy("q")
    return (f.filter(F.col("q") < 70)
            .withColumn("y", F.col("v") * 2.0)
            .withColumn("rn", F.row_number().over(w)))


SHAPES = {"join": _q_join, "sort": _q_sort, "window": _q_window}


def _run(shape, fused, encoded, coalesce):
    sess = _session(fused=fused, encoded=encoded, coalesce=coalesce)
    q = SHAPES[shape](sess)
    q.collect()  # warm: compiles + speculation learning
    kc.clear_cache()
    out = _canon(q.collect())
    stats = kc.cache_stats()
    return out, stats, dict(sess.last_query_metrics)


# --------------------------------------------------------------------------
# fused vs kill-switched parity x encoded x coalescer
# --------------------------------------------------------------------------

@pytest.mark.parametrize("shape", sorted(SHAPES))
@pytest.mark.parametrize("encoded", [False, True])
@pytest.mark.parametrize("coalesce", [False, True])
def test_fused_parity_and_budget(shape, encoded, coalesce):
    """Fused output is bit-identical to the kill-switched per-op
    baseline under every encoded/coalescer combination, and never costs
    MORE launches than the baseline."""
    out_f, st_f, _ = _run(shape, True, encoded, coalesce)
    out_u, st_u, _ = _run(shape, False, encoded, coalesce)
    pd.testing.assert_frame_equal(out_f, out_u)
    assert st_f["dispatches"] <= st_u["dispatches"], (
        f"{shape}: fused path launched MORE programs "
        f"({st_f['dispatches']} > {st_u['dispatches']})")


@pytest.mark.parametrize("shape", ["sort", "window"])
def test_stage_terminal_dispatch_reduction(shape):
    """Sort/window stage terminals: >= 2x fewer stage-scope launches
    than the kill-switched per-op chain (ISSUE 14 acceptance)."""
    _, st_f, m_f = _run(shape, True, False, False)
    _, st_u, m_u = _run(shape, False, False, False)
    fused = int(m_f.get("stageOpDispatches", 0)) or st_f["dispatches"]
    unfused = int(m_u.get("stageOpDispatches", 0)) or st_u["dispatches"]
    assert fused * 2 <= unfused, (
        f"{shape}: stage dispatches fused={fused} unfused={unfused}")


def test_join_launches_per_probe_batch_budget():
    """The fused probe pipeline keeps the whole join under the
    per-probe-batch launch budget (search + expansion + pairs + gather
    in ONE program; the pre-fusion baseline was ~107 launches)."""
    _, stats, m = _run("join", True, False, False)
    probes = int(m.get("joinFastpathProbes", 0)
                 + m.get("joinFallbackProbes", 0))
    assert probes > 0, m
    assert int(m.get("joinFusedProbes", 0)) > 0, m
    per_probe = stats["dispatches"] / probes
    assert per_probe <= JOIN_LAUNCH_BUDGET, (
        f"{per_probe:.1f} launches/probe batch > {JOIN_LAUNCH_BUDGET} "
        f"(dispatches={stats['dispatches']} probes={probes})")


# --------------------------------------------------------------------------
# readback budget: <= 1 blocking host fetch per probe batch, both paths
# --------------------------------------------------------------------------

def _readbacks_for(chunk_rows=None):
    over = {}
    if chunk_rows is not None:
        over["spark.rapids.sql.join.outputChunkRows"] = chunk_rows
    sess = _session(**over)
    q = _q_join(sess)
    q.collect()  # warm + selectivity learning
    before = dict(J.STATS)
    out = _canon(q.collect())
    m = dict(sess.last_query_metrics)
    probes = (J.STATS["fastpath_probes"] - before["fastpath_probes"]) + \
        (J.STATS["fallback_probes"] - before["fallback_probes"])
    reads = J.STATS["host_readbacks"] - before["host_readbacks"]
    return out, probes, reads, m


def test_join_hit_path_single_readback():
    out, probes, reads, _ = _readbacks_for()
    assert probes > 0
    assert reads <= probes, (
        f"{reads} blocking readbacks for {probes} probe batches")
    assert len(out) > 0


def test_join_overflow_and_chunked_paths_single_readback():
    """Forcing tiny output chunks drives every probe batch down the
    overflow/chunked path; the re-gather and per-chunk row counts are
    host arithmetic over the ONE sizing fetch — a second blocking
    readback per probe batch is the regression this test pins."""
    base, _, _, _ = _readbacks_for()
    out, probes, reads, m = _readbacks_for(chunk_rows=256)
    assert probes > 0
    assert reads <= probes, (
        f"{reads} blocking readbacks for {probes} probe batches on the "
        f"chunked path")
    pd.testing.assert_frame_equal(out, base)  # chunking is invisible


# --------------------------------------------------------------------------
# dispatch coalescer
# --------------------------------------------------------------------------

def _stage_with_stub_child(sess, k):
    """A real planned FusedStageExec whose child is replaced by a stub
    yielding the scan's batch K times — partition streams are naturally
    single-batch in this engine, so coalescer engagement is pinned at
    the exec level."""
    from spark_rapids_tpu.sql.physical.fusion import FusedStageExec
    df = (sess.create_dataframe(FACT)
          .filter(F.col("q") < 80)
          .withColumn("y", F.col("v") * 2.0)
          .select("k", "y"))
    plan = sess.physical_plan(df)
    stack = [plan]
    stage = None
    while stack:
        n = stack.pop()
        if isinstance(n, FusedStageExec):
            stage = n
            break
        stack.extend(n.children)
    assert stage is not None, plan.tree_string()
    inner = stage.children[0]

    class Stub:
        output = inner.output
        children = ()

        def execute(self, pid, tctx):
            for _ in range(k):
                yield from inner.execute(pid, tctx)

        def num_partitions(self):
            return 1

    stage.children = (Stub(),)
    stage._fns = {}
    return stage


def _drive(stage, coalesce, max_batches=8):
    from spark_rapids_tpu.sql.physical.base import TaskContext
    conf = RapidsConf.get_global().copy({
        "spark.rapids.tpu.sql.dispatch.coalesce.enabled": coalesce,
        "spark.rapids.tpu.sql.dispatch.coalesce.maxBatches": max_batches,
    })
    stage._fns = {}
    kc.clear_cache()
    tctx = TaskContext(0, conf)
    with tctx.as_current():
        outs = list(stage.execute(0, tctx))
    return outs, kc.cache_stats()["dispatches"], dict(tctx.metrics)


def test_coalescer_one_launch_and_parity():
    sess = _session()
    stage = _stage_with_stub_child(sess, k=5)
    outs_on, d_on, m_on = _drive(stage, True)
    outs_off, d_off, m_off = _drive(stage, False)
    assert d_on == 1 and d_off == 5, (d_on, d_off)
    assert m_on["dispatchCoalescedBatches"] == 5
    assert m_on["dispatchCoalescedLaunches"] == 1
    assert "dispatchCoalescedLaunches" not in m_off
    assert len(outs_on) == len(outs_off) == 5
    for a, b in zip(outs_on, outs_off):
        assert a.num_rows_int == b.num_rows_int
        for i in range(len(a.names)):
            np.testing.assert_array_equal(
                np.asarray(a.column(i).data)[:a.num_rows_int],
                np.asarray(b.column(i).data)[:b.num_rows_int])


def test_coalescer_respects_max_batches():
    sess = _session()
    stage = _stage_with_stub_child(sess, k=5)
    outs, dispatches, m = _drive(stage, True, max_batches=2)
    # 5 batches at maxBatches=2 -> groups of 2+2+1: two coalesced
    # launches + one singleton
    assert len(outs) == 5
    assert m["dispatchCoalescedLaunches"] == 2
    assert m["dispatchCoalescedBatches"] == 4
    assert dispatches == 3, dispatches


def test_coalescer_declines_encoded_batches():
    """Encoded columns carry per-instance aux data (dictionary identity)
    — stacking them would collide treedefs, so their signature is None
    and each batch runs the per-batch program."""
    from spark_rapids_tpu.columnar.encoded import DictEncodedColumn
    from spark_rapids_tpu.sql.physical.fusion import coalesce_signature
    sess = _session(encoded=True)
    df = (sess.create_dataframe(pa.table(
        {"s": pa.array(["a", "b", "a", "c"] * 8)}))
        .filter(F.col("s") <= "b"))
    plan = sess.physical_plan(df)
    # upload through the planned scan and check the signature contract
    from spark_rapids_tpu.sql.physical.base import TaskContext
    tctx = TaskContext(0, sess._conf)
    with tctx.as_current():
        stack = [plan]
        scan = None
        while stack:
            n = stack.pop()
            if not n.children:
                scan = n
            stack.extend(n.children)
        batches = list(scan.execute(0, tctx))
    assert batches
    b = batches[0]
    if any(isinstance(c, DictEncodedColumn) for c in b.columns):
        assert coalesce_signature(b) is None
    else:  # encoded session may keep plain columns for tiny tables
        assert coalesce_signature(b) is not None


def test_coalesced_span_carries_n():
    from spark_rapids_tpu.observability import tracer as OT
    sess = _session()
    stage = _stage_with_stub_child(sess, k=3)
    prev = OT.TRACING["on"]
    OT.get_tracer().reset(512)
    OT.TRACING["on"] = True
    try:
        _drive(stage, True)
        events = OT.get_tracer().snapshot()
    finally:
        OT.TRACING["on"] = prev
        OT.get_tracer().reset()
    spans = [e for e in events if e.get("cat") == "stage"
             and (e.get("args") or {}).get("coalesced_n")]
    assert spans, events
    assert spans[0]["args"]["coalesced_n"] == 3


def test_plan_construction_registers_no_kernels():
    """Laziness contract extends to the new terminals: building a plan
    with sort/window terminals and fused probes must not register any
    kernel-cache entry (cold planning stays readback- and compile-free)."""
    sess = _session()
    before = kc.cache_stats()["misses"]
    for shape in sorted(SHAPES):
        sess.physical_plan(SHAPES[shape](sess))
    assert kc.cache_stats()["misses"] == before
