"""Bottleneck doctor (observability/doctor.py) + bench regression
sentinel (tools/bench_diff.py): synthetic traces with known injected
bottlenecks -> expected ranked verdicts (sem_wait-bound and h2d-bound
fixtures per ISSUE 8), nested-span self-time attribution, truncation
caveats, summary-mode degradation, and the live/stale evidence gate."""

import importlib.util
import json
import os
import subprocess
import sys

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.observability import doctor as OD
from spark_rapids_tpu.sql import functions as F

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ev(cat, name, ms, ts=0.0, tid=1, exec_="TpuJoin", **args):
    """Synthetic tracer event (ts/dur in µs like the real ring)."""
    ev = {"cat": cat, "name": name, "ts": ts * 1e3, "dur": ms * 1e3,
          "tid": tid, "exec": exec_}
    if args:
        ev["args"] = args
    return ev


def _categories(diag):
    return [r["category"] for r in diag["ranked"]]


# --------------------------------------------------------------------------
# synthetic single-bottleneck fixtures -> expected top verdict
# --------------------------------------------------------------------------

def test_sync_bound_fixture():
    events = [_ev("sync", "join.readback", 50.0, ts=i * 60.0)
              for i in range(5)]
    events += [_ev("h2d", "upload", 1.0, ts=400.0, bytes=100)]
    diag = OD.diagnose(events, wall_ms=300.0)
    assert diag["schema"] == OD.SCHEMA
    assert diag["verdict"] == "sync-bound"
    top = diag["ranked"][0]
    assert top["ms"] == pytest.approx(250.0)
    assert top["count"] == 5
    assert top["share"] == pytest.approx(250.0 / 300.0, rel=1e-3)
    assert top["evidence"]["top_execs"][0]["exec"] == "TpuJoin"


def test_sem_wait_bound_fixture():
    """ISSUE 8 required fixture: semaphore contention dominates."""
    events = [_ev("sem_wait", "semaphore.acquire", 80.0, ts=i * 100.0,
                  tid=i, exec_="TpuHashAggregate") for i in range(4)]
    events += [_ev("sync", "readback", 2.0, ts=500.0)]
    diag = OD.diagnose(events)
    assert diag["verdict"] == "sem_wait-bound"
    assert diag["ranked"][0]["count"] == 4
    assert _categories(diag)[1] == "sync-bound"


def test_h2d_bound_fixture():
    """ISSUE 8 required fixture: uploads dominate, bytes in evidence."""
    events = [_ev("h2d", "arrow_to_device", 120.0, ts=i * 150.0,
                  exec_="TpuInMemoryScan", bytes=1 << 20)
              for i in range(3)]
    events += [_ev("d2h", "device_get", 30.0, ts=600.0, bytes=4096),
               _ev("sync", "readback", 5.0, ts=700.0)]
    diag = OD.diagnose(events, wall_ms=500.0)
    assert diag["verdict"] == "h2d-d2h-bound"
    top = diag["ranked"][0]
    assert top["ms"] == pytest.approx(390.0)
    assert top["count"] == 4                       # h2d + d2h combined
    assert top["evidence"]["bytes"] == 3 * (1 << 20) + 4096
    assert top["evidence"]["top_execs"][0]["exec"] == "TpuInMemoryScan"


def test_compile_spill_shuffle_fixtures():
    for cat, verdict in (("kernel_compile", "compile-bound"),
                         ("spill", "spill-bound"),
                         ("shuffle", "shuffle-bound")):
        events = [_ev(cat, "x", 200.0), _ev("sync", "r", 1.0, ts=300.0)]
        diag = OD.diagnose(events)
        assert diag["verdict"] == verdict, (cat, diag)


def test_dispatch_bound_from_counters():
    """Many launches, almost no attributed span time -> dispatch-bound
    (estimated), with the launch counts as evidence."""
    events = [_ev("sync", "r", 0.5)]
    diag = OD.diagnose(events, counters={"deviceDispatches": 2000},
                       metrics={"stageOpDispatches": 1500},
                       wall_ms=500.0)
    assert diag["verdict"] == "dispatch-bound"
    top = diag["ranked"][0]
    assert top["count"] == 2000
    assert top["evidence"]["estimated"] is True
    assert top["evidence"]["device_dispatches"] == 2000
    assert top["evidence"]["stage_op_dispatches"] == 1500


def test_dispatch_floor_suppresses_small_counts():
    diag = OD.diagnose([_ev("sync", "r", 5.0)],
                       counters={"deviceDispatches": 8})
    assert "dispatch-bound" not in _categories(diag)


# --------------------------------------------------------------------------
# self-time attribution: container spans must not absorb nested time
# --------------------------------------------------------------------------

def test_nested_compile_inside_shuffle_attributes_to_compile():
    """exchange.materialize wraps the map side; a kernel compile inside
    it must count as compile-bound, not shuffle-bound."""
    events = [
        _ev("shuffle", "exchange.materialize", 300.0, ts=0.0,
            exec_="TpuShuffleExchange"),
        _ev("kernel_compile", "HashAggregateExec#1", 280.0, ts=10.0,
            exec_="TpuHashAggregate"),
    ]
    diag = OD.diagnose(events, wall_ms=320.0)
    assert diag["verdict"] == "compile-bound"
    by_cat = {r["category"]: r for r in diag["ranked"]}
    assert by_cat["shuffle-bound"]["ms"] == pytest.approx(20.0)
    assert by_cat["compile-bound"]["ms"] == pytest.approx(280.0)


def test_op_spans_are_neutral_containers():
    """A shuffle span whose time is really the child plan's op compute
    keeps only its self time; the op span itself is never a verdict."""
    events = [
        _ev("shuffle", "exchange.materialize", 200.0, ts=0.0),
        _ev("op", "TpuHashAggregate", 180.0, ts=5.0),
        _ev("sync", "readback", 20.0, ts=10.0),
    ]
    diag = OD.diagnose(events)
    by_cat = {r["category"]: r for r in diag["ranked"]}
    assert by_cat["shuffle-bound"]["ms"] == pytest.approx(20.0)
    assert by_cat["sync-bound"]["ms"] == pytest.approx(20.0)
    assert "op" not in _categories(diag)


def test_parallel_threads_do_not_cross_subtract():
    """Spans overlapping in time on DIFFERENT threads are independent."""
    events = [
        _ev("shuffle", "serialize", 100.0, ts=0.0, tid=1),
        _ev("kernel_compile", "k", 100.0, ts=0.0, tid=2),
    ]
    diag = OD.diagnose(events)
    by_cat = {r["category"]: r for r in diag["ranked"]}
    assert by_cat["shuffle-bound"]["ms"] == pytest.approx(100.0)
    assert by_cat["compile-bound"]["ms"] == pytest.approx(100.0)


# --------------------------------------------------------------------------
# caveats, schema, summary mode
# --------------------------------------------------------------------------

def test_truncated_trace_flagged():
    diag = OD.diagnose([_ev("sync", "r", 1.0)], dropped_events=123)
    assert diag["trace_truncated"] is True
    assert any("123" in c for c in diag["caveats"])
    assert OD.diagnose([_ev("sync", "r", 1.0)])["trace_truncated"] is False


def test_empty_trace_no_bottleneck():
    diag = OD.diagnose([])
    assert diag["verdict"] == "no-bottleneck"
    assert diag["ranked"] == []
    assert diag["caveats"]


def test_ranked_ordering_and_shares():
    events = [_ev("sync", "r", 50.0), _ev("spill", "s", 100.0, ts=60.0),
              _ev("sem_wait", "w", 10.0, ts=200.0)]
    diag = OD.diagnose(events, wall_ms=200.0)
    ms = [r["ms"] for r in diag["ranked"]]
    assert ms == sorted(ms, reverse=True)
    assert all(0.0 <= r["share"] <= 1.0 for r in diag["ranked"])
    assert all(r["category"] in OD.VERDICTS for r in diag["ranked"])


def test_diagnose_summary_degraded_mode():
    summary = {"sync_count": 40, "sync_ms": 900.0, "compile_count": 2,
               "compile_ms": 100.0, "h2d_bytes": 1 << 20,
               "d2h_bytes": 2048, "spill_ms": 0.0, "sem_wait_ms": 1.0,
               "device_dispatches": 500, "trace_truncated": False}
    diag = OD.diagnose_summary(summary, wall_ms=1200.0)
    assert diag["verdict"] == "sync-bound"
    cats = _categories(diag)
    assert "h2d-d2h-bound" in cats and "dispatch-bound" in cats
    assert any("trace_summary" in c for c in diag["caveats"])


def test_compact_form_for_bench():
    events = [_ev("sync", "r", 50.0), _ev("spill", "s", 10.0, ts=60.0)]
    c = OD.compact(OD.diagnose(events, dropped_events=5), top=1)
    assert c["verdict"] == "sync-bound"
    assert len(c["ranked"]) == 1
    assert c["trace_truncated"] is True
    assert set(c["ranked"][0]) >= {"category", "ms", "share", "count"}


# --------------------------------------------------------------------------
# end-to-end: traced join -> session doctor + CLI over the event log
# --------------------------------------------------------------------------

def _join_query(sess, n=12000):
    rng = np.random.default_rng(7)
    fact = pa.table({"fk": rng.integers(0, 300, n), "x": rng.random(n)})
    dim = pa.table({"pk": np.arange(300, dtype=np.int64),
                    "cat": rng.integers(0, 8, 300)})
    f = sess.create_dataframe(fact, num_partitions=2)
    d = sess.create_dataframe(dim)
    return (f.join(d, f.fk == d.pk, "inner").groupBy("cat")
            .agg(F.count("*").alias("n")).orderBy("cat"))


def test_session_diagnose_last_query_end_to_end(tmp_path):
    sink = str(tmp_path / "eventlog")
    sess = srt.session(**{"spark.rapids.tpu.trace.sink": sink})
    _join_query(sess).collect()
    diag = sess.diagnose_last_query()
    assert diag["schema"] == OD.SCHEMA
    assert diag["verdict"] in OD.VERDICTS + ("no-bottleneck",)
    assert diag["ranked"], "a traced join must attribute SOMETHING"
    # every verdict carries supporting exec-level spans or counters
    for r in diag["ranked"]:
        ev = r["evidence"]
        assert ev.get("top_execs") or ev.get("device_dispatches"), r
    # CLI over the exported JSONL event log emits the same schema
    logs = os.listdir(sink)
    assert logs
    out = subprocess.run(
        [sys.executable, "-m", "spark_rapids_tpu.observability.doctor",
         os.path.join(sink, logs[0])],
        capture_output=True, text=True,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    assert out.returncode == 0, out.stderr
    cli = json.loads(out.stdout)
    assert cli["schema"] == OD.SCHEMA
    assert cli["verdict"] == diag["verdict"]


def test_diagnose_without_trace_raises():
    sess = srt.session(**{"spark.rapids.tpu.profile.enabled": False})
    sess.create_dataframe(pa.table({"k": [1]})).collect()
    with pytest.raises(RuntimeError):
        sess.diagnose_last_query()


# --------------------------------------------------------------------------
# bench_diff: thresholded verdicts + the live/stale evidence gate
# --------------------------------------------------------------------------

def _bench_diff():
    spec = importlib.util.spec_from_file_location(
        "bench_diff", os.path.join(REPO, "tools", "bench_diff.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _artifact(tmp_path, name, **kw):
    rec = {"metric": "tpch_q1_like_rows_per_sec", "value": 1000,
           "unit": "rows/s", "rows": 1000, "platform": "tpu"}
    rec.update(kw)
    p = tmp_path / name
    p.write_text(json.dumps({"parsed": rec}))
    return str(p)


def test_bench_diff_verdict_directions(tmp_path):
    bd = _bench_diff()
    a = _artifact(tmp_path, "a.json", value=1000, evidence="live",
                  extra_metrics={"join_rows_per_sec": 100,
                                 "join_trace_summary": {"sync_count": 50}})
    b = _artifact(tmp_path, "b.json", value=1300, evidence="live",
                  extra_metrics={"join_rows_per_sec": 80,
                                 "join_trace_summary": {"sync_count": 10}})
    rc, rows = bd.run(a, b, 0.10, allow_stale=False, as_json=False)
    assert rc == 0
    by = {r["metric"]: r["verdict"] for r in rows}
    assert by["tpch_q1_like_rows_per_sec"] == "IMPROVED"   # up = better
    assert by["join_rows_per_sec"] == "REGRESSED"          # down = worse
    assert by["join_trace_summary.sync_count"] == "IMPROVED"  # down=better


def test_bench_diff_refuses_live_vs_stale(tmp_path):
    bd = _bench_diff()
    a = _artifact(tmp_path, "a.json", captured_at="2026-08-01T00:00:00Z")
    b = _artifact(tmp_path, "b.json", evidence="live")
    assert bd.evidence_of(json.loads(
        (tmp_path / "a.json").read_text())["parsed"]) == "stale-replay"
    rc, _ = bd.run(a, b, 0.10, allow_stale=False, as_json=False)
    assert rc == 2
    rc, rows = bd.run(a, b, 0.10, allow_stale=True, as_json=False)
    assert rc == 0 and rows


def test_bench_diff_threshold_band(tmp_path):
    bd = _bench_diff()
    a = _artifact(tmp_path, "a.json", value=1000, evidence="live")
    b = _artifact(tmp_path, "b.json", value=1050, evidence="live")
    _, rows = bd.run(a, b, 0.10, allow_stale=False, as_json=False)
    assert {r["metric"]: r["verdict"]
            for r in rows}["tpch_q1_like_rows_per_sec"] == "OK"


def test_bench_diff_banked_artifacts_smoke():
    """The committed round artifacts diff cleanly (the CI smoke): both
    are stale replays, so the evidence gate PASSES without --allow-stale
    (same class) and the join improvement r04->r05 is visible."""
    bd = _bench_diff()
    a, b = os.path.join(REPO, "BENCH_r04.json"), \
        os.path.join(REPO, "BENCH_r05.json")
    ra, rb = bd.load_artifact(a), bd.load_artifact(b)
    assert bd.evidence_of(ra) == bd.evidence_of(rb) == "stale-replay"
    rc, rows = bd.run(a, b, 0.10, allow_stale=False, as_json=False)
    assert rc == 0
    by = {r["metric"]: r["verdict"] for r in rows}
    assert by["join_rows_per_sec"] == "IMPROVED"
