"""Dynamic partition pruning (GpuSubqueryBroadcastExec/DPP analog): a
hive-partitioned scan joined on its partition column against a broadcast
build side must skip files the build keys rule out — and produce exactly
the same results as without pruning."""

import numpy as np
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu.delta import DeltaTable
from spark_rapids_tpu.sql.physical import dpp as D
from spark_rapids_tpu.sql import functions as F


@pytest.fixture()
def sess():
    return srt.session()


def _partitioned_table(sess, tmp_path, n=2000, cats=10):
    rng = np.random.default_rng(6)
    t = pa.table({"cat": pa.array([f"c{i % cats}" for i in range(n)]),
                  "v": rng.random(n)})
    df = sess.create_dataframe(t)
    path = str(tmp_path / "facts")
    df.write.format("delta").partitionBy("cat").save(path)
    return path, t


def test_dpp_prunes_files_and_matches_oracle(sess, tmp_path):
    path, t = _partitioned_table(sess, tmp_path)
    facts = sess.read.format("delta").load(path)
    dims = sess.create_dataframe(pa.table({
        "cat": ["c1", "c3"], "w": [10.0, 20.0]}))

    before_applied = D.STATS["dpp_applied"]
    before_pruned = D.STATS["files_pruned"]
    got = (facts.join(dims, on="cat", how="inner")
           .groupBy("cat").agg(F.count("*").alias("n"),
                               F.sum(facts.v).alias("sv"))
           .orderBy("cat").collect().to_pandas())
    assert D.STATS["dpp_applied"] > before_applied, "DPP not planned"
    # 10 partition files, 2 allowed -> 8 pruned
    assert D.STATS["files_pruned"] - before_pruned == 8

    pdf = t.to_pandas()
    exp = (pdf[pdf.cat.isin(["c1", "c3"])]
           .groupby("cat").agg(n=("v", "size"), sv=("v", "sum"))
           .reset_index())
    assert list(got["cat"]) == list(exp["cat"])
    assert np.array_equal(got["n"], exp["n"])
    assert np.allclose(got["sv"], exp["sv"])


def test_dpp_not_applied_on_non_partition_key(sess, tmp_path):
    path, t = _partitioned_table(sess, tmp_path)
    facts = sess.read.format("delta").load(path)
    dims = sess.create_dataframe(pa.table({
        "v": [0.5], "w": [1.0]}))
    before = D.STATS["dpp_applied"]
    # join on v (not the partition column): no pruning, still correct
    out = facts.join(dims, on="v", how="left_semi").collect()
    assert D.STATS["dpp_applied"] == before
    assert out.num_rows <= 2000


def test_dpp_with_filter_above_scan(sess, tmp_path):
    path, t = _partitioned_table(sess, tmp_path)
    facts = sess.read.format("delta").load(path)
    dims = sess.create_dataframe(pa.table({"cat": ["c2"], "w": [1.0]}))
    before = D.STATS["files_pruned"]
    got = (facts.filter(facts.v < 0.5).join(dims, on="cat", how="inner")
           .collect().to_pandas())
    assert D.STATS["files_pruned"] - before == 9
    pdf = t.to_pandas()
    exp = pdf[(pdf.cat == "c2") & (pdf.v < 0.5)]
    assert len(got) == len(exp)


def test_dpp_not_applied_to_outer_or_anti_joins(sess, tmp_path):
    """Outer/anti joins must emit probe rows WITHOUT build matches —
    pruning those files would drop them (review r2 finding)."""
    path, t = _partitioned_table(sess, tmp_path, n=100, cats=5)
    facts = sess.read.format("delta").load(path)
    dims = sess.create_dataframe(pa.table({"cat": ["c1"], "w": [1.0]}))
    anti = facts.join(dims, on="cat", how="left_anti").collect()
    assert anti.num_rows == 80  # all non-c1 rows survive
    left = facts.join(dims, on="cat", how="left").collect()
    assert left.num_rows == 100
