"""Encoded columnar execution (ISSUE 6): dictionary/RLE representations,
op parity encoded-on vs encoded-off (filter/join/group-by/sort), the
encoded-batch shuffle wire format (narrowed codes, dictionary refs),
scan-side retention in the device decoders, decode-engagement counters,
and structural kill-switch reversion (mirror of test_async_pipeline's
plan-shape reversion: with the switch off NO encoded column ever
exists, so every plan takes the raw path)."""

import numpy as np
import pandas as pd
import pyarrow as pa
import pytest

import spark_rapids_tpu as srt
from spark_rapids_tpu import types as T
from spark_rapids_tpu.columnar import arrow_to_device, device_to_arrow
from spark_rapids_tpu.columnar import encoded as E
from spark_rapids_tpu.config import RapidsConf
from spark_rapids_tpu.sql import functions as F

ROWS = 6000
CATS = [f"cat_{i:03d}" for i in range(24)]


def _conf(on: bool, **extra):
    base = {"spark.rapids.tpu.sql.encoded.enabled": on}
    base.update(extra)
    return RapidsConf.get_global().copy(base)


def _sess(on: bool, **extra):
    return srt.session(conf=_conf(on, **extra))


@pytest.fixture(scope="module")
def tables():
    rng = np.random.default_rng(29)
    fact = pa.table({
        "k": pa.array([None if rng.random() < 0.05
                       else CATS[i] for i in rng.integers(0, 24, ROWS)]),
        "q": rng.integers(0, 100, ROWS),
        "v": rng.random(ROWS)})
    dim = pa.table({"k": CATS, "w": np.arange(float(len(CATS)))})
    return fact, dim


def _rows(df):
    return df.collect().to_pylist()


# --------------------------------------------------------------------------
# representation unit tests
# --------------------------------------------------------------------------


def test_dict_encode_roundtrip_and_killswitch():
    t = pa.table({"s": pa.array(["b", "a", None, "b", "c", "a"] * 40)})
    enc = arrow_to_device(t, conf=_conf(True))
    raw = arrow_to_device(t, conf=_conf(False))
    assert isinstance(enc.columns[0], E.DictEncodedColumn)
    # structural kill switch: OFF means no encoded column is created
    assert not isinstance(raw.columns[0], E.DictEncodedColumn)
    assert device_to_arrow(enc).equals(device_to_arrow(raw))
    d = enc.columns[0].dictionary
    assert d.sorted and d.size == 3
    assert list(d.host_values()) == [b"a", b"b", b"c"]


def test_dict_materialize_zeroes_null_rows():
    t = pa.table({"s": pa.array(["xx", None, "yy"] * 50)})
    enc = arrow_to_device(t, conf=_conf(True))
    raw = arrow_to_device(t, conf=_conf(False))
    c = enc.columns[0]
    assert isinstance(c, E.DictEncodedColumn)
    # the decline path (.data/.lengths) must produce the raw pipeline's
    # exact buffers, null rows zeroed included
    np.testing.assert_array_equal(np.asarray(c.data),
                                  np.asarray(raw.columns[0].data))
    np.testing.assert_array_equal(np.asarray(c.lengths),
                                  np.asarray(raw.columns[0].lengths))


def test_rle_encode_roundtrip():
    reps = np.repeat(np.arange(40, dtype=np.int64), 50)
    t = pa.table({"r": reps})
    enc = arrow_to_device(t, conf=_conf(True))
    raw = arrow_to_device(t, conf=_conf(False))
    assert isinstance(enc.columns[0], E.RLEColumn)
    assert enc.columns[0].num_runs == 40
    assert device_to_arrow(enc).equals(device_to_arrow(raw))


def test_high_cardinality_declines():
    t = pa.table({"s": pa.array([f"u{i}" for i in range(5000)])})
    enc = arrow_to_device(
        t, conf=_conf(True, **{
            "spark.rapids.tpu.sql.encoded.maxDictionaryCardinality": 256}))
    assert not isinstance(enc.columns[0], E.DictEncodedColumn)


def test_gather_stays_encoded():
    import jax.numpy as jnp
    t = pa.table({"s": pa.array(["a", "b", "c", "d"] * 64)})
    enc = arrow_to_device(t, conf=_conf(True))
    out = enc.columns[0].gather(jnp.asarray([3, 1, 0, 2], dtype=jnp.int32))
    assert isinstance(out, E.DictEncodedColumn)
    assert out.dictionary is enc.columns[0].dictionary
    got = [bytes(np.asarray(out.data)[i, :np.asarray(out.lengths)[i]])
           for i in range(4)]
    assert got == [b"d", b"b", b"a", b"c"]


def test_concat_unifies_different_dictionaries():
    a = arrow_to_device(pa.table({"s": ["a", "b"] * 32}), conf=_conf(True))
    b = arrow_to_device(pa.table({"s": ["b", "c"] * 32}), conf=_conf(True))
    from spark_rapids_tpu.columnar.batch import ColumnarBatch
    cat = ColumnarBatch.concat([a, b])
    assert isinstance(cat.columns[0], E.DictEncodedColumn)
    assert list(cat.columns[0].dictionary.host_values()) == \
        [b"a", b"b", b"c"]
    assert device_to_arrow(cat).column(0).to_pylist() == \
        ["a", "b"] * 32 + ["b", "c"] * 32


# --------------------------------------------------------------------------
# op parity + engagement: filter / join / group-by / sort
# --------------------------------------------------------------------------


def _q_filter(sess, fact, dim):
    return (sess.create_dataframe(fact, num_partitions=3)
            .filter(F.col("k") <= "cat_011")
            .groupBy("k").agg(F.sum(F.col("q")).alias("sq"))
            .orderBy("k"))


def _q_join(sess, fact, dim):
    f = sess.create_dataframe(fact, num_partitions=3)
    d = sess.create_dataframe(dim, num_partitions=2)
    return (f.join(d, on="k", how="inner").groupBy("k")
            .agg(F.count("*").alias("n"), F.sum(F.col("v")).alias("sv"))
            .orderBy("k"))


def _q_agg_sort(sess, fact, dim):
    return (sess.create_dataframe(fact, num_partitions=3)
            .groupBy("k").agg(F.count("*").alias("c"),
                              F.sum(F.col("v")).alias("sv"))
            .orderBy(F.col("k").desc()))


@pytest.mark.parametrize("mk", [_q_filter, _q_join, _q_agg_sort],
                         ids=["filter", "join", "agg_sort"])
def test_op_parity_encoded_vs_raw(tables, mk):
    fact, dim = tables
    on = _rows(mk(_sess(True, **{
        "spark.rapids.sql.autoBroadcastJoinThreshold": 1}), fact, dim))
    off = _rows(mk(_sess(False, **{
        "spark.rapids.sql.autoBroadcastJoinThreshold": 1}), fact, dim))
    assert on == off


def test_filter_fast_path_engages(tables):
    from spark_rapids_tpu.sql.physical.kernel_cache import (
        release_compiled_programs)
    fact, dim = tables
    # dict_filters counts TRACE-time fast-path engagement; drop compiled
    # programs so this query's predicate actually retraces
    release_compiled_programs()
    sess = _sess(True)
    _rows(_q_filter(sess, fact, dim))
    m = sess.last_query_metrics
    assert m.get("encodedDictFilters", 0) >= 1, m
    # NOTE: encodedColumnsEncoded counts NEW encodes at upload; since
    # the serving tier made the upload/split caches process-shared
    # (docs/serving.md), a table another test already scanned encoded
    # serves its resident dict batches with zero fresh encodes — the
    # dict-filter engagement above is the proof encoded columns flowed


def test_filter_null_semantics_parity(tables):
    fact, dim = tables
    for pred in (F.col("k").isNull(), F.col("k").isNotNull(),
                 F.col("k").isin("cat_001", "cat_007")):
        on = _rows(_sess(True).create_dataframe(fact).filter(pred)
                   .groupBy("k").count().orderBy("k"))
        off = _rows(_sess(False).create_dataframe(fact).filter(pred)
                    .groupBy("k").count().orderBy("k"))
        assert on == off


def test_join_probes_on_codes(tables):
    fact, dim = tables
    sess = _sess(True, **{"spark.rapids.sql.autoBroadcastJoinThreshold": 1})
    _rows(_q_join(sess, fact, dim))
    assert sess.last_query_metrics.get("joinCodeLowerings", 0) >= 1


def test_broadcast_join_parity_and_lowering(tables):
    """The broadcast path: the dim side broadcasts (in-process, dict-
    aware concat), and the join still lowers to code space."""
    fact, dim = tables
    sess = _sess(True)  # default broadcast threshold: dim broadcasts
    on = _rows(_q_join(sess, fact, dim))
    assert sess.last_query_metrics.get("joinCodeLowerings", 0) >= 1
    off = _rows(_q_join(_sess(False), fact, dim))
    assert on == off


def test_join_types_parity(tables):
    fact, dim = tables
    half = dim.slice(0, 12)  # build misses exercise the -1 sentinel
    for how in ("inner", "left", "left_semi", "left_anti"):
        def q(sess):
            f = sess.create_dataframe(fact, num_partitions=2)
            d = sess.create_dataframe(half)
            j = f.join(d, on="k", how=how)
            cols = ["k"] if how in ("left_semi", "left_anti") else ["k", "w"]
            return j.groupBy(*cols).count().orderBy("k")
        on = _rows(q(_sess(True,
                           **{"spark.rapids.sql.autoBroadcastJoinThreshold": 1})))
        off = _rows(q(_sess(False,
                            **{"spark.rapids.sql.autoBroadcastJoinThreshold": 1})))
        assert on == off, how


# --------------------------------------------------------------------------
# wire format
# --------------------------------------------------------------------------


def _wire_tables():
    rng = np.random.default_rng(7)
    return pa.table({
        "s": pa.array([None if rng.random() < 0.1
                       else CATS[i] for i in rng.integers(0, 24, 2000)]),
        "r": np.repeat(np.arange(20, dtype=np.int64), 100),
        "v": rng.random(2000)})


def test_wire_roundtrip_and_narrowing():
    from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                     serialize_batch)
    t = _wire_tables()
    conf = _conf(True, **{
        "spark.rapids.tpu.sql.encoded.shuffle.dictRefs.enabled": False})
    enc = arrow_to_device(t, conf=conf)
    assert isinstance(enc.columns[0], E.DictEncodedColumn)
    assert isinstance(enc.columns[1], E.RLEColumn)
    frame = serialize_batch(enc, conf)
    raw_frame = serialize_batch(arrow_to_device(t, conf=_conf(False)),
                                _conf(False))
    assert len(frame) < len(raw_frame)
    back = deserialize_batch(frame)
    assert device_to_arrow(back).equals(
        device_to_arrow(arrow_to_device(t, conf=_conf(False))))


def test_wire_dict_refs_ship_dictionary_once():
    from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                     serialize_batch)
    t = pa.table({"s": pa.array([CATS[i % 24] for i in range(1000)])})
    conf = _conf(True)
    enc = arrow_to_device(t, conf=conf)
    first = serialize_batch(enc, conf)
    second = serialize_batch(enc, conf)
    # the second frame replaces the (registered) dictionary with a
    # content-hash ref: only code bytes remain
    assert len(second) < len(first)
    for frame in (first, second):
        got = deserialize_batch(frame)
        assert device_to_arrow(got).column(0).to_pylist() == \
            t.column(0).to_pylist()


def test_wire_reader_materializes_when_disabled():
    from spark_rapids_tpu.shuffle.serializer import (deserialize_batch,
                                                     serialize_batch)
    t = _wire_tables()
    conf = _conf(True)
    frame = serialize_batch(arrow_to_device(t, conf=conf), conf)
    g = RapidsConf.get_global()
    old = g.get("spark.rapids.tpu.sql.encoded.enabled")
    try:
        g.set("spark.rapids.tpu.sql.encoded.enabled", False)
        back = deserialize_batch(frame)
        # a disabled session must never observe encoded representations
        assert not E.has_encoded_columns(back)
    finally:
        g.set("spark.rapids.tpu.sql.encoded.enabled", old)
    assert device_to_arrow(back).equals(
        device_to_arrow(arrow_to_device(t, conf=_conf(False))))


def test_shuffle_bytes_on_wire_metric(tables):
    fact, dim = tables
    wire = {}
    for on in (True, False):
        sess = _sess(on, **{
            "spark.rapids.shuffle.localDeviceResident.enabled": False,
            "spark.rapids.sql.autoBroadcastJoinThreshold": 1})
        _rows(_q_join(sess, fact, dim))
        m = sess.last_query_metrics
        assert m.get("shuffleBytesOnWire", 0) > 0, m
        wire[on] = m["shuffleBytesOnWire"]
    # the encoded-vs-raw claim, measured per query: encoding must shrink
    # the join shape's wire bytes
    assert wire[True] < wire[False], wire


# --------------------------------------------------------------------------
# scan-side retention + decode engagement (satellite 1)
# --------------------------------------------------------------------------


def test_scan_retention_and_engagement(tmp_path):
    from spark_rapids_tpu.testing.scaletest import scan_engagement_report
    rep = scan_engagement_report(rows=5000, tmpdir=str(tmp_path))
    for fmt in ("parquet", "orc"):
        assert rep[fmt]["files_engaged"] >= 1, rep
        assert rep[fmt]["files_declined"] == 0, rep
    assert "decline_reasons" in rep["decode_stats"]["parquet"]


def test_parquet_dict_page_retention_parity(tmp_path):
    import pyarrow.parquet as pq
    rng = np.random.default_rng(3)
    t = pa.table({
        "k": pa.array([CATS[i] for i in rng.integers(0, 24, 4000)]),
        "v": rng.random(4000)})
    path = str(tmp_path / "t.parquet")
    pq.write_table(t, path)
    res = {}
    for on in (True, False):
        sess = _sess(on)
        q = (sess.read.parquet(path).filter(F.col("k") >= "cat_010")
             .groupBy("k").agg(F.sum(F.col("v")).alias("sv")).orderBy("k"))
        res[on] = _rows(q)
        m = sess.last_query_metrics
        assert m.get("parquetDecodeFilesEngaged", 0) >= 1, m
        enc_cols = m.get("encodedColumnsEncoded", 0)
        assert (enc_cols >= 1) == on, (on, m)
    assert res[True] == res[False]


# --------------------------------------------------------------------------
# structural kill-switch reversion (acceptance criterion)
# --------------------------------------------------------------------------


def test_killswitch_reverts_every_path(tables):
    """Mirror of test_async_pipeline's plan-shape reversion: the switch
    is structural, so OFF must mean zero encoded columns anywhere —
    scans, shuffle reads, concats — across a shuffling join query."""
    fact, dim = tables
    sess = _sess(False, **{
        "spark.rapids.shuffle.localDeviceResident.enabled": False,
        "spark.rapids.sql.autoBroadcastJoinThreshold": 1})
    _rows(_q_join(sess, fact, dim))
    m = sess.last_query_metrics
    assert m.get("encodedColumnsEncoded", 0) == 0, m
    assert m.get("encodedDictFilters", 0) == 0, m
    assert m.get("joinCodeLowerings", 0) in (0.0, 0, None), m
    assert m.get("encodedWireDictInline", 0) == 0, m
    # and the scan upload cache keys on the switch: flipping it ON in a
    # fresh session over the SAME tables serves ENCODED batches (the
    # dict filter fast path engages), never the raw entries the OFF
    # session just cached.  The upload cache is process-shared across
    # sessions (docs/serving.md), so the encode itself may have happened
    # in an earlier test over these module-scoped tables — assert the
    # representation served, not a fresh-encode counter delta.
    from spark_rapids_tpu.sql.physical.kernel_cache import (
        release_compiled_programs)
    release_compiled_programs()  # dict_filters counts trace-time hits
    sess_on = _sess(True, **{
        "spark.rapids.sql.autoBroadcastJoinThreshold": 1})
    _rows(_q_filter(sess_on, fact, dim))
    assert sess_on.last_query_metrics.get("encodedDictFilters", 0) >= 1
